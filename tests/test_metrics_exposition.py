"""Metrics exposition (``core/metrics.py``): nearest-rank percentile
edge behavior (empty / single observation / q=0 / q=1 / ring
wraparound past KEEP), the Prometheus text renderer (format validity,
label folding, counter round-trip), the atomic file exposition, and the
``trace metrics`` subcommand."""

import glob
import json
import os
import re

import pytest

from cme213_tpu.core import metrics
from cme213_tpu.core.metrics import (
    KEEP,
    Histogram,
    _nearest_rank,
    render_prometheus,
    write_exposition,
)


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    monkeypatch.delenv(metrics.METRICS_FILE_ENV, raising=False)
    metrics.reset()
    yield
    metrics.reset()


# ------------------------------------------------------- percentile edges

def test_percentile_empty_histogram_is_none():
    h = Histogram("empty")
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.percentile(q) is None


def test_percentile_single_observation_all_quantiles():
    h = Histogram("one").observe(42.0)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.percentile(q) == 42.0


def test_percentile_q0_q1_are_window_extremes():
    h = Histogram("ends")
    for v in (7.0, 3.0, 9.0, 5.0):
        h.observe(v)
    assert h.percentile(0.0) == 3.0
    assert h.percentile(1.0) == 9.0


def test_percentile_nearest_rank_pinned():
    h = Histogram("nr")
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        h.observe(v)
    # nearest rank: sorted[ceil(q*n) - 1]
    assert h.percentile(0.50) == 3.0          # ceil(2.5)=3 -> index 2
    assert h.percentile(0.25) == 2.0          # ceil(1.25)=2 -> index 1
    assert h.percentile(0.99) == 100.0        # ceil(4.95)=5 -> index 4
    assert _nearest_rank([], 0.5) is None


def test_percentile_ring_wraparound_past_keep():
    """Past KEEP observations, percentiles see only the retained window
    while count/sum/min/max stay exact over the full stream."""
    h = Histogram("ring")
    n = KEEP + 904                            # 5000 with KEEP=4096
    for v in range(1, n + 1):
        h.observe(float(v))
    assert h.count == n
    assert h.total == n * (n + 1) / 2
    assert h.min == 1.0 and h.max == float(n)
    assert h.percentile(0.0) == float(n - KEEP + 1)   # oldest retained
    assert h.percentile(1.0) == float(n)


# ------------------------------------------------------ prometheus render

_TYPE_LINE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                        r"(counter|gauge|summary|histogram)$")
_HELP_LINE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
_SAMPLE_LINE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*")*\})?'
    r" (?P<value>[^ ]+)$")


def _validate(text):
    """Prometheus text-format validator: every line is a HELP line, a
    TYPE line, or a ``name{labels} value`` sample with a float-parseable
    value."""
    samples = {}
    for line in text.rstrip("\n").split("\n"):
        if _TYPE_LINE.match(line) or _HELP_LINE.match(line):
            continue
        m = _SAMPLE_LINE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        float(m.group("value"))
        samples[line.rsplit(" ", 1)[0]] = float(m.group("value"))
    return samples


def test_render_prometheus_round_trips_format_validator():
    metrics.counter("serve.shed.queue-full").inc(2)
    metrics.counter("serve.tenant.t0.served").inc(4)
    metrics.counter("served.echo.fast").inc()
    metrics.counter("faults.slow").inc(5)
    metrics.counter("checkpoint.rollbacks").inc()
    metrics.gauge("serve.slo.burn").set(1.5)
    metrics.gauge("world-size").set(8)
    metrics.gauge("last-op").set("heat2d")     # string: no sample
    metrics.gauge("armed").set(True)           # bool: no sample
    for v in range(1, 101):
        metrics.histogram("serve.latency.ms").observe(float(v))

    samples = _validate(render_prometheus())
    # dotted families fold their variable segments into labels
    assert samples['cme213_serve_shed_total{reason="queue-full"}'] == 2
    assert samples['cme213_serve_tenant_served_total{tenant="t0"}'] == 4
    assert samples['cme213_served_total{op="echo",rung="fast"}'] == 1
    assert samples['cme213_faults_total{kind="slow"}'] == 5
    # flat names sanitize dots/dashes to underscores
    assert samples["cme213_checkpoint_rollbacks_total"] == 1
    assert samples["cme213_serve_slo_burn"] == 1.5
    assert samples["cme213_world_size"] == 8
    assert not any("last_op" in k or "armed" in k for k in samples)
    # histograms render natively: cumulative le-labeled buckets + exact
    # sum/count
    assert samples['cme213_serve_latency_ms_bucket{le="64"}'] == 64
    assert samples['cme213_serve_latency_ms_bucket{le="128"}'] == 100
    assert samples['cme213_serve_latency_ms_bucket{le="+Inf"}'] == 100
    assert samples["cme213_serve_latency_ms_sum"] == 5050.0
    assert samples["cme213_serve_latency_ms_count"] == 100


def test_render_prometheus_histogram_buckets_are_cumulative():
    for v in (0.1, 0.5, 3.0, 1e6):
        metrics.histogram("lat.ms").observe(v)
    samples = _validate(render_prometheus())
    assert samples['cme213_lat_ms_bucket{le="0.25"}'] == 1
    assert samples['cme213_lat_ms_bucket{le="0.5"}'] == 2
    assert samples['cme213_lat_ms_bucket{le="4"}'] == 3
    assert samples['cme213_lat_ms_bucket{le="32768"}'] == 3
    assert samples['cme213_lat_ms_bucket{le="+Inf"}'] == 4    # overflow
    assert samples["cme213_lat_ms_count"] == 4
    assert "# TYPE cme213_lat_ms histogram" in render_prometheus()


def test_render_prometheus_summary_compat_flag(monkeypatch):
    """``CME213_METRICS_SUMMARY_COMPAT`` restores the historical
    quantile-summary rendering; bucket-less (older) snapshots fall back
    to it per metric regardless of the flag."""
    for v in range(1, 101):
        metrics.histogram("serve.latency.ms").observe(float(v))
    monkeypatch.setenv(metrics.SUMMARY_COMPAT_ENV, "1")
    samples = _validate(render_prometheus())
    assert samples['cme213_serve_latency_ms{quantile="0.5"}'] == 50.0
    assert samples['cme213_serve_latency_ms{quantile="0.99"}'] == 99.0
    assert samples["cme213_serve_latency_ms_count"] == 100
    assert not any("_bucket" in k for k in samples)
    monkeypatch.delenv(metrics.SUMMARY_COMPAT_ENV)
    legacy = {"histograms": {"old.ms": {"count": 2, "sum": 3.0,
                                        "p50": 1.5, "p90": 2.0,
                                        "p99": 2.0}}}
    text = render_prometheus(legacy)
    assert 'cme213_old_ms{quantile="0.5"} 1.5' in text
    assert "# TYPE cme213_old_ms summary" in text


def test_render_prometheus_escapes_label_values():
    metrics.counter('serve.shed.we"ird\\reason').inc()
    samples = _validate(render_prometheus())
    assert samples['cme213_serve_shed_total{reason="we\\"ird\\\\reason"}'] == 1


def test_render_prometheus_help_lines_cover_every_family():
    metrics.counter("serve.batches").inc()
    metrics.gauge("depth").set(3)
    metrics.histogram("lat.ms").observe(1.0)
    lines = render_prometheus().splitlines()
    typed = {ln.split()[2] for ln in lines if ln.startswith("# TYPE ")}
    helped = {ln.split()[2] for ln in lines if ln.startswith("# HELP ")}
    assert typed and typed == helped
    for fam in typed:  # HELP immediately precedes its TYPE line
        ti = next(i for i, ln in enumerate(lines)
                  if ln.startswith(f"# TYPE {fam} "))
        assert lines[ti - 1].startswith(f"# HELP {fam} ")
    # compat shim for consumers that reject comment chatter
    assert "# HELP" not in render_prometheus(help_lines=False)


def test_render_prometheus_empty_registry_and_explicit_snapshot():
    assert render_prometheus() == ""
    metrics.counter("a.b").inc()
    snap = metrics.snapshot()
    metrics.reset()
    assert "cme213_a_b_total 1" in render_prometheus(snap)


# ----------------------------------------------------------- file exposition

def test_write_exposition_noop_without_destination():
    metrics.counter("x").inc()
    assert write_exposition() is None


def test_write_exposition_env_path_atomic(tmp_path, monkeypatch):
    out = tmp_path / "metrics.prom"
    monkeypatch.setenv(metrics.METRICS_FILE_ENV, str(out))
    metrics.counter("serve.batches").inc(3)
    assert write_exposition() == str(out)
    text = out.read_text()
    assert _validate(text)["cme213_serve_batches_total"] == 3
    assert text == render_prometheus()
    assert glob.glob(str(tmp_path / "*.tmp*")) == []
    # repeat writes replace, never append
    metrics.counter("serve.batches").inc()
    write_exposition()
    assert _validate(out.read_text())["cme213_serve_batches_total"] == 4


# ------------------------------------------------------- trace metrics CLI

def _trace_main(argv):
    from cme213_tpu.trace_cli import main
    return main(argv)


def test_trace_metrics_from_snapshot_json(tmp_path, capsys):
    metrics.counter("faults.fail").inc(2)
    f = tmp_path / "snap.json"
    f.write_text(json.dumps(metrics.snapshot()))
    assert _trace_main(["metrics", str(f)]) == 0
    out = capsys.readouterr().out
    assert 'cme213_faults_total{kind="fail"} 2' in out
    _validate(out)


def test_trace_metrics_from_flight_dump(tmp_path, capsys):
    metrics.counter("serve.failed").inc()
    doc = {"flight": 1, "reason": "rankkill", "events": [],
           "metrics": metrics.snapshot()}
    f = tmp_path / "flight-1-2-3.json"
    f.write_text(json.dumps(doc))
    assert _trace_main(["metrics", str(f)]) == 0
    assert "cme213_serve_failed_total 1" in capsys.readouterr().out


def test_trace_metrics_from_trace_jsonl(tmp_path, capsys):
    metrics.counter("retries").inc(7)
    f = tmp_path / "trace.jsonl"
    f.write_text(
        json.dumps({"event": "heartbeat", "t": 0.5, "rank": 0, "step": 1})
        + "\n"
        + json.dumps({"event": "metrics-snapshot", "t": 1.0,
                      "metrics": metrics.snapshot()}) + "\n")
    assert _trace_main(["metrics", str(f)]) == 0
    assert "cme213_retries_total 7" in capsys.readouterr().out


def test_trace_metrics_rejects_snapshotless_input(tmp_path, capsys):
    f = tmp_path / "nothing.json"
    f.write_text('{"foo": 1}')
    assert _trace_main(["metrics", str(f)]) == 2
    assert "trace:" in capsys.readouterr().err
    g = tmp_path / "garbage.txt"
    g.write_text("hello\n")
    assert _trace_main(["metrics", str(g)]) == 2
