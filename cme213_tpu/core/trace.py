"""Telemetry: trace spans, structured events, and hardened per-rank sinks.

The reference instruments every workload with labeled phase timers —
``event_pair`` + ``start_timer``/``stop_timer`` CUDA-event pairs
(``hw/hw1/programming/mp1-util.h:21-39``), ``omp_get_wtime`` phases
(``hw/hw4/programming/mergesort.cpp:168-184``), ``MPI_Wtime``
(``hw/hw5/programming/2dHeat.cpp:832-841``) — and derives its metrics
offline (SURVEY §5).  This module is the production form of that story,
in three pieces:

- **Structured events** (``record_event``): op failures
  (``core/errors.check_op``), fallback-ladder demotions and retries
  (``core/resilience.py``), checkpoint quarantines (``core/checkpoint.py``),
  epoch commits (``dist/ckpt.py``), gang verdicts (``dist/launch.py``) and
  injected faults (``core/faults.py``) all flow through here as dicts.
  Every record carries process tags — ``pid``, ``rank``
  (``JAX_PROCESS_ID``), ``incarnation`` (``CME213_INCARNATION``) — so
  per-rank files can be merged back into one gang view.  The registry of
  known event names and their required fields is :data:`EVENT_SCHEMA`
  (pinned by a tier-1 test over every call site in the package).

- **Spans** (``span``): causally-linked begin/end pairs in the Dapper
  style — unique ids, parent links via a contextvar stack, monotonic
  durations, and a ``.block(*arrays)`` hook that ``jax.block_until_ready``s
  device work before the clock stops (the ``cudaEventSynchronize`` analog,
  same discipline as ``core/timing.PhaseTimer`` — whose phases emit spans
  automatically).  Span durations also feed the metrics registry
  (``core/metrics.py``) as ``span.<name>.ms`` histograms.

- **Cross-process context**: every record is stamped with a ``trace``
  id that spans the whole job, not just one process.  A launcher
  (``dist/launch.py``) exports ``CME213_TRACE_CONTEXT`` — JSON
  ``{"trace_id", "parent_span_id"}`` — into its children via
  :func:`propagation_env`; a child inherits the id (else mints one per
  process) and parents its root spans under the launcher's open span
  (the ``gang-launch`` span), so a merged multi-rank trace is one
  causal tree under one id, Dapper-style.  The serving front end
  carries the same id on every ``SolveRequest``/``request-served``
  record, so one id follows loadgen → queue → batch → execution.

- **Sinks**: set ``CME213_TRACE_FILE`` to append each record as a JSON
  line.  The handle is opened once and cached (not reopened per event),
  guarded by a lock, flushed per line (a hard-killed rank —
  ``os._exit`` — keeps everything it recorded) and closed at exit.  A
  ``{rank}`` placeholder in the path is expanded per process (the
  launcher templates it for workers; this module resolves any remainder
  from ``JAX_PROCESS_ID``, or ``main`` for non-rank processes), so gang
  members never interleave into one file.  ``CME213_TRACE_BUFFER`` caps
  the in-process event list as a ring buffer (default unbounded — the
  historical behavior tests rely on).

Offline analysis: ``python -m cme213_tpu trace summary|timeline|merge``
(``trace_cli.py``) over one or many sink files.  With no sink configured,
an event is one dict append under a lock — effectively free next to any
device work it annotates.

``device_trace`` is unchanged: the kernel-level XPlane profile
(TensorBoard/Perfetto) for overlap verification, which spans deliberately
do not replace.
"""

from __future__ import annotations

import atexit
import contextvars
import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager

#: JSON-lines sink path; may contain a ``{rank}`` placeholder
TRACE_FILE_ENV = "CME213_TRACE_FILE"
#: ring-buffer cap on the in-process event list (0/unset = unbounded)
TRACE_BUFFER_ENV = "CME213_TRACE_BUFFER"
#: cross-process trace context a launcher exports to its children:
#: JSON ``{"trace_id": str, "parent_span_id": str|null}``
TRACE_CONTEXT_ENV = "CME213_TRACE_CONTEXT"
#: truthy -> tail-based sampling: request-hop spans are buffered per
#: request and only written when the tail decision keeps them (slow /
#: shed / failed / requeued / drift-flagged), so always-on tracing costs
#: ~0 sink traffic on the happy path
TRACE_TAIL_ENV = "CME213_TRACE_TAIL"
#: head-sampling rate (0..1): this deterministic fraction of requests
#: bypasses the tail buffer entirely and is always kept
TRACE_HEAD_RATE_ENV = "CME213_TRACE_HEAD_RATE"
#: explicit "slow" latency threshold (ms) for the tail keep decision;
#: unset means latency alone never forces a keep
TRACE_TAIL_SLOW_MS_ENV = "CME213_TRACE_TAIL_SLOW_MS"

#: Known event names -> required fields (beyond the automatic
#: event/t/pid/rank/incarnation/trace tags).  ``tests/test_telemetry.py``
#: statically checks every ``record_event`` call site in the package
#: against this table; ``trace_cli.py`` validates records offline.
EVENT_SCHEMA: dict[str, tuple[str, ...]] = {
    # op barriers / ingestion (core/errors.py)
    "op-failure": ("op", "error", "ms", "message"),
    "data-validation": ("source", "invariant", "detail"),
    # resilience ladder (core/resilience.py)
    "retry": ("op", "attempt", "kind", "error", "next_delay_s"),
    "rung-failed": ("op", "rung", "kind", "error"),
    "served": ("op", "rung", "demoted", "failed_rungs"),
    # fault injection (core/faults.py)
    "fault-injected": ("kind", "op"),
    # conformance gating (core/conformance.py)
    "conformance-probe": ("op", "rung", "shape_class", "ok", "ms"),
    "conformance-failed": ("op", "rung", "shape_class", "detail"),
    # admission control (core/admission.py, core/checkpoint.py,
    # ops/stencil_pipeline.py, dist solvers)
    "admission-rejected": ("op", "requested_bytes", "budget_bytes", "detail"),
    "chunk-shrunk": ("op", "from_size", "to_size", "reason"),
    # single-process checkpoints (core/checkpoint.py)
    "checkpoint-quarantine": ("path", "quarantined_to", "error", "message"),
    "numeric-abort": ("op", "step", "retries"),
    "checkpoint-rollback": ("op", "resumed_step", "retries"),
    # bench harness (bench/run_all.py, bench.py)
    "sweep-failed": ("sweep", "attempt", "error"),
    "sweep-complete": ("sweep", "rows", "ms"),
    "kernel-failure": ("op", "kernel", "error", "stage"),
    "device-memory": ("path", "bytes"),
    # device-health doctor + staged forensics (core/diag.py)
    "device-health": ("healthy", "platform", "devices", "probe_ms"),
    "attribution-mismatch": ("op", "rung", "shape_class", "metric",
                             "predicted", "measured", "ratio"),
    # compile/run split (this module; ROADMAP item 5's measurement half)
    "compile-retrace": ("op", "shape_class", "kernel", "count"),
    # program cache (core/programs.py; ROADMAP item 5's amortization half)
    "program-cache-hit": ("op", "rung", "shape_class"),
    "program-cache-miss": ("op", "rung", "shape_class"),
    # autotuner (core/tune.py; ROADMAP item 2b): trial/winner from the
    # measured search, hit/default from every dispatch-time consult
    "tune-trial": ("op", "shape_class", "candidate", "ok", "ms", "gbs"),
    "tune-winner": ("op", "shape_class", "dtype", "candidate", "statics",
                    "gbs"),
    "tune-hit": ("op", "shape_class", "statics"),
    "tune-default": ("op", "shape_class"),
    # distributed commits (dist/ckpt.py)
    "epoch-commit": ("epoch", "step", "world", "shards", "ms"),
    "commit-invalid": ("candidate", "error", "message"),
    "commit-loaded": ("epoch", "step", "candidate"),
    # gang supervision (dist/launch.py, dist/supervisor.py)
    "rank-failed": ("rank", "reason", "incarnation"),
    "gang-restart": ("incarnation", "reason", "rank"),
    "gang-launch": ("incarnation", "world", "coordinator"),
    "gang-exit": ("incarnation", "rc"),
    "heartbeat": ("rank", "step"),
    # circuit breaker (core/resilience.py)
    "breaker-open": ("op", "rung", "failures", "kind"),
    "breaker-half-open": ("op", "rung"),
    "breaker-close": ("op", "rung"),
    # serving front end (serve/server.py)
    "queue-shed": ("op", "reason", "depth", "age_ms"),
    "deadline-shed": ("op", "rid", "late_ms", "depth", "age_ms"),
    "batch-executed": ("op", "shape_class", "size", "occupancy"),
    # request lifecycle (serve/server.py): one per served/failed request,
    # linking the request id to the batch span that executed it
    "request-served": ("rid", "op", "tenant", "batch", "status", "total_ms"),
    # wire codec span tags (serve/transport.py): one per encode/decode
    # on either side of a v2 frame, sampled past the first 64 rids of a
    # connection — the serve.request.{encode,decode}_ms histograms see
    # the full population
    "request-serialized": ("rid", "op", "ms", "nbytes"),
    "request-deserialized": ("rid", "op", "ms", "nbytes"),
    # SLO burn-rate monitor (serve/slo.py)
    "slo-burn": ("objective", "burn_short", "burn_long", "threshold"),
    "slo-ok": ("objective", "burn_short"),
    # replicated serving fleet (serve/fleet.py, serve/router.py)
    "replica-up": ("replica", "incarnation", "addr"),
    "replica-down": ("replica", "incarnation", "reason"),
    "request-routed": ("rid", "op", "tenant", "replica"),
    "request-requeued": ("rid", "op", "tenant", "from_replica"),
    "scale-up": ("replicas", "reason"),
    "scale-down": ("replicas", "reason"),
    # numeric-health observatory (core/numerics.py): shadow conformance
    # sampling, output sentinels, convergence tracing
    "numeric-drift": ("op", "rung", "shape_class", "rel_l2", "max_ulps",
                      "over_budget"),
    "numeric-sentinel": ("op", "rung", "kind", "count", "size"),
    "solver-progress": ("op", "step", "residual", "delta_norm",
                        "iters_per_s", "job"),
    "drift-budget-burn": ("op", "rung", "burn_short", "burn_long",
                          "threshold"),
    "drift-budget-ok": ("op", "rung", "burn_short"),
    # durable long-job lane (serve/jobs.py): one per accepted submit,
    # one per committed epoch (emitted only after the record publish —
    # epoch numbers are unique per job across crashes by construction),
    # one per epoch-boundary preemption, one per resume (preempted /
    # crash / restart), one per terminal transition
    "job-submitted": ("job", "op", "total_epochs"),
    "job-epoch": ("job", "op", "epoch", "residual"),
    "job-preempted": ("job", "op", "epoch", "reason"),
    "job-resumed": ("job", "op", "epoch", "source"),
    "job-done": ("job", "op", "state", "epochs"),
    "job-reassigned": ("job", "source", "target"),
    # game-day chaos campaigns (core/chaos.py): one per campaign run,
    # one per invariant violation, one per completed ddmin shrink
    "chaos-campaign": ("seed", "campaign", "cocktail", "backend"),
    "chaos-violation": ("campaign", "invariant", "detail"),
    "chaos-shrunk": ("campaign", "from_clauses", "to_clauses", "cocktail"),
    # flight recorder (core/flight.py)
    "flight-dump": ("reason", "path", "events"),
    # wall-clock alignment (this module + serve/transport.py): one per
    # completed ping-train sync; offset_ms is "peer wall clock minus
    # mine", err_ms the midpoint-of-RTT uncertainty bound
    "clock-offset": ("peer_pid", "offset_ms", "err_ms", "rtt_ms",
                     "samples"),
    # telemetry itself
    "span-begin": ("span", "id", "parent"),
    "span-end": ("span", "id", "parent", "ms"),
    "metrics-snapshot": ("metrics",),
}


def validate_record(rec: dict) -> list[str]:
    """Required fields missing from ``rec`` for its (known) event name;
    ``[]`` when the record is valid or the event name is unregistered."""
    required = EVENT_SCHEMA.get(rec.get("event", ""))
    if not required:
        return []
    return [k for k in required if k not in rec]


_LOCK = threading.Lock()
_EVENTS: deque = deque()
_BUFFER_CONFIGURED = False

_SINK_PATH: str | None = None   # resolved path the cached handle points at
_SINK_FILE = None
_ATEXIT_INSTALLED = False


# -------------------------------------------------- cross-process context

_CONTEXT_RAW: str | None = None   # env string the cached parse came from
_CONTEXT: dict = {}
_LOCAL_TRACE_ID: str | None = None


def _context() -> dict:
    """The inherited cross-process context (``{}`` outside a launched
    child).  Re-parsed only when the env string changes — the same
    string-compare discipline as the sink handle, so monkeypatched tests
    see context flips without a process restart."""
    global _CONTEXT_RAW, _CONTEXT
    raw = os.environ.get(TRACE_CONTEXT_ENV) or None
    if raw != _CONTEXT_RAW:
        ctx: dict = {}
        if raw:
            try:
                doc = json.loads(raw)
                if isinstance(doc, dict):
                    ctx = doc
            except ValueError:
                pass  # a torn context must never kill the workload
        _CONTEXT_RAW, _CONTEXT = raw, ctx
    return _CONTEXT


def trace_id() -> str:
    """The process-spanning trace id stamped on every record: inherited
    from the launcher (``CME213_TRACE_CONTEXT``) when present, else
    minted once per process — so a gang (or a loadgen session under the
    launcher) shares one id across every pid it touches."""
    global _LOCAL_TRACE_ID
    inherited = _context().get("trace_id")
    if inherited:
        return str(inherited)
    if _LOCAL_TRACE_ID is None:
        _LOCAL_TRACE_ID = (f"{os.getpid():x}-"
                           f"{time.time_ns() & 0xFFFFFFFFFF:010x}")
    return _LOCAL_TRACE_ID


def inherited_parent_id() -> str | None:
    """Span id (in the spawning process) this process's root spans parent
    under — the launcher's open ``gang-launch`` span, typically."""
    p = _context().get("parent_span_id")
    return str(p) if p else None


def propagation_env() -> dict:
    """Env entries a launcher injects into a child process so the child
    joins this trace: the shared ``trace_id`` plus the currently open
    span id as the child's root-span parent."""
    ctx = {"trace_id": trace_id(),
           "parent_span_id": current_span_id() or inherited_parent_id()}
    return {TRACE_CONTEXT_ENV: json.dumps(ctx)}


def _proc_tags() -> dict:
    """The per-record process tags (pid/rank/incarnation/trace) that let
    ``trace merge`` and the live collector (``core/collector.py``)
    reconstruct a gang view from per-rank files."""
    rank = os.environ.get("JAX_PROCESS_ID")
    return {
        "pid": os.getpid(),
        "rank": int(rank) if rank else None,
        "incarnation": int(os.environ.get("CME213_INCARNATION", "0") or 0),
        "trace": trace_id(),
    }


def format_trace_path(template: str, rank) -> str:
    """Expand the ``{rank}`` placeholder of a sink-path template.  A
    non-rank process (``rank`` None or the empty string) expands to
    ``main`` — a leftover literal ``{rank}`` must never reach ``open``."""
    if rank is None or rank == "":
        rank = "main"
    return template.replace("{rank}", str(rank))


def _resolve_sink_path() -> str | None:
    path = os.environ.get(TRACE_FILE_ENV)
    if not path:
        return None
    if "{rank}" in path:
        # launcher children get a concrete path from dist/launch.py; this
        # fallback covers processes using the template env directly (the
        # single-process library path), including an empty JAX_PROCESS_ID
        path = format_trace_path(path, os.environ.get("JAX_PROCESS_ID"))
    return path


def _sink_file():
    """The cached append handle for the current sink path (caller holds
    ``_LOCK``).  Re-resolved per event only by string compare, so a test
    flipping the env (or a ``flush_sink``) rotates the handle; a broken
    sink caches ``None`` and is never retried until the path changes."""
    global _SINK_PATH, _SINK_FILE, _ATEXIT_INSTALLED
    path = _resolve_sink_path()
    if path != _SINK_PATH:
        if _SINK_FILE is not None:
            try:
                _SINK_FILE.close()
            except OSError:
                pass
        _SINK_FILE = None
        _SINK_PATH = path
        if path:
            try:
                _SINK_FILE = open(path, "a")
            except OSError:
                _SINK_FILE = None  # broken sink must never kill the workload
        if not _ATEXIT_INSTALLED:
            atexit.register(flush_sink)
            _ATEXIT_INSTALLED = True
    return _SINK_FILE


def flush_sink() -> None:
    """Flush and close the cached sink handle (reopened lazily by the
    next event).  Registered atexit; also the test hook for rotating the
    handle after an env change without recording an event."""
    global _SINK_PATH, _SINK_FILE
    with _LOCK:
        if _SINK_FILE is not None:
            try:
                _SINK_FILE.flush()
                _SINK_FILE.close()
            except OSError:
                pass
        _SINK_FILE = None
        _SINK_PATH = None


def _buffer() -> deque:
    """The in-process event buffer, ring-capped by ``CME213_TRACE_BUFFER``
    (read once; ``clear_events`` re-reads).  Caller holds ``_LOCK``."""
    global _EVENTS, _BUFFER_CONFIGURED
    if not _BUFFER_CONFIGURED:
        raw = os.environ.get(TRACE_BUFFER_ENV, "")
        try:
            cap = int(raw) if raw.strip() else 0
        except ValueError:
            cap = 0
        if cap > 0 and _EVENTS.maxlen != cap:
            _EVENTS = deque(_EVENTS, maxlen=cap)
        _BUFFER_CONFIGURED = True
    return _EVENTS


def record_event(event: str, **fields) -> dict:
    """Append a structured event to the in-process log (and the
    ``CME213_TRACE_FILE`` JSON-lines sink, when set).  Returns the record.

    Every record carries ``pid``/``rank``/``incarnation``/``trace``
    process tags (explicit fields win, e.g. the launcher reporting on a
    worker's rank).  Sink writes reuse one cached handle and flush per line, so a
    rank hard-killed mid-solve (``os._exit``) loses nothing it recorded.

    A ``_tail=<key>`` kwarg (used by the request-hop spans) diverts the
    record into the per-request tail-sampling buffer instead — it is
    withheld from the buffer and sink until :func:`tail_decide` keeps or
    drops the request, and never appears as a record field.
    """
    tail_key = fields.pop("_tail", None)
    rec = {"event": event, "t": round(time.time(), 6),
           **_proc_tags(), **fields}
    if tail_key is not None:
        _tail_defer(str(tail_key), rec)
        return rec
    with _LOCK:
        _buffer().append(rec)
        f = _sink_file()
        if f is not None:
            try:
                f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
            except OSError:
                pass  # a broken sink must never take down the workload
    return rec


def events(event: str | None = None) -> list[dict]:
    """Snapshot of recorded events, optionally filtered by event name."""
    with _LOCK:
        snap = list(_EVENTS)
    if event is None:
        return snap
    return [e for e in snap if e["event"] == event]


def clear_events() -> None:
    """Drop recorded events (and the retrace detector's compile counts)
    and re-read the ring-buffer cap env.  The program cache
    (``core/programs.py``) resets with the compile counts: the two move
    together so "first call compiles, later calls hit" stays an invariant
    a fresh telemetry slate can rely on."""
    global _EVENTS, _BUFFER_CONFIGURED
    with _LOCK:
        _EVENTS = deque()
        _BUFFER_CONFIGURED = False
        _COMPILE_COUNTS.clear()
        _TAIL_BUFFERS.clear()
    from . import programs

    programs.reset()


# ------------------------------------------------- tail-based sampling

#: per-request deferred hop-span records, keyed by a process-unique
#: request key; flushed (kept) or discarded (dropped) by ``tail_decide``
_TAIL_BUFFERS: dict[str, list] = {}
_TAIL_ATEXIT_INSTALLED = False


def tail_enabled() -> bool:
    """Whether tail-based sampling is on (``CME213_TRACE_TAIL`` truthy)."""
    raw = os.environ.get(TRACE_TAIL_ENV, "")
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


def head_keep(key) -> bool:
    """Deterministic head-sampling decision for a request: a stable
    ``CME213_TRACE_HEAD_RATE`` fraction of keys (hashed with the trace
    id, so reruns under one trace are reproducible) bypasses the tail
    buffer and is always written."""
    raw = os.environ.get(TRACE_HEAD_RATE_ENV, "")
    try:
        rate = float(raw) if raw.strip() else 0.0
    except ValueError:
        rate = 0.0
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = zlib.crc32(f"{trace_id()}:{key}".encode()) / 0xFFFFFFFF
    return h < rate


def tail_slow_threshold_ms() -> float | None:
    """The explicit "slow" latency keep-threshold, or None when unset."""
    raw = os.environ.get(TRACE_TAIL_SLOW_MS_ENV, "")
    if not raw.strip():
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def tail_keep_reason(status=None, latency_ms=None, requeues=0,
                     drift=False) -> str | None:
    """The tail keep-decision shared by every layer: the reason a
    request's buffered hops must be kept (``shed``/``failed``/
    ``requeued``/``drift``/``slow``), or None for the happy-path drop."""
    if status in ("shed", "failed"):
        return str(status)
    if requeues:
        return "requeued"
    if drift:
        return "drift"
    thresh = tail_slow_threshold_ms()
    if (thresh is not None and latency_ms is not None
            and float(latency_ms) > thresh):
        return "slow"
    return None


def _tail_defer(key: str, rec: dict) -> None:
    """Park ``rec`` in the per-request buffer until ``tail_decide``."""
    global _TAIL_ATEXIT_INSTALLED
    with _LOCK:
        _TAIL_BUFFERS.setdefault(key, []).append(rec)
        if not _TAIL_ATEXIT_INSTALLED:
            atexit.register(_tail_flush_all)
            _TAIL_ATEXIT_INSTALLED = True
    from . import metrics

    metrics.counter("trace.sampling.buffered").inc()


def tail_pending() -> int:
    """Number of requests with undecided buffered hops (test hook)."""
    with _LOCK:
        return len(_TAIL_BUFFERS)


def tail_decide(key, keep: bool, reason: str = "ok") -> int:
    """Resolve one request's buffered hop spans: flush them to the event
    buffer/sink in recorded order (``keep``) or discard them.  Returns
    the number of buffered records resolved (0 for an unknown/undecided
    key — the decision is idempotent).  Feeds the ``trace.sampling.*``
    counters that prove the drop rate."""
    if key is None:
        return 0
    with _LOCK:
        recs = _TAIL_BUFFERS.pop(str(key), None)
    if recs is None:
        return 0
    from . import metrics

    if keep:
        metrics.counter("trace.sampling.kept").inc()
        metrics.counter(f"trace.sampling.kept.{reason}").inc()
        with _LOCK:
            buf = _buffer()
            f = _sink_file()
            for rec in recs:
                buf.append(rec)
                if f is not None:
                    try:
                        f.write(json.dumps(rec, default=str) + "\n")
                    except OSError:
                        pass
            if f is not None:
                try:
                    f.flush()
                except OSError:
                    pass
    else:
        metrics.counter("trace.sampling.dropped").inc()
    return len(recs)


def _tail_flush_all() -> None:
    """Atexit safety net: a process dying with undecided requests keeps
    them — losing the happy path is cheap, losing a crash is not."""
    with _LOCK:
        keys = list(_TAIL_BUFFERS)
    for k in keys:
        tail_decide(k, keep=True, reason="exit")


# ------------------------------------------------------------------ spans

_SPAN_STACK: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "cme213_span_stack", default=())
_SPAN_COUNTER = itertools.count(1)
_SPAN_PREFIX: str | None = None


def _span_prefix() -> str:
    """The per-process span-id prefix.  A bare pid collides across
    incarnations sharing one fleet trace (pid reuse after a relaunch) —
    widen it with the incarnation and a random per-process nonce, minted
    once so ids stay stable within a process."""
    global _SPAN_PREFIX
    if _SPAN_PREFIX is None:
        inc = int(os.environ.get("CME213_INCARNATION", "0") or 0)
        _SPAN_PREFIX = (f"{os.getpid():x}-{inc}-"
                        f"{os.urandom(3).hex()}")
    return _SPAN_PREFIX


def _mint_span_id() -> str:
    return f"{_span_prefix()}.{next(_SPAN_COUNTER)}"


class SpanHandle:
    """Yielded by ``span``: ``.block(*arrays)`` registers device arrays to
    ``jax.block_until_ready`` before the span's clock stops — async device
    work is attributed to the span that launched it, like the reference's
    ``cudaEventSynchronize`` before ``stop_timer``.  ``.roofline(nbytes,
    flops)`` declares the op's cost-model traffic so the ``span-end``
    record carries ``achieved_gbs``/``pct_peak``/``bound`` computed from
    the measured duration (``core/roofline.py``)."""

    __slots__ = ("_blocked", "_roofline")

    def __init__(self) -> None:
        self._blocked: list = []
        self._roofline: tuple | None = None

    def block(self, *arrays) -> None:
        for a in arrays:
            self._blocked.append(a)

    def roofline(self, nbytes: float, flops: float = 0.0) -> None:
        """Declare this span's useful traffic (bytes moved, flops) so its
        end record gains roofline attribution once the duration is known."""
        self._roofline = (float(nbytes), float(flops))


def current_span_id() -> str | None:
    """Id of the innermost open span in this context (None outside any)."""
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else None


class OpenSpan:
    """A manually-closed span for request hops that begin and end on
    different threads (submit on the caller, completion on a receiver
    loop) — no contextvar stack, the parent is wired explicitly.
    ``end`` is idempotent and returns the duration; hop durations feed
    both ``span.<name>.ms`` and, for ``serve.hop.*`` spans, the
    ``serve.hop.<hop>.ms`` histograms."""

    __slots__ = ("name", "id", "parent", "tail_key", "_tags", "_start",
                 "_done")

    def __init__(self, name: str, sid: str, parent: str | None,
                 tail_key: str | None, tags: dict) -> None:
        self.name = name
        self.id = sid
        self.parent = parent
        self.tail_key = tail_key
        self._tags = tags
        self._start = time.perf_counter()
        self._done = False

    def end(self, **extra) -> float | None:
        if self._done:
            return None
        self._done = True
        ms = round((time.perf_counter() - self._start) * 1e3, 3)
        record_event("span-end", span=self.name, id=self.id,
                     parent=self.parent, ms=ms, _tail=self.tail_key,
                     **{**self._tags, **extra})
        from . import metrics

        metrics.histogram(f"span.{self.name}.ms").observe(ms)
        if self.name.startswith("serve.hop."):
            metrics.histogram(f"{self.name}.ms").observe(ms)
        return ms


def begin_span(name: str, parent: str | None = None, tail_key=None,
               head_key=None, **tags) -> OpenSpan:
    """Open a cross-thread request-hop span (see :class:`OpenSpan`).

    ``parent`` overrides the contextvar/inherited default — this is how
    a hop parents under a span id carried over the wire.  When tail
    sampling is on and ``tail_key`` is given (a process-unique request
    key), the begin/end records are deferred under that key until
    :func:`tail_decide`; ``head_key`` (default ``tail_key``) is the
    stable identity hashed for the deterministic head-sampling bypass.
    ``tags`` ride on both records.
    """
    sid = _mint_span_id()
    if parent is None:
        stack = _SPAN_STACK.get()
        parent = stack[-1] if stack else inherited_parent_id()
    key = None
    if tail_key is not None and tail_enabled():
        hk = head_key if head_key is not None else tail_key
        if not head_keep(hk):
            key = str(tail_key)
    record_event("span-begin", span=name, id=sid, parent=parent,
                 _tail=key, **tags)
    return OpenSpan(name, sid, parent, key, tags)


# --------------------------------------------------- clock alignment

class ClockSync:
    """Per-peer wall-clock offset estimator from ping round trips.

    Each sample is the classic midpoint-of-RTT estimate: with local send
    /receive times ``t0``/``t1`` and the peer's reply timestamp ``tr``,
    ``offset = tr - (t0 + t1)/2`` with uncertainty ``rtt/2`` (the true
    offset always lies within ±rtt/2 of the estimate, whatever the
    path asymmetry).  Samples are EWMA-smoothed with one ``alpha`` for
    both the offset and its error bound, which preserves the invariant
    ``|offset_ms - true| <= err_ms`` by convexity.  Pure arithmetic over
    caller-supplied timestamps, so tests drive it from a
    ``VirtualClock``."""

    __slots__ = ("alpha", "offset_ms", "err_ms", "rtt_ms", "samples")

    def __init__(self, alpha: float = 0.4) -> None:
        self.alpha = float(alpha)
        self.offset_ms = 0.0
        self.err_ms = float("inf")
        self.rtt_ms = 0.0
        self.samples = 0

    def update(self, t_send_s: float, t_remote_s: float,
               t_recv_s: float) -> tuple[float, float]:
        """Fold one ping exchange (all seconds; local send/recv on one
        clock, remote timestamp on the peer's).  Returns the smoothed
        ``(offset_ms, err_ms)``."""
        rtt_ms = max(0.0, (t_recv_s - t_send_s) * 1e3)
        off_ms = (t_remote_s - (t_send_s + t_recv_s) / 2.0) * 1e3
        err_ms = rtt_ms / 2.0
        if self.samples == 0:
            self.offset_ms, self.err_ms, self.rtt_ms = off_ms, err_ms, rtt_ms
        else:
            a = self.alpha
            self.offset_ms += a * (off_ms - self.offset_ms)
            self.err_ms += a * (err_ms - self.err_ms)
            self.rtt_ms += a * (rtt_ms - self.rtt_ms)
        self.samples += 1
        return self.offset_ms, self.err_ms


@contextmanager
def span(name: str, **tags):
    """Trace the enclosed block as a ``span-begin``/``span-end`` pair.

    Ids are unique across a gang and across relaunches
    (``<pid hex>-<incarnation>-<nonce>.<counter>``); the parent
    link comes from a contextvar stack, so nesting — including across
    threads started inside a span — produces a causal tree ``trace
    summary`` can aggregate.  ``tags`` ride on both records (kernel rung,
    epoch number, ...).  The span-end carries the monotonic duration
    ``ms`` (after blocking on any ``.block()``-registered arrays) and an
    ``error`` tag when the block raised; the duration also feeds the
    ``span.<name>.ms`` metrics histogram.
    """
    sid = _mint_span_id()
    stack = _SPAN_STACK.get()
    # a root span in a launched child parents under the spawning
    # process's open span (CME213_TRACE_CONTEXT), so a merged multi-rank
    # trace is one causal tree
    parent = stack[-1] if stack else inherited_parent_id()
    record_event("span-begin", span=name, id=sid, parent=parent, **tags)
    token = _SPAN_STACK.set(stack + (sid,))
    handle = SpanHandle()
    err: str | None = None
    start = time.perf_counter()
    try:
        yield handle
        if handle._blocked:
            import jax

            for a in handle._blocked:
                jax.block_until_ready(a)
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        ms = round((time.perf_counter() - start) * 1e3, 3)
        _SPAN_STACK.reset(token)
        end = dict(span=name, id=sid, parent=parent, ms=ms, **tags)
        if err is not None:
            end["error"] = err
        if handle._roofline is not None and err is None and ms > 0:
            try:
                from . import roofline

                nbytes, flops = handle._roofline
                gbs = nbytes / 1e9 / (ms / 1e3)
                att = roofline.attribute(gbs, flops / 1e9 / (ms / 1e3))
                end["achieved_gbs"] = round(gbs, 3)
                if att["pct_peak"] is not None:
                    end["pct_peak"] = att["pct_peak"]
                    end["bound"] = att["bound"]
            except Exception:  # noqa: BLE001 — attribution never kills work
                pass
        record_event("span-end", **end)
        from . import metrics

        metrics.histogram(f"span.{name}.ms").observe(ms)
        if err is None:
            _note_compile_run(name, tags.get("shape_class"), ms,
                              tags.get("kernel"))


# --------------------------------------------------- compile/run split

#: (op, shape_class, kernel) -> completed ``<op>.compile`` span count —
#: the retrace detector's state (ROADMAP item 5: heterogeneous traffic
#: must not re-trace known shape classes).  The kernel rung is part of
#: the key: a fallback ladder (or conformance gate) compiling a SECOND
#: rung for a class it already serves builds a fresh program, not a
#: retrace.  Reset by ``clear_events``.
_COMPILE_COUNTS: dict[tuple, int] = {}


def compile_counts() -> dict[tuple, int]:
    """Snapshot of per-(op, shape_class, kernel) compile counts this
    process (``kernel`` is ``None`` for spans without a kernel tag)."""
    with _LOCK:
        return dict(_COMPILE_COUNTS)


def _note_compile_run(name: str, shape_class, ms: float,
                      kernel=None) -> None:
    """Feed per-(op, shape-class) ``compile.ms``/``run.ms`` histograms
    from ``<op>.compile``/``<op>.run`` spans, and fire the retrace
    detector: a (shape class, kernel) whose compile span completes more
    than once in a process re-entered the trace/compile path — the
    retracing cost the program cache (``core/programs.py``) exists to
    kill — so it emits a ``compile-retrace`` event and bumps the
    ``compile.retraces`` counter.  Errored spans are excluded upstream
    (a rung that failed to compile is a demotion, not a retrace)."""
    if shape_class is None:
        return
    from . import metrics

    if name.endswith(".compile"):
        op = name[: -len(".compile")]
        metrics.histogram(f"compile.{op}.{shape_class}.ms").observe(ms)
        with _LOCK:
            n = _COMPILE_COUNTS[(op, shape_class, kernel)] = (
                _COMPILE_COUNTS.get((op, shape_class, kernel), 0) + 1)
        if n > 1:
            metrics.counter("compile.retraces").inc()
            record_event("compile-retrace", op=op,
                         shape_class=shape_class, kernel=kernel, count=n)
    elif name.endswith(".run"):
        op = name[: -len(".run")]
        metrics.histogram(f"run.{op}.{shape_class}.ms").observe(ms)


@contextmanager
def device_trace(log_dir: str):
    """Capture a device profile of the enclosed block into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
