"""Integration: the resilience layer wired through the solvers, the
launcher and the bench harness, exercised under deterministic injected
faults (no sleeps, no timing races — every fault fires on an exact call
count).

Covers the ISSUE-2 acceptance paths: ladder demotion under injected pallas
failure with correct results on the demoted rung, resume-after-NaN-abort
bitwise-matching an uninterrupted run, corrupt-checkpoint quarantine
(tests/test_resilience.py), a CPU-only rank-kill/restart through
``dist.launch``, and ``bench.run_all`` surviving an injected sweep failure
with a populated ``failures.json``.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from cme213_tpu.core import faults, trace


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    yield
    faults.reset()


# --------------------------------------------------------- spmv ladder

def test_spmv_injected_pallas_failure_demotes_to_blocked():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(512, 16, 15, iters=4, seed=0)
    with faults.injected("fail:spmv_scan.pallas-fused"):
        out = sp.run_spmv_scan(prob, kernel="pallas-fused")
    served = trace.events("served")[-1]
    assert served["op"] == "spmv_scan"
    assert served["rung"] == "blocked" and served["demoted"]
    assert served["failed_rungs"] == ["pallas-fused"]
    # the demoted rung's result is still correct against the f64 golden
    errs = sp.external_check(prob, out)
    assert errs["rel_l2"] < 1e-4, errs


def test_spmv_double_failure_lands_on_flat():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(256, 8, 7, iters=3, seed=1)
    with faults.injected("fail:spmv_scan.pallas,fail:spmv_scan.blocked"):
        out = sp.run_spmv_scan(prob, kernel="pallas")
    assert trace.events("served")[-1]["rung"] == "flat"
    assert sp.external_check(prob, out)["rel_l2"] < 1e-4


def test_spmv_no_faults_serves_requested_rung():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(256, 8, 7, iters=3, seed=2)
    out = sp.run_spmv_scan(prob, kernel="blocked")
    served = trace.events("served")[-1]
    assert served["rung"] == "blocked" and not served["demoted"]
    assert not trace.events("rung-failed")
    assert sp.external_check(prob, out)["rel_l2"] < 1e-4


def test_spmv_fallback_off_is_failfast():
    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.core import FrameworkError

    prob = sp.generate_problem(128, 4, 3, iters=2, seed=3)
    with faults.injected("fail:spmv_scan.flat"):
        with pytest.raises(FrameworkError):
            sp.run_spmv_scan(prob, kernel="flat", fallback=False)


def test_spmv_checkpointed_nan_resume_bitwise(tmp_path):
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(512, 16, 15, iters=6, seed=4)
    with faults.injected("nan:spmv_scan:2"):
        out_faulted = sp.run_spmv_scan_checkpointed(
            prob, str(tmp_path / "f.npz"), every=2, kernel="flat")
    assert trace.events("checkpoint-rollback"), "rollback must have fired"
    out_clean = sp.run_spmv_scan_checkpointed(
        prob, str(tmp_path / "c.npz"), every=2, kernel="flat")
    # resume-and-retry is bitwise-invisible: deterministic chunking
    np.testing.assert_array_equal(out_faulted, out_clean)


# --------------------------------------------------------- heat ladder

def test_heat_pipeline_injected_failure_demotes_bitwise():
    import jax.numpy as jnp

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat
    from cme213_tpu.ops.stencil_pipeline import run_heat_resilient

    p = SimParams(nx=24, ny=24, order=2, iters=4)
    u0 = make_initial_grid(p, dtype=jnp.float32)
    ref = np.asarray(run_heat(jnp.array(u0), p.iters, p.order, p.xcfl,
                              p.ycfl))
    with faults.injected("fail:heat.pipeline"):
        res = run_heat_resilient(jnp.array(u0), p.iters, p.order, p.xcfl,
                                 p.ycfl, p.bc, k=1, interpret=True)
    assert res.rung == "pipeline2d" and res.demoted
    np.testing.assert_array_equal(np.asarray(res.value), ref)

    # both Pallas rungs dead -> the XLA formulation serves, still bitwise
    with faults.injected("fail:heat.pipeline,fail:heat.pipeline2d"):
        res = run_heat_resilient(jnp.array(u0), p.iters, p.order, p.xcfl,
                                 p.ycfl, p.bc, k=1, interpret=True)
    assert res.rung == "xla"
    assert [f.rung for f in res.failures] == ["pipeline", "pipeline2d"]
    np.testing.assert_array_equal(np.asarray(res.value), ref)


def test_heat_single_driver_survives_injected_pallas_failure():
    from cme213_tpu.apps.heat2d import run_single
    from cme213_tpu.config import SimParams

    p = SimParams(nx=24, ny=24, order=2, iters=4)
    with faults.injected("fail:heat.pipeline,fail:heat.pipeline2d"):
        res = run_single(p, check_cpu=True)
    # ULP-vs-golden checks still pass on the demoted rung
    assert res.ok
    assert any("pallas->xla" in r for r in res.reports), res.reports


def test_heat_checkpointed_nan_resume_bitwise(tmp_path):
    from cme213_tpu.apps.heat2d import run_heat_checkpointed
    from cme213_tpu.config import SimParams

    p = SimParams(nx=20, ny=20, order=4, iters=12)
    with faults.injected("nan:heat2d:2"):
        out_faulted = run_heat_checkpointed(p, str(tmp_path / "f.npz"),
                                            every=4)
    out_clean = run_heat_checkpointed(p, str(tmp_path / "c.npz"), every=4)
    np.testing.assert_array_equal(out_faulted, out_clean)


# --------------------------------------------------------- launcher

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a rank body that needs no jax: report rank+incarnation, honor rankkill
_RANK_BODY = (
    f"import sys; sys.path.insert(0, {_REPO!r}); import os; "
    "from cme213_tpu.core import faults; faults.maybe_kill_rank(); "
    "print('rank', os.environ['JAX_PROCESS_ID'], "
    "'incarnation', faults.incarnation(), 'ok')")


def test_launch_rank_kill_restart_survives(monkeypatch, capsys):
    from cme213_tpu.dist.launch import launch

    monkeypatch.setenv("CME213_FAULTS", "rankkill:1:0")
    rc = launch(2, [sys.executable, "-c", _RANK_BODY], max_restarts=1)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "injected kill: rank 1" in out
    assert "restarting (incarnation 1/1)" in out
    assert "rank 1 incarnation 1 ok" in out  # same rank id relaunched
    assert "rank 0 incarnation 0 ok" in out


def test_launch_rank_kill_without_restart_budget_fails(monkeypatch):
    from cme213_tpu.dist.launch import launch

    monkeypatch.setenv("CME213_FAULTS", "rankkill:1:0")
    rc = launch(2, [sys.executable, "-c", _RANK_BODY], max_restarts=0)
    assert rc == faults.KILL_EXIT


def test_launch_timeout_kills_stuck_job():
    from cme213_tpu.dist.launch import launch

    t0 = time.monotonic()
    rc = launch(1, [sys.executable, "-c", "import time; time.sleep(60)"],
                timeout=1.0)
    assert rc == 124
    assert time.monotonic() - t0 < 30


def test_launch_exports_handshake_deadline(capsys):
    from cme213_tpu.dist.launch import launch

    rc = launch(1, [sys.executable, "-c",
                    "import os; print('HS', "
                    "os.environ['CME213_HANDSHAKE_TIMEOUT'], "
                    "os.environ['CME213_INCARNATION'])"],
                handshake_timeout=7.5)
    out = capsys.readouterr().out
    assert rc == 0
    assert "HS 7.5 0" in out


def test_multihost_handshake_deadline_reaches_initialize(monkeypatch):
    import jax

    from cme213_tpu.dist.multihost import initialize_multihost

    seen = {}

    def fake_initialize(**kwargs):
        seen.update(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setenv("CME213_HANDSHAKE_TIMEOUT", "12")
    initialize_multihost(coordinator_address="127.0.0.1:1234",
                         num_processes=2, process_id=0)
    assert seen["initialization_timeout"] == 12
    assert seen["process_id"] == 0


# --------------------------------------------------------- bench harness

def test_run_all_retries_injected_sweep_failure(tmp_path):
    from cme213_tpu.bench.run_all import main

    with faults.injected("fail:sweep.scan_bandwidth"):
        rc = main(["--quick", "--out", str(tmp_path),
                   "--only", "scan_bandwidth"])
    assert rc == 0  # the retry recovered the run
    assert (tmp_path / "scan_bandwidth.csv").exists()
    manifest = json.loads((tmp_path / "failures.json").read_text())
    assert manifest["failed"] == []
    assert [r["sweep"] for r in manifest["retried"]] == ["scan_bandwidth"]
    assert manifest["retried"][0]["error"] == "InjectedFault"


def test_run_all_double_failure_is_recorded_and_nonzero(tmp_path):
    from cme213_tpu.bench.run_all import main

    with faults.injected("fail:sweep.scan_bandwidth:1:2"):
        rc = main(["--quick", "--out", str(tmp_path),
                   "--only", "scan_bandwidth"])
    assert rc == 1  # both attempts failed: the capture layer must see it
    assert not (tmp_path / "scan_bandwidth.csv").exists()
    manifest = json.loads((tmp_path / "failures.json").read_text())
    assert [r["sweep"] for r in manifest["failed"]] == ["scan_bandwidth"]
    assert [r["sweep"] for r in manifest["retried"]] == ["scan_bandwidth"]


def test_run_all_clean_run_writes_empty_manifest(tmp_path):
    from cme213_tpu.bench.run_all import main

    rc = main(["--quick", "--out", str(tmp_path),
               "--only", "scan_bandwidth"])
    assert rc == 0
    manifest = json.loads((tmp_path / "failures.json").read_text())
    assert manifest == {"failed": [], "retried": []}
