"""Concurrent socket transport for the serving front end.

The batching server (``serve/server.py``) is deliberately synchronous:
``submit`` / ``step`` on one thread, deterministic under a virtual
clock.  That leaves ROADMAP item 1's acknowledged gap — nothing could
exert *genuinely concurrent* pressure on the queue.  This module closes
it without giving up the synchronous core: a threaded socket front end
accepts requests from many client connections at once, funnels them
into the one server under a lock, and a background **batcher thread**
drains the queue — the caller-driven ``step()`` loop becomes one of two
drive modes:

- ``drive="caller"`` — nothing runs in the background; the owner calls
  :meth:`TransportServer.pump` to step the server and deliver results.
  Deterministic (virtual-clock friendly): every existing test pattern
  still works with sockets in front.
- ``drive="thread"`` — a daemon batcher thread wakes on every accepted
  request (the ``Server.on_submit`` waker) and steps until the queue is
  empty.  This is the live-serving mode the fleet replicas run.

**Wire protocol** (one frame per message, both directions)::

    [4-byte big-endian length][UTF-8 JSON body]

A request body carries ``{"op", "payload", "tenant", "deadline_ms",
"trace_id"}``; the response is the :class:`~.request.SolveResult`
serialized field-for-field (numpy arrays as base64 ``{"__nd__":
[dtype, shape, data]}`` triples — bitwise round-trip, so a remotely
served solve compares bitwise-equal to a serial one).  A body with a
``"control"`` key instead of ``"op"`` is a control frame (``ping`` /
``stats``) answered by the server without touching the queue.  One
request is in flight per connection — concurrency comes from many
connections, exactly how loadgen's client threads use it.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading

import numpy as np

from ..core import trace
from ..core.faults import incarnation, maybe_kill_replica
from .request import FAILED, SolveResult
from .server import Server

#: response safety net: a transport request that produces no result in
#: this many wall seconds fails with reason "transport-timeout" instead
#: of hanging its client connection forever
RESPONSE_TIMEOUT_S = 120.0

_LEN = struct.Struct(">I")


# ------------------------------------------------------------ framing

def send_frame(sock: socket.socket, doc: dict) -> None:
    body = json.dumps(doc).encode("utf-8")
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> dict | None:
    """One frame, or None on a clean EOF at a frame boundary."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    body = _recv_exact(sock, length)
    if body is None:
        raise ConnectionError("EOF mid-frame")
    return json.loads(body.decode("utf-8"))


# ------------------------------------------------------------ wire codec

def _nd_encode(arr: np.ndarray) -> dict:
    # ascontiguousarray promotes 0-d to (1,): keep the caller's shape
    shape = list(np.shape(arr))
    arr = np.ascontiguousarray(arr)
    return {"__nd__": [str(arr.dtype), shape,
                       base64.b64encode(arr.tobytes()).decode("ascii")]}


def _nd_decode(doc: dict) -> np.ndarray:
    dtype, shape, data = doc["__nd__"]
    return np.frombuffer(base64.b64decode(data),
                         dtype=np.dtype(dtype)).reshape(shape).copy()


def encode_value(value):
    """JSON-encode a result value: numpy/jax arrays become bitwise
    base64 triples; containers recurse; scalars pass through."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, np.ndarray):
        return _nd_encode(value)
    if isinstance(value, (np.generic,)):
        return _nd_encode(np.asarray(value))
    if isinstance(value, (list, tuple)):
        return {"__seq__": [encode_value(v) for v in value]}
    if isinstance(value, dict):
        return {"__map__": {str(k): encode_value(v)
                            for k, v in value.items()}}
    if hasattr(value, "__array__"):     # jax.Array et al.
        return _nd_encode(np.asarray(value))
    return {"__repr__": repr(value)}


def decode_value(doc):
    if isinstance(doc, dict):
        if "__nd__" in doc:
            return _nd_decode(doc)
        if "__seq__" in doc:
            return [decode_value(v) for v in doc["__seq__"]]
        if "__map__" in doc:
            return {k: decode_value(v) for k, v in doc["__map__"].items()}
        if "__repr__" in doc:
            return doc["__repr__"]
    return doc


def encode_payload(op: str, payload) -> dict:
    """Per-op payload serialization (the inverse of
    :func:`decode_payload`); ops are the ``serve.workloads.ADAPTERS``
    keys."""
    if op == "spmv_scan":
        return {"a": _nd_encode(payload.a), "s": _nd_encode(payload.s),
                "k": _nd_encode(payload.k), "x": _nd_encode(payload.x),
                "iters": int(payload.iters)}
    if op == "heat":
        return {k: getattr(payload, k)
                for k in ("nx", "ny", "lx", "ly", "alpha", "iters",
                          "order", "ic", "bc_top", "bc_left",
                          "bc_bottom", "bc_right")}
    if op == "cipher":
        return {"text": _nd_encode(payload.text), "shift": int(payload.shift)}
    raise ValueError(f"no wire codec for op {op!r}")


def decode_payload(op: str, doc: dict):
    if op == "spmv_scan":
        from ..apps.spmv_scan import Problem

        return Problem(a=_nd_decode(doc["a"]), s=_nd_decode(doc["s"]),
                       k=_nd_decode(doc["k"]), x=_nd_decode(doc["x"]),
                       iters=int(doc["iters"]))
    if op == "heat":
        from ..config import SimParams

        return SimParams(**{k: doc[k] for k in doc})
    if op == "cipher":
        from .workloads import CipherRequest

        return CipherRequest(text=_nd_decode(doc["text"]),
                             shift=int(doc["shift"]))
    raise ValueError(f"no wire codec for op {op!r}")


_RESULT_FIELDS = ("rid", "op", "status", "reason", "rung", "shape_class",
                  "latency_ms", "batch_size", "degraded", "tenant",
                  "timing", "trace_id")


def encode_result(res: SolveResult, **extra) -> dict:
    doc = {f: getattr(res, f) for f in _RESULT_FIELDS}
    doc["value"] = encode_value(res.value)
    doc.update(extra)
    return doc


def decode_result(doc: dict) -> SolveResult:
    res = SolveResult(
        **{f: doc.get(f) for f in _RESULT_FIELDS},
        value=decode_value(doc.get("value")))
    # transport-level extras (e.g. which fleet replica served it) ride
    # as plain attributes; consumers use getattr(res, "replica", None)
    for k, v in doc.items():
        if k not in _RESULT_FIELDS and k != "value":
            setattr(res, k, v)
    return res


def parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


# ------------------------------------------------------------ servers

class FrameServer:
    """Threaded accept loop speaking the length-prefixed frame protocol;
    subclasses implement :meth:`handle` (one request doc -> one response
    doc, may block) and optionally extend :meth:`control`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle

    def start(self) -> "FrameServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._sock.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name="transport-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def close(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    # -- plumbing

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="transport-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    doc = recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                if doc is None:
                    return
                try:
                    if "control" in doc:
                        resp = self.control(doc)
                    else:
                        resp = self.handle(doc)
                except Exception as e:       # noqa: BLE001 - wire boundary
                    resp = {"status": FAILED, "reason": "transport",
                            "error": f"{type(e).__name__}: {e}"}
                try:
                    send_frame(conn, resp)
                except (ConnectionError, OSError):
                    return

    # -- overridables

    def handle(self, doc: dict) -> dict:
        raise NotImplementedError

    def control(self, doc: dict) -> dict:
        kind = doc.get("control")
        if kind == "ping":
            return {"ok": True, "pid": os.getpid(),
                    "rank": os.environ.get("JAX_PROCESS_ID", "main"),
                    "incarnation": incarnation()}
        if kind == "stats":
            return {"ok": True, "stats": self.stats()}
        return {"ok": False, "error": f"unknown control {kind!r}"}

    def stats(self) -> dict:
        return {}


class TransportServer(FrameServer):
    """The socket front end over one local :class:`~.server.Server`.

    ``drive="thread"`` starts a background batcher that wakes on every
    accepted request and steps the server until its queue is empty
    (calling the ``replica-kill`` fault guard once per non-empty sweep
    when ``kill_guard`` is set — the fleet replica's deterministic
    mid-batch death point).  ``drive="caller"`` leaves stepping to the
    owner via :meth:`pump`.
    """

    def __init__(self, server: Server, host: str = "127.0.0.1",
                 port: int = 0, drive: str = "thread",
                 poll_interval_s: float = 0.05, kill_guard: bool = False):
        if drive not in ("thread", "caller"):
            raise ValueError(f"drive must be thread|caller, got {drive!r}")
        super().__init__(host, port)
        self.server = server
        self.drive = drive
        self.kill_guard = kill_guard
        self._poll_interval_s = poll_interval_s
        self._mu = threading.Lock()          # guards the synchronous core
        self._wake = threading.Event()
        self._pending: dict[int, list] = {}  # rid -> [Event, result]
        self.batches = 0                     # batcher sweeps that executed
        server.on_submit = self._wake.set

    def start(self) -> "TransportServer":
        super().start()
        if self.drive == "thread":
            t = threading.Thread(target=self._batch_loop,
                                 name="transport-batcher", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    # -- request path (one per connection thread)

    def handle(self, doc: dict) -> dict:
        op = doc["op"]
        payload = decode_payload(op, doc["payload"])
        waiter = None
        with self._mu:
            out = self.server.submit(
                op, payload, deadline_ms=doc.get("deadline_ms"),
                tenant=doc.get("tenant", "default"),
                trace_id=doc.get("trace_id"))
            if isinstance(out, SolveResult):         # shed at the door
                return encode_result(out)
            waiter = [threading.Event(), None]
            self._pending[out] = waiter
        if self.drive == "caller":
            # the owner pumps; just wait for delivery below
            pass
        if not waiter[0].wait(RESPONSE_TIMEOUT_S):
            with self._mu:
                self._pending.pop(out, None)
            return {"rid": out, "op": op, "status": FAILED,
                    "reason": "transport-timeout", "tenant":
                    doc.get("tenant", "default")}
        return encode_result(waiter[1])

    # -- drive modes

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._poll_interval_s)
            self._wake.clear()
            self._sweep()

    def _sweep(self) -> None:
        """Step until the queue is empty, delivering results."""
        while True:
            with self._mu:
                if not len(self.server.queue):
                    return
                if self.kill_guard:
                    maybe_kill_replica()
                results = self.server.step()
                self.batches += 1
                self._deliver_locked(results)

    def pump(self) -> list[SolveResult]:
        """Caller-driven drive mode: one server step + delivery."""
        with self._mu:
            results = self.server.step()
            self._deliver_locked(results)
        return results

    def _deliver_locked(self, results) -> None:
        for res in results:
            waiter = self._pending.pop(res.rid, None)
            if waiter is not None:
                waiter[1] = res
                waiter[0].set()

    def stats(self) -> dict:
        with self._mu:
            return {"queue_depth": len(self.server.queue),
                    "pending": len(self._pending),
                    "batches": self.batches,
                    "degraded": self.server.degraded}


# ------------------------------------------------------------ client

class TransportClient:
    """Blocking client: one connection, one request in flight.  Loadgen
    opens one per worker thread — concurrency across connections."""

    def __init__(self, addr: str, timeout_s: float = RESPONSE_TIMEOUT_S,
                 connect_timeout_s: float = 10.0):
        host, port = parse_addr(addr)
        self.addr = addr
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout_s)
        self._sock.settimeout(timeout_s)
        self._mu = threading.Lock()

    def request(self, doc: dict) -> dict:
        with self._mu:
            send_frame(self._sock, doc)
            resp = recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("server closed connection")
        return resp

    def solve(self, op: str, payload, deadline_ms: float | None = None,
              tenant: str = "default",
              trace_id: str | None = None) -> SolveResult:
        doc = {"op": op, "payload": encode_payload(op, payload),
               "tenant": tenant,
               "trace_id": trace_id or trace.trace_id()}
        if deadline_ms is not None:
            doc["deadline_ms"] = deadline_ms
        return decode_result(self.request(doc))

    def control(self, kind: str, **fields) -> dict:
        return self.request({"control": kind, **fields})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
