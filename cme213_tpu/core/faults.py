"""Deterministic, env-driven fault injection.

The reference's robustness story is fail-fast one-liners — ``check_launch``
aborts on the first CUDA error (``hw/hw1/programming/mp1-util.h:8-18``) and
``MPI_SAFE_CALL`` kills the job (``hw/hw5/programming/2dHeat.cpp:45-51``) —
so nothing in it could ever be *tested* for graceful degradation.  This
module is the other half of that story: a deterministic fault plan, read
once from ``CME213_FAULTS``, that the resilience layer
(``core/resilience.py``, ``core/checkpoint.py``, ``dist/launch.py``,
``bench/run_all.py``) consults at its named guard points.  Faults fire on
exact call counts — never timers or randomness — so every injected failure
is reproducible in CI.

Spec grammar (comma-separated clauses)::

    CME213_FAULTS="clause[,clause...]"

    fail:<op>[:<nth>[:<count>]]   the <nth> call (1-based, default 1) of
                                  ``maybe_fail(op)`` raises InjectedFault,
                                  as do the following <count>-1 calls
                                  (default count 1) — the stand-in for an
                                  XlaRuntimeError out of a named kernel
    nan:<op>[:<nth>]              the <nth> call of ``maybe_poison(op, s)``
                                  returns ``s`` with its first float leaf
                                  NaN-poisoned (a mid-solve blow-up)
    ckpt:truncate[:<nth>]         the <nth> checkpoint file written through
                                  ``maybe_truncate_file`` is cut in half
                                  (a torn write / preempted host)
    ckpt:commit[:<nth>]           the <nth> call of ``maybe_fail_commit``
                                  raises InjectedFault *before* the COMMIT
                                  manifest is published — a crash in the
                                  shard-written-but-uncommitted window of
                                  the distributed commit protocol
                                  (``dist/ckpt.py``); first incarnation
                                  only, so a gang restart recovers
    rankkill:<rank>[:<step>]      ``maybe_kill_rank()`` hard-exits with
                                  ``KILL_EXIT`` on guarded step <step>
                                  (0-based, default 0) when
                                  ``JAX_PROCESS_ID == rank`` and this is the
                                  process's first incarnation
                                  (``CME213_INCARNATION`` unset or 0) — so a
                                  launcher restart survives deterministically
    replica-kill:<rank>[:<nth>]   ``maybe_kill_replica()`` SIGKILLs the
                                  serving replica whose
                                  ``JAX_PROCESS_ID == rank`` on the <nth>
                                  guarded batch (1-based, default 1) —
                                  mid-batch, after requests are accepted
                                  and queued but before they execute, so
                                  the fleet's zero-loss requeue path
                                  (``serve/fleet.py``) is deterministically
                                  testable; the flight recorder dumps
                                  first (SIGKILL skips atexit); first
                                  incarnation only, so the relaunched
                                  replica serves clean
    wrong:<op>[:<nth>]            the <nth> call of ``maybe_perturb(op, v)``
                                  returns ``v`` with ONE element of its
                                  first float leaf perturbed (finite, large)
                                  — the silently-wrong kernel the
                                  conformance gate (``core/conformance.py``)
                                  exists to catch; first incarnation only,
                                  like rankkill
    drift:<op>[:<scale>[:<nth>]]  every call of ``maybe_drift(op, v)`` from
                                  the <nth> (1-based, default 1) onward
                                  returns ``v`` with every float leaf
                                  scaled by ``1 + <scale>`` (default 1e-3)
                                  — a *small* relative error, below the
                                  ``wrong:`` blow-up, that only the shadow
                                  conformance sampler (``core/numerics.py``)
                                  can see; persistent (a drifted kernel
                                  stays drifted) so the drift error budget
                                  deterministically burns; first
                                  incarnation only, like ``wrong:``
    oom:<op>[:<nth>]              the <nth> call of ``maybe_oom(op)`` raises
                                  a synthetic RESOURCE_EXHAUSTED
                                  (``InjectedResourceExhausted``) — the HBM
                                  out-of-memory the admission layer
                                  (``core/admission.py``) degrades under;
                                  first incarnation only
    slow:<op>[:<ms>[:<nth>[:<count>]]]
                                  calls <nth> .. <nth>+<count>-1 (1-based,
                                  default nth 1, count 1) of
                                  ``maybe_slow(op)`` inject <ms>
                                  milliseconds of latency (default 100) —
                                  the deterministic straggler the serving
                                  layer's deadline/degradation paths are
                                  tested against on CPU; a large <count>
                                  models *sustained* overload (what trips
                                  the SLO burn-rate monitor); the sleep
                                  hook is injectable so tests advance a
                                  virtual clock instead of waiting
                                  wall-time; first incarnation only
    unreachable:<nth>[:<count>]   calls <nth> .. <nth>+<count>-1 (1-based)
                                  of ``maybe_unreachable(...)`` report the
                                  device as unreachable — consulted by
                                  ``platform.device_preflight`` and the
                                  doctor's liveness probe
                                  (``core/diag.py``), so a dead device is
                                  deterministically injectable without a
                                  dead device; first incarnation only
    stage:<op>:<stage>[:<nth>[:<count>]]
                                  the <nth> call of
                                  ``maybe_fail_stage(op, stage)`` raises
                                  InjectedFault pre-tagged with the named
                                  dispatch stage (lower | compile |
                                  execute | conformance) — drives the
                                  staged kernel-forensics attribution in
                                  ``core/diag.py`` end to end; first
                                  incarnation only

Op names are dotted paths (``spmv_scan.pallas-fused``, ``heat.pipeline``,
``sweep.heat_bandwidth``); colons are reserved for the grammar.

Zero overhead when disabled: every ``maybe_*`` entry point returns after one
cached ``None`` check, no env re-reads, no jax import at module scope — the
guards live *outside* jitted code by construction.
"""

from __future__ import annotations

import os
import sys
from contextlib import contextmanager
from dataclasses import dataclass, field

#: exit code of an injected rank kill (distinct from shell/timeout codes)
KILL_EXIT = 113


class InjectedFault(RuntimeError):
    """Deterministic injected failure (stands in for XlaRuntimeError)."""

    injected = True


class InjectedResourceExhausted(InjectedFault):
    """Synthetic out-of-memory (stands in for an HBM RESOURCE_EXHAUSTED);
    classified as ``FailureKind.RESOURCE`` by ``classify_failure``."""


class FaultSpecError(ValueError):
    """Malformed CME213_FAULTS clause."""


@dataclass
class _Clause:
    kind: str           # fail | nan | ckpt | rankkill | replica-kill | wrong
                        # | oom | slow | unreachable | stage | drift
    op: str             # op name ("truncate" for ckpt; rank id for rankkill/
                        # replica-kill; "*" for the op-agnostic unreachable)
    nth: int = 1        # 1-based trigger call (rankkill: 0-based step)
    count: int = 1      # consecutive triggered calls (fail/slow/unreachable)
    ms: float = 0.0     # injected latency (slow) / relative scale (drift)
    stage: str = ""     # dispatch stage (stage only)
    calls: int = 0      # mutable per-clause call counter

    def fires(self) -> bool:
        """Advance the counter; True when this call is in the window."""
        self.calls += 1
        return self.nth <= self.calls < self.nth + self.count

    def __str__(self) -> str:
        """Canonical spec text: ``FaultPlan.parse(str(c))`` rebuilds an
        identical clause (modulo the mutable ``calls`` counter), which is
        what lets the chaos runner bank cocktails as replayable JSON
        fixtures (``core/chaos.py``)."""
        if self.kind == "unreachable":
            return f"unreachable:{self.nth}:{self.count}"
        if self.kind == "stage":
            return f"stage:{self.op}:{self.stage}:{self.nth}:{self.count}"
        if self.kind == "slow":
            return f"slow:{self.op}:{self.ms!r}:{self.nth}:{self.count}"
        if self.kind == "drift":
            # count is the parser's persistent 1<<30, not spec text
            return f"drift:{self.op}:{self.ms!r}:{self.nth}"
        if self.kind == "fail":
            return f"fail:{self.op}:{self.nth}:{self.count}"
        # nan | wrong | oom | ckpt | rankkill | replica-kill: kind:op:nth
        return f"{self.kind}:{self.op}:{self.nth}"


@dataclass
class FaultPlan:
    clauses: list[_Clause] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        clauses = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            parts = raw.split(":")
            kind = parts[0]
            if (kind not in ("fail", "nan", "ckpt", "rankkill",
                             "replica-kill", "wrong", "oom", "slow",
                             "unreachable", "stage", "drift")
                    or len(parts) < 2):
                raise FaultSpecError(
                    f"bad fault clause {raw!r} (kinds: fail:<op>[:nth[:count]]"
                    f", nan:<op>[:nth], wrong:<op>[:nth], oom:<op>[:nth], "
                    f"drift:<op>[:scale[:nth]], "
                    f"slow:<op>[:ms[:nth[:count]]], ckpt:truncate[:nth], "
                    f"rankkill:<rank>[:step], replica-kill:<rank>[:nth], "
                    f"unreachable:<nth>[:count], "
                    f"stage:<op>:<stage>[:nth[:count]])")
            try:
                if kind == "fail":
                    clauses.append(_Clause(
                        kind, parts[1],
                        nth=int(parts[2]) if len(parts) > 2 else 1,
                        count=int(parts[3]) if len(parts) > 3 else 1))
                elif kind == "slow":
                    ms = float(parts[2]) if len(parts) > 2 else 100.0
                    if ms < 0:
                        raise FaultSpecError(
                            f"slow clause needs ms >= 0, got {ms}")
                    clauses.append(_Clause(
                        kind, parts[1], ms=ms,
                        nth=int(parts[3]) if len(parts) > 3 else 1,
                        count=int(parts[4]) if len(parts) > 4 else 1))
                elif kind == "unreachable":
                    clauses.append(_Clause(
                        kind, "*",
                        nth=int(parts[1]),
                        count=int(parts[2]) if len(parts) > 2 else 1))
                elif kind == "stage":
                    if len(parts) < 3 or parts[2] not in (
                            "lower", "compile", "execute", "conformance"):
                        raise FaultSpecError(
                            f"stage clause needs stage:<op>:<stage> with "
                            f"stage in lower|compile|execute|conformance, "
                            f"got {raw!r}")
                    clauses.append(_Clause(
                        kind, parts[1], stage=parts[2],
                        nth=int(parts[3]) if len(parts) > 3 else 1,
                        count=int(parts[4]) if len(parts) > 4 else 1))
                elif kind == "drift":
                    scale = float(parts[2]) if len(parts) > 2 else 1e-3
                    if not scale > 0:
                        raise FaultSpecError(
                            f"drift clause needs scale > 0, got {scale}")
                    # persistent from <nth> onward: a drifted kernel stays
                    # drifted, so the shadow sampler's budget can burn
                    clauses.append(_Clause(
                        kind, parts[1], ms=scale,
                        nth=int(parts[3]) if len(parts) > 3 else 1,
                        count=1 << 30))
                elif kind in ("nan", "wrong", "oom"):
                    clauses.append(_Clause(
                        kind, parts[1],
                        nth=int(parts[2]) if len(parts) > 2 else 1))
                elif kind == "ckpt":
                    if parts[1] not in ("truncate", "commit"):
                        raise FaultSpecError(
                            f"unknown ckpt fault {parts[1]!r}")
                    clauses.append(_Clause(
                        kind, parts[1],
                        nth=int(parts[2]) if len(parts) > 2 else 1))
                elif kind == "replica-kill":
                    clauses.append(_Clause(
                        kind, parts[1],
                        nth=int(parts[2]) if len(parts) > 2 else 1))
                else:  # rankkill
                    clauses.append(_Clause(
                        kind, parts[1],
                        nth=int(parts[2]) if len(parts) > 2 else 0))
            except ValueError as e:
                if isinstance(e, FaultSpecError):
                    raise
                raise FaultSpecError(f"bad fault clause {raw!r}: {e}") from e
        return cls(clauses)

    def _matching(self, kind: str, op: str):
        return [c for c in self.clauses if c.kind == kind and c.op == op]

    def __str__(self) -> str:
        """The comma-joined spec; ``parse(str(plan))`` round-trips."""
        return ",".join(str(c) for c in self.clauses)

    def reset_counters(self) -> "FaultPlan":
        """Zero every clause's call counter so an already-used plan can
        be re-armed fresh (fixture replay, repeated chaos campaigns)."""
        for c in self.clauses:
            c.calls = 0
        return self


# cache: None = env not read yet; False = read and disabled
_PLAN: FaultPlan | None | bool = None


def active() -> FaultPlan | None:
    """The installed plan, lazily read from ``CME213_FAULTS`` once."""
    global _PLAN
    if _PLAN is None:
        spec = os.environ.get("CME213_FAULTS", "")
        _PLAN = FaultPlan.parse(spec) if spec.strip() else False
    return _PLAN or None


def install(spec: str) -> FaultPlan:
    """Install a plan programmatically (tests); overrides the env."""
    return install_plan(FaultPlan.parse(spec))


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Install an already-built :class:`FaultPlan`, overriding the env —
    the chaos runner's in-process arming path (``core/chaos.py``): a
    drawn cocktail is armed, driven, then swapped back out without ever
    touching ``CME213_FAULTS``.  The caller owns counter state; use
    ``plan.reset_counters()`` to re-arm a used plan fresh."""
    global _PLAN
    _PLAN = plan
    return plan


def reset() -> None:
    """Forget the cached plan; the next guard re-reads the env."""
    global _PLAN
    _PLAN = None


@contextmanager
def injected(spec: str):
    """Scoped plan installation for tests: counters are fresh inside."""
    prev = _PLAN
    try:
        yield install(spec)
    finally:
        globals()["_PLAN"] = prev


def _record(kind: str, op: str, **fields) -> None:
    from .metrics import counter
    from .trace import record_event

    counter(f"faults.{kind}").inc()
    record_event("fault-injected", kind=kind, op=op, **fields)


def maybe_fail(op: str) -> None:
    """Raise InjectedFault if a ``fail:<op>`` clause fires on this call."""
    plan = active()
    if plan is None:
        return
    for c in plan._matching("fail", op):
        if c.fires():
            _record("fail", op, call=c.calls)
            raise InjectedFault(
                f"injected failure in {op} (call {c.calls})")


def maybe_poison(op: str, state):
    """NaN-poison the first float leaf of ``state`` if a ``nan:<op>``
    clause fires on this call; otherwise return ``state`` unchanged."""
    plan = active()
    if plan is None:
        return state
    fire = any(c.fires() for c in plan._matching("nan", op))
    if not fire:
        return state
    import numpy as np

    try:
        from jax import tree_util
        leaves, treedef = tree_util.tree_flatten(state)
    except ImportError:  # pragma: no cover - jax always present here
        leaves, treedef = [state], None
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            arr = np.array(arr)  # host copy; never mutate a device buffer
            arr.reshape(-1)[0] = np.nan
            leaves[i] = arr
            _record("nan", op, leaf=i)
            break
    return treedef.unflatten(leaves) if treedef is not None else leaves[0]


def maybe_perturb(op: str, value):
    """Perturb ONE element of ``value``'s first float leaf if a
    ``wrong:<op>`` clause fires on this call — the silently-wrong kernel
    the conformance gate exists to catch.  The perturbation is finite and
    large (``x -> x + 1 + |x|``), so it trips both bitwise and declared-
    tolerance comparisons.  First incarnation only (like ``rankkill``), so
    a restarted gang re-probes clean.  Returns ``value`` unchanged when no
    clause fires; never mutates device buffers."""
    plan = active()
    if plan is None:
        return value
    fire = any(c.fires() for c in plan._matching("wrong", op))
    if not fire or incarnation() != 0:
        return value
    import numpy as np

    try:
        from jax import tree_util
        leaves, treedef = tree_util.tree_flatten(value)
    except ImportError:  # pragma: no cover - jax always present here
        leaves, treedef = [value], None
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            arr = np.array(arr)  # host copy; never mutate a device buffer
            flat = arr.reshape(-1)
            flat[0] = flat[0] + 1.0 + abs(flat[0])
            leaves[i] = arr
            _record("wrong", op, leaf=i)
            break
    else:
        # no float leaf (integer-keyed probes, e.g. the sort golden
        # gate): flip one element's bits instead — still ONE element,
        # still finite/large, still dtype-preserving
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if np.issubdtype(arr.dtype, np.integer) and arr.size:
                arr = np.array(arr)
                flat = arr.reshape(-1)
                flat[0] = ~flat[0]
                leaves[i] = arr
                _record("wrong", op, leaf=i)
                break
    return treedef.unflatten(leaves) if treedef is not None else leaves[0]


def maybe_drift(op: str, value):
    """Scale every float leaf of ``value`` by ``1 + scale`` if a
    ``drift:<op>`` clause covers this call — the *small* silent error a
    one-shot conformance probe misses but continuous shadow sampling
    (``core/numerics.py``) catches.  Unlike ``wrong:`` (one element,
    large), drift perturbs whole leaves by a relative amount well below
    the blow-up threshold, and the clause is persistent (every call from
    ``nth`` onward), so the drift error budget burns deterministically.
    First incarnation only, so a restarted gang serves clean.  Returns
    ``value`` unchanged when no clause fires; never mutates device
    buffers."""
    plan = active()
    if plan is None:
        return value
    fired = [c for c in plan._matching("drift", op) if c.fires()]
    if not fired or incarnation() != 0:
        return value
    scale = fired[0].ms
    import numpy as np

    try:
        from jax import tree_util
        leaves, treedef = tree_util.tree_flatten(value)
    except ImportError:  # pragma: no cover - jax always present here
        leaves, treedef = [value], None
    touched = 0
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            # host copy; never mutate a device buffer
            leaves[i] = (np.array(arr) * (1.0 + scale)).astype(arr.dtype)
            touched += 1
    if touched:
        _record("drift", op, leaves=touched, scale=scale)
    return treedef.unflatten(leaves) if treedef is not None else leaves[0]


def maybe_oom(op: str) -> None:
    """Raise a synthetic RESOURCE_EXHAUSTED if an ``oom:<op>`` clause
    fires on this call — the injected HBM out-of-memory the admission
    layer's chunk-shrink response is tested against.  First incarnation
    only, so a restarted solve retries clean."""
    plan = active()
    if plan is None:
        return
    for c in plan._matching("oom", op):
        if c.fires() and incarnation() == 0:
            _record("oom", op, call=c.calls)
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected out-of-memory in {op} "
                f"(call {c.calls})")


def maybe_unreachable(op: str = "device") -> bool:
    """True if an ``unreachable:<nth>`` clause fires on this call — the
    deterministic stand-in for a dead/hung device.  ``op`` names the
    probe point for the ``fault-injected`` record (the clause itself is
    op-agnostic: device death is not scoped to one kernel).  First
    incarnation only, so a launcher restart finds the device back."""
    plan = active()
    if plan is None:
        return False
    fired = False
    for c in plan.clauses:
        if c.kind != "unreachable":
            continue
        if c.fires() and incarnation() == 0:
            _record("unreachable", op, call=c.calls)
            fired = True
    return fired


def maybe_fail_stage(op: str, stage: str) -> None:
    """Raise InjectedFault pre-tagged with ``stage`` if a
    ``stage:<op>:<stage>`` clause fires on this call.  The tag (the
    ``_cme213_stage`` attribute ``core/diag.py`` reads) survives the
    exception's trip up the dispatch ladder, so forensics attribution can
    be tested for every stage without a real Mosaic/XLA failure.  First
    incarnation only."""
    plan = active()
    if plan is None:
        return
    for c in plan.clauses:
        if c.kind != "stage" or c.op != op or c.stage != stage:
            continue
        if c.fires() and incarnation() == 0:
            _record("stage", op, stage=stage, call=c.calls)
            e = InjectedFault(
                f"injected {stage}-stage failure in {op} (call {c.calls})")
            e._cme213_stage = stage  # read by diag.failure_stage
            raise e


def maybe_slow(op: str, sleep=None) -> float:
    """Inject deterministic latency if a ``slow:<op>`` clause fires on
    this call — the straggler stand-in for a contended device or a slow
    collective.  Calls ``sleep(seconds)`` (default ``time.sleep``; pass a
    virtual clock's sleep so tests never wait wall-time) and returns the
    injected milliseconds (0.0 when nothing fired).  First incarnation
    only, like ``oom:``/``wrong:``, so a restarted solve runs at speed."""
    plan = active()
    if plan is None:
        return 0.0
    total = 0.0
    for c in plan._matching("slow", op):
        if c.fires() and incarnation() == 0:
            _record("slow", op, ms=c.ms, call=c.calls)
            total += c.ms
    if total:
        if sleep is None:
            import time
            sleep = time.sleep
        sleep(total / 1e3)
    return total


def maybe_truncate_file(path: str) -> bool:
    """Cut ``path`` in half if a ``ckpt:truncate`` clause fires (the torn
    checkpoint write).  Returns True when the file was damaged."""
    plan = active()
    if plan is None:
        return False
    if not any(c.fires() for c in plan._matching("ckpt", "truncate")):
        return False
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    _record("ckpt-truncate", path, bytes=size // 2)
    return True


def maybe_fail_commit() -> None:
    """Raise InjectedFault before a distributed COMMIT publish if a
    ``ckpt:commit`` clause fires — the shard-files-written-but-manifest-
    unpublished crash window of ``dist/ckpt.py``.  Like ``rankkill``,
    gated to the first incarnation so a supervised gang restart recovers
    deterministically instead of re-crashing forever."""
    plan = active()
    if plan is None:
        return
    for c in plan._matching("ckpt", "commit"):
        if c.fires() and incarnation() == 0:
            _record("ckpt-commit-abort", "commit", call=c.calls)
            raise InjectedFault(
                f"injected crash before COMMIT publish (call {c.calls})")


def incarnation() -> int:
    """This process's launcher restart count (0 = first launch)."""
    return int(os.environ.get("CME213_INCARNATION", "0") or "0")


def maybe_kill_rank(step: int | None = None) -> None:
    """Hard-exit (``os._exit(KILL_EXIT)``) if a ``rankkill`` clause matches
    this rank at this guarded step, first incarnation only.

    ``step=None`` uses the clause's own call counter as the step index, so
    a solver can simply call this once per chunk.
    """
    plan = active()
    if plan is None:
        return
    rank = os.environ.get("JAX_PROCESS_ID", "0")
    for c in plan.clauses:
        if c.kind != "rankkill" or c.op != rank:
            continue
        at = step if step is not None else c.calls
        c.calls += 1
        if at == c.nth and incarnation() == 0:
            _record("rankkill", rank, step=at)
            sys.stderr.write(
                f"[faults] injected kill: rank {rank} at step {at}\n")
            sys.stderr.flush()
            # os._exit skips atexit AND sys.excepthook — the flight
            # recorder must dump here or the event ring dies with us
            from . import flight
            flight.dump("rankkill")
            os._exit(KILL_EXIT)


def maybe_kill_replica() -> None:
    """SIGKILL this serving replica if a ``replica-kill`` clause matches
    this rank on this guarded batch, first incarnation only.

    The replica worker (``serve/fleet.py``) calls this once per batch,
    after requests have been accepted into its queue but before they
    execute — the exact window where the fleet's in-flight requeue path
    must prove zero accepted-request loss.  SIGKILL (unlike ``os._exit``)
    is how an OOM-killed or preempted replica actually dies, so the
    flight recorder dumps *before* the signal is raised.
    """
    plan = active()
    if plan is None:
        return
    rank = os.environ.get("JAX_PROCESS_ID", "0")
    for c in plan.clauses:
        if c.kind != "replica-kill" or c.op != rank:
            continue
        if c.fires() and incarnation() == 0:
            _record("replica-kill", rank, call=c.calls)
            sys.stderr.write(
                f"[faults] injected replica kill: rank {rank} at batch "
                f"{c.calls}\n")
            sys.stderr.flush()
            # SIGKILL skips atexit AND signal handlers — the flight
            # recorder must dump here or the event ring dies with us
            import signal

            from . import flight
            flight.dump("replica-kill")
            os.kill(os.getpid(), signal.SIGKILL)
