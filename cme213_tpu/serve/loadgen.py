"""Deterministic load generator + SLO report for the serving front end.

``python -m cme213_tpu serve loadgen`` drives a :class:`~.server.Server`
with a synthetic request population drawn from the hw workload mix and
reports what the paper's operator would ask of a serving tier: p50/p99
latency, throughput, shed rate, breaker transitions, batching occupancy.

Two arrival disciplines:

- **closed** (default): a fixed concurrency window — submit until the
  window is full, step, repeat.  Offered load adapts to service rate, so
  the run is CPU-deterministic (same seed → same batches) and measures
  steady-state behaviour: batching efficiency, latency distribution.
- **open**: arrivals ignore completions — requests land in bursts of
  ``--burst`` regardless of queue state.  Offered load over capacity is
  *guaranteed* to shed, which is the point: this is the overload smoke
  (``scripts/faultcheck.sh``) that proves backpressure refuses the
  excess instead of melting.

Fault clauses compose naturally: run under ``CME213_FAULTS=
"fail:serve.cipher.packed:1:4"`` and the report's ``breaker`` section
shows the open/half-open/close transitions; ``slow:serve.heat:50``
stretches the latency tail.  ``--baseline`` replays the same request
sequence through a ``max_batch=1`` server and reports the batched/serial
throughput ratio — the serving tier's reason to exist, measured.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..core import flight, metrics, numerics, trace
from ..core.metrics import _nearest_rank
from ..core.resilience import Clock
from . import slo as slo_mod
from .request import OK, SHED, FAILED, PHASES, RequestSpec
from .server import Server

#: ops the ``--mix`` flag accepts, comma-separated.  ``stub`` is the
#: transport-measurement op: the adapter echoes the payload with no jax
#: on the path, so a closed-loop run over it measures the wire + queue
#: cost alone (the tier-1 >= 10k req/s gate drives this mix).
MIX_OPS = ("spmv", "heat", "cipher", "sort", "stub")


def build_mix(mix: str, requests: int, seed: int = 0,
              deadline_ms: float | None = None,
              tenants: int = 1, stub_bytes: int = 1024) -> list[RequestSpec]:
    """The synthetic request population: ``requests`` specs cycling
    through the ops named in ``mix``, shapes chosen so that same-op
    requests recur in a handful of shape classes (batching has something
    to coalesce) without being identical payloads.  ``tenants`` > 1
    round-robins the specs over tenants ``t0..t{n-1}`` so per-tenant
    attribution has something to attribute."""
    ops = [o.strip() for o in mix.split(",") if o.strip()]
    unknown = [o for o in ops if o not in MIX_OPS]
    if unknown:
        raise ValueError(f"unknown mix op(s) {unknown} (choose from {MIX_OPS})")
    rng = np.random.default_rng(seed)
    specs: list[RequestSpec] = []
    for i in range(requests):
        op = ops[i % len(ops)]
        tenant = f"t{i % tenants}" if tenants > 1 else "default"
        if op == "spmv":
            from ..apps.spmv_scan import generate_problem

            n = (512, 1024)[(i // len(ops)) % 2]  # two shape classes
            prob = generate_problem(n, p=max(2, n // 64), q=n // 2,
                                    iters=6, seed=seed + i)
            specs.append(RequestSpec("spmv_scan", prob,
                                     deadline_ms=deadline_ms, tenant=tenant))
        elif op == "stub":
            # one shape class on purpose: every request batches with its
            # neighbours and the measured cost is pure transport + queue
            specs.append(RequestSpec(
                "stub", rng.integers(0, 255, size=stub_bytes)
                .astype(np.uint8),
                deadline_ms=deadline_ms, tenant=tenant))
        elif op == "sort":
            # two shape classes, like spmv: same-sized requests batch,
            # uint32 keys so every rung (lax/radix/bitonic) is eligible
            n = (512, 1024)[(i // len(ops)) % 2]
            specs.append(RequestSpec(
                "sort", rng.integers(0, 2**32, size=n, dtype=np.uint32),
                deadline_ms=deadline_ms, tenant=tenant))
        elif op == "heat":
            from ..config import SimParams

            params = SimParams(nx=24, ny=24, order=2, iters=4,
                               alpha=float(rng.uniform(0.5, 2.0)))
            specs.append(RequestSpec("heat", params,
                                     deadline_ms=deadline_ms, tenant=tenant))
        else:
            from .workloads import CipherRequest

            text = rng.integers(0, 200, size=4096).astype(np.uint8)
            specs.append(RequestSpec(
                "cipher", CipherRequest(text, int(rng.integers(0, 56))),
                deadline_ms=deadline_ms, tenant=tenant))
    return specs


def run_load(server: Server, specs: list[RequestSpec],
             mode: str = "closed", concurrency: int = 8,
             burst: int = 16, clock: Clock | None = None) -> dict:
    """Drive ``server`` with ``specs`` under the chosen arrival
    discipline; returns ``{"results": [...], "elapsed_s": float}``."""
    clock = clock if clock is not None else server.clock
    results = []
    t0 = clock.now()
    if mode == "closed":
        pending = list(specs)
        inflight = 0
        while pending or inflight:
            while pending and inflight < concurrency:
                spec = pending.pop(0)
                out = server.submit(spec.op, spec.payload,
                                    deadline_ms=spec.deadline_ms,
                                    tenant=spec.tenant)
                if isinstance(out, int):
                    inflight += 1
                else:
                    results.append(out)  # shed at submit
            stepped = server.step()
            inflight -= len(stepped)
            results.extend(stepped)
    elif mode == "open":
        pending = list(specs)
        while pending:
            for spec in pending[:burst]:
                out = server.submit(spec.op, spec.payload,
                                    deadline_ms=spec.deadline_ms,
                                    tenant=spec.tenant)
                if not isinstance(out, int):
                    results.append(out)
            pending = pending[burst:]
            results.extend(server.step())  # one service slot per burst
        results.extend(server.drain())
    else:
        raise ValueError(f"unknown mode {mode!r} (closed | open)")
    return {"results": results, "elapsed_s": clock.now() - t0}


def run_load_transport(addr: str, specs: list[RequestSpec],
                       mode: str = "closed", concurrency: int = 8,
                       burst: int = 16,
                       burst_interval_s: float = 0.005,
                       pipeline: int = 1) -> dict:
    """Drive a socket front end (``serve/transport.py`` — one server or
    a whole fleet) with **real concurrent client threads**, which the
    in-process :func:`run_load` cannot do.  Closed keeps ``concurrency``
    connections each with ``pipeline`` requests in flight (the v2
    submit/result window — ``pipeline=1`` degenerates to the blocking
    solve loop, which also covers v1 servers); open fires every
    request in its own thread, ``burst`` at a time, arrivals ignoring
    completions — genuine concurrent pressure on the accept path."""
    import threading
    import time as time_mod

    from .request import SolveResult
    from .transport import TransportClient

    results: list = []
    mu = threading.Lock()

    def _failed(spec: RequestSpec, err: Exception) -> SolveResult:
        return SolveResult(-1, spec.op, FAILED, reason="transport",
                           tenant=spec.tenant)

    t0 = time_mod.monotonic()
    if mode == "closed":
        remaining = list(specs)

        def _take(k: int) -> list[RequestSpec]:
            with mu:
                out, remaining[:k] = remaining[:k], []
                return out

        def worker() -> None:
            client = None
            window: list[tuple[int, RequestSpec]] = []  # (rid, spec) FIFO
            batch: list[RequestSpec] = []               # taken, not sent

            def settle_many(rs: list) -> None:
                with mu:
                    results.extend(rs)

            while True:
                try:
                    if not batch and not window:
                        batch = _take(max(1, pipeline))
                        if not batch:
                            break
                    if client is None:
                        # sync pipelined mode: this worker is the only
                        # caller, so it parses responses itself instead
                        # of paying a receiver-thread handoff per request
                        client = TransportClient(addr, recv_thread=False)
                    if client.proto != 2 or pipeline <= 1:
                        # stop-and-wait (the only v1 option)
                        spec = batch.pop(0)
                        settle_many([client.solve(
                            spec.op, spec.payload,
                            deadline_ms=spec.deadline_ms,
                            tenant=spec.tenant)])
                        continue
                    # sliding window: fill to depth (submits corked,
                    # one vectored write for the whole refill), then
                    # retire the oldest half — ``pipeline`` requests
                    # ride one connection and the syscall + lock count
                    # is ~2/chunk, not 2/request
                    while len(window) < pipeline:
                        if not batch:
                            batch = _take(pipeline - len(window))
                            if not batch:
                                break
                        spec = batch.pop(0)
                        window.append((client.submit(
                            spec.op, spec.payload,
                            deadline_ms=spec.deadline_ms,
                            tenant=spec.tenant, flush=False), spec))
                    client.flush()
                    done = []
                    for _ in range(min(len(window),
                                       max(1, pipeline // 2))):
                        rid, _ = window[0]
                        done.append(client.result(rid))
                        window.pop(0)
                    settle_many(done)
                except (OSError, ConnectionError, ValueError,
                        TimeoutError, KeyError) as e:
                    if client is not None:
                        client.close()
                        client = None
                    # everything on the dead connection fails, plus one
                    # unsent spec so a dead server can't spin this loop;
                    # the rest of the unsent batch goes back in the pool
                    dead = [_failed(lost, e) for _, lost in window]
                    window = []
                    if batch:
                        dead.append(_failed(batch.pop(0), e))
                        if batch:
                            with mu:
                                remaining[:0] = batch
                            batch = []
                    settle_many(dead)
            if client is not None:
                client.close()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, min(concurrency, len(specs))))]
    elif mode == "open":
        def fire(spec: RequestSpec) -> None:
            try:
                with TransportClient(addr) as client:
                    res = client.solve(spec.op, spec.payload,
                                       deadline_ms=spec.deadline_ms,
                                       tenant=spec.tenant)
            except (OSError, ConnectionError, ValueError) as e:
                res = _failed(spec, e)
            with mu:
                results.append(res)

        threads = [threading.Thread(target=fire, args=(spec,), daemon=True)
                   for spec in specs]
    else:
        raise ValueError(f"unknown mode {mode!r} (closed | open)")

    # gc pauses inside the drive window read as multi-ms latency spikes
    # that have nothing to do with the transport under test; collect
    # once up front, then hold gc off until the window closes
    import gc
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        if mode == "open":
            # arrivals ignore completions: launch in bursts, never wait
            for i, t in enumerate(threads):
                t.start()
                if burst and (i + 1) % burst == 0:
                    time_mod.sleep(burst_interval_s)
        else:
            for t in threads:
                t.start()
        for t in threads:
            t.join()
    finally:
        if gc_was_on:
            gc.enable()
    return {"results": results, "elapsed_s": time_mod.monotonic() - t0}


def fleet_section(run: dict, addr: str) -> dict:
    """The SLO report's ``fleet`` section for a ``--transport`` run:
    which replicas served (stamped on each wire response), plus the
    front tier's own routing stats via a ``stats`` control frame."""
    from .transport import TransportClient

    seen = sorted({r.replica for r in run["results"]
                   if getattr(r, "replica", None) is not None})
    section: dict = {"replicas_seen": [f"r{n}" for n in seen]}
    try:
        with TransportClient(addr, timeout_s=5.0) as client:
            stats = client.control("stats").get("stats") or {}
    except (OSError, ConnectionError, ValueError):
        stats = {}
    for key in ("replicas_up", "requeues", "scale_ups", "scale_downs",
                "occupancy", "backlog", "replicas", "flight_confirmed"):
        if key in stats:
            section[key] = stats[key]
    return section


def transport_section(run: dict, before: dict, after: dict) -> dict:
    """The SLO report's ``transport`` subsection: where a wire request's
    milliseconds actually went.  Client-side attribution rides each
    result (``res.client`` — encode/decode ms and the submit→response
    RTT measured at the socket); server-side codec cost comes from the
    ``serve.request.encode_ms``/``decode_ms`` histograms the transport
    layer feeds (the same numbers ``trace summary`` renders).  The
    honest-measurement gate reads ``codec_share``: the p99 of per-request
    client encode+decode as a fraction of the p99 RTT — transport framing
    is an overhead and must price like one."""
    infos = [r.client for r in run["results"]
             if getattr(r, "client", None)]
    enc = [i["encode_ms"] for i in infos if "encode_ms" in i]
    dec = [i["decode_ms"] for i in infos if "decode_ms" in i]
    rtt = [i["rtt_ms"] for i in infos if "rtt_ms" in i]
    codec = [i.get("encode_ms", 0.0) + i.get("decode_ms", 0.0)
             for i in infos]
    # wire + queue time: RTT minus the server's own request clock (the
    # timing breakdown every served result carries)
    overhead = [r.client["rtt_ms"] - r.timing["total_ms"]
                for r in run["results"]
                if getattr(r, "client", None)
                and "rtt_ms" in r.client
                and r.timing and r.timing.get("total_ms") is not None]

    d = metrics.delta(before, after)
    bh, ah = before.get("histograms", {}), after.get("histograms", {})

    def hist_delta(name: str) -> dict | None:
        h, p = ah.get(name), bh.get(name) or {}
        if not h:
            return None
        n = int(h.get("count", 0)) - int(p.get("count", 0))
        if n <= 0:
            return None
        s = float(h.get("sum") or 0.0) - float(p.get("sum") or 0.0)
        return {"count": n, "mean": round(s / n, 4)}

    section = {
        "client": {"encode_ms": _pcts(enc), "decode_ms": _pcts(dec),
                   "rtt_ms": _pcts(rtt)},
        "server": {"encode_ms": hist_delta("serve.request.encode_ms"),
                   "decode_ms": hist_delta("serve.request.decode_ms")},
        "transport_ms": _pcts(overhead),
        "proto_v1_frames": d["counters"].get("transport.proto_v1", 0),
    }
    codec_p = _pcts(codec)
    rtt_p = _pcts(rtt)
    if codec_p and rtt_p and rtt_p["p99"]:
        section["codec_share"] = round(codec_p["p99"] / rtt_p["p99"], 4)
    return section


def _waterfall_segments(rtt_ms: float, hops: dict, timing: dict) -> dict:
    """Decompose one wire request's RTT into disjoint hop segments:
    client wire+codec, front-tier residency (DRR wait + requeue detours),
    replica-side waiting (queue/admit/batch-wait), and the kernel run.
    Segments a layer didn't report (e.g. no front tier on a single
    TransportServer) are None, not zero."""
    total = timing.get("total_ms")
    route = hops.get("route_ms")
    dispatch = hops.get("dispatch_ms")
    inner = route if route is not None else total
    wire = round(max(0.0, rtt_ms - inner), 3) if inner is not None else None
    front = (round(max(0.0, route - dispatch), 3)
             if route is not None and dispatch is not None else None)
    waits = [timing.get(k) for k in ("queue_ms", "admit_ms",
                                     "batch_wait_ms")]
    replica_wait = (round(sum(w for w in waits if w is not None), 3)
                    if any(w is not None for w in waits) else None)
    return {"wire_ms": wire, "front_ms": front,
            "replica_wait_ms": replica_wait,
            "run_ms": timing.get("run_ms")}


def waterfall_section(run: dict, before: dict, after: dict) -> dict:
    """The SLO report's ``waterfall`` section for a ``--transport`` run:
    per-segment latency percentiles from the hop breakdown each response
    carries (``res.hops`` — the front tier's route/dispatch/requeue
    residency — joined with the replica's phase timing), a decomposition
    of the p99-RTT request naming its **dominant** hop, and the
    tail-sampling counters that prove the post-hoc drop rate."""
    rows = []
    for r in run["results"]:
        info = getattr(r, "client", None) or {}
        rtt = info.get("rtt_ms")
        if rtt is None:
            continue
        rows.append((rtt, _waterfall_segments(
            rtt, getattr(r, "hops", None) or {}, r.timing or {})))
    section: dict = {}
    if rows:
        hops_p: dict[str, dict] = {}
        for key in ("wire_ms", "front_ms", "replica_wait_ms", "run_ms"):
            p = _pcts(seg.get(key) for _, seg in rows)
            if p is not None:
                hops_p[key] = p
        if hops_p:
            section["hops"] = hops_p
        rows.sort(key=lambda x: x[0])
        # nearest-rank p99 row: sorted[ceil(0.99 * n) - 1]
        rtt, seg = rows[min(len(rows) - 1,
                            max(0, -(-99 * len(rows)) // 100 - 1))]
        present = {k: v for k, v in seg.items() if v is not None}
        section["p99"] = {
            "rtt_ms": round(rtt, 3),
            "segments": present,
            "dominant": (max(present, key=present.get)
                         if present else None),
        }
    d = metrics.delta(before, after)["counters"]
    kept = d.get("trace.sampling.kept", 0)
    dropped = d.get("trace.sampling.dropped", 0)
    if d.get("trace.sampling.buffered", 0) or kept or dropped:
        section["sampling"] = {
            "buffered": d.get("trace.sampling.buffered", 0),
            "kept": kept,
            "dropped": dropped,
            "keep_rate": (round(kept / (kept + dropped), 4)
                          if kept + dropped else None),
            "kept_by_reason": {
                k[len("trace.sampling.kept."):]: v for k, v in d.items()
                if k.startswith("trace.sampling.kept.")},
        }
    return section


def compile_attribution(before: dict, after: dict) -> dict:
    """Per-shape-class compile-vs-run attribution from the metrics delta:
    how much of the pass went to (re)tracing (``compile.<op>.<class>.ms``)
    vs executing (``run.<op>.<class>.ms``), plus the retrace count and the
    program cache's hit/miss counts.  ``compile_share`` near zero is the
    warmed steady state the program cache exists to reach."""
    bh, ah = before.get("histograms", {}), after.get("histograms", {})
    bc, ac = before.get("counters", {}), after.get("counters", {})

    def counter_delta(name: str) -> int:
        return int(ac.get(name, 0)) - int(bc.get(name, 0))

    per_class: dict[str, dict] = {}
    totals = {"compile": 0.0, "run": 0.0}
    for name, h in ah.items():
        for kind, ms_key, n_key in (("compile", "compile_ms", "compiles"),
                                    ("run", "run_ms", "runs")):
            if not (name.startswith(kind + ".") and name.endswith(".ms")):
                continue
            key = name[len(kind) + 1:-3]
            prev = bh.get(name) or {}
            d_ms = float(h.get("sum") or 0.0) - float(prev.get("sum") or 0.0)
            d_n = int(h.get("count", 0)) - int(prev.get("count", 0))
            if d_n <= 0:
                continue
            row = per_class.setdefault(
                key, {"compile_ms": 0.0, "compiles": 0,
                      "run_ms": 0.0, "runs": 0})
            row[ms_key] = round(row[ms_key] + d_ms, 3)
            row[n_key] += d_n
            totals[kind] += d_ms
    total = totals["compile"] + totals["run"]
    return {
        "per_class": per_class,
        "compile_ms": round(totals["compile"], 3),
        "run_ms": round(totals["run"], 3),
        "compile_share": round(totals["compile"] / total, 4) if total else 0.0,
        "retraces": counter_delta("compile.retraces"),
        "cache_hits": counter_delta("programs.hits"),
        "cache_misses": counter_delta("programs.misses"),
    }


def submit_job_over(addr: str, args) -> dict:
    """Submit the ``--job`` long job over the transport's control
    channel before the interactive load starts (idempotent: a duplicate
    submit adopts the existing record)."""
    from .transport import TransportClient

    params = {"nodes": args.job_nodes, "iters": args.job_iters,
              "epoch": args.job_epoch}
    with TransportClient(addr, timeout_s=10.0) as client:
        reply = client.control("job-submit", job=args.job, op=args.job_op,
                               params=params)
    if not reply.get("ok"):
        return {"submitted": False, "error": reply.get("error")}
    return {"submitted": True, "created": reply.get("created"),
            "job": reply.get("job")}


def wait_job_over(addr: str, args, section: dict) -> dict:
    """After the load pass: poll ``--job`` until it is terminal (or the
    ``--job-wait-s`` budget runs out) and return the report section —
    the durable record's final public view plus how it got there."""
    import time as time_mod

    from .transport import TransportClient

    out = {"job": args.job, "op": args.job_op,
           "submitted": section.get("submitted", False),
           "created": section.get("created")}
    if not section.get("submitted"):
        out["state"] = None
        out["error"] = section.get("error", "submit failed")
        return out
    deadline = time_mod.monotonic() + args.job_wait_s
    rec = None
    while time_mod.monotonic() < deadline:
        try:
            with TransportClient(addr, timeout_s=10.0) as client:
                reply = client.control("job-status", job=args.job)
        except (OSError, ConnectionError, ValueError):
            time_mod.sleep(0.25)
            continue
        rec = reply.get("job") if reply.get("ok") else None
        if rec and rec["state"] in ("DONE", "FAILED", "STALLED"):
            break
        time_mod.sleep(0.25)
    if rec is None:
        out["state"] = None
        out["error"] = "status unavailable"
        return out
    out.update({k: rec.get(k) for k in
                ("state", "epoch", "total_epochs", "iters", "total_iters",
                 "residual", "resumes", "preemptions", "reason")})
    if rec["state"] not in ("DONE", "FAILED", "STALLED"):
        out["error"] = f"not terminal after {args.job_wait_s}s"
    return out


def _pcts(values) -> dict | None:
    """{p50, p99} by nearest rank, or None with no samples."""
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    return {"p50": round(_nearest_rank(vals, 0.50), 3),
            "p99": round(_nearest_rank(vals, 0.99), 3)}


def phase_attribution(served) -> dict:
    """Per-op (plus ``overall``) p50/p99 for each lifecycle phase, from
    the served results' ``timing`` breakdowns."""
    by_op: dict[str, list] = {}
    for r in served:
        if r.timing:
            by_op.setdefault(r.op, []).append(r.timing)
    out: dict[str, dict] = {}
    groups = {"overall": [t for ts in by_op.values() for t in ts], **by_op}
    for group, timings in groups.items():
        row = {}
        for phase in PHASES + ("total",):
            p = _pcts(t.get(f"{phase}_ms") for t in timings)
            if p is not None:
                row[phase] = p
        if row:
            out[group] = row
    return out


def tenant_attribution(results) -> dict:
    """Per-tenant request accounting + served-latency percentiles."""
    out: dict[str, dict] = {}
    for r in results:
        row = out.setdefault(r.tenant, {"requests": 0, "served": 0,
                                        "shed": 0, "failed": 0,
                                        "_lat": []})
        row["requests"] += 1
        if r.status == OK:
            row["served"] += 1
            if r.latency_ms is not None:
                row["_lat"].append(r.latency_ms)
        elif r.status == SHED:
            row["shed"] += 1
        else:
            row["failed"] += 1
    for row in out.values():
        row["latency_ms"] = _pcts(row.pop("_lat"))
    return out


def slo_report(run: dict, before: dict, after: dict, slo=None) -> dict:
    """The SLO view of a :func:`run_load` run: latency percentiles over
    served requests, throughput, shed accounting, breaker transitions,
    per-phase and per-tenant attribution — computed from the results plus
    the metrics-registry delta (the same numbers ``trace summary`` reads
    from the trace file)."""
    results = run["results"]
    served = [r for r in results if r.status == OK]
    shed = [r for r in results if r.status == SHED]
    failed = [r for r in results if r.status == FAILED]
    lat = sorted(r.latency_ms for r in served if r.latency_ms is not None)

    def pct(q):
        v = _nearest_rank(lat, q)
        return None if v is None else round(v, 3)

    d = metrics.delta(before, after)
    counters = d["counters"]
    shed_by_reason: dict[str, int] = {}
    for r in shed:
        shed_by_reason[r.reason] = shed_by_reason.get(r.reason, 0) + 1
    elapsed = run["elapsed_s"]
    sizes = [r.batch_size for r in served if r.batch_size]
    return {
        # the process-spanning trace id this session's records carry —
        # inherited from a launcher when run under one, so the report is
        # joinable against the merged gang trace
        "trace_id": trace.trace_id(),
        "requests": len(results),
        "served": len(served),
        "shed": len(shed),
        "failed": len(failed),
        "shed_rate": round(len(shed) / len(results), 4) if results else 0.0,
        "shed_by_reason": shed_by_reason,
        "latency_ms": {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
                       "max": round(lat[-1], 3) if lat else None},
        "elapsed_s": round(elapsed, 4),
        "throughput_rps": (round(len(served) / elapsed, 2)
                           if elapsed > 0 else None),
        "batches": counters.get("serve.batches", 0),
        "batch_mean_size": (round(sum(sizes) / len(sizes), 2)
                            if sizes else None),
        "degraded_served": sum(1 for r in served if r.degraded),
        "breaker": {
            "opened": counters.get("breaker.open", 0),
            "half_open": counters.get("breaker.half_open", 0),
            "closed": counters.get("breaker.close", 0),
            "skipped": counters.get("breaker.skipped", 0),
        },
        "demotions": counters.get("fallback.demotions", 0),
        "compile": compile_attribution(before, after),
        "phases": phase_attribution(served),
        "tenants": tenant_attribution(results),
        "slo": {
            "objectives": slo.state() if slo is not None else {},
            "burn_events": len(trace.events("slo-burn")),
            "ok_events": len(trace.events("slo-ok")),
        },
        # numeric health (core/numerics.py): shadow-sample drift counts
        # from the metrics delta + the drift budget's live snapshot
        "numerics": {
            "shadow_samples": counters.get("numerics.shadow.samples", 0),
            "shadow_over_budget":
                counters.get("numerics.shadow.over_budget", 0),
            "shadow_errors": counters.get("numerics.shadow.errors", 0),
            "sentinel_trips": counters.get("numerics.sentinel.tripped", 0),
            "budget_burns": counters.get("numerics.budget.burns", 0),
            "demoted": (numerics.last_drift() or {}).get("demoted", []),
        },
    }


def format_report(report: dict) -> str:
    lines = [
        f"requests {report['requests']}: {report['served']} served, "
        f"{report['shed']} shed ({report['shed_rate']:.1%}), "
        f"{report['failed']} failed",
    ]
    for reason, n in sorted(report["shed_by_reason"].items()):
        lines.append(f"  shed {reason}: {n}")
    lt = report["latency_ms"]
    if lt["p50"] is not None:
        lines.append(f"latency ms: p50 {lt['p50']}  p90 {lt['p90']}  "
                     f"p99 {lt['p99']}  max {lt['max']}")
    if report["throughput_rps"] is not None:
        lines.append(f"throughput: {report['throughput_rps']} req/s over "
                     f"{report['elapsed_s']} s")
    if report["batches"]:
        lines.append(f"batches: {report['batches']} "
                     f"(mean size {report['batch_mean_size']})")
    if report["degraded_served"]:
        lines.append(f"degraded-mode served: {report['degraded_served']}")
    br = report["breaker"]
    if any(br.values()):
        lines.append(f"breaker: {br['opened']} opened, {br['half_open']} "
                     f"half-open probes, {br['closed']} closed, "
                     f"{br['skipped']} requests routed around")
    comp = report.get("compile")
    if comp:
        lines.append(
            f"compile: {comp['compile_ms']} ms vs run {comp['run_ms']} ms "
            f"(share {comp['compile_share']:.1%}), "
            f"{comp['retraces']} retrace(s), program cache "
            f"{comp['cache_hits']} hit / {comp['cache_misses']} miss")
        for key in sorted(comp["per_class"]):
            row = comp["per_class"][key]
            lines.append(
                f"  {key}: compile {row['compile_ms']} ms "
                f"x{row['compiles']}, run {row['run_ms']} ms x{row['runs']}")
    phases = report.get("phases") or {}
    if "overall" in phases:
        lines.append("phase attribution (p50/p99 ms):")
        for group in sorted(phases, key=lambda g: (g != "overall", g)):
            row = phases[group]
            cells = "  ".join(
                f"{ph} {row[ph]['p50']}/{row[ph]['p99']}"
                for ph in PHASES + ("total",) if ph in row)
            lines.append(f"  {group}: {cells}")
    tenants = report.get("tenants") or {}
    if len(tenants) > 1 or (tenants and "default" not in tenants):
        lines.append("tenants:")
        for t in sorted(tenants):
            row = tenants[t]
            lm = row["latency_ms"]
            tail = (f", p50 {lm['p50']} p99 {lm['p99']} ms" if lm else "")
            lines.append(f"  {t}: {row['served']}/{row['requests']} served, "
                         f"{row['shed']} shed, {row['failed']} failed{tail}")
    slo_sec = report.get("slo") or {}
    if slo_sec.get("objectives") or slo_sec.get("burn_events"):
        lines.append(f"slo: {slo_sec.get('burn_events', 0)} burn / "
                     f"{slo_sec.get('ok_events', 0)} ok transitions")
        for name, st in sorted((slo_sec.get("objectives") or {}).items()):
            lines.append(
                f"  {name} ({st['kind']} target {st['target']}): "
                f"burn short {st['burn_short']} long {st['burn_long']}"
                f"{'  BURNING' if st['burning'] else ''}")
    num = report.get("numerics") or {}
    if num.get("shadow_samples") or num.get("sentinel_trips") \
            or num.get("demoted"):
        lines.append(
            f"numerics: {num['shadow_samples']} shadow sample(s), "
            f"{num['shadow_over_budget']} over budget, "
            f"{num['budget_burns']} budget burn(s), "
            f"{num['sentinel_trips']} sentinel trip(s)")
        for key in num.get("demoted") or []:
            lines.append(f"  DEMOTED {key}")
    tp = report.get("transport")
    if tp:
        lines.append("transport (p50/p99 ms):")
        cl = tp.get("client") or {}
        cells = "  ".join(
            f"{k.replace('_ms', '')} {cl[k]['p50']}/{cl[k]['p99']}"
            for k in ("encode_ms", "decode_ms", "rtt_ms") if cl.get(k))
        if cells:
            lines.append(f"  client: {cells}")
        sv = tp.get("server") or {}
        cells = "  ".join(
            f"{k.replace('_ms', '')} mean {sv[k]['mean']} x{sv[k]['count']}"
            for k in ("encode_ms", "decode_ms") if sv.get(k))
        if cells:
            lines.append(f"  server: {cells}")
        if tp.get("transport_ms"):
            t = tp["transport_ms"]
            lines.append(f"  wire+queue: {t['p50']}/{t['p99']}")
        if tp.get("codec_share") is not None:
            lines.append(f"  codec share of p99 rtt: "
                         f"{tp['codec_share']:.2%}")
        if tp.get("proto_v1_frames"):
            lines.append(f"  legacy v1 frames: {tp['proto_v1_frames']}")
    wf = report.get("waterfall")
    if wf:
        hops = wf.get("hops") or {}
        if hops:
            cells = "  ".join(
                f"{k.replace('_ms', '')} {v['p50']}/{v['p99']}"
                for k, v in hops.items())
            lines.append(f"waterfall (p50/p99 ms): {cells}")
        p99 = wf.get("p99")
        if p99 and p99.get("segments"):
            cells = "  ".join(f"{k.replace('_ms', '')} {v}"
                              for k, v in p99["segments"].items())
            lines.append(f"  p99 request ({p99['rtt_ms']} ms rtt): {cells}"
                         f"  -> dominant hop: "
                         f"{(p99['dominant'] or '?').replace('_ms', '')}")
        samp = wf.get("sampling")
        if samp:
            decided = samp["kept"] + samp["dropped"]
            rate = (f"{samp['keep_rate']:.1%}"
                    if samp.get("keep_rate") is not None else "-")
            reasons = ", ".join(
                f"{k} {v}" for k, v in
                sorted((samp.get("kept_by_reason") or {}).items())) or "-"
            lines.append(
                f"  tail sampling: kept {samp['kept']}/{decided} "
                f"decided ({rate}), {samp['buffered']} buffered; "
                f"kept by reason: {reasons}")
    fleet = report.get("fleet")
    if fleet:
        seen = ", ".join(fleet.get("replicas_seen") or []) or "-"
        lines.append(
            f"fleet: replicas seen {seen}; "
            f"{fleet.get('requeues', 0)} requeue(s); "
            f"scale +{fleet.get('scale_ups', 0)}/-"
            f"{fleet.get('scale_downs', 0)}")
        for label in sorted(fleet.get("replicas") or {}):
            row = fleet["replicas"][label]
            lines.append(
                f"  {label}: routed {row.get('routed', 0)}, "
                f"requeues {row.get('requeues', 0)}, "
                f"breaker {row.get('breaker', '?')}"
                f"{'' if row.get('up') else '  DOWN'}")
    job = report.get("job")
    if job:
        lines.append(
            f"job {job.get('job')}: {job.get('state')} "
            f"(epoch {job.get('epoch')}/{job.get('total_epochs')}, "
            f"{job.get('resumes', 0)} resume(s), "
            f"{job.get('preemptions', 0)} preemption(s))")
    if "baseline" in report:
        b = report["baseline"]
        lines.append(f"baseline (max_batch=1): {b['throughput_rps']} req/s "
                     f"-> batched speedup {b['speedup']}x")
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="serve loadgen",
        description="drive the serving front end with synthetic load and "
                    "print an SLO report")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="closed-loop in-flight window")
    ap.add_argument("--burst", type=int, default=16,
                    help="open-loop arrivals per service step")
    ap.add_argument("--capacity", type=int, default=64,
                    help="server queue capacity")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--mix", default="spmv,heat,cipher",
                    help=f"comma-separated ops from {MIX_OPS}")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenants", type=int, default=1,
                    help="round-robin requests over this many tenants "
                    "(t0..tN-1) for per-tenant attribution")
    ap.add_argument("--degrade-depth", type=int, default=None)
    ap.add_argument("--degrade-p99-ms", type=float, default=None)
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="p99 latency objective (ms); arms the SLO "
                    "burn-rate monitor as a degraded-mode trigger")
    ap.add_argument("--slo-shed-rate", type=float, default=None,
                    help="shed-rate budget objective (fraction)")
    ap.add_argument("--slo-error-rate", type=float, default=None,
                    help="error-rate budget objective (fraction)")
    ap.add_argument("--slo-drift-rate", type=float, default=None,
                    help="numeric-drift budget objective: fraction of "
                    "shadow-sampled requests allowed over the drift "
                    "tolerance (needs CME213_SHADOW_RATE)")
    ap.add_argument("--slo-short-s", type=float, default=5.0)
    ap.add_argument("--slo-long-s", type=float, default=60.0)
    ap.add_argument("--slo-burn-threshold", type=float, default=2.0)
    ap.add_argument("--slo-min-samples", type=int, default=10)
    ap.add_argument("--breaker-threshold", type=int, default=3)
    ap.add_argument("--breaker-cooldown-s", type=float, default=30.0)
    ap.add_argument("--baseline", action="store_true",
                    help="also replay through max_batch=1 and report the "
                    "batched/serial throughput ratio")
    ap.add_argument("--warm", action="store_true",
                    help="run one untimed pass first so the measured pass "
                    "reflects the warmed steady state (every program a "
                    "cache hit; compile share ~ 0)")
    ap.add_argument("--max-retraces", type=int, default=None,
                    help="exit nonzero when the pass records more than this "
                    "many compile retraces (the steady-state gate: with the "
                    "program cache every shape class compiles at most once, "
                    "so 0 is the expected value)")
    ap.add_argument("--transport", default=None, metavar="HOST:PORT",
                    help="drive a socket front end (serve/transport.py or "
                    "a fleet) with real concurrent client threads instead "
                    "of an in-process server; the report gains fleet and "
                    "transport sections.  'self' spins up an in-process "
                    "TransportServer for the run (the CI rate gate)")
    ap.add_argument("--pipeline", type=int, default=1,
                    help="requests in flight per connection in closed "
                    "--transport mode (v2 submit/result window; 1 = "
                    "blocking solve per request)")
    ap.add_argument("--stub-bytes", type=int, default=1024,
                    help="payload size for the 'stub' mix op")
    ap.add_argument("--stub-solve", action="store_true",
                    help="with --transport self: serve from a "
                    "StubSolveServer (decode-echo-encode inline, no "
                    "queue/batcher) so the run measures the transport "
                    "alone")
    ap.add_argument("--min-rps", type=float, default=None,
                    help="exit nonzero when served throughput falls below "
                    "this (the transport rate gate: --transport self "
                    "--mix stub measures the wire+queue path alone)")
    ap.add_argument("--max-codec-share", type=float, default=None,
                    help="exit nonzero when client encode+decode p99 "
                    "exceeds this fraction of the p99 rtt (the framing-"
                    "overhead gate; needs --transport)")
    ap.add_argument("--max-trace-keep-rate", type=float, default=None,
                    help="exit nonzero when tail sampling kept more than "
                    "this fraction of trace-buffered requests (the "
                    "sampling drop-rate gate; needs --transport and "
                    "CME213_TRACE_TAIL=1)")
    ap.add_argument("--job", default=None, metavar="JOB_ID",
                    help="with --transport: submit a durable long job "
                    "before the interactive load and report its fate "
                    "alongside the SLO report (needs a job lane — fleet "
                    "up --jobs-dir)")
    ap.add_argument("--job-op", default="pagerank",
                    help="job kind for --job (serve/workloads.JOB_KINDS)")
    ap.add_argument("--job-nodes", type=int, default=4096)
    ap.add_argument("--job-iters", type=int, default=48)
    ap.add_argument("--job-epoch", type=int, default=8,
                    help="iterations per durable epoch for --job")
    ap.add_argument("--job-wait-s", type=float, default=120.0,
                    help="after the load pass, wait this long for --job "
                    "to reach DONE (exit nonzero otherwise)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    flight.install()   # a crashing load run leaves its black box behind
    specs = build_mix(args.mix, args.requests, seed=args.seed,
                      deadline_ms=args.deadline_ms, tenants=args.tenants,
                      stub_bytes=args.stub_bytes)

    if args.transport:
        from .transport import (
            StubSolveServer,
            TransportClient,
            TransportServer,
        )

        own_server = None
        addr = args.transport
        if addr == "self":
            own_server = (StubSolveServer() if args.stub_solve
                          else TransportServer(
                              Server(capacity=args.capacity,
                                     max_batch=args.max_batch,
                                     clock=Clock()),
                              drive="thread",
                              poll_interval_s=0.001)).start()
            addr = own_server.addr
        try:
            # clock alignment for the request waterfalls: bound the
            # front end's wall-clock offset before any spans are cut
            try:
                with TransportClient(addr, timeout_s=5.0) as sync_client:
                    sync_client.sync_clock(samples=5)
            except (OSError, ConnectionError, ValueError, TimeoutError):
                pass
            if args.job:
                job_section = submit_job_over(addr, args)
            if args.warm:
                run_load_transport(addr, specs, mode=args.mode,
                                   concurrency=args.concurrency,
                                   burst=args.burst,
                                   pipeline=args.pipeline)
            before = metrics.snapshot()
            run = run_load_transport(addr, specs, mode=args.mode,
                                     concurrency=args.concurrency,
                                     burst=args.burst,
                                     pipeline=args.pipeline)
            after = metrics.snapshot()
            report = slo_report(run, before, after)
            report["transport"] = transport_section(run, before, after)
            report["waterfall"] = waterfall_section(run, before, after)
            report["fleet"] = fleet_section(run, addr)
            if args.job:
                report["job"] = wait_job_over(addr, args, job_section)
        finally:
            if own_server is not None:
                own_server.close()
        print(json.dumps(report, indent=2) if args.as_json
              else format_report(report))
        rc = 0
        if args.job and report["job"].get("state") != "DONE":
            print(f"FAIL: job {args.job} is "
                  f"{report['job'].get('state')!r}, not DONE "
                  f"({report['job'].get('error')})", file=sys.stderr)
            rc = 1
        rps = report["throughput_rps"]
        if args.min_rps is not None and (rps or 0) < args.min_rps:
            print(f"FAIL: {rps} req/s below --min-rps={args.min_rps}",
                  file=sys.stderr)
            rc = 1
        share = report["transport"].get("codec_share")
        if args.max_codec_share is not None:
            if share is None or share > args.max_codec_share:
                print(f"FAIL: codec share {share} exceeds "
                      f"--max-codec-share={args.max_codec_share}",
                      file=sys.stderr)
                rc = 1
        if args.max_trace_keep_rate is not None:
            samp = report["waterfall"].get("sampling") or {}
            rate = samp.get("keep_rate")
            if rate is None or rate > args.max_trace_keep_rate:
                print(f"FAIL: trace keep rate {rate} exceeds "
                      f"--max-trace-keep-rate={args.max_trace_keep_rate} "
                      f"(tail sampling must drop the happy path)",
                      file=sys.stderr)
                rc = 1
        return rc

    last_slo = None

    def make_server(max_batch: int) -> Server:
        nonlocal last_slo
        clock = Clock()
        last_slo = slo_mod.from_flags(
            clock, p99_ms=args.slo_p99_ms, shed_rate=args.slo_shed_rate,
            error_rate=args.slo_error_rate,
            drift_rate=args.slo_drift_rate, short_s=args.slo_short_s,
            long_s=args.slo_long_s, burn_threshold=args.slo_burn_threshold,
            min_samples=args.slo_min_samples)
        return Server(capacity=args.capacity, max_batch=max_batch,
                      clock=clock,
                      breaker_threshold=args.breaker_threshold,
                      breaker_cooldown_s=args.breaker_cooldown_s,
                      degrade_depth=args.degrade_depth,
                      degrade_p99_ms=args.degrade_p99_ms,
                      slo=last_slo)

    def run_pass(max_batch: int) -> dict:
        return run_load(make_server(max_batch), specs, mode=args.mode,
                        concurrency=args.concurrency, burst=args.burst)

    baseline = None
    if args.baseline:
        # the ratio measures SERVING throughput, not compile time: warm
        # both paths first (every batch size is its own jit shape), then
        # compare the warmed passes — the repo's bench discipline
        run_pass(args.max_batch)
        run_pass(1)
        b_run = run_pass(1)
        b_served = [r for r in b_run["results"] if r.status == OK]
        baseline = {"served": len(b_served),
                    "elapsed_s": round(b_run["elapsed_s"], 4),
                    "throughput_rps":
                        round(len(b_served) / b_run["elapsed_s"], 2)
                        if b_run["elapsed_s"] > 0 else None}

    if args.warm:
        # same seed + closed-loop discipline → the warm pass forms the
        # same batches, so the measured pass serves every shape class
        # (and batch width) from the program cache
        run_pass(args.max_batch)
    before = metrics.snapshot()
    run = run_pass(args.max_batch)
    report = slo_report(run, before, metrics.snapshot(), slo=last_slo)
    if baseline is not None:
        speedup = None
        if baseline["throughput_rps"] and report["throughput_rps"]:
            speedup = round(report["throughput_rps"]
                            / baseline["throughput_rps"], 2)
        report["baseline"] = {**baseline, "speedup": speedup}

    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
    retraces = report["compile"]["retraces"]
    if args.max_retraces is not None and retraces > args.max_retraces:
        print(f"FAIL: {retraces} compile retrace(s) exceed "
              f"--max-retraces={args.max_retraces}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
