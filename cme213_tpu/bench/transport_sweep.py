"""Transport bench: codec + wire throughput over msg size × lane × depth.

``python -m cme213_tpu.bench.transport_sweep [--quick] [--out CSV]
[--assert-speedup F]``

Two sweeps, one CSV (``bench_results/transport_sweep.csv``, regression-
gated like every other sweep via ``bench/regress.py``):

- **codec** — pure in-memory encode+decode of one stub request at each
  message size, v1 spelling (JSON document with the array as a base64
  triple) vs v2 (binary frame, array bytes straight off
  ``ndarray.data``).  ``mbs`` here is the honest codec number the
  tentpole claims: payload MB through one encode+decode round trip per
  second of CPU.  ``--assert-speedup F`` exits 1 unless v2/v1 >= F at
  the largest size (the tier-1 gate pins 5x at 1 MiB).
- **wire** — closed-loop echo against an in-process
  :class:`~cme213_tpu.serve.transport.StubSolveServer` over a loopback
  socket: v1 stop-and-wait, v2 at pipeline depths 1/8/32, and the
  shared-memory lane when the platform has one.  ``req_s`` is the
  request rate; ``mbs`` counts payload bytes both directions (the echo
  moves each byte twice).

Identity columns are ``sweep, lane, msg_bytes, depth``; metric columns
``ms, mbs, req_s`` (`regress.py` knows ``mbs``/``req_s`` are
higher-better).
"""

from __future__ import annotations

import argparse
import gc
import json
import sys
import time

import numpy as np

from ..serve import wire

#: message sizes swept (bytes); the last one anchors the speedup gate
SIZES = (1 << 10, 1 << 16, 1 << 20)
QUICK_SIZES = (1 << 10, 1 << 20)


def _payload(n: int) -> np.ndarray:
    return np.random.default_rng(n).integers(
        0, 255, size=n).astype(np.uint8)


def _codec_v1_ms(arr: np.ndarray, iters: int) -> float:
    """One v1 encode+decode round trip: base64 triple inside a JSON
    document, the PR 15 wire spelling."""
    from ..serve.transport import decode_payload, encode_payload

    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        blob = json.dumps({"op": "stub",
                           "payload": encode_payload("stub", arr)})
        doc = json.loads(blob)
        out = decode_payload("stub", doc["payload"])
        best = min(best, time.perf_counter() - t0)
    assert out.tobytes() == arr.tobytes()
    return best * 1e3


def _codec_v2_ms(arr: np.ndarray, iters: int) -> float:
    """One v2 encode+decode round trip through the binary frame codec
    (pack to a contiguous blob, parse back, materialize the payload)."""
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        sw = wire.SectionWriter()
        doc = {"op": "stub", "payload": wire.encode_payload(
            "stub", arr, sw)}
        blob = wire.frame_bytes(wire.FT_REQUEST, 1, doc, sw.arrays)
        ftype, rid, meta, sections = wire.parse_frame(blob)
        out = wire.decode_payload("stub", meta["payload"], sections)
        best = min(best, time.perf_counter() - t0)
    assert out.tobytes() == arr.tobytes()
    return best * 1e3


def codec_sweep(sizes=SIZES, iters: int = 20) -> list[dict]:
    rows = []
    for n in sizes:
        arr = _payload(n)
        for lane, fn in (("v1json", _codec_v1_ms),
                         ("v2bin", _codec_v2_ms)):
            ms = fn(arr, iters)
            rows.append({"sweep": "codec", "lane": lane,
                         "msg_bytes": n, "depth": 1,
                         "ms": round(ms, 4),
                         "mbs": round(n / 1e6 / (ms / 1e3), 2),
                         "req_s": round(1e3 / ms, 1)})
    return rows


def _drive(addr: str, arr: np.ndarray, requests: int, depth: int,
           proto: int = 2, shm: bool = False) -> float:
    """Closed-loop echo of ``requests`` payloads; returns elapsed s."""
    from ..serve.transport import TransportClient

    client = TransportClient(addr, proto=proto, shm=shm,
                             recv_thread=bool(shm))
    try:
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        if client.proto != 2 or depth <= 1:
            for _ in range(requests):
                res = client.solve("stub", arr)
                assert res.status == "ok", res.reason
        else:
            window: list[int] = []
            sent = 0
            while sent < requests or window:
                while sent < requests and len(window) < depth:
                    window.append(client.submit("stub", arr,
                                                flush=False))
                    sent += 1
                client.flush()
                for _ in range(min(len(window), max(1, depth // 2))):
                    res = client.result(window.pop(0))
                    assert res.status == "ok", res.reason
        return time.perf_counter() - t0
    finally:
        gc.enable()
        client.close()


def wire_sweep(sizes=SIZES, quick: bool = False) -> list[dict]:
    from ..serve.transport import StubSolveServer

    depths = (1, 32) if quick else (1, 8, 32)
    server = StubSolveServer().start()
    rows = []
    try:
        for n in sizes:
            arr = _payload(n)
            # enough requests to swamp connection setup, capped so the
            # 1 MiB x 32-deep cell stays CI-sized
            requests = max(50, min(2000, (8 << 20) // n))
            lanes = [("v1json", 1, False), ("v2bin", 2, False)]
            if sys.platform.startswith("linux"):
                lanes.append(("v2shm", 2, True))
            for lane, proto, shm in lanes:
                for depth in (1,) if proto == 1 else depths:
                    el = _drive(server.addr, arr, requests, depth,
                                proto=proto, shm=shm)
                    rows.append({
                        "sweep": "wire", "lane": lane, "msg_bytes": n,
                        "depth": depth,
                        "ms": round(el * 1e3 / requests, 4),
                        "mbs": round(2 * n * requests / 1e6 / el, 2),
                        "req_s": round(requests / el, 1)})
    finally:
        server.close()
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="bench_results/transport_sweep.csv")
    ap.add_argument("--quick", action="store_true",
                    help="2 sizes, 2 depths — the CI shape")
    ap.add_argument("--codec-only", action="store_true",
                    help="skip the socket sweep (codec rows only)")
    ap.add_argument("--assert-speedup", type=float, default=None,
                    metavar="F",
                    help="exit 1 unless v2/v1 codec MB/s >= F at the "
                    "largest swept size")
    args = ap.parse_args(argv)

    sizes = QUICK_SIZES if args.quick else SIZES
    rows = codec_sweep(sizes, iters=5 if args.quick else 20)
    if not args.codec_only:
        rows += wire_sweep(sizes, quick=args.quick)

    for r in rows:
        print(f"{r['sweep']:>5} {r['lane']:>6} {r['msg_bytes']:>8} B "
              f"depth {r['depth']:>2}: {r['ms']:>9.3f} ms  "
              f"{r['mbs']:>9.2f} MB/s  {r['req_s']:>9.1f} req/s")

    if args.out:
        from .sweeps import write_csv

        write_csv(rows, args.out)
        print(f"wrote {args.out} ({len(rows)} rows)")

    if args.assert_speedup is not None:
        top = max(sizes)
        by_lane = {r["lane"]: r["mbs"] for r in rows
                   if r["sweep"] == "codec" and r["msg_bytes"] == top}
        ratio = by_lane["v2bin"] / by_lane["v1json"]
        ok = ratio >= args.assert_speedup
        print(f"codec speedup @ {top} B: {ratio:.1f}x "
              f"(gate {args.assert_speedup:.1f}x) "
              f"{'OK' if ok else 'FAIL'}")
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
