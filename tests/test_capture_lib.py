"""Pin the capture retry-classification semantics (scripts/capture_lib.sh).

These shell predicates decide what device evidence is final vs re-run on
the next tunnel window — the logic has been the round's main source of
review findings, so the truth table lives in tests.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "scripts", "capture_lib.sh")

GOOD_BENCH = ('{"metric": "heat2d ...", "value": 123.4, "unit": "GB/s", '
              '"kernels": [{"kernel": "xla", "ok": true}]}\n')
PARTIAL_BENCH = ('{"metric": "heat2d ...", "value": 14.6, "unit": "GB/s", '
                 '"kernels": [{"kernel": "xla", "ok": true}, '
                 '{"kernel": "pipeline-k8", "ok": false, '
                 '"error": "preflight: device unreachable"}]}\n')
DEAD_BENCH = ('{"metric": "heat2d ... (DEVICE UNAVAILABLE)", "value": 0.0, '
              '"unit": "GB/s", "vs_baseline": 0.0}\n')


def _call(fn: str, *args: str) -> int:
    return subprocess.run(
        ["bash", "-c", f'. "{LIB}"; {fn} "$@"', "_", *args],
        capture_output=True).returncode


@pytest.mark.parametrize("content,ok,complete", [
    (GOOD_BENCH, 0, 0),
    (PARTIAL_BENCH, 0, 1),   # usable headline, but NOT final evidence
    (DEAD_BENCH, 1, 1),
    ("", 1, 1),
])
def test_bench_predicates(tmp_path, content, ok, complete):
    f = tmp_path / "bench.json"
    f.write_text(content)
    assert _call("bench_ok", str(f)) == ok
    assert _call("bench_complete", str(f)) == complete


def test_bench_predicates_missing_file(tmp_path):
    missing = str(tmp_path / "nope.json")
    assert _call("bench_ok", missing) == 1
    assert _call("bench_complete", missing) == 1


def test_sweep_attempted_truth_table(tmp_path):
    out = tmp_path
    # captured CSV -> attempted
    (out / "a.csv").write_text("x\n1\n")
    assert _call("sweep_attempted", str(out), "a") == 0
    # no csv, sticky failure record -> attempted (not retried)
    (out / "b.failed").write_text("TypeError: bad tile\n")
    assert _call("sweep_attempted", str(out), "b") == 0
    # no csv, device failure record -> NOT attempted (retried next window)
    for tag in ("UNAVAILABLE: socket closed",
                "timeout after 2700s — device hang suspected",
                "preflight: device unreachable",
                "JaxRuntimeError: ... TPU device error ..."):
        (out / "c.failed").write_text(tag + "\n")
        assert _call("sweep_attempted", str(out), "c") == 1, tag
    # nothing recorded -> not attempted
    assert _call("sweep_attempted", str(out), "d") == 1


def test_python_device_tags_subset_of_shell_classifier():
    """_raise_if_device_error's tag set must stay a subset of DEVICE_ERR,
    or a sweep aborted for a device reason would be classified sticky."""
    import re

    from cme213_tpu.bench.sweeps import _raise_if_device_error

    src = open(LIB).read()
    pattern = re.search(r"DEVICE_ERR='([^']+)'", src).group(1)
    for tag in ("UNAVAILABLE", "DEADLINE", "unreachable", "device error"):
        try:
            _raise_if_device_error(RuntimeError(f"xx {tag} yy"))
        except RuntimeError:
            pass
        else:
            pytest.fail(f"python classifier no longer raises on {tag!r}")
        assert re.search(pattern, f"xx {tag} yy"), (
            f"shell DEVICE_ERR does not match python tag {tag!r}")
