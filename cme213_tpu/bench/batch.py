"""Batch job runner — the Torque/PBS layer (L6), framework-native.

The reference drives its measurement campaigns through batch scripts whose
header directives declare resources and whose body is re-run across an
environment sweep, with every run's stdout/stderr captured to job files
(``hw/hw4/programming/pa4.pbs:20-28`` sweeps ``OMP_NUM_THREADS`` over
1..64 and leaves ``pa4.pbs.o26386``/``.e26386`` logs; submission via
``qsub``, ``hw/hw4/PA4_Handout.pdf`` §7).  There is no cluster queue here,
but the *artifact discipline* — declarative sweep, one captured ``.o``/
``.e`` pair per point, a machine-readable summary — is the part worth
keeping, so this runner reproduces it for any framework workload:

    python -m cme213_tpu.bench.batch jobs/sorts_scaling.job

Job-file format (shell script + ``#CME`` header directives, the ``#PBS``
analog)::

    #CME name=sorts_scaling
    #CME out=bench_results/jobs
    #CME sweep OMP_NUM_THREADS=1,2,4,8
    #CME timeout=900
    python -m cme213_tpu sorts 4096 4096 16000000 0

Multiple ``sweep`` directives form a cartesian product, evaluated in
directive order (last directive varies fastest).  Each sweep point ``i``
runs the body under ``bash`` with the point's variables exported, writing
``<out>/<name>.o<i>`` and ``<name>.e<i>``; a ``<name>.jobs.csv`` summary
records the variable values, exit status, and wall seconds per point.
Exit status is nonzero if any point failed — batch evidence with a silent
hole should not look green.
"""

from __future__ import annotations

import argparse
import csv
import itertools
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field


@dataclass
class JobSpec:
    name: str
    out: str = "batch_logs"
    timeout: float = 3600.0
    sweeps: list[tuple[str, list[str]]] = field(default_factory=list)
    body: str = ""

    def points(self) -> list[dict[str, str]]:
        """Cartesian product of the sweep axes (one dict per run)."""
        if not self.sweeps:
            return [{}]
        axes = [[(var, v) for v in values] for var, values in self.sweeps]
        return [dict(combo) for combo in itertools.product(*axes)]


def parse_job(path: str) -> JobSpec:
    spec = JobSpec(name=os.path.splitext(os.path.basename(path))[0])
    body_lines = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            stripped = line.strip()
            if not stripped.startswith("#CME"):
                body_lines.append(line)
                continue
            directive = stripped[len("#CME"):].strip()
            if directive.startswith("sweep "):
                assignment = directive[len("sweep "):].strip()
                var, _, csv_values = assignment.partition("=")
                values = [v.strip() for v in csv_values.split(",") if v.strip()]
                if not var.strip() or not values:
                    raise ValueError(
                        f"{path}:{lineno}: bad sweep directive {stripped!r}")
                spec.sweeps.append((var.strip(), values))
            elif "=" in directive:
                key, _, value = directive.partition("=")
                key, value = key.strip(), value.strip()
                if key == "name":
                    spec.name = value
                elif key == "out":
                    spec.out = value
                elif key == "timeout":
                    spec.timeout = float(value)
                else:
                    raise ValueError(
                        f"{path}:{lineno}: unknown directive key {key!r}")
            else:
                raise ValueError(
                    f"{path}:{lineno}: unparseable directive {stripped!r}")
    spec.body = "".join(body_lines)
    if not spec.body.strip():
        raise ValueError(f"{path}: job body is empty")
    return spec


def run_job(spec: JobSpec, dry_run: bool = False) -> list[dict]:
    if not dry_run:
        os.makedirs(spec.out, exist_ok=True)
    rows = []
    for i, env_point in enumerate(spec.points()):
        label = " ".join(f"{k}={v}" for k, v in env_point.items()) or "(none)"
        if dry_run:
            print(f"[{spec.name}.{i}] {label}")
            rows.append({"point": i, **env_point, "rc": "", "seconds": ""})
            continue
        out_path = os.path.join(spec.out, f"{spec.name}.o{i}")
        err_path = os.path.join(spec.out, f"{spec.name}.e{i}")
        env = {**os.environ, **env_point}
        t0 = time.perf_counter()
        with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
            # own process group: on timeout, kill the whole tree — killing
            # only bash would orphan the workload, which then skews the
            # wall-clock of every later sweep point
            proc = subprocess.Popen(
                ["bash", "-c", spec.body], env=env, stdout=out_f,
                stderr=err_f, start_new_session=True)
            try:
                rc = proc.wait(timeout=spec.timeout)
            except subprocess.TimeoutExpired:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait()
                rc = 124
        secs = time.perf_counter() - t0
        print(f"[{spec.name}.{i}] {label}: rc={rc} ({secs:.1f} s)")
        rows.append({"point": i, **env_point, "rc": rc,
                     "seconds": round(secs, 2)})
    if not dry_run:
        summary = os.path.join(spec.out, f"{spec.name}.jobs.csv")
        with open(summary, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
            w.writeheader()
            w.writerows(rows)
        print(f"summary: {summary}")
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Run a #CME batch job file (the PBS-script analog).")
    ap.add_argument("jobfile")
    ap.add_argument("--dry-run", action="store_true",
                    help="list sweep points without running")
    args = ap.parse_args(argv)
    spec = parse_job(args.jobfile)
    rows = run_job(spec, dry_run=args.dry_run)
    failed = [r for r in rows if r["rc"] not in ("", 0)]
    if failed:
        print(f"{len(failed)}/{len(rows)} points failed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
