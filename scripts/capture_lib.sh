# Shared definitions for the device-capture scripts (sourced by
# tpu_capture.sh and tpu_autocapture.sh) — one home for the sweep list,
# the device-failure signatures, and the bench-result gate.

# stderr signatures of a dead/dropped tunnel (vs a sticky kernel/compile
# bug): such failures are retried on the next capture attempt
DEVICE_ERR='UNAVAILABLE|unreachable|DEADLINE|preflight|device hang|device error'

# EV-ordered: the tuned-kernel grids (the standing deliverable — hw2's
# measured table) run first so a window that dies mid-capture loses the
# cheaper, lower-stakes sweeps instead; transfer_bandwidth is usually
# already banked by tranche 1 and skips instantly
SWEEPS="heat_kernels pipeline_tune heat_bandwidth \
spmv_pallas_coverage spmv_suite spmv_scan_sweep transfer_bandwidth \
data_bandwidth_vector_length bandwidth_vs_avg_edges scan_bandwidth \
dist_heat_scaling dist_heat_compile_coverage pallas_tile"

device_up_quick() {  # tunnel answers a trivial op within ~85 s?
  # Guards every sweep/stage start: a sweep launched against a dead
  # tunnel hangs inside PJRT client creation until its own 2700-5400 s
  # timeout — one dead pass through the sweep list would otherwise burn
  # ~7 h of watcher deadline (observed round 5, 03:34 UTC drop).
  # CAPTURE_PREFLIGHT_S shortens the probe for the shell unit tests.
  # $1 = optional platform name the answering device must also report
  # (e.g. 'tpu') — checked on the already-created client, so it is free.
  local t req
  t="${CAPTURE_PREFLIGHT_S:-85}"
  req="${1:-}"
  timeout $((t + 15)) python -c "
from cme213_tpu.core.platform import device_preflight
import sys
ok = device_preflight($t)
if ok and '$req':
    import jax
    ok = jax.devices()[0].platform == '$req'
sys.exit(0 if ok else 1)" >/dev/null 2>&1
}

bench_ok() {  # $1 = bench json path: holds a real (non-zero) number?
  [ -s "$1" ] && grep -q '"unit": "GB/s"' "$1" \
    && ! grep -q 'DEVICE UNAVAILABLE' "$1"
}

bench_complete() {  # $1: bench_ok AND no per-kernel device-failure rows —
  # a window that closed mid-bench leaves rows like "preflight: device
  # unreachable"; such a file is a partial result worth re-running, not
  # final evidence
  bench_ok "$1" && ! grep -qE "$DEVICE_ERR" "$1"
}

sweep_attempted() {  # $1 = outdir, $2 = sweep: captured, or sticky-failed?
  [ -s "$1/$2.csv" ] && return 0
  [ -s "$1/$2.failed" ] && ! grep -qE "$DEVICE_ERR" "$1/$2.failed"
}

row_ok() {  # $1 = per-kernel row json (bench.py child mode): real number?
  [ -s "$1" ] && grep -q '"ok": true' "$1"
}

count_measured_rows() {  # $1 = bench json: ok:true rows in the "kernels"
  # array ONLY.  A DEVICE-UNAVAILABLE bench output carries the committed
  # banked_device_rows (all ok:true by construction) for the reader; a
  # whole-file grep would count those as live measurements and let a
  # dead-tunnel re-run outvote a file holding real measured rows.
  [ -s "$1" ] || { echo 0; return; }
  python - "$1" <<'PY' 2>/dev/null || echo 0
import json, sys
try:
    with open(sys.argv[1]) as f:
        doc = json.load(f)
    print(sum(1 for r in doc.get("kernels", []) if r.get("ok")))
except Exception:
    print(0)
PY
}

row_conclusive() {  # $1: banked number, or a sticky (non-device) failure —
  # a compile bug is a result worth keeping; a device-tagged failure is
  # retried on the next tunnel window
  [ -s "$1" ] && { grep -q '"ok": true' "$1" \
                   || ! grep -qE "$DEVICE_ERR" "$1"; }
}

failure_signature() {  # $1 = stderr log: device-signature lines from the
  # FINAL failure only — the last traceback if one exists, else the last
  # 15 lines.  Anchoring to the failure itself (not a fixed 60-line
  # window) keeps a transient recovered-UNAVAILABLE warning that merely
  # sits near the end of a long sticky-failure log from writing a device
  # signature into <sweep>.failed, which would make the sweep retry
  # until the deadline.
  awk '/Traceback \(most recent call last\)/ { n = NR }
       { l[NR] = $0 }
       END { s = n ? n : (NR > 15 ? NR - 14 : 1)
             for (i = s; i <= NR; i++) print l[i] }' "$1" \
    | grep -E "$DEVICE_ERR" | head -n 3
}
