"""2-D heat-diffusion workload driver (reference hw2 single-device main and
hw5 distributed main).

Orchestration mirrors ``hw/hw2/programming/2dHeat.cu:674-714``: parse params
→ build grid → save initial state → (optional) host golden → device solve
with the XLA-fused stencil ("global memory" analog) → ULP check → device
solve with the Pallas VMEM-tiled kernel ("shared memory" analog) → ULP check
→ save finals, report bandwidth/GFLOPs for each.  The distributed entry
(``run_distributed``) is the hw5 main (``2dHeat.cpp:817-851``): grid method
and sync/async selected by the params file.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimParams
from ..core import PhaseTimer, bandwidth_gbs, gflops
from ..dist import mesh_for_method, run_distributed_heat
from ..grid import make_initial_grid, save_grid_to_file
from ..ops import run_heat
from ..ops.stencil import BORDER_FOR_ORDER, flops_per_point, stencil_interior
from ..ops.stencil_pipeline import pick_pipeline_tile, run_heat_resilient
from ..verify import check_ulp, golden


@dataclass
class HeatResult:
    ok: bool
    reports: list[str] = field(default_factory=list)


def _report(params: SimParams, label: str, ms: float) -> str:
    per_iter = ms / params.iters
    nbytes = 2 * 4 * params.nx * params.ny
    nflops = flops_per_point(params.order) * params.nx * params.ny
    return (f"{label}: {ms:.1f} ms total, "
            f"{bandwidth_gbs(nbytes, per_iter):.2f} GB/s, "
            f"{gflops(nflops, per_iter):.2f} GFLOP/s")


def run_single(params: SimParams, check_cpu: bool = True,
               save_files: bool = False, out_dir: str = ".") -> HeatResult:
    timer = PhaseTimer(verbose=True)
    u0 = make_initial_grid(params, dtype=jnp.float32)
    if save_files:
        save_grid_to_file(u0, f"{out_dir}/grid_init.txt")

    ref = None
    if check_cpu:
        with timer.phase("cpu computation"):
            ref = golden.host_heat(np.asarray(u0), params.iters, params.order,
                                   params.xcfl, params.ycfl)

    result = HeatResult(ok=True)

    # XLA-fused path (the "global memory" kernel analog); warmup uses the
    # SAME iteration count — it is a static jit arg, so any other count
    # would leave compilation inside the timed phase
    run_heat(jnp.array(u0), params.iters, params.order, params.xcfl,
             params.ycfl).block_until_ready()
    with timer.phase("gpu computation global") as ph:
        out_xla = run_heat(jnp.array(u0), params.iters, params.order,
                           params.xcfl, params.ycfl)
        ph.block(out_xla)
    result.reports.append(
        _report(params, "xla", timer.last_ms("gpu computation global")))

    # tuned Pallas path (the "shared memory" kernel analog): the pipelined
    # kernel (ops/stencil_pipeline.py), behind the fallback ladder — a
    # rung that fails to lower or run (real or CME213_FAULTS-injected)
    # demotes pipeline → pipeline2d → xla instead of aborting the solve
    tile = pick_pipeline_tile(params.gy, 1, params.order, width=params.gx)
    interpret = jax.devices()[0].platform != "tpu"
    res = run_heat_resilient(jnp.array(u0), params.iters, params.order,
                             params.xcfl, params.ycfl, params.bc,
                             k=1, tile_y=tile, interpret=interpret,
                             timer=timer)
    out_pl = res.value
    label = "pallas" if not res.demoted else f"pallas->{res.rung}"
    if res.demoted:
        print(f"heat2d: tuned kernel demoted to {res.rung!r} "
              f"(failed: {', '.join(f.rung for f in res.failures)})")
    result.reports.append(
        _report(params, label, timer.last_ms("gpu computation shared")))

    if save_files and ref is not None:
        # the reference's artifact set includes the golden dump
        # (grid_final_cpu.txt, 2dHeat.cu:686-711)
        save_grid_to_file(jnp.asarray(ref), f"{out_dir}/grid_final_cpu.txt")

    for label, out in [("global", out_xla), ("shared", out_pl)]:
        if ref is not None:
            res = check_ulp(ref, np.asarray(out), max_ulps=10,
                            label=f"heat-{label}")
            if not res:
                print(res.message)
                result.ok = False
        if save_files:
            save_grid_to_file(out, f"{out_dir}/grid_final_gpu_{label}.txt")

    for r in result.reports:
        print(r)
    return result


@partial(jax.jit, static_argnames=("iters", "order"), donate_argnums=(0,))
def _heat_batched(u, iters: int, order: int, xcfl, ycfl):
    """B same-shape heat solves as one device program: ``u`` is a
    (B, gy, gx) stack, ``xcfl``/``ycfl`` are per-lane (B,) scalars, and
    each lane runs the exact ``run_heat`` loop body under ``jax.vmap`` —
    so per-lane results are bitwise-equal to the serial solve (pinned by
    tests/test_serve.py)."""
    b = BORDER_FOR_ORDER[order]

    def one(g0, xc, yc):
        def body(_, g):
            return g.at[b:-b, b:-b].set(stencil_interior(g, order, xc, yc))

        return jax.lax.fori_loop(0, iters, body, g0)

    return jax.vmap(one)(u, xcfl, ycfl)


def run_heat_batched(grids: list[np.ndarray], iters: int, order: int,
                     xcfls: list[float],
                     ycfls: list[float]) -> list[np.ndarray]:
    """Serve B same-class heat requests (equal grid shape, ``order``,
    ``iters``) from one jitted program — the vmap/stacking path the
    serving layer batches same-shape-class grids through.  CFL factors
    ride as vmapped per-lane scalars, so requests need not share them to
    share a bucket."""
    if not grids:
        return []
    shape = np.asarray(grids[0]).shape
    for g in grids:
        if np.asarray(g).shape != shape:
            raise ValueError(
                f"batch mixes grid shapes: {np.asarray(g).shape} vs {shape}")
    from ..core import check_op, programs, span

    b, (gy, gx) = len(grids), shape
    shape_class = f"{gy}x{gx}/order{order}/i{iters}/b{b}"

    def build():
        return lambda u, xc, yc: _heat_batched(u, iters, order, xc, yc)

    def warm(fn):
        z = jnp.zeros((b,), jnp.float32)
        check_op("heat_batched.xla",
                 fn(jnp.zeros((b, gy, gx), jnp.float32), z, z))

    runner = programs.get("heat_batched", "xla", shape_class, build,
                          dtype="f32", warm=warm, iters=iters, order=order,
                          batch=b)
    u = jnp.asarray(np.stack([np.asarray(g) for g in grids]), jnp.float32)
    with span("heat_batched.run", kernel="xla",
              shape_class=shape_class) as sp:
        out = runner(u, jnp.asarray(xcfls, jnp.float32),
                     jnp.asarray(ycfls, jnp.float32))
        sp.block(out)
    out = np.asarray(out)
    return [out[i] for i in range(len(grids))]


def run_heat_checkpointed(params: SimParams, path: str, every: int = 0,
                          max_retries: int = 1) -> np.ndarray:
    """Long-solve form of the single-device heat driver: checkpointed
    chunks with a finiteness guard between them (host-side, outside the
    jitted loop — the hot ``fori_loop`` is untouched).

    The checkpoint state is a pytree ``{"grid": u}`` — the halo bands ride
    inside the grid, and ``core/checkpoint.py`` restores arbitrary pytrees,
    so richer states (e.g. split ``(grid, halo)``) checkpoint the same way
    without hand-flattening.  A NaN blow-up (injected via
    ``CME213_FAULTS=nan:heat2d`` or real, e.g. an unstable CFL) rolls back
    to the last good checksummed checkpoint and retries the chunk; a
    killed process resumes from ``path``.  Deterministic chunking makes an
    interrupted-and-resumed solve bitwise equal to an uninterrupted one.

    Memory pressure degrades instead of dying: the chunk program is
    preflighted against the memory budget (``core/admission.preflight``;
    a grid the budget can never hold is refused up front), and a chunk
    that dies ``RESOURCE_EXHAUSTED`` at runtime (real, or
    ``CME213_FAULTS=oom:heat_chunk``) is halved and retried from the
    last checkpoint — bitwise-neutral, every iteration runs the same
    stencil whatever the chunk boundaries.
    """
    from ..core import admission
    from ..core.checkpoint import run_with_checkpoints
    from ..core.numerics import ConvergenceTracker
    from ..core.resilience import all_finite

    u0 = make_initial_grid(params, dtype=jnp.float32)
    every_eff = every or params.iters
    decision = admission.preflight(
        run_heat, jnp.zeros_like(u0), min(every_eff, params.iters),
        params.order, params.xcfl, params.ycfl, op="heat2d")
    if not decision.admitted:
        raise admission.AdmissionError(f"heat2d: {decision.detail}")

    def step(state, k):
        return {"grid": run_heat(jnp.asarray(state["grid"]), k,
                                 params.order, params.xcfl, params.ycfl)}

    # diffusion decays monotonically toward steady state, so a residual
    # flat for 3 chunks already means the solve is burning iterations
    # for nothing — a tighter stall policy than the generic default
    out = run_with_checkpoints(step, {"grid": u0}, params.iters, path,
                               every=every, guard=all_finite, op="heat2d",
                               max_retries=max_retries,
                               chunk_op="heat_chunk",
                               tracker=ConvergenceTracker(
                                   "heat2d", stall_epochs=3))
    return np.asarray(out["grid"])


def run_distributed(params: SimParams, num_devices: int | None = None,
                    save_files: bool = False, out_dir: str = ".",
                    local_kernel: str = "xla") -> np.ndarray:
    """hw5 main: mesh from ``params.grid_method``, sync/overlap from
    ``params.synchronous``; writes per-run init/final dumps like the
    reference's per-rank files.  ``local_kernel="pallas"`` runs the tuned
    pipeline kernel per shard."""
    mesh = mesh_for_method(params.grid_method, num_devices)
    timer = PhaseTimer(verbose=True)
    if save_files:
        save_grid_to_file(make_initial_grid(params), f"{out_dir}/grid_init.txt")
    with timer.phase("distributed computation"):
        out = run_distributed_heat(params, mesh, local_kernel=local_kernel)
    if save_files:
        save_grid_to_file(out, f"{out_dir}/grid_final.txt")
        # per-rank interior dumps, like the reference's grid{rank}_final.txt
        # (2dHeat.cpp:549-557) — used for offline N-vs-1 diffing
        b = params.border_size
        interior_grid = out[b:-b, b:-b]
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        ylocal = params.ny // axes.get("y", 1)
        xlocal = params.nx // axes.get("x", 1)
        rank = 0
        for yi in range(axes.get("y", 1)):
            for xi in range(axes.get("x", 1)):
                blockview = interior_grid[yi * ylocal:(yi + 1) * ylocal,
                                          xi * xlocal:(xi + 1) * xlocal]
                save_grid_to_file(blockview, f"{out_dir}/grid{rank}_final.txt")
                rank += 1
    return out


def run_distributed_supervised(params: SimParams,
                               num_devices: int | None = None,
                               ckpt_dir: str | None = None,
                               ckpt_every: int = 0,
                               resume: bool | None = None,
                               save_files: bool = False,
                               out_dir: str = ".") -> np.ndarray:
    """hw5 main under gang supervision: the worker entry a supervised
    launcher gang runs (``dist.launch --stall-timeout ... -- python -m
    cme213_tpu.apps.heat2d params.in --distributed --supervised``).

    Checkpoint plumbing defaults from the launcher's exported env
    (``CME213_CKPT_DIR`` / ``CME213_CKPT_EVERY`` / ``CME213_RESUME``);
    heartbeats wire up automatically when ``CME213_HEARTBEAT_DIR`` is set.
    Joins the multi-process runtime first when launched with real ranks.
    Runs the sync path (the bitwise-reproducible decomposition-invariant
    scheme), committing an epoch every ``ckpt_every`` iterations.
    """
    import os

    from ..dist.heat import run_distributed_heat_supervised
    from ..dist.multihost import initialize_multihost
    from ..dist.supervisor import heartbeat_from_env, supervised_env_config

    cfg = supervised_env_config()
    ckpt_dir = ckpt_dir or cfg["ckpt_dir"]
    if not ckpt_dir:
        raise ValueError("supervised run needs a checkpoint directory "
                         "(--ckpt-dir or CME213_CKPT_DIR)")
    ckpt_every = ckpt_every or cfg["ckpt_every"]
    resume = cfg["resume"] if resume is None else resume
    if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
        initialize_multihost()
    mesh = mesh_for_method(params.grid_method, num_devices)
    timer = PhaseTimer(verbose=True)
    with timer.phase("supervised distributed computation"):
        out = run_distributed_heat_supervised(
            params, mesh, ckpt_dir, ckpt_every=ckpt_every, resume=resume,
            heartbeat=heartbeat_from_env())
    print(f"supervised solve complete: {params.iters} iters, "
          f"epoch commits in {ckpt_dir}")
    if save_files:
        save_grid_to_file(out, f"{out_dir}/grid_final.txt")
    return out


def main(argv: list[str]) -> int:
    # supervised workers inherit CME213_FLIGHT_DIR from the launcher; a
    # rank dying uncleanly then leaves a per-rank flight dump behind
    from ..core import flight

    flight.install_from_env()
    paths = [a for a in argv[1:] if not a.startswith("--")]
    path = paths[0] if paths else "params.in"
    distributed = "--distributed" in argv
    supervised = "--supervised" in argv
    local_kernel = next((a.split("=", 1)[1] for a in argv
                         if a.startswith("--local-kernel=")), "xla")
    ckpt_dir = next((a.split("=", 1)[1] for a in argv
                     if a.startswith("--ckpt-dir=")), None)
    ckpt_every = int(next((a.split("=", 1)[1] for a in argv
                           if a.startswith("--ckpt-every=")), "0"))
    params = SimParams.from_file(path, distributed=distributed or supervised)
    if supervised:
        run_distributed_supervised(params, ckpt_dir=ckpt_dir,
                                   ckpt_every=ckpt_every, save_files=True)
        return 0
    if distributed:
        run_distributed(params, save_files=True, local_kernel=local_kernel)
        return 0
    res = run_single(params, check_cpu=params.nx * params.ny <= 512 * 512,
                     save_files=True)
    return 0 if res.ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
