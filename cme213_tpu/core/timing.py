"""Phase timers and derived-metric helpers.

TPU-native analog of the reference's harness utilities: ``event_pair`` +
``start_timer``/``stop_timer`` (CUDA-event wall-clock ms, reference
``hw/hw1/programming/mp1-util.h:21-39``), ``omp_get_wtime`` phases
(``hw/hw4/programming/mergesort.cpp:168-184``) and ``MPI_Wtime``
(``hw/hw5/programming/2dHeat.cpp:832-841``).  On TPU, device work is async, so
the timer blocks on the provided arrays (``jax.block_until_ready``) before
reading the clock — the analog of ``cudaEventSynchronize``.

Every phase also emits a ``span-begin``/``span-end`` pair through
``core/trace.span`` (same label, same blocking discipline), so any
workload already instrumented with a ``PhaseTimer`` shows up in
``python -m cme213_tpu trace summary`` for free.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

import jax


@dataclass
class PhaseRecord:
    label: str
    ms: float


@dataclass
class PhaseTimer:
    """Labeled wall-clock phase timer.

    Usage::

        timer = PhaseTimer()
        with timer.phase("gpu shift cypher") as ph:
            out = jitted(x)
            ph.block(out)          # block_until_ready before stopping the clock
        timer.report()
    """

    records: list[PhaseRecord] = field(default_factory=list)
    verbose: bool = False

    @contextmanager
    def phase(self, label: str):
        from .trace import span

        # the phase clock starts after span-begin is emitted and stops
        # before span-end is — record emission stays OUTSIDE the measured
        # window, so phase timings match the pre-telemetry ones exactly
        # (the span's own ms is marginally wider; that's its job)
        with span(label) as ph:
            start = time.perf_counter()
            try:
                yield ph
            finally:
                for a in ph._blocked:
                    jax.block_until_ready(a)
                ms = (time.perf_counter() - start) * 1e3
                self.records.append(PhaseRecord(label, ms))
                if self.verbose:
                    # labeled timing printout, like stop_timer's
                    # "%s took %.1f ms"
                    print(f"{label} took {ms:.1f} ms")

    def ms(self, label: str) -> float:
        """Total milliseconds across all phases with this label."""
        return sum(r.ms for r in self.records if r.label == label)

    def last_ms(self, label: str | None = None) -> float:
        if label is None:
            return self.records[-1].ms
        for r in reversed(self.records):
            if r.label == label:
                return r.ms
        raise KeyError(label)

    def report(self) -> str:
        lines = [f"{r.label} took {r.ms:.1f} ms" for r in self.records]
        out = "\n".join(lines)
        print(out)
        return out


def time_fn(fn, *args, warmup: int = 1, iters: int = 3) -> float:
    """Best-of-N wall-clock milliseconds for a (usually jitted) function.

    Runs ``warmup`` untimed calls first (absorbs compilation), then takes the
    minimum over ``iters`` timed calls, blocking on the outputs each time.
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        start = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, (time.perf_counter() - start) * 1e3)
    return best


def bandwidth_gbs(num_bytes: int, ms: float) -> float:
    """Effective bandwidth in GB/s given bytes moved and elapsed ms.

    Byte accounting follows the reference's explicit counting style
    (``hw/hw1/programming/analysis/pagerank.cu:47-62``).
    """
    return (num_bytes / 1e9) / (ms / 1e3)


def gflops(num_flops: int, ms: float) -> float:
    return (num_flops / 1e9) / (ms / 1e3)
