"""Device-health doctor, staged forensics, and attribution (core/diag.py).

Covers the diagnostic ladder end to end: probe pass/timeout/injected-
unreachable, stage attribution for all four dispatch stages through real
``with_fallback`` dispatch, cost-analysis mismatch detection against a
deliberately wrong model, health-ring persistence across a subprocess,
and the ``doctor`` CLI's ``--json`` round-trip and exit codes.
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import pytest

from cme213_tpu.core import diag, faults, programs, trace
from cme213_tpu.core.faults import injected
from cme213_tpu.core.resilience import with_fallback
from cme213_tpu.core.roofline import Cost


@pytest.fixture(autouse=True)
def _clean():
    trace.clear_events()
    diag.reset()
    faults.reset()
    yield
    trace.clear_events()
    diag.reset()
    faults.reset()


# ------------------------------------------------------------ health ladder

def test_health_report_passes_on_cpu():
    rep = diag.health_report(timeout_s=60.0)
    assert rep["healthy"] is True
    assert rep["platform"] == "cpu"
    assert rep["device_count"] >= 1
    assert rep["probe_ms"] is not None and rep["probe_ms"] >= 0
    stages = {s["stage"]: s for s in rep["stages"]}
    assert stages["enumerate"]["ok"] and stages["liveness"]["ok"]
    # the report emitted a schema-valid device-health event
    evs = trace.events("device-health")
    assert evs and trace.validate_record(evs[-1]) == []
    assert evs[-1]["healthy"] is True
    # ... and set the gauges render_prometheus picks up
    from cme213_tpu.core import metrics
    snap = metrics.snapshot()["gauges"]
    assert snap["diag.device.healthy"] == 1.0
    assert snap["diag.device.count"] == rep["device_count"]
    assert "cme213_diag_device_healthy 1" in metrics.render_prometheus()


def test_health_probe_timeout_is_a_report_not_a_hang(monkeypatch):
    import threading

    hang = threading.Event()
    monkeypatch.setattr(diag, "_probe_liveness",
                        lambda: hang.wait(30))
    rep = diag.health_report(timeout_s=0.2)
    assert rep["healthy"] is False
    live = next(s for s in rep["stages"] if s["stage"] == "liveness")
    assert live["timed_out"] and not live["ok"]
    hang.set()


def test_health_report_injected_unreachable():
    with injected("unreachable:1"):
        rep = diag.health_report(timeout_s=60.0)
    assert rep["healthy"] is False
    live = next(s for s in rep["stages"] if s["stage"] == "liveness")
    assert not live["ok"] and "unreachable" in live["detail"]
    # enumerate still succeeded: the report says WHICH stage died
    assert next(s for s in rep["stages"]
                if s["stage"] == "enumerate")["ok"]
    assert trace.events("device-health")[-1]["healthy"] is False


def test_unreachable_is_incarnation_gated(monkeypatch):
    monkeypatch.setenv("CME213_INCARNATION", "1")
    with injected("unreachable:1"):
        assert faults.maybe_unreachable("x") is False


def test_device_preflight_consults_unreachable():
    from cme213_tpu.core.platform import device_preflight

    with injected("unreachable:1"):
        assert device_preflight(30.0) is False
    assert device_preflight(30.0) is True


# -------------------------------------------------------- staged forensics

def _dispatch_stages():
    """One with_fallback dispatch whose rung builds through the program
    cache and conformance-gates — the real four-stage ladder."""

    def gate(rung):
        from cme213_tpu.core import conformance
        return conformance.check(
            "diagop", rung, "n8",
            candidate=lambda: jnp.arange(8.0),
            reference=lambda: jnp.arange(8.0)).ok

    def thunk():
        fn = programs.get("diagop", "fancy", "n8",
                          lambda: (lambda x: x + 1),
                          warm=lambda f: f(jnp.zeros(8)))
        return fn(jnp.arange(8.0))

    return with_fallback("diagop", [("fancy", thunk),
                                    ("safe", lambda: jnp.arange(8.0) + 1)],
                         gate=gate)


@pytest.mark.parametrize("clause,stage", [
    ("stage:diagop.fancy:lower:1", "lower"),
    ("stage:diagop.fancy:compile:1", "compile"),
    ("stage:diagop.fancy:execute:1", "execute"),
    ("stage:diagop.fancy:conformance:1", "conformance"),
])
def test_stage_attribution_through_with_fallback(clause, stage):
    from cme213_tpu.core import conformance

    conformance.reset()
    programs.reset()
    with injected(clause):
        result = _dispatch_stages()
    assert result.rung == "safe"          # demoted off the poisoned rung
    kf = [e for e in trace.events("kernel-failure")
          if e["kernel"] == "fancy"]
    assert kf, "dispatch must emit a kernel-failure forensics event"
    assert kf[0]["stage"] == stage
    assert trace.validate_record(kf[0]) == []


def test_conformance_refusal_tagged_conformance_stage():
    from cme213_tpu.core import conformance

    conformance.reset()
    programs.reset()

    def gate(rung):
        return rung != "fancy"  # refuse, don't crash

    r = with_fallback("diagop2", [("fancy", lambda: 1), ("safe", lambda: 2)],
                      gate=gate)
    assert r.value == 2
    kf = trace.events("kernel-failure")
    assert kf[0]["stage"] == "conformance"
    assert kf[0]["error"] == "ConformanceFailed"


def test_failure_stage_heuristics_without_tag():
    assert diag.failure_stage(RuntimeError("Mosaic lowering failed")) \
        == "lower"
    assert diag.failure_stage(RuntimeError("XLA compilation oom: vmem")) \
        == "compile"
    assert diag.failure_stage(RuntimeError("boring crash")) == "execute"
    # explicit tag wins over the default...
    e = diag.mark_stage(RuntimeError("boring crash"), "conformance")
    assert diag.failure_stage(e) == "conformance"
    # ...but a compile-scope tag refines to lower on Mosaic noise
    e2 = diag.mark_stage(RuntimeError("Mosaic unsupported op"), "compile")
    assert diag.failure_stage(e2) == "lower"


def test_stage_scope_records_forensics_state():
    with pytest.raises(ValueError):
        with diag.stage_scope("op.r", "lower"):
            raise ValueError("nope")
    st = diag.forensics_state()
    assert st["open"] is None
    assert st["last_failed"]["op"] == "op.r"
    assert st["last_failed"]["stage"] == "lower"
    assert st["last_failed"]["error"] == "ValueError"


def test_flight_dump_embeds_health_and_forensics(tmp_path, monkeypatch):
    from cme213_tpu.core import flight

    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    diag.health_report(timeout_s=60.0, ring=False)
    with pytest.raises(ValueError):
        with diag.stage_scope("heat.pipeline", "compile"):
            raise ValueError("warm died")
    path = flight.dump("test")
    doc = json.loads(open(path).read())
    assert doc["health"]["healthy"] is True
    assert doc["forensics"]["last_failed"]["op"] == "heat.pipeline"
    # and trace flight renders both
    import io

    from cme213_tpu import trace_cli
    out = io.StringIO()
    trace_cli.render_flight(trace_cli.load_flight(path), out=out)
    text = out.getvalue()
    assert "last device health: HEALTHY" in text
    assert "last failed stage: heat.pipeline @ compile" in text


# -------------------------------------------------- cost-model attribution

def test_wrong_cost_model_trips_attribution_mismatch():
    row = diag.check_attribution(
        "fake", "r", "n4096", lambda x: x + 1.0,
        (jnp.zeros(4096, jnp.float32),),
        Cost(nbytes=10**12, flops=10**12))  # absurd on purpose
    assert row["ok"] is False
    assert "bytes" in row["mismatches"]
    evs = trace.events("attribution-mismatch")
    assert evs and all(trace.validate_record(e) == [] for e in evs)
    assert any(e["metric"] == "bytes" for e in evs)
    assert diag.attribution_records()[-1]["op"] == "fake"


def test_sane_cost_model_passes():
    n = 4096
    # x + 1 reads and writes one f32 vector: ~2*4*n bytes, ~n flops
    row = diag.check_attribution(
        "fake", "r", f"n{n}", lambda x: x + 1.0,
        (jnp.zeros(n, jnp.float32),),
        Cost(nbytes=2 * 4 * n, flops=n))
    assert row["ok"] is True
    assert trace.events("attribution-mismatch") == []


def test_programs_get_runs_attribution_when_enabled(monkeypatch):
    programs.reset()
    monkeypatch.setenv(diag.ATTRIBUTION_ENV, "1")
    programs.get("attrop", "r", "n128", lambda: (lambda x: x * 2.0),
                 warm=lambda f: f(jnp.zeros(128)),
                 cost=Cost(nbytes=10**12, flops=10**12),
                 probe=lambda: (jnp.zeros(128, jnp.float32),))
    assert any(r["op"] == "attrop" for r in diag.attribution_records())
    assert trace.events("attribution-mismatch")
    # disabled by default: no re-lowering on the hot path
    monkeypatch.delenv(diag.ATTRIBUTION_ENV)
    diag.reset()
    programs.reset()
    trace.clear_events()
    programs.get("attrop", "r", "n128", lambda: (lambda x: x * 2.0),
                 cost=Cost(nbytes=1, flops=1),
                 probe=lambda: (jnp.zeros(128, jnp.float32),))
    assert diag.attribution_records() == []


def test_calibrate_reports_flagship_ops():
    rows = diag.calibrate()
    assert {r["op"] for r in rows} == {"spmv_scan", "heat", "sort"}
    spmv = next(r for r in rows if r["op"] == "spmv_scan")
    assert "error" not in spmv
    assert spmv["measured_bytes"] is not None


# -------------------------------------------------------- ring persistence

def test_health_ring_persists_across_subprocess(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "CME213_DIAG_DIR": str(tmp_path)}
    env.pop("CME213_FAULTS", None)
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "cme213_tpu", "doctor", "--json"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert report["healthy"] is True
    assert report["ring_path"] == str(tmp_path / diag.RING_NAME)
    entries = [json.loads(ln) for ln in
               open(tmp_path / diag.RING_NAME) if ln.strip()]
    assert len(entries) == 2
    assert all(e["healthy"] for e in entries)
    assert entries[0]["pid"] != entries[1]["pid"]


def test_ring_caps_entries(tmp_path, monkeypatch):
    monkeypatch.setenv(diag.DIAG_DIR_ENV, str(tmp_path))
    monkeypatch.setattr(diag, "RING_CAP", 3)
    for i in range(5):
        diag._append_ring({"doctor": 1, "n": i})
    entries = diag.read_ring()
    assert [e["n"] for e in entries] == [2, 3, 4]


# ------------------------------------------------------------------- CLI

def test_doctor_cli_json_roundtrip_and_exit_codes(tmp_path):
    base = {**os.environ, "JAX_PLATFORMS": "cpu",
            "CME213_DIAG_DIR": str(tmp_path)}
    base.pop("CME213_FAULTS", None)
    ok = subprocess.run(
        [sys.executable, "-m", "cme213_tpu", "doctor", "--json"],
        capture_output=True, text=True, env=base, timeout=300)
    assert ok.returncode == 0, ok.stderr
    rep = json.loads(ok.stdout)
    assert rep["healthy"] is True and rep["platform"] == "cpu"
    assert [s["stage"] for s in rep["stages"]] == \
        ["enumerate", "memory", "liveness"]

    dead = subprocess.run(
        [sys.executable, "-m", "cme213_tpu", "doctor", "--json"],
        capture_output=True, text=True, timeout=300,
        env={**base, "CME213_FAULTS": "unreachable:1"})
    assert dead.returncode == 1
    rep = json.loads(dead.stdout)      # still a structured report
    assert rep["healthy"] is False
    live = next(s for s in rep["stages"] if s["stage"] == "liveness")
    assert "unreachable" in live["detail"]
    # the failed probe still banked a ring entry
    assert any(not e["healthy"] for e in
               (json.loads(ln) for ln in
                open(tmp_path / diag.RING_NAME) if ln.strip()))

    # gated off past the first incarnation: a restarted process probes ok
    reborn = subprocess.run(
        [sys.executable, "-m", "cme213_tpu", "doctor"],
        capture_output=True, text=True, timeout=300,
        env={**base, "CME213_FAULTS": "unreachable:1",
             "CME213_INCARNATION": "1"})
    assert reborn.returncode == 0, reborn.stderr


def test_trace_summary_renders_forensics_and_require(tmp_path):
    """trace summary groups kernel-failure by stage (conformance refusals
    apart from crashes) and --require accepts the new event names."""
    sink = tmp_path / "t.jsonl"
    recs = [
        {"event": "kernel-failure", "t": 1.0, "op": "heat2d",
         "kernel": "pipeline-k4", "stage": "lower",
         "error": "Mosaic lowering failed", "pid": 1, "incarnation": 0},
        {"event": "kernel-failure", "t": 2.0, "op": "spmv_scan",
         "kernel": "pallas-fused", "stage": "conformance",
         "error": "ConformanceFailed", "pid": 1, "incarnation": 0},
        {"event": "device-health", "t": 3.0, "healthy": False,
         "platform": "tpu", "devices": 4, "probe_ms": None,
         "pid": 1, "incarnation": 0},
        {"event": "attribution-mismatch", "t": 4.0, "op": "heat",
         "rung": "xla", "shape_class": "n64", "metric": "bytes",
         "predicted": 1.0, "measured": 9.0, "ratio": 9.0,
         "pid": 1, "incarnation": 0},
    ]
    sink.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    import io

    from cme213_tpu import trace_cli
    events = trace_cli.load_events([str(sink)])
    out = io.StringIO()
    agg = trace_cli.summarize(events, out=out)
    text = out.getvalue()
    assert "kernel forensics: 2 failure(s), 1 crash(es), " \
           "1 conformance refusal(s)" in text
    assert "lower" in text and "refused: spmv_scan.pallas-fused" in text
    assert "device health: 1 probe(s); last UNHEALTHY" in text
    assert "attribution mismatches: 1" in text
    assert agg["forensics"][
        "heat2d.pipeline-k4:lower:Mosaic lowering failed"] == 1
    assert agg["health"]["last_healthy"] is False
    assert agg["attribution_mismatches"] == 1
    # --require: the new names gate cleanly
    rc = trace_cli.main(["summary", str(sink), "--require",
                         "device-health,attribution-mismatch,"
                         "kernel-failure"])
    assert rc == 0
    assert trace_cli.main(["summary", str(sink), "--require",
                           "no-such-event"]) == 1


def test_fault_grammar_rejects_bad_stage():
    from cme213_tpu.core.faults import FaultPlan, FaultSpecError

    with pytest.raises(FaultSpecError):
        FaultPlan.parse("stage:op.r:warp:1")
    with pytest.raises(FaultSpecError):
        FaultPlan.parse("unreachable")
    plan = FaultPlan.parse("unreachable:2:3,stage:op.r:execute:1")
    assert plan.clauses[0].nth == 2 and plan.clauses[0].count == 3
    assert plan.clauses[1].stage == "execute"
