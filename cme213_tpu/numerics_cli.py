"""``python -m cme213_tpu numerics`` — the numeric-health report and gate.

The reference validates numerics offline: hw2 diffs ``grid_final_*``
files after the run, hw_final checks a relative error at exit.  This
framework moves that check in-path (``core/numerics.py``: shadow
conformance sampling, drift budgets, output sentinels, convergence
tracing) and this CLI is the offline rollup over the trace sinks those
subsystems write — the artifact-only view for CI and post-mortems.

Subcommand::

    numerics report <sink.jsonl> [...] [--json]
                    [--max-over-budget N] [--min-samples N]
                    [--forbid-stall]

``report`` reuses the trace summarizer's aggregation (``trace_cli.py``)
and prints only the numeric-health and convergence sections.  Gates:

- ``--max-over-budget N``: exit 1 when more than N shadow samples were
  over the drift tolerance (``--max-over-budget 0`` is the "clean run
  must show zero drift" CI gate).
- ``--min-samples N``: exit 1 unless at least N shadow samples landed —
  guards against a gate that trivially passes because sampling was off.
- ``--forbid-stall``: exit 1 when any solver's convergence trace ends
  STALLED.
"""

from __future__ import annotations

import argparse
import io
import json
import sys

from .trace_cli import TraceParseError, load_events, summarize


def report(files: list[str]) -> dict:
    """Aggregate the numeric-health view of one or many sinks."""
    events = load_events(files)
    agg = summarize(events, out=io.StringIO())  # text discarded; dict kept
    return {
        "events": agg["events"],
        "numerics": agg.get("numerics"),
        "convergence": agg.get("convergence"),
    }


def render(doc: dict, out=None) -> None:
    out = out or sys.stdout
    w = out.write
    numeric = doc.get("numerics")
    if not numeric:
        w("numeric health: no shadow samples, sentinels, or budget "
          "events in these sinks\n")
    else:
        w(f"numeric health: {numeric['samples']} shadow sample(s), "
          f"{numeric['over_budget']} over budget, "
          f"{len(numeric['demotions'])} demotion(s), "
          f"{numeric['sentinels']['trips']} sentinel trip(s)\n")
        for key, row in sorted((numeric.get("drift") or {}).items()):
            w(f"  {key}: {row['samples']} sample(s), "
              f"{row['over_budget']} over, "
              f"worst rel_l2 {row['worst_rel_l2']}\n")
        for key in numeric["demotions"]:
            w(f"  DEMOTED {key}\n")
    convergence = doc.get("convergence")
    if convergence:
        for op, row in sorted(convergence.items()):
            verdict = "STALLED" if row.get("stalled") else "converging"
            w(f"solver {op}: {row['epochs']} epoch(s), residual "
              f"{row['first_residual']} -> {row['last_residual']}, "
              f"{verdict}\n")


def _gate(doc: dict, args) -> list[str]:
    """The CI verdicts; each string is one failed gate."""
    numeric = doc.get("numerics") or {}
    samples = numeric.get("samples", 0)
    over = numeric.get("over_budget", 0)
    failures = []
    if args.min_samples is not None and samples < args.min_samples:
        failures.append(f"only {samples} shadow sample(s), "
                        f"gate needs >= {args.min_samples}")
    if args.max_over_budget is not None and over > args.max_over_budget:
        failures.append(f"{over} shadow sample(s) over the drift budget, "
                        f"gate allows <= {args.max_over_budget}")
    if args.forbid_stall:
        stalled = sorted(op for op, row in
                         (doc.get("convergence") or {}).items()
                         if row.get("stalled"))
        if stalled:
            failures.append("stalled solver(s): " + ", ".join(stalled))
    return failures


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cme213_tpu numerics",
        description="numeric-health report + CI gate over trace sinks")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="numeric-health rollup over sinks")
    p.add_argument("files", nargs="+")
    p.add_argument("--json", action="store_true",
                   help="emit the rollup as one JSON document")
    p.add_argument("--max-over-budget", type=int, default=None,
                   help="exit 1 when more shadow samples than this were "
                        "over the drift tolerance (0 = clean-run gate)")
    p.add_argument("--min-samples", type=int, default=None,
                   help="exit 1 unless at least this many shadow samples "
                        "landed (guards against sampling being off)")
    p.add_argument("--forbid-stall", action="store_true",
                   help="exit 1 when any solver convergence trace ends "
                        "STALLED")
    args = ap.parse_args(argv)

    try:
        doc = report(args.files)
    except (OSError, TraceParseError) as e:
        print(f"numerics: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, sort_keys=True, default=str))
    else:
        render(doc)
    failures = _gate(doc, args)
    for f in failures:
        print(f"GATE FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
