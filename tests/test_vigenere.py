import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.apps import vigenere as vg

# English letter frequencies (approx) for synthetic corpus generation —
# IOC of iid text from this distribution is 26·Σp² ≈ 1.73 > 1.6, matching
# real English (the reference uses mobydick.txt; we synthesize).
ENGLISH_FREQ = np.array([
    8.17, 1.49, 2.78, 4.25, 12.70, 2.23, 2.02, 6.09, 6.97, 0.15, 0.77, 4.03,
    2.41, 6.75, 7.51, 1.93, 0.10, 5.99, 6.33, 9.06, 2.76, 0.98, 2.36, 0.15,
    1.97, 0.07,
])
ENGLISH_FREQ = ENGLISH_FREQ / ENGLISH_FREQ.sum()


def english_like(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.choice(26, size=n, p=ENGLISH_FREQ) + ord("a")).astype(np.uint8)


def test_sanitize():
    raw = np.frombuffer(b"Hello, World! 123 abcXYZ", dtype=np.uint8)
    out = vg.sanitize(raw)
    assert bytes(out) == b"helloworldabcxyz"


def test_sanitize_empty_and_all_kept():
    assert vg.sanitize(np.frombuffer(b"!!!", dtype=np.uint8)).size == 0
    clean = np.frombuffer(b"abc", dtype=np.uint8)
    assert bytes(vg.sanitize(clean)) == b"abc"


def test_generate_key_range_and_determinism():
    k1 = vg.generate_key(7, seed=123)
    k2 = vg.generate_key(7, seed=123)
    np.testing.assert_array_equal(k1, k2)
    assert (k1 >= 1).all() and (k1 <= 26).all()


def test_encode_decode_roundtrip():
    text = english_like(1000)
    shifts = vg.generate_key(5)
    enc = vg.encode(text, shifts)
    dec = vg.decode(enc, shifts)
    np.testing.assert_array_equal(dec, text)
    assert (enc >= ord("a")).all() and (enc <= ord("z")).all()


def test_letter_histogram():
    text = english_like(20000, seed=3)
    hist = np.asarray(vg.letter_histogram(jnp.asarray(text)))
    ref = np.bincount(text - ord("a"), minlength=26)
    np.testing.assert_array_equal(hist, ref)
    assert hist.sum() == 20000
    assert hist.argmax() == ord("e") - ord("a")


def test_digraph_top20():
    text = np.frombuffer(b"ababababac", dtype=np.uint8)
    codes, counts = vg.digraph_top20(jnp.asarray(text))
    codes, counts = np.asarray(codes), np.asarray(counts)
    ab = 0 * 26 + 1
    ba = 1 * 26 + 0
    assert codes[0] == ab and counts[0] == 4
    assert codes[1] == ba and counts[1] == 4


def test_ioc_flat_vs_english():
    flat = (np.arange(26, dtype=np.uint8) + ord("a"))[
        np.tile(np.arange(26), 1000)]
    eng = english_like(26000, seed=5)
    assert vg.index_of_coincidence(jnp.asarray(flat), 3) < 1.3
    assert vg.index_of_coincidence(jnp.asarray(eng), 3) > 1.6


def test_full_crack_roundtrip():
    """Cross-implementation round-trip (reference hw3 grading methodology,
    PA3_handout §3.1): create_cipher output must be crackable."""
    text = english_like(60000, seed=7)
    shifts = vg.generate_key(6, seed=99)
    cipher = vg.encode(text, shifts)
    result = vg.crack(cipher)
    assert result.key_length == 6
    np.testing.assert_array_equal(result.shifts % 26, shifts % 26)
    np.testing.assert_array_equal(result.plain_text, text)


def test_cli_round_trip(tmp_path, capsys):
    """File-level create→solve round trip (the PA3 §3.1 grading commands)."""
    import re

    from cme213_tpu.apps.vigenere import main_create, main_solve

    raw = tmp_path / "input.txt"
    # sprinkle punctuation/uppercase so sanitize has work to do
    body = english_like(60000, seed=19)
    noisy = np.insert(body, np.arange(0, body.size, 97), ord("!"))
    noisy.astype(np.uint8).tofile(str(raw))
    cipher_path = tmp_path / "cipher_text.txt"
    plain_path = tmp_path / "plain_text.txt"

    main_create(["create", str(raw), "5"], out_path=str(cipher_path))
    created_out = capsys.readouterr().out
    key_created = re.search(r"Key: (\w+)", created_out).group(1)

    main_solve(["solve", str(cipher_path)], out_path=str(plain_path))
    solved_out = capsys.readouterr().out
    key_solved = re.search(r"Key: (\w+)", solved_out).group(1)

    assert key_created == key_solved
    plain = np.fromfile(str(plain_path), dtype=np.uint8)
    np.testing.assert_array_equal(plain, body)


def test_workload_registry():
    from cme213_tpu.models import WORKLOADS, dispatch, usage

    assert set(WORKLOADS) == {"cipher", "pagerank", "heat2d", "vigenere",
                              "sorts", "spmv_scan", "trace", "serve",
                              "tune", "doctor", "collect", "top",
                              "numerics", "fleet", "chaos"}
    assert dispatch(["--help"]) == 0
    assert dispatch(["no-such-workload"]) == 2
    for w in WORKLOADS.values():
        assert w.name in usage() and w.reference_unit in usage()


def test_crack_key_length_one():
    text = english_like(30000, seed=11)
    shifts = np.array([13], dtype=np.int32)
    cipher = vg.encode(text, shifts)
    result = vg.crack(cipher)
    assert result.key_length == 1
    np.testing.assert_array_equal(result.plain_text, text)
