// Host-native problem-file IO for the SpMV-scan engine.
//
// The reference's loader is a native C++ component (`matrix::load()`,
// hw/hw_final/programming/aux/mp1-util.h:81-169) reading the `a.txt`
// header `n p q N` followed by the value/segment/gather vectors, and the
// driver writes `b.txt` one value per line (fp.cu:192-212).  This is the
// framework's equivalent: a single-pass buffered tokenizer (no iostream
// locale machinery), ~20x faster than a Python split() loop on the
// benchmark-suite instances, exposed to Python via ctypes with a pure
// Python fallback.

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>

namespace {

struct FileBuf {
    std::unique_ptr<char[]> data;
    long long size = 0;
    bool ok = false;
};

FileBuf slurp(const char *path) {
    FileBuf fb;
    FILE *f = std::fopen(path, "rb");
    if (!f) return fb;
    std::fseek(f, 0, SEEK_END);
    long long sz = std::ftell(f);
    if (sz < 0) {  // non-seekable (pipe): no clean size, refuse
        std::fclose(f);
        return fb;
    }
    std::fseek(f, 0, SEEK_SET);
    fb.data.reset(new char[sz + 1]);
    fb.size = sz;
    fb.ok = (std::fread(fb.data.get(), 1, sz, f) == (size_t)sz);
    fb.data[sz] = '\0';
    std::fclose(f);
    return fb;
}

inline void skip_ws(const char *&p) {
    while (*p && std::isspace((unsigned char)*p)) ++p;
}

inline bool next_ll(const char *&p, long long &out) {
    skip_ws(p);
    if (!*p) return false;
    char *end;
    out = std::strtoll(p, &end, 10);
    if (end == p) return false;
    p = end;
    return true;
}

inline bool next_f(const char *&p, float &out) {
    skip_ws(p);
    if (!*p) return false;
    char *end;
    out = std::strtof(p, &end);
    if (end == p) return false;
    p = end;
    return true;
}

}  // namespace

extern "C" {

// Header of a.txt: n p q iters.  Returns 0 on success.  Reads only a
// prefix — suite-scale a.txt files run to hundreds of MB and the header
// is the first line.
int spmv_read_header(const char *path, long long out[4]) {
    FILE *f = std::fopen(path, "rb");
    if (!f) return 1;
    char buf[256];
    size_t got = std::fread(buf, 1, sizeof buf - 1, f);
    std::fclose(f);
    buf[got] = '\0';
    const char *p = buf;
    for (int i = 0; i < 4; ++i)
        if (!next_ll(p, out[i])) return 2;
    return 0;
}

// Full a.txt: header (skipped) then a[n] floats, s[p] ints, k[n] ints.
// Caller allocates.  Returns 0 on success, >0 = parse error position class.
int spmv_read_arrays(const char *path, float *a, long long n, int *s,
                     long long p_len, int *k) {
    FileBuf fb = slurp(path);
    if (!fb.ok) return 1;
    const char *p = fb.data.get();
    long long tmp;
    for (int i = 0; i < 4; ++i)
        if (!next_ll(p, tmp)) return 2;
    for (long long i = 0; i < n; ++i)
        if (!next_f(p, a[i])) return 3;
    for (long long i = 0; i < p_len; ++i) {
        if (!next_ll(p, tmp)) return 4;
        s[i] = (int)tmp;
    }
    for (long long i = 0; i < n; ++i) {
        if (!next_ll(p, tmp)) return 5;
        k[i] = (int)tmp;
    }
    return 0;
}

// Whitespace-separated floats (x.txt / b.txt).  Returns the count parsed
// (up to cap), or -1 on open failure.
long long read_floats(const char *path, float *out, long long cap) {
    FileBuf fb = slurp(path);
    if (!fb.ok) return -1;
    const char *p = fb.data.get();
    long long cnt = 0;
    float v;
    while (cnt < cap && next_f(p, v)) out[cnt++] = v;
    return cnt;
}

// One value per line, shortest round-trip float formatting (b.txt shape,
// fp.cu:192-199).  Returns 0 on success.
int write_floats(const char *path, const float *v, long long count) {
    FILE *f = std::fopen(path, "wb");
    if (!f) return 1;
    char buf[64];
    for (long long i = 0; i < count; ++i) {
        int len = std::snprintf(buf, sizeof buf, "%.9g\n", (double)v[i]);
        if (std::fwrite(buf, 1, len, f) != (size_t)len) {
            std::fclose(f);
            return 2;
        }
    }
    return std::fclose(f) ? 3 : 0;
}

}  // extern "C"
