"""Segmented inclusive scan — the hw_final engine primitive.

TPU-native redesign of the reference's intra-warp segmented-scan kernel
(one 32-thread warp per segment sliding a 31-element Hillis-Steele window,
``hw/hw_final/programming/fp.cu:28-59``).  TPUs have no warps; the idiomatic
form is a flag-based associative scan (Blelloch/Sengupta operator, cf.
``my-refs/scan.pdf``): scan pairs ``(value, head_flag)`` with

    (va, fa) ⊕ (vb, fb) = (vb + (fb ? 0 : va), fa | fb)

which is associative, so ``lax.associative_scan`` runs it in log depth fused
by XLA across the whole array regardless of segment boundaries — replacing
the reference's data-dependent per-segment loops with regular control flow.

Segment descriptors match the reference's: ``s`` = sorted segment start
indices with ``s[0] == 0`` (validated like ``load()``,
``hw/hw_final/programming/aux/mp1-util.h:81-169``); the precomputed
``key[i] = segment id`` vector (``fp.cu:111-125``) is ``segment_ids`` here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def head_flags_from_starts(seg_starts: jnp.ndarray, n: int) -> jnp.ndarray:
    """int32 {0,1} vector with 1 at each segment head."""
    flags = jnp.zeros((n,), jnp.int32)
    return flags.at[seg_starts].set(1, mode="drop")


def segment_ids_from_starts(seg_starts: jnp.ndarray, n: int) -> jnp.ndarray:
    """``key[i] = segment id`` (the fp.cu:111-125 precompute): cumulative sum
    of head flags minus one."""
    return jnp.cumsum(head_flags_from_starts(seg_starts, n)) - 1


def segmented_scan(values: jnp.ndarray, head_flags: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented sum scan over (value, flag) pairs.

    Hillis-Steele log-depth sweep — the same doubling-stride recurrence the
    reference's ``scan_warp`` runs over a 31-element warp window
    (fp.cu:28-58), here applied to the whole array at once with the
    segment-aware operator: at stride d,

        v[i] += f[i] ? 0 : v[i-d]        (stop at segment heads)
        f[i] |= f[i-d]

    One traced body under ``fori_loop`` (stride computed from the loop index)
    keeps compilation O(1) in n.
    """
    n = values.shape[0]
    steps = max(1, (n - 1).bit_length())
    idx = jnp.arange(n, dtype=jnp.int32)

    def body(i, carry):
        v, f = carry
        d = jnp.int32(1) << i
        pv = jnp.roll(v, d)
        pf = jnp.roll(f, d)
        valid = idx >= d
        add = jnp.where(valid & (f == 0), pv, jnp.zeros_like(v))
        newf = jnp.where(valid, f | pf, f)
        return (v + add, newf)

    out, _ = lax.fori_loop(0, steps, body, (values, head_flags.astype(jnp.int32)))
    return out


def segmented_scan_from_starts(values: jnp.ndarray, seg_starts: jnp.ndarray) -> jnp.ndarray:
    flags = head_flags_from_starts(seg_starts, values.shape[0])
    return segmented_scan(values, flags)


def segmented_scan_dense(values: jnp.ndarray, seg_starts: jnp.ndarray,
                         max_seg_len: int) -> jnp.ndarray:
    """Dense per-segment formulation — the regular-shape analog of the
    reference's naive one-thread-per-segment kernel (``fp_old.cu:30-58``).

    Scatters each segment into a row of a (p, max_seg_len) matrix, cumsums
    along the row axis, and gathers back.  O(p·max_seg_len) work — efficient
    only when segment lengths are balanced; kept as the performance
    strawman/alternative, exactly the role fp_old.cu played.
    """
    n = values.shape[0]
    ids = segment_ids_from_starts(seg_starts, n)
    offs = jnp.arange(n, dtype=jnp.int32) - seg_starts[ids]
    p = seg_starts.shape[0]
    dense = jnp.zeros((p, max_seg_len), values.dtype)
    dense = dense.at[ids, offs].set(values, mode="drop")
    scanned = jnp.cumsum(dense, axis=1)
    return scanned[ids, offs]


def validate_segments(seg_starts, n: int, num_segments: int | None = None) -> None:
    """Host-side invariant checks, as the reference ``load()`` asserts
    (aux/mp1-util.h:128-148): strictly increasing, s[0]==0, all < n."""
    import numpy as np

    s = np.asarray(seg_starts)
    if num_segments is not None and s.shape[0] != num_segments:
        raise ValueError(f"expected {num_segments} segments, got {s.shape[0]}")
    if s.shape[0] == 0 or s[0] != 0:
        raise ValueError("first segment must start at 0")
    if (np.diff(s) <= 0).any():
        raise ValueError("segment starts must be strictly increasing")
    if s[-1] >= n:
        raise ValueError("segment start beyond array end")
