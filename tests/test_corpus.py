"""The shipped corpus (examples/corpus.txt) must carry real English
statistics at the reference's input scale (hw/hw1/programming/mobydick.txt,
1.2 MB) — the hw3 attack's assumptions are tested against it directly."""

import collections
import os

import numpy as np
import jax.numpy as jnp
import pytest

from cme213_tpu.apps import vigenere as vg
from cme213_tpu.apps.corpus import (corpus_path, load_corpus,
                                    make_english_corpus)


@pytest.fixture(scope="module")
def corpus() -> np.ndarray:
    data = load_corpus()
    assert data.size >= 1_200_000, "corpus must match mobydick scale"
    return data


def test_shipped_file_matches_generator(corpus):
    """examples/corpus.txt is exactly make_english_corpus() — the artifact
    is committed for stability, but must never drift from its generator.
    Byte-equality is only meaningful on the numpy version the artifact was
    generated with (Generator streams aren't stable across versions); the
    statistics tests below run unconditionally."""
    from cme213_tpu.apps.corpus import GENERATED_WITH_NUMPY

    if not os.path.exists(corpus_path()):
        pytest.skip("no shipped corpus file")
    if np.__version__ != GENERATED_WITH_NUMPY:
        pytest.skip(f"numpy {np.__version__} != {GENERATED_WITH_NUMPY}")
    regen = np.frombuffer(make_english_corpus(), dtype=np.uint8)
    np.testing.assert_array_equal(corpus, regen)


def test_letter_frequencies_english_order(corpus):
    clean = vg.sanitize(corpus)
    hist = np.bincount(clean - ord("a"), minlength=26)
    top = "".join(chr(ord("a") + i) for i in np.argsort(hist)[::-1][:4])
    # e and t lead in any English-statistics text
    assert top[0] == "e" and top[1] == "t", top


def test_ioc_is_english_not_uniform(corpus):
    clean = jnp.asarray(vg.sanitize(corpus))
    # Real text is *correlated*: at lag 1 coincidences are rare (double
    # letters), while mid lags sit well above the 1.6 detector threshold
    # (uniform text is ~1.0 at every lag).  Measured on this corpus:
    # lag 1 ≈ 0.84, lag 3 ≈ 2.08, lag 7 ≈ 1.84.
    assert vg.index_of_coincidence(clean, 1) < 1.2
    for lag in (3, 7):
        assert 1.6 < vg.index_of_coincidence(clean, lag) < 2.6


def test_top_digraphs_are_english(corpus):
    clean = bytes(vg.sanitize(corpus))
    dg = collections.Counter(zip(clean, clean[1:]))
    top10 = {bytes(p).decode() for p, _ in dg.most_common(10)}
    # the classic English digraph leaders
    assert {"th", "he", "an", "er", "in"} <= top10, top10


def test_crack_roundtrip_at_full_scale(corpus):
    """VERDICT r3 item 4: the create→crack round trip at ~1.2 MB (the
    reference grades at mobydick scale, PA3_handout §3.1)."""
    clean = vg.sanitize(corpus)
    shifts = vg.generate_key(7, seed=42)
    cipher = vg.encode(clean, shifts)
    result = vg.crack(cipher)
    assert result.key_length == 7
    np.testing.assert_array_equal(result.shifts % 26, shifts % 26)
    np.testing.assert_array_equal(result.plain_text, clean)


def test_load_corpus_tiles_to_length():
    data = load_corpus(3_000_000)
    assert data.size == 3_000_000


def test_generator_never_short():
    """The word pool redraws when sentence draws skew long — the output
    must reach the requested size for any (size, seed), including sizes
    far above the initial block estimate."""
    for n, seed in [(500, 0), (5_000, 11), (10_000, 3), (40_000, 7)]:
        data = make_english_corpus(n, seed)
        assert len(data) >= n, (n, seed, len(data))
        assert data.decode("ascii")  # stays pure ASCII


def test_generator_exact_boundary():
    """Requesting exactly an achievable output length must not come up a
    byte short: same seed re-emits the same paragraphs, so asking for the
    previous output's exact length exercises the size==n_bytes exit."""
    for n, seed in [(500, 0), (5_000, 11), (10_000, 3)]:
        m = len(make_english_corpus(n, seed))
        data = make_english_corpus(m, seed)
        assert len(data) >= m, (n, seed, m, len(data))
