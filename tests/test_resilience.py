"""Core resilience primitives: fault-plan parsing, failure classification,
deterministic retry, the fallback-ladder combinator, structured check_op
records, and the hardened checkpoint layer (checksums, quarantine,
last-good retention, pytree states, NaN rollback)."""

import os
import warnings

import numpy as np
import pytest

from cme213_tpu.core import (FailureKind, FrameworkError, NonFiniteError,
                             RetryPolicy, all_finite, check_op,
                             classify_failure, clear_events, events,
                             with_fallback)
from cme213_tpu.core import faults
from cme213_tpu.core.checkpoint import (CORRUPT_SUFFIX, PREV_SUFFIX,
                                        load_checkpoint, run_with_checkpoints,
                                        save_checkpoint,
                                        save_state_checkpoint)


# ------------------------------------------------------------ fault plans

def test_fault_spec_parsing():
    plan = faults.FaultPlan.parse(
        "fail:op.a:2:3, nan:solve, ckpt:truncate:4, rankkill:1:5")
    kinds = [(c.kind, c.op, c.nth, c.count) for c in plan.clauses]
    assert kinds == [("fail", "op.a", 2, 3), ("nan", "solve", 1, 1),
                     ("ckpt", "truncate", 4, 1), ("rankkill", "1", 5, 1)]


@pytest.mark.parametrize("bad", ["explode:x", "fail", "ckpt:corrupt",
                                 "fail:op:notanint"])
def test_fault_spec_errors(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan.parse(bad)


def test_maybe_fail_nth_and_count():
    with faults.injected("fail:op.x:2:2"):
        faults.maybe_fail("op.x")                       # call 1: clean
        with pytest.raises(faults.InjectedFault):
            faults.maybe_fail("op.x")                   # call 2: fires
        with pytest.raises(faults.InjectedFault):
            faults.maybe_fail("op.x")                   # call 3: window
        faults.maybe_fail("op.x")                       # call 4: clean
        faults.maybe_fail("op.other")                   # other op untouched


def test_disabled_plan_is_a_noop(monkeypatch):
    monkeypatch.delenv("CME213_FAULTS", raising=False)
    faults.reset()
    faults.maybe_fail("anything")
    state = np.ones(3)
    assert faults.maybe_poison("anything", state) is state


def test_maybe_poison_pytree():
    with faults.injected("nan:solve:2"):
        state = {"grid": np.ones(4), "halo": np.zeros(2, np.int32)}
        out1 = faults.maybe_poison("solve", state)      # call 1: clean
        assert np.isfinite(out1["grid"]).all()
        out2 = faults.maybe_poison("solve", state)      # call 2: poisoned
        assert np.isnan(out2["grid"]).any()
        # int leaves are never poisoned; original state never mutated
        assert np.isfinite(state["grid"]).all()
        np.testing.assert_array_equal(out2["halo"], state["halo"])


# ------------------------------------------------------------ classification

@pytest.mark.parametrize("exc,kind", [
    (NonFiniteError("nan state"), FailureKind.NUMERIC),
    (FloatingPointError("overflow"), FailureKind.NUMERIC),
    (RuntimeError("output contains NaN values"), FailureKind.NUMERIC),
    (NotImplementedError("no lowering rule"), FailureKind.COMPILE),
    (RuntimeError("Mosaic failed to compile the kernel"),
     FailureKind.COMPILE),
    (ValueError("unsupported op in lowering"), FailureKind.COMPILE),
    (faults.InjectedFault("injected failure in op"), FailureKind.RUNTIME),
    (OSError("connection reset"), FailureKind.RUNTIME),
])
def test_classify_failure(exc, kind):
    assert classify_failure(exc) == kind


def test_classify_unwraps_framework_error():
    try:
        try:
            raise NotImplementedError("no lowering rule")
        except NotImplementedError as e:
            raise FrameworkError("error in op") from e
    except FrameworkError as fe:
        assert classify_failure(fe) == FailureKind.COMPILE


def test_all_finite():
    import jax.numpy as jnp

    assert all_finite({"a": jnp.ones(3), "b": (np.arange(4),)})
    assert all_finite(np.arange(5, dtype=np.int32))  # ints trivially finite
    bad = {"a": np.array([1.0, np.nan])}
    assert not all_finite(bad)


# ------------------------------------------------------------ retry policy

def test_retry_policy_deterministic_backoff():
    sleeps = []
    pol = RetryPolicy(max_retries=3, base_delay_s=0.01, multiplier=2.0,
                      sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "done"

    assert pol.run(flaky) == "done"
    assert sleeps == [0.01, 0.02]  # geometric, no jitter


def test_retry_policy_does_not_retry_compile_failures():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise NotImplementedError("no lowering rule")

    with pytest.raises(NotImplementedError):
        RetryPolicy(max_retries=3, sleep=lambda s: None).run(broken)
    assert calls["n"] == 1


def test_retry_policy_exhausts():
    def broken():
        raise RuntimeError("always")

    with pytest.raises(RuntimeError):
        RetryPolicy(max_retries=2, sleep=lambda s: None).run(broken)


# ------------------------------------------------------------ fallback ladder

def test_with_fallback_serves_first_healthy_rung():
    clear_events()
    res = with_fallback("op", [("a", lambda: "A"), ("b", lambda: "B")])
    assert (res.value, res.rung, res.demoted) == ("A", "a", False)
    served = events("served")[-1]
    assert served["rung"] == "a" and not served["demoted"]


def test_with_fallback_demotes_and_records():
    clear_events()

    def dead():
        raise RuntimeError("Mosaic failed to compile")

    res = with_fallback("op", [("pallas", dead), ("xla", lambda: 42)])
    assert res.value == 42 and res.rung == "xla" and res.demoted
    assert [f.rung for f in res.failures] == ["pallas"]
    assert res.failures[0].kind == FailureKind.COMPILE
    rec = events("rung-failed")[-1]
    assert rec["op"] == "op" and rec["rung"] == "pallas"
    assert events("served")[-1]["failed_rungs"] == ["pallas"]


def test_with_fallback_injected_fault_demotes():
    clear_events()
    ran = []
    with faults.injected("fail:op.pallas"):
        res = with_fallback("op", [
            ("pallas", lambda: ran.append("pallas") or "P"),
            ("flat", lambda: ran.append("flat") or "F")])
    # the injected fault fires BEFORE the rung runs — the pallas thunk
    # must never execute, exactly like a launch failure
    assert ran == ["flat"] and res.rung == "flat"


def test_with_fallback_all_rungs_dead():
    def dead():
        raise RuntimeError("boom")

    with pytest.raises(FrameworkError, match="all 2 rungs"):
        with_fallback("op", [("a", dead), ("b", dead)])


# ------------------------------------------------------------ check_op

def test_check_op_success_feeds_timer():
    import jax.numpy as jnp

    from cme213_tpu.core import PhaseTimer

    t = PhaseTimer()
    out = check_op("fine", jnp.ones(8), timer=t)
    assert out.shape == (8,)
    assert t.records[-1].label == "fine" and t.records[-1].ms >= 0


def test_check_op_failure_emits_structured_record(monkeypatch):
    import cme213_tpu.core.errors as errors_mod

    def boom(_):
        raise RuntimeError("device exploded")

    monkeypatch.setattr(errors_mod.jax, "block_until_ready", boom)
    clear_events()
    with pytest.raises(FrameworkError, match="error in bad op") as ei:
        check_op("bad op", np.ones(3))
    rec = events("op-failure")[-1]
    assert rec["op"] == "bad op" and rec["error"] == "RuntimeError"
    assert rec["ms"] >= 0
    assert ei.value.record is rec


# ------------------------------------------------------------ checkpoints

def test_checkpoint_corrupt_quarantine(tmp_path):
    p = str(tmp_path / "ck.npz")
    save_checkpoint(p, 3, state=np.arange(6.0))
    data = open(p, "rb").read()
    open(p, "wb").write(data[: len(data) // 2])  # torn write
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert load_checkpoint(p) is None
    assert os.path.exists(p + CORRUPT_SUFFIX)
    assert not os.path.exists(p)
    assert any("quarantined" in str(x.message) for x in w)


def test_checkpoint_foreign_npz_quarantine(tmp_path):
    p = str(tmp_path / "foreign.npz")
    np.savez(p, a=np.arange(3))  # no __step
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert load_checkpoint(p) is None
    assert os.path.exists(p + CORRUPT_SUFFIX)


def test_checkpoint_checksum_mismatch_falls_back_to_prev(tmp_path):
    p = str(tmp_path / "ck.npz")
    save_checkpoint(p, 1, state=np.arange(4.0))
    save_checkpoint(p, 2, state=np.arange(4.0) + 1)
    assert os.path.exists(p + PREV_SUFFIX)
    # flip payload bytes inside the zip without breaking the container:
    # rewrite the current file as a VALID npz whose __crc doesn't match
    with np.load(p) as z:
        step, crc = int(z["__step"]), z["__crc"]
        arr = z["state"]
    np.savez(p, __step=np.int64(step), __crc=crc, state=arr + 100.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        loaded = load_checkpoint(p)
    assert loaded is not None
    step, arrays = loaded
    assert step == 1  # recovered from .prev
    np.testing.assert_array_equal(arrays["state"], np.arange(4.0))
    assert any("checksum" in str(x.message) for x in w)


def test_checkpoint_injected_truncation_recovers(tmp_path):
    p = str(tmp_path / "ck.npz")
    with faults.injected("ckpt:truncate:2"):
        save_checkpoint(p, 1, state=np.zeros(3))
        save_checkpoint(p, 2, state=np.ones(3))  # this write is torn
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        step, arrays = load_checkpoint(p)
    assert step == 1
    np.testing.assert_array_equal(arrays["state"], np.zeros(3))


def test_pytree_state_roundtrip(tmp_path):
    p = str(tmp_path / "ck.npz")
    state = {"grid": np.arange(8.0).reshape(2, 4), "halo": (np.ones(3),)}
    save_state_checkpoint(p, 5, state)
    from cme213_tpu.core.checkpoint import _unflatten_state

    step, arrays = load_checkpoint(p)
    restored = _unflatten_state(arrays)
    assert step == 5
    np.testing.assert_array_equal(restored["grid"], state["grid"])
    np.testing.assert_array_equal(restored["halo"][0], state["halo"][0])


def test_run_with_checkpoints_pytree_resume(tmp_path):
    p = str(tmp_path / "run.npz")
    calls = []

    def step(state, k):
        calls.append(k)
        return {"grid": state["grid"] + k, "halo": state["halo"] * 1}

    init = {"grid": np.zeros(4), "halo": np.arange(2)}
    out = run_with_checkpoints(step, init, 10, p, every=3)
    np.testing.assert_array_equal(out["grid"], np.full(4, 10.0))
    assert calls == [3, 3, 3, 1]
    calls.clear()
    out2 = run_with_checkpoints(step, init, 10, p, every=3)
    np.testing.assert_array_equal(out2["grid"], np.full(4, 10.0))
    np.testing.assert_array_equal(out2["halo"], np.arange(2))
    assert calls == []  # resumed from the final checkpoint


def test_run_with_checkpoints_nan_rollback_bitwise(tmp_path):
    def step(state, k):
        return state + k

    with faults.injected("nan:solve:2"):
        out = run_with_checkpoints(step, np.zeros(3), 10,
                                   str(tmp_path / "a.npz"), every=3,
                                   guard=all_finite, op="solve")
    ref = run_with_checkpoints(step, np.zeros(3), 10,
                               str(tmp_path / "b.npz"), every=3,
                               guard=all_finite, op="clean")
    np.testing.assert_array_equal(out, ref)
    assert np.isfinite(out).all()


def test_run_with_checkpoints_first_chunk_rollback(tmp_path):
    # a blow-up in the FIRST chunk rolls back to the step-0 checkpoint
    with faults.injected("nan:solve:1"):
        out = run_with_checkpoints(lambda s, k: s + k, np.zeros(3), 6,
                                   str(tmp_path / "a.npz"), every=2,
                                   guard=all_finite, op="solve")
    np.testing.assert_array_equal(out, np.full(3, 6.0))


def test_run_with_checkpoints_retry_budget(tmp_path):
    # every chunk poisoned: the bounded rollback budget must trip
    with faults.injected("nan:solve,nan:solve:2,nan:solve:3"):
        with pytest.raises(NonFiniteError):
            run_with_checkpoints(lambda s, k: s + k, np.zeros(3), 6,
                                 str(tmp_path / "a.npz"), every=2,
                                 guard=all_finite, op="solve",
                                 max_retries=1)
