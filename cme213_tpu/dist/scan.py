"""Multi-device segmented scan — long-sequence (context) parallelism.

The reference scales scans beyond one worker with the block-scan
decomposition: per-block partial results, a scan over block totals, then a
downsweep (``hw/hw4/programming/radixsort.cpp:44-108``), and slides a warp
window over arbitrarily long segments (``hw/hw_final/programming/fp.cu:
41-59``).  This module is that same pattern at mesh scale (SURVEY §5
"long-context"): a sequence sharded over a mesh axis is scanned per-shard,
shard carries are combined with the segmented-scan operator across devices,
and each shard applies its incoming carry to the elements before its first
segment head.

Two carry-combine backends:

- ``ring`` (default): log2(P) ``lax.ppermute`` distance-d shifts running
  the segmented-scan operator over the mesh axis itself — every hop is a
  neighbor shift on the ICI ring, no gather; the same pattern ring
  attention uses to pipeline KV blocks, applied to scan carries.
- ``gather``: ``lax.all_gather`` of the P carries + an unrolled exclusive
  prefix on each shard — the mesh-scale equivalent of the serial bucket
  scan between the two parallel phases of the reference's radix pass
  (fine for small P).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.segmented import segmented_scan
from .mesh import shard_map


def _carry_gather(carry_v, carry_f, axis_name: str, axis_size: int):
    """Exclusive segmented prefix of shard carries via all_gather +
    unrolled combine (O(P) work replicated on every shard)."""
    vs = lax.all_gather(carry_v, axis_name)      # (P,)
    fs = lax.all_gather(carry_f, axis_name)      # (P,)
    prefixes_v = [jnp.zeros_like(carry_v)]
    prefixes_f = [jnp.zeros_like(carry_f)]
    for j in range(axis_size - 1):
        pv, pf = prefixes_v[-1], prefixes_f[-1]
        prefixes_v.append(vs[j] + jnp.where(fs[j] > 0, jnp.zeros_like(pv), pv))
        prefixes_f.append(pf | fs[j])
    idx = lax.axis_index(axis_name)
    return jnp.stack(prefixes_v)[idx]


def _carry_ring(carry_v, carry_f, axis_name: str, axis_size: int):
    """Exclusive segmented prefix of shard carries via log2(P) ppermute
    shifts — the segmented Hillis-Steele sweep run over the device axis.

    Distance-d hops are neighbor shifts on the ICI ring; shards with no
    source at a given distance receive ppermute's zero fill, which is
    exactly the scan identity (sum 0, no head seen)."""
    inc_v, inc_f = carry_v, carry_f      # inclusive combine through shard i
    idx = lax.axis_index(axis_name)
    d = 1
    while d < axis_size:
        perm = [(i, i + d) for i in range(axis_size - d)]
        pv = lax.ppermute(inc_v, axis_name, perm)
        pf = lax.ppermute(inc_f, axis_name, perm)
        valid = idx >= d
        inc_v = inc_v + jnp.where(valid & (inc_f == 0), pv,
                                  jnp.zeros_like(pv))
        inc_f = jnp.where(valid, inc_f | pf, inc_f)
        d *= 2
    # exclusive = inclusive of the previous shard, shifted down the ring
    perm1 = [(i, i + 1) for i in range(axis_size - 1)]
    return lax.ppermute(inc_v, axis_name, perm1)


def _local_with_carry(values, flags, axis_name: str, axis_size: int,
                      carry_mode: str = "ring"):
    local = segmented_scan(values, flags)
    # shard carry: (last partial sum, does my shard contain a head?)
    carry_v = local[-1]
    carry_f = jnp.max(flags).astype(jnp.int32)
    combine = _carry_ring if carry_mode == "ring" else _carry_gather
    incoming = combine(carry_v, carry_f, axis_name, axis_size)
    # apply to elements of the incoming open segment: position i belongs to
    # it iff no head at any position <= i (cummax of flags still 0)
    no_head_yet = lax.cummax(flags, axis=0) == 0
    return local + jnp.where(no_head_yet, incoming, jnp.zeros_like(incoming))


def make_iterated_sharded_scan(mesh: Mesh, axis_name: str | None = None,
                               carry_mode: str = "ring"):
    """Build the device-resident iterated form of the sharded scan — the
    ``a ← segmented_scan(a · xx)`` hot loop of ``apps/spmv_scan`` run as N
    iterations inside ONE ``shard_map``-of-``jit`` (no resharding between
    iterations, input buffer donated).

    Returns ``iterate(a, xx, flags, iters)``; all three arrays must
    already be sharded over ``axis_name``.  This is the chunk runner the
    supervised/checkpointed distributed solve drives epoch by epoch: the
    same jitted callable serves every chunk length from one cache entry
    per distinct ``iters``.
    """
    axis_name = axis_name or mesh.axis_names[0]
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    spec = P(axis_name)

    @partial(jax.jit, static_argnames=("iters",), donate_argnums=(0,))
    def iterate(a_d, xx_d, fl_d, iters: int):
        def sharded(a_blk, xx_blk, fl_blk):
            def body(_, v):
                return _local_with_carry(v * xx_blk, fl_blk,
                                         axis_name=axis_name,
                                         axis_size=axis_size,
                                         carry_mode=carry_mode)

            return jax.lax.fori_loop(0, iters, body, a_blk)

        return shard_map(sharded, mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec)(a_d, xx_d, fl_d)

    return iterate


def make_iterated_sharded_scan_gated(mesh: Mesh, axis_name: str | None = None):
    """``make_iterated_sharded_scan`` behind the conformance gate.

    The carry-combine backends form a natural ladder — ``ring`` (log-P
    ppermute, the fast path) demoting to ``gather`` (all_gather + local
    prefix, structurally simpler) — and each mode's first use per process
    is probed: a small deterministic sharded scan against the
    single-device ``segmented_scan_flat`` reference, to the iterated-scan
    tolerance (both modes reorder the carry combine, so bitwise is not
    their contract).  A mode whose probe diverges (real, or
    ``CME213_FAULTS=wrong:dist_scan``) is demoted with ``WRONG_ANSWER``
    before it can serve.  Returns ``(iterate, carry_mode)``.
    """
    import numpy as np

    from ..core import conformance
    from ..core.resilience import with_fallback
    from ..ops.segmented import segmented_scan_flat

    axis_name = axis_name or mesh.axis_names[0]
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def gate(mode: str) -> bool:
        def probe():
            n = 64 * axis_size
            values = jnp.asarray(
                np.sin(np.arange(n, dtype=np.float32)) + 0.5)
            flags = jnp.asarray((np.arange(n) % 23 == 0).astype(np.int32))
            return np.asarray(distributed_segmented_scan(
                values, flags, mesh, axis_name, carry_mode=mode))

        def reference():
            n = 64 * axis_size
            values = jnp.asarray(
                np.sin(np.arange(n, dtype=np.float32)) + 0.5)
            flags = jnp.asarray((np.arange(n) % 23 == 0).astype(np.int32))
            return np.asarray(segmented_scan_flat(values, flags))

        return conformance.check(
            "dist_scan", mode, shape_class=f"p{axis_size}",
            candidate=probe, reference=reference, rel_l2=1e-5).ok

    res = with_fallback(
        "dist_scan",
        [(mode, lambda m=mode: make_iterated_sharded_scan(
            mesh, axis_name, carry_mode=m)) for mode in ("ring", "gather")],
        gate=gate)
    return res.value, res.rung


def distributed_segmented_scan(values: jnp.ndarray, head_flags: jnp.ndarray,
                               mesh: Mesh, axis_name: str | None = None,
                               carry_mode: str = "ring"):
    """Segmented inclusive scan of a sequence sharded over one mesh axis.

    ``len(values)`` must divide evenly over the axis.  Works under jit; the
    result carries the same sharding as the input.  ``carry_mode``:
    ``"ring"`` (log-P ppermute sweep) or ``"gather"`` (all_gather + local
    prefix).
    """
    axis_name = axis_name or mesh.axis_names[0]
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if values.shape[0] % axis_size:
        raise ValueError("sequence length must divide over the mesh axis")
    if carry_mode not in ("ring", "gather"):
        raise ValueError(f"unknown carry_mode {carry_mode!r}")
    spec = P(axis_name)
    sharding = NamedSharding(mesh, spec)
    values = jax.device_put(values, sharding)
    head_flags = jax.device_put(head_flags.astype(jnp.int32), sharding)

    fn = jax.jit(shard_map(
        partial(_local_with_carry, axis_name=axis_name, axis_size=axis_size,
                carry_mode=carry_mode),
        mesh=mesh, in_specs=(spec, spec), out_specs=spec,
    ))
    return fn(values, head_flags)
