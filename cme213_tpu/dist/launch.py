"""Multi-process launcher — the ``mpirun -np N`` / PBS layer, as a tool.

The reference launches distributed runs with ``mpirun -np N ./2dHeat`` under
Torque/PBS (``hw/hw5/PA5_Handout.pdf`` §4, ``hw/hw4/programming/pa4.pbs``).
This is the JAX-native equivalent for single-machine and same-host testing:

    python -m cme213_tpu.dist.launch --np 2 [--devices-per-proc 2] -- \
        python my_workload.py

It picks a free coordinator port, spawns N copies of the command with the
standard launcher env (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
``JAX_PROCESS_ID``) that ``dist.multihost.initialize_multihost`` consumes,
prefixes each line of output with its rank (mpirun's ``-tag-output``), and
exits nonzero if any rank fails.

Unlike the reference's MPI_Abort-only model, failure handling is layered:

- ``--max-restarts N``: a rank that exits nonzero is relaunched with the
  SAME rank id (and ``CME213_INCARNATION`` bumped, so deterministic fault
  injection — ``CME213_FAULTS=rankkill:...`` — fires only on the first
  incarnation) up to N times before the job is declared dead.  Restarts
  cover restart-tolerant workloads (idempotent scripts, solvers resuming
  from ``core/checkpoint.py``); ranks blocked inside a collective when a
  peer dies still need the whole-job retry their checkpoint enables.
- ``--timeout SECS``: a hard wall-clock deadline on the whole job — the
  fix for a stuck coordinator handshake hanging the launcher forever.
  Expiry kills all ranks and returns 124 (the ``timeout(1)`` convention,
  which the capture layer already classifies as a device hang).
- ``--handshake-timeout SECS``: exported to ranks as
  ``CME213_HANDSHAKE_TIMEOUT``; ``dist.multihost.initialize_multihost``
  feeds it to ``jax.distributed.initialize(initialization_timeout=...)``
  so a rank whose coordinator never appears fails fast (and can then be
  restarted) instead of blocking for JAX's 5-minute default.

Only a rank exhausting its restart budget fails the job (fail-fast: the
remaining ranks are then terminated, the MPI_Abort analog).

**Supervised gangs** (``--stall-timeout``, ``launch_supervised``): the
per-rank restart above cannot help a rank that dies *mid-collective* — its
peers stay blocked in the halo exchange forever, and only the blunt
whole-job ``--timeout`` ends the misery.  Supervised mode instead treats
the gang as the failure unit (TorchElastic-style): ranks emit file-based
heartbeats carrying their step counter (``dist/supervisor.py``), and the
launcher distinguishes "rank exited" (poll) from "rank alive but frozen"
(heartbeat step unchanged for ``--stall-timeout`` seconds — the hung
collective).  Either verdict kills the WHOLE gang and relaunches it — on a
fresh coordinator port, with the gang incarnation bumped — and the
workload resumes from the last committed epoch (``dist/ckpt.py``, wired by
``--ckpt-dir``/``--ckpt-every``/``--resume``).  Recovery from an injected
``CME213_FAULTS=rankkill:...`` is deterministic: the fault fires only in
incarnation 0, and epoch-committed checkpoints make the recovered solve
bitwise-equal to an uninterrupted sync-path run.

On a real multi-host TPU pod each host runs its own process via the cluster
scheduler and ``--np``/``--proc-id`` come from it; this launcher covers the
reference's single-node ``nodes=1:ppn=N`` placement axis and CI, where
``--devices-per-proc`` fakes per-process chips with host CPU devices.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pump(rank: int, stream, out) -> None:
    for line in stream:
        out.write(f"[rank {rank}] {line}")
        out.flush()


def _template_trace_file(env: dict, rank: int) -> str | None:
    """Expand a ``{rank}`` placeholder in the worker's ``CME213_TRACE_FILE``
    so gang members write per-rank sink files instead of interleaving into
    one (the launcher's own events keep the un-expanded path, which
    ``core/trace`` resolves to ``...main...`` for non-rank processes).
    Returns the worker's resolved sink path (for the live collector and
    the end-of-gang federated exposition), or None when unconfigured."""
    tf = env.get("CME213_TRACE_FILE")
    if tf and "{rank}" in tf:
        tf = tf.replace("{rank}", str(rank))
        env["CME213_TRACE_FILE"] = tf
    return tf


def _template_metrics_file(env: dict, rank: int) -> None:
    """Point the worker's ``CME213_METRICS_FILE`` at a per-rank path —
    ``{rank}``-expanded, else ``.rank<N>``-suffixed — so N workers plus
    the launcher's federated aggregate never clobber one file."""
    mf = env.get("CME213_METRICS_FILE")
    if not mf:
        return
    if "{rank}" in mf:
        env["CME213_METRICS_FILE"] = mf.replace("{rank}", str(rank))
    else:
        env["CME213_METRICS_FILE"] = f"{mf}.rank{rank}"


def _fleet_exposition(sink_paths: list[str]) -> None:
    """After the gang ends, fold every rank's final ``metrics-snapshot``
    (from the per-rank sinks) plus the launcher's own live registry into
    one federated exposition at ``CME213_METRICS_FILE`` — and pin that
    file against the launcher's atexit single-process overwrite."""
    dest = os.environ.get("CME213_METRICS_FILE")
    if not dest:
        return
    try:
        from ..core import metrics
        from ..core.collector import write_fleet_exposition

        write_fleet_exposition(
            [p for p in sink_paths if p], path=dest,
            extra={"launcher": metrics.snapshot()})
    except Exception as exc:  # telemetry must never fail the job
        print(f"[launcher] fleet exposition failed: {exc}", flush=True)


def launch(np_procs: int, cmd: list[str], devices_per_proc: int | None = None,
           coordinator: str | None = None, timeout: float | None = None,
           handshake_timeout: float | None = None,
           max_restarts: int = 0) -> int:
    """Spawn ``np_procs`` copies of ``cmd`` with launcher env; returns the
    first unrecovered nonzero exit code (terminating the other ranks),
    124 on ``timeout`` expiry, else 0.  A failed rank is relaunched with
    the same rank id up to ``max_restarts`` times first."""
    from ..core.trace import propagation_env, record_event, span

    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    procs: dict[int, subprocess.Popen] = {}
    restarts = {rank: 0 for rank in range(np_procs)}
    sink_paths: dict[int, str | None] = {}
    pumps = []
    ctx_env: dict = {}
    rc = 0

    def spawn(rank: int, incarnation: int) -> subprocess.Popen:
        env = dict(os.environ,
                   JAX_COORDINATOR_ADDRESS=coordinator,
                   JAX_NUM_PROCESSES=str(np_procs),
                   JAX_PROCESS_ID=str(rank),
                   CME213_INCARNATION=str(incarnation),
                   **ctx_env)
        sink_paths[rank] = _template_trace_file(env, rank)
        _template_metrics_file(env, rank)
        if handshake_timeout is not None:
            env["CME213_HANDSHAKE_TIMEOUT"] = str(handshake_timeout)
        if devices_per_proc:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{devices_per_proc}").strip()
            env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        t = threading.Thread(target=_pump, args=(rank, p.stdout, sys.stdout),
                             daemon=True)
        t.start()
        pumps.append(t)
        return p

    deadline = (time.monotonic() + timeout) if timeout else None
    try:
        # the gang-launch span is the root every child's spans parent
        # under (via CME213_TRACE_CONTEXT), so a merged multi-rank trace
        # is one causal tree sharing the launcher's trace id
        with span("gang-launch", world=np_procs, coordinator=coordinator):
            record_event("gang-launch", incarnation=0, world=np_procs,
                         coordinator=coordinator)
            ctx_env.update(propagation_env())
            for rank in range(np_procs):
                procs[rank] = spawn(rank, 0)

            # poll ALL ranks: a sequential wait() in rank order would miss
            # a higher rank dying first (e.g. rank 1 crashing while rank 0
            # blocks in the coordinator handshake forever) and never fail
            # fast
            live = set(range(np_procs))
            while live and not rc:
                for i in sorted(live):
                    code = procs[i].poll()
                    if code is None:
                        continue
                    if code and restarts[i] < max_restarts:
                        restarts[i] += 1
                        print(f"[launcher] rank {i} exited {code}; "
                              f"restarting (incarnation "
                              f"{restarts[i]}/{max_restarts})", flush=True)
                        procs[i] = spawn(i, restarts[i])
                        continue
                    live.discard(i)
                    if code and not rc:
                        rc = code
                        # fail-fast: take survivors down
                        for q in procs.values():
                            if q.poll() is None:
                                q.terminate()
                if (deadline is not None and time.monotonic() > deadline
                        and live):
                    print(f"[launcher] timeout after {timeout}s; killing "
                          f"{len(live)} live rank(s)", flush=True)
                    rc = 124
                    for q in procs.values():
                        if q.poll() is None:
                            q.terminate()
                    break
                if live and not rc:
                    time.sleep(0.05)
        record_event("gang-exit", incarnation=0, rc=rc)
    finally:
        for q in procs.values():
            if q.poll() is None:
                q.kill()
        for t in pumps:
            t.join(timeout=5)
        from ..core.trace import flush_sink

        flush_sink()
        _fleet_exposition([p for p in sink_paths.values() if p])
    return rc


def launch_supervised(np_procs: int, cmd: list[str],
                      devices_per_proc: int | None = None,
                      timeout: float | None = None,
                      handshake_timeout: float | None = None,
                      max_restarts: int = 1,
                      heartbeat_interval: float = 1.0,
                      stall_timeout: float = 30.0,
                      ckpt_dir: str | None = None, ckpt_every: int = 0,
                      resume: bool = False,
                      poll_interval: float = 0.05) -> int:
    """Run ``cmd`` as a supervised gang of ``np_procs`` ranks.

    Failure unit = the gang: a rank exiting nonzero OR a rank whose
    heartbeat step freezes for ``stall_timeout`` seconds (hung collective)
    condemns the incarnation — every rank is killed and the gang is
    relaunched on a fresh coordinator port with ``CME213_INCARNATION``
    bumped, up to ``max_restarts`` times.  Relaunched incarnations always
    get ``CME213_RESUME=1`` so the workload resumes from the last
    committed epoch; the first incarnation resumes only when ``resume``.

    Returns 0 on success, the condemning rank's exit code once the budget
    is exhausted (124 for a stall — it is a hang, and the capture layer
    already classifies 124 that way), or 124 on whole-job ``timeout``.
    """
    import contextlib

    from ..core.trace import propagation_env, record_event, span
    from .supervisor import (CKPT_DIR_ENV, CKPT_EVERY_ENV, GangSupervisor,
                             HEARTBEAT_DIR_ENV, HEARTBEAT_INTERVAL_ENV,
                             RESUME_ENV)

    if ckpt_dir:
        os.makedirs(ckpt_dir, exist_ok=True)
        hb_dir = os.path.join(ckpt_dir, ".heartbeats")
    else:
        hb_dir = tempfile.mkdtemp(prefix="cme213_hb_")
    supervisor = GangSupervisor(hb_dir, np_procs, stall_timeout)
    pumps = []
    sink_paths: dict[int, str | None] = {}
    # one gang-launch span per incarnation: children parent their root
    # spans under the incarnation that spawned them, so a merged trace
    # separates pre- and post-restart causality
    gang_span = contextlib.ExitStack()

    def spawn_gang(incarnation: int) -> dict[int, subprocess.Popen]:
        # fresh coordinator port per incarnation: the previous port may be
        # lingering in TIME_WAIT or held by a not-yet-reaped rank
        coordinator = f"127.0.0.1:{free_port()}"
        gang_span.close()
        gang_span.enter_context(
            span("gang-launch", incarnation=incarnation, world=np_procs,
                 coordinator=coordinator))
        record_event("gang-launch", incarnation=incarnation,
                     world=np_procs, coordinator=coordinator)
        ctx_env = propagation_env()
        procs = {}
        for rank in range(np_procs):
            env = dict(os.environ,
                       JAX_COORDINATOR_ADDRESS=coordinator,
                       JAX_NUM_PROCESSES=str(np_procs),
                       JAX_PROCESS_ID=str(rank),
                       CME213_INCARNATION=str(incarnation),
                       **ctx_env)
            sink_paths[rank] = _template_trace_file(env, rank)
            _template_metrics_file(env, rank)
            env[HEARTBEAT_DIR_ENV] = hb_dir
            env[HEARTBEAT_INTERVAL_ENV] = str(heartbeat_interval)
            if ckpt_dir:
                env[CKPT_DIR_ENV] = ckpt_dir
                env[CKPT_EVERY_ENV] = str(ckpt_every)
            env[RESUME_ENV] = "1" if (resume or incarnation > 0) else "0"
            if handshake_timeout is not None:
                env["CME213_HANDSHAKE_TIMEOUT"] = str(handshake_timeout)
            if devices_per_proc:
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count="
                      f"{devices_per_proc}").strip()
                env["JAX_PLATFORMS"] = "cpu"
            p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
            t = threading.Thread(target=_pump,
                                 args=(rank, p.stdout, sys.stdout),
                                 daemon=True)
            t.start()
            pumps.append(t)
            procs[rank] = p
        return procs

    def kill_gang(procs) -> None:
        for q in procs.values():
            if q.poll() is None:
                q.terminate()
        deadline = time.monotonic() + 5
        for q in procs.values():
            while q.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if q.poll() is None:
                q.kill()
                q.wait()

    deadline = (time.monotonic() + timeout) if timeout else None
    incarnation = 0
    procs = spawn_gang(0)
    rc = 0
    try:
        while True:
            condemned = None  # {"rank", "reason", ...} of the first verdict
            exited = {r: p.poll() for r, p in procs.items()}
            for rank, code in sorted(exited.items()):
                if code is not None and code != 0:
                    condemned = {"rank": rank, "reason": "exit",
                                 "code": code}
                    break
            if condemned is None and all(c == 0 for c in exited.values()):
                record_event("gang-exit", incarnation=incarnation, rc=0)
                return 0
            if condemned is None:
                for s in supervisor.stalled():
                    if exited[s["rank"]] is None:  # alive but frozen
                        condemned = {**s, "reason": "stall"}
                        break
            if condemned is None:
                if deadline is not None and time.monotonic() > deadline:
                    print(f"[launcher] timeout after {timeout}s; killing "
                          f"the gang", flush=True)
                    record_event("gang-exit", incarnation=incarnation,
                                 rc=124)
                    return 124
                time.sleep(poll_interval)
                continue

            rc = condemned.get("code", 124)  # stall = hang = 124
            record_event("rank-failed", **condemned,
                         incarnation=incarnation)
            print(f"[launcher] rank {condemned['rank']} "
                  + (f"exited {condemned['code']}"
                     if condemned["reason"] == "exit"
                     else f"stalled at step {condemned.get('step')} for "
                          f"{condemned.get('stalled_s')}s")
                  + "; condemning the gang", flush=True)
            kill_gang(procs)
            if incarnation >= max_restarts:
                print(f"[launcher] gang restart budget exhausted "
                      f"({max_restarts}); failing", flush=True)
                record_event("gang-exit", incarnation=incarnation, rc=rc)
                return rc
            incarnation += 1
            record_event("gang-restart", incarnation=incarnation,
                         reason=condemned["reason"],
                         rank=condemned["rank"])
            print(f"[launcher] gang restart "
                  f"(incarnation {incarnation}/{max_restarts}), resuming "
                  f"from last committed epoch", flush=True)
            supervisor.reset()
            procs = spawn_gang(incarnation)
    finally:
        kill_gang(procs)
        gang_span.close()
        for t in pumps:
            t.join(timeout=5)
        from ..core.trace import flush_sink

        flush_sink()
        _fleet_exposition([p for p in sink_paths.values() if p])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="mpirun-style launcher for multi-process JAX runs")
    ap.add_argument("--np", dest="np_procs", type=int, required=True,
                    help="number of processes (MPI world size)")
    ap.add_argument("--devices-per-proc", type=int, default=None,
                    help="fake this many CPU devices per process "
                         "(testing without a pod)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port (default: 127.0.0.1:<free port>)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="hard wall-clock deadline in seconds for the whole "
                         "job (returns 124 on expiry)")
    ap.add_argument("--handshake-timeout", type=float, default=None,
                    help="coordinator-handshake deadline in seconds, "
                         "exported to ranks as CME213_HANDSHAKE_TIMEOUT")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="relaunch a failed rank (same rank id) up to this "
                         "many times before failing the job; in supervised "
                         "mode (--stall-timeout) this is the GANG restart "
                         "budget")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    help="supervised mode: condemn the gang when any live "
                         "rank's heartbeat step is frozen this many "
                         "seconds (hung collective); the gang is killed "
                         "and relaunched from the last committed epoch")
    ap.add_argument("--heartbeat-interval", type=float, default=1.0,
                    help="supervised mode: seconds between same-step "
                         "heartbeat re-emits (exported as "
                         "CME213_HEARTBEAT_INTERVAL)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="supervised mode: epoch-commit checkpoint "
                         "directory (exported as CME213_CKPT_DIR)")
    ap.add_argument("--ckpt-every", type=int, default=0,
                    help="supervised mode: iterations per committed epoch "
                         "(exported as CME213_CKPT_EVERY)")
    ap.add_argument("--resume", action="store_true",
                    help="supervised mode: the FIRST incarnation also "
                         "resumes from an existing commit in --ckpt-dir "
                         "(gang restarts always resume)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no command given (append: -- python your_script.py)")
    # the launcher records its own black box; workers inherit
    # CME213_FLIGHT_DIR through the env and arm their own recorders
    from ..core import flight

    flight.install()
    if args.stall_timeout is not None:
        return launch_supervised(
            args.np_procs, cmd, args.devices_per_proc,
            timeout=args.timeout, handshake_timeout=args.handshake_timeout,
            max_restarts=args.max_restarts,
            heartbeat_interval=args.heartbeat_interval,
            stall_timeout=args.stall_timeout, ckpt_dir=args.ckpt_dir,
            ckpt_every=args.ckpt_every, resume=args.resume)
    return launch(args.np_procs, cmd, args.devices_per_proc,
                  args.coordinator, timeout=args.timeout,
                  handshake_timeout=args.handshake_timeout,
                  max_restarts=args.max_restarts)


if __name__ == "__main__":
    raise SystemExit(main())
