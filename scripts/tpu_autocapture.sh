#!/bin/bash
# Watch for tunnel recovery, then run the full round-3 device sequence
# unattended: compile bisect -> headline bench -> sweep capture.
# Logs to /tmp/tpu_autocapture.log; touches /tmp/tpu_capture_done when
# finished so an operator (or the session) can pick up tuning from there.
INTERVAL="${1:-60}"
DEADLINE="${2:-28800}"
cd "$(dirname "$0")/.."
start=$(date +%s)
log=/tmp/tpu_autocapture.log
while true; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE" ]; then
    echo "$(date -Is) GAVE UP" >> "$log"
    exit 1
  fi
  if timeout 90 python -c "
from cme213_tpu.core.platform import device_preflight
import jax, sys
sys.exit(0 if device_preflight(75) and jax.devices()[0].platform == 'tpu'
         else 1)" >/dev/null 2>&1; then
    echo "$(date -Is) TPU UP — starting capture" >> "$log"
    break
  fi
  sleep "$INTERVAL"
done

{
  echo "== bisect =="
  timeout 3600 python scripts/tpu_pipeline_bisect.py
  echo "== bench f32 =="
  timeout 5400 python bench.py 2>&1
  echo "== full capture =="
  timeout 14000 bash scripts/tpu_capture.sh bench_results
  echo "$(date -Is) capture complete"
} >> "$log" 2>&1
touch /tmp/tpu_capture_done
