"""Crash flight recorder — the black box for uncleanly dying processes.

The trace layer (``core/trace.py``) keeps an in-process event ring
(``CME213_TRACE_BUFFER``) and optionally streams to a JSONL sink; both
are great while the process lives, but a rank that dies uncleanly — the
exact scenario the supervision ladder (``dist/launch.py``) hardens
against — takes its in-memory ring with it, and a sink only helps when
one was configured.  This module is the always-available fallback: on an
unhandled exception, a fatal signal, or an explicit ``dump()`` it
atomically writes the last-N events, a metrics snapshot, the still-open
spans, and platform info to ``flight-<pid>-<ts>.json`` so every gang
failure is diagnosable from artifacts alone.

Usage::

    from cme213_tpu.core import flight
    flight.install()              # CLI entry points: always record
    flight.install_from_env()     # library paths: only when
                                  # CME213_FLIGHT_DIR is set

``install()`` chains ``sys.excepthook`` and registers handlers for the
fatal-ish signals a supervisor sends (SIGTERM, SIGQUIT, SIGABRT —
SIGKILL is uncatchable by definition, which is what the ``rankkill``
fault's direct ``dump()`` call covers).  Dumps land in
``CME213_FLIGHT_DIR`` when set, else the install-time directory, else
the current working directory.  Writes are tmp + ``os.replace`` so a
reader never sees a torn JSON file.  Rendering: ``python -m cme213_tpu
trace flight <dump>`` (``trace_cli.py``).
"""

from __future__ import annotations

import itertools
import json
import os
import platform as _platform
import signal
import sys
import threading
import time
import traceback

from . import metrics, trace

#: directory flight dumps are written to (also arms library-path dumps)
FLIGHT_DIR_ENV = "CME213_FLIGHT_DIR"

#: events retained in a dump (the tail of the trace ring)
DUMP_EVENTS = 512

#: signals that trigger a dump before the process dies (SIGKILL cannot be
#: caught; ``faults.maybe_kill_rank`` dumps explicitly instead)
FATAL_SIGNALS = ("SIGTERM", "SIGQUIT", "SIGABRT")

_LOCK = threading.Lock()
_INSTALLED = False
_DIR: str | None = None
_PREV_EXCEPTHOOK = None
_PLATFORM: dict | None = None
_DUMP_SEQ = itertools.count(1)
_DUMPING = False


def _platform_info() -> dict:
    """Cheap once-per-install platform facts (never imports jax — reads
    the version only if something else already loaded it)."""
    jax_mod = sys.modules.get("jax")
    return {
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
        "jax": getattr(jax_mod, "__version__", None),
        "argv": list(sys.argv),
    }


def installed() -> bool:
    return _INSTALLED


def _armed() -> bool:
    """Dumps happen when hooks were installed or the env var opts in."""
    return _INSTALLED or bool(os.environ.get(FLIGHT_DIR_ENV))


def _dump_dir() -> str:
    return os.environ.get(FLIGHT_DIR_ENV) or _DIR or os.getcwd()


def _open_spans(events: list[dict]) -> list[dict]:
    """span-begin records without a matching span-end — what the process
    was inside when it died."""
    open_by_id: dict = {}
    for e in events:
        if e.get("event") == "span-begin":
            open_by_id[e.get("id")] = e
        elif e.get("event") == "span-end":
            open_by_id.pop(e.get("id"), None)
    return list(open_by_id.values())


def dump(reason: str, exc: BaseException | None = None) -> str | None:
    """Write a flight dump now; returns its path.

    No-op (returns None) unless armed via ``install()``/
    ``install_from_env()`` or a set ``CME213_FLIGHT_DIR`` — library code
    can call this unconditionally on its failure paths.  Re-entrant calls
    (a dump failing inside a dump) are dropped rather than recursing.
    """
    global _DUMPING
    if not _armed():
        return None
    with _LOCK:
        if _DUMPING:
            return None
        _DUMPING = True
    try:
        # last health snapshot + open/last-failed forensics stage
        # (core/diag.py) — best-effort: a crash dump without them still
        # beats no dump
        try:
            from . import diag
            health = diag.last_health()
            forensics = diag.forensics_state()
        except Exception:  # noqa: BLE001
            health, forensics = None, None
        # last drift-budget snapshot (core/numerics.py) — same
        # best-effort contract as the diag imports above
        try:
            from . import numerics
            numeric = numerics.last_drift() or None
        except Exception:  # noqa: BLE001
            numeric = None
        events = trace.events()[-DUMP_EVENTS:]
        doc = {
            "flight": 1,
            "reason": reason,
            "t": round(time.time(), 6),
            "pid": os.getpid(),
            "rank": os.environ.get("JAX_PROCESS_ID"),
            "incarnation": os.environ.get("CME213_INCARNATION", "0"),
            "platform": _PLATFORM or _platform_info(),
            "traceback": ("".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)) if exc else None),
            "open_spans": _open_spans(events),
            "health": health,
            "forensics": forensics,
            "numerics": numeric,
            "events": events,
            "metrics": metrics.snapshot(),
        }
        out_dir = _dump_dir()
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir,
            f"flight-{os.getpid()}-{int(time.time() * 1000)}"
            f"-{next(_DUMP_SEQ)}.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        trace.record_event("flight-dump", reason=reason, path=path,
                           events=len(events))
        trace.flush_sink()
        return path
    except Exception:
        return None  # the recorder must never mask the original failure
    finally:
        with _LOCK:
            _DUMPING = False


def _excepthook(exc_type, exc, tb):
    dump("unhandled-exception", exc=exc)
    hook = _PREV_EXCEPTHOOK or sys.__excepthook__
    hook(exc_type, exc, tb)


def _signal_handler(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    dump(f"signal:{name}")
    # die with the signal's own semantics (exit status, core dump, the
    # supervisor's SIGKILL escalation) rather than swallowing it
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install(dir: str | None = None) -> None:
    """Arm the recorder: chain ``sys.excepthook`` and register fatal
    signal handlers.  Idempotent; safe from any thread (signal handlers
    are skipped off the main thread — the excepthook still works)."""
    global _INSTALLED, _DIR, _PREV_EXCEPTHOOK, _PLATFORM
    with _LOCK:
        if dir:
            _DIR = dir
        if _INSTALLED:
            return
        _INSTALLED = True
        _PLATFORM = _platform_info()
        _PREV_EXCEPTHOOK = sys.excepthook
    sys.excepthook = _excepthook
    for sig_name in FATAL_SIGNALS:
        sig = getattr(signal, sig_name, None)
        if sig is None:
            continue
        try:
            existing = signal.getsignal(sig)
            # don't stomp an application handler; default/ignore is ours
            if existing in (signal.SIG_DFL, signal.SIG_IGN, None):
                signal.signal(sig, _signal_handler)
        except (ValueError, OSError):
            pass  # non-main thread or unsupported signal


def install_from_env() -> bool:
    """``install()`` only when ``CME213_FLIGHT_DIR`` is set — the opt-in
    for library paths (serving loop, checkpointed solves) where an
    unconditional excepthook swap would surprise embedders."""
    if os.environ.get(FLIGHT_DIR_ENV):
        install()
        return True
    return False


def _uninstall_for_tests() -> None:
    """Reset module state (tests only — does not restore signal
    dispositions)."""
    global _INSTALLED, _DIR, _PREV_EXCEPTHOOK, _PLATFORM
    with _LOCK:
        if _INSTALLED and _PREV_EXCEPTHOOK is not None:
            sys.excepthook = _PREV_EXCEPTHOOK
        _INSTALLED = False
        _DIR = None
        _PREV_EXCEPTHOOK = None
        _PLATFORM = None
