"""Shift-cipher workload driver (reference hw1).

Full pipeline of ``hw/hw1/programming/cipher.cu:127-282``: load (or
synthesize) a text corpus, replicate ×16 so the device has enough work, run
the host golden and the three device variants (per-byte, 4-byte-packed,
8-byte-packed — strategy P2), byte-compare each against the golden, and
report per-phase timings + effective bandwidths.

The default corpus is the shipped 1.25 MB English-like text
(``examples/corpus.txt``, see ``apps/corpus.py``) — the same scale as the
reference's public-domain novel input (``hw/hw1/programming/mobydick.txt``,
1.2 MB), which this environment can't fetch and won't copy.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core import PhaseTimer, bandwidth_gbs
from ..ops import shift_cipher, shift_cipher_packed
from ..verify import check_exact, golden

_WORD_CHARS = np.frombuffer(b"etaoinshrdlucmfwypvbgkjqxz", dtype=np.uint8)
_WORD_FREQ = np.array([12.7, 9.1, 8.2, 7.5, 7.0, 6.7, 6.3, 6.1, 6.0, 4.3,
                       4.0, 2.8, 2.8, 2.4, 2.2, 2.4, 2.0, 1.9, 1.0, 1.5,
                       2.0, 0.8, 0.15, 0.1, 0.15, 0.07])
_WORD_FREQ = _WORD_FREQ / _WORD_FREQ.sum()


def make_corpus(length: int = 1 << 20, seed: int = 0) -> np.ndarray:
    """Deterministic letter-frequency byte soup (letters, spaces, newlines).

    Kept for cheap in-memory test inputs; real workloads use the shipped
    word-level corpus (``apps/corpus.py``), whose digraph/IOC statistics
    are English-like, not just its unigrams.
    """
    rng = np.random.default_rng(seed)
    letters = rng.choice(_WORD_CHARS, size=length, p=_WORD_FREQ)
    # sprinkle spaces/newlines at word-ish intervals
    spaces = rng.random(length) < 0.18
    letters[spaces] = ord(" ")
    letters[:: 4096] = ord("\n")
    return letters.astype(np.uint8)


def run_cipher(text: np.ndarray | None = None, shift: int = 17,
               replicate: int = 16, timer: PhaseTimer | None = None,
               out_path: str | None = None) -> bool:
    """Returns True iff all device variants byte-match the host golden.
    With ``out_path``, writes the enciphered bytes (un-replicated prefix) —
    the ``mobydick_enciphered.txt`` artifact (cipher.cu:262-275)."""
    timer = timer or PhaseTimer(verbose=True)
    if text is None:
        from .corpus import load_corpus

        text = load_corpus()
    # replicate ×16 "otherwise everything happens too quickly"
    # (cipher.cu:148-159)
    data = np.tile(text, replicate)
    n = data.size

    with timer.phase("host shift cypher"):
        ref = golden.host_shift_cipher(data, shift)

    with timer.phase("copy data to device") as ph:
        dev = jnp.asarray(data)
        ph.block(dev)

    ok = True
    variants = [
        ("gpu shift cypher", lambda d: shift_cipher(d, shift)),
        ("gpu shift cypher uint", lambda d: shift_cipher_packed(d, shift, 4)),
        ("gpu shift cypher uint2", lambda d: shift_cipher_packed(d, shift, 8)),
    ]
    for name, fn in variants:
        fn(dev).block_until_ready()  # compile outside the timed region
        with timer.phase(name) as ph:
            out = fn(dev)
            ph.block(out)
        ms = timer.last_ms(name)
        # 1 read + 1 write per byte (the reference's bandwidth accounting)
        print(f"{name}: {bandwidth_gbs(2 * n, ms):.2f} GB/s")
        with timer.phase("copy from device") as ph:
            host = np.asarray(out)
        res = check_exact(ref, host, name)
        if not res:
            print(f"Output of TPU {name} version and host version didn't match!")
            print(res.message)
            ok = False
    if ok and out_path is not None:
        ref[:text.size].tofile(out_path)
    return ok


def main(argv: list[str]) -> int:
    """CLI of the reference driver (cipher.cu:127-160): ``[input.txt
    [shift]]`` — loads the text (falling back to a synthetic corpus),
    replicates x16, runs host golden + all device variants, and writes
    ``<input>_enciphered.txt``."""
    text, out_path = None, None
    shift = 17
    if len(argv) > 1:
        try:
            text = np.fromfile(argv[1], dtype=np.uint8)
        except OSError as e:
            print(f"error: {e}")
            return 2
        base = argv[1].rsplit(".", 1)[0]
        out_path = f"{base}_enciphered.txt"
    if len(argv) > 2:
        shift = int(argv[2])
    ok = run_cipher(text=text, shift=shift, out_path=out_path)
    if out_path and ok:
        print(f"wrote {out_path}")
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv))
