#!/bin/bash
# Unattended device-capture loop for a round: wait for the tunnel, run
# compile bisect -> headline bench -> sweep capture, and — because the
# tunnel drops mid-sequence (round-3: first child preflight died after the
# watcher's own preflight passed) — RETRY the whole sequence until the
# headline bench lands a real number AND the sweep capture finishes,
# instead of giving up after one shot.
#
#   bash scripts/tpu_autocapture.sh [poll_interval_s] [deadline_s]
#
# Logs to /tmp/tpu_autocapture.log; touches /tmp/tpu_capture_done on
# success so an operator (or the session) can pick up tuning from there.
INTERVAL="${1:-60}"
DEADLINE="${2:-28800}"
cd "$(dirname "$0")/.."
. scripts/capture_lib.sh
start=$(date +%s)
log=/tmp/tpu_autocapture.log
bisected=0

up() {
  timeout 90 python -c "
from cme213_tpu.core.platform import device_preflight
import jax, sys
sys.exit(0 if device_preflight(75) and jax.devices()[0].platform == 'tpu'
         else 1)" >/dev/null 2>&1
}

while true; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE" ]; then
    echo "$(date -Is) GAVE UP" >> "$log"
    exit 1
  fi
  if ! up; then
    sleep "$INTERVAL"
    continue
  fi
  echo "$(date -Is) TPU UP — starting capture attempt" >> "$log"
  # gate: ONE kernel measurement (bench.py child mode), not the full
  # 10-kernel race — the capture runs the real f32 bench itself, and a
  # short window shouldn't be spent proving the device twice
  echo "== gate (single-kernel measurement) ==" >> "$log"
  timeout 900 python bench.py --run-measurement --kernel=xla \
    > /tmp/tpu_gate_last.json 2>> "$log"
  cat /tmp/tpu_gate_last.json >> "$log"
  if grep -q '"ok": true' /tmp/tpu_gate_last.json; then
    mkdir -p bench_results
    echo "== full capture ==" >> "$log"
    if SKIP_F32=1 timeout 14000 bash scripts/tpu_capture.sh bench_results \
        >> "$log" 2>&1; then
      # the bisect deliberately offers the compiler over-budget cells, so
      # it runs LAST — a crash-wedged tunnel then costs nothing already
      # captured (headline + sweeps are on disk at this point)
      if [ "$bisected" = 0 ]; then
        echo "== bisect (diagnostics) ==" >> "$log"
        timeout 3600 python scripts/tpu_pipeline_bisect.py \
          > /tmp/tpu_bisect_last.txt 2>&1
        cat /tmp/tpu_bisect_last.txt >> "$log"
        # the matrix is evidence only if no row failed for a DEVICE
        # reason (a drop mid-matrix leaves spurious FAIL rows); sticky
        # compile failures are what the bisect is for
        if grep -qE ": (OK|FAIL)" /tmp/tpu_bisect_last.txt \
           && ! grep -E ": FAIL" /tmp/tpu_bisect_last.txt \
                | grep -qE "$DEVICE_ERR"; then
          bisected=1
        fi
      fi
      echo "$(date -Is) capture complete" >> "$log"
      touch /tmp/tpu_capture_done
      exit 0
    fi
    echo "$(date -Is) capture incomplete — re-waiting" >> "$log"
  else
    echo "$(date -Is) gate measurement failed — re-waiting" >> "$log"
  fi
  sleep "$INTERVAL"
done
