"""Pallas VMEM-tiled heat stencil — the hand-tuned kernel path.

TPU-native analog of the reference's shared-memory stencil kernel
(``gpuShared``, ``hw/hw2/programming/2dHeat.cu:466-515``): where 128×4 CUDA
threads cooperatively staged a 128×32 halo tile into ``__shared__`` and each
thread emitted multiple rows, here each Pallas grid step DMAs a
``(tile_y + halo, gx)`` row band from HBM into a VMEM scratch buffer
(the explicit analog of the cooperative staging) and computes a full-width
output tile.

Mosaic (TPU) lowering constraints shape the design:

- HBM→VMEM copies need the lane (last) dimension to be 128-aligned, so the
  callers pad the grid's x-extent to a multiple of 128 and the kernels work
  full-width; the padding columns are dead weight the valid-interior masks
  ignore.
- Sub-array slices carry (sublane, lane) offset layouts that many Mosaic
  ops refuse to combine, so the stencil's ±border shifts are expressed as
  ``pltpu.roll`` (circular lane/sublane rotations) of the whole band, with
  the wrapped edges masked off / discarded — the roll-and-mask formulation
  of the same shifted-slice sum as the XLA path (`ops/stencil.py`), and the
  results are bitwise comparable.

``run_heat_multistep`` additionally fuses k timesteps per HBM pass
(temporal blocking): each band carries k·border extra halo rows and applies
the stencil k times on-chip, re-imposing the Dirichlet bands between
sub-steps; the validity margin shrinks by ``border`` rows per sub-step,
exactly covering the extra halo.  HBM traffic per k steps ≈ one read + one
write of the grid vs k of each — the optimization the 48 KB shared
memories of the reference's era couldn't hold enough halo for.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import BORDER_FOR_ORDER, STENCIL_COEFFS

LANE = 128


def _pad_lanes(gx: int) -> int:
    return -(-gx // LANE) * LANE


def _roll(u, shift: int, axis: int, interpret: bool):
    if shift == 0:
        return u
    if interpret:  # pltpu.roll has no interpret-mode rule; jnp.roll matches
        return jnp.roll(u, shift, axis)
    return pltpu.roll(u, shift % u.shape[axis], axis)


def _stage_band(u_hbm, bands, sems, tile_y: int, H: int):
    """Double-buffered cooperative band staging, shared by both kernels:
    start the DMA for band i+1, wait for band i, return it (scratch
    persists across the sequentially-executed grid steps)."""
    i = pl.program_id(0)
    nblk = pl.num_programs(0)

    def get_dma(slot, blk):
        return pltpu.make_async_copy(
            u_hbm.at[pl.ds(blk * tile_y, H), :], bands.at[slot],
            sems.at[slot])

    @pl.when(i == 0)
    def _():
        get_dma(0, 0).start()

    @pl.when(i + 1 < nblk)
    def _():
        get_dma((i + 1) % 2, i + 1).start()

    get_dma(i % 2, i).wait()
    return bands[i % 2]


def _make_kernel(order: int, tile_y: int, xcfl: float, ycfl: float,
                 interpret: bool):
    b = BORDER_FOR_ORDER[order]
    coeffs = STENCIL_COEFFS[order]
    H = tile_y + 2 * b

    def kernel(u_hbm, out_ref, bands, sems):
        u = _stage_band(u_hbm, bands, sems, tile_y, H)
        dtype = u.dtype
        accx = jnp.zeros_like(u)
        accy = jnp.zeros_like(u)
        for k, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * _roll(u, b - k, 1, interpret)
            accy = accy + c * _roll(u, b - k, 0, interpret)
        new = (u + jnp.asarray(xcfl, dtype) * accx
               + jnp.asarray(ycfl, dtype) * accy)
        # output rows are band rows [b, b+tile_y): rotate up, take the top
        out_ref[:] = _roll(new, -b, 0, interpret)[:tile_y, :]

    return kernel


def _stencil_full(up: jnp.ndarray, order: int, xcfl: float, ycfl: float,
                  tile_y: int, interpret: bool) -> jnp.ndarray:
    """(ny, gxp) full-width new interior from lane-padded halo grid."""
    b = BORDER_FOR_ORDER[order]
    gy, gxp = up.shape
    ny = gy - 2 * b
    assert gxp % LANE == 0 and ny % tile_y == 0
    kernel = _make_kernel(order, tile_y, float(xcfl), float(ycfl), interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((ny, gxp), up.dtype),
        grid=(ny // tile_y,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile_y, gxp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, tile_y + 2 * b, gxp), up.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(up)


@partial(jax.jit,
         static_argnames=("order", "xcfl", "ycfl", "tile_y", "interpret"))
def stencil_interior_pallas(u: jnp.ndarray, order: int, xcfl: float,
                            ycfl: float, tile_y: int = 256,
                            interpret: bool = False) -> jnp.ndarray:
    """New interior (ny, nx) from halo grid (gy, gx), VMEM-tiled.

    ``ny`` must divide by ``tile_y``, ideally a multiple of 8 (drivers pick
    a divisor; see ``pick_tile``).  ``xcfl``/``ycfl`` must be concrete
    floats (they are baked into the kernel as constants).
    """
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    gxp = _pad_lanes(gx)
    up = jnp.pad(u, ((0, 0), (0, gxp - gx))) if gxp != gx else u
    out = _stencil_full(up, order, xcfl, ycfl, tile_y, interpret)
    return out[:, b:b + nx]


def pick_tile(ny: int, target: int = 256) -> int:
    """Largest divisor of ny not exceeding ``target``.

    Prefers multiples of 8 (the f32 sublane quantum: Mosaic wants
    8-aligned sublane extents), falling back to any divisor only when ny
    has no 8-aligned one.
    """
    t = min(target, ny)
    t -= t % 8
    while t >= 8 and ny % t:
        t -= 8
    if t >= 8:
        return t
    t = min(target, ny)
    while ny % t:
        t -= 1
    return t


def _make_multistep_kernel(order: int, k: int, tile_y: int, gy: int, gx: int,
                           bc: tuple[float, float, float, float],
                           xcfl: float, ycfl: float, interpret: bool):
    """k fused timesteps per HBM pass (temporal blocking)."""
    b = BORDER_FOR_ORDER[order]
    K = k * b
    coeffs = STENCIL_COEFFS[order]
    H = tile_y + 2 * K
    bc_top, bc_left, bc_bottom, bc_right = bc

    def kernel(u_hbm, out_ref, bands, sems):
        i = pl.program_id(0)
        u = _stage_band(u_hbm, bands, sems, tile_y, H)
        gxp = bands.shape[2]
        # global halo-grid row of band-local row l: hr = i*tile_y + l - (K-b)
        hr0 = i * tile_y - (K - b)
        rows = jax.lax.broadcasted_iota(jnp.int32, (H, gxp), 0) + hr0
        cols = jax.lax.broadcasted_iota(jnp.int32, (H, gxp), 1)

        dtype = u.dtype
        for _ in range(k):
            accx = jnp.zeros_like(u)
            accy = jnp.zeros_like(u)
            for kk, c in enumerate(coeffs):
                c = jnp.asarray(c, dtype)
                accx = accx + c * _roll(u, b - kk, 1, interpret)
                accy = accy + c * _roll(u, b - kk, 0, interpret)
            new = (u + jnp.asarray(xcfl, dtype) * accx
                   + jnp.asarray(ycfl, dtype) * accy)
            # band-edge cells hold roll-wrap garbage, but any cell within
            # s·b of the band edge is outside substep s's validity margin
            # anyway — only the Dirichlet bands need re-imposing
            # (bottom/top then left/right, the reference's band order)
            new = jnp.where(rows < b, jnp.asarray(bc_bottom, dtype), new)
            new = jnp.where(rows >= gy - b, jnp.asarray(bc_top, dtype), new)
            new = jnp.where(cols < b, jnp.asarray(bc_left, dtype), new)
            new = jnp.where(cols >= gx - b,
                            jnp.asarray(bc_right, dtype), new)
            u = new
        # output rows are band rows [K, K+tile_y)
        out_ref[:] = _roll(u, -K, 0, interpret)[:tile_y, :]

    return kernel


@partial(jax.jit,
         static_argnames=("order", "iters", "k", "xcfl", "ycfl", "bc",
                          "tile_y", "interpret"),
         donate_argnums=(0,))
def run_heat_multistep(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                       bc: tuple[float, float, float, float], k: int = 4,
                       tile_y: int = 128, interpret: bool = False):
    """Iterated solve with k timesteps fused per HBM pass.

    ``u`` is the (gy, gx) halo grid; ``bc`` = (top, left, bottom, right)
    Dirichlet values (as in ``SimParams.bc``).  ``iters`` must divide by
    ``k`` and ``ny`` by ``tile_y``.  Returns the full halo grid.
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    assert iters % k == 0, "iters must divide by k"
    assert ny % tile_y == 0, "ny must divide by tile_y"
    gxp = _pad_lanes(gx)
    bc_top, bc_left, bc_bottom, bc_right = bc

    kernel = _make_multistep_kernel(order, k, tile_y, gy, gx, bc,
                                    float(xcfl), float(ycfl), interpret)
    pad = K - b

    def call(padded):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((ny, gxp), u.dtype),
            grid=(ny // tile_y,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((tile_y, gxp), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, tile_y + 2 * K, gxp), u.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
        )(padded)

    # extend the halo grid with replicated BC rows so every tile's input
    # window is in-bounds with a static size (the replicas hold exactly the
    # values an infinite Dirichlet border would), and pad lanes to 128
    padded = u
    if gxp != gx:
        padded = jnp.pad(padded, ((0, 0), (0, gxp - gx)),
                         constant_values=bc_right)
    if pad:
        padded = jnp.concatenate([
            jnp.full((pad, gxp), jnp.asarray(bc_bottom, u.dtype)),
            padded,
            jnp.full((pad, gxp), jnp.asarray(bc_top, u.dtype)),
        ], axis=0)
        # left/right bands must extend through the replica rows too
        padded = padded.at[:pad, :b].set(jnp.asarray(bc_left, u.dtype))
        padded = padded.at[-pad:, :b].set(jnp.asarray(bc_left, u.dtype))
        padded = padded.at[:pad, gx - b:].set(jnp.asarray(bc_right, u.dtype))
        padded = padded.at[-pad:, gx - b:].set(jnp.asarray(bc_right, u.dtype))

    def body(_, p):
        # the kernel's BC masking keeps halo columns (and lane padding) at
        # their Dirichlet values, so the full-width band writes back whole
        return p.at[K:K + ny, :].set(call(p))

    padded = lax.fori_loop(0, iters // k, body, padded)
    return padded[pad:pad + gy, :gx] if pad else padded[:, :gx]


@partial(jax.jit,
         static_argnames=("order", "iters", "xcfl", "ycfl", "tile_y",
                          "interpret"),
         donate_argnums=(0,))
def run_heat_pallas(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                    tile_y: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Iterated solve using the Pallas stencil (functional ping-pong)."""
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    gxp = _pad_lanes(gx)
    up = jnp.pad(u, ((0, 0), (0, gxp - gx))) if gxp != gx else u

    def body(_, p):
        new = _stencil_full(p, order, xcfl, ycfl, tile_y, interpret)
        # only columns [b, b+nx) of the full-width tile are valid
        return p.at[b:b + ny, b:b + nx].set(new[:, b:b + nx])

    up = lax.fori_loop(0, iters, body, up)
    return up[:, :gx]
