"""Concurrent socket transport for the serving front end.

The batching server (``serve/server.py``) is deliberately synchronous:
``submit`` / ``step`` on one thread, deterministic under a virtual
clock.  This module puts sockets in front of it without giving up that
core: a threaded accept loop funnels many client connections into the
one server under a lock, and a background **batcher thread** drains the
queue — the caller-driven ``step()`` loop becomes one of two drive
modes:

- ``drive="caller"`` — nothing runs in the background; the owner calls
  :meth:`TransportServer.pump` to step the server and deliver results.
  Deterministic (virtual-clock friendly): every existing test pattern
  still works with sockets in front.
- ``drive="thread"`` — a daemon batcher thread wakes on every accepted
  request (the ``Server.on_submit`` waker) and steps until the queue is
  empty.  This is the live-serving mode the fleet replicas run.

**Two wire protocols share every port**, distinguished per-frame by the
first four bytes:

- **v2 (binary, default)** — ``serve/wire.py``'s zero-copy framing:
  fixed header (magic / version / frame type / request id / section
  count), JSON only for small metadata, arrays as raw sections written
  with ``sendmsg`` and read with ``recv_into``.  Requests are
  **pipelined**: many in flight per connection, responses matched by
  request id in whatever order batches complete.  Same-host clients can
  negotiate a shared-memory lane (``serve/shm.py``) via a control
  frame, with transparent socket fallback.
- **v1 (legacy)** — ``[4-byte big-endian length][UTF-8 JSON]`` with
  numpy as base64 ``{"__nd__": [dtype, shape, data]}`` triples, one
  request in flight per connection.  A v2 server still speaks it frame
  by frame (the ``transport.proto_v1`` counter exposes how much legacy
  traffic remains), so old clients and mixed fleets keep working.

Clients negotiate with a ``{"control": "hello", "proto": 2}`` frame;
a client whose hello dies mid-handshake reconnects in v1 mode.  Either
way :meth:`TransportClient.solve` returns a
:class:`~.request.SolveResult` that compares bitwise-equal to a serial
solve; v2 adds :meth:`~TransportClient.submit` /
:meth:`~TransportClient.result` pairs for pipelining from one thread.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
import time

import numpy as np

from . import wire
from ..core import metrics, trace
from ..core.faults import incarnation, maybe_kill_replica
from .request import FAILED, OK, SolveResult
from .server import Server

#: response safety net: a transport request that produces no result in
#: this many wall seconds fails with reason "transport-timeout" instead
#: of hanging its client connection forever
RESPONSE_TIMEOUT_S = 120.0

_LEN = struct.Struct(">I")


# ------------------------------------------------------------ v1 framing

def send_frame(sock: socket.socket, doc: dict) -> None:
    body = json.dumps(doc).encode("utf-8")
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> dict | None:
    """One v1 frame, or None on a clean EOF at a frame boundary."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    body = _recv_exact(sock, length)
    if body is None:
        raise ConnectionError("EOF mid-frame")
    return json.loads(body.decode("utf-8"))


# ------------------------------------------------------------ wire codec
#
# The document codecs live in serve/wire.py, shared between protocols
# via a pluggable array encoder; these v1-shaped wrappers keep the
# PR 15 surface (and its base64 self-describing docs) intact.

def _nd_encode(arr: np.ndarray) -> dict:
    return wire.nd_b64(arr)


def _nd_decode(doc: dict) -> np.ndarray:
    return wire.nd_b64_decode(doc)


def encode_value(value):
    """JSON-encode a result value: numpy/jax arrays become bitwise
    base64 triples; containers recurse; scalars pass through."""
    return wire.encode_value(value, wire.nd_b64)


def decode_value(doc):
    return wire.decode_value(doc)


def encode_payload(op: str, payload) -> dict:
    """Per-op payload serialization (the inverse of
    :func:`decode_payload`); ops are the ``serve.workloads.ADAPTERS``
    keys."""
    return wire.encode_payload(op, payload, wire.nd_b64)


def decode_payload(op: str, doc: dict):
    return wire.decode_payload(op, doc)


_RESULT_FIELDS = wire.RESULT_FIELDS


def encode_result(res: SolveResult, **extra) -> dict:
    return wire.encode_result(res, wire.nd_b64, **extra)


def decode_result(doc: dict) -> SolveResult:
    return wire.decode_result(doc)


def parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _observe_codec(direction: str, rid, op, ms: float, nbytes: int) -> None:
    """One encode/decode observation: histogram + span tag event (the
    loadgen ``transport`` subsection and ``trace summary`` both read
    these).  The histogram sees every request; the trace event is
    **sampled** past the first 64 rids of a connection (1 in 16 after
    that) — at wire speed the event record itself would be a measurable
    share of the request, and rids restart per connection so short runs
    always trace fully."""
    metrics.histogram(f"serve.request.{direction}_ms").observe(ms)
    if isinstance(rid, int) and rid > 64 and rid % 16:
        return
    if direction == "encode":
        trace.record_event("request-serialized", rid=rid, op=op,
                           ms=round(ms, 4), nbytes=int(nbytes))
    else:
        trace.record_event("request-deserialized", rid=rid, op=op,
                           ms=round(ms, 4), nbytes=int(nbytes))


# ------------------------------------------------------------ connections

class _Conn:
    """One accepted (or dialed) socket: a write lock so pipelined
    responses interleave whole frames only, plus the optionally
    negotiated shared-memory lane."""

    __slots__ = ("sock", "wlock", "lane", "alive")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.wlock = threading.Lock()
        self.lane = None          # shm.ShmLane once negotiated
        self.alive = True

    def send_v1(self, doc: dict) -> None:
        with self.wlock:
            send_frame(self.sock, doc)

    def send_v2(self, ftype: int, rid: int, meta: dict,
                sections=()) -> None:
        self.send_packed(wire.pack_frame(ftype, rid, meta, sections), rid)

    def send_packed(self, bufs: list, rid: int = 0) -> None:
        """Send a packed frame — through the shm lane when negotiated
        and a slot credit is free, else the socket."""
        with self.wlock:
            if self.lane is not None:
                bell = self.lane.tx.try_send(bufs)
                if bell is not None:
                    wire.send_frame_v2(self.sock, wire.FT_SHM, rid, bell)
                    return
            wire.send_buffers(self.sock, bufs)

    def close(self) -> None:
        self.alive = False
        try:
            # shutdown first: close() alone does not send the FIN while
            # a reader thread is blocked in recv on this fd (the
            # in-flight syscall keeps the kernel socket alive), so the
            # peer would never see the EOF
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self.lane is not None:
            try:
                self.lane.close()
            except Exception:
                pass
            self.lane = None


# ------------------------------------------------------------ servers

class FrameServer:
    """Threaded accept loop speaking both wire protocols (sniffed per
    frame); subclasses implement :meth:`handle` (v1: one request doc ->
    one response doc, may block) and :meth:`handle_v2` (pipelined: must
    not block the reader), and optionally extend :meth:`control`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[_Conn] = set()
        self._conns_mu = threading.Lock()

    # -- lifecycle

    def start(self) -> "FrameServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._sock.settimeout(0.2)
        t = threading.Thread(target=self._accept_loop,
                             name="transport-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def close(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # sever live connections too: their reader threads exit and
        # pipelined clients see the EOF immediately (the same signal a
        # SIGKILLed replica's clients get)
        with self._conns_mu:
            conns = list(self._conns)
        for c in conns:
            c.close()

    # -- plumbing

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="transport-conn", daemon=True)
            t.start()

    def _serve_conn(self, sock: socket.socket) -> None:
        conn = _Conn(sock)
        with self._conns_mu:
            self._conns.add(conn)
        try:
            with sock:
                rd = wire.BufReader(sock)
                while not self._stop.is_set():
                    try:
                        if not rd.pending():
                            self._flush(conn)   # before we block reading
                        first4 = rd.first4()
                        if first4 is None:
                            return
                        if first4[:1] == wire.MAGIC[:1]:
                            self._serve_v2_frame(conn, rd, first4)
                        else:
                            self._serve_v1_frame(conn, rd, first4)
                    except (ConnectionError, OSError, ValueError):
                        return
        finally:
            with self._conns_mu:
                self._conns.discard(conn)
            conn.close()

    def _serve_v1_frame(self, conn: _Conn, rd: "wire.BufReader",
                        head: bytes) -> None:
        (length,) = _LEN.unpack(head)
        doc = json.loads(rd.recv_exact(length).decode("utf-8"))
        metrics.counter("transport.proto_v1").inc()
        try:
            if "control" in doc:
                resp = self.control(doc)
            else:
                resp = self.handle(doc)
        except Exception as e:       # noqa: BLE001 - wire boundary
            resp = {"status": FAILED, "reason": "transport",
                    "error": f"{type(e).__name__}: {e}"}
        conn.send_v1(resp)

    def _serve_v2_frame(self, conn: _Conn, rd: "wire.BufReader",
                        first4: bytes) -> None:
        t0 = time.perf_counter()
        ftype, rid, meta, sections = wire.read_frame_rest(rd, first4)
        if ftype == wire.FT_SHM:
            if conn.lane is None:
                raise wire.WireError("shm doorbell without a lane")
            slot = int(meta["slot"])
            ftype, rid, meta, sections = conn.lane.read(slot,
                                                        int(meta["len"]))
            # the slot is parsed out; return the writer's credit
            conn.send_v2(wire.FT_CONTROL, 0,
                         {"control": "shm-ack", "slot": slot})
        read_s = time.perf_counter() - t0
        if ftype == wire.FT_CONTROL:
            self._control_v2(conn, rid, meta)
        elif ftype == wire.FT_REQUEST:
            try:
                self.handle_v2(conn, rid, meta, sections, read_s)
            except Exception as e:   # noqa: BLE001 - wire boundary
                conn.send_v2(wire.FT_RESPONSE, rid,
                             {"status": FAILED, "reason": "transport",
                              "error": f"{type(e).__name__}: {e}"})
        else:
            raise wire.WireError(f"unexpected frame type {ftype}")

    def _control_v2(self, conn: _Conn, rid: int, meta: dict) -> None:
        kind = meta.get("control")
        if kind == "shm-ack":
            if conn.lane is not None:
                conn.lane.tx.ack(int(meta["slot"]))
            return                   # credit return: no reply
        if kind == "shm-setup":
            from . import shm as shm_mod
            try:
                lane = shm_mod.attach_server_lane(meta)
                resp = {"ok": True, "slots": lane.tx.ring.slots}
            except Exception as e:   # noqa: BLE001 - stay on sockets
                lane = None
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
            # reply over the socket FIRST: the lane goes live only after
            # the client has heard the answer (it is not reading slots yet)
            conn.send_v2(wire.FT_CONTROL_REPLY, rid, resp)
            conn.lane = lane
            return
        resp = self.control(meta)
        conn.send_v2(wire.FT_CONTROL_REPLY, rid, resp)

    # -- overridables

    def _flush(self, conn: _Conn) -> None:
        """Called by the connection loop whenever its read buffer runs
        dry (i.e. just before it might block): subclasses that batch
        their replies write them out here."""

    def handle(self, doc: dict) -> dict:
        raise NotImplementedError

    def handle_v2(self, conn: _Conn, rid: int, meta: dict,
                  sections: list, read_s: float = 0.0) -> None:
        raise NotImplementedError

    def control(self, doc: dict) -> dict:
        kind = doc.get("control")
        if kind == "ping":
            # "t" is this process's wall clock at reply time: the
            # client's ClockSync turns ping round trips into a per-peer
            # offset ± error bound for waterfall clock alignment
            return {"ok": True, "pid": os.getpid(), "t": time.time(),
                    "rank": os.environ.get("JAX_PROCESS_ID", "main"),
                    "incarnation": incarnation()}
        if kind == "hello":
            # protocol negotiation: we always speak v2; echo it so the
            # client pipelines, and ping fields ride along for free
            return {"ok": True, "proto": wire.VERSION, "pid": os.getpid(),
                    "t": time.time(),
                    "rank": os.environ.get("JAX_PROCESS_ID", "main"),
                    "incarnation": incarnation()}
        if kind == "stats":
            return {"ok": True, "stats": self.stats()}
        return {"ok": False, "error": f"unknown control {kind!r}"}

    def stats(self) -> dict:
        return {}


class TransportServer(FrameServer):
    """The socket front end over one local :class:`~.server.Server`.

    ``drive="thread"`` starts a background batcher that wakes on every
    accepted request and steps the server until its queue is empty
    (calling the ``replica-kill`` fault guard once per non-empty sweep
    when ``kill_guard`` is set — the fleet replica's deterministic
    mid-batch death point).  ``drive="caller"`` leaves stepping to the
    owner via :meth:`pump`.

    v1 connections block their reader thread per request (one in
    flight); v2 connections register ``(conn, wire rid)`` with the
    request and the batcher writes responses back in completion order —
    arbitrarily many in flight per connection.
    """

    def __init__(self, server: Server, host: str = "127.0.0.1",
                 port: int = 0, drive: str = "thread",
                 poll_interval_s: float = 0.05, kill_guard: bool = False):
        if drive not in ("thread", "caller"):
            raise ValueError(f"drive must be thread|caller, got {drive!r}")
        super().__init__(host, port)
        self.server = server
        self.drive = drive
        self.kill_guard = kill_guard
        self._poll_interval_s = poll_interval_s
        self._mu = threading.Lock()          # guards the synchronous core
        self._wake = threading.Event()
        # rid -> [Event, result] (v1 blocking) | (_Conn, wire_rid) (v2)
        self._pending: dict[int, object] = {}
        self.batches = 0                     # batcher sweeps that executed
        server.on_submit = self._wake.set

    def attach_jobs(self, executor) -> "TransportServer":
        """Wire a ``serve.jobs.JobExecutor`` into this transport: the
        batcher (or :meth:`pump`) ticks one job epoch per idle gap, and
        ``job-*`` control frames are served against its store."""
        executor.server = self.server
        self.server.jobs = executor
        return self

    def start(self) -> "TransportServer":
        super().start()
        if self.drive == "thread":
            t = threading.Thread(target=self._batch_loop,
                                 name="transport-batcher", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    # -- request paths

    def handle(self, doc: dict) -> dict:
        """v1: decode, submit, block this connection thread on delivery."""
        op = doc["op"]
        t0 = time.perf_counter()
        payload = decode_payload(op, doc["payload"])
        dec_ms = (time.perf_counter() - t0) * 1e3
        waiter = None
        with self._mu:
            out = self.server.submit(
                op, payload, deadline_ms=doc.get("deadline_ms"),
                tenant=doc.get("tenant", "default"),
                trace_id=doc.get("trace_id"),
                parent_span=doc.get("parent_span"))
            if isinstance(out, SolveResult):         # shed at the door
                return encode_result(out)
            waiter = [threading.Event(), None]
            self._pending[out] = waiter
        _observe_codec("decode", out, op, dec_ms, 0)
        if not waiter[0].wait(RESPONSE_TIMEOUT_S):
            with self._mu:
                self._pending.pop(out, None)
            return {"rid": out, "op": op, "status": FAILED,
                    "reason": "transport-timeout", "tenant":
                    doc.get("tenant", "default")}
        t0 = time.perf_counter()
        resp = encode_result(waiter[1])
        _observe_codec("encode", out, op,
                       (time.perf_counter() - t0) * 1e3, 0)
        return resp

    def handle_v2(self, conn: _Conn, rid: int, meta: dict,
                  sections: list, read_s: float = 0.0) -> None:
        """v2: decode, submit, register — never blocks the reader."""
        op = meta["op"]
        t0 = time.perf_counter()
        payload = wire.decode_payload(op, meta["payload"], sections)
        dec_ms = (time.perf_counter() - t0 + read_s) * 1e3
        nbytes = sum(s.nbytes for s in sections)
        shed = None
        with self._mu:
            out = self.server.submit(
                op, payload, deadline_ms=meta.get("deadline_ms"),
                tenant=meta.get("tenant", "default"),
                trace_id=meta.get("trace_id"),
                parent_span=meta.get("parent_span"))
            if isinstance(out, SolveResult):
                shed = out
            else:
                self._pending[out] = (conn, rid)
        _observe_codec("decode", rid if shed else out, op, dec_ms, nbytes)
        if shed is not None:
            self._reply_v2(conn, rid, shed)

    def _encode_reply(self, wire_rid: int, res: SolveResult) -> list:
        t0 = time.perf_counter()
        sw = wire.SectionWriter()
        meta = wire.encode_result(res, sw)
        bufs = wire.pack_frame(wire.FT_RESPONSE, wire_rid, meta, sw.arrays)
        _observe_codec("encode", res.rid, res.op,
                       (time.perf_counter() - t0) * 1e3,
                       sum(np.asarray(a).nbytes for a in sw.arrays))
        return bufs

    def _reply_v2(self, conn: _Conn, wire_rid: int,
                  res: SolveResult) -> None:
        try:
            conn.send_packed(self._encode_reply(wire_rid, res), wire_rid)
        except (ConnectionError, OSError):
            pass                     # client went away; result is dropped

    # -- drive modes

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._poll_interval_s)
            self._wake.clear()
            self._sweep()
            self._job_tick()

    def _job_tick(self) -> None:
        """One long-job epoch in this idle gap (outside ``_mu``: the
        epoch runs while interactive submits keep landing, and the next
        ``_sweep`` drains them the moment the epoch yields — epoch
        boundaries ARE the preemption points).  Re-arms the wake event
        while job work remains so back-to-back idle gaps keep the job
        moving instead of waiting out the poll interval."""
        if self.server.jobs is None or self._stop.is_set():
            return
        try:
            if self.server.job_tick():
                self._wake.set()
        except Exception:             # noqa: BLE001 — never take down
            # the batcher thread; the executor already FAILed the job
            metrics.counter("jobs.tick_errors").inc()

    def _sweep(self) -> None:
        """Step until the queue is empty, delivering results."""
        while True:
            with self._mu:
                if not len(self.server.queue):
                    return
                if self.kill_guard:
                    maybe_kill_replica()
                results = self.server.step()
                self.batches += 1
                v2_out = self._deliver_locked(results)
            self._send_v2(v2_out)

    def pump(self) -> list[SolveResult]:
        """Caller-driven drive mode: one server step + delivery, then
        (with a job lane attached) one job epoch if the gap is idle."""
        with self._mu:
            results = self.server.step()
            v2_out = self._deliver_locked(results)
        self._send_v2(v2_out)
        self._job_tick()
        return results

    def _deliver_locked(self, results) -> list:
        """Match results to waiters; v2 sends happen outside the lock."""
        v2_out = []
        for res in results:
            waiter = self._pending.pop(res.rid, None)
            if waiter is None:
                continue
            if isinstance(waiter, list):      # v1: wake the conn thread
                waiter[1] = res
                waiter[0].set()
            else:                             # v2: write when unlocked
                v2_out.append((waiter, res))
        return v2_out

    def _send_v2(self, v2_out: list) -> None:
        """Deliver a sweep's responses: per connection, the whole
        batch's frames go out as ONE vectored write (a per-response
        ``sendmsg`` costs a syscall + a GIL bounce each — at batch 64
        that was most of the batcher's time).  Connections with a shm
        lane keep per-frame sends: each frame targets its own slot."""
        by_conn: dict = {}
        for (conn, wire_rid), res in v2_out:
            by_conn.setdefault(conn, []).append((wire_rid, res))
        for conn, items in by_conn.items():
            if conn.lane is not None or len(items) == 1:
                for wire_rid, res in items:
                    self._reply_v2(conn, wire_rid, res)
                continue
            bufs: list = []
            for wire_rid, res in items:
                bufs += self._encode_reply(wire_rid, res)
            try:
                with conn.wlock:
                    wire.send_buffers(conn.sock, bufs)
            except (ConnectionError, OSError):
                pass                 # client went away; results dropped

    def control(self, doc: dict) -> dict:
        kind = doc.get("control")
        if isinstance(kind, str) and kind.startswith("job-"):
            from . import jobs as jobs_mod

            if self.server.jobs is None:
                return {"ok": False,
                        "error": "no job lane on this server"}
            return jobs_mod.handle_control(self.server.jobs.store, doc)
        return super().control(doc)

    def stats(self) -> dict:
        with self._mu:
            out = {"queue_depth": len(self.server.queue),
                   "pending": len(self._pending),
                   "batches": self.batches,
                   "degraded": self.server.degraded}
        if self.server.jobs is not None:
            out["jobs"] = self.server.jobs.stats()
        return out


class StubSolveServer(FrameServer):
    """The rate gate's front end: the solve is a stub.  Every request is
    decoded, echoed, and re-encoded inline on its connection thread — no
    queue, no batcher, no device — so a closed-loop run against this
    server measures the transport alone: framing, codec, socket, and
    nothing else.  ``serve loadgen --transport self --stub-solve`` drives
    it for tier-1's CPU rate gate.  Replies for pipelined requests are
    batched per connection and flushed as one vectored write whenever
    the read buffer runs dry (:meth:`FrameServer._flush`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self.served = 0
        # conn -> pending reply buffers; only ever touched by that
        # connection's own reader thread, so no lock
        self._replies: dict = {}

    def handle(self, doc: dict) -> dict:
        op = doc["op"]
        payload = decode_payload(op, doc["payload"])
        self.served += 1
        return encode_result(SolveResult(
            -1, op, OK, value=payload, rung="stub-solve",
            tenant=doc.get("tenant", "default")))

    def handle_v2(self, conn: _Conn, rid: int, meta: dict,
                  sections: list, read_s: float = 0.0) -> None:
        op = meta["op"]
        t0 = time.perf_counter()
        payload = wire.decode_payload(op, meta["payload"], sections)
        _observe_codec("decode", rid, op,
                       (time.perf_counter() - t0 + read_s) * 1e3,
                       sum(s.nbytes for s in sections))
        t0 = time.perf_counter()
        sw = wire.SectionWriter()
        out = wire.encode_result(
            SolveResult(rid, op, OK, value=payload, rung="stub-solve",
                        tenant=meta.get("tenant", "default")), sw)
        bufs = wire.pack_frame(wire.FT_RESPONSE, rid, out, sw.arrays)
        _observe_codec("encode", rid, op,
                       (time.perf_counter() - t0) * 1e3,
                       sum(np.asarray(a).nbytes for a in sw.arrays))
        self.served += 1
        self._replies.setdefault(conn, []).extend(bufs)

    def _flush(self, conn: _Conn) -> None:
        bufs = self._replies.pop(conn, None)
        if bufs:
            with conn.wlock:
                wire.send_buffers(conn.sock, bufs)

    def stats(self) -> dict:
        return {"served": self.served}


# ------------------------------------------------------------ client

#: process-wide connection sequence: rids restart at 1 per connection,
#: so the client-hop tail-sampling keys need a connection discriminator
#: to stay unique within the process
_CONN_SEQ = itertools.count(1)


class TransportClient:
    """Transport client; v2 (default) pipelines many requests over one
    connection and supports a same-host shared-memory lane, v1 is the
    PR 15 blocking protocol (one request in flight, concurrency across
    connections).

    v2 surface: :meth:`submit` returns a request id immediately,
    :meth:`result` blocks for that id; :meth:`solve` is the pair.
    Constructed with ``on_response=`` the client runs in **callback
    mode** — responses are delivered to the callback on the receiver
    thread instead of parked for :meth:`result` (how a fleet sender
    pipelines to its replica), and ``on_error`` fires once when the
    connection dies with requests outstanding.
    """

    def __init__(self, addr: str, timeout_s: float = RESPONSE_TIMEOUT_S,
                 connect_timeout_s: float = 10.0, proto: int = 2,
                 shm: bool = False, shm_slots: int = 8,
                 shm_slot_bytes: int = 1 << 20,
                 on_response=None, on_error=None,
                 recv_thread: bool = True):
        host, port = parse_addr(addr)
        self.addr = addr
        self.timeout_s = timeout_s
        self._connect_timeout_s = connect_timeout_s
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout_s)
        self._mu = threading.Lock()
        self._rid = itertools.count(1)
        self._pending: dict[int, list] = {}   # rid -> [Event, payload]
        self._ctl: dict[int, list] = {}       # control rid -> [Event, doc]
        self._outbox: list = []               # corked (bufs, rid) pairs
        self._on_response = on_response
        self._on_error = on_error
        self._closing = False
        self._conn: _Conn | None = None
        self._sync = False
        self._conn_seq = next(_CONN_SEQ)
        self.clock_sync: trace.ClockSync | None = None
        self.proto = 1
        if proto >= 2:
            self._negotiate(host, port)
        if self.proto == 2:
            if shm:
                self._setup_shm(shm_slots, shm_slot_bytes)
            if recv_thread or on_response is not None or self.shm_active:
                self._sock.settimeout(None)
                t = threading.Thread(target=self._recv_loop,
                                     name="transport-client-recv",
                                     daemon=True)
                t.start()
                self._recv_thread = t
            else:
                # sync pipelined mode (``recv_thread=False``): the
                # calling thread parses response frames itself — no
                # receiver thread, no per-request Event/lock handoff.
                # Single-caller clients only (the closed-loop loadgen
                # hot path); shm lanes keep the threaded receiver for
                # doorbell handling.
                self._sync = True
                self._rd = wire.BufReader(self._sock)
                self._inflight: dict[int, dict] = {}
                self._parked: dict[int, tuple] = {}
                self._sock.settimeout(timeout_s)
        else:
            self._sock.settimeout(timeout_s)

    # -- handshake (synchronous, before the receiver thread exists)

    def _sync_control(self, doc: dict) -> dict:
        rid = next(self._rid)
        self._conn.send_v2(wire.FT_CONTROL, rid, doc)
        while True:
            first4 = wire.recv_exact(self._sock, 4)
            ftype, frid, meta, _ = wire.read_frame_rest(self._sock, first4)
            if ftype == wire.FT_CONTROL_REPLY and frid == rid:
                return meta

    def _negotiate(self, host: str, port: int) -> None:
        self._conn = _Conn(self._sock)
        try:
            hello = self._sync_control({"control": "hello",
                                        "proto": wire.VERSION})
            if hello.get("proto", 1) >= 2:
                self.proto = 2
                return
        except (ConnectionError, OSError, socket.timeout, ValueError):
            pass
        # a pre-v2 server choked on the binary hello: reconnect legacy
        self._conn = None
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = socket.create_connection(
            (host, port), timeout=self._connect_timeout_s)
        self.proto = 1

    def _setup_shm(self, slots: int, slot_bytes: int) -> None:
        from . import shm as shm_mod
        try:
            lane = shm_mod.create_client_lane(slots, slot_bytes)
        except Exception:
            return                    # no shm on this platform: sockets
        try:
            resp = self._sync_control({"control": "shm-setup",
                                       **shm_mod.setup_doc(lane)})
        except (ConnectionError, OSError, socket.timeout):
            lane.close()
            raise
        if resp.get("ok"):
            self._conn.lane = lane
        else:
            lane.close()

    # -- receiver (v2)

    def _recv_loop(self) -> None:
        err: Exception | None = None
        rd = wire.BufReader(self._sock)
        try:
            while True:
                first4 = rd.first4()
                if first4 is None:
                    break
                ftype, rid, meta, sections = wire.read_frame_rest(
                    rd, first4)
                if ftype == wire.FT_SHM:
                    lane = self._conn.lane
                    if lane is None:
                        raise wire.WireError("shm doorbell without a lane")
                    slot = int(meta["slot"])
                    ftype, rid, meta, sections = lane.read(
                        slot, int(meta["len"]))
                    self._conn.send_v2(wire.FT_CONTROL, 0,
                                       {"control": "shm-ack",
                                        "slot": slot})
                if ftype == wire.FT_CONTROL:
                    if meta.get("control") == "shm-ack" and self._conn.lane:
                        self._conn.lane.tx.ack(int(meta["slot"]))
                    continue
                if ftype == wire.FT_CONTROL_REPLY:
                    with self._mu:
                        waiter = self._ctl.pop(rid, None)
                    if waiter is not None:
                        waiter[1] = meta
                        waiter[0].set()
                    continue
                if ftype == wire.FT_RESPONSE:
                    self._dispatch_response(rid, meta, sections)
        except Exception as e:        # noqa: BLE001 - connection fate
            err = e
        finally:
            self._fail_all(err or
                           ConnectionError("server closed connection"))

    def _dispatch_response(self, rid: int, meta: dict,
                           sections: list) -> None:
        if self._on_response is not None:
            self._on_response(rid, meta, sections)
            return
        with self._mu:
            # left registered until result() consumes it — popping here
            # would race a result() call that hasn't looked yet
            waiter = self._pending.get(rid)
        if waiter is not None:
            waiter[1] = ("ok", meta, sections, time.perf_counter())
            waiter[0].set()

    def _fail_all(self, exc: Exception) -> None:
        with self._mu:
            dead = list(self._pending.values()) + list(self._ctl.values())
            self._pending.clear()
            self._ctl.clear()
            closing = self._closing
        for waiter in dead:
            waiter[1] = ("err", exc)
            waiter[0].set()
        if self._on_error is not None and not closing:
            self._on_error(exc)

    # -- sync pipelined mode (no receiver thread)

    def _read_sync(self) -> tuple[int, int, dict, list]:
        first4 = self._rd.first4()
        if first4 is None:
            raise ConnectionError("server closed connection")
        return wire.read_frame_rest(self._rd, first4)

    def _result_sync(self, rid: int) -> SolveResult:
        if self._outbox:
            self.flush()
        info = self._inflight.pop(rid, None)
        if info is None:
            raise KeyError(f"no outstanding request {rid}")
        hit = self._parked.pop(rid, None)
        while hit is None:
            ftype, frid, meta, sections = self._read_sync()
            if ftype != wire.FT_RESPONSE:
                continue              # control replies have their own loop
            if frid == rid:
                hit = (meta, sections, time.perf_counter())
            else:
                self._parked[frid] = (meta, sections,
                                      time.perf_counter())
        meta, sections, recv_s = hit
        t0 = time.perf_counter()
        res = wire.decode_result(meta, sections)
        hop = info.pop("_hop", None)
        info["decode_ms"] = (time.perf_counter() - t0) * 1e3
        if "sent_s" in info:
            info["rtt_ms"] = (recv_s - info.pop("sent_s")) * 1e3
        res.client = info
        self._finish_hop(hop, res=res)
        return res

    # -- request surface

    def next_rid(self) -> int:
        """Reserve a request id (callback-mode senders register their
        bookkeeping under it *before* the wire can answer)."""
        return next(self._rid)

    def submit_doc(self, doc: dict, sections=(),
                   rid: int | None = None) -> int:
        """Pipeline a pre-encoded request document (fleet forwarding:
        the payload's section refs pass through untouched)."""
        if self.proto != 2:
            raise RuntimeError("submit_doc requires a v2 connection")
        rid = next(self._rid) if rid is None else rid
        bufs = wire.pack_frame(wire.FT_REQUEST, rid, doc, sections)
        if self._sync:
            self._inflight[rid] = {}
        elif self._on_response is None:
            waiter = [threading.Event(), None, {}]
            with self._mu:
                self._pending[rid] = waiter
        self._conn.send_packed(bufs, rid)
        return rid

    def submit(self, op: str, payload, deadline_ms: float | None = None,
               tenant: str = "default",
               trace_id: str | None = None, flush: bool = True) -> int:
        """Encode and send one request; returns its id immediately.
        Many submits may be outstanding on this one connection.

        ``flush=False`` corks the frame instead of writing it: a burst
        of corked submits goes out as ONE vectored write on the next
        :meth:`flush` (or implicitly when :meth:`result` would block),
        which is how the closed-loop loadgen refills a deep pipeline
        window without paying one sendmsg per request."""
        if self.proto != 2:
            raise RuntimeError("submit/result pipelining requires v2; "
                               "use solve() on a v1 connection")
        t0 = time.perf_counter()
        rid = next(self._rid)
        tid = trace_id or trace.trace_id()
        # the client hop is the waterfall root: its id rides the wire as
        # ``parent_span`` so every downstream hop (route/dispatch/
        # replica/run) parents under it across process boundaries
        hop = trace.begin_span("serve.hop.client",
                               tail_key=f"c{self._conn_seq}.{rid}",
                               head_key=rid, rid=rid, op=op,
                               tenant=tenant, trace=tid)
        sw = wire.SectionWriter()
        doc = {"op": op, "payload": wire.encode_payload(op, payload, sw),
               "tenant": tenant, "trace_id": tid,
               "parent_span": hop.id}
        if deadline_ms is not None:
            doc["deadline_ms"] = deadline_ms
        bufs = wire.pack_frame(wire.FT_REQUEST, rid, doc, sw.arrays)
        enc_ms = (time.perf_counter() - t0) * 1e3
        info = {"encode_ms": enc_ms, "sent_s": time.perf_counter(),
                "_hop": hop}
        if self._sync:
            self._inflight[rid] = info
        else:
            waiter = [threading.Event(), None, info]
            with self._mu:
                self._pending[rid] = waiter
        if not flush:
            self._outbox.append((bufs, rid))
            return rid
        try:
            self._conn.send_packed(bufs, rid)
        except (ConnectionError, OSError):
            if self._sync:
                self._inflight.pop(rid, None)
            else:
                with self._mu:
                    self._pending.pop(rid, None)
            self._finish_hop(hop, error="ConnectionError")
            raise ConnectionError("server closed connection")
        return rid

    def flush(self) -> None:
        """Write every corked submit.  Socket path: all frames in one
        vectored send under one lock hold.  A negotiated shm lane keeps
        its per-frame slot/doorbell accounting instead."""
        if not self._outbox:
            return
        out, self._outbox = self._outbox, []
        try:
            if self._conn.lane is not None:
                for bufs, rid in out:
                    self._conn.send_packed(bufs, rid)
                return
            flat = [b for bufs, _ in out for b in bufs]
            if self._sync:
                self._flush_sync(flat)
                return
            with self._conn.wlock:
                wire.send_buffers(self._conn.sock, flat)
        except (ConnectionError, OSError):
            raise ConnectionError("server closed connection")

    def _flush_sync(self, bufs: list) -> None:
        """Deadlock-proof corked write for sync mode: with no receiver
        thread, a blocking send of a deep window can stall against the
        peer's own blocked response writes (both socket buffers full,
        neither side reading).  Send non-blocking and *drain* response
        frames into the parked set whenever the send buffer is full —
        consuming the response stream is what lets the peer resume
        reading our requests."""
        import select

        sock = self._conn.sock
        views = [v if isinstance(v, memoryview) else memoryview(v)
                 for v in bufs]
        views = [v for v in views if len(v)]
        sock.settimeout(0)            # non-blocking while flushing
        try:
            while views:
                readable, writable, _ = select.select(
                    [sock], [sock], [], self.timeout_s)
                if not readable and not writable:
                    raise TimeoutError("flush stalled")
                if readable and not writable:
                    sock.settimeout(self.timeout_s)
                    try:
                        ftype, frid, meta, sections = self._read_sync()
                    finally:
                        sock.settimeout(0)
                    if ftype == wire.FT_RESPONSE:
                        self._parked[frid] = (meta, sections,
                                              time.perf_counter())
                    continue
                try:
                    sent = sock.sendmsg(views[:512])
                except (BlockingIOError, InterruptedError):
                    continue
                while sent:
                    if sent >= len(views[0]):
                        sent -= len(views[0])
                        views.pop(0)
                    else:
                        views[0] = views[0][sent:]
                        sent = 0
        finally:
            sock.settimeout(self.timeout_s)

    def result(self, rid: int,
               timeout_s: float | None = None) -> SolveResult:
        """Block for one submitted request's result (any order)."""
        if self._sync:
            return self._result_sync(rid)
        if self._outbox:
            self.flush()     # corked submits must hit the wire first
        with self._mu:
            waiter = self._pending.get(rid)
        if waiter is None:
            raise KeyError(f"no outstanding request {rid}")
        ok = waiter[0].wait(self.timeout_s if timeout_s is None
                            else timeout_s)
        with self._mu:
            self._pending.pop(rid, None)
        if not ok:
            self._finish_hop(waiter[2].pop("_hop", None),
                             error="TimeoutError")
            raise TimeoutError(f"no response for request {rid}")
        kind = waiter[1][0]
        if kind == "err":
            self._finish_hop(waiter[2].pop("_hop", None),
                             error=type(waiter[1][1]).__name__)
            raise waiter[1][1]
        _, meta, sections, recv_s = waiter[1]
        t0 = time.perf_counter()
        res = wire.decode_result(meta, sections)
        info = dict(waiter[2])
        hop = info.pop("_hop", None)
        info["decode_ms"] = (time.perf_counter() - t0) * 1e3
        if "sent_s" in info:
            info["rtt_ms"] = (recv_s - info.pop("sent_s")) * 1e3
        res.client = info            # transport-side attribution
        self._finish_hop(hop, res=res)
        return res

    def _finish_hop(self, hop, res: SolveResult | None = None,
                    error: str | None = None) -> None:
        """End a ``serve.hop.client`` span and make its tail-sampling
        call: the client is the last hop to see the request, so the
        end-to-end keep/drop verdict (slow / shed / failed / requeued)
        lands here."""
        if hop is None:
            return
        if error is not None:
            ms, status, requeues = hop.end(error=error), FAILED, 0
        else:
            requeues = int((getattr(res, "hops", None) or {})
                           .get("requeues", 0) or 0)
            ms, status = hop.end(status=res.status), res.status
        if ms is None or hop.tail_key is None:
            return
        reason = trace.tail_keep_reason(status=status, latency_ms=ms,
                                        requeues=requeues)
        trace.tail_decide(hop.tail_key, keep=reason is not None,
                          reason=reason or "ok")

    def sync_clock(self, samples: int = 5) -> trace.ClockSync | None:
        """Estimate the server's wall-clock offset from ``samples`` ping
        round trips (midpoint-of-RTT, EWMA-smoothed) and record it as a
        ``clock-offset`` event — the edge ``trace waterfall`` uses to
        shift this peer's hops onto one timeline.  Returns the
        :class:`~..core.trace.ClockSync` (also kept on ``clock_sync``),
        or None when the peer predates the ``"t"`` ping field or the
        connection died mid-sync."""
        cs = trace.ClockSync()
        peer_pid = None
        for _ in range(max(1, int(samples))):
            t0 = time.time()
            try:
                resp = self.control("ping")
            except (ConnectionError, OSError, TimeoutError):
                return None
            t1 = time.time()
            if not resp.get("ok") or resp.get("t") is None:
                return None
            peer_pid = resp.get("pid")
            cs.update(t0, float(resp["t"]), t1)
        self.clock_sync = cs
        trace.record_event("clock-offset", peer_pid=peer_pid,
                           offset_ms=round(cs.offset_ms, 3),
                           err_ms=round(cs.err_ms, 3),
                           rtt_ms=round(cs.rtt_ms, 3),
                           samples=cs.samples)
        return cs

    def solve(self, op: str, payload, deadline_ms: float | None = None,
              tenant: str = "default",
              trace_id: str | None = None) -> SolveResult:
        if self.proto == 2:
            return self.result(self.submit(op, payload,
                                           deadline_ms=deadline_ms,
                                           tenant=tenant,
                                           trace_id=trace_id))
        rid = next(self._rid)
        tid = trace_id or trace.trace_id()
        hop = trace.begin_span("serve.hop.client",
                               tail_key=f"c{self._conn_seq}.{rid}",
                               head_key=rid, rid=rid, op=op,
                               tenant=tenant, trace=tid)
        doc = {"op": op, "payload": encode_payload(op, payload),
               "tenant": tenant, "trace_id": tid,
               "parent_span": hop.id}
        if deadline_ms is not None:
            doc["deadline_ms"] = deadline_ms
        try:
            res = decode_result(self.request(doc))
        except Exception as e:
            self._finish_hop(hop, error=type(e).__name__)
            raise
        self._finish_hop(hop, res=res)
        return res

    def request(self, doc: dict) -> dict:
        """One request doc -> one response doc.  v1: the blocking wire
        call.  v2: pipelined under the hood; section refs in the reply
        are inlined so the document is self-describing like v1's."""
        if self.proto == 2:
            if "control" in doc:
                return self.control(doc["control"],
                                    **{k: v for k, v in doc.items()
                                       if k != "control"})
            rid = self.submit_doc(doc)
            if self._sync:
                self._inflight.pop(rid, None)
                while True:
                    ftype, frid, meta, sections = self._read_sync()
                    if ftype == wire.FT_RESPONSE and frid == rid:
                        return wire.inline_sections(meta, sections)
                    if ftype == wire.FT_RESPONSE:
                        self._parked[frid] = (meta, sections,
                                              time.perf_counter())
            with self._mu:
                waiter = self._pending.get(rid)
            ok = waiter is not None and waiter[0].wait(self.timeout_s)
            with self._mu:
                self._pending.pop(rid, None)
            if not ok:
                raise TimeoutError(f"no response for request {rid}")
            if waiter[1][0] == "err":
                raise waiter[1][1]
            _, meta, sections, _ = waiter[1]
            return wire.inline_sections(meta, sections)
        with self._mu:
            send_frame(self._sock, doc)
            resp = recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("server closed connection")
        return resp

    def control(self, kind: str, **fields) -> dict:
        if self.proto != 2:
            return self.request({"control": kind, **fields})
        if self._sync:
            if self._outbox:
                self.flush()
            rid = next(self._rid)
            self._conn.send_v2(wire.FT_CONTROL, rid,
                               {"control": kind, **fields})
            while True:
                ftype, frid, meta, sections = self._read_sync()
                if ftype == wire.FT_CONTROL_REPLY and frid == rid:
                    return meta
                if ftype == wire.FT_RESPONSE:
                    self._parked[frid] = (meta, sections,
                                          time.perf_counter())
        rid = next(self._rid)
        waiter = [threading.Event(), None]
        with self._mu:
            self._ctl[rid] = waiter
        self._conn.send_v2(wire.FT_CONTROL, rid,
                           {"control": kind, **fields})
        if not waiter[0].wait(self.timeout_s):
            with self._mu:
                self._ctl.pop(rid, None)
            raise TimeoutError(f"no reply to control {kind!r}")
        if isinstance(waiter[1], tuple) and waiter[1][0] == "err":
            raise waiter[1][1]
        return waiter[1]

    @property
    def shm_active(self) -> bool:
        return bool(self._conn is not None and self._conn.lane)

    def close(self) -> None:
        self._closing = True
        if self._conn is not None:
            self._conn.close()
        else:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
