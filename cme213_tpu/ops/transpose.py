"""Tiled matrix transpose — VMEM-tile Pallas kernel + XLA reference.

The coalesced tiled transpose is one of the reference's studied techniques
(``my-refs/MatrixTranspose.pdf``, the shared-memory staging pattern of
``hw/hw2``'s tiled kernels).  On TPU the XLA transpose is already tiled by
the compiler; the Pallas kernel makes the VMEM staging explicit: each grid
step loads a (T, T) tile into VMEM, transposes on-chip, and writes the
mirrored output block — the exact analog of the classic shared-memory tile
transpose, with the bank-conflict padding replaced by the compiler's lane
layout handling.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(in_ref, out_ref):
    out_ref[:] = in_ref[:].T


@partial(jax.jit, static_argnames=("tile", "interpret"))
def transpose_pallas(x: jnp.ndarray, tile: int = 256,
                     interpret: bool = False) -> jnp.ndarray:
    """Transpose an (M, N) array with (tile × tile) VMEM blocks.
    M and N must divide by ``tile``."""
    m, n = x.shape
    assert m % tile == 0 and n % tile == 0
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((n, m), x.dtype),
        grid=(m // tile, n // tile),
        in_specs=[pl.BlockSpec((tile, tile), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tile, tile), lambda i, j: (j, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


@jax.jit
def transpose_xla(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.transpose(x)
