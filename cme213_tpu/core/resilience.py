"""Failure classification, bounded retry, and the kernel fallback ladder.

The reference's failure model is binary — ``check_launch`` aborts, or the
job is fine (``hw/hw1/programming/mp1-util.h:8-18``).  A production jax_graft
system needs the middle ground: a Pallas kernel that fails to lower on one
platform should *demote* to the XLA formulation of the same op, a transient
runtime error should retry with bounded deterministic backoff, and a NaN
blow-up should be recognized as numeric (retrying the same program is
pointless; rolling back to a checkpoint is not).  Three pieces:

- ``classify_failure`` buckets an exception as COMPILE (lowering/Mosaic/
  unsupported-op — deterministic, never retried on the same rung), NUMERIC
  (non-finite values — handled by checkpoint rollback, see
  ``core/checkpoint.run_with_checkpoints``), RESOURCE (an HBM
  RESOURCE_EXHAUSTED — retrying the same program refinds the same wall;
  the response is *shrinking*: halve the solve chunk / pipeline tile and
  retry, see ``core/admission.py``), or RUNTIME (everything else,
  including XlaRuntimeError and injected faults — retryable).  A fifth
  kind, WRONG_ANSWER, is never produced by classification — it is
  assigned by the conformance gate (``core/conformance.py``) when a rung
  returns finite-but-divergent results on its probe.
- ``RetryPolicy`` — bounded attempts with a deterministic geometric backoff
  (no jitter: CI reproducibility beats thundering-herd avoidance at this
  scale).
- ``with_fallback`` — run a ladder of (rung, thunk) candidates in order,
  consult the fault plan per rung (``faults.maybe_fail``), record every
  demotion through the structured trace log, and report which rung actually
  served the request.

Every guard here runs in host Python outside jit — zero device overhead,
and zero work at all when no faults are installed and the first rung holds.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum

from . import metrics
from .errors import FrameworkError
from .faults import maybe_fail
from .trace import record_event


class FailureKind(str, Enum):
    COMPILE = "compile"
    RUNTIME = "runtime"
    NUMERIC = "numeric"
    RESOURCE = "resource"           # out of device memory: shrink, don't retry
    WRONG_ANSWER = "wrong_answer"   # conformance probe diverged: demote


class NonFiniteError(ArithmeticError):
    """A finiteness guard tripped: the state contains NaN/Inf."""


# substrings (lowercased) marking a deterministic compile/lowering failure —
# retrying the identical program cannot succeed, but a different kernel
# formulation of the same op can
_COMPILE_MARKERS = ("mosaic", "lowering", "lower", "compil", "unsupported",
                    "unimplemented", "vmem", "mlir")
_NUMERIC_MARKERS = ("nan", "non-finite", "not finite", "overflow")
# runtime HBM exhaustion (XlaRuntimeError RESOURCE_EXHAUSTED and friends);
# compile-time VMEM over-budget stays COMPILE — a different kernel
# formulation can fix that, while no reformulation shrinks the arrays
_RESOURCE_MARKERS = ("resource_exhausted", "resource exhausted",
                     "out of memory", "out-of-memory")


def classify_failure(exc: BaseException) -> FailureKind:
    """COMPILE / NUMERIC / RESOURCE / RUNTIME bucket for a caught
    exception."""
    from .faults import InjectedResourceExhausted

    if isinstance(exc, (NonFiniteError, FloatingPointError, ZeroDivisionError)):
        return FailureKind.NUMERIC
    if isinstance(exc, InjectedResourceExhausted):
        return FailureKind.RESOURCE
    if isinstance(exc, FrameworkError) and exc.__cause__ is not None:
        return classify_failure(exc.__cause__)
    if isinstance(exc, NotImplementedError):
        return FailureKind.COMPILE
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _NUMERIC_MARKERS):
        return FailureKind.NUMERIC
    if any(m in msg for m in _RESOURCE_MARKERS):
        return FailureKind.RESOURCE
    if any(m in msg for m in _COMPILE_MARKERS):
        return FailureKind.COMPILE
    return FailureKind.RUNTIME


def all_finite(state) -> bool:
    """Finiteness guard over a pytree of arrays (host-side, outside jit)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(state):
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not bool(np.asarray(jnp.all(jnp.isfinite(arr)))):
            return False
    return True


@dataclass
class RetryPolicy:
    """Bounded retry with deterministic geometric backoff.

    ``run(fn)`` retries only RUNTIME-classified failures (by default):
    compile failures are deterministic and numeric failures belong to the
    checkpoint-rollback path, so retrying either wastes device minutes.
    """

    max_retries: int = 2
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    retry_on: tuple = (FailureKind.RUNTIME,)
    sleep: object = field(default=time.sleep, repr=False)

    def delays(self) -> list[float]:
        return [min(self.base_delay_s * self.multiplier ** i,
                    self.max_delay_s) for i in range(self.max_retries)]

    def run(self, fn, op: str = "retry"):
        last = None
        for attempt, delay in enumerate([0.0] + self.delays()):
            if delay:
                self.sleep(delay)
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classify, then decide
                kind = classify_failure(e)
                last = e
                if kind not in self.retry_on or attempt >= self.max_retries:
                    raise
                metrics.counter("retry.attempts").inc()
                record_event("retry", op=op, attempt=attempt + 1,
                             kind=kind.value, error=type(e).__name__,
                             next_delay_s=self.delays()[attempt])
        raise last  # pragma: no cover — loop always returns or raises


@dataclass
class RungFailure:
    rung: str
    kind: FailureKind
    error: str
    message: str


@dataclass
class FallbackResult:
    """What ``with_fallback`` actually ran: the value, the serving rung,
    and every rung that failed on the way down the ladder."""

    value: object
    rung: str
    failures: list[RungFailure] = field(default_factory=list)

    @property
    def demoted(self) -> bool:
        return bool(self.failures)


def with_fallback(op: str, ladder, policy: RetryPolicy | None = None,
                  gate=None) -> FallbackResult:
    """Run the first rung of ``ladder`` (a sequence of ``(name, thunk)``)
    that succeeds, demoting down the ladder on failure.

    Per rung: the conformance ``gate`` is consulted first when given
    (``gate(name) -> bool`` — typically a closure over
    ``core/conformance.check``; a False verdict or a raising probe demotes
    with ``FailureKind.WRONG_ANSWER`` exactly like a rung exception), then
    the fault plan (``maybe_fail(f"{op}.{name}")`` — an injected failure
    demotes exactly like a real one), then the thunk runs (under
    ``policy`` when given, which retries transient RUNTIME failures
    *within* the rung before demoting).  Each failed rung emits a
    structured ``rung-failed`` event; the serving rung emits ``served``
    with ``demoted`` and the failure list, so capture logs show which
    kernel actually handled the request.  All-rungs-failed raises
    FrameworkError chained to the last failure.
    """
    failures: list[RungFailure] = []
    last: Exception | None = None
    for name, thunk in ladder:
        if gate is not None:
            try:
                admitted = gate(name)
            except Exception as e:  # noqa: BLE001 — a crashed probe is a
                # rung failure: the rung cannot even run its probe problem
                kind = classify_failure(e)
                failures.append(RungFailure(name, kind, type(e).__name__,
                                            str(e)[:300]))
                metrics.counter("fallback.demotions").inc()
                record_event("rung-failed", op=op, rung=name,
                             kind=kind.value, error=type(e).__name__)
                last = e
                continue
            if not admitted:
                failures.append(RungFailure(
                    name, FailureKind.WRONG_ANSWER, "ConformanceFailed",
                    "probe output diverged from the reference rung"))
                metrics.counter("fallback.demotions").inc()
                record_event("rung-failed", op=op, rung=name,
                             kind=FailureKind.WRONG_ANSWER.value,
                             error="ConformanceFailed")
                continue
        try:
            maybe_fail(f"{op}.{name}")
            value = (thunk() if policy is None
                     else policy.run(thunk, op=f"{op}.{name}"))
        except Exception as e:  # noqa: BLE001 — every rung failure is data
            kind = classify_failure(e)
            failures.append(RungFailure(name, kind, type(e).__name__,
                                        str(e)[:300]))
            metrics.counter("fallback.demotions").inc()
            record_event("rung-failed", op=op, rung=name, kind=kind.value,
                         error=type(e).__name__)
            last = e
            continue
        metrics.counter(f"served.{op}.{name}").inc()
        record_event("served", op=op, rung=name, demoted=bool(failures),
                     failed_rungs=[f.rung for f in failures])
        return FallbackResult(value, name, failures)
    raise FrameworkError(
        f"all {len(failures)} rungs of {op} failed: "
        + "; ".join(f"{f.rung}[{f.kind.value}] {f.error}" for f in failures)
    ) from last
