"""Numeric-health observatory — continuous correctness as a served signal.

The reference course verified numerics *offline*: hw2 diffed the
``grid_final_*`` grids after the run, hw_final printed one relative-error
number per matrix.  Our production path checks a rung exactly once
(``core/conformance.py``'s first-use probe) and then serves it blind — a
rung that drifts after warmup, a slow NaN creep in a long solve, or a
stalling iteration count would never surface in any span, metric, or
SLO.  This module keeps the check **on** for the life of the process:

- **Shadow conformance sampling** — the serve batcher re-executes a
  deterministic 1-in-N sample of served requests (``CME213_SHADOW_RATE``,
  seeded per trace id so every rank of a gang samples the *same*
  requests) against the op's reference rung, off the hot path, and
  records the measured rel-L2 / max-ulp drift as ``numeric-drift``
  events and ``numerics.drift.<op>.<rung>`` histograms.
- **Drift error budget** — per (op, rung), the same two-window burn
  machinery as ``serve/slo.py`` (short window proves it is still
  happening, long window proves it is sustained; hysteresis on
  recovery), but over *sample counts* instead of wall-clock windows so
  the budget is deterministic under CI load.  A burned budget demotes
  the rung through the existing ``with_fallback`` ladder: the server
  passes :func:`demoted` as the ladder ``gate``, so a drifting rung is
  routed around with ``FailureKind.WRONG_ANSWER`` exactly like a failed
  conformance probe, and serving falls back to the reference rung.
- **Output sentinels** — one vectorized NaN/Inf (and optional range)
  reduction over every served batch, feeding ``numeric-sentinel`` events
  and the circuit breaker's failure classification
  (``FailureKind.NUMERIC``), so a rung that goes non-finite repeatedly
  trips its breaker even though the batch was already served.
- **Convergence tracing** — long solves emit per-epoch
  ``solver-progress`` events (residual, delta-norm, iterations/s)
  through :class:`ConvergenceTracker`, which also renders the STALLED
  verdict ``top`` shows when the residual stops improving across K
  epochs.

``drift:<op>[:<scale>[:<nth>]]`` fault clauses (``core/faults.py``)
perturb served outputs *below* the ``wrong:`` blow-up threshold, so the
whole sample → budget → demote loop is deterministically testable on
CPU.  Offline, the ``numerics`` CLI (``numerics_cli.py``) replays these
events from a trace sink into the same report.
"""

from __future__ import annotations

import hashlib
import os
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from . import metrics
from .trace import record_event, trace_id

#: 1-in-N shadow sampling rate (0/unset = disabled; 1 = every request)
SHADOW_RATE_ENV = "CME213_SHADOW_RATE"
#: rel-L2 drift tolerance for a shadow sample (default: 1e-5 — the
#: shadow re-executes the sampled requests at a *different batch width*
#: than they were served at, so reduction-order noise up to ~1e-7 at
#: f32 is legitimate; anything structural — the smallest ``drift:``
#: scale is 100× this — still clears the bar)
SHADOW_REL_L2_ENV = "CME213_SHADOW_REL_L2"
#: optional max-ulp drift tolerance (0/unset = rel-L2 only)
SHADOW_MAX_ULPS_ENV = "CME213_SHADOW_MAX_ULPS"
#: drift error budget: allowed fraction of shadow samples over tolerance
DRIFT_BUDGET_ENV = "CME213_DRIFT_BUDGET"

_DEFAULT_REL_L2 = 1e-5
_DEFAULT_BUDGET = 0.1


def shadow_rate() -> int:
    """The configured 1-in-N sampling rate (0 = shadow sampling off)."""
    raw = os.environ.get(SHADOW_RATE_ENV, "").strip()
    if not raw:
        return 0
    try:
        n = int(raw)
    except ValueError:
        return 0
    return n if n >= 1 else 0


def should_sample(rid: str, rate: int | None = None,
                  trace: str | None = None) -> bool:
    """Deterministic 1-in-``rate`` membership for request ``rid``.

    The decision hashes ``(trace, rid)`` (``trace`` defaults to this
    process's trace id) — no RNG state, no call counters — so every
    process sharing a trace context (a gang under
    ``CME213_TRACE_CONTEXT``, or a server keying by the request's own
    ``trace_id``) samples exactly the same requests, and a re-run of the
    same trace replays the same sample.
    """
    n = shadow_rate() if rate is None else rate
    if n <= 0:
        return False
    if n == 1:
        return True
    key = f"{trace if trace is not None else trace_id()}|{rid}".encode()
    h = int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(), "big")
    return h % n == 0


def measure_drift(out, ref) -> tuple[float, int]:
    """(rel_l2, max_ulps) between a served output and its shadow
    reference.  This is a *measure*, not a verdict: shape/dtype mismatch
    or a non-finite served output returns ``inf`` so the caller's
    tolerance check always classifies it as over budget.  ``max_ulps``
    is 0 for non-float outputs (bitwise workloads measure via rel-L2 on
    the float64 cast)."""
    out = np.asarray(out)
    ref = np.asarray(ref)
    if out.shape != ref.shape or out.dtype != ref.dtype:
        return float("inf"), -1
    if out.size == 0:
        return 0.0, 0
    if (np.issubdtype(out.dtype, np.floating)
            and not np.isfinite(out).all()):
        return float("inf"), -1
    denom = float(np.linalg.norm(ref.astype(np.float64)))
    rel_l2 = (float(np.linalg.norm((out.astype(np.float64)
                                    - ref.astype(np.float64))))
              / max(denom, float(np.finfo(np.float64).tiny)))
    ulps = 0
    if np.issubdtype(out.dtype, np.floating):
        from .compare import ulp_distance

        ulps = int(np.max(ulp_distance(ref, out)))
    return rel_l2, ulps


def _tolerances() -> tuple[float, int]:
    try:
        rel = float(os.environ.get(SHADOW_REL_L2_ENV, "") or _DEFAULT_REL_L2)
    except ValueError:
        rel = _DEFAULT_REL_L2
    try:
        ulps = int(os.environ.get(SHADOW_MAX_ULPS_ENV, "") or 0)
    except ValueError:
        ulps = 0
    return rel, ulps


# ---------------------------------------------------------------- budget


@dataclass
class _BudgetState:
    """Per-(op, rung) drift budget — ``serve/slo.py``'s two-window AND
    burn over the last N shadow samples instead of wall-clock windows
    (sample-count windows make the burn independent of request rate, so
    the same fault spec burns identically in CI and in a live fleet)."""

    window: deque = field(default_factory=lambda: deque(maxlen=64))
    burning: bool = False
    samples: int = 0
    over: int = 0
    last_rel_l2: float = 0.0
    last_max_ulps: int = 0


class DriftBudget:
    """Per-(op, rung) error budget over shadow-sample outcomes.

    ``target`` is the allowed fraction of shadow samples over tolerance
    (the error budget); burn = observed over-rate / target, evaluated
    over a short (last ``short_n``) and long (last ``long_n``) sample
    window.  Both burns must reach ``burn_threshold`` (with at least
    ``min_samples`` observed) before the budget fires — and recovery
    needs the short burn back under ``threshold * hysteresis``, exactly
    the flap filter ``serve/slo.py`` uses for latency/shed/error SLOs.
    """

    def __init__(self, target: float | None = None, short_n: int = 8,
                 long_n: int = 32, burn_threshold: float = 2.0,
                 min_samples: int = 8, hysteresis: float = 0.5):
        if target is None:
            try:
                target = float(os.environ.get(DRIFT_BUDGET_ENV, "")
                               or _DEFAULT_BUDGET)
            except ValueError:
                target = _DEFAULT_BUDGET
        if target <= 0:
            raise ValueError(f"drift budget must be > 0, got {target}")
        self.target = target
        self.short_n = short_n
        self.long_n = max(long_n, short_n)
        self.burn_threshold = burn_threshold
        self.min_samples = max(1, min_samples)
        self.hysteresis = hysteresis
        self._states: dict[tuple[str, str], _BudgetState] = {}

    def _st(self, op: str, rung: str) -> _BudgetState:
        return self._states.setdefault((op, rung),
                                       _BudgetState(deque(maxlen=self.long_n)))

    def observe(self, op: str, rung: str, over: bool,
                rel_l2: float = 0.0, max_ulps: int = 0) -> bool:
        """Fold one shadow-sample outcome in; returns the (possibly
        transitioned) burning state.  Transitions record
        ``drift-budget-burn`` / ``drift-budget-ok`` events."""
        st = self._st(op, rung)
        st.window.append(bool(over))
        st.samples += 1
        st.over += bool(over)
        st.last_rel_l2 = rel_l2
        st.last_max_ulps = max_ulps
        long_win = list(st.window)
        short_win = long_win[-self.short_n:]
        burn_short = (sum(short_win) / len(short_win)) / self.target
        burn_long = (sum(long_win) / len(long_win)) / self.target
        if (not st.burning and len(long_win) >= self.min_samples
                and burn_short >= self.burn_threshold
                and burn_long >= self.burn_threshold):
            st.burning = True
            metrics.counter("numerics.budget.burns").inc()
            record_event("drift-budget-burn", op=op, rung=rung,
                         burn_short=round(burn_short, 3),
                         burn_long=round(burn_long, 3),
                         threshold=self.burn_threshold)
        elif (st.burning
              and burn_short <= self.burn_threshold * self.hysteresis):
            st.burning = False
            record_event("drift-budget-ok", op=op, rung=rung,
                         burn_short=round(burn_short, 3))
        return st.burning

    def burning(self, op: str, rung: str) -> bool:
        st = self._states.get((op, rung))
        return bool(st and st.burning)

    def state(self) -> dict:
        """JSON-able per-(op, rung) budget state (reports, flight)."""
        out = {}
        for (op, rung), st in sorted(self._states.items()):
            out[f"{op}|{rung}"] = {
                "samples": st.samples, "over": st.over,
                "last_rel_l2": st.last_rel_l2,
                "last_max_ulps": st.last_max_ulps,
                "burning": st.burning,
                "demoted": (op, rung) in _DEMOTED,
            }
        return out


#: module singletons: the serving path's budget + the demoted-rung set
_BUDGET: DriftBudget | None = None
_DEMOTED: set[tuple[str, str]] = set()


def budget() -> DriftBudget:
    """The process-wide drift budget (lazily built from the env)."""
    global _BUDGET
    if _BUDGET is None:
        _BUDGET = DriftBudget()
    return _BUDGET


def demoted(op: str, rung: str) -> bool:
    """True when (op, rung)'s drift budget burned and the rung must be
    routed around.  Shaped as a ``with_fallback`` gate verdict: the
    server passes ``lambda rung: not demoted(op, rung)`` so demotion
    flows through the ladder's existing WRONG_ANSWER path.  Demotion is
    sticky for the life of the process — a drifting kernel does not
    silently rejoin the ladder; a restart (new incarnation) re-probes
    clean."""
    return (op, rung) in _DEMOTED


def shadow_compare(op: str, rung: str, shape_class: str, outputs,
                   references) -> dict:
    """Compare one sampled batch's served ``outputs`` against its
    re-executed reference ``references`` (parallel sequences, one entry
    per request).  Records the drift histogram + ``numeric-drift``
    event, feeds the (op, rung) budget, and flips the rung into the
    demoted set when the budget burns.  Returns a summary dict
    (``rel_l2``, ``max_ulps``, ``over_budget``, ``burning``,
    ``demoted``).  Runs off the hot path by construction: callers invoke
    it after the request latency was stamped."""
    rel_tol, ulp_tol = _tolerances()
    worst_rel, worst_ulps = 0.0, 0
    for out, ref in zip(outputs, references):
        rel_l2, ulps = measure_drift(out, ref)
        worst_rel = max(worst_rel, rel_l2)
        worst_ulps = max(worst_ulps, ulps) if ulps >= 0 else -1
    over = worst_rel > rel_tol or (ulp_tol > 0 and worst_ulps > ulp_tol)
    metrics.counter("numerics.shadow.samples").inc()
    hist_rel = worst_rel if np.isfinite(worst_rel) else 1.0
    metrics.histogram(f"numerics.drift.{op}.{rung}").observe(hist_rel)
    if over:
        metrics.counter("numerics.shadow.over_budget").inc()
    record_event("numeric-drift", op=op, rung=rung, shape_class=shape_class,
                 rel_l2=(round(worst_rel, 9) if np.isfinite(worst_rel)
                         else "inf"),
                 max_ulps=worst_ulps, over_budget=over)
    burning = budget().observe(op, rung, over, rel_l2=hist_rel,
                               max_ulps=worst_ulps)
    if burning and (op, rung) not in _DEMOTED:
        _DEMOTED.add((op, rung))
        metrics.gauge("numerics.demoted").set(len(_DEMOTED))
    return {"rel_l2": worst_rel, "max_ulps": worst_ulps,
            "over_budget": over, "burning": burning,
            "demoted": demoted(op, rung)}


# -------------------------------------------------------------- sentinels


def sentinel(op: str, rung: str, outputs, lo: float | None = None,
             hi: float | None = None, breaker=None) -> int:
    """Cheap output sentinel over one served batch: a single vectorized
    non-finite reduction per output array (plus an optional [lo, hi]
    range check), no reference execution.  Returns the bad-element
    count; a non-zero count records a ``numeric-sentinel`` event and
    feeds ``breaker.record_failure(op, rung, FailureKind.NUMERIC)`` so a
    rung that keeps emitting NaNs trips its circuit even though each
    batch was already served."""
    bad = 0
    size = 0
    kind = "non-finite"
    for out in outputs:
        arr = np.asarray(out)
        size += arr.size
        if np.issubdtype(arr.dtype, np.floating):
            finite = np.isfinite(arr)
            bad += int(arr.size - np.count_nonzero(finite))
            if lo is not None or hi is not None:
                in_range = finite.copy()
                if lo is not None:
                    in_range &= arr >= lo
                if hi is not None:
                    in_range &= arr <= hi
                out_of_range = int(np.count_nonzero(finite)
                                   - np.count_nonzero(in_range))
                if out_of_range:
                    kind = "out-of-range"
                    bad += out_of_range
    if bad:
        metrics.counter("numerics.sentinel.tripped").inc()
        record_event("numeric-sentinel", op=op, rung=rung, kind=kind,
                     count=bad, size=size)
        if breaker is not None:
            from .resilience import FailureKind

            breaker.record_failure(op, rung, FailureKind.NUMERIC)
    return bad


# ------------------------------------------------------------ convergence


class ConvergenceTracker:
    """Per-solve convergence trace: one ``solver-progress`` event per
    epoch/chunk (residual, delta-norm, iterations/s) plus the STALLED
    verdict — the residual failing to improve by ``min_improve``
    (relative) for ``stall_epochs`` consecutive steps.  The checkpointed
    and supervised solve loops feed it; ``trace summary`` and ``top``
    read the events back.  ``job`` tags every event with a long-job id
    (``serve/jobs.py``) so two concurrent jobs of the same op stay
    distinct rows in the summary; None (the default) means the solve is
    not a job."""

    def __init__(self, op: str, stall_epochs: int = 5,
                 min_improve: float = 1e-3, job: str | None = None):
        self.op = op
        self.stall_epochs = max(1, stall_epochs)
        self.min_improve = min_improve
        self.job = job
        self.best: float | None = None
        self.last_residual: float | None = None
        self.since_improve = 0
        self.steps = 0

    def step(self, step: int, residual: float, delta_norm: float,
             iters_per_s: float) -> None:
        """Record one epoch's progress (events + gauges) and advance the
        stall detector."""
        self.steps += 1
        residual = float(residual)
        self.last_residual = residual
        record_event("solver-progress", op=self.op, step=int(step),
                     residual=round(residual, 9),
                     delta_norm=round(float(delta_norm), 9),
                     iters_per_s=round(float(iters_per_s), 3),
                     job=self.job)
        metrics.counter("numerics.progress").inc()
        metrics.gauge(f"numerics.residual.{self.op}").set(round(residual, 9))
        if (self.best is None
                or residual < self.best * (1.0 - self.min_improve)):
            self.best = residual
            self.since_improve = 0
        else:
            self.since_improve += 1

    @property
    def stalled(self) -> bool:
        return self.since_improve >= self.stall_epochs


def state_snapshot(state):
    """Host copy of ``state``'s first float leaf, or None.  Take it
    BEFORE running a step whose jitted program donates its input buffers
    (e.g. heat2d's ``donate_argnums``) — the device array is deleted by
    the time :func:`progress_from_states` would read it; the snapshot is
    the ``old_state`` that survives."""
    try:
        arr = _first_float_leaf(state)
        return None if arr is None else np.array(arr)
    except Exception:  # noqa: BLE001 — same contract as below
        return None


def progress_from_states(tracker: ConvergenceTracker, step: int,
                         old_state, new_state, iters: int,
                         elapsed_s: float) -> None:
    """Feed a tracker from two consecutive solver states: delta-norm is
    ``||new - old||`` over the first float leaf, residual the relative
    change ``delta / max(||new||, tiny)`` — the generic convergence
    signal every fixed-point solve exposes without knowing its PDE."""
    try:
        old_arr = _first_float_leaf(old_state)
        new_arr = _first_float_leaf(new_state)
    except Exception:  # noqa: BLE001 — progress tracing must never take
        # down the solve it observes (e.g. a non-addressable shard)
        return
    if old_arr is None or new_arr is None or old_arr.shape != new_arr.shape:
        return
    delta = float(np.linalg.norm((new_arr.astype(np.float64)
                                  - old_arr.astype(np.float64))))
    denom = max(float(np.linalg.norm(new_arr.astype(np.float64))),
                float(np.finfo(np.float64).tiny))
    tracker.step(step, residual=delta / denom, delta_norm=delta,
                 iters_per_s=(iters / elapsed_s if elapsed_s > 0 else 0.0))


def _first_float_leaf(state):
    try:
        from jax import tree_util
        leaves = tree_util.tree_flatten(state)[0]
    except ImportError:  # pragma: no cover - jax always present here
        leaves = [state]
    for leaf in leaves:
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return arr
    return None


# ------------------------------------------------------------- snapshots


def last_drift() -> dict:
    """Best-effort numeric-health snapshot for the flight recorder and
    reports: per-(op, rung) budget state + the demoted set.  ``{}`` when
    nothing was ever sampled."""
    if _BUDGET is None and not _DEMOTED:
        return {}
    snap = {"budget": budget().state(),
            "demoted": sorted(f"{op}|{rung}" for op, rung in _DEMOTED)}
    return snap


def reset() -> None:
    """Forget budgets, demotions, and cached config (tests)."""
    global _BUDGET
    _BUDGET = None
    _DEMOTED.clear()
