"""MatrixMarket → SpMV-scan problem instances (the readMM.py parity path).

The reference's dataset generators (``hw/hw_final/programming/aux/readMM.py``,
``aux/fileReadMM.py``) read SuiteSparse ``.mtx`` files with SciPy and emit
``a.txt``/``x.txt`` instances: ``a`` = the nonzero values, ``s`` = a random
sorted subset of indices (with 0/n sentinels), ``k`` = random gather indices,
``x`` = uniform(−1,1), ``N`` ∈ [5,100].  This module does the same with a
dependency-free coordinate-format parser, so real SuiteSparse matrices can be
fed to the engine when available.
"""

from __future__ import annotations

import gzip

import numpy as np

from .spmv_scan import Problem


def read_matrix_market(path: str):
    """Minimal MatrixMarket coordinate parser.

    Supports ``matrix coordinate (real|integer|pattern) (general|symmetric)``.
    Returns (rows, cols, values, shape) with 0-based indices, symmetric
    entries expanded.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        header = f.readline().strip().lower().split()
        if header[:2] != ["%%matrixmarket", "matrix"]:
            raise ValueError("not a MatrixMarket matrix file")
        if header[2] != "coordinate":
            raise ValueError("only coordinate format supported")
        field, sym = header[3], header[4]
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        nr, nc, nnz = (int(v) for v in line.split())
        data = np.loadtxt(f, ndmin=2)
    rows = data[:, 0].astype(np.int64) - 1
    cols = data[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones(rows.shape[0], dtype=np.float32)
    else:
        vals = data[:, 2].astype(np.float32)
    if sym == "symmetric":
        off = rows != cols
        rows, cols = (np.concatenate([rows, cols[off]]),
                      np.concatenate([cols, rows[off]]))
        vals = np.concatenate([vals, vals[off]])
    return rows, cols, vals, (nr, nc)


def gr_30_30_mtx() -> str:
    """Reconstruct SuiteSparse ``HB/gr_30_30`` as MatrixMarket text.

    The published problem is exactly defined: the nine-point star
    discretization of the Laplacian on a 30×30 grid (n = 900,
    nnz = 7744 expanded — 900 diagonal + 6844 king-graph adjacencies),
    symmetric.  This environment has no network access, so the framework
    ships this *reconstruction* instead of the downloaded file: the
    nonzero pattern is forced by the discretization and matches the
    SuiteSparse instance; values use the standard 9-point star
    coefficients (8 on the diagonal, −1 for the eight neighbours).
    Stored as symmetric/lower like the original HB-derived .mtx
    (4322 stored entries), which also exercises the reader's symmetric
    expansion path.
    """
    side = 30
    entries = []  # (row, col, value) 1-based, lower triangle
    for i in range(side):
        for j in range(side):
            r = i * side + j
            entries.append((r + 1, r + 1, 8.0))
            for di in (-1, 0, 1):
                for dj in (-1, 0, 1):
                    if di == 0 and dj == 0:
                        continue
                    ni, nj = i + di, j + dj
                    if not (0 <= ni < side and 0 <= nj < side):
                        continue
                    c = ni * side + nj
                    if c < r:  # store lower triangle only
                        entries.append((r + 1, c + 1, -1.0))
    entries.sort(key=lambda e: (e[1], e[0]))  # column-major like HB files
    n = side * side
    lines = [
        "%%MatrixMarket matrix coordinate real symmetric",
        "% HB/gr_30_30 — nine-point star discretization on a 30x30 grid.",
        "% Reconstructed from the published problem definition (no network",
        "% access in this environment): pattern is exactly the SuiteSparse",
        "% instance's (n=900, nnz=7744 expanded); values are the standard",
        "% 9-point star coefficients.",
        f"{n} {n} {len(entries)}",
    ]
    lines += [f"{r} {c} {v:.1f}" for r, c, v in entries]
    return "\n".join(lines) + "\n"


def gr_30_30_path() -> str:
    """Path of the shipped real-matrix instance (examples/gr_30_30.mtx)."""
    import os

    return os.path.join(os.path.dirname(__file__), "..", "..", "examples",
                        "gr_30_30.mtx")


def dense2_problem(iters: int | None = 10, seed: int = 0) -> Problem:
    """Reconstruct the suite's ``Williams/dense2`` instance.

    The published problem (named in ``aux/reference_spMVscan-released.cu:
    168-185``) is a literal dense 2000×2000 matrix stored in sparse
    format, so its nonzero pattern is fully determined: all 4,000,000
    entries, column-major in the MatrixMarket file the readMM.py pipeline
    consumed (``aux/readMM.py:16-73``).  As with the shipped gr_30_30
    reconstruction, this environment has no network access, so values are
    canonical (1.0) and the row is labeled a reconstruction.  Built
    in memory rather than via a .mtx detour — a 4M-line text file would
    add ~60 MB and a multi-second parse for zero extra information.

    The default iteration count is the suite table's published N=10 for
    dense2 (``paper/Final_Report_DongBang_Tsai.tex:236-251``), so the
    real row is directly comparable to the suite-shaped synthetic row.
    """
    vals = np.ones(2000 * 2000, dtype=np.float32)
    return _problem_from_values(vals, nr=2000, iters=iters, seed=seed)


def real_instance_specs():
    """Shipped/reconstructed *real* suite instances: a list of
    ``(name, source_label, problem_factory)``.

    The benchmark suite is defined over named SuiteSparse matrices; these
    are the ones whose published definitions pin them down well enough to
    rebuild offline (pattern exact, values canonical, labels say so).
    The rest of the 15-instance suite stays honestly synthetic.
    """
    import os

    specs = []
    mtx = gr_30_30_path()
    if os.path.exists(mtx):
        specs.append(("gr_30_30", "real (HB/gr_30_30, reconstructed)",
                      lambda: problem_from_mtx(mtx, iters=50, seed=0)))
    specs.append(("dense2", "real (Williams/dense2, reconstructed)",
                  lambda: dense2_problem(iters=10, seed=0)))
    return specs


def problem_from_mtx(path: str, iters: int | None = None,
                     seed: int = 0) -> Problem:
    """readMM.py construction: values → ``a``; random sorted row-index subset
    → ``s``; random ``k``; uniform(−1,1) ``x``; N ∈ [5,100]."""
    _, _, vals, (nr, _) = read_matrix_market(path)
    return _problem_from_values(vals, nr=nr, iters=iters, seed=seed)


def _problem_from_values(vals: np.ndarray, nr: int,
                         iters: int | None = None, seed: int = 0) -> Problem:
    rng = np.random.default_rng(seed)
    n = vals.shape[0]
    p_interior = min(max(nr - 1, 1), n - 1)
    interior = np.sort(rng.choice(np.arange(1, n), size=p_interior,
                                  replace=False))
    s = np.concatenate([[0], interior, [n]]).astype(np.int32)
    q = max(nr, 2)
    k = rng.integers(0, q, size=n, dtype=np.int32)
    x = rng.uniform(-1, 1, size=q).astype(np.float32)
    if iters is None:
        iters = int(rng.integers(5, 101))
    prob = Problem(vals.astype(np.float32), s, k, x, iters)
    prob.validate()
    return prob
