"""Elementwise cipher ops — flat data parallelism + lane-packing variants.

TPU-native redesign of the reference's hw1 cipher kernels: the per-byte shift
(``hw/hw1/programming/cipher.cu:64-70``) becomes a single fused XLA op over a
``uint8`` array; the coalesced-access widening variants (uint / uint2 loads,
``cipher.cu:75-92``, shift packed as ``(s<<24)|(s<<16)|(s<<8)|s`` at ``:231``)
become dtype-packing via ``lax.bitcast_convert_type`` — the same
strategy-P2 idea (move more bytes per lane) expressed for the VPU's 8×128
lanes.  The Thrust one-liner (``hw/hw1/solution/cipher_solution.cu:234-245``)
is the plain ``shift_cipher`` here.

Semantics: unsigned-char wrapping add, matching the host golden
(``cipher.cu:53-60``).  Like the reference's packed kernels, the packed
variants assume no per-byte carry overflow (byte + shift < 256) — true for
ASCII text with the reference's shift values.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=())
def shift_cipher(data: jnp.ndarray, shift) -> jnp.ndarray:
    """Per-byte wrapping shift of a uint8 array."""
    assert data.dtype == jnp.uint8
    return data + jnp.asarray(shift, jnp.uint8)


@partial(jax.jit, static_argnames=("width",))
def shift_cipher_packed(data: jnp.ndarray, shift, width: int = 4) -> jnp.ndarray:
    """Packed-lane shift: process ``width`` bytes per lane (width ∈ {4, 8}).

    ``width=4`` mirrors the uint kernel, ``width=8`` the uint2 kernel.  The
    length must be divisible by ``width`` (the reference guarantees this by
    replicating the corpus ×16, ``cipher.cu:148-159``).
    """
    assert data.dtype == jnp.uint8
    assert width in (4, 8)
    # width=8 is two uint32 lanes, exactly like the reference's uint2 kernel
    # (cipher.cu:85-92 shifts .x and .y separately).
    packed = lax.bitcast_convert_type(data.reshape(-1, width // 4, 4), jnp.uint32)
    s = jnp.asarray(shift, jnp.uint32)
    rep = jnp.zeros((), jnp.uint32)
    for k in range(4):
        rep = rep | (s << (8 * k))
    out = packed + rep
    return lax.bitcast_convert_type(out, jnp.uint8).reshape(-1)


@jax.jit
def shift_cipher_batched(data: jnp.ndarray, shifts: jnp.ndarray) -> jnp.ndarray:
    """B same-length shifts in one program: ``data`` is a (B, n) uint8
    stack, ``shifts`` a (B,) vector — each lane is the exact
    ``shift_cipher`` expression under ``jax.vmap``, so per-lane output is
    bitwise-equal to the serial op (integer arithmetic; no rounding to
    worry about either way)."""
    assert data.dtype == jnp.uint8
    return jax.vmap(lambda d, s: d + jnp.asarray(s, jnp.uint8))(data, shifts)


@partial(jax.jit, static_argnames=("width",))
def shift_cipher_packed_batched(data: jnp.ndarray, shifts: jnp.ndarray,
                                width: int = 4) -> jnp.ndarray:
    """Batched form of the packed-lane shift: (B, n) stack, per-lane
    shift, n divisible by ``width``."""
    assert data.dtype == jnp.uint8

    def one(d, s):
        packed = lax.bitcast_convert_type(
            d.reshape(-1, width // 4, 4), jnp.uint32)
        rep = jnp.zeros((), jnp.uint32)
        for k in range(4):
            rep = rep | (jnp.asarray(s, jnp.uint32) << (8 * k))
        return lax.bitcast_convert_type(packed + rep, jnp.uint8).reshape(-1)

    assert width in (4, 8)
    return jax.vmap(one)(data, shifts)


@jax.jit
def saxpy(alpha, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """y ← α·x + y — the canonical bandwidth-bound elementwise op (one fused
    VPU pass)."""
    return jnp.asarray(alpha, x.dtype) * x + y


@jax.jit
def parallel_sum(x: jnp.ndarray):
    """Full reduction (tree-reduced by XLA across sublanes/lanes)."""
    return jnp.sum(x)


@jax.jit
def vigenere_shift(text: jnp.ndarray, shifts: jnp.ndarray) -> jnp.ndarray:
    """Vigenère encode over lowercase bytes with a periodic key.

    The reference expresses the periodic key as a
    ``transform_iterator(periodic_shifts_fun)`` over a counting iterator
    (``hw/hw3/programming/create_cipher.cu:54-73,135-144``); here the gather
    ``shifts[i % period]`` is one XLA ``take``.  ``apply_shift`` math matches:
    ``(c - 'a' + s) % 26 + 'a'``.
    """
    n = text.shape[0]
    idx = jnp.arange(n) % shifts.shape[0]
    s = shifts[idx].astype(jnp.int32)
    c = text.astype(jnp.int32) - ord("a")
    return ((c + s) % 26 + ord("a")).astype(jnp.uint8)


@jax.jit
def vigenere_unshift(text: jnp.ndarray, shifts: jnp.ndarray) -> jnp.ndarray:
    """Vigenère decode: inverse shift ``(c - 'a' + 26 - s) % 26 + 'a'``
    (reference ``hw/hw3/programming/solve_cipher.cu:94-101``)."""
    n = text.shape[0]
    idx = jnp.arange(n) % shifts.shape[0]
    s = shifts[idx].astype(jnp.int32)
    c = text.astype(jnp.int32) - ord("a")
    return ((c + 26 - s % 26) % 26 + ord("a")).astype(jnp.uint8)
