"""Op-level error barriers.

TPU-native analog of the reference's ``check_launch(name)`` (sync +
``cudaGetLastError`` + abort, ``hw/hw1/programming/mp1-util.h:8-18``) and
``MPI_SAFE_CALL`` (``hw/hw5/programming/2dHeat.cpp:45-51``).  JAX device
errors surface lazily on materialization; ``check_op`` forces them at a named
point so failures carry the op label, like the reference's kernel names.
"""

from __future__ import annotations

import jax


class FrameworkError(RuntimeError):
    pass


def check_op(name: str, *arrays):
    """Block until ``arrays`` are ready; re-raise any device error with ``name``.

    Returns the arrays (single array unwrapped) so it can be used inline::

        out = check_op("gpu shift cypher", shift(x))
    """
    try:
        for a in arrays:
            jax.block_until_ready(a)
    except Exception as e:  # XlaRuntimeError et al.
        raise FrameworkError(f"error in {name}: {e}") from e
    if len(arrays) == 1:
        return arrays[0]
    return arrays
