"""Failure classification, bounded retry, and the kernel fallback ladder.

The reference's failure model is binary — ``check_launch`` aborts, or the
job is fine (``hw/hw1/programming/mp1-util.h:8-18``).  A production jax_graft
system needs the middle ground: a Pallas kernel that fails to lower on one
platform should *demote* to the XLA formulation of the same op, a transient
runtime error should retry with bounded deterministic backoff, and a NaN
blow-up should be recognized as numeric (retrying the same program is
pointless; rolling back to a checkpoint is not).  Three pieces:

- ``classify_failure`` buckets an exception as COMPILE (lowering/Mosaic/
  unsupported-op — deterministic, never retried on the same rung), NUMERIC
  (non-finite values — handled by checkpoint rollback, see
  ``core/checkpoint.run_with_checkpoints``), RESOURCE (an HBM
  RESOURCE_EXHAUSTED — retrying the same program refinds the same wall;
  the response is *shrinking*: halve the solve chunk / pipeline tile and
  retry, see ``core/admission.py``), or RUNTIME (everything else,
  including XlaRuntimeError and injected faults — retryable).  A fifth
  kind, WRONG_ANSWER, is never produced by classification — it is
  assigned by the conformance gate (``core/conformance.py``) when a rung
  returns finite-but-divergent results on its probe.
- ``RetryPolicy`` — bounded attempts with a deterministic geometric backoff
  (no jitter: CI reproducibility beats thundering-herd avoidance at this
  scale).
- ``with_fallback`` — run a ladder of (rung, thunk) candidates in order,
  consult the fault plan per rung (``faults.maybe_fail``), record every
  demotion through the structured trace log, and report which rung actually
  served the request.

Every guard here runs in host Python outside jit — zero device overhead,
and zero work at all when no faults are installed and the first rung holds.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum

from . import metrics
from .diag import failure_stage
from .errors import FrameworkError
from .faults import maybe_fail, maybe_fail_stage
from .trace import record_event


class FailureKind(str, Enum):
    COMPILE = "compile"
    RUNTIME = "runtime"
    NUMERIC = "numeric"
    RESOURCE = "resource"           # out of device memory: shrink, don't retry
    WRONG_ANSWER = "wrong_answer"   # conformance probe diverged: demote
    BREAKER_OPEN = "breaker_open"   # circuit open: routed around, not a crash


@dataclass
class Clock:
    """Injectable time source: ``now()`` (monotonic seconds) + ``sleep``.

    Every wall-time consumer in the serving/retry path takes one of these
    so tests substitute :class:`VirtualClock` and never sleep for real.
    """

    now: object = field(default=time.monotonic, repr=False)
    sleep: object = field(default=time.sleep, repr=False)


class VirtualClock:
    """Deterministic test clock: ``sleep`` advances ``now`` instantly."""

    def __init__(self, start: float = 0.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def sleep(self, seconds: float) -> None:
        self._t += max(0.0, float(seconds))

    def advance(self, seconds: float) -> None:
        self._t += float(seconds)


class NonFiniteError(ArithmeticError):
    """A finiteness guard tripped: the state contains NaN/Inf."""


# substrings (lowercased) marking a deterministic compile/lowering failure —
# retrying the identical program cannot succeed, but a different kernel
# formulation of the same op can
_COMPILE_MARKERS = ("mosaic", "lowering", "lower", "compil", "unsupported",
                    "unimplemented", "vmem", "mlir")
_NUMERIC_MARKERS = ("nan", "non-finite", "not finite", "overflow")
# runtime HBM exhaustion (XlaRuntimeError RESOURCE_EXHAUSTED and friends);
# compile-time VMEM over-budget stays COMPILE — a different kernel
# formulation can fix that, while no reformulation shrinks the arrays
_RESOURCE_MARKERS = ("resource_exhausted", "resource exhausted",
                     "out of memory", "out-of-memory")


def classify_failure(exc: BaseException) -> FailureKind:
    """COMPILE / NUMERIC / RESOURCE / RUNTIME bucket for a caught
    exception."""
    from .faults import InjectedResourceExhausted

    if isinstance(exc, (NonFiniteError, FloatingPointError, ZeroDivisionError)):
        return FailureKind.NUMERIC
    if isinstance(exc, InjectedResourceExhausted):
        return FailureKind.RESOURCE
    if isinstance(exc, FrameworkError) and exc.__cause__ is not None:
        return classify_failure(exc.__cause__)
    if isinstance(exc, NotImplementedError):
        return FailureKind.COMPILE
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _NUMERIC_MARKERS):
        return FailureKind.NUMERIC
    if any(m in msg for m in _RESOURCE_MARKERS):
        return FailureKind.RESOURCE
    if any(m in msg for m in _COMPILE_MARKERS):
        return FailureKind.COMPILE
    return FailureKind.RUNTIME


def all_finite(state) -> bool:
    """Finiteness guard over a pytree of arrays (host-side, outside jit)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(state):
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if not bool(np.asarray(jnp.all(jnp.isfinite(arr)))):
            return False
    return True


@dataclass
class RetryPolicy:
    """Bounded retry with deterministic geometric backoff.

    ``run(fn)`` retries only RUNTIME-classified failures (by default):
    compile failures are deterministic and numeric failures belong to the
    checkpoint-rollback path, so retrying either wastes device minutes.
    """

    max_retries: int = 2
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    retry_on: tuple = (FailureKind.RUNTIME,)
    sleep: object = field(default=time.sleep, repr=False)
    # when set, the clock's sleep wins over ``sleep`` — callers that already
    # hold an injectable Clock/VirtualClock pass it straight through
    clock: object = field(default=None, repr=False)

    def delays(self) -> list[float]:
        return [min(self.base_delay_s * self.multiplier ** i,
                    self.max_delay_s) for i in range(self.max_retries)]

    def _sleep(self, seconds: float) -> None:
        (self.clock.sleep if self.clock is not None else self.sleep)(seconds)

    def run(self, fn, op: str = "retry"):
        last = None
        for attempt, delay in enumerate([0.0] + self.delays()):
            if delay:
                self._sleep(delay)
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classify, then decide
                kind = classify_failure(e)
                last = e
                if kind not in self.retry_on or attempt >= self.max_retries:
                    raise
                metrics.counter("retry.attempts").inc()
                record_event("retry", op=op, attempt=attempt + 1,
                             kind=kind.value, error=type(e).__name__,
                             next_delay_s=self.delays()[attempt])
        raise last  # pragma: no cover — loop always returns or raises


@dataclass
class RungFailure:
    rung: str
    kind: FailureKind
    error: str
    message: str


@dataclass
class FallbackResult:
    """What ``with_fallback`` actually ran: the value, the serving rung,
    and every rung that failed on the way down the ladder."""

    value: object
    rung: str
    failures: list[RungFailure] = field(default_factory=list)

    @property
    def demoted(self) -> bool:
        return bool(self.failures)


@dataclass
class _BreakerState:
    state: str = "closed"       # closed | open | half-open
    failures: int = 0           # consecutive classified failures
    opened_at: float = 0.0
    transitions: int = 0        # total open events (observability)


class CircuitBreaker:
    """Per-(op, rung) circuit breaker layered on the fallback ladder.

    A rung that keeps failing burns a full classify-and-demote cycle on
    every request.  The breaker remembers: after ``threshold`` consecutive
    classified failures of ``(op, rung)`` the circuit *opens* and
    ``with_fallback`` routes around the rung without executing it (a
    ``rung-failed`` event with kind ``breaker_open``, not an exception).
    After ``cooldown_s`` (on the injectable clock) the next request is
    admitted as a *half-open probe*: success closes the circuit and the
    rung serves again, failure re-opens it for another cooldown.  While a
    probe is the admitted call, concurrent requests keep routing around —
    one probe at a time.

    Only execution failures trip the breaker; a conformance-gate rejection
    is deterministic and already cached by ``core/conformance.py``, so
    counting it here would be redundant.  State transitions emit
    ``breaker-open`` / ``breaker-half-open`` / ``breaker-close`` events
    and ``breaker.<transition>`` counters, so SLO reports and
    ``trace summary`` show the full arc.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 clock: Clock | None = None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock if clock is not None else Clock()
        self._states: dict[tuple[str, str], _BreakerState] = {}

    def _st(self, op: str, rung: str) -> _BreakerState:
        return self._states.setdefault((op, rung), _BreakerState())

    def state(self, op: str, rung: str) -> str:
        return self._st(op, rung).state

    def allow(self, op: str, rung: str) -> bool:
        """May this call execute ``(op, rung)``?  Advances open->half-open
        when the cooldown has elapsed (the admitted call is the probe)."""
        st = self._st(op, rung)
        if st.state == "closed":
            return True
        if st.state == "open":
            if self._clock.now() - st.opened_at >= self.cooldown_s:
                st.state = "half-open"
                metrics.counter("breaker.half_open").inc()
                record_event("breaker-half-open", op=op, rung=rung)
                return True
            return False
        # half-open: a probe is already in flight this cycle
        return False

    def record_failure(self, op: str, rung: str, kind: FailureKind) -> None:
        st = self._st(op, rung)
        if st.state == "half-open":
            # failed probe: straight back to open, fresh cooldown
            st.state = "open"
            st.opened_at = self._clock.now()
            st.transitions += 1
            metrics.counter("breaker.open").inc()
            record_event("breaker-open", op=op, rung=rung,
                         failures=st.failures, kind=kind.value)
            return
        st.failures += 1
        if st.state == "closed" and st.failures >= self.threshold:
            st.state = "open"
            st.opened_at = self._clock.now()
            st.transitions += 1
            metrics.counter("breaker.open").inc()
            record_event("breaker-open", op=op, rung=rung,
                         failures=st.failures, kind=kind.value)

    def record_success(self, op: str, rung: str) -> None:
        st = self._st(op, rung)
        if st.state == "half-open":
            record_event("breaker-close", op=op, rung=rung)
            metrics.counter("breaker.close").inc()
        st.state = "closed"
        st.failures = 0


def with_fallback(op: str, ladder, policy: RetryPolicy | None = None,
                  gate=None, breaker: CircuitBreaker | None = None,
                  ) -> FallbackResult:
    """Run the first rung of ``ladder`` (a sequence of ``(name, thunk)``)
    that succeeds, demoting down the ladder on failure.

    Per rung: the conformance ``gate`` is consulted first when given
    (``gate(name) -> bool`` — typically a closure over
    ``core/conformance.check``; a False verdict or a raising probe demotes
    with ``FailureKind.WRONG_ANSWER`` exactly like a rung exception), then
    the fault plan (``maybe_fail(f"{op}.{name}")`` — an injected failure
    demotes exactly like a real one), then the thunk runs (under
    ``policy`` when given, which retries transient RUNTIME failures
    *within* the rung before demoting).  A ``breaker`` (``CircuitBreaker``)
    is consulted before everything: a rung with an open circuit is routed
    around without executing (kind ``breaker_open``), and execution
    successes/failures feed its state machine.  Each failed rung emits a
    structured ``rung-failed`` event plus a stage-attributed
    ``kernel-failure`` forensics event (``core/diag.py`` decides the
    ``lower``/``compile``/``execute``/``conformance`` bucket from the
    exception's stage tag or message); the serving rung emits ``served``
    with ``demoted`` and the failure list, so capture logs show which
    kernel actually handled the request.  All-rungs-failed raises
    FrameworkError chained to the last failure.
    """
    failures: list[RungFailure] = []
    last: Exception | None = None
    for name, thunk in ladder:
        if breaker is not None and not breaker.allow(op, name):
            # open circuit: route around without executing — cheaper than a
            # guaranteed failure, and NOT counted as a fallback demotion
            # (nothing ran, nothing failed)
            failures.append(RungFailure(
                name, FailureKind.BREAKER_OPEN, "BreakerOpen",
                "circuit open for this rung; routed to next rung"))
            metrics.counter("breaker.skipped").inc()
            record_event("rung-failed", op=op, rung=name,
                         kind=FailureKind.BREAKER_OPEN.value,
                         error="BreakerOpen")
            continue
        if gate is not None:
            try:
                admitted = gate(name)
            except Exception as e:  # noqa: BLE001 — a crashed probe is a
                # rung failure: the rung cannot even run its probe problem
                kind = classify_failure(e)
                failures.append(RungFailure(name, kind, type(e).__name__,
                                            str(e)[:300]))
                metrics.counter("fallback.demotions").inc()
                record_event("rung-failed", op=op, rung=name,
                             kind=kind.value, error=type(e).__name__)
                # forensics: a raising probe usually died while building/
                # warming its probe program — the stage tag (or message
                # heuristics) says which phase, defaulting to conformance
                record_event("kernel-failure", op=op, kernel=name,
                             error=type(e).__name__,
                             stage=failure_stage(e, default="conformance"))
                last = e
                continue
            if not admitted:
                failures.append(RungFailure(
                    name, FailureKind.WRONG_ANSWER, "ConformanceFailed",
                    "probe output diverged from the reference rung"))
                metrics.counter("fallback.demotions").inc()
                record_event("rung-failed", op=op, rung=name,
                             kind=FailureKind.WRONG_ANSWER.value,
                             error="ConformanceFailed")
                record_event("kernel-failure", op=op, kernel=name,
                             error="ConformanceFailed", stage="conformance")
                continue
        try:
            maybe_fail(f"{op}.{name}")
            maybe_fail_stage(f"{op}.{name}", "execute")
            value = (thunk() if policy is None
                     else policy.run(thunk, op=f"{op}.{name}"))
        except Exception as e:  # noqa: BLE001 — every rung failure is data
            kind = classify_failure(e)
            failures.append(RungFailure(name, kind, type(e).__name__,
                                        str(e)[:300]))
            metrics.counter("fallback.demotions").inc()
            record_event("rung-failed", op=op, rung=name, kind=kind.value,
                         error=type(e).__name__)
            record_event("kernel-failure", op=op, kernel=name,
                         error=type(e).__name__, stage=failure_stage(e))
            if breaker is not None:
                breaker.record_failure(op, name, kind)
            last = e
            continue
        if breaker is not None:
            breaker.record_success(op, name)
        metrics.counter(f"served.{op}.{name}").inc()
        record_event("served", op=op, rung=name, demoted=bool(failures),
                     failed_rungs=[f.rung for f in failures])
        return FallbackResult(value, name, failures)
    raise FrameworkError(
        f"all {len(failures)} rungs of {op} failed: "
        + "; ".join(f"{f.rung}[{f.kind.value}] {f.error}" for f in failures)
    ) from last
