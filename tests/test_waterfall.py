"""Cross-fleet request waterfalls (ISSUE 19): clock-offset estimation,
waterfall reconstruction/alignment, tail-sampling determinism, and the
`trace waterfall` / Chrome-flow surfacing.

The load-bearing invariants: the Cristian midpoint estimator never lies
about its uncertainty (|estimate - true offset| <= err, whatever the
path asymmetry or jitter), hop ordering on the reconstructed waterfall
holds once per-pid timestamps are shifted through the clock-offset peer
graph, and the tail sampler's kept-trace set is a pure function of the
request outcomes (same trace id + same SLO outcomes => same kept set).
"""

import json
import random
import threading
import time

import pytest

from cme213_tpu import top_cli, trace_cli
from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core.collector import Collector
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.core.trace import ClockSync


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.flush_sink()
    trace.clear_events()
    trace._TAIL_BUFFERS.clear()
    metrics.reset()
    yield
    trace.flush_sink()
    trace.clear_events()
    trace._TAIL_BUFFERS.clear()
    metrics.reset()
    faults.reset()


# ----------------------------------------------------- clock estimation

def test_clocksync_symmetric_exchange_recovers_offset():
    cs = ClockSync()
    # peer clock 250 ms ahead; symmetric 2 ms each way
    off, err = cs.update(10.0, 10.002 + 0.250, 10.004)
    assert off == pytest.approx(250.0)
    assert err == pytest.approx(2.0)
    assert cs.rtt_ms == pytest.approx(4.0)
    assert cs.samples == 1


def test_clocksync_bound_holds_under_asymmetric_jitter():
    """|estimate - true| <= err after every EWMA fold, driven from a
    VirtualClock with deterministic asymmetric delays."""
    rng = random.Random(213)
    true_off_s = -0.075  # peer clock 75 ms behind
    clk = VirtualClock(start=100.0)
    cs = ClockSync()
    for _ in range(50):
        t0 = clk.now()
        clk.advance(rng.uniform(0.001, 0.005))      # request leg
        t_remote = clk.now() + true_off_s           # peer stamps its clock
        clk.advance(rng.uniform(0.0005, 0.012))     # slower, jittery reply
        t1 = clk.now()
        off, err = cs.update(t0, t_remote, t1)
        assert abs(off - true_off_s * 1e3) <= err + 1e-9
        clk.advance(0.01)
    assert cs.samples == 50
    # converged well inside the single-sample worst case
    assert cs.err_ms < 8.5


def test_clocksync_ewma_damps_an_rtt_spike():
    cs = ClockSync()
    for i in range(5):
        t0 = float(i)
        cs.update(t0, t0 + 0.001 + 0.050, t0 + 0.002)  # clean: +50 ms
    before = cs.offset_ms
    # one wildly asymmetric 100 ms round trip
    cs.update(10.0, 10.099 + 0.050, 10.100)
    assert abs(cs.offset_ms - before) < 25.0  # alpha-damped, not adopted
    assert abs(cs.offset_ms - 50.0) <= cs.err_ms


# ------------------------------------------------ waterfall reconstruction

def _rec(event, span, sid, parent, pid, t, trace_id="T", **tags):
    return {"event": event, "span": span, "id": sid, "parent": parent,
            "pid": pid, "rank": None, "incarnation": 0, "trace": trace_id,
            "t": t, **tags}


def _skewed_fleet_events():
    """One requeued request across three pids with big clock skew.

    Front tier (pid 200) is the reference.  The client's clock (pid 100)
    runs 2 s AHEAD — raw timestamps would order the client hop after
    everything it caused — and the replica's (pid 300) runs 500 ms
    behind.  True on-the-front-tier times are encoded below; each
    record's ``t`` is in its own pid's skewed clock.
    """
    c = 2.0     # client clock = front + 2.0 s
    r = -0.5    # replica clock = front - 0.5 s
    evs = [
        {"event": "clock-offset", "pid": 100, "rank": None,
         "incarnation": 0, "trace": "T", "t": 0.9 + c, "peer_pid": 200,
         "offset_ms": -2000.0, "err_ms": 1.5, "rtt_ms": 3.0, "samples": 5},
        {"event": "clock-offset", "pid": 200, "rank": None,
         "incarnation": 0, "trace": "T", "t": 0.95, "peer_pid": 300,
         "offset_ms": -500.0, "err_ms": 2.0, "rtt_ms": 4.0, "samples": 3},
        _rec("span-begin", "serve.hop.client", "c.1", None, 100,
             1.000 + c, rid=7),
        _rec("span-begin", "serve.hop.route", "f.1", "c.1", 200,
             1.010, rid=3),
        _rec("span-begin", "serve.hop.dispatch", "f.2", "f.1", 200,
             1.012, rid=3),
        _rec("span-end", "serve.hop.dispatch", "f.2", "f.1", 200,
             1.015, ms=3.0, rid=3, requeued=True),
        _rec("span-begin", "serve.hop.requeue", "f.3", "f.1", 200,
             1.015, rid=3),
        _rec("span-end", "serve.hop.requeue", "f.3", "f.1", 200,
             1.030, ms=15.0, rid=3),
        _rec("span-begin", "serve.hop.dispatch", "f.4", "f.1", 200,
             1.030, rid=3),
        _rec("span-begin", "serve.hop.replica", "r.1", "f.1", 300,
             1.032 + r, rid=1),
        _rec("span-begin", "serve.hop.run", "r.2", "r.1", 300,
             1.035 + r, rid=1),
        _rec("span-end", "serve.hop.run", "r.2", "r.1", 300,
             1.045 + r, ms=10.0, rid=1),
        _rec("span-end", "serve.hop.replica", "r.1", "f.1", 300,
             1.050 + r, ms=18.0, rid=1),
        _rec("span-end", "serve.hop.dispatch", "f.4", "f.1", 200,
             1.052, ms=22.0, rid=3),
        _rec("span-end", "serve.hop.route", "f.1", "c.1", 200,
             1.055, ms=45.0, rid=3, requeues=1),
        _rec("span-end", "serve.hop.client", "c.1", None, 100,
             1.060 + c, ms=60.0, rid=7),
    ]
    return evs


def test_waterfall_aligns_hops_across_skewed_clocks():
    doc = trace_cli.build_waterfalls(_skewed_fleet_events(), "3")
    assert len(doc["trees"]) == 1
    tree = doc["trees"][0]
    assert tree["ref_pid"] == 200          # the front tier anchors time
    assert tree["pids"] == [100, 200, 300]
    assert tree["trace_ids"] == ["T"]
    hops = {h["id"]: h for h in tree["hops"]}
    assert len(hops) == 7
    # depths follow the parent chain
    assert [hops[i]["depth"] for i in ("c.1", "f.1", "f.2", "r.1", "r.2")] \
        == [0, 1, 2, 2, 3]
    # shifted starts land on the true front-tier ordering despite the
    # client's +2 s and the replica's -0.5 s clocks
    assert hops["c.1"]["start_ms"] == pytest.approx(0.0)
    assert hops["f.1"]["start_ms"] == pytest.approx(10.0)
    assert hops["r.1"]["start_ms"] == pytest.approx(32.0)
    assert hops["r.2"]["start_ms"] == pytest.approx(35.0)
    # every child starts no earlier than its parent minus the combined
    # alignment uncertainty of the two pids involved
    for h in tree["hops"]:
        parent = hops.get(h["parent"])
        if parent is not None:
            slack = h["err_ms"] + parent["err_ms"] + 1e-6
            assert h["start_ms"] >= parent["start_ms"] - slack
    # uncertainty is per-link: front-tier hops are exact, remote hops
    # carry their sync error
    assert hops["f.1"]["err_ms"] == 0.0
    assert hops["c.1"]["err_ms"] == pytest.approx(1.5)
    assert hops["r.2"]["err_ms"] == pytest.approx(2.0)
    assert all(h["aligned"] for h in tree["hops"])
    # the requeue shows up where the zero-loss story needs it
    assert hops["f.2"]["requeued"] is True
    assert hops["f.3"]["span"] == "serve.hop.requeue"


def test_waterfall_unsynced_pid_is_flagged_not_shifted():
    evs = [e for e in _skewed_fleet_events()
           if e["event"] != "clock-offset" or e["pid"] != 200]
    doc = trace_cli.build_waterfalls(evs, "3")
    hops = {h["id"]: h for h in doc["trees"][0]["hops"]}
    assert hops["r.1"]["aligned"] is False  # no path to the reference
    assert hops["c.1"]["aligned"] is True


def test_waterfall_rid_domains_yield_separate_trees():
    """Rids restart per process: one number can name different requests
    in different tiers.  Distinct parent-chain roots stay distinct."""
    evs = _skewed_fleet_events() + [
        _rec("span-begin", "serve.hop.client", "c2.1", None, 100,
             5.0, trace_id="T2", rid=3),
        _rec("span-end", "serve.hop.client", "c2.1", None, 100,
             5.01, trace_id="T2", ms=10.0, rid=3),
    ]
    doc = trace_cli.build_waterfalls(evs, "3")
    assert len(doc["trees"]) == 2
    traces = {tuple(t["trace_ids"]) for t in doc["trees"]}
    assert traces == {("T",), ("T2",)}


def test_waterfall_matches_by_trace_id_too():
    doc = trace_cli.build_waterfalls(_skewed_fleet_events(), "T")
    assert len(doc["trees"]) == 1


def test_waterfall_open_hop_survives_reconstruction():
    """A hop whose end record never landed (SIGKILLed replica) renders
    as open instead of vanishing."""
    evs = [e for e in _skewed_fleet_events()
           if not (e.get("id") == "r.1" and e["event"] == "span-end")]
    doc = trace_cli.build_waterfalls(evs, "3")
    hops = {h["id"]: h for h in doc["trees"][0]["hops"]}
    assert hops["r.1"]["open"] is True and hops["r.1"]["dur_ms"] is None
    assert hops["r.2"]["open"] is False


def test_waterfall_cli_text_and_json(tmp_path, capsys):
    path = tmp_path / "t.jsonl"
    path.write_text("".join(json.dumps(e) + "\n"
                            for e in _skewed_fleet_events()))
    assert trace_cli.main(["waterfall", "3", str(path)]) == 0
    text = capsys.readouterr().out
    assert "serve.hop.client" in text and "REQUEUED" in text
    assert "±1.5" in text.replace("1.500", "1.5") or "±1.500" in text

    assert trace_cli.main(["waterfall", "3", "--json", str(path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["trees"][0]["pids"] == [100, 200, 300]

    assert trace_cli.main(["waterfall", "no-such-rid", str(path)]) == 1


# ------------------------------------------------------- chrome flow export

def test_export_emits_flow_arrows_across_pid_lanes():
    doc = trace_cli.to_chrome_trace(_skewed_fleet_events())
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
    # 7 closed hops in one request: one s, one f, five t steps
    assert [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])] \
        == ["s", "t", "t", "t", "t", "t", "f"]
    assert len({e["id"] for e in flows}) == 1
    assert flows[-1].get("bp") == "e" or \
        [e for e in flows if e["ph"] == "f"][0]["bp"] == "e"


def test_export_single_hop_request_gets_no_flow():
    evs = [
        _rec("span-begin", "serve.hop.client", "c.9", None, 100, 1.0, rid=9),
        _rec("span-end", "serve.hop.client", "c.9", None, 100, 1.1,
             ms=100.0, rid=9),
    ]
    doc = trace_cli.to_chrome_trace(evs)
    assert not [e for e in doc["traceEvents"] if e.get("cat") == "flow"]


# -------------------------------------------------- tail-sampling determinism

#: synthetic request outcomes: rid -> (status, latency_ms, requeues)
_OUTCOMES = [
    ("ok", 10.0, 0), ("ok", 80.0, 0), ("failed", 12.0, 0),
    ("ok", 15.0, 0), ("ok", 22.0, 1), ("shed", 1.0, 0),
    ("ok", 9.0, 0), ("ok", 49.9, 0), ("ok", 50.1, 0), ("ok", 30.0, 0),
]


def _drive_tail_once():
    trace.clear_events()
    for rid, (status, lat, requeues) in enumerate(_OUTCOMES):
        hop = trace.begin_span("serve.hop.client", tail_key=f"c1.{rid}",
                               head_key=rid, rid=rid)
        hop.end(status=status)
        reason = trace.tail_keep_reason(status=status, latency_ms=lat,
                                        requeues=requeues)
        trace.tail_decide(hop.tail_key, keep=reason is not None,
                          reason=reason or "ok")
    assert trace.tail_pending() == 0
    kept = []
    for e in trace.events("span-end"):
        if e.get("span") == "serve.hop.client":
            kept.append((e["rid"], e["status"]))
    return sorted(kept)


def test_tail_kept_set_is_deterministic(monkeypatch):
    """Same trace id + same SLO outcomes => identical kept-trace set,
    run to run — including the hashed head-sampling bypass."""
    monkeypatch.setenv(trace.TRACE_TAIL_ENV, "1")
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV,
                       json.dumps({"trace_id": "T-fixed"}))
    monkeypatch.setenv(trace.TRACE_HEAD_RATE_ENV, "0.3")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "50")
    first = _drive_tail_once()
    second = _drive_tail_once()
    assert first == second
    kept_rids = {rid for rid, _ in first}
    # SLO violators always survive: failed, shed, requeued, slow (>50)
    assert {1, 2, 4, 5, 8} <= kept_rids
    # the happy path is actually shed — not everything is kept
    assert len(kept_rids) < len(_OUTCOMES)


def test_tail_head_rate_zero_drops_every_happy_path(monkeypatch):
    monkeypatch.setenv(trace.TRACE_TAIL_ENV, "1")
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV,
                       json.dumps({"trace_id": "T-fixed"}))
    monkeypatch.delenv(trace.TRACE_HEAD_RATE_ENV, raising=False)
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "50")
    kept = {rid for rid, _ in _drive_tail_once()}
    assert kept == {1, 2, 4, 5, 8}
    snap = metrics.snapshot()["counters"]
    assert snap["trace.sampling.kept"] == 5
    assert snap["trace.sampling.dropped"] == 5
    assert snap["trace.sampling.kept.slow"] == 2
    assert snap["trace.sampling.kept.failed"] == 1
    assert snap["trace.sampling.kept.shed"] == 1
    assert snap["trace.sampling.kept.requeued"] == 1


# -------------------------------------------------- slowest-traces ribbon

def test_collector_tracks_slowest_request_hops(tmp_path, capsys):
    path = tmp_path / "s.jsonl"
    evs = []
    for rid in range(12):
        evs.append(_rec("span-end", "serve.hop.client", f"c.{rid}", None,
                        100, 1.0 + rid * 0.01, ms=float(10 + rid * 10),
                        rid=rid, status="ok",
                        requeues=1 if rid == 11 else 0))
    path.write_text("".join(json.dumps(e) + "\n" for e in evs))
    coll = Collector([str(path)])
    coll.poll()
    state = coll.state()
    ribbon = state["slowest_traces"]
    assert len(ribbon) == Collector._SLOWEST_N
    assert [e["rid"] for e in ribbon] == [11, 10, 9, 8, 7, 6, 5, 4]
    assert ribbon[0]["ms"] == 120.0 and ribbon[0]["requeues"] == 1
    assert ribbon[0]["trace"] == "T"  # the waterfall join key rides along

    top_cli.render_top(state)
    text = capsys.readouterr().out
    assert "slowest requests" in text
    assert "rid=11" in text and "1 requeue(s)" in text


# ------------------------------------------------------------ e2e fleet arc

def _tolerant_load(path) -> list[dict]:
    """Parse a sink file skipping torn lines — a SIGKILLed replica may
    die mid-write, and this test wants its surviving records, not a
    parse verdict (``trace waterfall`` CI runs use intact files)."""
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "event" in rec:
                out.append(rec)
    return out


@pytest.mark.slow
def test_requeued_request_renders_one_aligned_waterfall(
        tmp_path, monkeypatch):
    """Two worker processes, SIGKILL one mid-batch: the requeued request
    renders as ONE waterfall tree spanning the front tier's pid and both
    replica incarnations' pids, with the requeue hop visible, one trace
    id, and the replica residency fitting inside the route hop within
    the clock-alignment error bounds."""
    from cme213_tpu.serve.fleet import Fleet
    from cme213_tpu.serve.loadgen import build_mix
    from cme213_tpu.serve.transport import TransportClient

    monkeypatch.setenv("CME213_FAULTS", "replica-kill:1:1")
    monkeypatch.setenv(trace.TRACE_FILE_ENV,
                       str(tmp_path / "wf-{rank}.jsonl"))
    fleet = Fleet(replicas=2, mix="cipher", warm_requests=2,
                  max_batch=4).start()
    try:
        specs = build_mix("cipher", 16, seed=19, tenants=2)
        results = [None] * len(specs)

        def client(i, spec):
            with TransportClient(fleet.addr) as c:
                results[i] = c.solve(spec.op, spec.payload,
                                     tenant=spec.tenant)

        threads = [threading.Thread(target=client, args=(i, s),
                                    daemon=True)
                   for i, s in enumerate(specs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert all(r is not None and r.status == "ok" for r in results)
        requeued = trace.events("request-requeued")
        assert requeued
    finally:
        fleet.close()
    trace.flush_sink()

    # merge this process's records (client + front tier share the test
    # pid) with every replica sink, torn tails tolerated
    events = [dict(e) for e in trace.events()]
    for p in sorted(tmp_path.glob("wf-*.jsonl")):
        events.extend(_tolerant_load(p))

    rid = str(requeued[0]["rid"])
    doc = trace_cli.build_waterfalls(events, rid)
    # rid domains can collide (another requeued request may carry the
    # same number in a different tier): the request we asked about is
    # the tree whose requeue hop itself bears this rid
    trees = [t for t in doc["trees"]
             if any(h["span"] == "serve.hop.requeue"
                    and str(h["rid"]) == rid for h in t["hops"])]
    assert len(trees) == 1, "the requeued rid must render as one tree"
    tree = trees[0]
    assert len(tree["trace_ids"]) == 1
    assert len(tree["hops"]) >= 5
    assert len(tree["pids"]) >= 3  # front/client pid + both incarnations
    hops = {h["id"]: h for h in tree["hops"]}
    by_span = {}
    for h in tree["hops"]:
        by_span.setdefault(h["span"], []).append(h)
    client_hop = by_span["serve.hop.client"][0]
    route = by_span["serve.hop.route"][0]
    # the client observed everything the front tier did
    assert client_hop["dur_ms"] >= route["dur_ms"]
    # the killed replica's hop survives as an open span on its own pid
    assert any(h["open"] for h in by_span.get("serve.hop.replica", []))
    # the served replica attempt fits inside the route hop within the
    # accumulated clock-alignment error (plus scheduling slack)
    served = [h for h in by_span.get("serve.hop.replica", [])
              if not h["open"]]
    assert served
    for h in served:
        assert h["aligned"], "replica pid must be clock-synced"
        slack = h["err_ms"] + route["err_ms"] + 20.0
        assert h["start_ms"] >= route["start_ms"] - slack
        assert (h["start_ms"] + h["dur_ms"]
                <= route["start_ms"] + route["dur_ms"] + slack)


@pytest.mark.slow
def test_tail_sampling_keeps_under_ten_percent_on_clean_fleet(monkeypatch):
    """Tail sampling ON, healthy 2-replica fleet, no SLO violations: the
    front tier + client drop (almost) every trace while every request
    still succeeds — always-on tracing at ~zero sink cost."""
    from cme213_tpu.serve.fleet import Fleet
    from cme213_tpu.serve.loadgen import build_mix
    from cme213_tpu.serve.transport import TransportClient

    monkeypatch.setenv(trace.TRACE_TAIL_ENV, "1")
    monkeypatch.delenv(trace.TRACE_HEAD_RATE_ENV, raising=False)
    fleet = Fleet(replicas=2, mix="cipher", warm_requests=2,
                  max_batch=4).start()
    try:
        before = metrics.snapshot()
        specs = build_mix("cipher", 30, seed=7, tenants=2)
        with TransportClient(fleet.addr) as c:
            for spec in specs:
                res = c.solve(spec.op, spec.payload, tenant=spec.tenant)
                assert res.status == "ok"
        after = metrics.snapshot()
    finally:
        fleet.close()
    d = metrics.delta(before, after)["counters"]
    kept = d.get("trace.sampling.kept", 0)
    dropped = d.get("trace.sampling.dropped", 0)
    assert kept + dropped >= 60  # client + front tier both decided
    assert kept / (kept + dropped) < 0.10
    assert trace.tail_pending() == 0
