"""Time one heat-kernel config at 4000^2 order 8 on the TPU: 
usage: tpu_time_one.py {xla | pallas TILE | multi K TILE} [iters]"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time
import jax, jax.numpy as jnp, numpy as np
from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat
from cme213_tpu.ops.stencil_pallas import run_heat_multistep, run_heat_pallas

p = SimParams(nx=4000, ny=4000, order=8, iters=1000)
u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
mode = sys.argv[1]
iters = int(sys.argv[-1]) if sys.argv[-1].isdigit() and len(sys.argv) > (3 if mode != "xla" else 2) + (1 if mode == "multi" else 0) else 200
if mode == "xla":
    fn = lambda u, it: run_heat(u, it, p.order, p.xcfl, p.ycfl)
elif mode == "pallas":
    t = int(sys.argv[2])
    fn = lambda u, it: run_heat_pallas(u, it, p.order, p.xcfl, p.ycfl, tile_y=t)
else:
    k, t = int(sys.argv[2]), int(sys.argv[3])
    fn = lambda u, it: run_heat_multistep(u, it, p.order, p.xcfl, p.ycfl, p.bc, k=k, tile_y=t)
jax.block_until_ready(fn(jax.device_put(u0), 8))
u = jax.device_put(u0)
t0 = time.perf_counter()
jax.block_until_ready(fn(u, iters))
dt = (time.perf_counter() - t0) / iters
print(f"{' '.join(sys.argv[1:])}: {dt*1e3:.3f} ms/iter, {2*4*4000*4000/dt/1e9:.1f} GB/s eff", flush=True)
