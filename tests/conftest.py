"""Test harness: run everything on a fake 8-device CPU mesh.

This preserves the reference's distributed-testing methodology — "compare an
N-rank result against a 1-rank result" (hw5 handout §5.1, SURVEY §4.4/§4.8) —
without cluster hardware, exactly as SURVEY §4.8 prescribes:
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``.

Must run before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
