"""Host golden models (numpy) — the "embedded golden model" half of the
reference's dual-implementation testing strategy (SURVEY §4.1).

Each device pipeline in ``apps/`` has a serial/host model here, mirroring the
reference: ``host_shift_cypher`` (``hw/hw1/programming/cipher.cu:53-60``),
``host_graph_propagate/iterate`` (``pagerank.cu:45-67``), ``cpuComputation``
stencils (``hw/hw2/programming/2dHeat.cu:361-428``), the OpenMP CPU golden for
the segmented scan (``hw/hw_final/programming/fp.cu:130-152``), and
``std::sort`` goldens for hw4.
"""

from __future__ import annotations

import numpy as np

from ..ops.stencil import BORDER_FOR_ORDER, STENCIL_COEFFS


def host_shift_cipher(data: np.ndarray, shift: int) -> np.ndarray:
    """Wrapping unsigned-char shift (cipher.cu:53-60)."""
    assert data.dtype == np.uint8
    return (data + np.uint8(shift)).astype(np.uint8)


def host_heat(u: np.ndarray, iters: int, order: int, xcfl, ycfl) -> np.ndarray:
    """Vectorized numpy heat iteration, same expression order as the device
    stencil (so float goldens stay within a few ULPs)."""
    coeffs = STENCIL_COEFFS[order]
    b = BORDER_FOR_ORDER[order]
    u = np.array(u, copy=True)
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    xcfl = u.dtype.type(xcfl)
    ycfl = u.dtype.type(ycfl)
    for _ in range(iters):
        center = u[b:-b, b:-b]
        accx = np.zeros_like(center)
        accy = np.zeros_like(center)
        for k, c in enumerate(coeffs):
            c = u.dtype.type(c)
            accx = accx + c * u[b:b + ny, k:k + nx]
            accy = accy + c * u[k:k + ny, b:b + nx]
        u[b:-b, b:-b] = center + xcfl * accx + ycfl * accy
    return u


def host_graph_propagate(indices: np.ndarray, edges: np.ndarray,
                         rank_in: np.ndarray, inv_deg: np.ndarray) -> np.ndarray:
    """One PageRank sweep: CSR gather + ``0.5/n + 0.5·Σ rank·inv_deg``
    (pagerank.cu:45-56), float32 accumulation in the same left-to-right
    per-row order as the serial loop (``np.add.reduceat`` is sequential
    within each segment).  Rows are never empty (degrees ≥ 1 by
    construction), so reduceat's empty-slice caveat doesn't apply."""
    n = rank_in.shape[0]
    contrib = (rank_in[edges] * inv_deg[edges]).astype(np.float32)
    sums = np.add.reduceat(contrib, indices[:-1].astype(np.int64))
    return (np.float32(0.5) / np.float32(n)
            + np.float32(0.5) * sums).astype(np.float32)


def host_graph_iterate(indices, edges, rank0, inv_deg, nr_iterations: int):
    """Ping-pong iteration (pagerank.cu:59-67); nr_iterations must be even."""
    assert nr_iterations % 2 == 0
    a = np.array(rank0, copy=True)
    for _ in range(nr_iterations):
        a = host_graph_propagate(indices, edges, a, inv_deg)
    return a


def host_segmented_scan(values: np.ndarray, seg_starts: np.ndarray) -> np.ndarray:
    """Inclusive segmented sum scan; one serial cumsum per segment
    (fp.cu:130-152 CPU golden)."""
    out = np.empty_like(values)
    n = values.shape[0]
    p = seg_starts.shape[0]
    for si in range(p):
        lo = seg_starts[si]
        hi = seg_starts[si + 1] if si + 1 < p else n
        out[lo:hi] = np.cumsum(values[lo:hi], dtype=values.dtype)
    return out


def host_spmv_scan(a: np.ndarray, seg_starts: np.ndarray, xx: np.ndarray,
                   iters: int, dtype=None) -> np.ndarray:
    """Iterated multiply + segmented scan, ``a ← segscan(a·xx)`` N times
    (fp.cu:130-152; double-precision external checker
    ``aux/reference_spMVscan-released.cu:65-144``)."""
    if dtype is not None:
        a = a.astype(dtype)
        xx = xx.astype(dtype)
    a = np.array(a, copy=True)
    for _ in range(iters):
        a = host_segmented_scan(a * xx, seg_starts)
    return a


def host_sort(keys: np.ndarray) -> np.ndarray:
    """``std::sort`` golden (mergesort.cpp:167-172, radixsort.cpp:180-186)."""
    return np.sort(keys, kind="stable")
