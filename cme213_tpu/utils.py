"""Utility layer (alias module).

Canonical home: ``cme213_tpu.core`` (timers, ULP comparison, error barriers,
checkpointing, tracing).
"""

from .core import *  # noqa: F401,F403
from .core import checkpoint, trace  # noqa: F401
from .core import __all__  # noqa: F401
