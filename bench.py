"""Headline benchmark: hw2-class 2-D heat stencil, order 8, 4000×4000, f32.

Mirrors the reference's measurement: hot iteration loop, effective bandwidth
= (1 read + 1 write) × 4 B × nx × ny per iteration (the accounting behind
``hw/hw2/programming/data/data.ods``; see BASELINE.md).  Baseline to beat:
shared-memory order-8 kernel at 4000² on a GTX 580 = **23.97 GB/s**.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Per-phase detail goes to stderr.

The measurement runs in a child process with a watchdog: if the TPU tunnel
is unreachable (device init can hang inside PJRT client creation, where
Python signal handlers can't fire), the parent times out, retries, and
finally emits a zero-valued line instead of hanging the driver.
"""

import json
import os
import subprocess
import sys

BASELINE_GBS = 23.97  # hw2 shared-memory order-8 4000² float (BASELINE.md)

_CHILD_FLAG = "--run-measurement"


def measure() -> None:
    import time

    import jax
    import jax.numpy as jnp

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat

    nx = ny = 4000
    order = 8
    iters_timed = 200

    params = SimParams(nx=nx, ny=ny, order=order, iters=1000)
    u0 = make_initial_grid(params, dtype=jnp.float32)
    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    u = jax.device_put(u0, dev)
    w = run_heat(u, 10, order, params.xcfl, params.ycfl)  # compile/warmup
    w.block_until_ready()

    u = jax.device_put(u0, dev)
    start = time.perf_counter()
    out = run_heat(u, iters_timed, order, params.xcfl, params.ycfl)
    out.block_until_ready()
    elapsed = time.perf_counter() - start

    ms_per_iter = elapsed * 1e3 / iters_timed
    bytes_per_iter = 2 * 4 * nx * ny          # read prev + write next, f32
    gbs = bytes_per_iter / (elapsed / iters_timed) / 1e9
    # order-8 per point: 2 axes × (9 mul + 8 add) + combine (2 mul, 2 add)
    flops_per_iter = 38 * nx * ny
    gfs = flops_per_iter / (elapsed / iters_timed) / 1e9
    print(f"{ms_per_iter:.3f} ms/iter, {gbs:.2f} GB/s eff, {gfs:.2f} GF/s",
          file=sys.stderr)

    print(json.dumps({
        "metric": "heat2d stencil order-8 4000x4000 f32 effective bandwidth",
        "value": round(gbs, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbs / BASELINE_GBS, 3),
    }))


def main() -> None:
    if _CHILD_FLAG in sys.argv:
        measure()
        return
    for attempt in range(3):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), _CHILD_FLAG],
                timeout=900, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"attempt {attempt + 1}: timed out (TPU tunnel stuck?)",
                  file=sys.stderr)
            continue
        sys.stderr.write(proc.stderr)
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        if proc.returncode == 0 and lines:
            print(lines[-1])
            return
        print(f"attempt {attempt + 1}: exit {proc.returncode}",
              file=sys.stderr)
    print(json.dumps({
        "metric": "heat2d stencil order-8 4000x4000 f32 effective bandwidth "
                  "(DEVICE UNAVAILABLE)",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
