"""Device-health doctor CLI — ``python -m cme213_tpu doctor [calibrate]``.

The runnable face of ``core/diag.py``:

- ``doctor [--json] [--timeout S]`` runs the staged health ladder
  (enumerate → memory → timed liveness) and exits 0 when the device is
  healthy, 1 when any required stage failed or timed out.  ``--json``
  prints the structured report (what ``bench.py`` banks into a capture
  tail on an unreachable round, and what the tier-1 CI gate validates);
  the text form prints one line per stage.  When ``CME213_DIAG_DIR`` is
  set the report is also appended to the persistent health-history ring,
  so "the device has been flaky since Tuesday" is answerable from
  artifacts.

- ``doctor calibrate [--json]`` runs the predicted-vs-measured
  attribution table for the flagship ops (spmv/heat/sort) on the local
  backend: the ``core/roofline.py`` cost model each bench row is graded
  with, against XLA's own ``compiled.cost_analysis()``.  Report-only
  (exit 0): calibration drift is a diagnosis, not a failure — the
  dispatch-time guard (``CME213_DIAG_ATTRIBUTION``) is what turns
  drift into ``attribution-mismatch`` events.
"""

from __future__ import annotations

import argparse
import json
import sys


def _render_health(report: dict, out) -> None:
    verdict = "HEALTHY" if report["healthy"] else "UNHEALTHY"
    out.write(f"doctor: device {verdict} "
              f"(platform {report.get('platform')}, "
              f"{report.get('device_count')} device(s))\n")
    for st in report["stages"]:
        status = "ok" if st["ok"] else (
            "TIMEOUT" if st.get("timed_out") else "FAIL")
        line = f"  {st['stage']:<10} {status:<8} {st['ms']:>9.2f} ms"
        if not st["ok"]:
            line += f"  {st.get('detail')}"
        elif st["stage"] == "liveness":
            line += f"  probe {(st['detail'] or {}).get('probe_ms')} ms"
        out.write(line + "\n")
    if report.get("ring_path"):
        out.write(f"  history ring: {report['ring_path']}\n")


def _render_calibration(rows: list, out) -> None:
    out.write(f"calibration: {len(rows)} program(s) "
              f"(roofline model vs XLA cost_analysis)\n")
    out.write(f"  {'op.rung [shape]':<34} {'metric':<7} {'predicted':>12} "
              f"{'measured':>12} {'ratio':>7}  verdict\n")
    for r in rows:
        label = f"{r.get('op')}.{r.get('rung')} [{r.get('shape_class')}]"
        if "error" in r:
            out.write(f"  {label:<34} probe failed: {r['error']}\n")
            continue
        for metric in ("flops", "bytes"):
            ratio = r.get(f"{metric}_ratio")
            measured = r.get(f"measured_{metric}")
            verdict = ("no signal" if ratio is None
                       else "MISMATCH" if metric in r["mismatches"]
                       else "ok")
            out.write(
                f"  {label:<34} {metric:<7} "
                f"{r[f'predicted_{metric}']:>12.3g} "
                f"{(measured if measured is not None else float('nan')):>12.3g} "
                f"{(ratio if ratio is not None else float('nan')):>7.3g}"
                f"  {verdict}\n")
            label = ""


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    calibrating = bool(argv) and argv[0] == "calibrate"
    if calibrating:
        argv = argv[1:]
    ap = argparse.ArgumentParser(
        prog=("python -m cme213_tpu doctor"
              + (" calibrate" if calibrating else "")),
        description=("roofline cost models vs XLA cost_analysis"
                     if calibrating else
                     "staged device-health ladder (exit 1 when unhealthy)"))
    ap.add_argument("--json", action="store_true",
                    help="print the structured report instead of text")
    if not calibrating:
        ap.add_argument("--timeout", type=float, default=None, metavar="S",
                        help="per-stage watchdog budget in seconds "
                             "(default CME213_DOCTOR_TIMEOUT_S or 30)")
    args = ap.parse_args(argv)

    from .core import diag, flight, trace

    flight.install_from_env()
    if calibrating:
        rows = diag.calibrate()
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
        else:
            _render_calibration(rows, sys.stdout)
        trace.flush_sink()
        return 0

    report = diag.health_report(timeout_s=args.timeout)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        _render_health(report, sys.stdout)
    trace.flush_sink()
    return 0 if report["healthy"] else 1


if __name__ == "__main__":
    sys.exit(main())
