"""Headline benchmark: hw2-class 2-D heat stencil, order 8, 4000×4000, f32.

Mirrors the reference's measurement: hot iteration loop, effective bandwidth
= (1 read + 1 write) × 4 B × nx × ny per iteration (the accounting behind
``hw/hw2/programming/data/data.ods``; see BASELINE.md).  Baseline to beat:
shared-memory order-8 kernel at 4000² on a GTX 580 = **23.97 GB/s**.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Per-phase detail goes to stderr.

The measurement runs in a child process with a watchdog: if the TPU tunnel
is unreachable (device init can hang inside PJRT client creation, where
Python signal handlers can't fire), the parent times out, retries, and
finally emits a zero-valued line instead of hanging the driver.
"""

import json
import os
import subprocess
import sys

BASELINE_GBS = 23.97  # hw2 shared-memory order-8 4000² float (BASELINE.md)

_CHILD_FLAG = "--run-measurement"


_PREFLIGHT_EXIT = 42


def _preflight(seconds: float = 90.0) -> bool:
    """Run a trivial device op on a watchdog thread.  A wedged TPU tunnel
    hangs inside PJRT client creation where Python signals can't fire, so
    the check runs in a daemon thread and the caller exits if it never
    returns."""
    import threading

    done = threading.Event()

    def probe():
        import jax
        import jax.numpy as jnp

        (jnp.ones((8, 8)) * 2).block_until_ready()
        done.set()

    threading.Thread(target=probe, daemon=True).start()
    return done.wait(seconds)


def measure() -> None:
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat, run_heat_conv
    from cme213_tpu.ops.stencil_pallas import run_heat_multistep, run_heat_pallas

    nx = ny = 4000
    order = 8
    iters_timed = 200

    params = SimParams(nx=nx, ny=ny, order=order, iters=1000)
    # Host copy: the heat loops donate their input buffer, and device_put of
    # an already-committed device array is a no-op returning the same buffer
    # — which the first donated call would delete out from under us.
    u0 = np.asarray(make_initial_grid(params, dtype=jnp.float32))
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"device: {dev}", file=sys.stderr)

    candidates = {
        "xla": lambda u, it: run_heat(u, it, order, params.xcfl, params.ycfl),
        "xla-conv": lambda u, it: run_heat_conv(
            u, it, order, params.xcfl, params.ycfl),
        "pallas": lambda u, it: run_heat_pallas(
            u, it, order, params.xcfl, params.ycfl, tile_y=200,
            interpret=not on_tpu),
        "pallas-k4": lambda u, it: run_heat_multistep(
            u, it, order, params.xcfl, params.ycfl, params.bc, k=4,
            tile_y=160, interpret=not on_tpu),
        "pallas-k8": lambda u, it: run_heat_multistep(
            u, it, order, params.xcfl, params.ycfl, params.bc, k=8,
            tile_y=80, interpret=not on_tpu),
    }
    if not on_tpu:  # interpret-mode pallas at 4000² would take forever
        candidates = {"xla": candidates["xla"]}

    bytes_per_iter = 2 * 4 * nx * ny          # read prev + write next, f32
    flops_per_iter = 38 * nx * ny  # 2×(9 mul+8 add) + combine (2 mul, 2 add)
    best_name, best_gbs = None, 0.0
    for name, fn in candidates.items():
        try:
            # warmup with the SAME iters: 'iters' is a static jit arg, so a
            # different count would leave compilation inside the timed bracket
            jax.block_until_ready(fn(jax.device_put(u0, dev), iters_timed))
            u = jax.device_put(u0, dev)
            start = time.perf_counter()
            jax.block_until_ready(fn(u, iters_timed))
            elapsed = time.perf_counter() - start
        except Exception as e:
            print(f"{name}: failed ({type(e).__name__}: {e})", file=sys.stderr)
            continue
        per_iter = elapsed / iters_timed
        gbs = bytes_per_iter / per_iter / 1e9
        gfs = flops_per_iter / per_iter / 1e9
        print(f"{name}: {per_iter * 1e3:.3f} ms/iter, {gbs:.2f} GB/s eff, "
              f"{gfs:.2f} GF/s", file=sys.stderr)
        if gbs > best_gbs:
            best_name, best_gbs = name, gbs

    print(json.dumps({
        "metric": "heat2d stencil order-8 4000x4000 f32 effective bandwidth "
                  f"(best kernel: {best_name})",
        "value": round(best_gbs, 2),
        "unit": "GB/s",
        "vs_baseline": round(best_gbs / BASELINE_GBS, 3),
    }))


def main() -> None:
    if _CHILD_FLAG in sys.argv:
        if not _preflight():
            print("preflight: device unreachable within 90s", file=sys.stderr)
            sys.exit(_PREFLIGHT_EXIT)
        measure()
        return
    import time as _time

    for attempt in range(3):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), _CHILD_FLAG],
                timeout=900, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"attempt {attempt + 1}: timed out (TPU tunnel stuck?)",
                  file=sys.stderr)
            continue
        sys.stderr.write(proc.stderr)
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        if proc.returncode == 0 and lines:
            print(lines[-1])
            return
        print(f"attempt {attempt + 1}: exit {proc.returncode}",
              file=sys.stderr)
        if proc.returncode == _PREFLIGHT_EXIT and attempt < 2:
            _time.sleep(120)  # wedged tunnel: give it a chance to recover
    print(json.dumps({
        "metric": "heat2d stencil order-8 4000x4000 f32 effective bandwidth "
                  "(DEVICE UNAVAILABLE)",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
