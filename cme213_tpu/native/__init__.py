"""Host-native C++/OpenMP components (hw4 sorts) with ctypes bindings.

The library is compiled on demand (g++ -O3 -fopenmp) and cached next to the
source; see ``build.py``.  Python entry points:

- ``merge_sort(arr, sort_threshold, merge_threshold)`` — in-place int32 sort
  via the fork-join task tree (reference CLI knobs, mergesort.cpp:148-158).
- ``radix_sort(arr, num_bits, block_size)`` / ``radix_sort_serial`` —
  in-place uint32 LSD radix sorts (reference knobs, radixsort.cpp:163-179).
- ``set_threads(n)`` / ``thread_count()`` — the OMP_NUM_THREADS control the
  reference's PBS harness swept (pa4.pbs:20-28).
"""

from __future__ import annotations

import ctypes

import numpy as np

from .build import build_library

_lib = None


def _load():
    global _lib
    if _lib is None:
        path = build_library()
        _lib = ctypes.CDLL(str(path))
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        _lib.merge_sort_omp.argtypes = [i32p, i32p, ctypes.c_long,
                                        ctypes.c_long, ctypes.c_long]
        _lib.radix_sort_omp.argtypes = [u32p, u32p, ctypes.c_long,
                                        ctypes.c_int, ctypes.c_long]
        _lib.radix_sort_serial.argtypes = [u32p, u32p, ctypes.c_long,
                                           ctypes.c_int]
        _lib.set_omp_threads.argtypes = [ctypes.c_int]
        _lib.omp_thread_count.restype = ctypes.c_int
        _lib.wtime_now.restype = ctypes.c_double
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        _lib.parallel_sum_omp.argtypes = [f32p, ctypes.c_long]
        _lib.parallel_sum_omp.restype = ctypes.c_double
        _lib.saxpy_omp.argtypes = [ctypes.c_float, f32p, f32p, ctypes.c_long]
        ll4 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        _lib.spmv_read_header.argtypes = [ctypes.c_char_p, ll4]
        _lib.spmv_read_header.restype = ctypes.c_int
        _lib.spmv_read_arrays.argtypes = [ctypes.c_char_p, f32p,
                                          ctypes.c_longlong, i32p,
                                          ctypes.c_longlong, i32p]
        _lib.spmv_read_arrays.restype = ctypes.c_int
        _lib.read_floats.argtypes = [ctypes.c_char_p, f32p,
                                     ctypes.c_longlong]
        _lib.read_floats.restype = ctypes.c_longlong
        _lib.write_floats.argtypes = [ctypes.c_char_p, f32p,
                                      ctypes.c_longlong]
        _lib.write_floats.restype = ctypes.c_int
        _lib.spmv_scan_omp.argtypes = [f32p, f32p, i32p, ctypes.c_long,
                                       ctypes.c_long, ctypes.c_int]
    return _lib


def spmv_read(a_path: str):
    """Parse the hw_final ``a.txt`` format natively.

    Returns ``(a, s, k, q, iters)``.  Raises ``OSError`` / ``ValueError``
    on unreadable or malformed files (the fail-fast behavior of the
    reference's validating loader)."""
    lib = _load()
    hdr = np.zeros(4, np.int64)
    rc = lib.spmv_read_header(a_path.encode(), hdr)
    if rc:
        raise OSError(f"cannot read header of {a_path} (code {rc})")
    n, p, q, iters = (int(v) for v in hdr)
    a = np.empty(n, np.float32)
    s = np.empty(p, np.int32)
    k = np.empty(n, np.int32)
    rc = lib.spmv_read_arrays(a_path.encode(), a, n, s, p, k)
    if rc:
        raise ValueError(f"malformed {a_path} (section {rc})")
    return a, s, k, q, iters


def read_floats(path: str, count: int) -> np.ndarray:
    """Read ``count`` whitespace-separated floats (x.txt / b.txt shape)."""
    lib = _load()
    out = np.empty(count, np.float32)
    got = lib.read_floats(path.encode(), out, count)
    if got < 0:
        raise OSError(f"cannot read {path}")
    if got < count:
        raise ValueError(f"{path}: expected {count} floats, found {got}")
    return out


def write_floats(path: str, values: np.ndarray) -> None:
    """Write one float per line (the b.txt output shape, fp.cu:192-199)."""
    lib = _load()
    values = np.ascontiguousarray(values, dtype=np.float32)
    rc = lib.write_floats(path.encode(), values, values.size)
    if rc:
        raise OSError(f"cannot write {path} (code {rc})")


def merge_sort(arr: np.ndarray, sort_threshold: int = 4096,
               merge_threshold: int = 4096) -> np.ndarray:
    """In-place parallel merge sort of an int32 array; returns ``arr``."""
    lib = _load()
    arr = np.ascontiguousarray(arr, dtype=np.int32)
    scratch = np.empty_like(arr)
    lib.merge_sort_omp(arr, scratch, arr.size, sort_threshold, merge_threshold)
    return arr


def radix_sort(arr: np.ndarray, num_bits: int = 8,
               block_size: int = 8192) -> np.ndarray:
    """In-place parallel LSD radix sort of a uint32 array; returns ``arr``."""
    lib = _load()
    arr = np.ascontiguousarray(arr, dtype=np.uint32)
    scratch = np.empty_like(arr)
    lib.radix_sort_omp(arr, scratch, arr.size, num_bits, block_size)
    return arr


def radix_sort_serial(arr: np.ndarray, num_bits: int = 8) -> np.ndarray:
    lib = _load()
    arr = np.ascontiguousarray(arr, dtype=np.uint32)
    scratch = np.empty_like(arr)
    lib.radix_sort_serial(arr, scratch, arr.size, num_bits)
    return arr


def parallel_sum(x: np.ndarray) -> float:
    """OpenMP reduction sum over a float32 array (f64 accumulator)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    return float(_load().parallel_sum_omp(x, x.size))


def saxpy(alpha: float, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """In-place y ← α·x + y over float32 arrays; returns ``y``."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    assert y.dtype == np.float32 and y.flags["C_CONTIGUOUS"]
    _load().saxpy_omp(alpha, x, y, x.size)
    return y


def spmv_scan_cpu(a: np.ndarray, seg_starts: np.ndarray, xx: np.ndarray,
                  iters: int) -> np.ndarray:
    """OpenMP CPU SpMV-scan: ``a ← segscan(a·xx)`` iterated ``iters`` times.

    The hw_final CPU reference axis (parallel multiply + one-segment-per-
    thread serial scan, ``fp.cu:130-152``).  ``seg_starts`` excludes the
    terminal sentinel.  Returns a new array; ``a`` is untouched.
    """
    lib = _load()
    out = np.array(a, dtype=np.float32, copy=True, order="C")
    xx = np.ascontiguousarray(xx, dtype=np.float32)
    s = np.ascontiguousarray(seg_starts, dtype=np.int32)
    lib.spmv_scan_omp(out, xx, s, s.size, out.size, iters)
    return out


def set_threads(n: int) -> None:
    _load().set_omp_threads(n)


def thread_count() -> int:
    return _load().omp_thread_count()
