"""Game-day chaos campaigns (``core/chaos.py``): the cocktail grammar
round-trip, the clause-compatibility matrix, the seeded drawer's
determinism, the ddmin shrinker on synthetic predicates, the
conformance-gated sort adapter, in-process campaigns end-to-end (benign
cocktails hold all five invariants; a handicapped drill violates,
shrinks to a minimal cocktail, banks, and replays), and the shipped
fixture bank.  The live-fleet campaign is ``slow``-marked; the CI chaos
gate runs it against real replica subprocesses.
"""

import glob
import json
import os

import numpy as np
import pytest

from cme213_tpu.core import chaos, conformance, faults, metrics, numerics, trace
from cme213_tpu.core.chaos import (
    MATRIX,
    TOPOLOGY,
    CampaignResult,
    bank_fixture,
    compatible,
    ddmin,
    draw_cocktail,
    replay_fixture,
    run_campaign,
    run_campaigns,
    shrink,
    validate_cocktail,
)
from cme213_tpu.core.faults import FaultPlan, _Clause
from cme213_tpu.serve.workloads import ADAPTERS, JOB_KINDS

FIXTURES = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "chaos_fixtures", "*.json")))


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    yield
    faults.reset()
    conformance.reset()
    numerics.reset()
    metrics.reset()
    trace.clear_events()


# ------------------------------------------------- grammar round-trip

def test_clause_str_roundtrip_every_kind():
    spec = ("fail:serve.cipher.packed:2:3,nan:solver:1,wrong:probe:1,"
            "oom:chunk:2,slow:serve.sort:50.0:1:2,drift:op.rung:0.001:1,"
            "stage:serve.spmv_scan.blocked:execute:2:1,unreachable:1:3,"
            "rankkill:1:2,replica-kill:0:1,ckpt:truncate:1,ckpt:commit:2")
    plan = FaultPlan.parse(spec)
    again = FaultPlan.parse(str(plan))
    assert len(again.clauses) == len(plan.clauses)
    for a, b in zip(plan.clauses, again.clauses):
        assert (a.kind, a.op, a.nth, a.count, a.ms, a.stage) == \
               (b.kind, b.op, b.nth, b.count, b.ms, b.stage)


def test_drawn_cocktails_roundtrip(seeds=range(6)):
    ops = ["cipher", "sort", "spmv_scan", "heat"]
    for s in seeds:
        plan = draw_cocktail(np.random.default_rng([s, 0]), "inproc", ops)
        again = FaultPlan.parse(str(plan))
        assert str(again) == str(plan)
        assert 2 <= len(plan.clauses) <= 5


def test_install_plan_overrides_env_and_reset_restores(monkeypatch):
    monkeypatch.setenv("CME213_FAULTS", "fail:env-op:1")
    faults.reset()
    assert faults.active().clauses[0].op == "env-op"
    plan = faults.install_plan(FaultPlan.parse("fail:prog-op:1"))
    assert faults.active() is plan
    assert faults.active().clauses[0].op == "prog-op"
    faults.reset()                      # back to reading the env
    assert faults.active().clauses[0].op == "env-op"


def test_reset_counters_rearms_clauses():
    plan = FaultPlan.parse("fail:op:1:1")
    faults.install_plan(plan)
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("op")
    faults.maybe_fail("op")             # count exhausted: no longer fires
    plan.reset_counters()
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("op")         # fires again from scratch


# ------------------------------------------------- compatibility matrix

def test_topology_matches_live_adapters():
    # job-lane entries describe long-job kinds, not serving adapters:
    # they must match JOB_KINDS instead of ADAPTERS
    serving = {op for op, t in TOPOLOGY.items() if not t.get("job")}
    job_ops = {op for op, t in TOPOLOGY.items() if t.get("job")}
    assert serving == set(ADAPTERS)
    assert job_ops == set(JOB_KINDS)
    assert job_ops == set(chaos.JOB_PARAMS)
    for op in serving:
        assert TOPOLOGY[op]["rungs"] == ADAPTERS[op].rungs(False), op


def test_matrix_covers_full_grammar():
    # every kind the parser accepts has a matrix row, and every row
    # carries a documented reason
    assert set(MATRIX) == {"fail", "nan", "wrong", "oom", "slow", "drift",
                           "stage", "unreachable", "rankkill",
                           "replica-kill", "ckpt"}
    for rule in MATRIX.values():
        assert rule.reason, rule.kind


def test_validate_flags_ineligible_and_backend():
    assert any("ineligible" in p for p in validate_cocktail(
        FaultPlan.parse("nan:solver:1,fail:serve.cipher.packed:1"),
        "inproc"))
    assert any("backend" in p for p in validate_cocktail(
        FaultPlan.parse("replica-kill:0:1,fail:serve.cipher.packed:1"),
        "inproc"))
    assert validate_cocktail(FaultPlan.parse(
        "replica-kill:0:1,fail:serve.cipher.packed:1"), "fleet") == []


def test_compatible_rejects_conflicts_duplicates_caps():
    drift = _Clause("drift", "serve.heat.xla", nth=1, ms=1e-3)
    kill = _Clause("replica-kill", "0", nth=1)
    assert not compatible([drift], kill)[0]         # declared conflict
    assert not compatible([kill], drift)[0]         # symmetric
    f = _Clause("fail", "serve.cipher.packed", nth=1, count=1)
    assert not compatible([f], f)[0]                # duplicate target
    s1 = _Clause("stage", "serve.sort.lax", stage="execute")
    s2 = _Clause("stage", "serve.sort.radix", stage="execute")
    assert compatible([s1], s2)[0] is False         # stage cap is 1


def test_wrong_never_codrawn_with_ladder_failure():
    # the chaos-s2000-c0 find, encoded: a poisoned probe plus rung
    # failures on the same ladder can exhaust it
    wrong = _Clause("wrong", "serve.sort", nth=1)
    fail_sort = _Clause("fail", "serve.sort.lax", nth=1, count=1)
    fail_ciph = _Clause("fail", "serve.cipher.packed", nth=1, count=1)
    assert not compatible([wrong], fail_sort)[0]
    assert not compatible([fail_sort], wrong)[0]
    assert compatible([wrong], fail_ciph)[0]        # other ladders fine


def test_draw_is_seed_deterministic_and_valid():
    ops = ["cipher", "sort", "spmv_scan", "heat"]
    for backend in ("inproc", "fleet"):
        for i in range(8):
            a = draw_cocktail(np.random.default_rng([5, i]), backend, ops)
            b = draw_cocktail(np.random.default_rng([5, i]), backend, ops)
            assert str(a) == str(b)
            assert validate_cocktail(a, backend) == []


def test_inproc_draw_never_contains_kill():
    for i in range(12):
        plan = draw_cocktail(np.random.default_rng([9, i]), "inproc",
                             ["cipher", "sort"])
        assert not any(c.kind in ("replica-kill", "rankkill")
                       for c in plan.clauses)


# ---------------------------------------------------------- ddmin units

def test_ddmin_single_culprit():
    assert ddmin(list("abcdefgh"), lambda s: "e" in s) == ["e"]


def test_ddmin_interacting_pair():
    got = ddmin(list("abcdefgh"), lambda s: "b" in s and "g" in s)
    assert sorted(got) == ["b", "g"]


def test_ddmin_preserves_order_and_already_minimal():
    got = ddmin([3, 1, 2], lambda s: 1 in s and 2 in s)
    assert got == [1, 2]
    assert ddmin([7], lambda s: True) == [7]


def test_shrink_drops_clauses_and_simplifies_params():
    plan = FaultPlan.parse(
        "slow:serve.cipher:50.0:2:3,fail:serve.cipher.packed:2:3,"
        "drift:serve.heat.xla:0.001:1")

    def failing(p):
        return any(c.kind == "fail" for c in p.clauses)

    minimal = shrink(plan, failing)
    assert len(minimal.clauses) == 1
    c = minimal.clauses[0]
    assert (c.kind, c.nth, c.count) == ("fail", 1, 1)   # params shrunk too


# --------------------------------------------- conformance-gated sort

def test_sort_adapter_every_rung_bitwise():
    adapter = ADAPTERS["sort"]
    keys = np.random.default_rng(0).integers(
        0, 2**32, size=(3, 512), dtype=np.uint32)
    golden = np.sort(keys, axis=1)
    for rung in adapter.rungs(False):
        out = adapter.run_batch(list(keys), rung)
        for lane, ref in zip(out, golden):
            assert np.asarray(lane).tobytes() == ref.tobytes(), rung


def test_sort_golden_gate_refuses_poisoned_rung():
    adapter = ADAPTERS["sort"]
    keys = [np.random.default_rng(1).integers(
        0, 2**32, size=512, dtype=np.uint32)]
    with faults.injected("wrong:serve.sort:1"):
        conformance.reset()
        with pytest.raises(RuntimeError, match="golden probe"):
            adapter.run_batch(keys, "lax")
    conformance.reset()
    out = adapter.run_batch(keys, "lax")    # disarmed: serves again
    assert np.asarray(out[0]).tobytes() == np.sort(keys[0]).tobytes()


def test_sort_in_loadgen_mix_and_wire():
    from cme213_tpu.serve.loadgen import build_mix
    from cme213_tpu.serve.transport import decode_payload, encode_payload

    specs = build_mix("cipher,sort", 8, seed=2)
    sorts = [s for s in specs if s.op == "sort"]
    assert sorts and {int(np.asarray(s.payload).shape[0])
                      for s in sorts} == {512, 1024}
    doc = json.loads(json.dumps(encode_payload("sort", sorts[0].payload)))
    back = decode_payload("sort", doc)
    assert np.asarray(back).tobytes() == \
        np.asarray(sorts[0].payload).tobytes()


# ------------------------------------------------- campaigns end-to-end

def test_benign_campaign_holds_all_invariants():
    res = run_campaign(
        "fail:serve.cipher.packed:1:2,slow:serve.cipher:20.0:1:2",
        backend="inproc", mix="cipher", requests=8, seed=3)
    assert res.ok, [v.as_dict() for v in res.violations]
    assert res.report["served"] + res.report["shed"] == 8
    names = [e["event"] for e in trace.events("chaos-campaign")]
    assert names == ["chaos-campaign"]


def test_campaign_is_deterministic_per_seed():
    kw = dict(backend="inproc", mix="cipher", requests=6, seed=7)
    a = run_campaign("fail:serve.cipher.packed:1:1", **kw)
    b = run_campaign("fail:serve.cipher.packed:1:1", **kw)
    assert a.ok and b.ok
    assert a.cocktail == b.cocktail
    assert a.report["served"] == b.report["served"]


def test_inproc_campaign_refuses_kill_clauses():
    with pytest.raises(ValueError, match="kill"):
        run_campaign("replica-kill:0:1", backend="inproc", mix="cipher",
                     requests=2, seed=0)


def test_unknown_handicap_and_backend_rejected():
    with pytest.raises(ValueError, match="handicap"):
        run_campaign("fail:x:1", backend="inproc", mix="cipher",
                     requests=2, seed=0, handicaps=("no-such",))
    with pytest.raises(ValueError, match="backend"):
        run_campaign("fail:x:1", backend="warp", mix="cipher",
                     requests=2, seed=0)


def test_ckpt_only_drawable_in_job_campaigns():
    # without a job op the pool has no ckpt targets; with one it does,
    # and the drawn clauses target the two durable-writer crash windows
    ops = ["cipher", "sort"]
    assert "ckpt" not in chaos.clause_targets("inproc", ops, 2)
    pool = chaos.clause_targets("inproc", ops + ["pagerank"], 2)
    assert sorted(t["op"] for t in pool["ckpt"]) == ["commit", "truncate"]
    # fleet backend never draws ckpt (the guards fire in the runner)
    assert "ckpt" not in chaos.clause_targets("fleet",
                                              ops + ["pagerank"], 2)


def test_ckpt_campaign_without_job_refused():
    with pytest.raises(ValueError, match="job campaign"):
        run_campaign("ckpt:commit:1", backend="inproc", mix="cipher",
                     requests=2, seed=0)
    with pytest.raises(ValueError, match="inproc"):
        run_campaign("ckpt:commit:1", backend="fleet", mix="cipher",
                     requests=2, seed=0, job="pagerank")


def test_job_campaign_survives_both_ckpt_windows():
    # the tentpole invariant, stated as a campaign: a torn epoch
    # checkpoint AND a lost record publish in one run, and the job
    # still reaches DONE with a bitwise-reference result and no
    # committed epoch re-executed
    res = run_campaign("ckpt:truncate:1,ckpt:commit:1", backend="inproc",
                       mix="cipher", requests=8, seed=11, job="pagerank")
    assert res.ok, [v.as_dict() for v in res.violations]
    assert res.job == "pagerank"
    done = [e for e in trace.events("job-done")]
    assert done and done[-1]["state"] == "DONE"


def test_job_campaign_handicap_drill_violates_and_replays(tmp_path):
    # the deliberate breakage: commit retries handicapped off, so one
    # injected publish crash fails the job -> "job" violation ->
    # shrinks to the single commit clause -> banked fixture reproduces
    cocktail = "ckpt:commit:1,slow:serve.cipher:20.0:1:1"
    kw = dict(backend="inproc", mix="cipher", requests=6, seed=12,
              job="pagerank", handicaps=("ckpt-retry",))
    res = run_campaign(cocktail, **kw)
    assert {v.invariant for v in res.violations} == {"job"}

    def failing(p):
        return bool(run_campaign(p, **kw).violations)

    minimal = shrink(FaultPlan.parse(cocktail), failing)
    assert str(minimal) == "ckpt:commit:1"
    path = bank_fixture(res, minimal, directory=str(tmp_path),
                        handicaps=("ckpt-retry",))
    replayed, expected, observed = replay_fixture(path)
    assert expected == observed == ["job"]
    assert replayed.job == "pagerank"


def test_drill_violates_shrinks_banks_and_replays(tmp_path):
    # the deliberate game-day drill: drift on the serving rung with
    # drift-compensation handicapped off -> conformance violation ->
    # ddmin to a minimal (<= 2 clause) cocktail -> banked fixture
    # reproduces on replay
    cocktail = ("drift:serve.spmv_scan.blocked:0.001:1,"
                "slow:serve.spmv_scan:20.0:1:1")
    kw = dict(backend="inproc", mix="spmv", requests=6, seed=5,
              handicaps=("drift-compensation",))
    res = run_campaign(cocktail, **kw)
    assert {v.invariant for v in res.violations} == {"conformance"}
    assert len(trace.events("chaos-violation")) >= 1

    def failing(p):
        return bool(run_campaign(p, **kw).violations)

    minimal = shrink(FaultPlan.parse(cocktail), failing)
    assert len(minimal.clauses) <= 2
    assert minimal.clauses[0].kind == "drift"

    path = bank_fixture(res, minimal, directory=str(tmp_path),
                        handicaps=("drift-compensation",))
    replayed, expected, observed = replay_fixture(path)
    assert expected == observed == ["conformance"]


def test_drift_with_compensation_is_conformant():
    # same drift cocktail, no handicap: the checker compensates the
    # declared scale exactly, so the campaign is clean
    res = run_campaign("drift:serve.spmv_scan.blocked:0.001:1",
                       backend="inproc", mix="spmv", requests=6, seed=5)
    assert res.ok, [v.as_dict() for v in res.violations]


def test_run_campaigns_orchestration(tmp_path):
    out = run_campaigns(seed=2, campaigns=2, backend="inproc",
                        mix="cipher", requests=6,
                        bank_dir=str(tmp_path))
    assert len(out["campaigns"]) == 2
    assert out["ok"] == (out["violations_total"] == 0)
    # every drawn cocktail validated and is recorded verbatim
    for c in out["campaigns"]:
        assert validate_cocktail(
            FaultPlan.parse(c["cocktail"]), "inproc") == []
    assert json.loads(json.dumps(out)) == out   # JSON-clean report


# --------------------------------------------------- banked fixtures

@pytest.mark.parametrize("path", FIXTURES,
                         ids=[os.path.basename(p) for p in FIXTURES])
def test_banked_fixture_replays(path):
    result, expected, observed = replay_fixture(path)
    assert observed == expected, \
        f"{os.path.basename(path)}: {result.violations}"


def test_fixture_bank_is_not_empty():
    # the bank must always hold at least one passing fixture and one
    # violation fixture: the replay test proves both directions
    docs = [json.load(open(p)) for p in FIXTURES]
    assert any(d["expect"]["violated"] == [] for d in docs)
    assert any(d["expect"]["violated"] for d in docs)
    for d in docs:
        assert FaultPlan.parse(d["minimal_cocktail"]).clauses


# ------------------------------------------------------------ CLI

def test_chaos_cli_draw_deterministic(capsys):
    from cme213_tpu.chaos_cli import main

    assert main(["draw", "--seed", "3", "--campaigns", "3",
                 "--mix", "cipher,sort"]) == 0
    first = capsys.readouterr().out
    assert main(["draw", "--seed", "3", "--campaigns", "3",
                 "--mix", "cipher,sort"]) == 0
    assert capsys.readouterr().out == first
    assert len(first.strip().splitlines()) == 3


def test_chaos_cli_matrix_and_help(capsys):
    from cme213_tpu.chaos_cli import main

    assert main(["matrix", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == set(MATRIX)
    assert main(["--help"]) == 0
    assert main(["no-such"]) == 2


# --------------------------------------------------- live-fleet campaign

@pytest.mark.slow
def test_fleet_campaign_with_replica_kill():
    # the full game day: a replica SIGKILLed mid-batch while another
    # clause fails a rung — zero accepted-request loss, bitwise
    # conformance, one trace id across the gang, nothing leaked
    res = run_campaign(
        "replica-kill:0:2,fail:serve.cipher.packed:1:1",
        backend="fleet", mix="cipher,sort", requests=12, seed=6,
        replicas=2)
    assert res.ok, [v.as_dict() for v in res.violations]
    assert res.report["served"] == 12
