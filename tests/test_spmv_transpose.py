"""CSR/ELL SpMV, tiled transpose, saxpy/parallel-sum (device + native)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.ops.gather import csr_row_ids
from cme213_tpu.ops.spmv import csr_spmv, csr_to_ell, ell_spmv
from cme213_tpu.ops.transpose import transpose_pallas, transpose_xla

INTERPRET = jax.devices()[0].platform != "tpu"


def random_csr(rng, rows, cols, avg_nnz):
    counts = rng.integers(0, 2 * avg_nnz + 1, rows)
    indices = np.zeros(rows + 1, dtype=np.int64)
    np.cumsum(counts, out=indices[1:])
    nnz = int(indices[-1])
    col_idx = rng.integers(0, cols, nnz).astype(np.int32)
    vals = rng.standard_normal(nnz).astype(np.float32)
    return indices, col_idx, vals


def dense_from_csr(indices, col_idx, vals, rows, cols):
    a = np.zeros((rows, cols), dtype=np.float32)
    for r in range(rows):
        for j in range(indices[r], indices[r + 1]):
            a[r, col_idx[j]] += vals[j]
    return a


def test_csr_spmv_matches_dense():
    rng = np.random.default_rng(0)
    rows, cols = 100, 80
    indices, col_idx, vals = random_csr(rng, rows, cols, 4)
    x = rng.standard_normal(cols).astype(np.float32)
    a = dense_from_csr(indices, col_idx, vals, rows, cols)
    row_ids = csr_row_ids(jnp.asarray(indices.astype(np.int32)),
                          col_idx.shape[0])
    y = np.asarray(csr_spmv(row_ids, jnp.asarray(col_idx), jnp.asarray(vals),
                            jnp.asarray(x), rows))
    np.testing.assert_allclose(y, a @ x, rtol=1e-4, atol=1e-4)


def test_ell_spmv_matches_csr():
    rng = np.random.default_rng(1)
    rows, cols = 64, 64
    indices, col_idx, vals = random_csr(rng, rows, cols, 3)
    x = rng.standard_normal(cols).astype(np.float32)
    a = dense_from_csr(indices, col_idx, vals, rows, cols)
    ell_cols, ell_vals = csr_to_ell(indices, col_idx, vals)
    y = np.asarray(ell_spmv(jnp.asarray(ell_cols), jnp.asarray(ell_vals),
                            jnp.asarray(x)))
    np.testing.assert_allclose(y, a @ x, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("shape,tile", [((64, 64), 32), ((128, 64), 32)])
def test_transpose_pallas(shape, tile):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    out = np.asarray(transpose_pallas(x, tile=tile, interpret=INTERPRET))
    np.testing.assert_array_equal(out, np.asarray(x).T)
    np.testing.assert_array_equal(np.asarray(transpose_xla(x)), np.asarray(x).T)


def test_device_saxpy_sum():
    from cme213_tpu.ops.elementwise import parallel_sum, saxpy

    rng = np.random.default_rng(3)
    x = rng.standard_normal(1000).astype(np.float32)
    y = rng.standard_normal(1000).astype(np.float32)
    out = np.asarray(saxpy(2.5, jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(out, 2.5 * x + y, rtol=1e-5, atol=1e-6)
    assert np.asarray(parallel_sum(jnp.asarray(x))) == pytest.approx(
        x.sum(), rel=1e-4)


def test_native_saxpy_sum():
    from cme213_tpu import native

    rng = np.random.default_rng(4)
    x = rng.standard_normal(10_000).astype(np.float32)
    y = rng.standard_normal(10_000).astype(np.float32)
    assert native.parallel_sum(x) == pytest.approx(float(x.sum()), rel=1e-6)
    expect = 1.5 * x + y
    native.saxpy(1.5, x, y)
    np.testing.assert_allclose(y, expect, rtol=1e-6)
