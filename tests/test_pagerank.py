import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.apps.pagerank import build_graph, run_pagerank
from cme213_tpu.verify import check_ulp, golden


def test_graph_builder_shapes():
    g = build_graph(num_nodes=128, avg_edges=4, seed=0)
    assert g.indices.shape == (129,)
    assert g.indices[0] == 0
    assert g.indices[-1] == g.edges.shape[0]
    # cyclic out-degree pattern 1..2*avg-1 (pagerank.cu:185-204)
    degs = np.diff(g.indices)
    np.testing.assert_array_equal(degs, np.arange(128) % 7 + 1)
    assert degs.min() >= 1 and degs.max() <= 2 * 4 - 1
    assert np.allclose(g.inv_deg[degs > 0], 1.0 / degs[degs > 0])


def test_pagerank_matches_host_golden():
    g = build_graph(num_nodes=256, avg_edges=3, seed=1)
    iters = 6
    ref = golden.host_graph_iterate(g.indices, g.edges, g.rank0, g.inv_deg, iters)
    out = run_pagerank(g, iters)
    res = check_ulp(ref, np.asarray(out), max_ulps=10, label="pagerank")
    assert res, res.message


def test_pagerank_stays_finite_positive():
    g = build_graph(num_nodes=512, avg_edges=8, seed=2)
    out = np.asarray(run_pagerank(g, 20))
    assert np.isfinite(out).all()
    # every node gets at least the teleport mass 0.5/n
    assert (out >= 0.5 / 512 - 1e-9).all()


def test_pagerank_odd_iterations_rejected():
    g = build_graph(num_nodes=64, avg_edges=2, seed=3)
    with pytest.raises(AssertionError):
        run_pagerank(g, 3)
