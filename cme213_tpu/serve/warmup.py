"""Pre-compile the canonical serving buckets — the warm-start half of the
compile-amortization story.

``python -m cme213_tpu serve warmup`` derives the shape classes a serving
mix will hit (the same population ``loadgen`` drives), then runs each
(op, shape class, batch width, rung) combination once through the
adapters' batch paths.  Every program lands in the process-wide cache
(``core/programs.py``) **and** — when ``CME213_COMPILE_CACHE`` points at
a directory (``core/platform.enable_compile_cache``) — in the persistent
XLA disk cache, so a later server process starts with every known shape
class loading from disk instead of compiling fresh: zero fresh compiles
on the request path from the first batch.

The report is the same compile-attribution section the loadgen SLO
report carries: per-class compile ms, program-cache misses (one per
warmed program), and where the disk cache landed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..core import metrics


def warm_buckets(mix: str, requests: int = 12, max_batch: int = 8,
                 seed: int = 0, tuned: bool = False) -> list[str]:
    """Run one batch per (op, shape class, batch width, rung) of the
    mix's canonical buckets through the adapters — compiling each program
    into the process cache and (if enabled) the persistent disk cache.
    Batch widths 1 and ``max_batch`` are warmed: the widths a drained
    tail and a full batch window actually dispatch.  With ``tuned``, the
    tuning cache's per-bucket batch width (``server.tuned_batch_cap``) is
    warmed too — the width a tuned server will actually form.  Returns
    the warmed ``op[class]/bN`` labels."""
    from .loadgen import build_mix
    from .server import tuned_batch_cap
    from .workloads import ADAPTERS

    specs = build_mix(mix, requests, seed=seed)
    groups: dict[tuple[str, str], list] = {}
    for spec in specs:
        adapter = ADAPTERS[spec.op]
        key = (spec.op, adapter.shape_class(spec.payload))
        groups.setdefault(key, []).append(spec.payload)

    warmed = []
    for (op, sc), payloads in sorted(groups.items()):
        adapter = ADAPTERS[op]
        widths = {1, max(1, max_batch)}
        if tuned:
            widths.add(tuned_batch_cap(op, sc, max(1, max_batch)))
        for b in sorted(widths):
            batch = (payloads * b)[:b]
            ok = True
            for rung in adapter.rungs():
                try:
                    adapter.run_batch(batch, rung)
                except Exception as e:  # noqa: BLE001 — warmup is advisory
                    ok = False
                    print(f"warmup: {op}[{sc}] rung {rung!r} failed: {e}",
                          file=sys.stderr)
            if ok:
                warmed.append(f"{op}[{sc}]/b{b}")
    return warmed


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="serve warmup",
        description="pre-compile the canonical serving buckets into the "
                    "program cache and (with CME213_COMPILE_CACHE set) the "
                    "persistent XLA disk cache")
    ap.add_argument("--mix", default="spmv,heat,cipher",
                    help="comma-separated ops, as for loadgen --mix")
    ap.add_argument("--requests", type=int, default=12,
                    help="mix length used to derive the bucket set")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="full batch width to warm (width 1 always is)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tuned", action="store_true",
                    help="also warm each bucket's tuned batch width "
                         "(from the CME213_TUNE_CACHE winners)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from ..core import flight, programs

    flight.install()
    from .loadgen import compile_attribution

    cache_dir = os.environ.get("CME213_COMPILE_CACHE")
    before = metrics.snapshot()
    warmed = warm_buckets(args.mix, requests=args.requests,
                          max_batch=args.max_batch, seed=args.seed,
                          tuned=args.tuned)
    report = {
        "warmed": warmed,
        "programs": programs.size(),
        "persistent_cache": cache_dir,
        "persistent_entries": (len(os.listdir(cache_dir))
                               if cache_dir and os.path.isdir(cache_dir)
                               else None),
        "compile": compile_attribution(before, metrics.snapshot()),
    }
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        comp = report["compile"]
        print(f"warmed {len(warmed)} bucket(s), {report['programs']} "
              f"cached program(s), compile {comp['compile_ms']} ms")
        for label in warmed:
            print(f"  {label}")
        if cache_dir:
            print(f"persistent cache {cache_dir}: "
                  f"{report['persistent_entries']} entr(ies)")
        else:
            print("persistent cache: disabled "
                  "(set CME213_COMPILE_CACHE=<dir> for warm process starts)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
