#!/bin/bash
# Full on-device measurement capture for a round: headline bench (f32 and
# f64), the device-side sweep CSVs, and the Pallas tile sweep.  Run on the
# real TPU (default axon platform) once the tunnel is healthy:
#
#   bash scripts/tpu_capture.sh [outdir]
#
# Resumable: a sweep whose CSV is already in outdir is skipped, and a sweep
# that failed for a non-device reason (recorded in <sweep>.failed) is not
# retried — so the autocapture watcher can re-invoke this script across
# tunnel drops and it only re-runs what a drop actually cost.  SKIP_F32=1
# skips the f32 headline bench only when a COMPLETE bench_f32.json from a
# prior attempt already sits in outdir (nothing is copied in from the
# watcher's gate run).  Exit 0 = both headline benches hold real numbers
# and every sweep has a CSV or a non-device failure record.
set -u
cd "$(dirname "$0")/.."
. scripts/capture_lib.sh
OUT="${1:-bench_results}"
mkdir -p "$OUT"

echo "== preflight =="
# one probe implementation for the whole pipeline (capture_lib.sh);
# 'tpu' additionally requires the answering device to BE a TPU
device_up_quick tpu || { echo "preflight failed — tunnel down?"; exit 1; }
echo "device: TPU answering"

# per-pass cache of a DOWN verdict: a dead tunnel HANGS the probe for its
# full budget (only an erroring backend fails fast), so the first failed
# gate stamps every later stage instead of re-probing ~15 times
TUNNEL_STATE=up
gate_up() {
  [ "$TUNNEL_STATE" = down ] && return 1
  if device_up_quick; then TUNNEL_STATE=up; return 0; fi
  TUNNEL_STATE=down
  return 1
}

# A re-run writes to .new and is promoted only if it holds a real
# number — a window that dies before the first kernel must not replace
# an earlier partial that banked real rows (e.g. the 03:18 UTC xla row)
promote_bench() {  # $1 = final json path (expects $1.new from the run)
  # measured rows = ok:true INSIDE the kernels array only
  # (capture_lib.count_measured_rows): a dead-tunnel re-run echoes the
  # committed banked_device_rows, and counting those would let it
  # replace a file holding live-measured rows
  new_ok=$(count_measured_rows "$1.new")
  old_ok=$(count_measured_rows "$1")
  # zero-zero tie guard: with no measured rows on either side, only a
  # structurally sane .new (it at least reached the report stage and
  # carries the kernels-table unit field) may replace the incumbent — an
  # early bench.py crash must not promote an empty/garbage file over a
  # previous structured DEVICE-UNAVAILABLE record
  if [ "$new_ok" -eq 0 ] && [ "$old_ok" -eq 0 ] \
     && ! grep -q '"unit": "GB/s"' "$1.new"; then
    echo "discarding $1.new (no measured rows and no structured report)"
    rm -f "$1.new"
    return
  fi
  if [ "$new_ok" -ge "$old_ok" ]; then
    mv "$1.new" "$1"   # at least as many measured rows (fresher wins ties)
  else
    echo "keeping earlier $1 ($old_ok measured rows vs $new_ok new)"
    rm -f "$1.new"
  fi
}

if [ "${SKIP_F32:-0}" = 1 ] && bench_complete "$OUT/bench_f32.json"; then
  echo "== headline bench (f32): using existing $OUT/bench_f32.json =="
else
  echo "== headline bench (f32) =="
  python bench.py 2>"$OUT/bench_f32.stderr.log" \
      | tee "$OUT/bench_f32.json.new"
  promote_bench "$OUT/bench_f32.json"
fi

if bench_complete "$OUT/bench_f64.json"; then
  echo "== headline bench (f64): using existing $OUT/bench_f64.json =="
else
  echo "== headline bench (f64, XLA kernel) =="
  python bench.py --dtype=f64 2>"$OUT/bench_f64.stderr.log" \
      | tee "$OUT/bench_f64.json.new"
  promote_bench "$OUT/bench_f64.json"
fi

# skip the smoke only if the recorded transcript is conclusive: all-OK, or
# failures that are NOT device errors (a tunnel-drop transcript is retried)
if [ -s "$OUT/smoke_tpu.txt" ] \
   && { grep -q "ALL PALLAS KERNELS OK" "$OUT/smoke_tpu.txt" \
        || { grep -q "FAILURES" "$OUT/smoke_tpu.txt" \
             && ! grep -qE "$DEVICE_ERR" "$OUT/smoke_tpu.txt"; }; }; then
  echo "== pallas smoke: already recorded =="
elif ! gate_up; then
  echo "pallas smoke: DEVICE DOWN (skipped this pass, retried next)"
else
  echo "== pallas smoke (small shapes, recorded evidence) =="
  if timeout 1800 python scripts/tpu_smoke.py > "$OUT/smoke_tpu.txt" 2>&1
  then :; else echo "smoke had failures (recorded; continuing)"; fi
  cat "$OUT/smoke_tpu.txt"
fi

for sweep in $SWEEPS; do
    if [ -s "$OUT/$sweep.csv" ]; then
        echo "-- $sweep: already captured"
        continue
    fi
    if sweep_attempted "$OUT" "$sweep"; then
        echo "-- $sweep: sticky failure recorded, not retrying"
        continue
    fi
    echo "-- $sweep"
    # pre-stage gate: don't launch a multi-hour sweep at a dead tunnel
    if ! gate_up; then
        echo "preflight: device unreachable (pre-sweep gate)" \
            > "$OUT/$sweep.failed"
        echo "$sweep: DEVICE DOWN (recorded as retryable)"
        continue
    fi
    # the heavy sweeps compile tens of executables through the remote
    # helper (~20-40 s each cold); give them a longer leash
    case "$sweep" in
      heat_bandwidth|pipeline_tune|heat_kernels) t=5400 ;;
      *) t=2700 ;;
    esac
    timeout "$t" python -m cme213_tpu.bench.run_all --out "$OUT" \
        --only "$sweep" 2>"$OUT/$sweep.stderr.log"
    rc=$?
    cat "$OUT/$sweep.stderr.log" >&2
    if [ "$rc" = 0 ]; then
        rm -f "$OUT/$sweep.failed"
    elif [ "$rc" = 124 ]; then
        # timeout kill: stderr usually holds no device signature, but a
        # hang IS a device failure — record one so the retry classifier
        # re-runs this sweep next attempt
        { echo "timeout after ${t}s — device hang suspected";
          tail -n 4 "$OUT/$sweep.stderr.log"; } > "$OUT/$sweep.failed"
        echo "$sweep: TIMED OUT (continuing)"
    else
        # classification is anchored to the final failure itself (last
        # traceback, else last 15 lines — capture_lib.failure_signature):
        # wide enough that a long final traceback can't push the
        # signature out, and a recovered-UNAVAILABLE warning that merely
        # sits near the end of a sticky-failure log can't reclassify it
        # as a device failure (which would make the sweep retry forever)
        { failure_signature "$OUT/$sweep.stderr.log";
          tail -n 5 "$OUT/$sweep.stderr.log"; } > "$OUT/$sweep.failed"
        echo "$sweep: FAILED (continuing)"
    fi
done

# XPlane overlap evidence (SURVEY §7: overlap verified from profiles, not
# assumed) — sync/async/CA wall-clock rows + a device trace of the async
# scheme.  Retried across windows like a sweep (same .failed protocol).
if [ -s "$OUT/overlap_sync_vs_async.csv" ] \
   && find "$OUT/xplane_overlap" -name "*.xplane.pb" 2>/dev/null \
      | grep -q .; then
    echo "-- overlap trace: already captured"
elif sweep_attempted "$OUT" "overlap_sync_vs_async"; then
    echo "-- overlap trace: sticky failure recorded, not retrying"
elif ! gate_up; then
    echo "preflight: device unreachable (pre-sweep gate)" \
        > "$OUT/overlap_sync_vs_async.failed"
    echo "overlap trace: DEVICE DOWN (recorded as retryable)"
else
    echo "== overlap XPlane trace (P11 profile evidence) =="
    if timeout 2700 python scripts/tpu_overlap_trace.py "$OUT" \
        2>"$OUT/overlap_sync_vs_async.stderr.log"; then
        rm -f "$OUT/overlap_sync_vs_async.failed"
    else
        cat "$OUT/overlap_sync_vs_async.stderr.log" >&2
        { failure_signature "$OUT/overlap_sync_vs_async.stderr.log";
          tail -n 5 "$OUT/overlap_sync_vs_async.stderr.log"; } \
            > "$OUT/overlap_sync_vs_async.failed"
        echo "overlap trace: FAILED (continuing)"
    fi
fi

f64csv="$OUT/heat_bandwidth_f64.csv"
if [ -s "$f64csv" ]; then
    echo "-- f64 heat rows: already captured"
elif ! gate_up; then
    echo "f64 heat rows: DEVICE DOWN (skipped this pass, retried next)"
else
    echo "== f64 heat rows (reference's double 4th-order axis) =="
    JAX_ENABLE_X64=1 timeout 2700 python - "$f64csv" <<'EOF'
from cme213_tpu.bench import sweeps
import sys
rows = sweeps.heat_sweep(sizes=(4000,), orders=(2, 4, 8), iters=100,
                         dtype="f64")
sweeps.write_csv(rows, sys.argv[1])
print(f"f64 rows: {len(rows)}")
EOF
fi

# completeness: both headline benches must hold real numbers; a sweep with
# a sticky (non-device) failure counts as attempted — only device-failure
# gaps make the capture incomplete
missing=0
bench_complete "$OUT/bench_f32.json" || missing=$((missing + 1))
bench_complete "$OUT/bench_f64.json" || missing=$((missing + 1))
for sweep in $SWEEPS; do
    sweep_attempted "$OUT" "$sweep" || missing=$((missing + 1))
done
[ -s "$f64csv" ] || missing=$((missing + 1))
sweep_attempted "$OUT" "overlap_sync_vs_async" || missing=$((missing + 1))

# regenerate the curated markdown view of whatever is captured so far —
# only for the canonical evidence directory (a scratch-outdir trial run
# must not clobber the committed document)
if [ "$OUT" = "bench_results" ]; then
  python -m cme213_tpu.bench.report --dir "$OUT" --out docs/DATA.md || true
fi

echo "capture complete: $OUT (unresolved items: $missing)"
[ "$missing" -le 0 ]
