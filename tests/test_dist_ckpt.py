"""Epoch-committed distributed checkpoints: the commit protocol's crash
windows, elastic resume across rank counts and decompositions, and the
supervised solvers' bitwise-recovery contract — all single-process on the
CPU fake mesh (the backend here has no multiprocess collectives; the
protocol is file-based precisely so these invariants are pinnable without
a pod).
"""

import json
import os

import numpy as np
import pytest

from cme213_tpu.config import GridMethod, SimParams
from cme213_tpu.core import faults, trace
from cme213_tpu.dist import (make_mesh_1d, make_mesh_2d, run_distributed_heat,
                             run_distributed_heat_supervised)
from cme213_tpu.dist.ckpt import (CommitError, check_meta, load_latest_commit)


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    yield
    faults.reset()


P_SMALL = SimParams(nx=32, ny=32, order=4, iters=8)


def _ckpt(tmp_path, name="ckpt"):
    return str(tmp_path / name)


# ------------------------------------------------------- commit + resume

def test_supervised_equals_uninterrupted_bitwise(tmp_path):
    mesh = make_mesh_1d(2)
    ref = run_distributed_heat(P_SMALL, mesh)
    out = run_distributed_heat_supervised(P_SMALL, mesh, _ckpt(tmp_path),
                                          ckpt_every=2)
    np.testing.assert_array_equal(out, ref)
    commits = trace.events("epoch-commit")
    assert [c["epoch"] for c in commits] == [1, 2, 3, 4]
    assert commits[-1]["step"] == 8


def test_resume_continues_from_commit_bitwise(tmp_path):
    """Stop after 4 of 8 iters (a committed mid-solve state), then resume
    the full solve: the recovered run is bitwise-equal to uninterrupted —
    deterministic chunking on the sync path."""
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2, iters=4)
    out = run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2)
    np.testing.assert_array_equal(out, run_distributed_heat(P_SMALL, mesh))
    # the resumed leg only commits epochs 3 and 4
    assert [c["epoch"] for c in trace.events("epoch-commit")] == [1, 2, 3, 4]


def test_resume_off_ignores_existing_commit(tmp_path):
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2, iters=4)
    out = run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=4,
                                          resume=False)
    np.testing.assert_array_equal(out, run_distributed_heat(P_SMALL, mesh))


def test_retention_keeps_two_generations(tmp_path):
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2)
    names = sorted(n for n in os.listdir(d) if n.startswith("epoch_"))
    assert names == ["epoch_00000003", "epoch_00000004"]  # older GC'd
    assert json.load(open(os.path.join(d, "COMMIT")))["epoch"] == 4
    assert json.load(open(os.path.join(d, "COMMIT.prev")))["epoch"] == 3


# ------------------------------------------------------- crash windows

def test_crash_between_shards_and_commit_resumes_prior_epoch(tmp_path):
    """The window the protocol exists for: epoch-2 shards are durable but
    the COMMIT publish never happened — resume must land on epoch 1,
    never the torn epoch 2."""
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    with faults.injected("ckpt:commit:2"):
        with pytest.raises(faults.InjectedFault):
            run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2)
    manifest, _ = load_latest_commit(d)
    assert (manifest["epoch"], manifest["step"]) == (1, 2)
    # recovery recomputes the lost epoch; final grid is bitwise-clean
    out = run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2)
    np.testing.assert_array_equal(out, run_distributed_heat(P_SMALL, mesh))


def test_torn_manifest_falls_back_a_generation(tmp_path):
    """ckpt:truncate tearing the COMMIT file itself (epoch 2's publish:
    write #3 after two epoch-1 shards... each epoch writes 2 shards +
    1 manifest, so the 6th checkpoint-file write is epoch 2's manifest):
    the torn COMMIT is skipped and COMMIT.prev (epoch 1) serves."""
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    with faults.injected("ckpt:truncate:6"):
        run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2,
                                        iters=4)
    manifest, _ = load_latest_commit(d)
    assert (manifest["epoch"], manifest["step"]) == (1, 2)
    assert any(e["candidate"] == "COMMIT"
               for e in trace.events("commit-invalid"))


def test_torn_shard_write_aborts_commit_not_resume(tmp_path):
    """A shard torn at write time (ckpt:truncate on an epoch-2 shard) is
    caught by the pre-publish read-back validation — the commit aborts
    with the previous epoch intact, instead of publishing a manifest over
    a bad shard and failing at resume time."""
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    with faults.injected("ckpt:truncate:4"):  # 2nd shard of epoch 2
        with pytest.raises(CommitError):
            run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2)
    manifest, _ = load_latest_commit(d)
    assert (manifest["epoch"], manifest["step"]) == (1, 2)


def test_shard_corrupted_after_publish_falls_back(tmp_path):
    """Bit-rot under a published commit: resume detects the checksum
    mismatch and falls back to the previous committed epoch."""
    mesh = make_mesh_1d(2)
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, mesh, d, ckpt_every=2, iters=4)
    live = json.load(open(os.path.join(d, "COMMIT")))
    shard = os.path.join(d, live["epoch_dir"], live["shards"][0]["file"])
    size = os.path.getsize(shard)
    with open(shard, "r+b") as f:
        f.truncate(size // 2)
    manifest, _ = load_latest_commit(d)
    assert manifest["epoch"] == live["epoch"] - 1
    assert trace.events("commit-invalid")


def test_nothing_recoverable_returns_none(tmp_path):
    assert load_latest_commit(str(tmp_path)) is None


# ------------------------------------------------------- elastic resume

@pytest.mark.parametrize("mesh_a, mesh_b", [
    (lambda: make_mesh_1d(2), lambda: make_mesh_1d(4)),   # 2 -> 4 ranks
    (lambda: make_mesh_1d(4), lambda: make_mesh_1d(2)),   # 4 -> 2 ranks
    (lambda: make_mesh_1d(4), lambda: make_mesh_2d(2, 2)),  # stripes->blocks
    (lambda: make_mesh_2d(2, 2), lambda: make_mesh_1d(2)),  # blocks->stripes
])
def test_elastic_resume_bitwise(tmp_path, mesh_a, mesh_b):
    """A commit written under one decomposition resumes under another —
    different rank count, even a different GridMethod — and the final
    grid still bitwise-matches the single-decomposition reference (the
    sync path is arithmetically identical per cell on every mesh)."""
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, mesh_a(), d, ckpt_every=2,
                                    iters=4)
    out = run_distributed_heat_supervised(P_SMALL, mesh_b(), d, ckpt_every=2)
    np.testing.assert_array_equal(
        out, run_distributed_heat(P_SMALL, make_mesh_1d(2)))


def test_elastic_resume_nondivisible_grid(tmp_path):
    """Ghost padding differs per mesh (30 rows over 4 devices pads to 32;
    over 2 it doesn't pad at all) — the commit stores the TRUE interior,
    so re-decomposition re-derives the padding."""
    p = SimParams(nx=30, ny=30, order=2, iters=6)
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(p, make_mesh_1d(4), d, ckpt_every=2,
                                    iters=2)
    out = run_distributed_heat_supervised(p, make_mesh_1d(2), d, ckpt_every=2)
    np.testing.assert_array_equal(out, run_distributed_heat(p, make_mesh_1d(2)))


def test_meta_mismatch_refuses_resume(tmp_path):
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, make_mesh_1d(2), d,
                                    ckpt_every=2, iters=2)
    other = SimParams(nx=32, ny=32, order=2, iters=8)  # different order
    with pytest.raises(CommitError):
        run_distributed_heat_supervised(other, make_mesh_1d(2), d,
                                        ckpt_every=2)


def test_check_meta_reports_mismatched_keys(tmp_path):
    d = _ckpt(tmp_path)
    run_distributed_heat_supervised(P_SMALL, make_mesh_1d(2), d,
                                    ckpt_every=2, iters=2)
    manifest, _ = load_latest_commit(d)
    check_meta(manifest, ny=32, order=4)  # matching subset passes
    with pytest.raises(CommitError, match="order"):
        check_meta(manifest, ny=32, order=8)


# ------------------------------------------------------- sharded scan

def test_supervised_scan_matches_plain_and_single_device(tmp_path):
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(512, 16, 15, iters=6, seed=0)
    mesh = make_mesh_1d(2)
    ref_dist = sp.run_spmv_scan_distributed(prob, mesh)
    out = sp.run_spmv_scan_distributed_supervised(prob, mesh,
                                                  _ckpt(tmp_path), every=2)
    np.testing.assert_array_equal(out, ref_dist)  # same mesh: bitwise
    np.testing.assert_allclose(out, sp.run_spmv_scan(prob), rtol=1e-5)


def test_supervised_scan_elastic_crash_resume(tmp_path):
    """Crash the scan solve in the commit window on 2 shards, resume on 4:
    the elastic path must still match the single-device reference."""
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(512, 16, 15, iters=6, seed=1)
    d = _ckpt(tmp_path)
    with faults.injected("ckpt:commit:2"):
        with pytest.raises(faults.InjectedFault):
            sp.run_spmv_scan_distributed_supervised(prob, make_mesh_1d(2),
                                                    d, every=2)
    manifest, _ = load_latest_commit(d)
    assert manifest["step"] == 2  # prior epoch survived the torn commit
    out = sp.run_spmv_scan_distributed_supervised(prob, make_mesh_1d(4),
                                                  d, every=2)
    np.testing.assert_allclose(out, sp.run_spmv_scan(prob), rtol=1e-5)


def test_supervised_scan_refuses_foreign_problem(tmp_path):
    """The commit pins a CRC of the problem's defining arrays — resuming a
    DIFFERENT problem from it must refuse, not silently mix solves."""
    from cme213_tpu.apps import spmv_scan as sp

    d = _ckpt(tmp_path)
    prob = sp.generate_problem(512, 16, 15, iters=6, seed=2)
    sp.run_spmv_scan_distributed_supervised(prob, make_mesh_1d(2), d,
                                            every=2)
    other = sp.generate_problem(512, 16, 15, iters=6, seed=3)
    with pytest.raises(CommitError):
        sp.run_spmv_scan_distributed_supervised(other, make_mesh_1d(2), d,
                                                every=2)
