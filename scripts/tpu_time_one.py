"""Time one heat-kernel config at 4000^2 order 8 on the TPU.

usage: tpu_time_one.py xla [iters]
       tpu_time_one.py pallas TILE [iters]          (stencil_pallas roll)
       tpu_time_one.py multi K TILE [iters]         (stencil_pallas k-step)
       tpu_time_one.py pipe K TILE [iters]          (pipeline, 1-D tiles)
       tpu_time_one.py pipe2d K TILE TILE_X [iters] (pipeline, 2-D tiles)

The post-capture tuning tool: one (kernel, tile, k) cell per invocation,
own process, so a crashed compile can't poison a longer campaign.  Run
ONLY when the capture watcher is done (/tmp/tpu_capture_done) — one TPU
client at a time.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time

import jax
import jax.numpy as jnp
import numpy as np

from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat
from cme213_tpu.ops.stencil_pallas import run_heat_multistep, run_heat_pallas
from cme213_tpu.ops.stencil_pipeline import (run_heat_pipeline,
                                             run_heat_pipeline2d)

p = SimParams(nx=4000, ny=4000, order=8, iters=1000)
u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
mode = sys.argv[1]
args = sys.argv[2:]


def _pop_int() -> int:
    try:
        return int(args.pop(0))
    except (IndexError, ValueError):
        raise SystemExit(__doc__)


if mode == "xla":
    fn = lambda u, it: run_heat(u, it, p.order, p.xcfl, p.ycfl)
elif mode == "pallas":
    t = _pop_int()
    fn = lambda u, it: run_heat_pallas(u, it, p.order, p.xcfl, p.ycfl,
                                       tile_y=t)
elif mode == "multi":
    k, t = _pop_int(), _pop_int()
    fn = lambda u, it: run_heat_multistep(u, it, p.order, p.xcfl, p.ycfl,
                                          p.bc, k=k, tile_y=t)
elif mode == "pipe":
    k, t = _pop_int(), _pop_int()
    fn = lambda u, it: run_heat_pipeline(u, it, p.order, p.xcfl, p.ycfl,
                                         p.bc, k=k, tile_y=t)
elif mode == "pipe2d":
    k, t, tx = _pop_int(), _pop_int(), _pop_int()
    fn = lambda u, it: run_heat_pipeline2d(u, it, p.order, p.xcfl, p.ycfl,
                                           p.bc, k=k, tile_y=t, tile_x=tx)
else:
    raise SystemExit(__doc__)

iters = _pop_int() if args else 200
if mode in ("multi", "pipe", "pipe2d"):
    # k-step kernels need iters to divide by k; never round down to zero
    iters = max(iters - iters % k, k)
# warmup/compile at both iteration counts; block the H2D upload BEFORE the
# clock (device_put is async — an unblocked put hides the 64 MB tunnel
# upload inside the timed region)
jax.block_until_ready(fn(jax.block_until_ready(jax.device_put(u0)), iters))
u = jax.block_until_ready(jax.device_put(u0))
t0 = time.perf_counter()
jax.block_until_ready(fn(u, iters))
dt = (time.perf_counter() - t0) / iters
print(f"{' '.join(sys.argv[1:])}: {dt*1e3:.3f} ms/iter, "
      f"{2*4*4000*4000/dt/1e9:.1f} GB/s eff", flush=True)
