"""Performance observability (ISSUE 6): roofline attribution, the
compile/run split + retrace detector, Chrome-trace export, and the bench
regression gate.

The load-bearing pins: cost-model bytes against hand-computed values
(the f32 ``gbs`` columns must not move when sweeps route through the
models), Chrome export structural validity (valid JSON, begin/end
pairing, rank→pid) on a synthetic 2-rank merged gang trace, the retrace
detector firing on a forced recompile of a known shape class, and the
regression gate's pass/fail verdicts on fixture metric pairs.
"""

import csv
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from cme213_tpu.core import metrics, roofline, trace
from cme213_tpu.core.trace import span
from cme213_tpu import trace_cli
from cme213_tpu.bench import regress


@pytest.fixture(autouse=True)
def _clean_telemetry():
    trace.flush_sink()
    trace.clear_events()
    yield
    trace.flush_sink()
    trace.clear_events()


# -------------------------------------------------------------- cost models

def test_heat_cost_hand_computed():
    # (1 read + 1 write) x 4 B x n^2 per iteration; 38 flops/point at
    # order 8 (the reference's data.ods accounting)
    c = roofline.heat_cost(100, order=8, iters=10)
    assert c.nbytes == 2 * 4 * 100 * 100 * 10
    from cme213_tpu.ops.stencil import flops_per_point

    assert flops_per_point(8) == 38
    assert c.flops == 38 * 100 * 100 * 10
    # dtype-aware by construction: f64 doubles the bytes, not the flops
    c64 = roofline.heat_cost(100, order=8, iters=10, dtype="f64")
    assert c64.nbytes == 2 * c.nbytes and c64.flops == c.flops
    # rectangular grids: ny x nx
    assert roofline.heat_cost(10, 20, order=2, iters=1).nbytes == 2 * 4 * 200


def test_spmv_cost_hand_computed_and_delegation():
    from cme213_tpu.apps import spmv_scan as sp

    c = roofline.spmv_scan_cost(1000, 7)
    assert c.nbytes == 1000 * (3 * 4 + 4) * 7 == sp.bytes_moved(1000, 7)
    assert sp.bytes_moved(1000, 2, elem=8) == 1000 * (3 * 8 + 4) * 2
    assert c.flops == 2 * 1000 * 7


def test_pagerank_cost_hand_computed_and_delegation():
    from cme213_tpu.apps import pagerank

    g = pagerank.build_graph(256, 4, seed=0)
    e = g.edges.shape[0]
    c = roofline.pagerank_cost(g.num_nodes, e, 6)
    assert c.nbytes == (e * 12 + 256 * 12) * 6 == pagerank.bytes_moved(g, 6)


def test_cipher_scan_transpose_costs():
    assert roofline.cipher_cost(4096).nbytes == 2 * 4096
    assert roofline.scan_cost(1 << 10).nbytes == 2 * 4 * (1 << 10)
    assert roofline.transpose_cost(64, 32).nbytes == 2 * 4 * 64 * 32
    assert roofline.transfer_cost(12345).nbytes == 12345
    # merge: ceil(log2 n) read+write passes; radix: 4 passes on u32 keys
    assert roofline.sort_cost(1024, "merge").nbytes == 2 * 4 * 1024 * 10
    assert roofline.sort_cost(1024, "radix").nbytes == 2 * 4 * 1024 * 4


def test_cost_gbs_helper():
    c = roofline.Cost(nbytes=2_000_000_000, flops=0)
    assert c.gbs(1000.0) == 2.0  # 2 GB in 1 s
    assert c.gbs(0.0) == 0.0


# ------------------------------------------------------------- device peaks

def test_peak_registry_and_env_override(monkeypatch):
    assert roofline.BUILTIN_PEAKS["tpu-v5e"].gbs == 819.0
    assert roofline.peak_for("TPU v5 lite").name == "tpu-v5e"
    assert roofline.peak_for("TPU v4").name == "tpu-v4"
    assert roofline.peak_for("mystery-chip") is None
    monkeypatch.setenv(roofline.DEVICE_PEAKS_ENV,
                       "mystery-chip:123:456, cpu:50:500, bad-entry")
    assert roofline.peak_for("mystery-chip").gbs == 123.0
    assert roofline.peak_for("cpu").gfs == 500.0  # override wins


def test_bench_peak_constant_matches_registry():
    """bench.py keeps a literal (imports must stay lazy there) pinned to
    the central registry."""
    import bench

    assert bench.HBM_PEAK_GBS == roofline.BUILTIN_PEAKS["tpu-v5e"].gbs


def test_attribute_pct_peak_and_bound(monkeypatch):
    monkeypatch.setenv(roofline.DEVICE_PEAKS_ENV, "testdev:100:1000")
    att = roofline.attribute(10.0, 1.0, device="testdev")
    assert att["pct_peak"] == 10.0 and att["bound"] == "memory"
    # high operational intensity flips the verdict
    att = roofline.attribute(1.0, 900.0, device="testdev")
    assert att["bound"] == "compute"
    # unknown device / no signal -> no verdict
    assert roofline.attribute(10.0, device="nope")["pct_peak"] is None
    assert roofline.attribute(0.0, device="testdev")["pct_peak"] is None


def test_span_roofline_attribution(monkeypatch):
    monkeypatch.setenv(roofline.DEVICE_PEAKS_ENV, "testdev:100:1000")
    monkeypatch.setattr(roofline, "_DETECTED", "testdev")
    with span("op.run", kernel="k", shape_class="s") as sp:
        sp.roofline(1_000_000, 10_000)
    end = trace.events("span-end")[-1]
    assert end["achieved_gbs"] > 0
    assert end["pct_peak"] > 0 and end["bound"] == "memory"


# ------------------------------------------------- compile/run + retraces

def test_compile_run_histograms_and_retrace_detector():
    metrics.reset()
    with span("op.compile", shape_class="a"):
        pass
    with span("op.run", shape_class="a"):
        pass
    assert trace.events("compile-retrace") == []
    # a different shape class is a fresh compile, not a retrace
    with span("op.compile", shape_class="b"):
        pass
    assert trace.events("compile-retrace") == []
    # a different kernel rung in the known class is a fresh program too
    # (a fallback ladder compiling its second rung is not a retrace)
    with span("op.compile", kernel="other", shape_class="a"):
        pass
    assert trace.events("compile-retrace") == []
    # the known (class, kernel) compiling again IS one
    with span("op.compile", shape_class="a"):
        pass
    ev = trace.events("compile-retrace")
    assert len(ev) == 1
    assert ev[0]["op"] == "op" and ev[0]["shape_class"] == "a"
    assert ev[0]["count"] == 2
    snap = metrics.snapshot()
    assert snap["counters"]["compile.retraces"] == 1
    assert snap["histograms"]["compile.op.a.ms"]["count"] == 3
    assert snap["histograms"]["compile.op.b.ms"]["count"] == 1
    assert snap["histograms"]["run.op.a.ms"]["count"] == 1
    assert trace.compile_counts()[("op", "a", None)] == 2
    assert trace.compile_counts()[("op", "a", "other")] == 1


def test_errored_compile_span_is_not_a_retrace():
    for _ in range(2):
        with pytest.raises(ValueError):
            with span("op.compile", shape_class="x"):
                raise ValueError("no lowering")
    assert trace.events("compile-retrace") == []
    assert ("op", "x", None) not in trace.compile_counts()


def test_forced_recompile_fires_through_real_dispatch(tmp_path, monkeypatch,
                                                      capsys):
    """Acceptance, both halves of ROADMAP item 5: the program cache kills
    the same-class retrace (second dispatch = cache hit, zero compile
    spans), and a genuinely forgotten program (cache reset mid-process)
    still fires the detector, visible in trace summary."""
    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.core import programs

    path = tmp_path / "t.jsonl"
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(path))
    prob = sp.generate_problem(256, 5, 4, iters=2, seed=1)
    sp.run_spmv_scan(prob, kernel="flat")
    # second call on the known shape class: a program-cache hit — no
    # compile span, no retrace (this used to rebuild the jit closure and
    # fire the detector; the cache is the fix the detector demanded)
    sp.run_spmv_scan(prob, kernel="flat")
    assert trace.events("compile-retrace") == []
    assert trace.events("program-cache-hit")
    # forget the program but NOT the detector's compile counts: the next
    # dispatch recompiles a class the process has seen -> a true retrace
    programs.reset()
    sp.run_spmv_scan(prob, kernel="flat")
    assert trace.events("compile-retrace")
    trace.flush_sink()
    monkeypatch.delenv(trace.TRACE_FILE_ENV)
    capsys.readouterr()
    assert trace_cli.main(["summary", str(path),
                           "--require", "compile-retrace"]) == 0
    out = capsys.readouterr().out
    assert "compile retraces: 1" in out
    assert "compile vs run (ms):" in out
    assert "roofline attribution:" in out


# --------------------------------------------------------------- summary

def _write_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _gang_fixture(tmp_path):
    """Synthetic 2-rank gang trace with nested spans (the export pins)."""
    base = {"pid": 11, "incarnation": 0}
    r0 = [
        {"event": "span-begin", "t": 1.0, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, **base},
        {"event": "span-begin", "t": 1.1, "rank": 0, "span": "solve.compile",
         "id": "a.2", "parent": "a.1", "shape_class": "n64", **base},
        {"event": "span-end", "t": 1.4, "rank": 0, "span": "solve.compile",
         "id": "a.2", "parent": "a.1", "ms": 300.0, "shape_class": "n64",
         **base},
        {"event": "heartbeat", "t": 1.5, "rank": 0, "step": 1, **base},
        {"event": "span-end", "t": 2.0, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, "ms": 1000.0, **base},
    ]
    r1 = [
        {"event": "span-begin", "t": 1.2, "rank": 1, "span": "solve",
         "id": "b.1", "parent": None, "pid": 12, "incarnation": 0},
        {"event": "span-end", "t": 1.9, "rank": 1, "span": "solve",
         "id": "b.1", "parent": None, "ms": 700.0, "pid": 12,
         "incarnation": 0},
        # an end whose begin was lost to the ring buffer -> X event
        {"event": "span-end", "t": 2.1, "rank": 1, "span": "orphan",
         "id": "b.9", "parent": None, "ms": 50.0, "pid": 12,
         "incarnation": 0},
        # an open span (killed rank) must be dropped, not left unpaired
        {"event": "span-begin", "t": 2.2, "rank": 1, "span": "open",
         "id": "b.5", "parent": None, "pid": 12, "incarnation": 0},
    ]
    launcher = [
        {"event": "gang-launch", "t": 0.5, "rank": None, "incarnation": 0,
         "world": 2, "coordinator": "127.0.0.1:1", "pid": 9},
    ]
    paths = []
    for name, recs in (("trace-main.jsonl", launcher),
                       ("trace-0.jsonl", r0), ("trace-1.jsonl", r1)):
        p = tmp_path / name
        _write_trace(p, recs)
        paths.append(str(p))
    return paths


def test_summary_json_machine_readable(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["summary", *paths, "--json"]) == 0
    out = capsys.readouterr().out
    agg = json.loads(out)  # the whole stdout is one JSON document
    assert agg["events"] == 10
    assert agg["ranks"] == ["main", "r0", "r1"]
    assert agg["spans"]["solve"] == [700.0, 1000.0]
    assert agg["compile_run"]["solve [n64]"]["compiles"] == 1
    assert agg["counts"]["heartbeat"] == 1


def test_summary_json_respects_require(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["summary", *paths, "--json",
                           "--require", "absent"]) == 1


# ---------------------------------------------------------------- export

def test_chrome_export_round_trip(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    out_path = tmp_path / "chrome.json"
    assert trace_cli.main(["export", *paths, "--out", str(out_path)]) == 0
    doc = json.loads(out_path.read_text())  # valid JSON
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"

    # rank -> pid mapping: main=0, rank0=1, rank1=2, named via metadata
    names = {e["pid"]: e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert names == {0: "main", 1: "rank 0", 2: "rank 1"}

    # begin/end pairing: every B has a matching E on the same (pid, tid),
    # properly nested in time (a stack machine never underflows)
    stacks = {}
    for e in sorted((e for e in evs if e["ph"] in "BE"),
                    key=lambda e: e["ts"]):
        key = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["name"])
        else:
            assert stacks.get(key), f"E without B on {key}"
            assert stacks[key].pop() == e["name"]
    assert all(not s for s in stacks.values())
    n_b = sum(1 for e in evs if e["ph"] == "B")
    assert n_b == sum(1 for e in evs if e["ph"] == "E") == 3

    # nesting depth -> tid: the compile child sits on tid 1 under its
    # parent's tid 0
    compile_b = next(e for e in evs if e["ph"] == "B"
                     and e["name"] == "solve.compile")
    assert compile_b["tid"] == 1 and compile_b["pid"] == 1

    # orphaned end reconstructed as a complete (X) event; open span dropped
    xs = [e for e in evs if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["orphan"]
    assert xs[0]["dur"] == 50.0 * 1e3
    assert not any(e.get("name") == "open" for e in evs)

    # non-span records become instant events
    assert {e["name"] for e in evs if e["ph"] == "i"} >= {"heartbeat",
                                                          "gang-launch"}
    # chronological for the viewer
    ts = [e["ts"] for e in evs if e["ph"] != "M"]
    assert ts == sorted(ts)


def test_chrome_export_stdout_and_parse_error(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["export", *paths]) == 0
    assert json.loads(capsys.readouterr().out)["traceEvents"]
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    assert trace_cli.main(["export", str(bad)]) == 2


# ------------------------------------------------------------ regression

def _write_csv(path, rows):
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        w.writeheader()
        w.writerows(rows)


def _fixture_dirs(tmp_path, fresh_gbs):
    base_d, fresh_d = tmp_path / "base", tmp_path / "fresh"
    base_d.mkdir()
    fresh_d.mkdir()
    rows = [{"size": 100, "kernel": "xla", "ms": 10.0, "gbs": 50.0,
             "error": ""}]
    _write_csv(base_d / "heat.csv", rows)
    _write_csv(fresh_d / "heat.csv",
               [{**rows[0], "gbs": fresh_gbs}])
    return str(fresh_d), str(base_d)


def test_regress_strict_fails_on_20pct_gbs_drop(tmp_path, capsys):
    """Acceptance: --strict exits nonzero on a synthetic 20% regression."""
    fresh, base = _fixture_dirs(tmp_path, fresh_gbs=40.0)  # 50 -> 40
    assert regress.main(["--fresh", fresh, "--baseline", base,
                         "--strict"]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    # report-only mode flags it but exits 0 (the advisory CI step)
    assert regress.main(["--fresh", fresh, "--baseline", base]) == 0


def test_regress_passes_within_noise(tmp_path, capsys):
    fresh, base = _fixture_dirs(tmp_path, fresh_gbs=47.5)  # -5% < threshold
    assert regress.main(["--fresh", fresh, "--baseline", base,
                         "--strict"]) == 0


def test_regress_lower_better_and_lost_signal(tmp_path):
    base_d, fresh_d = tmp_path / "b", tmp_path / "f"
    base_d.mkdir()
    fresh_d.mkdir()
    _write_csv(base_d / "s.csv", [
        {"op": "a", "ms": 10.0}, {"op": "b", "ms": 10.0}])
    _write_csv(fresh_d / "s.csv", [
        {"op": "a", "ms": 15.0},           # 1.5x slower
        {"op": "b", "ms": -1.0}])          # error row: lost signal
    out = tmp_path / "v.json"
    assert regress.main(["--fresh", str(fresh_d), "--baseline", str(base_d),
                         "--strict", "--json", str(out)]) == 1
    verdict = json.loads(out.read_text())
    assert verdict["verdict"] == "fail"
    assert {(r["row"], r["metric"]) for r in verdict["regressions"]} == {
        ("op=a", "ms"), ("op=b", "ms")}


def test_regress_metrics_json_row_counts(tmp_path):
    base_d, fresh_d = tmp_path / "b", tmp_path / "f"
    base_d.mkdir()
    fresh_d.mkdir()
    (base_d / "metrics.json").write_text(json.dumps(
        {"heat_bandwidth": {"rows": 12}, "scan_bandwidth": {"rows": 4}}))
    (fresh_d / "metrics.json").write_text(json.dumps(
        {"heat_bandwidth": {"rows": 9}, "scan_bandwidth": {"rows": 4}}))
    out = tmp_path / "v.json"
    assert regress.main(["--fresh", str(fresh_d), "--baseline", str(base_d),
                         "--strict", "--json", str(out)]) == 1
    verdict = json.loads(out.read_text())
    assert verdict["regressions"][0]["row"] == "heat_bandwidth"
    assert verdict["regressions"][0]["metric"] == "rows"


def test_regress_no_overlap_is_advisory_pass(tmp_path, capsys):
    base_d, fresh_d = tmp_path / "b", tmp_path / "f"
    base_d.mkdir()
    fresh_d.mkdir()
    _write_csv(base_d / "x.csv", [{"k": 1, "gbs": 5.0}])
    _write_csv(fresh_d / "y.csv", [{"k": 1, "gbs": 5.0}])
    assert regress.main(["--fresh", str(fresh_d), "--baseline", str(base_d),
                         "--strict"]) == 0
    assert "nothing to compare" in capsys.readouterr().out


def test_regress_banked_cpu_baselines_self_compare():
    """Acceptance: zero exit on the banked CPU baselines."""
    banked = str(Path(__file__).resolve().parent.parent
                 / "bench_results" / "cpu")
    assert regress.main(["--fresh", banked, "--baseline", banked,
                         "--strict"]) == 0


def test_regress_trajectory_from_bench_captures(tmp_path):
    hist = tmp_path / "hist"
    hist.mkdir()
    (hist / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "rc": 0, "tail": 'noise\n{"metric": "heat", '
                                  '"value": 100.0, "unit": "GB/s"}'}))
    (hist / "BENCH_r02.json").write_text(json.dumps(
        {"metric": "heat", "value": 50.0, "unit": "GB/s"}))
    fresh_bench = tmp_path / "bench.json"
    fresh_bench.write_text(json.dumps({"metric": "heat", "value": 61.0}))
    t = regress.trajectory_check(str(fresh_bench), str(hist), 0.1)
    assert t["best_prior"]["value"] == 100.0
    assert t["regression"] is True  # 0.61x: the BENCH_r02 class
    ok = regress.trajectory_check(str(fresh_bench), str(hist), 0.5)
    assert ok["regression"] is False


def test_regress_via_trace_cli(tmp_path):
    fresh, base = _fixture_dirs(tmp_path, fresh_gbs=40.0)
    assert trace_cli.main(["regress", "--fresh", fresh, "--baseline", base,
                           "--strict"]) == 1


# -------------------------------------------------- sweep columns + bench

def test_sweep_rows_carry_pct_peak_and_bound(monkeypatch):
    """Every sweep CSV row carries pct_peak/bound from the one cost-model
    source of truth, and the f32 gbs math is unchanged."""
    monkeypatch.setenv(roofline.DEVICE_PEAKS_ENV, "cpu:40:400")
    from cme213_tpu.bench.sweeps import heat_sweep, scan_sweep

    rows = heat_sweep(sizes=(32,), orders=(2,), iters=2, ks=(1,))
    for r in rows:
        assert "pct_peak" in r and "bound" in r
        assert r["bound"] == "memory"
        c = roofline.heat_cost(r["size"], order=r["order"],
                               iters=r["iters"], dtype=r["dtype"])
        # unchanged f32 math (rel tolerance: ms is rounded in the row)
        assert r["gbs"] == pytest.approx(c.gbs(r["ms"]), rel=0.1)
        # pct_peak derives from the unrounded gbs; the CSV gbs is rounded
        # to 2 decimals, so compare loosely at these tiny CI sizes
        assert r["pct_peak"] == pytest.approx(
            100 * r["gbs"] / 40.0, rel=0.05, abs=0.05)
    rows = scan_sweep(n=1 << 10, num_segments=4)
    assert all("pct_peak" in r and "bound" in r for r in rows)


def test_bench_kernel_failure_events_and_attribution(monkeypatch, capsys):
    """bench.py parent records per-rung failures as structured
    kernel-failure events and fills attribution on measured rows."""
    import subprocess

    import bench

    def fake_run(cmd, **kwargs):
        name = next(a.split("=", 1)[1] for a in cmd
                    if a.startswith("--kernel="))
        if name == "xla":
            return type("P", (), {
                "returncode": 0, "stderr": "",
                "stdout": json.dumps({
                    "kernel": name, "ok": True, "iters": 100,
                    "platform": "tpu", "ms_per_iter": 1.0,
                    "gbs": 200.0, "gflops": 9.5}) + "\n"})()
        return type("P", (), {
            "returncode": 0, "stderr": "",
            "stdout": json.dumps({
                "kernel": name, "ok": False, "platform": "tpu",
                "error": "UNAVAILABLE: pallas lowering"}) + "\n"})()

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    failures = trace.events("kernel-failure")
    assert len(failures) == len(bench.KERNELS) - 1
    assert all(trace.validate_record(r) == [] for r in failures)
    assert failures[0]["op"] == "heat2d"
    assert "UNAVAILABLE" in failures[0]["error"]
    # parent-side attribution vs the v5e registry entry (819 GB/s)
    assert out["pct_peak"] == pytest.approx(100 * 200.0 / 819.0, rel=1e-3)
    assert out["bound"] == "memory"
    row = next(r for r in out["kernels"] if r["kernel"] == "xla")
    assert row["pct_peak"] == out["pct_peak"]


def test_run_all_profile_dir_hook(tmp_path, monkeypatch):
    """CME213_PROFILE_DIR wraps the run in jax.profiler.trace and records
    device-memory snapshots as structured events."""
    from cme213_tpu.bench import run_all

    prof = tmp_path / "prof"
    monkeypatch.setenv("CME213_PROFILE_DIR", str(prof))
    rc = run_all.main(["--out", str(tmp_path / "out"), "--quick",
                       "--only", "scan_bandwidth"])
    assert rc == 0
    ev = trace.events("device-memory")
    assert ev and Path(ev[0]["path"]).exists()
    assert ev[0]["bytes"] > 0
    assert all(trace.validate_record(r) == [] for r in ev)
    assert any(prof.rglob("*"))  # the XPlane profile landed


def test_event_schema_covers_new_events():
    for name, fields in (("kernel-failure", ("op", "kernel", "error",
                                             "stage")),
                         ("device-memory", ("path", "bytes")),
                         ("compile-retrace", ("op", "shape_class",
                                              "kernel", "count")),
                         ("device-health", ("healthy", "platform",
                                            "devices", "probe_ms")),
                         ("attribution-mismatch", ("op", "rung",
                                                   "shape_class", "metric",
                                                   "predicted", "measured",
                                                   "ratio"))):
        assert trace.EVENT_SCHEMA[name] == fields
