"""Roofline attribution: per-op cost models + a device-peak registry.

The reference grades every kernel against *effective bandwidth relative
to hardware peak* — the hw2/hw_final GB/s tables quote each variant as a
fraction of the GTX 580's theoretical 192 GB/s, which is what turns a
bare number into a verdict ("14.6 GB/s" reads very differently once it
is "~2% of HBM peak, memory-bound").  This module is that grading layer
for the whole framework, following the Roofline model (Williams et al.):

- **Cost models** — one function per op family giving exact bytes moved
  and flops as a function of shape, dtype, and iteration count.  These
  replace the hand-rolled ``nbytes = 2*4*size*size*...`` formulas that
  used to be scattered through ``bench/sweeps.py`` (some dtype-aware,
  some hard-coding f32) — every bench row, span, and report now quotes
  bandwidth against the same accounting.
- **Device peaks** — detected device → peak HBM GB/s and GF/s
  (:func:`detect_device`, :func:`peak_for`).  The builtin table covers
  the TPU generations this framework targets plus a nominal host-DRAM
  entry for CPU stand-in runs; ``CME213_DEVICE_PEAKS=name:gbs:gfs[,...]``
  overrides or extends it (the peak numbers are published specs, i.e.
  knobs — not measurements).
- **Attribution** (:func:`attribute`) — achieved GB/s (+ GF/s) →
  ``pct_peak`` and a memory-vs-compute ``bound`` classification: an op
  is memory-bound when its operational intensity (flops/byte) sits below
  the machine balance (peak GF/s ÷ peak GB/s), compute-bound otherwise.

Everything here is host-side arithmetic over published constants; jax is
imported only (and lazily) to detect the local device kind.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

#: override/extend the peak table: ``name:gbs:gfs[,name:gbs:gfs...]``
DEVICE_PEAKS_ENV = "CME213_DEVICE_PEAKS"

_DTYPE_SIZES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2,
                "i32": 4, "u32": 4, "u8": 1, "i8": 1}


def elem_size(dtype) -> int:
    """Element size in bytes for a short dtype name ("f32"), a numpy
    dtype, or anything ``np.dtype`` accepts."""
    if isinstance(dtype, str) and dtype in _DTYPE_SIZES:
        return _DTYPE_SIZES[dtype]
    import numpy as np

    return int(np.dtype(dtype).itemsize)


@dataclass(frozen=True)
class Cost:
    """Exact useful-traffic accounting for one op invocation.

    ``nbytes`` is the *single-pass useful-byte* count (the "effective
    bandwidth" convention of ``bench.py``): kernels that move more than
    this — multi-sweep scans, halo re-reads — are quoted against the
    same denominator, which is what makes GB/s columns comparable."""

    nbytes: int
    flops: int

    def gbs(self, ms: float) -> float:
        """Achieved effective GB/s for a measured duration."""
        return self.nbytes / 1e9 / (ms / 1e3) if ms > 0 else 0.0

    def gflops(self, ms: float) -> float:
        return self.flops / 1e9 / (ms / 1e3) if ms > 0 else 0.0


@dataclass(frozen=True)
class DevicePeak:
    name: str
    gbs: float   # peak HBM/DRAM bandwidth, GB/s
    gfs: float   # peak dense-compute throughput, GF/s


#: Published per-chip peaks (HBM GB/s, dense GF/s).  The GF/s column is
#: the MXU dense number for the chip's native matmul precision — a
#: roofline *ceiling*, not a promise for the VPU-heavy stencil work here
#: (which is why everything in this framework classifies memory-bound).
#: The ``cpu`` entry is a nominal host-DRAM figure for CI stand-in runs;
#: override per host via CME213_DEVICE_PEAKS.
BUILTIN_PEAKS: dict[str, DevicePeak] = {
    "tpu-v2": DevicePeak("tpu-v2", 700.0, 46_000.0),
    "tpu-v3": DevicePeak("tpu-v3", 900.0, 123_000.0),
    "tpu-v4": DevicePeak("tpu-v4", 1228.0, 275_000.0),
    "tpu-v5e": DevicePeak("tpu-v5e", 819.0, 197_000.0),
    "tpu-v5p": DevicePeak("tpu-v5p", 2765.0, 459_000.0),
    "tpu-v6e": DevicePeak("tpu-v6e", 1640.0, 918_000.0),
    "cpu": DevicePeak("cpu", 40.0, 400.0),
}

#: substring (normalized device_kind) -> canonical peak-table key;
#: checked in order, first hit wins (v5 lite before the bare v5)
_KIND_ALIASES = (
    ("v5-lite", "tpu-v5e"), ("v5e", "tpu-v5e"),
    ("v6-lite", "tpu-v6e"), ("v6e", "tpu-v6e"),
    ("v5p", "tpu-v5p"), ("v5", "tpu-v5p"),
    ("v4", "tpu-v4"), ("v3", "tpu-v3"), ("v2", "tpu-v2"),
    ("cpu", "cpu"),
)


def normalize(name: str) -> str:
    return str(name).strip().lower().replace(" ", "-").replace("_", "-")


def peaks() -> dict[str, DevicePeak]:
    """The peak table: builtins overlaid with ``CME213_DEVICE_PEAKS``
    entries (malformed entries are ignored — a typo'd env var must not
    take down a bench run)."""
    table = dict(BUILTIN_PEAKS)
    for entry in os.environ.get(DEVICE_PEAKS_ENV, "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 3:
            continue
        try:
            table[normalize(parts[0])] = DevicePeak(
                normalize(parts[0]), float(parts[1]), float(parts[2]))
        except ValueError:
            continue
    return table


def peak_for(device: str | None) -> DevicePeak | None:
    """Peak entry for a device name/kind; None when unknown."""
    if not device:
        return None
    table = peaks()
    key = normalize(device)
    if key in table:
        return table[key]
    for frag, canonical in _KIND_ALIASES:
        if frag in key and canonical in table:
            return table[canonical]
    return None


_DETECTED: str | None = None
_DETECT_LOCK = threading.Lock()


def detect_device() -> str:
    """Normalized local device identity (``device_kind`` of device 0,
    falling back to the platform name).  Cached per process — backend
    initialization is expensive and the answer cannot change."""
    global _DETECTED
    with _DETECT_LOCK:
        if _DETECTED is None:
            try:
                import jax

                dev = jax.devices()[0]
                _DETECTED = normalize(
                    getattr(dev, "device_kind", "") or dev.platform)
            except Exception:  # noqa: BLE001 — attribution is best-effort
                _DETECTED = "unknown"
    return _DETECTED


def attribute(gbs: float, gflops: float = 0.0,
              device: str | None = None) -> dict:
    """Roofline verdict for an achieved (GB/s, GF/s) pair.

    Returns ``{"device", "peak_gbs", "peak_gfs", "pct_peak", "bound"}``;
    ``pct_peak`` is None (and ``bound`` empty) when the device has no
    peak entry or there is no bandwidth signal.  ``bound`` is "memory"
    when the op's operational intensity sits below the machine balance,
    "compute" above it.
    """
    dev = device if device is not None else detect_device()
    pk = peak_for(dev)
    out = {"device": normalize(dev) if dev else "unknown",
           "peak_gbs": pk.gbs if pk else None,
           "peak_gfs": pk.gfs if pk else None,
           "pct_peak": None, "bound": ""}
    if pk is None or not gbs or gbs <= 0:
        return out
    mem_frac = gbs / pk.gbs
    comp_frac = (gflops / pk.gfs) if (gflops and pk.gfs) else 0.0
    out["pct_peak"] = round(100.0 * mem_frac, 2)
    out["bound"] = "compute" if comp_frac > mem_frac else "memory"
    return out


# ------------------------------------------------------------ cost models

def heat_cost(ny: int, nx: int | None = None, *, order: int, iters: int,
              dtype="f32") -> Cost:
    """hw2 stencil accounting: (1 read + 1 write) × elem × ny×nx per
    iteration; flops from ``ops.stencil.flops_per_point`` (order 8 → the
    reference's 38 flops/point)."""
    from ..ops.stencil import flops_per_point

    nx = ny if nx is None else nx
    elem = elem_size(dtype)
    return Cost(2 * elem * ny * nx * iters,
                flops_per_point(order) * ny * nx * iters)


def spmv_scan_cost(n: int, iters: int, dtype="f32") -> Cost:
    """Single-pass form of the iterated SpMV-scan engine (fp.cu): per
    iteration read the value vector, the gathered ``xx`` vector, and the
    int32 head flags, write the value vector — ``(3·elem + 4)·n`` bytes;
    one multiply + one scan-add per element."""
    elem = elem_size(dtype)
    return Cost(n * (3 * elem + 4) * iters, 2 * n * iters)


def pagerank_cost(num_nodes: int, num_edges: int, iters: int) -> Cost:
    """hw1 accounting (``analysis/pagerank.cu:47-62``): per iteration each
    edge reads a 4B neighbor id + 4B rank + 4B inv_deg; each node reads
    2×4B offsets and writes a 4B rank.  Flops: multiply+add per edge plus
    the per-node damping combine."""
    return Cost((num_edges * 12 + num_nodes * 12) * iters,
                (2 * num_edges + 2 * num_nodes) * iters)


def cipher_cost(length: int, iters: int = 1) -> Cost:
    """hw1 shift cipher: read + write one byte per character (the packed
    variants move the same useful bytes — that is the point of quoting
    them against one count); one integer add-mod per character."""
    return Cost(2 * length * iters, length * iters)


def scan_cost(n: int, dtype="f32") -> Cost:
    """Single-pass scan family traffic: read + write each element once.
    Multi-sweep implementations (the flat log-n scan) are quoted against
    this same useful-byte count, exposing their extra traffic as lost
    effective bandwidth."""
    elem = elem_size(dtype)
    return Cost(2 * elem * n, n)


def transpose_cost(rows: int, cols: int, dtype="f32") -> Cost:
    elem = elem_size(dtype)
    return Cost(2 * elem * rows * cols, 0)


def transfer_cost(nbytes: int) -> Cost:
    """Host↔device copy: the bytes themselves, no flops."""
    return Cost(int(nbytes), 0)


def sort_cost(n: int, kind: str = "merge", key_bytes: int = 4) -> Cost:
    """Comparison/radix sort traffic models: merge sort reads + writes
    every key once per merge level (⌈log2 n⌉ passes); LSD radix on 32-bit
    keys with 8-bit digits makes 4 read+write passes."""
    import math

    passes = max(1, math.ceil(math.log2(max(2, n)))) if kind == "merge" else 4
    return Cost(2 * key_bytes * n * passes, 0)


#: discoverable registry: op family -> cost model
COST_MODELS = {
    "heat": heat_cost,
    "spmv_scan": spmv_scan_cost,
    "pagerank": pagerank_cost,
    "cipher": cipher_cost,
    "scan": scan_cost,
    "transpose": transpose_cost,
    "transfer": transfer_cost,
    "sort": sort_cost,
}
