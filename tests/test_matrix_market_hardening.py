"""MatrixMarket ingestion hardening: every malformed input is rejected at
the boundary with a structured ``DataValidationError`` (plus a
``data-validation`` trace event) instead of flowing downstream as garbage —
where a bad column index would surface as a silent gather clamp and a
truncated file as a wrong-but-finite answer.

The corruption matrix is property-style: start from one known-good file
and apply independent, realistic damage (truncation at several byte
offsets, header lies, out-of-range indices, non-finite values, fractional
indices) — each must either parse to the SAME arrays as the pristine file
or raise the structured error, never a third thing.
"""

import numpy as np
import pytest

from cme213_tpu.core import DataValidationError, trace
from cme213_tpu.apps.matrix_market import (coo_to_csr, csr_from_mtx,
                                           read_matrix_market, validate_csr)

GOOD = (
    "%%MatrixMarket matrix coordinate real general\n"
    "% a comment\n"
    "3 4 5\n"
    "1 1 2.0\n"
    "2 2 3.0\n"
    "3 1 -1.0\n"
    "3 3 4.0\n"
    "1 4 0.5\n"
)


def _write(tmp_path, text, name="m.mtx"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_good_file_parses_and_csr_validates(tmp_path):
    indptr, indices, data, shape = csr_from_mtx(_write(tmp_path, GOOD))
    assert shape == (3, 4)
    np.testing.assert_array_equal(indptr, [0, 2, 3, 5])
    np.testing.assert_array_equal(indices, [0, 3, 1, 0, 2])
    # canonical: columns sorted within each row
    np.testing.assert_array_equal(data, [2.0, 0.5, 3.0, -1.0, 4.0])


@pytest.mark.parametrize("mutation, invariant", [
    ("not a matrix at all\n1 2 3\n", "banner"),
    ("%%MatrixMarket matrix coordinate\n1 1 1\n1 1 1.0\n", "banner"),
    ("%%MatrixMarket matrix array real general\n2 2\n1.0\n", "format"),
    ("%%MatrixMarket matrix coordinate complex general\n1 1 1\n"
     "1 1 1.0 0.0\n", "field"),
    ("%%MatrixMarket matrix coordinate real skew-symmetric\n1 1 1\n"
     "1 1 1.0\n", "symmetry"),
    ("%%MatrixMarket matrix coordinate real general\nthree three 4\n",
     "size-line"),
    ("%%MatrixMarket matrix coordinate real general\n0 3 1\n1 1 1.0\n",
     "size-line"),
])
def test_header_lies_raise_structured(tmp_path, mutation, invariant):
    trace.clear_events()
    with pytest.raises(DataValidationError) as ei:
        read_matrix_market(_write(tmp_path, mutation))
    assert ei.value.record["invariant"] == invariant
    assert trace.events("data-validation")


@pytest.mark.parametrize("bad_entry, invariant", [
    ("4 1 1.0", "index-bounds"),       # row beyond nr=3
    ("1 5 1.0", "index-bounds"),       # col beyond nc=4
    ("0 1 1.0", "index-bounds"),       # below the 1-based origin
    ("1.5 1 1.0", "index-integrality"),
    ("1 1 nan", "value-finiteness"),
    ("1 1 inf", "value-finiteness"),
])
def test_bad_entries_raise_structured(tmp_path, bad_entry, invariant):
    text = GOOD.replace("1 4 0.5", bad_entry)
    with pytest.raises(DataValidationError) as ei:
        read_matrix_market(_write(tmp_path, text))
    assert ei.value.record["invariant"] == invariant


def test_truncation_at_every_entry_boundary(tmp_path):
    """A download cut at ANY entry boundary (fewer data lines than the
    header's nnz) is a structured entry-count error, never a silent
    short parse."""
    lines = GOOD.strip().split("\n")
    for keep in range(3, len(lines)):  # header + size kept, entries cut
        text = "\n".join(lines[:keep]) + "\n"
        with pytest.raises(DataValidationError) as ei:
            read_matrix_market(_write(tmp_path, text, f"t{keep}.mtx"))
        assert ei.value.record["invariant"] == "entry-count"


def test_truncation_at_every_byte_offset_never_silent(tmp_path):
    """Property: a file cut at ANY byte offset inside the entry block
    either raises the structured error or still parses to exactly the
    declared nnz with in-bounds indices (a text format cannot detect a
    cut that lands on a shorter-but-valid numeral — "0.5" → "0" — but it
    must never yield a wrong-shaped or out-of-bounds result)."""
    entries_start = GOOD.index("1 1 2.0")
    for cut in range(entries_start, len(GOOD)):
        path = _write(tmp_path, GOOD[:cut], f"c{cut}.mtx")
        try:
            rows, cols, vals, (nr, nc) = read_matrix_market(path)
        except DataValidationError:
            continue
        assert len(rows) == len(cols) == len(vals) == 5
        assert ((0 <= rows) & (rows < nr)).all()
        assert ((0 <= cols) & (cols < nc)).all()
        assert np.isfinite(vals).all()


def test_extra_entries_rejected(tmp_path):
    text = GOOD + "2 3 9.0\n"  # one more entry than the header declares
    with pytest.raises(DataValidationError) as ei:
        read_matrix_market(_write(tmp_path, text))
    assert ei.value.record["invariant"] == "entry-count"


def test_symmetric_upper_triangle_rejected(tmp_path):
    text = ("%%MatrixMarket matrix coordinate real symmetric\n"
            "2 2 2\n"
            "1 1 5.0\n"
            "1 2 7.0\n")  # upper-triangle entry in a symmetric file
    with pytest.raises(DataValidationError) as ei:
        read_matrix_market(_write(tmp_path, text))
    assert ei.value.record["invariant"] == "symmetry"


def test_pattern_field_two_columns(tmp_path):
    text = ("%%MatrixMarket matrix coordinate pattern general\n"
            "2 2 2\n"
            "1 1\n"
            "2 2\n")
    rows, cols, vals, shape = read_matrix_market(_write(tmp_path, text))
    np.testing.assert_array_equal(vals, [1.0, 1.0])


def test_validate_csr_invariants():
    shape = (3, 4)
    indptr = np.array([0, 2, 3, 5], np.int64)
    indices = np.array([0, 3, 1, 0, 2], np.int64)
    data = np.array([2.0, 0.5, 3.0, -1.0, 4.0], np.float32)
    validate_csr(indptr, indices, data, shape)  # pristine passes

    cases = [
        (np.array([0, 2, 1, 5], np.int64), indices, data,
         "indptr-monotone"),
        (np.array([1, 2, 3, 5], np.int64), indices, data,
         "indptr-origin"),
        (np.array([0, 2, 3, 4], np.int64), indices, data,
         "nnz-consistency"),
        (np.array([0, 2, 3], np.int64), indices, data, "indptr-length"),
        (indptr, np.array([0, 3, 1, 0, 4], np.int64), data,
         "column-bounds"),
        (indptr, indices, np.array([2.0, 0.5, np.nan, -1.0, 4.0],
                                   np.float32), "value-finiteness"),
    ]
    for p, i, d, invariant in cases:
        with pytest.raises(DataValidationError) as ei:
            validate_csr(p, i, d, shape)
        assert ei.value.record["invariant"] == invariant, invariant


def test_coo_to_csr_roundtrip_random():
    """Random COO sets → CSR always satisfies the invariants and
    preserves every (row, col, value) triplet."""
    rng = np.random.default_rng(0)
    for trial in range(10):
        nr, nc = rng.integers(1, 20, size=2)
        nnz = int(rng.integers(0, nr * nc))
        rows = rng.integers(0, nr, size=nnz).astype(np.int64)
        cols = rng.integers(0, nc, size=nnz).astype(np.int64)
        vals = rng.standard_normal(nnz).astype(np.float32)
        indptr, indices, data = coo_to_csr(rows, cols, vals, (nr, nc))
        validate_csr(indptr, indices, data, (int(nr), int(nc)))
        got = sorted(zip(np.repeat(np.arange(nr), np.diff(indptr)),
                         indices, data))
        want = sorted(zip(rows, cols, vals))
        assert [(r, c) for r, c, _ in got] == [(r, c) for r, c, _ in want]
        np.testing.assert_allclose(sorted(v for _, _, v in got),
                                   sorted(v for _, _, v in want))
