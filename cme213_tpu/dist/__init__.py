from .mesh import make_mesh_1d, make_mesh_2d, mesh_for_method
from .heat import (distributed_heat_step, prepare_distributed_heat,
                   run_distributed_heat, run_distributed_heat_supervised)
from .scan import distributed_segmented_scan, make_iterated_sharded_scan

__all__ = [
    "make_mesh_1d",
    "make_mesh_2d",
    "mesh_for_method",
    "distributed_heat_step",
    "prepare_distributed_heat",
    "run_distributed_heat",
    "run_distributed_heat_supervised",
    "distributed_segmented_scan",
    "make_iterated_sharded_scan",
]
