"""Crash flight recorder (``core/flight.py``): explicit dumps, the
crash paths (unhandled exception, fatal signal, ``rankkill`` hard-exit —
including inside a supervised 2-rank gang), dump-file atomicity, and the
``trace flight`` rendering."""

import glob
import json
import os
import signal
import subprocess
import sys

import pytest

from cme213_tpu.core import faults, flight, metrics, trace

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    monkeypatch.delenv(flight.FLIGHT_DIR_ENV, raising=False)
    # earlier suite members may have run a CLI main() that installs the
    # recorder (loadgen does); start from the uninstalled state
    flight._uninstall_for_tests()
    trace.clear_events()
    metrics.reset()
    yield
    flight._uninstall_for_tests()
    faults.reset()
    metrics.reset()


def _dumps(d):
    return sorted(glob.glob(os.path.join(str(d), "flight-*.json")))


def _run(body, tmp_path, **env):
    """Run a python -c body with the flight dir pointed at tmp_path."""
    full = dict(os.environ)
    full.pop("CME213_FAULTS", None)
    full.pop("CME213_INCARNATION", None)
    full.update({flight.FLIGHT_DIR_ENV: str(tmp_path)}, **env)
    return subprocess.run(
        [sys.executable, "-c", f"import sys; sys.path.insert(0, {_REPO!r})\n"
         + body],
        env=full, capture_output=True, text=True, timeout=60)


# ------------------------------------------------------------ dump basics

def test_dump_unarmed_is_noop(tmp_path):
    assert not flight.installed()
    assert flight.dump("nothing-listening") is None
    assert _dumps(tmp_path) == []


def test_explicit_dump_contents(tmp_path, monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    metrics.counter("faults.fail").inc(3)
    with trace.span("heat.run", shape_class="32x32"):
        path = flight.dump("operator-requested")   # mid-span: span open
    assert path and os.path.dirname(path) == str(tmp_path)
    doc = json.loads(open(path).read())
    assert doc["flight"] == 1
    assert doc["reason"] == "operator-requested"
    assert doc["pid"] == os.getpid()
    assert doc["platform"]["python"] == sys.version.split()[0]
    assert doc["traceback"] is None
    assert doc["metrics"]["counters"]["faults.fail"] == 3
    assert [s["span"] for s in doc["open_spans"]] == ["heat.run"]
    assert any(e["event"] == "span-begin" for e in doc["events"])
    # the dump records itself in the trace ring
    (ev,) = trace.events("flight-dump")
    assert ev["reason"] == "operator-requested" and ev["path"] == path


def test_dump_is_atomic_no_tmp_leftovers(tmp_path, monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    for i in range(3):
        metrics.counter("x").inc()
        assert flight.dump(f"r{i}")
    paths = _dumps(tmp_path)
    assert len(paths) == 3                       # unique names, no clobber
    for p in paths:
        json.loads(open(p).read())               # every file parses whole
    assert glob.glob(os.path.join(str(tmp_path), "*.tmp*")) == []


def test_dump_with_exception_carries_traceback(tmp_path, monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    try:
        raise ValueError("poisoned state at step 7")
    except ValueError as e:
        path = flight.dump("numeric-abort", exc=e)
    doc = json.loads(open(path).read())
    assert "poisoned state at step 7" in doc["traceback"]
    assert "ValueError" in doc["traceback"]


# ------------------------------------------------------------ crash paths

def test_unhandled_exception_dumps_before_death(tmp_path):
    proc = _run(
        "from cme213_tpu.core import flight\n"
        "flight.install()\n"
        "raise RuntimeError('solver blew up')\n", tmp_path)
    assert proc.returncode == 1
    assert "solver blew up" in proc.stderr       # chained hook still prints
    (path,) = _dumps(tmp_path)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "unhandled-exception"
    assert "solver blew up" in doc["traceback"]


def test_rankkill_hard_exit_dumps(tmp_path):
    """``os._exit`` bypasses atexit and the excepthook — the kill guard
    dumps inline, so even the hard-exit path leaves a black box."""
    proc = _run(
        "from cme213_tpu.core import faults\n"
        "faults.maybe_kill_rank(step=0)\n", tmp_path,
        CME213_FAULTS="rankkill:0:0", JAX_PROCESS_ID="0")
    assert proc.returncode == faults.KILL_EXIT
    (path,) = _dumps(tmp_path)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "rankkill"
    assert doc["rank"] == "0" and doc["incarnation"] == "0"
    assert doc["metrics"]["counters"]["faults.rankkill"] == 1
    assert any(e["event"] == "fault-injected" for e in doc["events"])


def test_fatal_signal_dumps_then_dies_by_signal(tmp_path):
    proc = _run(
        "import os, signal\n"
        "from cme213_tpu.core import flight\n"
        "flight.install()\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n", tmp_path)
    assert proc.returncode == -signal.SIGTERM    # signal semantics kept
    (path,) = _dumps(tmp_path)
    assert json.loads(open(path).read())["reason"] == "signal:SIGTERM"


def test_supervised_gang_rankkill_leaves_per_rank_dump(tmp_path,
                                                       monkeypatch, capsys):
    """A rank hard-killed inside a supervised gang leaves a parseable
    flight dump behind while the gang restarts and completes."""
    from cme213_tpu.dist.launch import launch_supervised

    monkeypatch.setenv("CME213_FAULTS", "rankkill:1:0")
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    body = (f"import sys; sys.path.insert(0, {_REPO!r}); import os; "
            "from cme213_tpu.core import faults; faults.maybe_kill_rank(); "
            "print('rank', os.environ['JAX_PROCESS_ID'], 'ok')")
    rc = launch_supervised(2, [sys.executable, "-c", body],
                           stall_timeout=60, max_restarts=1, timeout=120)
    out = capsys.readouterr().out
    assert rc == 0, out
    (path,) = _dumps(tmp_path)                   # the killed rank's box
    doc = json.loads(open(path).read())
    assert doc["reason"] == "rankkill"
    assert doc["rank"] == "1" and doc["incarnation"] == "0"


# --------------------------------------------------------------- rendering

def test_trace_flight_renders_dump(tmp_path, monkeypatch, capsys):
    from cme213_tpu.trace_cli import main as trace_main

    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    metrics.counter("serve.batches").inc(2)
    with trace.span("serve.batch", op="echo", shape_class="k", size=2):
        try:
            raise RuntimeError("ladder exhausted")
        except RuntimeError as e:
            path = flight.dump("serve-crash", exc=e)
    assert trace_main(["flight", path]) == 0
    out = capsys.readouterr().out
    assert "flight dump: reason 'serve-crash'" in out
    assert "ladder exhausted" in out
    assert "serve.batch" in out                  # open span + timeline
    assert "metrics at death: 1 counters" in out


def test_trace_flight_rejects_non_dump(tmp_path, capsys):
    from cme213_tpu.trace_cli import main as trace_main

    bad = tmp_path / "not-a-dump.json"
    bad.write_text('{"counters": {}}')
    assert trace_main(["flight", str(bad)]) == 2
    assert "not a flight dump" in capsys.readouterr().err
