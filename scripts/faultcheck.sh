#!/bin/bash
# Fault-injection smoke gate — the resilience layer exercised end-to-end
# under an injected-failure matrix (CPU backend, deterministic faults,
# no sleeps).  CI runs this next to tier1.sh; humans run it the same way:
#
#   bash scripts/faultcheck.sh
#
# Asserts, per ISSUE 2:
#  1. bench harness: run_all under an injected first-attempt sweep failure
#     exits 0 (the retry recovers) with a POPULATED failures.json — a
#     single flaky sweep must not zero a capture run;
#  2. kernel ladder: spmv_scan under an injected pallas-fused failure
#     completes on a demoted rung with f64-checked-correct results, and
#     the demotion appears in the structured trace log;
#  3. launcher: an injected rank kill is survived by --max-restarts 1
#     (same rank id relaunched), and kills the job without the budget.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
OUT=$(mktemp -d)
trap 'rm -rf "$OUT"' EXIT

echo "== 1/3 run_all: injected sweep failure -> retry + failures.json"
CME213_FAULTS="fail:sweep.scan_bandwidth" \
    python -m cme213_tpu.bench.run_all --quick --out "$OUT" \
    --only scan_bandwidth
python - "$OUT" <<'PY'
import json, sys
m = json.load(open(sys.argv[1] + "/failures.json"))
assert m["failed"] == [], m
assert [r["sweep"] for r in m["retried"]] == ["scan_bandwidth"], m
print("failures.json populated:", m["retried"][0]["error"])
PY

echo "== 2/3 spmv ladder: injected pallas failure -> demoted, correct"
CME213_FAULTS="fail:spmv_scan.pallas-fused" python - <<'PY'
from cme213_tpu.apps import spmv_scan as sp
from cme213_tpu.core import trace
prob = sp.generate_problem(4096, 64, 63, iters=4, seed=0)
out = sp.run_spmv_scan(prob, kernel="pallas-fused")
served = trace.events("served")[-1]
assert served["demoted"] and served["rung"] == "blocked", served
errs = sp.external_check(prob, out)
assert errs["rel_l2"] < 1e-4, errs
print("demoted to", served["rung"], "rel_l2", errs["rel_l2"])
PY

echo "== 3/3 launcher: injected rank kill survived by --max-restarts 1"
CME213_FAULTS="rankkill:1:0" python -m cme213_tpu.dist.launch \
    --np 2 --max-restarts 1 --timeout 120 -- \
    python -c "import os; from cme213_tpu.core import faults; \
faults.maybe_kill_rank(); print('rank', os.environ['JAX_PROCESS_ID'], 'ok')"
if CME213_FAULTS="rankkill:1:0" python -m cme213_tpu.dist.launch \
    --np 2 --timeout 120 -- \
    python -c "from cme213_tpu.core import faults; faults.maybe_kill_rank()" \
    2>/dev/null; then
  echo "ERROR: rank kill without restart budget should fail the job" >&2
  exit 1
fi

echo "faultcheck OK"
