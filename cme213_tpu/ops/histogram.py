"""Histograms — three TPU-native formulations.

The reference builds dense histograms two ways: sort + ``upper_bound`` binary
search (``hw/hw3/programming/solve_cipher.cu:131-154``) and ``reduce_by_key``
over sorted data (``hw/hw3/solution/solve_cipher_solution.cu:118-127``).  Here:

- ``histogram_sort``     — the sort + searchsorted formulation (direct analog).
- ``histogram_onehot``   — one-hot reduction; for digit histograms this is a
  (n × nbins) matmul against ones, i.e. MXU-shaped (used by the radix sort's
  per-block histograms, strategy P7).
- ``histogram_segment``  — ``segment_sum`` scatter-add (reduce_by_key analog).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def histogram_sort(x: jnp.ndarray, nbins: int) -> jnp.ndarray:
    """Sort, then count per bin via searchsorted upper bounds."""
    xs = jnp.sort(x)
    bounds = jnp.searchsorted(xs, jnp.arange(nbins, dtype=xs.dtype), side="right")
    lower = jnp.concatenate([jnp.zeros((1,), bounds.dtype), bounds[:-1]])
    return (bounds - lower).astype(jnp.int32)


def histogram_onehot(x: jnp.ndarray, nbins: int) -> jnp.ndarray:
    """Sum of one-hot rows (XLA fuses; MXU-friendly for blocked shapes)."""
    oh = jax.nn.one_hot(x, nbins, dtype=jnp.int32)
    return oh.sum(axis=tuple(range(oh.ndim - 1)))


def histogram_segment(x: jnp.ndarray, nbins: int) -> jnp.ndarray:
    """Scatter-add formulation (Thrust reduce_by_key analog)."""
    ones = jnp.ones_like(x, dtype=jnp.int32)
    return jax.ops.segment_sum(ones, x.astype(jnp.int32), num_segments=nbins)
