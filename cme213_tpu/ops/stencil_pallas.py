"""Pallas VMEM-tiled heat stencil — the hand-tuned kernel path.

TPU-native analog of the reference's shared-memory stencil kernel
(``gpuShared``, ``hw/hw2/programming/2dHeat.cu:466-515``): where 128×4 CUDA
threads cooperatively staged a 128×32 halo tile into ``__shared__`` and each
thread emitted multiple rows, here each Pallas grid step DMAs a
``(tile_y + 2·border, gx)`` row band from HBM into a VMEM scratch buffer
(the explicit analog of the cooperative staging), then computes a
``(tile_y, nx)`` output tile with the same shifted-slice expression as the
XLA path (`ops/stencil.py`) — so results are bitwise comparable.

The pure-XLA path usually reaches the HBM roofline on TPU because XLA fuses
the whole stencil into one pass; this kernel exists as (a) the explicit
VMEM-tiling parity artifact for strategy P3, and (b) a base to hand-tune
(e.g. fusing the iteration loop or double-buffering the band DMA).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import BORDER_FOR_ORDER, STENCIL_COEFFS


def _make_kernel(order: int, tile_y: int, gx: int, xcfl: float, ycfl: float):
    b = BORDER_FOR_ORDER[order]
    coeffs = STENCIL_COEFFS[order]
    nx = gx - 2 * b

    def kernel(u_hbm, out_ref, band, sem):
        i = pl.program_id(0)
        # cooperative tile staging: DMA the row band (+halo) into VMEM
        dma = pltpu.make_async_copy(
            u_hbm.at[pl.ds(i * tile_y, tile_y + 2 * b), :], band, sem)
        dma.start()
        dma.wait()
        u = band[:]
        dtype = u.dtype
        center = u[b:b + tile_y, b:b + nx]
        accx = jnp.zeros_like(center)
        accy = jnp.zeros_like(center)
        for k, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * u[b:b + tile_y, k:k + nx]
            accy = accy + c * u[k:k + tile_y, b:b + nx]
        out_ref[:] = (center + jnp.asarray(xcfl, dtype) * accx
                      + jnp.asarray(ycfl, dtype) * accy)

    return kernel


@partial(jax.jit,
         static_argnames=("order", "xcfl", "ycfl", "tile_y", "interpret"))
def stencil_interior_pallas(u: jnp.ndarray, order: int, xcfl: float,
                            ycfl: float, tile_y: int = 256,
                            interpret: bool = False) -> jnp.ndarray:
    """New interior (ny, nx) from halo grid (gy, gx), VMEM-tiled.

    ``ny`` must divide by ``tile_y`` (drivers pick a divisor; see
    ``pick_tile``).  ``xcfl``/``ycfl`` must be concrete floats (they are
    baked into the kernel as constants).
    """
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    assert ny % tile_y == 0, "ny must divide by tile_y"
    kernel = _make_kernel(order, tile_y, gx, float(xcfl), float(ycfl))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((ny, nx), u.dtype),
        grid=(ny // tile_y,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile_y, nx), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((tile_y + 2 * b, gx), u.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(u)


def pick_tile(ny: int, target: int = 256) -> int:
    """Largest divisor of ny not exceeding ``target``."""
    t = min(target, ny)
    while ny % t:
        t -= 1
    return t


def _make_multistep_kernel(order: int, k: int, tile_y: int, gy: int, gx: int,
                           bc: tuple[float, float, float, float],
                           xcfl: float, ycfl: float):
    """k fused timesteps per HBM pass (temporal blocking).

    Each grid step loads a ``(tile_y + 2·k·b, gx)`` band into VMEM and
    applies the stencil k times entirely on-chip, re-imposing the Dirichlet
    BC bands between sub-steps (masked writes keyed on global row/column
    indices, in the reference's band order: bottom/top rows then left/right
    columns overwrite corners).  The validity margin shrinks by ``b`` rows
    per sub-step, exactly covering the extra halo — the central ``tile_y``
    rows are exact after k steps.  HBM traffic per k steps ≈ one read + one
    write of the grid, vs k of each for the one-step-per-pass kernels: the
    optimization the 48 KB shared memories of the reference's era couldn't
    hold enough halo for.
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    coeffs = STENCIL_COEFFS[order]
    nx = gx - 2 * b
    H = tile_y + 2 * K
    bc_top, bc_left, bc_bottom, bc_right = bc

    def substep(u):
        dtype = u.dtype
        center = u[b:H - b, b:b + nx]
        accx = jnp.zeros_like(center)
        accy = jnp.zeros_like(center)
        for kk, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * u[b:H - b, kk:kk + nx]
            accy = accy + c * u[kk:kk + H - 2 * b, b:b + nx]
        return (center + jnp.asarray(xcfl, dtype) * accx
                + jnp.asarray(ycfl, dtype) * accy)

    def kernel(u_hbm, out_ref, band, sem):
        i = pl.program_id(0)
        dma = pltpu.make_async_copy(
            u_hbm.at[pl.ds(i * tile_y, H), :], band, sem)
        dma.start()
        dma.wait()
        # global halo-grid row of band-local row l: hr = i*tile_y + l - (K-b)
        hr0 = i * tile_y - (K - b)
        rows = jax.lax.broadcasted_iota(jnp.int32, (H, gx), 0) + hr0
        cols = jax.lax.broadcasted_iota(jnp.int32, (H, gx), 1)

        u = band[:]
        for _ in range(k):
            new = u.at[b:H - b, b:b + nx].set(substep(u))
            # re-impose Dirichlet bands (order: bottom/top, then left/right)
            new = jnp.where(rows < b, jnp.asarray(bc_bottom, u.dtype), new)
            new = jnp.where(rows >= gy - b,
                            jnp.asarray(bc_top, u.dtype), new)
            new = jnp.where(cols < b, jnp.asarray(bc_left, u.dtype), new)
            new = jnp.where(cols >= gx - b,
                            jnp.asarray(bc_right, u.dtype), new)
            u = new
        out_ref[:] = u[K:K + tile_y, b:b + nx]

    return kernel


@partial(jax.jit,
         static_argnames=("order", "iters", "k", "xcfl", "ycfl", "bc",
                          "tile_y", "interpret"),
         donate_argnums=(0,))
def run_heat_multistep(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                       bc: tuple[float, float, float, float], k: int = 4,
                       tile_y: int = 128, interpret: bool = False):
    """Iterated solve with k timesteps fused per HBM pass.

    ``u`` is the (gy, gx) halo grid; ``bc`` = (top, left, bottom, right)
    Dirichlet values (as in ``SimParams.bc``).  ``iters`` must divide by
    ``k`` and ``ny`` by ``tile_y``.  Returns the full halo grid.
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    assert iters % k == 0, "iters must divide by k"
    assert ny % tile_y == 0, "ny must divide by tile_y"

    kernel = _make_multistep_kernel(order, k, tile_y, gy, gx, bc,
                                    float(xcfl), float(ycfl))
    bc_top, bc_left, bc_bottom, bc_right = bc
    pad = K - b

    def call(padded):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((ny, nx), u.dtype),
            grid=(ny // tile_y,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((tile_y, nx), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((tile_y + 2 * K, gx), u.dtype),
                pltpu.SemaphoreType.DMA,
            ],
            interpret=interpret,
        )(padded)

    # extend the halo grid with replicated BC rows so every tile's input
    # window is in-bounds with a static size (the replicas hold exactly the
    # values an infinite Dirichlet border would)
    padded = jnp.concatenate([
        jnp.full((pad, gx), jnp.asarray(bc_bottom, u.dtype)),
        u,
        jnp.full((pad, gx), jnp.asarray(bc_top, u.dtype)),
    ], axis=0) if pad else u
    if pad:
        # left/right bands must extend through the replica rows too
        padded = padded.at[:pad, :b].set(jnp.asarray(bc_left, u.dtype))
        padded = padded.at[:pad, gx - b:].set(jnp.asarray(bc_right, u.dtype))
        padded = padded.at[-pad:, :b].set(jnp.asarray(bc_left, u.dtype))
        padded = padded.at[-pad:, gx - b:].set(jnp.asarray(bc_right, u.dtype))

    def body(_, p):
        new_int = call(p)
        return p.at[K:K + ny, b:b + nx].set(new_int)

    padded = lax.fori_loop(0, iters // k, body, padded)
    return padded[pad:pad + gy, :] if pad else padded


@partial(jax.jit,
         static_argnames=("order", "iters", "xcfl", "ycfl", "tile_y",
                          "interpret"),
         donate_argnums=(0,))
def run_heat_pallas(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                    tile_y: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Iterated solve using the Pallas stencil (functional ping-pong)."""
    b = BORDER_FOR_ORDER[order]

    def body(_, g):
        new = stencil_interior_pallas(g, order, xcfl, ycfl, tile_y=tile_y,
                                      interpret=interpret)
        return g.at[b:-b, b:-b].set(new)

    return lax.fori_loop(0, iters, body, u)
