"""Durable long-job lane (``serve/jobs.py``): store durability, the
write-ahead epoch loop, preemption/resume, and the transport controls.

The contract under test is the one Torque gave the reference's
``qsub`` scripts: a submitted solve survives the death of whatever was
running it.  Here that means (a) the record store survives torn writes
(CRC + ``.prev`` fallback + quarantine), (b) a committed epoch is never
re-executed — after any crash/injected-fault recovery the ``job-epoch``
numbers stay unique and the final ranking is **bitwise-equal** to an
uninterrupted run, and (c) interactive traffic strictly preempts job
epochs at epoch boundaries.  The ``slow``-marked arcs run the same
story against a real worker fleet: SIGKILL mid-job and a whole-fleet
down/up with the jobs directory as the only survivor.
"""

import json
import threading
import time

import numpy as np
import pytest

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.serve import Server
from cme213_tpu.serve.loadgen import build_mix
from cme213_tpu.serve import jobs as jobs_mod
from cme213_tpu.serve import wire
from cme213_tpu.serve.jobs import (
    DONE,
    FAILED,
    PENDING,
    PREEMPTED,
    RUNNING,
    JobError,
    JobExecutor,
    JobStore,
    submit_job,
)
from cme213_tpu.serve.workloads import JOB_KINDS, PageRankJob


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    faults.reset()
    yield
    faults.reset()
    metrics.reset()


#: small-but-multi-epoch PageRank: 3 epochs of 4 iterations (the
#: kind requires even epochs: the fused rung iterates in pairs)
PARAMS = {"nodes": 96, "avg_edges": 4, "iters": 12, "epoch": 4, "seed": 7}


def _bits(arr) -> bytes:
    return np.ascontiguousarray(np.asarray(arr)).tobytes()


def _run_to_terminal(ex: JobExecutor, budget: int = 200) -> None:
    for _ in range(budget):
        if not ex.tick():
            if all(r["state"] in jobs_mod.TERMINAL
                   for r in ex.store.list_jobs()):
                return
        time.sleep(0)
    raise AssertionError("job did not reach a terminal state in budget")


def _clean_result(tmp_path, params=None) -> np.ndarray:
    """Uninterrupted run in a scratch store — the bitwise baseline."""
    store = JobStore(str(tmp_path / "baseline"))
    submit_job(store, "baseline", "pagerank", dict(params or PARAMS))
    _run_to_terminal(JobExecutor(store, rank="base"))
    rec = store.load("baseline")
    assert rec["state"] == DONE
    return store.load_result("baseline")


# ------------------------------------------------------------- the store


def test_submit_is_idempotent(tmp_path):
    store = JobStore(str(tmp_path))
    rec1, created1 = submit_job(store, "j1", "pagerank", dict(PARAMS))
    rec2, created2 = submit_job(store, "j1", "pagerank", dict(PARAMS))
    assert created1 and not created2
    assert rec1 == rec2 and rec2["state"] == PENDING
    assert len(trace.events("job-submitted")) == 1
    assert rec1["total_epochs"] == 3 and rec1["epoch_iters"] == 4


def test_bad_ids_and_unknown_ops_are_refused(tmp_path):
    store = JobStore(str(tmp_path))
    with pytest.raises(JobError):
        submit_job(store, "../escape", "pagerank", {})
    with pytest.raises(JobError):
        submit_job(store, "j1", "not-a-job", {})
    with pytest.raises(ValueError):
        submit_job(store, "j1", "pagerank", {"bogus_knob": 3})


def test_illegal_transition_raises(tmp_path):
    store = JobStore(str(tmp_path))
    rec, _ = submit_job(store, "j1", "pagerank", dict(PARAMS))
    with pytest.raises(JobError):
        store.publish(rec, state=DONE)       # PENDING -> DONE is illegal
    rec = store.load("j1")
    assert rec["state"] == PENDING


def test_torn_record_falls_back_to_prev_and_quarantines(tmp_path):
    store = JobStore(str(tmp_path))
    rec, _ = submit_job(store, "j1", "pagerank", dict(PARAMS))
    store.publish(rec, state=RUNNING)        # retains PENDING at .prev
    path = store.record_path("j1")
    with open(path, "w") as f:
        f.write('{"torn": tru')              # torn mid-write
    loaded = store.load("j1")
    assert loaded is not None and loaded["state"] == PENDING
    assert (tmp_path / "job-j1.json.corrupt").exists()
    assert metrics.counter("jobs.record_quarantines").value == 1
    # a CRC mismatch (bit rot, not torn JSON) is quarantined the same way
    doc = json.loads((tmp_path / "job-j1.json.prev").read_text())
    doc["state"] = RUNNING                   # flipped without re-CRC
    (tmp_path / "job-j1.json.prev").write_text(json.dumps(doc))
    assert store.load("j1") is None
    assert (tmp_path / "job-j1.json.prev.corrupt").exists()


def test_reassign_from_moves_only_live_jobs(tmp_path):
    store = JobStore(str(tmp_path))
    for jid in ("a", "b", "c"):
        submit_job(store, jid, "pagerank", dict(PARAMS))
    assert store.claim("a", "0") and store.claim("b", "0")
    assert store.claim("c", "1")
    rec = store.load("b")
    store.publish(rec, state=FAILED, reason="x")   # terminal: stays put
    moved = store.reassign_from("0", "2")
    assert moved == ["a"]
    assert store.owner("a") == "2" and store.owner("b") == "0"
    assert store.owner("c") == "1"


# ---------------------------------------------------------- the executor


def test_executor_runs_pagerank_to_done(tmp_path):
    store = JobStore(str(tmp_path))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex = JobExecutor(store, rank="0")
    _run_to_terminal(ex)
    rec = store.load("j1")
    assert rec["state"] == DONE
    assert rec["epoch"] == rec["total_epochs"] == 3
    assert rec["iters"] == rec["total_iters"] == 12
    value = store.load_result("j1")
    ref = PageRankJob.reference(rec["params"])
    np.testing.assert_allclose(value, ref, rtol=1e-5, atol=1e-7)
    # committed epochs are unique — nothing ran twice
    epochs = [e["epoch"] for e in trace.events("job-epoch")]
    assert epochs == [1, 2, 3]
    done = trace.events("job-done")
    assert done and done[-1]["state"] == DONE


def test_duplicate_submit_after_done_returns_original_result(tmp_path):
    store = JobStore(str(tmp_path))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    _run_to_terminal(JobExecutor(store, rank="0"))
    first = store.load_result("j1")
    rec, created = submit_job(store, "j1", "pagerank", dict(PARAMS))
    assert not created and rec["state"] == DONE
    assert _bits(store.load_result("j1")) == _bits(first)
    # the executor has nothing to do for it either
    assert JobExecutor(store, rank="0").tick() is False


def test_cancel_finishes_the_job_failed(tmp_path):
    store = JobStore(str(tmp_path))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    store.request_cancel("j1")
    ex = JobExecutor(store, rank="0")
    assert ex.tick() is True
    rec = store.load("j1")
    assert rec["state"] == FAILED and rec["reason"] == "cancelled"


def test_injected_commit_abort_replays_intent_bitwise(tmp_path):
    """The ``ckpt:commit`` window: the epoch checkpoint is durable but
    the record publish dies.  The write-ahead intent re-targets the SAME
    epoch next tick; iterations already committed are never re-run and
    the final ranking is bitwise-equal to an uninterrupted solve."""
    baseline = _clean_result(tmp_path)
    store = JobStore(str(tmp_path / "jobs"))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex = JobExecutor(store, rank="0")
    # publish #1 is the PENDING->RUNNING activation; #2 is epoch 1's
    with faults.injected("ckpt:commit:2"):
        _run_to_terminal(ex)
    assert metrics.counter("jobs.commit_failures").value == 1
    assert metrics.counter("jobs.intent_replays").value == 1
    rec = store.load("j1")
    assert rec["state"] == DONE
    epochs = [e["epoch"] for e in trace.events("job-epoch")
              if e["job"] == "j1"]
    assert epochs == [1, 2, 3]               # no committed epoch re-ran
    assert _bits(store.load_result("j1")) == _bits(baseline)


def test_commit_retry_budget_fails_the_job(tmp_path):
    store = JobStore(str(tmp_path))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex = JobExecutor(store, rank="0", commit_retries=0)
    with faults.injected("ckpt:commit:2"):
        _run_to_terminal(ex)
    rec = store.load("j1")
    assert rec["state"] == FAILED and rec["reason"] == "commit-failed"


def test_torn_epoch_checkpoint_recovers_from_prev(tmp_path):
    """``ckpt:truncate`` tears the epoch ``.npz`` mid-write: the loader
    quarantines it, the retained ``.prev`` serves, and the job still
    finishes bitwise-equal."""
    baseline = _clean_result(tmp_path)
    store = JobStore(str(tmp_path / "jobs"))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex = JobExecutor(store, rank="0")
    with faults.injected("ckpt:truncate:2"):
        _run_to_terminal(ex)
    rec = store.load("j1")
    assert rec["state"] == DONE
    assert _bits(store.load_result("j1")) == _bits(baseline)


def test_crash_resume_is_bitwise_equal(tmp_path):
    """A new process (new executor, same rank) finds a RUNNING record it
    never started: resumes with source ``crash`` from the last durable
    epoch, continues the epoch numbering, and lands bitwise-equal."""
    baseline = _clean_result(tmp_path)
    store = JobStore(str(tmp_path / "jobs"))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex1 = JobExecutor(store, rank="0")
    assert ex1.tick() and ex1.tick()         # activate + epochs 1..2
    while store.load("j1")["epoch"] < 2:
        ex1.tick()
    del ex1                                  # SIGKILL stand-in: no exit path
    # another rank must NOT steal the claim while the owner may be alive
    thief = JobExecutor(store, rank="1")
    assert thief.tick() is False
    ex2 = JobExecutor(JobStore(str(tmp_path / "jobs")), rank="0")
    _run_to_terminal(ex2)
    resumed = trace.events("job-resumed")
    assert [e["source"] for e in resumed] == ["crash"]
    rec = store.load("j1")
    assert rec["state"] == DONE and rec["resumes"] == 1
    epochs = [e["epoch"] for e in trace.events("job-epoch")
              if e["job"] == "j1"]
    assert sorted(set(epochs)) == epochs == [1, 2, 3]
    assert _bits(store.load_result("j1")) == _bits(baseline)


def test_interactive_queue_preempts_then_resumes(tmp_path):
    """Queued interactive work preempts the job at the epoch boundary
    (never mid-epoch); the drained queue lets it resume where it left
    off with source ``preempted``."""
    server = Server(capacity=8, max_batch=4)
    store = JobStore(str(tmp_path))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex = JobExecutor(store, server=server, rank="0")
    assert ex.tick() is True                 # epoch 1 in an idle gap
    spec = build_mix("cipher", 1, seed=5)[0]
    assert server.submit(spec.op, spec.payload) is not None
    assert ex.tick() is False                # preempted, no epoch ran
    rec = store.load("j1")
    assert rec["state"] == PREEMPTED and rec["preemptions"] == 1
    assert rec["epoch"] == 1                 # boundary, not mid-epoch
    assert trace.events("job-preempted")[-1]["reason"] == "queue-depth"
    server.step()                            # interactive batch drains
    _run_to_terminal(ex)
    assert [e["source"] for e in trace.events("job-resumed")] \
        == ["preempted"]
    assert store.load("j1")["state"] == DONE


def test_stalled_job_gets_the_stalled_verdict(tmp_path):
    store = JobStore(str(tmp_path))
    # tiny graph converges almost immediately; a 1-epoch stall budget
    # trips STALLED long before the iteration budget runs out
    submit_job(store, "j1", "pagerank",
               {"nodes": 16, "avg_edges": 2, "iters": 400, "epoch": 2,
                "stall_epochs": 1})
    _run_to_terminal(JobExecutor(store, rank="0"))
    rec = store.load("j1")
    assert rec["state"] == jobs_mod.STALLED
    assert rec["reason"] == "convergence-stall"
    assert rec["iters"] < rec["total_iters"]


# ------------------------------------------------- controls + transport


def test_handle_control_verbs(tmp_path):
    store = JobStore(str(tmp_path))
    out = jobs_mod.handle_control(
        store, {"control": "job-submit", "job": "j1", "op": "pagerank",
                "params": dict(PARAMS)})
    assert out["ok"] and out["created"] and out["job"]["state"] == PENDING
    again = jobs_mod.handle_control(
        store, {"control": "job-submit", "job": "j1", "op": "pagerank"})
    assert again["ok"] and not again["created"]
    assert jobs_mod.handle_control(
        store, {"control": "job-status", "job": "nope"})["ok"] is False
    assert jobs_mod.handle_control(
        store, {"control": "job-result", "job": "j1"})["ok"] is False
    _run_to_terminal(JobExecutor(store, rank="0"))
    res = jobs_mod.handle_control(store, {"control": "job-result",
                                          "job": "j1"})
    assert res["ok"] and res["job"]["state"] == DONE
    value = wire.nd_b64_decode(res["value"])
    assert _bits(value) == _bits(store.load_result("j1"))
    listing = jobs_mod.handle_control(store, {"control": "job-list"})
    assert [r["job"] for r in listing["jobs"]] == ["j1"]


def test_job_lane_over_transport_under_interactive_load(tmp_path):
    """The full wire arc on one replica: submit over a control frame,
    interactive solves keep landing (and strictly win the server),
    status polls show progress, and the result round-trips bitwise."""
    from cme213_tpu.serve import OK
    from cme213_tpu.serve.transport import TransportClient, TransportServer

    baseline = _clean_result(tmp_path)
    server = Server(capacity=32, max_batch=4)
    store = JobStore(str(tmp_path / "jobs"))
    ts = TransportServer(server, drive="thread")
    ts.attach_jobs(JobExecutor(store, server=server, rank="0"))
    ts.start()
    try:
        with TransportClient(ts.addr) as c:
            out = c.control("job-submit", job="j1", op="pagerank",
                            params=dict(PARAMS))
            assert out["ok"] and out["created"]
            for spec in build_mix("cipher", 6, seed=5):
                res = c.solve(spec.op, spec.payload)   # rides along
                assert res.status == OK
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                st = c.control("job-status", job="j1")
                assert st["ok"]
                if st["job"]["state"] in jobs_mod.TERMINAL:
                    break
                time.sleep(0.05)
            assert st["job"]["state"] == DONE
            assert st["job"]["owner"] == "0"
            res = c.control("job-result", job="j1")
            assert res["ok"]
            assert _bits(wire.nd_b64_decode(res["value"])) \
                == _bits(baseline)
    finally:
        ts.close()


def test_orphan_adoption_after_restart(tmp_path):
    """Whole-fleet restart in miniature: the previous owner's rank is
    gone, the store's claim is reassigned, and the adopting executor
    resumes from the durable epoch — the ``job-reassigned`` +
    ``job-resumed(restart/crash)`` arc ``serve/fleet.py`` drives."""
    baseline = _clean_result(tmp_path)
    store = JobStore(str(tmp_path / "jobs"))
    submit_job(store, "j1", "pagerank", dict(PARAMS))
    ex0 = JobExecutor(store, rank="7")       # a rank that will not return
    while store.load("j1")["epoch"] < 2:
        ex0.tick()
    del ex0
    moved = store.reassign_from("7", "0")
    assert moved == ["j1"]
    _run_to_terminal(JobExecutor(store, rank="0"))
    rec = store.load("j1")
    assert rec["state"] == DONE and rec["resumes"] == 1
    assert trace.events("job-resumed")[-1]["source"] == "crash"
    assert _bits(store.load_result("j1")) == _bits(baseline)


# ------------------------------------------------- e2e fleet kill arcs


def _fleet_submit_and_wait(addr, job, params, deadline_s=120.0,
                           min_epoch_before=None, poke=None):
    from cme213_tpu.serve.transport import TransportClient

    with TransportClient(addr) as c:
        out = c.control("job-submit", job=job, op="pagerank", params=params)
        assert out["ok"]
    deadline = time.monotonic() + deadline_s
    poked = False
    last = None
    while time.monotonic() < deadline:
        try:
            with TransportClient(addr) as c:
                st = c.control("job-status", job=job)
        except (ConnectionError, OSError):
            time.sleep(0.2)                  # front end mid-restart
            continue
        assert st["ok"], st
        last = st["job"]
        if (poke is not None and not poked
                and (last["epoch"] or 0) >= (min_epoch_before or 1)):
            poke()
            poked = True
        if last["state"] in jobs_mod.TERMINAL:
            return last
        time.sleep(0.1)
    raise AssertionError(f"job never finished: {last}")


def _fleet_result(addr, job):
    from cme213_tpu.serve.transport import TransportClient

    with TransportClient(addr) as c:
        res = c.control("job-result", job=job)
    assert res["ok"], res
    return wire.nd_b64_decode(res["value"])


@pytest.mark.slow
def test_fleet_job_survives_replica_sigkill(tmp_path, monkeypatch):
    """One replica, SIGKILLed mid-job by an injected ``replica-kill``
    clause: the relaunched incarnation resumes its own claim from the
    durable epoch and the final ranking is bitwise-equal to an
    uninterrupted in-process run."""
    from cme213_tpu.serve import OK
    from cme213_tpu.serve.fleet import Fleet
    from cme213_tpu.serve.transport import TransportClient

    # long enough (40 epochs) that the kill lands mid-job, not after it
    params = {"nodes": 3000, "avg_edges": 6, "iters": 160, "epoch": 4,
              "seed": 11, "stall_epochs": 1000}
    baseline = _clean_result(tmp_path, params)
    monkeypatch.setenv("CME213_FAULTS", "replica-kill:0:1")
    fleet = Fleet(replicas=1, mix="cipher", warm_requests=2, max_batch=4,
                  jobs_dir=str(tmp_path / "jobs")).start()
    try:
        def poke():
            # interactive batches arm the kill guard; every accepted
            # request must still be served (zero interactive loss)
            with TransportClient(fleet.addr) as c:
                for spec in build_mix("cipher", 4, seed=5):
                    res = c.solve(spec.op, spec.payload)
                    assert res.status == OK
        rec = _fleet_submit_and_wait(fleet.addr, "kill-arc", params,
                                     min_epoch_before=1, poke=poke)
        assert rec["state"] == DONE
        assert rec["resumes"] >= 1           # the relaunch resumed it
        value = _fleet_result(fleet.addr, "kill-arc")
        stats = fleet.front.stats()          # the wire-facing view
    finally:
        fleet.close()
    assert _bits(value) == _bits(baseline)
    assert stats["replicas"]["r0"]["incarnation"] >= 1
    assert stats["jobs"].get(DONE) == 1


@pytest.mark.slow
def test_fleet_down_up_resumes_job(tmp_path):
    """Whole-fleet restart: every process dies, the jobs directory is
    the only survivor, and a brand-new fleet finishes the job
    bitwise-equal without re-running committed epochs."""
    from cme213_tpu.serve.fleet import Fleet
    from cme213_tpu.serve.transport import TransportClient

    params = {"nodes": 3000, "avg_edges": 6, "iters": 160, "epoch": 4,
              "seed": 12, "stall_epochs": 1000}
    baseline = _clean_result(tmp_path, params)
    jobs_dir = str(tmp_path / "jobs")
    fleet = Fleet(replicas=1, mix="cipher", warm_requests=2,
                  jobs_dir=jobs_dir).start()
    try:
        with TransportClient(fleet.addr) as c:
            out = c.control("job-submit", job="downup", op="pagerank",
                            params=params)
            assert out["ok"] and out["created"]
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            with TransportClient(fleet.addr) as c:
                st = c.control("job-status", job="downup")
            if (st["job"]["epoch"] or 0) >= 2:
                break
            time.sleep(0.1)
        assert (st["job"]["epoch"] or 0) >= 2, st
    finally:
        fleet.close()                        # the whole fleet goes down
    fleet2 = Fleet(replicas=1, mix="cipher", warm_requests=2,
                   jobs_dir=jobs_dir).start()
    try:
        rec = _fleet_submit_and_wait(fleet2.addr, "downup", params)
        assert rec["state"] == DONE
        assert rec["resumes"] >= 1
        value = _fleet_result(fleet2.addr, "downup")
    finally:
        fleet2.close()
    assert _bits(value) == _bits(baseline)
