"""Game-day chaos campaigns: seeded fault cocktails, global invariants,
failure shrinking.

The reference's verification habit is *offline*: hw2 diffs a grid dump
against a host golden after the run, hw_final prints a relative error at
exit.  PRs 2-16 built the modern in-path equivalents one at a time —
deterministic fault clauses (``core/faults.py``), breakers, a zero-loss
requeue ledger, drift budgets, flight recorders — but each was only ever
exercised by the single-clause scenario it shipped with.  This module is
the missing composition layer (ROADMAP item 5's payoff): draw
randomized-but-**seeded** cocktails of 2-5 fault clauses, arm them
against a live serving run, and check **global invariants** that no
single-feature test can state.

The pieces
==========

- :data:`MATRIX` — the clause-compatibility matrix.  Every kind in the
  ``CME213_FAULTS`` grammar has an entry saying whether it is drawable
  against a serving target, on which backends, against which targets,
  and what it conflicts with; ineligible kinds carry the *reason* (e.g.
  ``nan:`` guards live in the checkpointed-solver loop, not the serving
  path), so exclusions are documented data, not folklore.
- :func:`draw_cocktail` — the seeded composer: 2-5 clauses drawn from
  the eligible pool, matrix-filtered, identical for identical seeds.
- :func:`run_campaign` — arm a cocktail, drive a serving run under a
  multi-op loadgen mix, disarm, then check the five global invariants:

  1. **zero accepted-request loss** — every submitted request produced
     exactly one response and ``submitted - shed == served`` (no FAILED,
     no vanished requests), whatever was killed mid-batch;
  2. **bitwise conformance** — every served result equals a disarmed
     reference re-solve on the rung that served it (modulo the armed
     plan's *declared* ``drift:`` scaling, which is compensated exactly
     — so the check verifies the corruption is precisely the injected
     one and nothing more); sort results are additionally held to the
     host ``np.sort`` golden;
  3. **SLO report** — present, JSON-parseable, and complete;
  4. **one trace id** — every event from every process of the gang
     carries the same trace id;
  5. **no leaks** — no shared-memory segments left in ``/dev/shm`` and
     no replica processes left running after close.

  Two backends: ``inproc`` (a :class:`~..serve.server.Server` driven by
  the in-process closed loop — fast enough for tier-1 fixture replay)
  and ``fleet`` (a live 2+-replica :class:`~..serve.fleet.Fleet` behind
  the socket front end — the real gang, used by the CI chaos gate).
- :func:`shrink` — on any violation, a delta-debugging shrinker: ddmin
  over clauses, then over each surviving clause's ``nth``/``count``/
  ``ms`` parameters, down to a minimal still-failing cocktail.
- :func:`bank_fixture` / :func:`replay_fixture` — minimal cocktails are
  banked as JSON under ``tests/chaos_fixtures/`` and replayed as
  ordinary tier-1 tests: every game-day find becomes a permanent
  regression test.

Handicaps (``handicaps=("drift-compensation",)``) deliberately switch
off one resilience behaviour for a drill, so game days can prove the
whole loop — violation, shrink, fixture, replay — against a known
breakage without shipping one.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field, replace

import numpy as np

from . import faults
from .faults import FaultPlan, _Clause

#: invariant names, in report order
INVARIANTS = ("loss", "conformance", "slo_report", "trace", "leaks", "job")

#: recognized game-day handicaps (deliberate breakages for drills)
HANDICAPS = ("drift-compensation", "ckpt-retry")


# --------------------------------------------------------------- topology

#: per-op serving topology the drawer needs: rung ladder (first serves,
#: last is the reference), whether outputs carry float leaves (``drift:``
#: and ``wrong:`` only bite float leaves — integer probes take the
#: bit-flip path), and the conformance-probe ops ``wrong:`` can target.
#: ``tests/test_chaos.py`` asserts this table against the live ADAPTERS.
TOPOLOGY: dict[str, dict] = {
    "spmv_scan": {"rungs": ("blocked", "flat"), "float": True,
                  "probe_ops": ()},
    "heat": {"rungs": ("xla",), "float": True, "probe_ops": ()},
    "cipher": {"rungs": ("packed", "bytes"), "float": False,
               "probe_ops": ()},
    "sort": {"rungs": ("lax", "radix", "bitonic"), "float": False,
             "probe_ops": ("serve.sort",)},
    "stub": {"rungs": ("echo",), "float": False, "probe_ops": ()},
    # job-lane entries (``"job": True``) are NOT serving adapters: they
    # name registered long-job kinds (serve/workloads.JOB_KINDS) a
    # campaign can run in the idle gaps via ``run_campaign(job=...)``.
    # Their presence in a campaign's op set is what makes ``ckpt:``
    # clauses drawable — the job lane's durable writers are the only
    # checkpoint path a serving campaign exercises.
    "pagerank": {"rungs": ("power",), "float": True, "probe_ops": (),
                 "job": True},
}

#: the job-lane campaign shape: small enough that a banked fixture
#: replays inside tier-1, large enough for several durable epochs
JOB_PARAMS: dict[str, dict] = {
    "pagerank": {"nodes": 128, "avg_edges": 4, "iters": 12, "epoch": 4,
                 "seed": 3},
}

#: loadgen ``--mix`` names -> adapter op names
MIX_TO_OP = {"spmv": "spmv_scan", "heat": "heat", "cipher": "cipher",
             "sort": "sort", "stub": "stub"}


# ------------------------------------------------------ compatibility matrix

@dataclass(frozen=True)
class KindRule:
    """One row of the compatibility matrix: whether (and how) a fault
    kind is drawable against a live serving target."""

    kind: str
    eligible: bool
    backends: tuple[str, ...] = ()      # "inproc" and/or "fleet"
    max_per_cocktail: int = 2
    conflicts: tuple[str, ...] = ()     # kinds this kind never co-draws with
    reason: str = ""                    # why eligible targets are what they
                                        # are, or why the kind is excluded


#: the clause-compatibility matrix over the full ``core/faults.py``
#: grammar.  Ineligible kinds are *documented* exclusions: their guards
#: have no call site on the serving path, or firing them there would be
#: nondeterministic, so drawing them would only produce inert or flaky
#: cocktails.
MATRIX: dict[str, KindRule] = {r.kind: r for r in (
    KindRule("fail", True, ("inproc", "fleet"), max_per_cocktail=2,
             reason="targets a non-terminal serve.<op>.<rung>; the "
                    "terminal rung is never targeted so the ladder "
                    "always has a clean rung to serve from"),
    KindRule("stage", True, ("inproc", "fleet"), max_per_cocktail=1,
             reason="execute-stage only: lower/compile guards fire on "
                    "program-cache misses, which warmup coverage makes "
                    "run-order-dependent"),
    KindRule("slow", True, ("inproc", "fleet"), max_per_cocktail=2,
             reason="targets serve.<op>; bounded ms*count so a cocktail "
                    "cannot starve the run past transport timeouts"),
    KindRule("drift", True, ("inproc", "fleet"), max_per_cocktail=1,
             conflicts=("replica-kill",),
             reason="float-output ops only (uint leaves don't drift); "
                    "nth=1 so the conformance check can compensate the "
                    "declared scale exactly; conflicts with replica-kill "
                    "because a relaunch clears drift mid-run, making "
                    "per-result expectations incarnation-dependent"),
    KindRule("wrong", True, ("inproc", "fleet"), max_per_cocktail=1,
             reason="targets a conformance-probe op (the sort golden "
                    "gate): the poisoned probe costs its rung and the "
                    "ladder serves clean from the next one.  Never "
                    "co-drawn with fail/stage on the same op's ladder: "
                    "the probe consumes whichever rung's gate misses "
                    "the verdict cache first, so rung-failure clauses "
                    "alongside it can exhaust the whole ladder (found "
                    "by campaign seed 2/0; banked as "
                    "chaos-s2000-c0.json)"),
    KindRule("replica-kill", True, ("fleet",), max_per_cocktail=1,
             conflicts=("drift",),
             reason="fleet backend only (in-process it would SIGKILL "
                    "the campaign runner itself); one per cocktail so "
                    "a 2-replica fleet never loses both replicas at "
                    "once"),
    KindRule("nan", False,
             reason="maybe_poison guards the checkpointed-solver chunk "
                    "loop (core/checkpoint.py), which the serving path "
                    "never enters — a nan: clause is inert here"),
    KindRule("oom", False,
             reason="maybe_oom guards solver chunk loops and the Pallas "
                    "pipeline, not the batched serve path — inert"),
    KindRule("rankkill", False,
             reason="maybe_kill_rank guards gang-solver epoch steps "
                    "(dist/launch.py); serving replicas are killed via "
                    "replica-kill instead"),
    KindRule("ckpt", True, ("inproc",), max_per_cocktail=2,
             reason="drawable only when the campaign runs a long job "
                    "(run_campaign(job=...)): the job lane's durable "
                    "writers (epoch checkpoints, record publishes) are "
                    "the serving tier's only checkpoint path.  inproc "
                    "only — the guards fire in the campaign runner's "
                    "own executor, so the invariant checker sees the "
                    "same store the clause corrupted"),
    KindRule("unreachable", False,
             reason="the op-agnostic device preflight is consulted at "
                    "replica startup and by the doctor; an unreachable "
                    "window there kills warmup nondeterministically "
                    "instead of exercising serving"),
)}


def clause_targets(backend: str, ops: list[str],
                   replicas: int) -> dict[str, list[dict]]:
    """Concrete drawable (kind, parameter-space) targets for a campaign
    over ``ops`` (adapter names).  Pure function of its inputs — the
    same campaign shape always offers the same pool."""
    pool: dict[str, list[dict]] = {}
    job_ops = [op for op in ops if TOPOLOGY[op].get("job")]
    for op in ops:
        topo = TOPOLOGY[op]
        if topo.get("job"):
            continue                    # not a serving adapter
        rungs = topo["rungs"]
        for rung in rungs[:-1]:         # never the terminal rung
            pool.setdefault("fail", []).append(
                {"op": f"serve.{op}.{rung}"})
            pool.setdefault("stage", []).append(
                {"op": f"serve.{op}.{rung}", "stage": "execute"})
        if len(rungs) > 1 or op in ("heat",):
            pool.setdefault("slow", []).append({"op": f"serve.{op}"})
        if topo["float"]:
            for rung in rungs:
                pool.setdefault("drift", []).append(
                    {"op": f"serve.{op}.{rung}"})
        for probe in topo["probe_ops"]:
            pool.setdefault("wrong", []).append({"op": probe})
    if backend == "fleet":
        for rank in range(replicas):
            pool.setdefault("replica-kill", []).append({"op": str(rank)})
    if job_ops:
        # both durable-writer crash windows: a torn epoch checkpoint
        # (quarantine + .prev fallback) and a lost record publish
        # (write-ahead intent replay)
        pool.setdefault("ckpt", []).append({"op": "truncate"})
        pool.setdefault("ckpt", []).append({"op": "commit"})
    return {k: v for k, v in pool.items()
            if MATRIX[k].eligible and backend in MATRIX[k].backends}


def compatible(existing: list[_Clause], cand: _Clause) -> tuple[bool, str]:
    """Whether ``cand`` may join ``existing`` under the matrix: per-kind
    caps, declared kind conflicts, no duplicate targets."""
    rule = MATRIX[cand.kind]
    same_kind = [c for c in existing if c.kind == cand.kind]
    if len(same_kind) >= rule.max_per_cocktail:
        return False, f"{cand.kind}: at most {rule.max_per_cocktail}"
    for c in existing:
        if c.kind in rule.conflicts or cand.kind in MATRIX[c.kind].conflicts:
            return False, f"{cand.kind} conflicts with {c.kind}"
        if (c.kind, c.op, c.stage) == (cand.kind, cand.op, cand.stage):
            return False, f"duplicate target {cand.kind}:{cand.op}"
        # a poisoned probe (wrong:serve.<op>) consumes one rung of
        # <op>'s ladder — whichever gate misses the verdict cache first
        # — so rung-failure clauses on the same ladder can exhaust it
        # (the chaos-s2000-c0 find: 2 requests FAILED)
        for w, other in ((cand, c), (c, cand)):
            if w.kind == "wrong" and other.kind in ("fail", "stage") \
                    and other.op.startswith(w.op + "."):
                return False, (f"wrong:{w.op} + {other.kind}:{other.op} "
                               f"can exhaust the {w.op} ladder")
    return True, ""


def validate_cocktail(plan: FaultPlan, backend: str) -> list[str]:
    """Matrix violations in ``plan`` (empty = sane for ``backend``).
    Used on drawn cocktails (must be []) and on replayed fixtures
    (deliberately-broken fixtures may carry violations by design)."""
    problems = []
    for i, c in enumerate(plan.clauses):
        rule = MATRIX.get(c.kind)
        if rule is None:
            problems.append(f"unknown kind {c.kind!r}")
            continue
        if not rule.eligible:
            problems.append(f"{c.kind}: ineligible ({rule.reason})")
        elif backend not in rule.backends:
            problems.append(f"{c.kind}: not sane on backend {backend!r}")
        ok, why = compatible(plan.clauses[:i], c)
        if not ok:
            problems.append(why)
    return problems


# ------------------------------------------------------------ the drawer

def draw_cocktail(rng: np.random.Generator, backend: str,
                  ops: list[str], replicas: int = 2) -> FaultPlan:
    """Draw one randomized-but-seeded cocktail of 2-5 clauses from the
    matrix-filtered pool.  Identical ``rng`` state -> identical cocktail."""
    pool = clause_targets(backend, ops, replicas)
    kinds = sorted(pool)
    if not kinds:
        raise ValueError(f"no drawable fault kinds for ops {ops}")
    want = int(rng.integers(2, 6))
    clauses: list[_Clause] = []
    for _ in range(want * 8):           # bounded rejection sampling
        if len(clauses) >= want:
            break
        kind = kinds[int(rng.integers(0, len(kinds)))]
        tgt = pool[kind][int(rng.integers(0, len(pool[kind])))]
        if kind == "fail":
            cand = _Clause("fail", tgt["op"],
                           nth=int(rng.integers(1, 3)),
                           count=int(rng.integers(1, 4)))
        elif kind == "stage":
            cand = _Clause("stage", tgt["op"], stage=tgt["stage"],
                           nth=int(rng.integers(1, 3)),
                           count=int(rng.integers(1, 3)))
        elif kind == "slow":
            cand = _Clause("slow", tgt["op"],
                           ms=float(rng.choice((20.0, 50.0))),
                           nth=int(rng.integers(1, 3)),
                           count=int(rng.integers(1, 4)))
        elif kind == "drift":
            cand = _Clause("drift", tgt["op"],
                           ms=float(rng.choice((1e-3, 2e-3))),
                           nth=1, count=1 << 30)
        elif kind == "wrong":
            cand = _Clause("wrong", tgt["op"], nth=1)
        elif kind == "ckpt":
            cand = _Clause("ckpt", tgt["op"],
                           nth=int(rng.integers(1, 3)))
        else:                           # replica-kill
            cand = _Clause("replica-kill", tgt["op"],
                           nth=int(rng.integers(1, 3)))
        if compatible(clauses, cand)[0]:
            clauses.append(cand)
    if len(clauses) < 2:
        raise RuntimeError("could not draw a 2-clause cocktail "
                           f"(pool {sorted(pool)})")
    return FaultPlan(clauses)


# ----------------------------------------------------------- invariants

@dataclass
class Violation:
    invariant: str                      # one of INVARIANTS
    detail: str

    def as_dict(self) -> dict:
        return {"invariant": self.invariant, "detail": self.detail}


@dataclass
class CampaignResult:
    """What one campaign run produced; serializable via :meth:`as_dict`."""

    seed: int
    index: int
    backend: str
    mix: str
    requests: int
    replicas: int
    cocktail: str
    job: str | None = None
    report: dict = field(default_factory=dict)
    violations: list = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return {
            "seed": self.seed, "campaign": self.index,
            "backend": self.backend, "mix": self.mix,
            "requests": self.requests, "replicas": self.replicas,
            "cocktail": self.cocktail, "job": self.job, "ok": self.ok,
            "violations": [v.as_dict() for v in self.violations],
            "elapsed_s": round(self.elapsed_s, 3),
            "report": self.report,
        }


def _drift_scales(plan: FaultPlan) -> dict[str, float]:
    """op-path -> declared scale, for drift clauses the conformance
    check compensates (nth=1 persistent clauses only — the matrix's
    drawable shape)."""
    return {c.op: c.ms for c in plan.clauses
            if c.kind == "drift" and c.nth == 1}


def _bits(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


def _reference_resolve(spec, rung: str):
    """Disarmed serial re-solve of ``spec`` on the rung that served it."""
    from ..serve.workloads import ADAPTERS

    return np.asarray(ADAPTERS[spec.op].run_batch([spec.payload], rung)[0])


def check_conformance(pairs, plan: FaultPlan,
                      handicaps: tuple[str, ...] = ()) -> list[Violation]:
    """Invariant 2: every OK result equals a disarmed reference re-solve
    on its recorded rung, bitwise — compensating the armed plan's
    declared ``drift:`` scale (unless the drill handicapped that), and
    additionally holding sort results to the host ``np.sort`` golden."""
    scales = {} if "drift-compensation" in handicaps else _drift_scales(plan)
    out = []
    for spec, res in pairs:
        if res.status != "ok" or res.value is None:
            continue
        ref = _reference_resolve(spec, res.rung)
        scale = scales.get(f"serve.{spec.op}.{res.rung}")
        if scale is not None and np.issubdtype(ref.dtype, np.floating):
            # exactly what maybe_drift did to the served batch: host
            # multiply + cast, so bitwise equality still holds
            ref = (ref * (1.0 + scale)).astype(ref.dtype)
        got = np.asarray(res.value)
        if got.shape != ref.shape or got.dtype != ref.dtype or \
                _bits(got) != _bits(ref):
            bad = int(np.count_nonzero(got != ref)) if \
                got.shape == ref.shape else -1
            out.append(Violation(
                "conformance",
                f"rid {res.rid} op {spec.op} rung {res.rung}: served "
                f"result != reference re-solve ({bad} differing elems)"))
            continue
        if spec.op == "sort":
            golden = np.sort(np.asarray(spec.payload))
            if _bits(got) != _bits(golden):
                out.append(Violation(
                    "conformance",
                    f"rid {res.rid} sort: served result != np.sort "
                    f"golden"))
    return out


def check_loss(pairs, submitted: int) -> list[Violation]:
    """Invariant 1: one response per request; submitted - shed == served."""
    out = []
    if len(pairs) != submitted:
        out.append(Violation(
            "loss", f"{submitted} submitted but {len(pairs)} responses"))
    served = sum(1 for _, r in pairs if r.status == "ok")
    shed = sum(1 for _, r in pairs if r.status == "shed")
    failed = [r for _, r in pairs if r.status not in ("ok", "shed")]
    if failed:
        out.append(Violation(
            "loss", f"{len(failed)} accepted request(s) failed "
                    f"(first: {failed[0].reason})"))
    if served != len(pairs) - shed - len(failed):
        out.append(Violation(
            "loss", f"served {served} != submitted {len(pairs)} - shed "
                    f"{shed}"))
    return out


def check_slo_report(report: dict) -> list[Violation]:
    """Invariant 3: the SLO report exists, round-trips through JSON, and
    carries the keys every consumer (trace regress, CI gates) reads."""
    try:
        doc = json.loads(json.dumps(report))
    except (TypeError, ValueError) as e:
        return [Violation("slo_report", f"not JSON-serializable: {e}")]
    missing = [k for k in ("trace_id", "requests", "served", "shed",
                           "failed", "latency_ms", "throughput_rps")
               if k not in doc]
    if missing:
        return [Violation("slo_report", f"missing keys {missing}")]
    return []


def check_trace(trace_ids: set, expected: str) -> list[Violation]:
    """Invariant 4: exactly one trace id spans the whole gang."""
    ids = {t for t in trace_ids if t}
    if ids == {expected}:
        return []
    return [Violation(
        "trace", f"expected one gang trace id {expected!r}, saw "
                 f"{sorted(ids)!r}")]


def _job_reference(op: str, params: dict):
    """Disarmed re-run of the campaign's long job in a fresh store —
    the value the armed run's durable result must equal bitwise."""
    import tempfile

    from ..serve import jobs as jobs_mod

    prev = faults.active()
    faults.install_plan(FaultPlan([]))
    try:
        store = jobs_mod.JobStore(tempfile.mkdtemp(prefix="chaos-jobref-"))
        jobs_mod.submit_job(store, "ref", op, params)
        ex = jobs_mod.JobExecutor(store, rank="ref")
        for _ in range(500):
            if not ex.tick():
                break
        return store.load_result("ref")
    finally:
        if prev is None:
            faults.reset()
        else:
            faults.install_plan(prev)


def check_job(job_ctx) -> list[Violation]:
    """Invariant 6 (job campaigns): the long job survived the cocktail —
    terminal state is DONE, no committed epoch was ever re-executed
    (``job-epoch`` publishes carry unique epoch numbers), and the durable
    result equals a disarmed re-run bitwise."""
    from ..serve import jobs as jobs_mod
    from . import trace

    store, jid, op, params = job_ctx
    rec = store.load(jid)
    if rec is None:
        return [Violation("job", f"job {jid}: record unreadable")]
    if rec["state"] != jobs_mod.DONE:
        return [Violation(
            "job", f"job {jid} ended {rec['state']} "
                   f"(reason {rec.get('reason')!r}) under the cocktail")]
    out = []
    epochs = [e["epoch"] for e in trace.events("job-epoch")
              if e.get("job") == jid]
    dupes = sorted({n for n in epochs if epochs.count(n) > 1})
    if dupes:
        out.append(Violation(
            "job", f"job {jid}: committed epoch(s) {dupes} re-executed"))
    got = store.load_result(jid)
    ref = _job_reference(op, params)
    if got is None or ref is None or _bits(got) != _bits(ref):
        out.append(Violation(
            "job", f"job {jid}: durable result != disarmed re-run "
                   f"(bitwise)"))
    return out


def _shm_segments() -> set:
    try:
        return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}
    except OSError:
        return set()


def check_leaks(shm_before: set, live_procs: list) -> list[Violation]:
    """Invariant 5: nothing outlives the campaign — no new shared-memory
    segments, no replica processes still running."""
    out = []
    leaked = _shm_segments() - shm_before
    if leaked:
        out.append(Violation(
            "leaks", f"leaked shm segment(s): {sorted(leaked)}"))
    if live_procs:
        out.append(Violation(
            "leaks", f"replica process(es) still alive: {live_procs}"))
    return out


# ------------------------------------------------------- campaign runners

def _campaign_hygiene() -> None:
    """Reset cross-campaign state so campaign N+1 starts clean: cached
    conformance verdicts (a ``wrong:``-poisoned probe must not leak),
    drift-budget/demotion state, buffered trace events."""
    from . import conformance, numerics, trace

    conformance.reset()
    numerics.reset()
    trace.clear_events()


def _run_inproc(plan: FaultPlan, mix: str, requests: int, seed: int,
                max_batch: int, concurrency: int = 6,
                job: str | None = None,
                handicaps: tuple[str, ...] = ()):
    """Drive an in-process Server under the armed cocktail; returns
    (pairs, report, trace_ids, shm_before, live_procs, job_ctx).  With
    ``job``, a long job runs in the serving gaps exactly as a replica
    would run it — submitted before the load, ticked between service
    steps (so queue-depth preemption has something to preempt), then
    driven to a terminal state after the interactive load drains."""
    import tempfile

    from ..serve import jobs as jobs_mod
    from ..serve.loadgen import build_mix, slo_report
    from ..serve.server import Server
    from . import metrics, trace

    shm_before = _shm_segments()
    specs = build_mix(mix, requests, seed=seed)
    server = Server(capacity=max(64, requests), max_batch=max_batch)
    before = metrics.snapshot()
    prev = faults.active()
    faults.install_plan(plan.reset_counters())
    t0 = time.monotonic()
    pairs = []
    job_ctx = None
    executor = None
    try:
        if job:
            params = dict(JOB_PARAMS[job])
            jstore = jobs_mod.JobStore(
                tempfile.mkdtemp(prefix="chaos-job-"))
            jid = f"chaos-{seed}"
            jobs_mod.submit_job(jstore, jid, job, params)
            executor = jobs_mod.JobExecutor(
                jstore, server=server, rank="chaos",
                commit_retries=0 if "ckpt-retry" in handicaps else 3)
            job_ctx = (jstore, jid, job, params)
        pending = list(specs)
        inflight: dict[int, object] = {}
        while pending or inflight:
            while pending and len(inflight) < concurrency:
                spec = pending.pop(0)
                out = server.submit(spec.op, spec.payload,
                                    deadline_ms=spec.deadline_ms,
                                    tenant=spec.tenant)
                if isinstance(out, int):
                    inflight[out] = spec
                else:
                    pairs.append((spec, out))    # shed at submit
            for res in server.step():
                pairs.append((inflight.pop(res.rid), res))
            if executor is not None:
                executor.tick()
        if executor is not None:
            # interactive load drained: the job owns the idle gaps now
            for _ in range(500):
                rec = job_ctx[0].load(job_ctx[1])
                if rec is None or rec["state"] in jobs_mod.TERMINAL:
                    break
                executor.tick()
    finally:
        if prev is None:
            faults.reset()
        else:
            faults.install_plan(prev)
    elapsed = time.monotonic() - t0
    run = {"results": [r for _, r in pairs], "elapsed_s": elapsed}
    report = slo_report(run, before, metrics.snapshot())
    trace_ids = {e.get("trace") for e in trace.events()}
    return pairs, report, trace_ids, shm_before, [], job_ctx


def _run_fleet(plan: FaultPlan, mix: str, requests: int, seed: int,
               max_batch: int, replicas: int, concurrency: int = 4,
               warm_requests: int = 4):
    """Drive a live replica fleet under the armed cocktail (the same
    fleet ``fleet up`` runs; workers inherit the cocktail via the
    ``CME213_FAULTS`` env).  Returns the same tuple as
    :func:`_run_inproc`."""
    import tempfile
    import threading

    from ..serve.fleet import Fleet
    from ..serve.loadgen import build_mix, fleet_section, slo_report
    from ..serve.transport import TransportClient
    from . import metrics, trace

    shm_before = _shm_segments()
    specs = build_mix(mix, requests, seed=seed)
    before = metrics.snapshot()
    prev_env = os.environ.get("CME213_FAULTS")
    prev_trace = os.environ.get("CME213_TRACE_FILE")
    tmp = tempfile.mkdtemp(prefix="chaos-")
    os.environ["CME213_FAULTS"] = str(plan)
    os.environ["CME213_TRACE_FILE"] = os.path.join(
        tmp, "trace-r{rank}.jsonl")
    # the runner's own process must NOT arm the cocktail: replica-kill
    # clauses match JAX_PROCESS_ID, and the front end runs here
    faults.install_plan(FaultPlan([]))
    t0 = time.monotonic()
    fleet = None
    pairs = []
    mu = threading.Lock()
    try:
        fleet = Fleet(replicas=replicas, mix=mix, max_batch=max_batch,
                      warm_requests=warm_requests).start()
        addr = fleet.addr
        work = list(specs)

        def worker() -> None:
            client = None
            while True:
                with mu:
                    if not work:
                        break
                    spec = work.pop(0)
                try:
                    if client is None:
                        client = TransportClient(addr, timeout_s=120.0)
                    res = client.solve(spec.op, spec.payload,
                                       deadline_ms=spec.deadline_ms,
                                       tenant=spec.tenant)
                except (OSError, ConnectionError, ValueError,
                        TimeoutError) as e:
                    from ..serve.request import FAILED, SolveResult
                    if client is not None:
                        client.close()
                        client = None
                    res = SolveResult(-1, spec.op, FAILED,
                                      reason=f"transport: {e}",
                                      tenant=spec.tenant)
                with mu:
                    pairs.append((spec, res))
            if client is not None:
                client.close()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, min(concurrency, len(specs))))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        run = {"results": [r for _, r in pairs], "elapsed_s": elapsed}
        report = slo_report(run, before, metrics.snapshot())
        report["fleet"] = fleet_section(run, addr)
    finally:
        live = []
        if fleet is not None:
            procs = list(fleet._procs.values())
            fleet.close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and any(
                    p.proc.poll() is None for p in procs):
                time.sleep(0.1)
            live = [f"r{p.rank}(pid {p.proc.pid})" for p in procs
                    if p.proc.poll() is None]
        if prev_env is None:
            os.environ.pop("CME213_FAULTS", None)
        else:
            os.environ["CME213_FAULTS"] = prev_env
        if prev_trace is None:
            os.environ.pop("CME213_TRACE_FILE", None)
        else:
            os.environ["CME213_TRACE_FILE"] = prev_trace
        faults.reset()
    trace_ids = {trace.trace_id()}
    for name in sorted(os.listdir(tmp)):
        with open(os.path.join(tmp, name), encoding="utf-8") as f:
            for line in f:
                try:
                    trace_ids.add(json.loads(line).get("trace"))
                except ValueError:
                    trace_ids.add(f"<unparseable line in {name}>")
    return pairs, report, trace_ids, shm_before, live


def run_campaign(cocktail: FaultPlan | str, backend: str = "inproc",
                 mix: str = "cipher,sort", requests: int = 12,
                 seed: int = 0, index: int = 0, replicas: int = 2,
                 max_batch: int = 4,
                 handicaps: tuple[str, ...] = (),
                 job: str | None = None) -> CampaignResult:
    """Arm ``cocktail``, drive one serving run, disarm, check the global
    invariants.  Deterministic for a deterministic cocktail.  ``job``
    names a registered long-job kind to run in the serving gaps; job
    campaigns add invariant 6 (the job reaches DONE with no committed
    epoch re-executed and a bitwise-reference result)."""
    from ..serve.workloads import JOB_KINDS
    from . import trace

    plan = (FaultPlan.parse(cocktail) if isinstance(cocktail, str)
            else cocktail)
    for h in handicaps:
        if h not in HANDICAPS:
            raise ValueError(f"unknown handicap {h!r} (know {HANDICAPS})")
    if backend not in ("inproc", "fleet"):
        raise ValueError(f"unknown backend {backend!r} (inproc | fleet)")
    if job is not None:
        if job not in JOB_KINDS or job not in JOB_PARAMS:
            raise ValueError(f"unknown job kind {job!r}")
        if backend != "inproc":
            raise ValueError(
                "job campaigns are inproc-only (the fleet job lane is "
                "exercised end to end by the CI job-lane gate instead)")
    for c in plan.clauses:
        if backend == "inproc" and c.kind in ("replica-kill", "rankkill"):
            raise ValueError(
                f"{c.kind} clause in an in-process campaign would kill "
                f"the runner itself; use backend='fleet'")
        if c.kind == "ckpt" and job is None:
            raise ValueError(
                "ckpt clauses need a job campaign (run_campaign(job=...)) "
                "— the job lane is the only checkpoint path here")
    _campaign_hygiene()
    record_kw = dict(seed=seed, campaign=index, cocktail=str(plan),
                     backend=backend)
    trace.record_event("chaos-campaign", **record_kw)
    t0 = time.monotonic()
    job_ctx = None
    if backend == "inproc":
        pairs, report, trace_ids, shm_before, live, job_ctx = _run_inproc(
            plan, mix, requests, seed, max_batch, job=job,
            handicaps=handicaps)
    else:
        pairs, report, trace_ids, shm_before, live = _run_fleet(
            plan, mix, requests, seed, max_batch, replicas)
    violations = []
    violations += check_loss(pairs, requests)
    violations += check_conformance(pairs, plan, handicaps)
    violations += check_slo_report(report)
    violations += check_trace(trace_ids, report.get("trace_id"))
    violations += check_leaks(shm_before, live)
    if job_ctx is not None:
        violations += check_job(job_ctx)
    for v in violations:
        trace.record_event("chaos-violation", campaign=index,
                           invariant=v.invariant, detail=v.detail)
    return CampaignResult(
        seed=seed, index=index, backend=backend, mix=mix,
        requests=requests, replicas=replicas, cocktail=str(plan),
        job=job, report=report, violations=violations,
        elapsed_s=time.monotonic() - t0)


# ------------------------------------------------------------- shrinking

def ddmin(items: list, failing) -> list:
    """Zeller's ddmin: a minimal sublist of ``items`` on which
    ``failing`` still returns True.  ``failing(items)`` must hold."""
    assert failing(items), "ddmin needs a failing starting point"
    n = 2
    while len(items) >= 2:
        chunk = max(1, len(items) // n)
        reduced = False
        for i in range(0, len(items), chunk):
            complement = items[:i] + items[i + chunk:]
            if complement and failing(complement):
                items = complement
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(items):
                break
            n = min(len(items), 2 * n)
    return items


def _param_candidates(c: _Clause) -> list[_Clause]:
    """Simpler-parameter variants of one clause, most aggressive first."""
    out = []
    if c.nth > 1:
        out.append(replace(c, nth=1, calls=0))
    if c.count > 1 and c.kind != "drift":
        out.append(replace(c, count=1, calls=0))
    if c.kind == "slow" and c.ms > 20.0:
        out.append(replace(c, ms=20.0, calls=0))
    return out


def shrink(plan: FaultPlan, failing) -> FaultPlan:
    """Delta-debug ``plan`` to a minimal failing cocktail: ddmin over
    clauses, then per-clause parameter simplification (nth -> 1,
    count -> 1, ms -> floor), re-validating failure at every step.
    ``failing(FaultPlan) -> bool`` runs a (deterministic) campaign."""
    def run(clauses: list[_Clause]) -> bool:
        return failing(FaultPlan([replace(c, calls=0) for c in clauses]))

    clauses = ddmin(list(plan.clauses), run)
    # parameter pass: try each clause's simpler variants in place,
    # re-deriving candidates after every accepted reduction so one
    # accepted simplification is never reverted by the next trial
    for i in range(len(clauses)):
        improved = True
        while improved:
            improved = False
            for cand in _param_candidates(clauses[i]):
                trial = clauses[:i] + [cand] + clauses[i + 1:]
                if run(trial):
                    clauses = trial
                    improved = True
                    break
    return FaultPlan([replace(c, calls=0) for c in clauses])


# -------------------------------------------------------------- fixtures

def fixtures_dir() -> str:
    """The banked-fixture directory (``tests/chaos_fixtures/``),
    resolved relative to the repo root this package lives in."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tests", "chaos_fixtures")


def bank_fixture(result: CampaignResult, minimal: FaultPlan,
                 directory: str | None = None,
                 handicaps: tuple[str, ...] = ()) -> str:
    """Write one replayable JSON fixture for a shrunk violation; the
    name is deterministic in (seed, campaign) so re-banking a known
    failure overwrites instead of accumulating."""
    directory = directory or fixtures_dir()
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"chaos-s{result.seed}-c{result.index}.json")
    doc = {
        "name": os.path.basename(path),
        "seed": result.seed,
        "campaign": result.index,
        "backend": result.backend,
        "mix": result.mix,
        "requests": result.requests,
        "replicas": result.replicas,
        "max_batch": 4,
        "cocktail": result.cocktail,
        "minimal_cocktail": str(minimal),
        "handicaps": list(handicaps),
        "job": result.job,
        "expect": {"violated": sorted({v.invariant
                                       for v in result.violations})},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def replay_fixture(path: str) -> tuple[CampaignResult, list[str], list[str]]:
    """Re-run a banked fixture's minimal cocktail under its recorded
    campaign shape; returns (result, expected_violated, observed_violated).
    A replay *passes* when observed == expected — passing fixtures prove
    the invariants hold, violation fixtures prove the detector and the
    shrinker still reproduce the find."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    result = run_campaign(
        doc["minimal_cocktail"], backend=doc.get("backend", "inproc"),
        mix=doc["mix"], requests=int(doc["requests"]),
        seed=int(doc["seed"]), index=int(doc["campaign"]),
        replicas=int(doc.get("replicas", 2)),
        max_batch=int(doc.get("max_batch", 4)),
        handicaps=tuple(doc.get("handicaps", ())),
        job=doc.get("job"))
    expected = sorted(doc.get("expect", {}).get("violated", []))
    observed = sorted({v.invariant for v in result.violations})
    return result, expected, observed


# ------------------------------------------------------------ orchestrator

def run_campaigns(seed: int, campaigns: int, backend: str = "inproc",
                  mix: str = "cipher,sort", requests: int = 12,
                  replicas: int = 2, max_batch: int = 4,
                  shrink_violations: bool = True,
                  bank_dir: str | None = None,
                  handicaps: tuple[str, ...] = (),
                  job: str | None = None) -> dict:
    """The game day: ``campaigns`` seeded draws, each armed against a
    live run and invariant-checked; violations are ddmin-shrunk and
    banked as fixtures.  Returns the campaign report (JSON-ready).
    ``job`` adds a long-job kind to every campaign (and its ``ckpt:``
    targets to the drawable pool)."""
    from . import trace

    ops = sorted({MIX_TO_OP[m.strip()] for m in mix.split(",")
                  if m.strip()})
    if job:
        ops.append(job)
    out: dict = {"seed": seed, "backend": backend, "mix": mix,
                 "job": job, "campaigns": [], "fixtures": []}
    for i in range(campaigns):
        rng = np.random.default_rng([seed, i])
        plan = draw_cocktail(rng, backend, ops, replicas)
        problems = validate_cocktail(plan, backend)
        assert not problems, f"drawer produced a matrix violation: " \
                             f"{problems}"
        result = run_campaign(
            plan, backend=backend, mix=mix, requests=requests,
            seed=seed * 1000 + i, index=i, replicas=replicas,
            max_batch=max_batch, handicaps=handicaps, job=job)
        out["campaigns"].append(result.as_dict())
        if result.violations and shrink_violations:
            def failing(p: FaultPlan) -> bool:
                r = run_campaign(
                    p, backend=backend, mix=mix, requests=requests,
                    seed=seed * 1000 + i, index=i, replicas=replicas,
                    max_batch=max_batch, handicaps=handicaps, job=job)
                return bool(r.violations)

            minimal = shrink(FaultPlan.parse(result.cocktail), failing)
            trace.record_event(
                "chaos-shrunk", campaign=i,
                from_clauses=len(FaultPlan.parse(result.cocktail).clauses),
                to_clauses=len(minimal.clauses), cocktail=str(minimal))
            out["fixtures"].append(bank_fixture(
                result, minimal, directory=bank_dir, handicaps=handicaps))
    out["violations_total"] = sum(
        len(c["violations"]) for c in out["campaigns"])
    out["ok"] = out["violations_total"] == 0
    return out
