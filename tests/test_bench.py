"""bench.py parent-process logic — the driver-facing artifact.

These tests fake the per-kernel child processes so the aggregation,
short-circuit, and fallback behavior (the parts that cost a whole round
when wrong, cf. BENCH_r02) are pinned without a device.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


class FakeProc:
    def __init__(self, stdout="", returncode=0, stderr=""):
        self.stdout = stdout
        self.returncode = returncode
        self.stderr = stderr


def _row(name, gbs, platform="tpu"):
    return json.dumps({"kernel": name, "ok": True, "iters": 100,
                       "platform": platform, "ms_per_iter": 1.0,
                       "gbs": gbs, "gflops": 1.0})


def test_best_kernel_selection(monkeypatch, capsys):
    gbs = {"xla": 14.0, "xla-roll": 100.0, "xla-roll-k8": 120.0,
           "xla-conv": 0.1,
           "pipeline-k1": 300.0, "pipeline-k2": 500.0,
           "pipeline-k4": 450.0, "pipeline-k8": 400.0,
           "pipeline2d-k1": 290.0, "pipeline2d-k8": 390.0}

    def fake_run(cmd, **kwargs):
        name = next(a.split("=", 1)[1] for a in cmd
                    if a.startswith("--kernel="))
        return FakeProc(stdout=_row(name, gbs[name]) + "\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert "pipeline-k2" in out["metric"]
    assert out["value"] == 500.0
    assert out["vs_baseline"] == round(500.0 / bench.BASELINE_GBS, 3)
    assert out["pct_hbm_peak"] == round(100 * 500.0 / bench.HBM_PEAK_GBS, 1)
    assert len(out["kernels"]) == len(bench.KERNELS)


def test_one_faulting_kernel_does_not_poison_others(monkeypatch, capsys):
    """The BENCH_r02 failure mode: one kernel dies, the rest still report."""
    def fake_run(cmd, **kwargs):
        name = next(a.split("=", 1)[1] for a in cmd
                    if a.startswith("--kernel="))
        if name == "xla-conv":
            return FakeProc(returncode=1, stderr="kernel fault")
        return FakeProc(stdout=_row(name, 20.0) + "\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    rows = {r["kernel"]: r for r in out["kernels"]}
    assert not rows["xla-conv"]["ok"]
    assert all(rows[k]["ok"] for k in rows if k != "xla-conv")
    assert out["value"] == 20.0


def test_dead_device_short_circuits(monkeypatch, capsys):
    """Two consecutive preflight failures skip the remaining kernels
    instead of burning 90s+120s each."""
    calls = []

    def fake_run(cmd, **kwargs):
        calls.append(cmd)
        return FakeProc(returncode=bench._PREFLIGHT_EXIT)

    monkeypatch.setattr(subprocess, "run", fake_run)
    import time

    monkeypatch.setattr(time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0
    assert "DEVICE UNAVAILABLE" in out["metric"]
    # 2 kernels probed (2 attempts each), the rest skipped without spawn
    assert len(calls) == 4
    skipped = [r for r in out["kernels"] if "skipped" in r.get("error", "")]
    assert len(skipped) == len(bench.KERNELS) - 2


def test_non_tpu_platform_skips_remaining_non_xla(monkeypatch, capsys):
    spawned = []

    def fake_run(cmd, **kwargs):
        name = next(a.split("=", 1)[1] for a in cmd
                    if a.startswith("--kernel="))
        spawned.append(name)
        if name == "xla":
            return FakeProc(stdout=_row(name, 0.3, platform="cpu") + "\n")
        return FakeProc(stdout=json.dumps(
            {"kernel": name, "ok": False, "platform": "cpu",
             "error": "skipped: not on TPU"}) + "\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    # only xla spawns a child once the platform is known to be CPU
    assert spawned == ["xla"]
    assert out["value"] == 0.3


def test_f64_runs_xla_only(monkeypatch, capsys):
    spawned = []

    def fake_run(cmd, **kwargs):
        name = next(a.split("=", 1)[1] for a in cmd
                    if a.startswith("--kernel="))
        spawned.append(name)
        assert "--dtype=f64" in cmd
        return FakeProc(stdout=_row(name, 25.0) + "\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--dtype=f64"])
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert spawned == ["xla"]
    assert "f64" in out["metric"]


def test_device_preflight_cpu():
    from cme213_tpu.core.platform import device_preflight

    assert device_preflight(60.0)  # CPU backend always reachable


def test_bisect_cell_parsing():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bisect", Path(__file__).resolve().parent.parent
        / "scripts" / "tpu_pipeline_bisect.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # 5-field cells pass through; legacy 4-field cells get tile_x=0
    cells = [(tuple(int(v) for v in c.split(",")) + (0,))[:5]
             for c in "4000,4000,256,1;512,512,64,2,128".split(";")]
    assert cells == [(4000, 4000, 256, 1, 0), (512, 512, 64, 2, 128)]
    assert all(len(c) == 5 for c in mod.DEFAULT_CELLS)


def test_pipeline_candidate_tile_ladder():
    """Pipeline children lead with the device-proven tile (64 — tile 128
    crashed Mosaic at k=4 width 4000, tranche-1 2026-07-31) and only
    then offer larger tiles; first-success-wins means a known-crashing
    tile at the front would burn minutes of every window."""
    from cme213_tpu.config import SimParams

    params = SimParams(nx=4000, ny=4000, order=8, iters=8)
    variants = bench._pipeline_candidates("pipeline-k8", params, 8, True)
    labels = [l for l, _ in variants]
    assert labels == ["tile_y=64", "tile_y=128"]
    # an explicit larger target is still honored (VMEM-clamped), placed
    # first, with the proven tile as fallback
    os.environ["BENCH_TILE_Y"] = "256"
    try:
        variants = bench._pipeline_candidates("pipeline-k8", params, 8,
                                              True)
        labels = [l for l, _ in variants]
        # the 256 target is VMEM-clamped to 160 at W=4096 (k=8) so the
        # compiler is never offered the 17 MiB band that crashed round 3
        assert labels == ["tile_y=160", "tile_y=64", "tile_y=128"]
    finally:
        del os.environ["BENCH_TILE_Y"]
    variants2d = bench._pipeline_candidates("pipeline2d-k1", params, 1, True)
    assert all("tile_x=512" in l for l, _ in variants2d)
