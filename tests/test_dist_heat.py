"""Distributed heat solver tests — the reference's N-rank-vs-1-rank
methodology (hw5 handout §5.1, SURVEY §4.4) on the fake 8-device CPU mesh.

The ``FMA_XFAIL``-marked pins document the known order-8 / k>1 bitwise
divergence between differently-fused XLA programs (FMA contraction on
concat-seam rows — docs/resilience.md, "Known divergence: FMA
contraction").  They run with ``conformance=False`` where the gated
serving path would otherwise demote the rung under test and make the pin
vacuous; the gated behavior itself is covered by
tests/test_guarded_execution.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

FMA_XFAIL = pytest.mark.xfail(
    strict=False,
    reason="1-ULP FMA-contraction divergence between XLA program "
           "formulations at order 8 / k>1 (docs/resilience.md 'Known "
           "divergence: FMA contraction'); the conformance gate demotes "
           "these rungs in serving paths")

from cme213_tpu.config import GridMethod, SimParams
from cme213_tpu.dist import make_mesh_1d, make_mesh_2d, mesh_for_method, run_distributed_heat
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat
from cme213_tpu.verify import check_ulp


def single_device_reference(params, iters, dtype=jnp.float32):
    u0 = make_initial_grid(params, dtype=dtype)
    return np.asarray(run_heat(jnp.array(u0), iters, params.order,
                               params.xcfl, params.ycfl))


@pytest.mark.parametrize("order", [2, 4, 8])
@pytest.mark.parametrize("overlap", [False, True])
def test_1d_matches_single_device(order, overlap):
    params = SimParams(nx=24, ny=32, order=order, iters=8)
    mesh = make_mesh_1d(4)
    ref = single_device_reference(params, 8)
    out = run_distributed_heat(params, mesh, overlap=overlap)
    res = check_ulp(ref, out, max_ulps=2,
                    label=f"dist1d-o{order}-{'async' if overlap else 'sync'}")
    assert res, res.message


@pytest.mark.parametrize("overlap", [False, True])
def test_2d_matches_single_device(overlap):
    params = SimParams(nx=32, ny=32, order=8, iters=6)
    mesh = make_mesh_2d(2, 2)
    ref = single_device_reference(params, 6)
    out = run_distributed_heat(params, mesh, overlap=overlap)
    res = check_ulp(ref, out, max_ulps=2, label="dist2d")
    assert res, res.message


def test_2d_rectangular_mesh():
    params = SimParams(nx=24, ny=32, order=4, iters=5)
    mesh = make_mesh_2d(4, 2)
    ref = single_device_reference(params, 5)
    out = run_distributed_heat(params, mesh, overlap=True)
    res = check_ulp(ref, out, max_ulps=2, label="dist2d-rect")
    assert res, res.message


@FMA_XFAIL
def test_sync_equals_overlap_bitwise():
    params = SimParams(nx=32, ny=32, order=8, iters=7)
    mesh = make_mesh_2d(2, 2)
    a = run_distributed_heat(params, mesh, overlap=False)
    b = run_distributed_heat(params, mesh, overlap=True)
    np.testing.assert_array_equal(a, b)


def test_one_device_mesh_matches():
    params = SimParams(nx=16, ny=16, order=2, iters=4)
    mesh = make_mesh_1d(1)
    ref = single_device_reference(params, 4)
    out = run_distributed_heat(params, mesh)
    res = check_ulp(ref, out, max_ulps=2, label="dist-1dev")
    assert res, res.message


def test_mesh_for_method():
    m1 = mesh_for_method(GridMethod.STRIPES_1D, 8)
    assert m1.devices.shape == (8,)
    m2 = mesh_for_method(GridMethod.BLOCKS_2D, 8)
    assert m2.devices.shape == (2, 4)
    m3 = mesh_for_method(GridMethod.BLOCKS_2D, 4)
    assert m3.devices.shape == (2, 2)


@pytest.mark.parametrize("overlap", [False, True])
def test_uneven_shards_match_single_device(overlap):
    """Grid sizes that don't divide the mesh (the reference's remainder-rank
    case) via ghost padding."""
    params = SimParams(nx=24, ny=30, order=2, iters=6)
    mesh = make_mesh_1d(4)  # 30 rows over 4 shards
    ref = single_device_reference(params, 6)
    out = run_distributed_heat(params, mesh, overlap=overlap)
    res = check_ulp(ref, out, max_ulps=2, label="dist-uneven")
    assert res, res.message


def test_uneven_2d_shards():
    params = SimParams(nx=21, ny=30, order=4, iters=5)
    mesh = make_mesh_2d(2, 2)
    ref = single_device_reference(params, 5)
    out = run_distributed_heat(params, mesh, overlap=True)
    res = check_ulp(ref, out, max_ulps=2, label="dist-uneven-2d")
    assert res, res.message


def test_thin_shards_fall_back_to_sync():
    # ny_loc = 4: ≥ border(4) but < 2·border(8) — overlap decomposition
    # infeasible, must auto-fall back to sync and stay correct
    params = SimParams(nx=24, ny=32, order=8, iters=3)
    mesh = make_mesh_1d(8)
    ref = single_device_reference(params, 3)
    out = run_distributed_heat(params, mesh, overlap=True)
    res = check_ulp(ref, out, max_ulps=2, label="dist-thin")
    assert res, res.message


def test_too_thin_shards_rejected():
    params = SimParams(nx=24, ny=16, order=8, iters=3)  # ny_loc=2 < border=4
    mesh = make_mesh_1d(8)
    with pytest.raises(ValueError):
        run_distributed_heat(params, mesh)


def test_synchronous_param_selects_variant():
    # smoke: params.synchronous=False triggers the overlap path
    params = SimParams(nx=16, ny=16, order=2, iters=3, synchronous=False)
    mesh = make_mesh_1d(2)
    ref = single_device_reference(params, 3)
    out = run_distributed_heat(params, mesh)
    res = check_ulp(ref, out, max_ulps=2, label="dist-async-param")
    assert res, res.message


@FMA_XFAIL
@pytest.mark.parametrize("method,ndev", [(GridMethod.STRIPES_1D, 4),
                                         (GridMethod.BLOCKS_2D, 4)])
@pytest.mark.parametrize("k", [2, 4])
def test_communication_avoiding_matches_k1(method, ndev, k):
    """k sub-steps per K-wide exchange must be bitwise identical to the
    exchange-every-step path (same stencil expression per cell).
    ``conformance=False``: the gate would demote the k>1 rung under test
    (tests/test_guarded_execution.py pins that demotion)."""
    from cme213_tpu.dist import prepare_distributed_heat

    # ny=64 over 4 stripes → ny_loc=16 ≥ K=k·4 for k≤4: the requested k
    # must actually be used (no silent fallback making the test vacuous)
    p = SimParams(nx=64, ny=64, order=8, iters=8, grid_method=method)
    mesh = mesh_for_method(method, ndev)
    _, _, k_used = prepare_distributed_heat(p, mesh, overlap=False,
                                            steps_per_exchange=k)
    assert k_used == k
    base = run_distributed_heat(p, mesh, overlap=False)
    multi = run_distributed_heat(p, mesh, overlap=False,
                                 steps_per_exchange=k, conformance=False)
    np.testing.assert_array_equal(multi, base)


def test_communication_avoiding_fallback_thin_shards():
    # 8 stripes of 6 rows each, order 8 (b=4): K=8 > 6 → must fall back
    # to k=1 and still be correct
    p = SimParams(nx=48, ny=48, order=8, iters=4)
    mesh = mesh_for_method(GridMethod.STRIPES_1D, 8)
    from cme213_tpu.dist import prepare_distributed_heat

    _, _, k_used = prepare_distributed_heat(p, mesh, steps_per_exchange=2)
    assert k_used == 1


@FMA_XFAIL
@pytest.mark.parametrize("k", [1, 2])
@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
def test_pallas_local_kernel_matches_single_device(k, mesh_kind):
    """Tuned Pallas pipeline kernel as the per-shard stencil (the hw5
    pattern: the optimized hw2 kernel under the comm layer) — bitwise
    against the single-device XLA solve.  The divergence here is the
    dist-vs-single-device program pair, not the Pallas kernel: the
    sharded solve (any local kernel) FMA-diverges from the single-device
    slice formulation at order 8 (see module docstring); the Pallas
    kernel agrees bitwise with the dist XLA rung, which is what the
    conformance gate enforces."""
    params = SimParams(nx=40, ny=48, order=8, iters=4 * k, bc_top=2.0,
                       bc_left=0.5, bc_bottom=1.0, bc_right=3.0)
    mesh = make_mesh_1d(4) if mesh_kind == "1d" else make_mesh_2d(2, 2)
    ref = single_device_reference(params, 4 * k)
    out = run_distributed_heat(params, mesh, steps_per_exchange=k,
                               local_kernel="pallas", conformance=False)
    np.testing.assert_array_equal(out, ref)


@FMA_XFAIL
def test_pallas_local_kernel_uneven_shards():
    params = SimParams(nx=30, ny=42, order=4, iters=4)
    mesh = make_mesh_1d(4)  # 42 rows over 4 shards: ghost-padded
    ref = single_device_reference(params, 4)
    out = run_distributed_heat(params, mesh, local_kernel="pallas",
                               conformance=False)
    np.testing.assert_array_equal(out, ref)


def test_pallas_local_kernel_keeps_requested_k_with_async_params():
    """synchronous=False params must not silently degrade the requested
    communication-avoiding k under the Pallas local kernel."""
    from cme213_tpu.dist import prepare_distributed_heat

    params = SimParams(nx=40, ny=48, order=8, iters=8, synchronous=False)
    mesh = make_mesh_1d(2)
    _, _, k_used = prepare_distributed_heat(params, mesh,
                                            steps_per_exchange=2,
                                            local_kernel="pallas")
    assert k_used == 2


def test_unknown_local_kernel_rejected():
    from cme213_tpu.dist import prepare_distributed_heat

    params = SimParams(nx=40, ny=48, order=8, iters=8)
    with pytest.raises(ValueError, match="local_kernel"):
        prepare_distributed_heat(params, make_mesh_1d(2),
                                 local_kernel="Pallas")
