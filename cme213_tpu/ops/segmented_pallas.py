"""Pallas blockwise segmented scan — single-pass O(n) kernel.

The TPU hand-tuned counterpart of the reference's intra-warp segmented scan
(``hw/hw_final/programming/fp.cu:28-59``).  The flat XLA formulation
(``ops/segmented.py``) sweeps the whole array log2(n) times; this kernel does
ONE pass over HBM using the hierarchical structure the reference's report
derives (warp window → block → grid; ``paper`` §design, and the radix
up/down-sweep, SURVEY §2.7 P7/P8):

- each grid step processes an (R, 128) VMEM tile in row-major element order:
  1. 7-step Hillis-Steele segmented scan along the 128-lane axis (the lane
     version of the warp scan, with the head-flag operator),
  2. log2(R)-step segmented scan of row summaries along the sublane axis,
     broadcast back to the rows,
  3. a scalar running carry — persisted in scratch across the sequentially-
     executed grid steps — is added to elements before the tile's first
     head, then updated to the scanned value of the tile's last element.

The cross-tile carry is correct without a flag because the local scan
already resets at heads: the last element's scanned value IS the running
sum of the open segment.

Exactness: identical additions in identical order as the flat version is
NOT guaranteed (different association), so float results agree to rounding,
not ULP — matching the reference's tolerance model for accumulating
pipelines (SURVEY §4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _roll(u, shift: int, axis: int, interpret: bool):
    """Circular shift; pltpu.roll on hardware (sub-array slices carry
    Mosaic offset layouts that concat — hence jnp.roll — can't combine)."""
    if interpret:
        return jnp.roll(u, shift, axis)
    return pltpu.roll(u, shift % u.shape[axis], axis)


def _make_kernel(rows: int, fused_multiply: bool = False,
                 interpret: bool = False):
    def kernel(*refs):
        if fused_multiply:
            v_ref, xx_ref, f_ref, out_ref, carry = refs
        else:
            v_ref, f_ref, out_ref, carry = refs
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            carry[0, 0] = 0.0

        v = v_ref[:]
        if fused_multiply:
            # the hw_final per-iteration elementwise multiply (fp.cu:176)
            # fused into the scan's load, saving a full HBM round trip
            v = v * xx_ref[:]
        f = f_ref[:]
        lane = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 1)
        rr = jax.lax.broadcasted_iota(jnp.int32, (rows, _LANES), 0)
        # 1) segmented scan along lanes
        d = 1
        while d < _LANES:
            pv = _roll(v, d, 1, interpret)
            pf = _roll(f, d, 1, interpret)
            valid = lane >= d
            v = v + jnp.where(valid & (f == 0), pv, jnp.zeros_like(v))
            f = jnp.where(valid, f | pf, f)
            d *= 2
        # 2) segmented scan of row summaries along sublanes, carried on
        # full-width (rows, 128) arrays (each row = its summary broadcast
        # across lanes).  The summary is extracted with a masked lane
        # reduce rather than a v[:, 127:] slice: single-lane slices carry
        # Mosaic offset layouts that later sublane ops refuse to combine,
        # while reduce + broadcast lower cleanly; the redundant lanes are
        # free on the VPU.
        last_lane = lane == _LANES - 1
        sv = jnp.broadcast_to(
            jnp.sum(jnp.where(last_lane, v, jnp.zeros_like(v)), axis=1,
                    keepdims=True), (rows, _LANES))
        sf = jnp.broadcast_to(
            jnp.max(jnp.where(last_lane, f, jnp.zeros_like(f)), axis=1,
                    keepdims=True), (rows, _LANES))
        d = 1
        while d < rows:
            pv = _roll(sv, d, 0, interpret)
            pf = _roll(sf, d, 0, interpret)
            valid = rr >= d
            sv = sv + jnp.where(valid & (sf == 0), pv, jnp.zeros_like(sv))
            sf = jnp.where(valid, sf | pf, sf)
            d *= 2
        # exclusive: row r's incoming = inclusive through row r-1
        inc_v = jnp.where(rr >= 1, _roll(sv, 1, 0, interpret),
                          jnp.zeros_like(sv))
        inc_f = jnp.where(rr >= 1, _roll(sf, 1, 0, interpret),
                          jnp.zeros_like(sf))
        v = v + jnp.where(f == 0, inc_v, jnp.zeros_like(v))
        # 3) cross-tile carry for elements before the tile's first head
        no_head_yet = (inc_f | f) == 0
        v = v + jnp.where(no_head_yet, carry[0, 0], jnp.zeros_like(v))
        # masked full-reduce scalar extract (vector.extract of a single
        # element is not a Mosaic-friendly shape)
        last = (rr == rows - 1) & (lane == _LANES - 1)
        carry[0, 0] = jnp.sum(jnp.where(last, v, jnp.zeros_like(v)))
        out_ref[:] = v

    return kernel


@partial(jax.jit, static_argnames=("rows", "interpret"))
def segmented_scan_pallas(values: jnp.ndarray, head_flags: jnp.ndarray,
                          rows: int = 32,
                          interpret: bool = False) -> jnp.ndarray:
    """Inclusive segmented sum scan of a 1-D f32 array, single HBM pass.

    Pads to a (rows × 128) tile multiple internally (padding isolated in its
    own segment and dropped on return).
    """
    assert values.dtype == jnp.float32
    n = values.shape[0]
    block = rows * _LANES
    nblk = max(1, -(-n // block))
    padded = nblk * block
    v = jnp.zeros((padded,), jnp.float32).at[:n].set(values)
    f = jnp.zeros((padded,), jnp.int32).at[:n].set(
        head_flags.astype(jnp.int32))
    if padded > n:
        f = f.at[n].set(1)  # quarantine the pad
    v2 = v.reshape(nblk * rows, _LANES)
    f2 = f.reshape(nblk * rows, _LANES)
    out = pl.pallas_call(
        _make_kernel(rows, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct((nblk * rows, _LANES), jnp.float32),
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.float32)],
        interpret=interpret,
    )(v2, f2)
    return out.reshape(padded)[:n]


@partial(jax.jit, static_argnames=("iters", "rows", "interpret"),
         donate_argnums=(0,))
def spmv_scan_pallas(a: jnp.ndarray, xx: jnp.ndarray,
                     head_flags: jnp.ndarray, iters: int, rows: int = 32,
                     interpret: bool = False) -> jnp.ndarray:
    """The full hw_final iteration with the multiply fused into the scan:
    N × one-HBM-pass ``a ← segscan(a·xx)``.  Pads once outside the loop."""
    assert a.dtype == jnp.float32
    n = a.shape[0]
    block = rows * _LANES
    nblk = max(1, -(-n // block))
    padded = nblk * block
    shape2 = (nblk * rows, _LANES)
    v2 = jnp.zeros((padded,), jnp.float32).at[:n].set(a).reshape(shape2)
    # pad xx with 1s so pad values stay 0 (0·1) without affecting real data
    xx2 = jnp.ones((padded,), jnp.float32).at[:n].set(xx).reshape(shape2)
    f = jnp.zeros((padded,), jnp.int32).at[:n].set(
        head_flags.astype(jnp.int32))
    if padded > n:
        f = f.at[n].set(1)
    f2 = f.reshape(shape2)

    spec = pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    step = pl.pallas_call(
        _make_kernel(rows, fused_multiply=True, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct(shape2, jnp.float32),
        grid=(nblk,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.float32)],
        interpret=interpret,
    )

    out = jax.lax.fori_loop(0, iters, lambda _, v: step(v, xx2, f2), v2)
    return out.reshape(padded)[:n]
