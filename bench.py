"""Headline benchmark: hw2-class 2-D heat stencil, order 8, 4000×4000, f32.

Mirrors the reference's measurement: hot iteration loop, effective bandwidth
= (1 read + 1 write) × 4 B × nx × ny per iteration (the accounting behind
``hw/hw2/programming/data/data.ods``; see BASELINE.md).  Baseline to beat:
shared-memory order-8 kernel at 4000² on a GTX 580 = **23.97 GB/s**.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
roofline context (``pct_hbm_peak``, ``pct_peak``, ``bound``, ``gflops``)
and per-kernel results.  Attribution comes from the centralized device-peak
registry + cost models (``cme213_tpu.core.roofline``): every per-kernel row
carries ``pct_peak`` (achieved/peak HBM bandwidth for the device it ran on)
and a memory-vs-compute ``bound`` verdict.  Per-rung failures are recorded
as structured ``kernel-failure`` events in the trace sink
(``CME213_TRACE_FILE``), so a capture's failure ladder is analyzable with
``python -m cme213_tpu trace`` instead of by grepping stderr tails.

Every candidate kernel runs in its OWN child process (``--run-measurement
--kernel=NAME``) with its own device preflight: a kernel that faults the
TPU client then reports a per-kernel error instead of poisoning the other
candidates (the BENCH_r02 failure mode, where one long-running conv blew
the tunnel's RPC deadline and every later kernel inherited a dead client).

Execution length is self-limiting: each child first times a short run, then
sizes the timed iteration count so a single device execution stays well
under the tunnel's RPC deadline.

``--spmv`` instead prints the iterated SpMV-scan engine row (flat vs
blocked vs Pallas-fused kernels, ``cme213_tpu.bench.sweeps.
spmv_scan_sweep``) as one JSON line of the same shape.
"""

import json
import os
import subprocess
import sys

BASELINE_GBS = 23.97  # hw2 shared-memory order-8 4000² float (BASELINE.md)
# TPU v5e HBM bandwidth (the chip bench runs on).  Must equal
# core/roofline.BUILTIN_PEAKS["tpu-v5e"].gbs (pinned by a tier-1 test);
# kept a literal because cme213_tpu imports must stay inside functions
# here — children apply JAX_PLATFORMS before jax ever loads.
HBM_PEAK_GBS = 819.0

_CHILD_FLAG = "--run-measurement"
_PREFLIGHT_EXIT = 42

# candidate kernel names; each runs in its own child process
# ordered by expected value: the safe baseline first (a number on the
# board), then pipeline-k4 — the kernel tranche-1 PROVED on device
# (251.8 GB/s, 10.5× baseline) — before the unproven deeper-unroll
# variants: round-5's first full-bench window died inside pipeline-k8's
# cold compile (15 min, then the tunnel dropped), so the proven winner
# banks first; xla-conv LAST — its ~200×-slower iterations are the
# kernel that blew the round-2 window and it is strictly diagnostic
KERNELS = ("xla", "pipeline-k4", "pipeline-k2", "pipeline-k8",
           "pipeline2d-k8", "xla-roll-k8", "pipeline-k1", "pipeline2d-k1",
           "xla-roll", "xla-conv")
_EXEC_CAP_S = 30.0
_MAX_ITERS = 400


def _apply_platform_env() -> None:
    """Honor an explicit JAX_PLATFORMS env var (this environment's
    sitecustomize otherwise overrides it — see core/platform)."""
    from cme213_tpu.core.platform import apply_platform_env

    apply_platform_env()


class DeviceUnreachable(RuntimeError):
    """Preflight watchdog failure — classifies RUNTIME, the one failure
    kind the bench retry policy backs off and retries on."""


def _preflight(seconds: float = 90.0, retry_sleep=None) -> bool:
    """Device-reachability watchdog (see core/platform.device_preflight),
    retried once through the shared ``core.resilience.RetryPolicy`` so a
    single dropped probe doesn't fail the whole child."""
    import time as _time

    from cme213_tpu.core.platform import device_preflight
    from cme213_tpu.core.resilience import FailureKind, RetryPolicy

    def probe() -> bool:
        if not device_preflight(seconds):
            raise DeviceUnreachable(f"no device response in {seconds}s")
        return True

    policy = RetryPolicy(max_retries=1, base_delay_s=5.0, multiplier=1.0,
                         max_delay_s=5.0, retry_on=(FailureKind.RUNTIME,),
                         sleep=retry_sleep or _time.sleep)
    try:
        return policy.run(probe, op="bench.preflight")
    except DeviceUnreachable:
        return False


def _make_candidate(name: str, params, on_tpu: bool):
    """Return (fn(u, iters), iters_quantum) for a kernel name."""
    from cme213_tpu.ops import run_heat, run_heat_conv
    from cme213_tpu.ops.stencil import run_heat_roll
    from cme213_tpu.ops.stencil_pipeline import run_heat_pipeline

    order = params.order
    if name == "xla":
        return (lambda u, it: run_heat(u, it, order, params.xcfl,
                                       params.ycfl), 1)
    if name == "xla-roll":
        return (lambda u, it: run_heat_roll(u, it, order, params.xcfl,
                                            params.ycfl, params.bc), 1)
    if name.startswith("xla-roll-k"):
        k = int(name.split("-k")[1])
        return (lambda u, it: run_heat_roll(u, it, order, params.xcfl,
                                            params.ycfl, params.bc, k=k), k)
    if name == "xla-conv":
        return (lambda u, it: run_heat_conv(u, it, order, params.xcfl,
                                            params.ycfl), 1)
    if name.startswith("pipeline-k") or name.startswith("pipeline2d-k"):
        k = int(name.split("-k")[1])
        return (_pipeline_candidates(name, params, k, on_tpu), k)
    raise SystemExit(f"unknown kernel {name!r}")


def _pipeline_candidates(name: str, params, k: int, on_tpu: bool):
    """(label, fn) variants for a pipeline kernel, proven tile first.

    The ladder opens with the DEVICE-PROVEN tile (BENCH_TILE_Y, default
    64 — the tile tranche-1 measured at 251.8 GB/s while 128 crashed
    Mosaic) and only then offers the larger tiles.  The remote compile
    helper is known to crash at some (width, tile) combinations; the
    child measures the first variant that calibrates, so an unattended
    bench run still records a tuned-kernel number instead of one error
    row per kernel — which is exactly why the ladder must NOT lead with
    an unproven large tile.
    """
    from cme213_tpu.ops.stencil_pipeline import (pick_pipeline_tile,
                                                 run_heat_pipeline,
                                                 run_heat_pipeline2d)

    order = params.order
    # BENCH_TILE_Y is a target; rounded to a valid multiple of the halo
    # quantum so an arbitrary override can't trip the tile assert.
    # Default ladder leads with the DEVICE-PROVEN tile: tranche-1
    # (2026-07-31 01:06 UTC) showed tile 128 crashes Mosaic at k=4
    # width 4000 while 64 compiles and hits 251.8 GB/s — and since this
    # loop takes the first variant that calibrates, opening with a
    # known-crashing tile costs minutes of window per bench re-run.
    # Tile *exploration* (measure every tile, best wins) belongs to the
    # pipeline_tune sweep, not the headline bench.
    target = int(os.environ.get("BENCH_TILE_Y", "64"))
    tiles = []
    for t in (target, 64, 128):
        # width-aware: a tile whose double-buffered band would overflow
        # VMEM at this grid width is clamped before the compiler sees it
        ty = pick_pipeline_tile(params.gy, k, order, target=t,
                                width=params.gx)
        if ty not in tiles:
            tiles.append(ty)
    variants = []
    for ty in tiles:
        if name.startswith("pipeline2d-k"):
            tile_x = max(int(os.environ.get("BENCH_TILE_X", "512"))
                         // 128 * 128, 128)
            variants.append((f"tile_y={ty},tile_x={tile_x}",
                             lambda u, it, ty=ty: run_heat_pipeline2d(
                                 u, it, order, params.xcfl, params.ycfl,
                                 params.bc, k=k, tile_y=ty, tile_x=tile_x,
                                 interpret=not on_tpu)))
        else:
            variants.append((f"tile_y={ty}",
                             lambda u, it, ty=ty: run_heat_pipeline(
                                 u, it, order, params.xcfl, params.ycfl,
                                 params.bc, k=k, tile_y=ty,
                                 interpret=not on_tpu)))
    return variants


def measure_one(name: str, dtype_name: str) -> dict:
    import time

    _apply_platform_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    if dtype_name == "f64":
        jax.config.update("jax_enable_x64", True)

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid

    nx = ny = 4000
    order = 8
    params = SimParams(nx=nx, ny=ny, order=order, iters=1000)
    dtype = {"f32": jnp.float32, "f64": jnp.float64}[dtype_name]
    # Host copy: the heat loops donate their input buffer, and device_put of
    # an already-committed device array is a no-op returning the same buffer
    # — which the first donated call would delete out from under us.
    u0 = np.asarray(make_initial_grid(params, dtype=dtype))
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"device: {dev}", file=sys.stderr)

    if not on_tpu and name != "xla":
        # interpret-mode Pallas (and CPU conv) at 4000² would take hours;
        # only the fused-XLA kernel is meaningful off-TPU
        return {"kernel": name, "ok": False, "platform": dev.platform,
                "error": "skipped: not on TPU"}
    if dtype_name == "f64" and name != "xla":
        # TPU Pallas/Mosaic has no f64 lowering and the conv path is
        # f32-tuned; the reference's double rows measure one kernel too
        return {"kernel": name, "ok": False, "platform": dev.platform,
                "error": "skipped: f64 is XLA-only"}

    cand, quantum = _make_candidate(name, params, on_tpu)
    variants = cand if isinstance(cand, list) else [("", cand)]

    fn = None
    variant_label = ""
    err = None
    iters_cal = 8 * quantum
    for label, vfn in variants:
        def timed(iters: int, vfn=vfn) -> float:
            # device_put is async: block on the H2D transfer BEFORE the
            # clock starts, or the 64 MB upload (seconds over the tunnel)
            # lands inside the timed region and deflates every kernel
            u = jax.block_until_ready(jax.device_put(u0, dev))
            start = time.perf_counter()
            jax.block_until_ready(vfn(u, iters))
            return time.perf_counter() - start

        try:
            # short calibration run (also the compile warmup); a variant
            # whose tile crashes the compiler fails here and the next
            # tile is tried
            timed(iters_cal)
            per_iter = timed(iters_cal) / iters_cal
            fn, variant_label = vfn, label
            break
        except Exception as e:  # noqa: BLE001 — try the next variant
            err = e
            print(f"{name} [{label}]: calibration failed "
                  f"({type(e).__name__})", file=sys.stderr)
    if fn is None:
        return {"kernel": name, "ok": False,
                "error": f"{type(err).__name__}: {err}"}

    def timed(iters: int) -> float:
        u = jax.block_until_ready(jax.device_put(u0, dev))
        start = time.perf_counter()
        jax.block_until_ready(fn(u, iters))
        return time.perf_counter() - start

    try:
        # size the timed run to stay under the single-execution cap (the
        # axon tunnel kills executions that outlive its RPC deadline)
        iters = max(int(_EXEC_CAP_S / max(per_iter, 1e-9)), iters_cal)
        iters = min(iters - iters % quantum or quantum, _MAX_ITERS)
        if iters != iters_cal:
            timed(iters)              # compile at the final count
        elapsed = timed(iters)
    except Exception as e:  # noqa: BLE001 — report any device failure
        return {"kernel": name, "ok": False,
                "error": f"{type(e).__name__}: {e}"}

    from cme213_tpu.core import roofline

    per_iter = elapsed / iters
    cost = roofline.heat_cost(ny, nx, order=order, iters=1,
                              dtype=dtype_name)
    gbs = round(cost.nbytes / per_iter / 1e9, 2)
    gflops = round(cost.flops / per_iter / 1e9, 2)
    att = roofline.attribute(gbs, gflops)
    return {
        "kernel": name, "ok": True, "iters": iters,
        "variant": variant_label,
        "platform": dev.platform,
        "device_kind": att["device"],
        "dtype": dtype_name,
        "ms_per_iter": round(per_iter * 1e3, 4),
        "gbs": gbs,
        "gflops": gflops,
        "pct_peak": att["pct_peak"],
        "bound": att["bound"],
    }


def _attempt_kernel(name: str, dtype_name: str) -> dict:
    """One child-process measurement attempt.

    Raises :class:`DeviceUnreachable` on a preflight exit (retryable:
    the retry policy backs off and reruns); returns an error row for a
    timeout (NOT retryable: with no result in 900 s the second cold
    attempt would do the same compile again and time out the same way —
    the persistent compile cache only helps once a compile has ever
    FINISHED) or any other child failure.
    """
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), _CHILD_FLAG,
             f"--kernel={name}", f"--dtype={dtype_name}"],
            timeout=900, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return {"kernel": name, "ok": False, "error": "timeout (900s)"}
    sys.stderr.write(proc.stderr)
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if lines:
        return json.loads(lines[-1])
    if proc.returncode == _PREFLIGHT_EXIT:
        raise DeviceUnreachable(f"{name}: preflight device unreachable")
    return {"kernel": name, "ok": False,
            "error": f"child exit {proc.returncode}"}


def run_children(dtype_name: str, budget_s: float = 2700.0,
                 retry_sleep=None) -> list[dict]:
    """Run every candidate in its own subprocess; collect per-kernel rows.

    Per-kernel retry goes through ``core.resilience.RetryPolicy``: one
    retry after a deterministic 120 s backoff, and ONLY on a
    device-unreachable preflight (RUNTIME) — timeouts and child crashes
    are not retried (see ``_attempt_kernel``).  ``retry_sleep`` is
    injectable so tests never wait the backoff for real.  Two
    consecutive device-unreachable kernels (or an exhausted global
    budget) short-circuit the remaining candidates — a dead tunnel would
    otherwise cost 90 s preflight + 120 s recovery sleep per kernel.
    """
    import time as _time

    from cme213_tpu.core.resilience import FailureKind, RetryPolicy

    # CI shrinks the recovery backoff (CME213_BENCH_RETRY_S) so the
    # injected-unreachable doctor gate doesn't sit through 120 s sleeps
    retry_s = float(os.environ.get("CME213_BENCH_RETRY_S", "120") or 120)
    policy = RetryPolicy(max_retries=1, base_delay_s=retry_s, multiplier=1.0,
                         max_delay_s=retry_s, retry_on=(FailureKind.RUNTIME,),
                         sleep=retry_sleep or _time.sleep)
    deadline = _time.monotonic() + budget_s
    rows = []
    dead_streak = 0
    platform = None
    kernels = ("xla",) if dtype_name == "f64" else KERNELS
    for name in kernels:
        if platform is not None and platform != "tpu" and name != "xla":
            rows.append({"kernel": name, "ok": False,
                         "error": "skipped: not on TPU"})
            continue
        if dead_streak >= 2 or _time.monotonic() > deadline:
            rows.append({"kernel": name, "ok": False,
                         "error": "skipped: device unreachable"
                         if dead_streak >= 2 else "skipped: bench budget"})
            continue
        try:
            row = policy.run(lambda: _attempt_kernel(name, dtype_name),
                             op="bench.heat2d")
        except DeviceUnreachable:
            row = {"kernel": name, "ok": False,
                   "error": "preflight: device unreachable"}
        platform = row.get("platform", platform)
        # only preflight failures indicate a dead device — a wedged tunnel
        # fails the 90 s preflight watchdog (exit 42), while a 900 s child
        # timeout just means a slow kernel/compile on a healthy device
        unreachable = (not row.get("ok")
                       and "unreachable" in row.get("error", ""))
        dead_streak = dead_streak + 1 if unreachable else 0
        rows.append(row)
        if not row.get("ok"):
            # structured form of the per-rung "pallas: failed (...)" tail
            # lines (BENCH_r02): lands in the CME213_TRACE_FILE sink so
            # TPU captures are analyzable with the trace CLI
            from cme213_tpu.core import diag, trace

            # stage attribution from the error text (the exception object
            # died with the child process): Mosaic/compile noise maps to
            # lower/compile, everything else — including the preflight's
            # "device unreachable" — is an execute-stage failure
            trace.record_event("kernel-failure", op="heat2d", kernel=name,
                               error=str(row.get("error", ""))[:500],
                               stage=diag.stage_for_message(
                                   row.get("error", "")))
        detail = (f"{row['ms_per_iter']} ms/iter, {row['gbs']} GB/s eff, "
                  f"{row['gflops']} GF/s" if row.get("ok")
                  else f"failed ({row.get('error')})")
        print(f"{name}: {detail}", file=sys.stderr)
    return rows


def _banked_rows(dtype_name: str = "f32") -> list[dict]:
    """Committed device measurements from earlier tunnel windows.

    NOT live numbers — each row is tagged with the evidence file it was
    committed to (tranche-1 first-window bank, or a prior full-bench
    capture) so the reader can tell banked from measured-now.  Rows are
    filtered to the requested bench dtype (pre-dtype-field rows were all
    f32 captures, so a missing field reads as f32) — an f32 device number
    must never surface as banked evidence in the f64 output (ADVICE r5).
    """
    here = os.path.dirname(os.path.abspath(__file__))
    out = []
    results = os.path.join(here, "bench_results")
    try:
        names = sorted(os.listdir(results))
    except OSError:
        return out
    for fname in names:
        if not (fname.startswith("tranche1_") and fname.endswith(".json")):
            continue
        try:
            with open(os.path.join(results, fname)) as f:
                row = json.load(f)
        except (OSError, ValueError):
            continue
        if (row.get("ok") and row.get("platform") == "tpu"
                and row.get("dtype", "f32") == dtype_name):
            out.append({"evidence": f"bench_results/{fname}", **row})
    return out


def run_spmv_bench() -> None:
    """``--spmv``: the iterated SpMV-scan engine row (ISSUE 1) — flat vs
    blocked vs Pallas-fused effective bandwidth at the sweep's largest n,
    printed as one JSON line like the headline heat metric.  Runs in-
    process (the sweep already classifies per-kernel failures as rows)."""
    _apply_platform_env()
    from cme213_tpu.bench.sweeps import spmv_scan_sweep
    from cme213_tpu.core import diag, trace

    rows = spmv_scan_sweep()
    ok = [r for r in rows if not r.get("error") and r["gbs"] > 0]
    for r in rows:
        if r.get("error"):
            trace.record_event("kernel-failure", op="spmv_scan",
                               kernel=r.get("kernel", "?"),
                               error=str(r["error"])[:500],
                               stage=diag.stage_for_message(r["error"]))
    if not ok:
        print(json.dumps({
            "metric": "spmv_scan iterated segmented-scan effective "
                      "bandwidth (NO MEASUREMENT)",
            "value": 0.0, "unit": "GB/s", "kernels": rows}))
        return
    n_max = max(r["n"] for r in ok)
    best = max((r for r in ok if r["n"] == n_max), key=lambda r: r["gbs"])
    print(json.dumps({
        "metric": f"spmv_scan iterated segmented-scan effective bandwidth "
                  f"at n={n_max} (best kernel: {best['kernel']})",
        "value": best["gbs"], "unit": "GB/s",
        "pct_hbm_peak": round(100 * best["gbs"] / HBM_PEAK_GBS, 1),
        "pct_peak": best.get("pct_peak"), "bound": best.get("bound"),
        "kernels": rows,
    }))


def main() -> int:
    if "--spmv" in sys.argv:
        run_spmv_bench()
        return 0
    if _CHILD_FLAG in sys.argv:
        kernel = next((a.split("=", 1)[1] for a in sys.argv
                       if a.startswith("--kernel=")), "xla")
        dtype_name = next((a.split("=", 1)[1] for a in sys.argv
                           if a.startswith("--dtype=")), "f32")
        if not _preflight():
            print("preflight: device unreachable within 90s", file=sys.stderr)
            sys.exit(_PREFLIGHT_EXIT)
        print(json.dumps(measure_one(kernel, dtype_name)))
        return 0

    dtype_name = next((a.split("=", 1)[1] for a in sys.argv
                       if a.startswith("--dtype=")), "f32")
    rows = run_children(dtype_name)
    ok = [r for r in rows if r.get("ok")]
    # rows from older children (or fakes) may predate in-child
    # attribution: fill pct_peak/bound from the registry, keyed by the
    # row's own platform — no jax needed in the parent
    from cme213_tpu.core import roofline

    for r in ok:
        if "pct_peak" not in r:
            device = r.get("device_kind") or (
                "tpu-v5e" if r.get("platform") == "tpu"
                else r.get("platform"))
            att = roofline.attribute(r.get("gbs", 0.0),
                                     r.get("gflops", 0.0), device=device)
            r["pct_peak"], r["bound"] = att["pct_peak"], att["bound"]
    best = max(ok, key=lambda r: r["gbs"]) if ok else None
    if best is None:
        # value stays 0 — no live measurement happened — but point at the
        # committed device rows from earlier tunnel windows so a dead
        # tunnel at capture time doesn't read as "never measured"
        unreachable = any("unreachable" in str(r.get("error", ""))
                          for r in rows)
        doc = {
            "metric": f"heat2d stencil order-8 4000x4000 {dtype_name} "
                      "effective bandwidth (DEVICE UNAVAILABLE)",
            "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0,
            "kernels": rows,
            "banked_device_rows": _banked_rows(dtype_name),
        }
        if unreachable:
            # bank a doctor report in the capture tail: the round still
            # failed, but it failed with a staged health ladder attached
            # instead of nothing to debug (the r03–r05 failure mode).
            # In-process, not a subprocess: the parent's own view of the
            # device is the one that matters (and tests fake the children)
            try:
                from cme213_tpu.core import diag

                doc["doctor"] = diag.health_report()
            except Exception as e:  # noqa: BLE001 — tail must still print
                doc["doctor"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(doc, default=str))
        # nonzero on a dead device: capture drivers see the round failed
        # (the JSON tail above still carries everything they should bank)
        return 1 if unreachable else 0
    print(json.dumps({
        "metric": f"heat2d stencil order-8 4000x4000 {dtype_name} effective "
                  f"bandwidth (best kernel: {best['kernel']})",
        "value": best["gbs"],
        "unit": "GB/s",
        "vs_baseline": round(best["gbs"] / BASELINE_GBS, 3),
        "pct_hbm_peak": round(100 * best["gbs"] / HBM_PEAK_GBS, 1),
        "pct_peak": best.get("pct_peak"),
        "bound": best.get("bound"),
        "gflops": best["gflops"],
        "kernels": rows,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
