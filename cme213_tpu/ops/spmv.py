"""Sparse matrix-vector products — CSR and ELL formats (Bell/Garland 2008).

The reference's SpMV-adjacent machinery (CSR gather in PageRank,
``hw/hw1/programming/pagerank.cu:70-83``; the Bell/Garland SpMV tech reports
shipped in ``refs/``; the hw_final segmented-scan formulation) generalizes to
two TPU-native SpMV kernels:

- ``csr_spmv``: edge-parallel gather + ``segment_sum`` — regular and
  XLA-fusable, like the PageRank op.
- ``ell_spmv``: the ELLPACK formulation — a dense ``(rows, max_nnz)`` index/
  value layout reduced over the nnz axis.  This is the TPU-sweet-spot
  format: fully static shapes, vectorized gather, no irregularity (the same
  reason Bell/Garland recommend ELL for wide-SIMD GPUs).
- ``csr_to_ell``: format conversion with zero padding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_rows",))
def csr_spmv(row_ids: jnp.ndarray, col_idx: jnp.ndarray, values: jnp.ndarray,
             x: jnp.ndarray, num_rows: int) -> jnp.ndarray:
    """y = A·x with A given as flat (row_ids, col_idx, values) triplets
    (row_ids precomputed from CSR offsets via ``ops.gather.csr_row_ids``).

    Precondition: ``row_ids`` must be non-decreasing (CSR order) — the
    sorted segment reduction is undefined for unsorted ids; sort COO
    triplets by row before calling."""
    contrib = values * x[col_idx]
    # row_ids derived from CSR offsets are non-decreasing — the sorted
    # lowering avoids a general scatter-add on TPU
    return jax.ops.segment_sum(contrib, row_ids, num_segments=num_rows,
                               indices_are_sorted=True)


@jax.jit
def ell_spmv(ell_cols: jnp.ndarray, ell_vals: jnp.ndarray,
             x: jnp.ndarray) -> jnp.ndarray:
    """y = A·x with A in ELL format: ``ell_cols``/``ell_vals`` of shape
    (rows, max_nnz), padded entries having value 0."""
    return jnp.sum(ell_vals * x[ell_cols], axis=1)


def csr_to_ell(indices: np.ndarray, col_idx: np.ndarray,
               values: np.ndarray):
    """CSR → ELL conversion (host-side, once per matrix)."""
    counts = np.diff(indices).astype(np.int64)
    rows = counts.shape[0]
    width = int(counts.max()) if rows else 0
    ell_cols = np.zeros((rows, width), dtype=np.int32)
    ell_vals = np.zeros((rows, width), dtype=values.dtype)
    for r in range(rows):
        lo, hi = indices[r], indices[r + 1]
        ell_cols[r, : hi - lo] = col_idx[lo:hi]
        ell_vals[r, : hi - lo] = values[lo:hi]
    return ell_cols, ell_vals
