"""Multi-device segmented scan — long-sequence (context) parallelism.

The reference scales scans beyond one worker with the block-scan
decomposition: per-block partial results, a scan over block totals, then a
downsweep (``hw/hw4/programming/radixsort.cpp:44-108``), and slides a warp
window over arbitrarily long segments (``hw/hw_final/programming/fp.cu:
41-59``).  This module is that same pattern at mesh scale (SURVEY §5
"long-context"): a sequence sharded over a mesh axis is scanned per-shard,
shard carries are combined with the segmented-scan operator across devices,
and each shard applies its incoming carry to the elements before its first
segment head.

The carry combine is O(P) on gathered carries (``lax.all_gather`` over ICI;
P = mesh axis size, so the unrolled prefix is tiny) — the mesh-scale
equivalent of the serial bucket scan between the two parallel phases of the
reference's radix pass.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.segmented import segmented_scan


def _local_with_carry(values, flags, axis_name: str, axis_size: int):
    local = segmented_scan(values, flags)
    # shard carry: (last partial sum, does my shard contain a head?)
    carry_v = local[-1]
    carry_f = jnp.max(flags).astype(jnp.int32)
    vs = lax.all_gather(carry_v, axis_name)      # (P,)
    fs = lax.all_gather(carry_f, axis_name)      # (P,)
    # exclusive prefix-combine of carries with the segmented operator,
    # unrolled over the (small, static) mesh axis
    prefixes_v = [jnp.zeros_like(carry_v)]
    prefixes_f = [jnp.zeros_like(carry_f)]
    for j in range(axis_size - 1):
        pv, pf = prefixes_v[-1], prefixes_f[-1]
        prefixes_v.append(vs[j] + jnp.where(fs[j] > 0, jnp.zeros_like(pv), pv))
        prefixes_f.append(pf | fs[j])
    idx = lax.axis_index(axis_name)
    incoming = jnp.stack(prefixes_v)[idx]
    # apply to elements of the incoming open segment: position i belongs to
    # it iff no head at any position <= i (cummax of flags still 0)
    no_head_yet = lax.cummax(flags, axis=0) == 0
    return local + jnp.where(no_head_yet, incoming, jnp.zeros_like(incoming))


def distributed_segmented_scan(values: jnp.ndarray, head_flags: jnp.ndarray,
                               mesh: Mesh, axis_name: str | None = None):
    """Segmented inclusive scan of a sequence sharded over one mesh axis.

    ``len(values)`` must divide evenly over the axis.  Works under jit; the
    result carries the same sharding as the input.
    """
    axis_name = axis_name or mesh.axis_names[0]
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if values.shape[0] % axis_size:
        raise ValueError("sequence length must divide over the mesh axis")
    spec = P(axis_name)
    sharding = NamedSharding(mesh, spec)
    values = jax.device_put(values, sharding)
    head_flags = jax.device_put(head_flags.astype(jnp.int32), sharding)

    fn = jax.jit(jax.shard_map(
        partial(_local_with_carry, axis_name=axis_name, axis_size=axis_size),
        mesh=mesh, in_specs=(spec, spec), out_specs=spec,
    ))
    return fn(values, head_flags)
