"""Durable long-job lane: preemptible checkpointed batch solves that
survive replica death and whole-fleet restarts.

The reference submitted its long solve — hw1's PageRank power iteration
— through Torque ``qsub`` batch scripts (``jobs/``): work queued beside
the interactive shell, surviving logout, polled with ``qstat``.  This
module is that batch queue rebuilt on the serving fleet, with the
durability story Torque delegated to the cluster:

- **JobStore** — one CRC-checked JSON record per job in a shared
  directory, written atomically (unique tmp + ``os.replace``) with the
  previous record retained at ``.prev`` and corrupt records quarantined
  to ``.corrupt`` (the same discipline as ``core/checkpoint.py``).  The
  state machine is PENDING → RUNNING ⇄ PREEMPTED → DONE/FAILED/STALLED;
  every transition is **write-ahead**: an ``intent`` field lands first,
  the work happens (the epoch's ``.npz`` checkpoint commits), then the
  record is published with the intent cleared.  A crash between the two
  writes is recovered by replaying the intent against the durable
  checkpoint — a committed epoch is *never* re-executed, because the
  next tick's ``run_with_checkpoints`` call resumes at the checkpoint's
  step and the pending intent merely re-targets the same epoch.
  Submission is **idempotent** keyed by the client's job id (exclusive
  ``os.link`` publish of the first record): a replayed submit returns
  the existing record — and, once DONE, the original result — instead
  of double-running.
- **JobExecutor** — runs registered job kinds (``serve/workloads.py``
  ``JOB_KINDS``; PageRank first) as epoch-sized chunks through
  ``core.checkpoint.run_with_checkpoints`` with the PR 14
  ``ConvergenceTracker``.  The serving thread calls :meth:`tick` only
  in idle gaps; each tick runs at most ONE epoch and re-checks the
  preemption signals (interactive queue depth, ``serve/slo.py`` burn)
  first, so interactive batches strictly win and a job is preempted at
  epoch boundaries — never mid-epoch, never losing committed work.
- **Ownership** — a ``.owner`` claim file per job, created with
  ``O_CREAT|O_EXCL`` (atomic across processes), holds the rank of the
  replica running it; a relaunched replica keeps its rank and resumes
  its own jobs, and the fleet reassigns claims off permanently-dead
  replicas (``serve/fleet.py``).

The epoch commit publish calls ``core.faults.maybe_fail_commit`` — the
``ckpt:commit`` crash window, now on the serving path (chaos campaigns
draw it; ``core/chaos.py``) — and the epoch checkpoints flow through
``save_checkpoint``'s ``ckpt:truncate`` torn-write hook, so both
checkpoint fault clauses exercise real recovery here.
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib

import numpy as np

from ..core import metrics
from ..core.faults import InjectedFault, maybe_fail_commit
from ..core.trace import record_event

#: shared job directory a fleet exports to its replicas
JOBS_DIR_ENV = "CME213_JOBS_DIR"

PENDING = "PENDING"
RUNNING = "RUNNING"
PREEMPTED = "PREEMPTED"
DONE = "DONE"
FAILED = "FAILED"
STALLED = "STALLED"

TERMINAL = frozenset({DONE, FAILED, STALLED})

#: legal state transitions (RUNNING → RUNNING is the per-epoch publish)
_ALLOWED = {
    PENDING: {RUNNING, FAILED},
    RUNNING: {RUNNING, PREEMPTED, DONE, FAILED, STALLED},
    PREEMPTED: {RUNNING, FAILED},
}

#: control kinds the transport/fleet front ends route to the job lane
JOB_CONTROLS = ("job-submit", "job-status", "job-list", "job-cancel",
                "job-result")

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

#: record fields exposed over the wire (everything small and JSON-safe)
_PUBLIC = ("job", "op", "state", "epoch", "total_epochs", "iters",
           "total_iters", "epoch_iters", "residual", "reason", "resumes",
           "preemptions", "intent", "result_crc", "submitted_t",
           "updated_t")


class JobError(ValueError):
    """Bad job id / parameters / illegal state transition."""


def _check_id(job: str) -> str:
    if not isinstance(job, str) or not _ID_RE.match(job):
        raise JobError(f"bad job id {job!r} (want [A-Za-z0-9][A-Za-z0-9._-]"
                       "{0,63})")
    return job


def _record_crc(rec: dict) -> int:
    body = {k: v for k, v in rec.items() if k != "crc"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return zlib.crc32(blob.encode("utf-8")) & 0xFFFFFFFF


def public(rec: dict) -> dict:
    """Wire-safe view of one record."""
    return {k: rec.get(k) for k in _PUBLIC}


class JobStore:
    """Durable job records in one directory; every mutation is an atomic
    replace and every read is CRC-verified with ``.prev`` fallback."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # ---------------------------------------------------------- paths

    def record_path(self, job: str) -> str:
        return os.path.join(self.directory, f"job-{_check_id(job)}.json")

    def checkpoint_path(self, job: str) -> str:
        return os.path.join(self.directory, f"job-{_check_id(job)}.npz")

    def result_path(self, job: str) -> str:
        return os.path.join(self.directory,
                            f"job-{_check_id(job)}.result.npz")

    def _owner_path(self, job: str) -> str:
        return os.path.join(self.directory, f"job-{_check_id(job)}.owner")

    def _cancel_path(self, job: str) -> str:
        return os.path.join(self.directory, f"job-{_check_id(job)}.cancel")

    # --------------------------------------------------------- records

    def submit(self, job: str, op: str, params: dict, total_iters: int,
               epoch_iters: int, total_epochs: int) -> tuple[dict, bool]:
        """Idempotent submit: publish the PENDING record exclusively
        (tmp + ``os.link``, atomic even across hosts on one filesystem);
        if the id already exists, return the existing record untouched —
        a replayed submission never double-runs."""
        path = self.record_path(job)
        rec = {
            "job": _check_id(job), "op": op, "params": dict(params),
            "state": PENDING, "epoch": 0, "total_epochs": int(total_epochs),
            "iters": 0, "total_iters": int(total_iters),
            "epoch_iters": int(epoch_iters), "intent": None,
            "residual": None, "reason": None, "result_crc": None,
            "resumes": 0, "preemptions": 0,
            "submitted_t": time.time(), "updated_t": time.time(),
        }
        rec["crc"] = _record_crc(rec)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)        # exclusive: fails if the id exists
        except FileExistsError:
            existing = self.load(job)
            if existing is not None:
                return existing, False
            return rec, False         # racing submit won; record torn —
            # the winner's retry (or ours) re-publishes
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return rec, True

    def load(self, job: str) -> dict | None:
        """The job's record, CRC-verified; a corrupt candidate is
        quarantined to ``.corrupt`` and the retained ``.prev`` serves —
        one torn record write never loses the job."""
        path = self.record_path(job)
        for candidate in (path, path + ".prev"):
            if not os.path.exists(candidate):
                continue
            try:
                with open(candidate) as f:
                    rec = json.load(f)
                if rec.get("crc") != _record_crc(rec):
                    raise JobError("record checksum mismatch")
                return rec
            except (OSError, ValueError) as e:
                quarantine = candidate + ".corrupt"
                try:
                    os.replace(candidate, quarantine)
                except OSError:
                    continue
                metrics.counter("jobs.record_quarantines").inc()
                record_event("checkpoint-quarantine", path=candidate,
                             quarantined_to=quarantine,
                             error=type(e).__name__, message=str(e)[:200])
        return None

    def _write(self, rec: dict) -> None:
        path = self.record_path(rec["job"])
        rec["updated_t"] = time.time()
        rec["crc"] = _record_crc(rec)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)

    def intent(self, rec: dict, **doc) -> None:
        """Write-ahead: land what is *about to happen* before doing it.
        A crash after this write replays the intent against the durable
        epoch checkpoint instead of guessing."""
        rec["intent"] = doc
        self._write(rec)

    def publish(self, rec: dict, **updates) -> None:
        """Commit a transition: apply ``updates``, clear the intent, and
        replace the record.  ``maybe_fail_commit`` fires first — the
        ``ckpt:commit`` window is work-durable-but-record-unpublished,
        exactly what intent replay recovers."""
        new_state = updates.get("state")
        if new_state is not None and new_state != rec["state"]:
            if new_state not in _ALLOWED.get(rec["state"], ()):
                raise JobError(f"illegal transition {rec['state']} -> "
                               f"{new_state} for job {rec['job']}")
        maybe_fail_commit()
        rec.update(updates)
        rec["intent"] = None
        self._write(rec)

    def list_jobs(self) -> list[dict]:
        recs = []
        for name in sorted(os.listdir(self.directory)):
            if not (name.startswith("job-") and name.endswith(".json")):
                continue
            rec = self.load(name[len("job-"):-len(".json")])
            if rec is not None:
                recs.append(rec)
        return recs

    # ------------------------------------------------------- ownership

    def claim(self, job: str, owner: str) -> bool:
        """Atomically claim an unowned job (O_CREAT|O_EXCL — exactly one
        process wins even when several scan at once)."""
        try:
            fd = os.open(self._owner_path(job),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(str(owner))
        return True

    def owner(self, job: str) -> str | None:
        try:
            with open(self._owner_path(job)) as f:
                return f.read().strip() or None
        except OSError:
            return None

    def reassign(self, job: str, owner: str) -> None:
        """Overwrite a claim (fleet rescheduling off a dead replica —
        only safe once the previous owner cannot write)."""
        path = self._owner_path(job)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(owner))
        os.replace(tmp, path)

    def reassign_from(self, dead_owner: str, new_owner: str) -> list[str]:
        """Move every non-terminal job claimed by ``dead_owner`` to
        ``new_owner``; returns the moved job ids."""
        moved = []
        for rec in self.list_jobs():
            if rec["state"] in TERMINAL:
                continue
            if self.owner(rec["job"]) == str(dead_owner):
                self.reassign(rec["job"], new_owner)
                moved.append(rec["job"])
        return moved

    # ---------------------------------------------------------- cancel

    def request_cancel(self, job: str) -> None:
        with open(self._cancel_path(job), "w") as f:
            f.write("cancel")

    def cancel_requested(self, job: str) -> bool:
        return os.path.exists(self._cancel_path(job))

    # ---------------------------------------------------------- results

    def save_result(self, job: str, iters: int, value: np.ndarray) -> int:
        from ..core.checkpoint import save_checkpoint

        return save_checkpoint(self.result_path(job), iters,
                               value=np.asarray(value))

    def load_result(self, job: str) -> np.ndarray | None:
        from ..core.checkpoint import load_checkpoint

        loaded = load_checkpoint(self.result_path(job))
        if loaded is None:
            return None
        _, arrays = loaded
        return arrays.get("value")


# ---------------------------------------------------------------- submit

def submit_job(store: JobStore, job: str, op: str,
               params: dict | None = None) -> tuple[dict, bool]:
    """Normalize ``params`` through the registered kind and publish the
    PENDING record (idempotent); emits ``job-submitted`` only when the
    record was actually created."""
    from .workloads import JOB_KINDS

    if op not in JOB_KINDS:
        raise JobError(f"unknown job op {op!r} (have: {sorted(JOB_KINDS)})")
    kind = JOB_KINDS[op]
    p = kind.normalize(params or {})
    total_iters, epoch_iters, total_epochs = kind.totals(p)
    rec, created = store.submit(job, op, p, total_iters=total_iters,
                                epoch_iters=epoch_iters,
                                total_epochs=total_epochs)
    if created:
        metrics.counter("jobs.submitted").inc()
        record_event("job-submitted", job=rec["job"], op=op,
                     total_epochs=total_epochs)
    return rec, created


# -------------------------------------------------------------- executor

class JobExecutor:
    """Runs job epochs in the serving lane's idle gaps; see the module
    docstring for the scheduling and durability contract."""

    def __init__(self, store: JobStore, server=None, rank: str | None = None,
                 commit_retries: int = 3):
        self.store = store
        self.server = server          # serve.server.Server | None
        self.rank = str(rank if rank is not None
                        else os.environ.get("JAX_PROCESS_ID", "main"))
        self.commit_retries = commit_retries
        self.epochs_run = 0
        self._active: str | None = None
        self._ctx: dict[str, dict] = {}
        self._started_here: set[str] = set()
        self._preempted_here: set[str] = set()
        self._commit_failures: dict[str, int] = {}

    # ------------------------------------------------------- scheduling

    def preempt_reason(self) -> str | None:
        """Why a job epoch must NOT run right now: interactive work is
        queued, or the SLO monitor is burning.  Checked before every
        epoch — the preemption boundary is the epoch boundary."""
        server = self.server
        if server is None:
            return None
        if len(server.queue):
            return "queue-depth"
        slo = getattr(server, "slo", None)
        if slo is not None and getattr(slo, "burning", False):
            return "slo-burn"
        return None

    def _acquire(self) -> str | None:
        """The next runnable job this rank owns (claiming unowned ones);
        sorted record order keeps the scan deterministic."""
        for rec in self.store.list_jobs():
            if rec["state"] in TERMINAL:
                continue
            jid = rec["job"]
            own = self.store.owner(jid)
            if own is None:
                if not self.store.claim(jid, self.rank):
                    continue
            elif own != self.rank:
                continue
            return jid
        return None

    def tick(self) -> bool:
        """At most one job epoch (or one state transition); returns True
        when durable progress was made.  Never raises into the serving
        thread — an unexpected error fails the job instead."""
        jid = self._active
        if jid is not None:
            rec = self.store.load(jid)
            if rec is None or rec["state"] in TERMINAL:
                self._active = None
                jid = None
        if jid is None:
            jid = self._acquire()
            if jid is None:
                return False
            self._active = jid
        try:
            return self._tick_one(jid)
        except InjectedFault:
            # an injected ``ckpt:commit`` abort at a record publish: all
            # durable state (the epoch checkpoint, the prior record) is
            # intact — the write-ahead intent replays next tick and the
            # work rolls forward without re-execution.  Bounded: past
            # ``commit_retries`` failures the job FAILs (the chaos
            # ``ckpt-retry`` handicap sets 0 to drill that path).
            n = self._commit_failures.get(jid, 0) + 1
            self._commit_failures[jid] = n
            metrics.counter("jobs.commit_failures").inc()
            if n > self.commit_retries:
                rec = self.store.load(jid)
                if rec is not None and rec["state"] not in TERMINAL:
                    self._finish(rec, FAILED, reason="commit-failed")
                self._active = None
            return True
        except Exception as e:        # noqa: BLE001 — job lane boundary
            metrics.counter("jobs.errors").inc()
            rec = self.store.load(jid)
            if rec is not None and rec["state"] not in TERMINAL:
                self._finish(rec, FAILED,
                             reason=f"{type(e).__name__}: {str(e)[:200]}")
            self._active = None
            return True

    def _tick_one(self, jid: str) -> bool:
        rec = self.store.load(jid)
        if rec is None:
            self._active = None
            return False
        if self.store.cancel_requested(jid):
            if rec["state"] in TERMINAL:
                self._active = None
                return False
            self._finish(rec, FAILED, reason="cancelled")
            self._active = None
            return True
        reason = self.preempt_reason()
        if reason is not None:
            if rec["state"] == RUNNING and jid in self._started_here:
                rec["preemptions"] = int(rec.get("preemptions") or 0) + 1
                self.store.publish(rec, state=PREEMPTED,
                                   preemptions=rec["preemptions"])
                metrics.counter("jobs.preemptions").inc()
                record_event("job-preempted", job=jid, op=rec["op"],
                             epoch=rec["epoch"], reason=reason)
                self._preempted_here.add(jid)
            return False
        self._activate(rec)
        return self._run_epoch(rec)

    def _activate(self, rec: dict) -> None:
        """PENDING/PREEMPTED/orphaned-RUNNING → RUNNING, emitting
        ``job-resumed`` with how the work got here: ``preempted`` (this
        process paused it), ``restart`` (a PREEMPTED record from disk —
        the previous owner is gone), ``crash`` (a RUNNING record from
        disk — the previous owner died mid-job)."""
        jid = rec["job"]
        source = None
        if rec["state"] == PREEMPTED:
            source = ("preempted" if jid in self._preempted_here
                      else "restart")
        elif rec["state"] == RUNNING and jid not in self._started_here:
            source = "crash"
        if rec["state"] != RUNNING or source is not None:
            updates = {"state": RUNNING}
            if source is not None:
                rec["resumes"] = int(rec.get("resumes") or 0) + 1
                updates["resumes"] = rec["resumes"]
            self.store.publish(rec, **updates)
        if source is not None:
            metrics.counter("jobs.resumes").inc()
            record_event("job-resumed", job=jid, op=rec["op"],
                         epoch=rec["epoch"], source=source)
        self._preempted_here.discard(jid)
        self._started_here.add(jid)

    def _context(self, rec: dict) -> dict:
        jid = rec["job"]
        ctx = self._ctx.get(jid)
        if ctx is None:
            from .workloads import JOB_KINDS

            kind = JOB_KINDS[rec["op"]]
            state0, step_fn = kind.make(rec["params"])
            ctx = {"state0": state0, "step_fn": step_fn,
                   "tracker": kind.tracker(rec["params"], jid),
                   "finalize": getattr(kind, "finalize", np.asarray)}
            self._ctx[jid] = ctx
        return ctx

    def _run_epoch(self, rec: dict) -> bool:
        """One write-ahead epoch: intent → checkpointed chunk → record
        publish.  A pending intent from a crashed/injected-fault commit
        re-targets the SAME epoch — ``run_with_checkpoints`` resumes at
        the durable checkpoint's step, so a committed epoch's iterations
        are never executed twice."""
        from ..core.checkpoint import run_with_checkpoints

        jid = rec["job"]
        ctx = self._context(rec)
        if int(rec["iters"]) >= int(rec["total_iters"]):
            # every iteration is committed but a terminal publish was
            # lost (crash/injected commit abort between the last epoch
            # and DONE): finalize straight from the durable checkpoint
            state = run_with_checkpoints(
                ctx["step_fn"], ctx["state0"], int(rec["total_iters"]),
                self.store.checkpoint_path(jid),
                every=int(rec["epoch_iters"]), op=f"job.{rec['op']}",
                tracker=ctx["tracker"])
            value = ctx["finalize"](state)
            crc = self.store.save_result(jid, int(rec["iters"]), value)
            self._finish(rec, DONE, result_crc=int(crc))
            self._active = None
            return True
        intent = rec.get("intent")
        if intent is not None and intent.get("kind") == "epoch":
            # write-ahead replay: a crash (or injected commit abort)
            # landed between the epoch checkpoint and the record publish.
            # Re-target the SAME epoch — run_with_checkpoints resumes at
            # the checkpoint's step, so anything already durable is
            # rolled forward, not re-executed.
            epoch_no = int(intent["epoch"])
            target = int(intent["iters"])
            metrics.counter("jobs.intent_replays").inc()
        else:
            epoch_no = int(rec["epoch"]) + 1
            target = min(int(rec["iters"]) + int(rec["epoch_iters"]),
                         int(rec["total_iters"]))
            self.store.intent(rec, kind="epoch", epoch=epoch_no,
                              iters=target)
        from ..core.resilience import all_finite

        tracker = ctx["tracker"]
        state = run_with_checkpoints(
            ctx["step_fn"], ctx["state0"], target,
            self.store.checkpoint_path(jid), every=int(rec["epoch_iters"]),
            op=f"job.{rec['op']}", guard=all_finite, tracker=tracker)
        residual = tracker.last_residual
        self.store.publish(
            rec, state=RUNNING, epoch=epoch_no, iters=target,
            residual=(None if residual is None
                      else round(float(residual), 9)))
        self._commit_failures.pop(jid, None)
        self.epochs_run += 1
        metrics.counter("jobs.epochs").inc()
        record_event("job-epoch", job=jid, op=rec["op"], epoch=epoch_no,
                     residual=rec["residual"])
        tol = float(rec["params"].get("tol") or 0.0)
        converged = (tol > 0.0 and residual is not None
                     and float(residual) <= tol)
        if target >= int(rec["total_iters"]) or converged:
            value = ctx["finalize"](state)
            crc = self.store.save_result(jid, target, value)
            self._finish(rec, DONE, result_crc=int(crc))
            self._active = None
        elif tracker.stalled:
            self._finish(rec, STALLED, reason="convergence-stall")
            self._active = None
        return True

    def _finish(self, rec: dict, state: str, reason: str | None = None,
                result_crc: int | None = None) -> None:
        self.store.publish(rec, state=state, reason=reason,
                           result_crc=result_crc)
        metrics.counter(f"jobs.{state.lower()}").inc()
        record_event("job-done", job=rec["job"], op=rec["op"], state=state,
                     epochs=rec["epoch"])

    def stats(self) -> dict:
        counts: dict[str, int] = {}
        for rec in self.store.list_jobs():
            counts[rec["state"]] = counts.get(rec["state"], 0) + 1
        return {"active": self._active, "epochs_run": self.epochs_run,
                "states": counts}


# -------------------------------------------------------------- controls

def handle_control(store: JobStore, doc: dict) -> dict:
    """Serve one ``job-*`` control document against a store — shared by
    the replica transport (``serve/transport.py``) and the fleet front
    end (``serve/fleet.py``), both of which see the same directory."""
    from . import wire

    kind = doc.get("control")
    try:
        if kind == "job-submit":
            rec, created = submit_job(store, doc.get("job", ""),
                                      doc.get("op", "pagerank"),
                                      doc.get("params") or {})
            return {"ok": True, "created": created, "job": public(rec)}
        if kind == "job-status":
            rec = store.load(doc.get("job", ""))
            if rec is None:
                return {"ok": False, "error": "no such job"}
            out = public(rec)
            out["owner"] = store.owner(rec["job"])
            return {"ok": True, "job": out}
        if kind == "job-list":
            return {"ok": True,
                    "jobs": [public(r) for r in store.list_jobs()]}
        if kind == "job-cancel":
            if store.load(doc.get("job", "")) is None:
                return {"ok": False, "error": "no such job"}
            store.request_cancel(doc["job"])
            return {"ok": True}
        if kind == "job-result":
            rec = store.load(doc.get("job", ""))
            if rec is None:
                return {"ok": False, "error": "no such job"}
            if rec["state"] != DONE:
                return {"ok": False, "state": rec["state"],
                        "error": f"job is {rec['state']}, not DONE"}
            value = store.load_result(rec["job"])
            if value is None:
                return {"ok": False, "state": rec["state"],
                        "error": "result file missing/corrupt"}
            return {"ok": True, "job": public(rec),
                    "value": wire.nd_b64(value)}
    except JobError as e:
        return {"ok": False, "error": str(e)}
    return {"ok": False, "error": f"unknown job control {kind!r}"}
