#!/bin/bash
# One-shot TPU validation + measurement once the device tunnel is up:
#  1. compile/correctness smoke of every Pallas kernel (small shapes)
#  2. kernel-strategy sweep at the headline size -> CSV
#  3. headline bench line
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH=/root/.axon_site:$PWD
echo "=== smoke ==="
python scripts/tpu_smoke.py || exit 1
echo "=== kernel sweep ==="
python - <<'PY'
from cme213_tpu.bench.sweeps import heat_kernel_sweep, write_csv
rows = heat_kernel_sweep(size=4000, order=8, iters=64)
for r in rows:
    print(r)
write_csv(rows, "bench_results/heat_kernels_tpu.csv")
PY
echo "=== bench ==="
python bench.py
