"""Pallas stencil kernel vs the XLA path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat, stencil_interior
from cme213_tpu.ops.stencil_pallas import (
    pick_tile,
    run_heat_pallas,
    stencil_interior_pallas,
)

INTERPRET = jax.devices()[0].platform != "tpu"


@pytest.mark.parametrize("order", [2, 4, 8])
def test_single_step_matches_xla(order):
    p = SimParams(nx=32, ny=32, order=order)
    u = make_initial_grid(p) + 0.01 * jnp.arange(p.gy * p.gx, dtype=jnp.float32).reshape(p.gy, p.gx)
    ref = np.asarray(stencil_interior(u, order, p.xcfl, p.ycfl))
    out = np.asarray(stencil_interior_pallas(
        u, order, p.xcfl, p.ycfl, tile_y=pick_tile(p.ny, 16),
        interpret=INTERPRET))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_iterated_matches_xla():
    p = SimParams(nx=24, ny=24, order=4, iters=6)
    u0 = make_initial_grid(p)
    ref = np.asarray(run_heat(jnp.array(u0), 6, 4, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_pallas(jnp.array(u0), 6, 4, p.xcfl, p.ycfl,
                                     tile_y=pick_tile(p.ny, 8),
                                     interpret=INTERPRET))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("order", [2, 8])
@pytest.mark.parametrize("k", [2, 3])
def test_multistep_matches_xla(order, k):
    from cme213_tpu.ops.stencil_pallas import run_heat_multistep

    p = SimParams(nx=32, ny=32, order=order, iters=6)
    iters = 6
    u0 = make_initial_grid(p)
    ref = np.asarray(run_heat(jnp.array(u0), iters, order, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_multistep(
        jnp.array(u0), iters, order, p.xcfl, p.ycfl, p.bc, k=k,
        tile_y=8, interpret=INTERPRET))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_multistep_nonuniform_state():
    """Multi-step fusion on a non-trivial state (gradient interior)."""
    from cme213_tpu.ops.stencil_pallas import run_heat_multistep

    p = SimParams(nx=24, ny=48, order=4, iters=4)
    u0 = np.array(make_initial_grid(p))
    b = p.border_size
    rng = np.random.default_rng(3)
    u0[b:-b, b:-b] += rng.standard_normal((p.ny, p.nx)).astype(np.float32)
    ref = np.asarray(run_heat(jnp.array(u0), 4, 4, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_multistep(
        jnp.array(u0), 4, 4, p.xcfl, p.ycfl, p.bc, k=4, tile_y=12,
        interpret=INTERPRET))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_pick_tile():
    assert pick_tile(4000, 256) == 200  # 8-aligned divisor preferred
    assert pick_tile(256, 256) == 256
    assert pick_tile(4000, 450) == 400
    assert pick_tile(30, 16) == 15      # no 8-aligned divisor: fall back
    assert pick_tile(7, 16) == 7
