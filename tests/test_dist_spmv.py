import numpy as np
import pytest

from cme213_tpu.apps import spmv_scan as sp
from cme213_tpu.dist import make_mesh_1d
from cme213_tpu.verify import golden


@pytest.mark.parametrize("ndev", [2, 8])
def test_distributed_matches_single(ndev):
    prob = sp.generate_problem(1000, 40, 64, iters=6, seed=11)
    mesh = make_mesh_1d(ndev)
    out = sp.run_spmv_scan_distributed(prob, mesh)
    ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx, prob.iters)
    np.testing.assert_allclose(out, ref, atol=1e-2)


def test_distributed_with_padding():
    # n = 1000 doesn't divide 8 shards... actually 1000 % 8 == 0; use 999
    prob = sp.generate_problem(999, 30, 32, iters=4, seed=12)
    mesh = make_mesh_1d(8)
    out = sp.run_spmv_scan_distributed(prob, mesh)
    ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx, prob.iters)
    np.testing.assert_allclose(out, ref, atol=1e-2)
    assert out.shape == (999,)


def test_multihost_noop_and_info():
    from cme213_tpu.dist.multihost import initialize_multihost, process_info

    initialize_multihost(num_processes=1)  # single-process no-op
    pid, count = process_info()
    assert pid == 0 and count == 1


def test_cli_distributed(tmp_path, monkeypatch, capsys):
    from cme213_tpu.apps import spmv_scan as sp

    monkeypatch.chdir(tmp_path)
    assert sp.main(["spmv_scan", "gen", "a.txt", "x.txt",
                    "2048", "32", "31", "5"]) == 0
    assert sp.main(["spmv_scan", "a.txt", "x.txt", "cpu_check",
                    "--distributed"]) == 0
    out = capsys.readouterr().out
    assert "(8 devices)" in out
    assert "Worked!" in out
