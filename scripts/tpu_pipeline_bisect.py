"""Bisect the pipeline-kernel remote-compile failure by shape.

Round-3 observation: run_heat_pipeline compiles and runs on the real v5e
at 512²–2048² but the remote compile helper crashes (HTTP 500, subprocess
exit 1) at 4000² (W=4096), for every tile_y tried.  This harness compiles
ONE (nx, ny, tile_y, k) cell per child process (a crashed compile can
poison the device client, BENCH_r02-style) and prints a pass/fail matrix,
so the failing dimension (lane width vs rows vs tile) is identifiable.

Usage: python scripts/tpu_pipeline_bisect.py [--cells "nx,ny,tile,k;..."]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import json
import subprocess

DEFAULT_CELLS = [
    # (nx, ny, tile_y, k, tile_x)  — tile_x 0 = full-width 1-D pipeline
    # vary total width at fixed tile (W = ceil128(nx+8))
    (2040, 2040, 256, 1, 0),   # W=2048
    (2552, 2552, 256, 1, 0),   # W=2560
    (3064, 3064, 256, 1, 0),   # W=3072
    (3576, 3576, 256, 1, 0),   # W=3584
    (4000, 4000, 256, 1, 0),   # W=4096  <- known bad
    (4504, 4504, 256, 1, 0),   # W=4608  past the 4096 boundary
    # 4000-wide, vary rows (is it rows x cols area?)
    (4000, 1016, 256, 1, 0),
    (4000, 2040, 256, 1, 0),
    # 4000-wide, vary tile
    (4000, 4000, 64, 1, 0),
    (4000, 4000, 128, 1, 0),
    # temporal blocking at the bad width
    (4000, 4000, 256, 8, 0),
    # column-tiled variant at the bad width
    (4000, 4000, 256, 1, 512),
    (4000, 4000, 256, 8, 512),
    (4000, 4000, 256, 1, 1024),
]

_CHILD = "--child"


def run_cell(nx: int, ny: int, tile: int, k: int, tile_x: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops.stencil_pipeline import (run_heat_pipeline,
                                                 run_heat_pipeline2d)

    p = SimParams(nx=nx, ny=ny, order=8, iters=k)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    if tile_x:
        out = run_heat_pipeline2d(jax.device_put(u0), k, 8, p.xcfl, p.ycfl,
                                  p.bc, k=k, tile_y=tile, tile_x=tile_x,
                                  interpret=False)
    else:
        out = run_heat_pipeline(jax.device_put(u0), k, 8, p.xcfl, p.ycfl,
                                p.bc, k=k, tile_y=tile, interpret=False)
    jax.block_until_ready(out)
    print(json.dumps({"ok": True, "checksum": float(np.asarray(out).sum())}))


def main() -> int:
    if _CHILD in sys.argv:
        i = sys.argv.index(_CHILD)
        nx, ny, tile, k, tile_x = (int(v) for v in
                                   sys.argv[i + 1].split(","))
        run_cell(nx, ny, tile, k, tile_x)
        return 0

    cells = DEFAULT_CELLS
    for a in sys.argv[1:]:
        if a.startswith("--cells="):
            # 4-tuples (the pre-tile_x format) default tile_x to 0 = 1-D
            cells = [(tuple(int(v) for v in c.split(",")) + (0,))[:5]
                     for c in a.split("=", 1)[1].split(";") if c]
    for nx, ny, tile, k, tile_x in cells:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), _CHILD,
                 f"{nx},{ny},{tile},{k},{tile_x}"],
                timeout=600, capture_output=True, text=True)
            ok = proc.returncode == 0 and '"ok": true' in proc.stdout
            tail = "" if ok else (proc.stderr.strip().splitlines() or [""])[-1][:160]
        except subprocess.TimeoutExpired:
            # "device hang" matches capture_lib.sh's DEVICE_ERR signatures,
            # so the autocapture watcher re-runs a drop-poisoned matrix
            ok, tail = False, "timeout — device hang suspected"
        print(f"nx={nx} ny={ny} tile={tile} k={k} tile_x={tile_x}: "
              f"{'OK' if ok else 'FAIL ' + tail}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
