"""cme213_tpu — a TPU-native parallel-computing framework.

A brand-new JAX / XLA / Pallas / shard_map framework providing every capability
of the Stanford CME213 (Spring 2012) parallel-workload suite (see SURVEY.md):

- ``core``    — timers, ULP comparison, op-level error barriers (reference L0,
  ``hw/hw1/programming/mp1-util.h``).
- ``config``  — ``params.in``-compatible config with CFL/timestep derivation
  (reference L1, ``hw/hw2/programming/2dHeat.cu:90-228``).
- ``grid``    — functional halo-grid abstraction with Dirichlet BCs (reference
  L2, ``hw/hw2/programming/2dHeat.cu:230-348``).
- ``ops``     — device op layer: elementwise ciphers, stencils (XLA + Pallas),
  scans, segmented scans, histograms, sorts, CSR gather (reference L3).
- ``dist``    — the distributed backend: 1-D/2-D device meshes, shard_map halo
  exchange via ``lax.ppermute``, sync/overlapped stencil steps, multi-device
  segmented scan (reference hw5 MPI backend, ``hw/hw5/programming/2dHeat.cpp``).
- ``verify``  — golden host models + exact/ULP/L2-Linf checkers (reference L4).
- ``apps``    — workload drivers: cipher, pagerank, heat2d, vigenere, sorts,
  spmv_scan (reference L5).
- ``bench``   — sweep drivers emitting CSV (reference L7).
- ``native``  — host-native C++/OpenMP components (hw4 sorts).
"""

__version__ = "0.5.0"

# make JAX_PLATFORMS authoritative for every CLI/driver in this package
# (this environment's sitecustomize otherwise overrides it; a wedged TPU
# tunnel would then hang runs that explicitly asked for CPU)
from .core.platform import apply_platform_env as _apply_platform_env
from .core.platform import enable_compile_cache as _enable_compile_cache

_apply_platform_env()
# TPU compiles survive process restarts and tunnel windows (see
# core/platform.enable_compile_cache); explicit-CPU runs skip it
_enable_compile_cache()
