from .checkers import (
    check_exact,
    check_ulp,
    l2_distance,
    relative_linf_error,
    CheckResult,
)
from . import golden

__all__ = [
    "check_exact",
    "check_ulp",
    "l2_distance",
    "relative_linf_error",
    "CheckResult",
    "golden",
]
