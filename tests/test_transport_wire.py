"""v2 binary wire format: codec property tests and transport behavior.

The satellite contract for the zero-copy framing (``serve/wire.py``):
every (dtype x shape) combination — 0-d scalars, empty arrays,
F-contiguous and strided views, explicit big-endian dtypes — must
round-trip **bitwise** through the binary sections, length fields must
be 8-byte (>2 GiB-safe), and the document codecs must accept both the
v2 ``__sec__`` refs and the legacy v1 ``__nd__`` base64 triples.  On
top of the codec: pipelining (many in-flight per connection, responses
out of order), protocol negotiation (v1 clients against a v2 server,
counted by ``transport.proto_v1``), and the shared-memory lane with
its socket fallback.
"""

import socket
import threading

import numpy as np
import pytest

from cme213_tpu.core import metrics, trace
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.serve import OK, Server
from cme213_tpu.serve import wire
from cme213_tpu.serve.loadgen import build_mix
from cme213_tpu.serve.transport import (
    TransportClient,
    TransportServer,
    send_frame,
    recv_frame,
)
from cme213_tpu.serve.workloads import ADAPTERS


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    yield
    metrics.reset()


def _roundtrip_socket(arrays, meta=None):
    a, b = socket.socketpair()
    try:
        wire.send_buffers(a, wire.pack_frame(
            wire.FT_REQUEST, 42, meta or {}, arrays))
        first4 = wire.recv_exact(b, 4)
        return wire.read_frame_rest(b, first4)
    finally:
        a.close()
        b.close()


# ------------------------------------------------------------ sections

#: the fuzz matrix the 0-d/endianness satellite demands: every dtype
#: crossed with every shape, bitwise both ways
DTYPES = ("<f8", ">f8", "<f4", ">f4", "<i8", ">i4", "<u2", "|u1", "|b1",
          "<c16")
SHAPES = ((), (0,), (1,), (7,), (5, 3), (2, 0, 3), (2, 3, 4))


def _make(dtype, shape, seed):
    rng = np.random.default_rng(seed)
    n = int(np.prod(shape)) if shape else 1
    base = rng.integers(0, 100, size=max(n, 1))
    arr = base.astype(np.dtype(dtype))[:n].reshape(shape)
    return arr


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_section_roundtrip_bitwise_every_dtype_shape(dtype, shape):
    arr = _make(dtype, shape, seed=hash((dtype, shape)) % 2**16)
    ftype, rid, meta, sections = _roundtrip_socket([arr])
    assert (ftype, rid) == (wire.FT_REQUEST, 42)
    (back,) = sections
    assert back.dtype == arr.dtype          # byte order preserved
    assert back.shape == arr.shape          # incl. 0-d and empty dims
    assert back.tobytes() == arr.tobytes()


def test_section_roundtrip_noncontiguous_views():
    base = np.arange(48, dtype="<f8").reshape(6, 8)
    cases = [np.asfortranarray(base),        # F-contiguous
             base[::2, 1::3],                # strided view
             base.T]                         # transposed view
    ftype, _, _, sections = _roundtrip_socket(cases)
    for src, back in zip(cases, sections):
        assert back.shape == src.shape
        assert np.ascontiguousarray(src).tobytes() == back.tobytes()


def test_section_roundtrip_0d_keeps_0d():
    # the PR 15 edge: ascontiguousarray silently promotes () to (1,);
    # the binary layer must hand back a true 0-d
    for val in (np.float64(2.5), np.array(7, dtype=">i8")):
        _, _, _, (back,) = _roundtrip_socket([val])
        assert back.shape == ()
        assert back.tobytes() == np.asarray(val).tobytes()


def test_section_length_fields_are_2gib_safe():
    # descriptors carry nbytes as an unsigned 8-byte field and dims as
    # signed 8-byte ints: sizes past 2**31 survive the pack/unpack
    big = 5 * 2**31 + 13
    desc = wire._SECT.pack(3, 1, 0, big)
    dlen, ndim, flags, nbytes = wire._SECT.unpack(desc)
    assert nbytes == big
    assert wire._DIM.unpack(wire._DIM.pack(2**40))[0] == 2**40


def test_parse_frame_matches_socket_read():
    arrays = [np.arange(12, dtype="<i4").reshape(3, 4),
              np.array(1.5, dtype=">f8"), np.empty((0, 2), "<f4")]
    meta = {"op": "stub", "tenant": "t0", "nested": {"k": [1, 2.5]}}
    blob = wire.frame_bytes(wire.FT_RESPONSE, 7, meta, arrays)
    ftype, rid, m2, secs = wire.parse_frame(blob)
    assert (ftype, rid, m2) == (wire.FT_RESPONSE, 7, meta)
    for src, back in zip(arrays, secs):
        assert back.dtype == src.dtype and back.shape == src.shape
        assert back.tobytes() == src.tobytes()


def test_malformed_frames_raise_wire_error():
    good = bytearray(wire.frame_bytes(wire.FT_REQUEST, 1, {"op": "x"}))
    bad_magic = bytes([0xC3, 0x00]) + bytes(good[2:])
    with pytest.raises(wire.WireError, match="magic"):
        wire.parse_frame(bad_magic)
    bad_ver = bytearray(good)
    bad_ver[4] = 99
    with pytest.raises(wire.WireError, match="version"):
        wire.parse_frame(bytes(bad_ver))


# ------------------------------------------------------ document codecs

def test_decode_value_accepts_both_nd_and_sec():
    arr = np.arange(5, dtype="<f4")
    v1_doc = wire.encode_value(arr, wire.nd_b64)
    assert wire.decode_value(v1_doc).tobytes() == arr.tobytes()
    sw = wire.SectionWriter()
    v2_doc = wire.encode_value({"xs": [arr, 3]}, sw)
    got = wire.decode_value(v2_doc, sw.arrays)
    assert got["xs"][0].tobytes() == arr.tobytes() and got["xs"][1] == 3
    with pytest.raises(wire.WireError, match="__sec__"):
        wire.decode_value({"__sec__": 0})    # sectionless context


def test_v2_payload_roundtrip_every_op_bitwise():
    specs = build_mix("spmv,heat,cipher", 6, seed=3)
    for spec in specs:
        sw = wire.SectionWriter()
        doc = wire.encode_payload(spec.op, spec.payload, sw)
        back = wire.decode_payload(spec.op, doc, sw.arrays)
        if spec.op == "spmv_scan":
            for f in ("a", "s", "k", "x"):
                assert np.asarray(getattr(back, f)).tobytes() == \
                    np.ascontiguousarray(getattr(spec.payload, f)).tobytes()
        elif spec.op == "cipher":
            assert back.text.tobytes() == spec.payload.text.tobytes()
            assert back.shift == spec.payload.shift


def test_inline_sections_downgrades_sec_refs():
    arr = np.arange(4, dtype="<u2")
    sw = wire.SectionWriter()
    doc = {"value": wire.encode_value([arr], sw), "status": "ok"}
    flat = wire.inline_sections(doc, sw.arrays)
    assert "__nd__" in flat["value"]["__seq__"][0]
    assert wire.decode_value(flat["value"])[0].tobytes() == arr.tobytes()


# ------------------------------------------------------------ transport

def _cipher_server(**kw):
    server = Server(adapters=ADAPTERS, clock=VirtualClock(), max_batch=8)
    return TransportServer(server, drive="thread", **kw).start()


def test_pipelined_submits_resolve_out_of_order():
    ts = _cipher_server()
    try:
        specs = build_mix("cipher", 6, seed=9)
        with TransportClient(ts.addr) as c:
            assert c.proto == 2
            rids = [c.submit(s.op, s.payload) for s in specs]
            # resolve in reverse submission order on one connection
            results = {rid: c.result(rid) for rid in reversed(rids)}
        assert all(results[r].status == OK for r in rids)
        assert [results[r].rid for r in rids] == sorted(
            results[r].rid for r in rids)
        # client-side attribution rode along
        info = results[rids[0]].client
        assert info["encode_ms"] >= 0 and info["rtt_ms"] > 0
    finally:
        ts.close()


def test_v1_client_still_served_and_counted():
    ts = _cipher_server()
    try:
        spec = build_mix("cipher", 1, seed=4)[0]
        before = metrics.counter("transport.proto_v1").value
        with TransportClient(ts.addr, proto=1) as c:
            assert c.proto == 1
            res = c.solve(spec.op, spec.payload)
        assert res.status == OK
        assert metrics.counter("transport.proto_v1").value > before
        after_v1 = metrics.counter("transport.proto_v1").value
        # v2 clients leave the legacy counter alone
        with TransportClient(ts.addr) as c:
            assert c.solve(spec.op, spec.payload).status == OK
        assert metrics.counter("transport.proto_v1").value == after_v1
    finally:
        ts.close()


def test_hello_negotiation_reports_v2():
    ts = _cipher_server()
    try:
        with TransportClient(ts.addr) as c:
            pong = c.control("hello", proto=2)
            assert pong["ok"] and pong["proto"] == wire.VERSION
    finally:
        ts.close()


def test_codec_histograms_and_span_tags_populate():
    ts = _cipher_server()
    try:
        spec = build_mix("cipher", 1, seed=2)[0]
        with TransportClient(ts.addr) as c:
            assert c.solve(spec.op, spec.payload).status == OK
        snap = metrics.snapshot()["histograms"]
        assert snap["serve.request.decode_ms"]["count"] >= 1
        assert snap["serve.request.encode_ms"]["count"] >= 1
        names = {e["event"] for e in trace.events()}
        assert {"request-serialized", "request-deserialized"} <= names
    finally:
        ts.close()


def test_shm_lane_negotiates_and_serves_bitwise():
    ts = _cipher_server()
    try:
        specs = build_mix("cipher", 4, seed=13)
        with TransportClient(ts.addr, shm=True) as c:
            if not c.shm_active:
                pytest.skip("shared memory unavailable on this host")
            results = [c.solve(s.op, s.payload) for s in specs]
        assert all(r.status == OK for r in results)
        # same requests over plain sockets: bitwise-equal values
        with TransportClient(ts.addr) as c:
            refs = [c.solve(s.op, s.payload) for s in specs]
        for res, ref in zip(results, refs):
            assert np.asarray(res.value).tobytes() == \
                np.asarray(ref.value).tobytes()
    finally:
        ts.close()


def test_shm_oversized_frames_fall_back_to_socket():
    ts = _cipher_server()
    try:
        spec = build_mix("cipher", 1, seed=8)[0]
        with TransportClient(ts.addr, shm=True, shm_slots=2,
                             shm_slot_bytes=256) as c:
            if not c.shm_active:
                pytest.skip("shared memory unavailable on this host")
            res = c.solve(spec.op, spec.payload)   # payload > slot
            assert res.status == OK
            assert c._conn.lane.tx.fallbacks >= 1
    finally:
        ts.close()


def test_raw_v1_socket_frames_against_v2_server():
    # a hand-rolled legacy client: length-prefixed JSON, one in flight
    ts = _cipher_server()
    try:
        from cme213_tpu.serve.transport import encode_payload
        spec = build_mix("cipher", 1, seed=5)[0]
        host, port = ts.addr.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=10) as s:
            send_frame(s, {"control": "ping"})
            assert recv_frame(s)["ok"] is True
            send_frame(s, {"op": spec.op,
                           "payload": encode_payload(spec.op, spec.payload),
                           "tenant": "legacy"})
            resp = recv_frame(s)
        assert resp["status"] == OK and resp["tenant"] == "legacy"
    finally:
        ts.close()
