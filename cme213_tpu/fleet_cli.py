"""``python -m cme213_tpu fleet`` — run the replicated serving fleet.

Two subcommands:

- ``up``: spawn N replica worker processes, start the tenant-fair front
  tier (``serve/fleet.py``), print/write the listen address, and serve
  until ``--max-seconds`` elapses or the process is terminated.  Drive
  it with ``python -m cme213_tpu serve loadgen --transport <addr>``.
- ``worker``: one replica process — spawned by ``up``; not normally run
  by hand.

Example (two replicas, open-loop load, one replica killed mid-run by an
injected fault — zero accepted-request loss)::

    CME213_FAULTS="replica-kill:1:2" CME213_FLIGHT_DIR=/tmp/fl \\
        python -m cme213_tpu fleet up --replicas 2 --addr-file /tmp/addr &
    python -m cme213_tpu serve loadgen --transport "$(cat /tmp/addr)" \\
        --mode open --requests 48 --json
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _arm_graceful_shutdown() -> None:
    """Route SIGTERM — and SIGINT even when inherited as ignored — into
    KeyboardInterrupt.  Shells start backgrounded jobs (``fleet up ... &``,
    the CI idiom) with SIGINT set to SIG_IGN, in which case Python never
    installs its own handler and ``kill -INT`` would be a silent no-op:
    the fleet would only exit at ``--max-seconds``.  With the handlers
    armed, a plain ``kill`` tears the fleet down gracefully (stats
    printed, replicas terminated, sinks flushed)."""
    import signal

    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:  # not the main thread (embedded use)
        pass


def _up_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet up",
        description="run a replicated serving fleet behind one socket "
                    "front end")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--port", type=int, default=0,
                    help="front-end port (0 = ephemeral; see --addr-file)")
    ap.add_argument("--addr-file", default=None,
                    help="write the bound host:port here once listening")
    ap.add_argument("--capacity", type=int, default=64,
                    help="per-replica server queue capacity")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--mix", default="spmv,heat,cipher",
                    help="warmup mix each replica pre-compiles on start")
    ap.add_argument("--warm-requests", type=int, default=6)
    ap.add_argument("--dispatch-width", type=int, default=None,
                    help="concurrent sends per replica (default max-batch)")
    ap.add_argument("--max-seconds", type=float, default=300.0)
    ap.add_argument("--ready-timeout-s", type=float, default=180.0)
    ap.add_argument("--autoscale", action="store_true",
                    help="arm the SLO-burn autoscaler (needs --slo-p99-ms)")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="p99 objective feeding the autoscaler's burn "
                    "signal")
    ap.add_argument("--jobs-dir", default=None,
                    help="durable long-job directory shared with every "
                    "replica (arms the preemptible job lane)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the final fleet stats as JSON on exit")
    args = ap.parse_args(argv)

    from .core.resilience import Clock
    from .serve import slo as slo_mod
    from .serve.fleet import Fleet
    from .serve.router import Autoscaler

    clock = Clock()
    slo = None
    autoscaler = None
    if args.slo_p99_ms is not None:
        slo = slo_mod.from_flags(clock, p99_ms=args.slo_p99_ms,
                                 shed_rate=None, error_rate=None,
                                 drift_rate=None, short_s=5.0, long_s=60.0,
                                 burn_threshold=2.0, min_samples=10)
    if args.autoscale:
        if slo is None:
            print("fleet up: --autoscale needs --slo-p99-ms",
                  file=sys.stderr)
            return 2
        autoscaler = Autoscaler(clock=clock,
                                min_replicas=args.min_replicas,
                                max_replicas=args.max_replicas)

    fleet = Fleet(replicas=args.replicas, capacity=args.capacity,
                  max_batch=args.max_batch, mix=args.mix,
                  warm_requests=args.warm_requests,
                  dispatch_width=args.dispatch_width,
                  port=args.port, ready_timeout_s=args.ready_timeout_s,
                  slo=slo, autoscaler=autoscaler, clock=clock,
                  jobs_dir=args.jobs_dir)
    try:
        fleet.start()
    except TimeoutError as e:
        print(f"fleet up: {e}", file=sys.stderr)
        return 1
    # banner to stderr under --json so stdout stays one parseable doc
    print(f"fleet: listening on {fleet.addr} "
          f"({args.replicas} replica(s))", flush=True,
          file=sys.stderr if args.as_json else sys.stdout)
    if args.addr_file:
        with open(args.addr_file, "w") as f:
            f.write(fleet.addr)
    _arm_graceful_shutdown()
    try:
        deadline = time.monotonic() + args.max_seconds
        while time.monotonic() < deadline:
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        stats = fleet.stats()
        fleet.close()
    if args.as_json:
        print(json.dumps(stats, indent=2))
    else:
        print(f"fleet: done; {stats['requeues']} requeue(s), "
              f"scale +{stats['scale_ups']}/-{stats['scale_downs']}")
    return 0


def _jobs_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet jobs",
        description="submit/inspect durable long jobs on a running fleet "
                    "(or single replica) over its control channel")
    ap.add_argument("verb",
                    choices=("submit", "status", "list", "cancel",
                             "result", "wait"))
    ap.add_argument("--addr", required=True,
                    help="front-end (or replica) host:port")
    ap.add_argument("--job", default=None,
                    help="client-chosen job id (idempotency key)")
    ap.add_argument("--op", default="pagerank",
                    help="registered job kind (serve/workloads.JOB_KINDS)")
    ap.add_argument("--param", action="append", default=[],
                    metavar="K=V",
                    help="job parameter override, repeatable "
                    "(e.g. --param nodes=8192 --param iters=96)")
    ap.add_argument("--wait-s", type=float, default=120.0,
                    help="wait: give up after this many seconds")
    ap.add_argument("--out", default=None,
                    help="result: write the array here as .npy")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from .serve import wire
    from .serve.transport import TransportClient

    if args.verb != "list" and not args.job:
        print("fleet jobs: --job is required", file=sys.stderr)
        return 2
    params = {}
    for kv in args.param:
        k, sep, v = kv.partition("=")
        if not sep:
            print(f"fleet jobs: bad --param {kv!r} (want K=V)",
                  file=sys.stderr)
            return 2
        params[k] = v

    def show(doc: dict) -> None:
        if args.as_json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            job = doc.get("job")
            if isinstance(job, dict):
                print(f"job {job['job']}: {job['state']} "
                      f"epoch {job['epoch']}/{job['total_epochs']} "
                      f"iters {job['iters']}/{job['total_iters']} "
                      f"residual {job.get('residual')} "
                      f"resumes {job['resumes']} "
                      f"preemptions {job['preemptions']}")
            else:
                print(json.dumps(doc, sort_keys=True))

    with TransportClient(args.addr, timeout_s=30.0) as c:
        if args.verb == "submit":
            reply = c.control("job-submit", job=args.job, op=args.op,
                              params=params)
        elif args.verb == "status":
            reply = c.control("job-status", job=args.job)
        elif args.verb == "list":
            reply = c.control("job-list")
            if reply.get("ok") and not args.as_json:
                for job in reply.get("jobs", []):
                    print(f"{job['job']:24s} {job['op']:10s} "
                          f"{job['state']:10s} "
                          f"epoch {job['epoch']}/{job['total_epochs']}")
                return 0
        elif args.verb == "cancel":
            reply = c.control("job-cancel", job=args.job)
        elif args.verb == "wait":
            deadline = time.monotonic() + args.wait_s
            reply = {"ok": False, "error": "wait timeout"}
            while time.monotonic() < deadline:
                reply = c.control("job-status", job=args.job)
                state = (reply.get("job") or {}).get("state")
                if state in ("DONE", "FAILED", "STALLED"):
                    break
                time.sleep(0.25)
            else:
                show(reply)
                return 1
        else:  # result
            reply = c.control("job-result", job=args.job)
            if reply.get("ok"):
                value = wire.nd_b64_decode(reply.pop("value"))
                if args.out:
                    import numpy as np

                    np.save(args.out, value)
                    reply["saved"] = args.out
                reply["shape"] = list(value.shape)
                reply["dtype"] = str(value.dtype)
    show(reply)
    if not reply.get("ok"):
        return 1
    if args.verb == "wait":
        return 0 if (reply.get("job") or {}).get("state") == "DONE" else 1
    return 0


def main(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m cme213_tpu fleet <up|worker|jobs> "
              "[args...]\n\n"
              "subcommands:\n"
              "  up      spawn N supervised server replicas behind a "
              "tenant-fair socket front end\n"
              "  worker  one replica process (spawned by `up`)\n"
              "  jobs    submit/inspect durable long jobs on a running "
              "fleet (submit|status|list|cancel|result|wait)")
        return 0 if argv else 2
    if argv[0] == "up":
        return _up_main(argv[1:])
    if argv[0] == "worker":
        from .serve.fleet import worker_main

        return worker_main(argv[1:])
    if argv[0] == "jobs":
        return _jobs_main(argv[1:])
    print(f"fleet: unknown subcommand {argv[0]!r} (try up | worker | jobs)",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
