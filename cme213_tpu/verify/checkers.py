"""Result checkers — the reference's dual-implementation testing model.

Tolerance hierarchy preserved from the reference (SURVEY §4):
- exact byte/int equality (cipher ``checkResults`` ``hw/hw1/programming/
  cipher.cu:94-125``; sort asserts ``hw/hw4/programming/radixsort.cpp:196-211``)
- ULP-10 for per-element float stencils (``hw/hw2/programming/2dHeat.cu:
  651-671``, ``pagerank.cu:216-235``)
- absolute tolerance for accumulating float pipelines (1e-2,
  ``hw/hw_final/programming/fp.cu:193-206``)
- L2 / relative-L∞ for the double-precision external checker
  (``hw/hw_final/programming/aux/reference_spMVscan-released.cu:38-54``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.compare import almost_equal_ulps


@dataclass
class CheckResult:
    ok: bool
    message: str
    num_bad: int = 0

    def __bool__(self) -> bool:
        return self.ok


def check_exact(expected, got, label: str = "") -> CheckResult:
    """Elementwise exact equality; reports the first mismatch position like
    the reference's ``checkResults`` ("Error at pos: ...")."""
    expected = np.asarray(expected)
    got = np.asarray(got)
    if expected.shape != got.shape:
        return CheckResult(False, f"{label}: shape {expected.shape} vs {got.shape}")
    bad = expected != got
    if bad.any():
        pos = np.unravel_index(int(np.argmax(bad)), bad.shape)
        return CheckResult(
            False,
            f"{label}: Error at pos: {pos} expected: {expected[pos]} got: {got[pos]}",
            int(bad.sum()),
        )
    return CheckResult(True, f"{label}: exact match")


def check_ulp(expected, got, max_ulps: int = 10, label: str = "") -> CheckResult:
    """Per-element ULP-distance equality (maxUlps=10 default, as the
    reference's ``checkErrors``)."""
    expected = np.asarray(expected)
    got = np.asarray(got)
    if expected.shape != got.shape:
        return CheckResult(False, f"{label}: shape {expected.shape} vs {got.shape}")
    ok = almost_equal_ulps(expected, got, max_ulps)
    nbad = int((~ok).sum())
    if nbad:
        pos = np.unravel_index(int(np.argmax(~ok)), ok.shape)
        return CheckResult(
            False,
            f"{label}: {nbad} mismatches; first at {pos}: "
            f"expected {expected[pos]!r} got {got[pos]!r}",
            nbad,
        )
    return CheckResult(True, f"{label}: ULP-{max_ulps} match")


def check_abs_tol(expected, got, tol: float = 1e-2, label: str = "") -> CheckResult:
    """Absolute-difference tolerance (hw_final fp.cu:193-206 style)."""
    expected = np.asarray(expected, dtype=np.float64)
    got = np.asarray(got, dtype=np.float64)
    bad = np.abs(expected - got) > tol
    nbad = int(bad.sum())
    if nbad:
        pos = np.unravel_index(int(np.argmax(bad)), bad.shape)
        return CheckResult(
            False,
            f"{label}: {nbad} elements exceed |diff|>{tol}; first at {pos}: "
            f"expected {expected[pos]} got {got[pos]}",
            nbad,
        )
    return CheckResult(True, f"{label}: within abs tol {tol}")


def l2_distance(a, b) -> float:
    """Absolute L2 distance (reference ``L2Distance``,
    ``aux/reference_spMVscan-released.cu``)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    return float(np.sqrt(np.sum((a - b) ** 2)))


def relative_l2_error(a, b) -> float:
    denom = float(np.sqrt(np.sum(np.asarray(a, np.float64) ** 2)))
    return l2_distance(a, b) / denom if denom else l2_distance(a, b)


def relative_linf_error(a, b) -> float:
    """Relative L∞ error (reference ``relativeLInfError``)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    denom = np.max(np.abs(a))
    num = np.max(np.abs(a - b))
    return float(num / denom) if denom else float(num)
