"""Irregular gather ops — CSR neighbor propagation (strategy P4).

TPU-native redesign of the reference PageRank kernel (one thread per
destination walking its CSR row, ``hw/hw1/programming/pagerank.cu:70-83``):
the row loop becomes a flat edge-parallel gather + ``segment_sum`` back to
rows — regular, vectorizable, and XLA-fusable, instead of data-dependent
per-thread loops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def csr_row_ids(indices: jnp.ndarray, num_edges: int) -> jnp.ndarray:
    """Destination-row id for each CSR edge slot (precomputed once per graph,
    like the reference's device graph upload)."""
    return (
        jnp.searchsorted(
            indices, jnp.arange(num_edges, dtype=indices.dtype), side="right"
        ).astype(jnp.int32)
        - 1
    )


@partial(jax.jit, static_argnames=("num_nodes",))
def pagerank_propagate(row_ids: jnp.ndarray, edges: jnp.ndarray,
                       rank_in: jnp.ndarray, inv_deg: jnp.ndarray,
                       num_nodes: int) -> jnp.ndarray:
    """One sweep: ``out[i] = 0.5/n + 0.5 · Σ_{j∈row i} rank[e_j]·inv_deg[e_j]``
    (pagerank.cu:45-56 math, edge-parallel form).

    Precondition: ``row_ids`` must be non-decreasing (as produced by
    ``csr_row_ids``) — the sorted segment reduction is undefined for
    unsorted ids."""
    contrib = rank_in[edges] * inv_deg[edges]
    # CSR edge order makes row_ids non-decreasing; telling XLA lets the
    # TPU backend lower a sorted segment reduction instead of a general
    # scatter-add over 16M edges
    sums = jax.ops.segment_sum(contrib, row_ids, num_segments=num_nodes,
                               indices_are_sorted=True)
    half = jnp.float32(0.5)
    return half / jnp.float32(num_nodes) + half * sums


@partial(jax.jit, static_argnames=("num_nodes", "nr_iterations"))
def pagerank_iterate(row_ids, edges, rank0, inv_deg, num_nodes: int,
                     nr_iterations: int):
    """Even-iteration ping-pong loop (pagerank.cu:59-67) as ``fori_loop``."""
    assert nr_iterations % 2 == 0

    def body(_, r):
        return pagerank_propagate(row_ids, edges, r, inv_deg, num_nodes)

    return jax.lax.fori_loop(0, nr_iterations, body, rank0)
