"""Pallas VMEM-tiled heat stencil — the hand-tuned kernel path.

TPU-native analog of the reference's shared-memory stencil kernel
(``gpuShared``, ``hw/hw2/programming/2dHeat.cu:466-515``): where 128×4 CUDA
threads cooperatively staged a 128×32 halo tile into ``__shared__`` and each
thread emitted multiple rows, here each Pallas grid step DMAs a
``(tile_y + 2·border, gx)`` row band from HBM into a VMEM scratch buffer
(the explicit analog of the cooperative staging), then computes a
``(tile_y, nx)`` output tile with the same shifted-slice expression as the
XLA path (`ops/stencil.py`) — so results are bitwise comparable.

The pure-XLA path usually reaches the HBM roofline on TPU because XLA fuses
the whole stencil into one pass; this kernel exists as (a) the explicit
VMEM-tiling parity artifact for strategy P3, and (b) a base to hand-tune
(e.g. fusing the iteration loop or double-buffering the band DMA).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import BORDER_FOR_ORDER, STENCIL_COEFFS


def _make_kernel(order: int, tile_y: int, gx: int, xcfl: float, ycfl: float):
    b = BORDER_FOR_ORDER[order]
    coeffs = STENCIL_COEFFS[order]
    nx = gx - 2 * b

    def kernel(u_hbm, out_ref, band, sem):
        i = pl.program_id(0)
        # cooperative tile staging: DMA the row band (+halo) into VMEM
        dma = pltpu.make_async_copy(
            u_hbm.at[pl.ds(i * tile_y, tile_y + 2 * b), :], band, sem)
        dma.start()
        dma.wait()
        u = band[:]
        dtype = u.dtype
        center = u[b:b + tile_y, b:b + nx]
        accx = jnp.zeros_like(center)
        accy = jnp.zeros_like(center)
        for k, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * u[b:b + tile_y, k:k + nx]
            accy = accy + c * u[k:k + tile_y, b:b + nx]
        out_ref[:] = (center + jnp.asarray(xcfl, dtype) * accx
                      + jnp.asarray(ycfl, dtype) * accy)

    return kernel


@partial(jax.jit,
         static_argnames=("order", "xcfl", "ycfl", "tile_y", "interpret"))
def stencil_interior_pallas(u: jnp.ndarray, order: int, xcfl: float,
                            ycfl: float, tile_y: int = 256,
                            interpret: bool = False) -> jnp.ndarray:
    """New interior (ny, nx) from halo grid (gy, gx), VMEM-tiled.

    ``ny`` must divide by ``tile_y`` (drivers pick a divisor; see
    ``pick_tile``).  ``xcfl``/``ycfl`` must be concrete floats (they are
    baked into the kernel as constants).
    """
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    assert ny % tile_y == 0, "ny must divide by tile_y"
    kernel = _make_kernel(order, tile_y, gx, float(xcfl), float(ycfl))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((ny, nx), u.dtype),
        grid=(ny // tile_y,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile_y, nx), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((tile_y + 2 * b, gx), u.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(u)


def pick_tile(ny: int, target: int = 256) -> int:
    """Largest divisor of ny not exceeding ``target``."""
    t = min(target, ny)
    while ny % t:
        t -= 1
    return t


@partial(jax.jit,
         static_argnames=("order", "iters", "xcfl", "ycfl", "tile_y",
                          "interpret"),
         donate_argnums=(0,))
def run_heat_pallas(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                    tile_y: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Iterated solve using the Pallas stencil (functional ping-pong)."""
    b = BORDER_FOR_ORDER[order]

    def body(_, g):
        new = stencil_interior_pallas(g, order, xcfl, ycfl, tile_y=tile_y,
                                      interpret=interpret)
        return g.at[b:-b, b:-b].set(new)

    return lax.fori_loop(0, iters, body, u)
