import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time
import jax, jax.numpy as jnp, numpy as np
from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops.stencil_pallas import run_heat_pallas

n = int(sys.argv[1]); t = int(sys.argv[2])
p = SimParams(nx=n, ny=n, order=8, iters=1000)
u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
t0 = time.perf_counter()
jax.block_until_ready(run_heat_pallas(jax.device_put(u0), 1, p.order, p.xcfl, p.ycfl, tile_y=t))
print(f"n={n} t={t} compile+1it: {time.perf_counter()-t0:.1f}s", flush=True)
for it in (1, 8):
    u = jax.device_put(u0)
    t0 = time.perf_counter()
    jax.block_until_ready(run_heat_pallas(u, it, p.order, p.xcfl, p.ycfl, tile_y=t))
    dt = time.perf_counter() - t0
    print(f"  iters={it}: {dt*1e3:.1f} ms total, {dt/it*1e3:.2f} ms/iter", flush=True)
