"""Multi-host startup layer (strategy P12) — exercised for real.

The reference's distributed entry point is ``mpirun -np N`` under
Torque/PBS (``hw/hw5/PA5_Handout.pdf`` §4).  These tests exercise the JAX
analog beyond a no-op: env-var parsing (launcher-provided rank/world like
MPI), and a genuine 2-process run on the CPU backend — two subprocesses
join a localhost coordinator via ``jax.distributed.initialize``, build a
global 2-process × 2-device mesh, and run a ``psum`` across all 4 devices
(the MPI_Allreduce-over-two-ranks smoke test).
"""

import socket
import subprocess
import sys
import textwrap

import pytest

from cme213_tpu.dist.multihost import (MULTIPROCESS_UNSUPPORTED_MSG,
                                       multiprocess_unsupported)


def _gate_multiprocess_capability(output: str) -> None:
    """Explicit-skip a run that died on this jaxlib's missing multiprocess-
    CPU capability (the probed error string is exact); anything else falls
    through to the test's own hard assertions."""
    if multiprocess_unsupported(output):
        pytest.skip(f"backend capability: {MULTIPROCESS_UNSUPPORTED_MSG} "
                    f"(this jaxlib); cross-process collectives need a real "
                    f"multi-host backend")


def test_env_parsing_defaults(monkeypatch):
    """Launcher env vars are the argument source, like MPI ranks."""
    from cme213_tpu.dist import multihost

    captured = {}

    class FakeDistributed:
        @staticmethod
        def initialize(coordinator_address=None, num_processes=None,
                       process_id=None):
            captured.update(addr=coordinator_address, n=num_processes,
                            pid=process_id)

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    monkeypatch.setattr("jax.distributed", FakeDistributed)
    multihost.initialize_multihost()
    assert captured == {"addr": "10.0.0.1:1234", "n": 4, "pid": 3}


def test_env_parsing_single_process_noop(monkeypatch):
    from cme213_tpu.dist import multihost

    def boom(**kwargs):
        raise AssertionError("initialize must not be called for 1 process")

    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    monkeypatch.setattr("jax.distributed.initialize", boom)
    multihost.initialize_multihost()  # no-op


def test_explicit_args_override_env(monkeypatch):
    from cme213_tpu.dist import multihost

    captured = {}
    monkeypatch.setenv("JAX_NUM_PROCESSES", "8")
    monkeypatch.setenv("JAX_PROCESS_ID", "7")
    monkeypatch.setattr(
        "jax.distributed.initialize",
        lambda coordinator_address=None, num_processes=None, process_id=None:
        captured.update(addr=coordinator_address, n=num_processes,
                        pid=process_id))
    multihost.initialize_multihost("127.0.0.1:9", num_processes=2,
                                   process_id=1)
    assert captured == {"addr": "127.0.0.1:9", "n": 2, "pid": 1}


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    from cme213_tpu.core.platform import force_cpu_devices
    # 2 local CPU devices per process BEFORE the distributed client forms
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from cme213_tpu.dist.multihost import initialize_multihost, process_info

    initialize_multihost()  # everything from the env, like an MPI launcher
    pid, count = process_info()
    assert count == 2, f"process_count={{count}}"

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = jax.devices()           # global: 2 processes x 2 devices
    assert len(devs) == 4, f"global devices={{len(devs)}}"
    mesh = Mesh(devs, ("d",))

    @jax.jit
    def allreduce():
        def body():
            return jax.lax.psum(jnp.float32(jax.lax.axis_index("d") + 1),
                                "d")
        return shard_map(body, mesh=mesh, in_specs=(), out_specs=P())()

    total = float(allreduce()[0] if allreduce().ndim else allreduce())
    assert total == 10.0, f"psum={{total}}"   # 1+2+3+4 over 4 devices
    print(f"rank {{pid}}/{{count}} OK psum={{total}}")
""")


def test_two_process_cpu_backend(tmp_path):
    """Two real processes, localhost coordinator, global mesh, cross-process
    psum — the 'compare against a single-rank run' methodology needs the
    runtime to actually form, which a no-op call never showed."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo))
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid))
        env.pop("JAX_PLATFORMS", None)  # worker forces cpu itself
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=150)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed CPU runtime unavailable in this sandbox "
                    "(coordinator handshake timed out); run manually with "
                    "JAX_COORDINATOR_ADDRESS=127.0.0.1:<port> "
                    "JAX_NUM_PROCESSES=2 JAX_PROCESS_ID={0,1}")
    if any(rc != 0 for rc, _, _ in outs):
        _gate_multiprocess_capability(
            "".join(out + err for _, out, err in outs))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed: {err[-2000:]}"
        assert "OK psum=10.0" in out


_LAUNCH_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import jax
    from cme213_tpu.dist.multihost import initialize_multihost, process_info

    initialize_multihost()
    pid, count = process_info()
    import jax.numpy as jnp
    total = float(jnp.ones(len(jax.devices())).sum())
    print(f"rank {{pid}}/{{count}} devices={{len(jax.devices())}} "
          f"sum={{total}}")
""")



def _run_launcher(tmp_path, worker_src: str, devices_per_proc: int | None,
                  np_procs: int = 2) -> int:
    """Shared launcher-test boilerplate: write the worker, clear the
    JAX_PLATFORMS override (workers pick their own platform), launch."""
    import os

    from cme213_tpu.dist.launch import launch

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(worker_src.format(repo=repo))
    env_backup = os.environ.pop("JAX_PLATFORMS", None)
    try:
        return launch(np_procs, [sys.executable, str(script)],
                      devices_per_proc=devices_per_proc)
    finally:
        if env_backup is not None:
            os.environ["JAX_PLATFORMS"] = env_backup


def test_launcher_two_ranks(tmp_path):
    """The mpirun-analog launcher: 2 ranks x 2 fake devices, rank-tagged
    output, zero exit."""
    assert _run_launcher(tmp_path, _LAUNCH_WORKER, devices_per_proc=2) == 0


def test_launcher_fail_fast(tmp_path):
    from cme213_tpu.dist.launch import launch

    script = tmp_path / "bad.py"
    script.write_text("import sys, os\n"
                      "sys.exit(3 if os.environ['JAX_PROCESS_ID'] == '0' "
                      "else 0)\n")
    rc = launch(2, [sys.executable, str(script)])
    assert rc == 3


def test_launcher_cli_requires_command(capsys):
    from cme213_tpu.dist.launch import main

    with pytest.raises(SystemExit):
        main(["--np", "2", "--"])


_SCAN_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax
    from cme213_tpu.dist.multihost import initialize_multihost, process_info

    initialize_multihost()
    import jax.numpy as jnp
    from cme213_tpu.dist import make_mesh_1d, distributed_segmented_scan
    from cme213_tpu.ops.segmented import head_flags_from_starts
    from cme213_tpu.verify.golden import host_segmented_scan

    pid, count = process_info()
    devs = jax.devices()
    assert len(devs) == 8, f"global devices={{len(devs)}}"
    mesh = make_mesh_1d(8)

    n = 128
    rng = np.random.default_rng(7)
    vals = rng.standard_normal(n).astype(np.float32)
    starts = np.array([0, 10, 50, 90], np.int32)
    flags = head_flags_from_starts(jnp.asarray(starts), n)
    out = distributed_segmented_scan(jnp.asarray(vals), flags, mesh)
    expected = host_segmented_scan(vals, starts)
    for shard in out.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data),
                                   expected[shard.index], rtol=1e-5)
    print(f"rank {{pid}}/{{count}} scan OK over", len(devs), "devices")
""")


def test_launcher_distributed_scan_two_ranks(tmp_path, capsys):
    """The long-context path (sharded segmented scan, ring carries) across
    two REAL processes: collectives ride the cross-process runtime, each
    rank checks its addressable shards against the host golden."""
    rc = _run_launcher(tmp_path, _SCAN_WORKER, devices_per_proc=4)
    if rc != 0:
        _gate_multiprocess_capability(capsys.readouterr().out)
    assert rc == 0


_HEAT_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax
    from cme213_tpu.dist.multihost import initialize_multihost, process_info

    initialize_multihost()
    import jax.numpy as jnp
    from cme213_tpu.config import SimParams
    from cme213_tpu.dist import make_mesh_1d
    from cme213_tpu.dist.heat import prepare_distributed_heat
    from cme213_tpu.grid import make_initial_grid, interior
    from cme213_tpu.ops import run_heat

    pid, count = process_info()
    assert len(jax.devices()) == 8
    mesh = make_mesh_1d(8)
    params = SimParams(nx=64, ny=64, order=8, iters=4)

    iterate, overlap_used, k_used = prepare_distributed_heat(params, mesh)
    secs, out = iterate()

    u0 = np.asarray(make_initial_grid(params, dtype=jnp.float32))
    ref_full = np.asarray(run_heat(jnp.array(u0), 4, 8, params.xcfl,
                                   params.ycfl))
    ref = np.asarray(interior(ref_full, params.border_size))
    for shard in out.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data),
                                      ref[shard.index])
    print(f"rank {{pid}}/{{count}} halo-exchange OK ({{secs:.3f}}s)")
""")


def test_launcher_distributed_heat_two_ranks(tmp_path, capsys):
    """The hw5 backbone — ppermute halo exchange + sharded stencil — across
    two REAL processes, shard-checked bitwise against the single-device
    solve (the reference's N-rank-vs-1-rank methodology, for real)."""
    rc = _run_launcher(tmp_path, _HEAT_WORKER, devices_per_proc=4)
    if rc != 0:
        _gate_multiprocess_capability(capsys.readouterr().out)
    assert rc == 0
