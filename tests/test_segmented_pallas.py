import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.ops.segmented import head_flags_from_starts
from cme213_tpu.ops.segmented_pallas import segmented_scan_pallas
from cme213_tpu.verify import golden

INTERPRET = jax.devices()[0].platform != "tpu"


def _case(rng, n, p):
    starts = np.sort(rng.choice(np.arange(1, n), size=p - 1, replace=False))
    s = np.concatenate([[0], starts]).astype(np.int32)
    v = rng.standard_normal(n).astype(np.float32)
    return v, s


@pytest.mark.parametrize("n,p,rows", [
    (128 * 8, 10, 8),        # exactly one tile
    (128 * 8 * 3, 50, 8),    # multiple tiles
    (5000, 37, 8),           # padding required
    (128 * 64, 200, 64),     # bigger tile rows
])
def test_matches_golden(n, p, rows):
    rng = np.random.default_rng(n + p)
    v, s = _case(rng, n, p)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(segmented_scan_pallas(jnp.asarray(v), flags, rows=rows,
                                           interpret=INTERPRET))
    ref = golden.host_segmented_scan(v, s)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_single_long_segment_crosses_tiles():
    n = 128 * 8 * 4
    v = np.ones(n, dtype=np.float32)
    flags = head_flags_from_starts(jnp.asarray([0], dtype=jnp.int32), n)
    out = np.asarray(segmented_scan_pallas(jnp.asarray(v), flags, rows=8,
                                           interpret=INTERPRET))
    np.testing.assert_allclose(out, np.arange(1, n + 1, dtype=np.float32),
                               rtol=1e-5)


def test_heads_at_tile_boundaries():
    rows = 8
    block = rows * 128
    n = block * 3
    v = np.ones(n, dtype=np.float32)
    s = np.array([0, block, 2 * block + 1], dtype=np.int32)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(segmented_scan_pallas(jnp.asarray(v), flags, rows=rows,
                                           interpret=INTERPRET))
    ref = golden.host_segmented_scan(v, s)
    np.testing.assert_allclose(out, ref)


def test_spmv_scan_pallas_engine():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(3000, 80, 64, iters=5, seed=21)
    out = sp.run_spmv_scan(prob, kernel="pallas")
    ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx, prob.iters)
    np.testing.assert_allclose(out, ref, atol=1e-2)


def test_every_element_own_segment():
    n = 1000
    rng = np.random.default_rng(0)
    v = rng.standard_normal(n).astype(np.float32)
    flags = jnp.ones(n, jnp.int32)
    out = np.asarray(segmented_scan_pallas(jnp.asarray(v), flags,
                                           interpret=INTERPRET))
    np.testing.assert_allclose(out, v, rtol=1e-6)
