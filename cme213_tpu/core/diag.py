"""Device-health doctor, staged kernel forensics, and cost attribution.

The reference enforces a brutal but effective diagnostic discipline:
``checkCudaErrors`` around every API call and ``cudaGetLastError`` after
every launch (``hw/hw1/programming/mp1-util.h:8-18``), so a failure is
always pinned to the exact call that caused it.  The JAX/TPU stack has no
equivalent — an async XLA error surfaces wherever the value is first
blocked on, a Mosaic lowering failure and a runtime crash look the same
from a bench row, and a dead device yields nothing but a hung
``block_until_ready``.  Five capture rounds died that way (BENCH_r02's
opaque Pallas failures; r03–r05's "preflight: device unreachable" with
nothing to debug).  This module is the missing layer, in three pillars:

- **Device health** (:func:`health_report`): a staged probe ladder —
  platform/device enumeration, a ``memory_stats()`` snapshot, a timed
  micro-kernel liveness check — where every stage runs under a watchdog
  timeout so a hung runtime yields a *report* saying which stage hung,
  never a hung doctor.  Reports emit a schema-registered
  ``device-health`` event, set ``diag.device.*`` gauges (picked up by
  ``metrics.render_prometheus`` like any other gauge), and append to a
  persistent JSONL history ring under ``CME213_DIAG_DIR`` so device decay
  is visible across runs and restarts.

- **Staged forensics** (:func:`stage_scope` / :func:`failure_stage`):
  dispatch wraps each phase of a rung's life — ``lower`` (build),
  ``compile`` (warm), ``execute``, ``conformance`` — and any exception is
  tagged with the stage it escaped from (an attribute on the exception,
  because contextvars unwind before the ladder's handler runs).
  ``with_fallback`` carries the tag onto ``kernel-failure`` events, so
  "Pallas rung failed" becomes "failed at lowering with Mosaic error X".
  :func:`forensics_state` exposes the open/last-failed stage for the
  flight recorder.

- **Predicted-vs-measured attribution** (:func:`check_attribution`):
  cross-checks ``compiled.cost_analysis()`` flops/bytes against the
  ``core/roofline.py`` model a bench row will be graded with, emitting
  ``attribution-mismatch`` beyond a tolerance (``CME213_DIAG_TOL``,
  default ratio 2.0) — the guard that keeps published ``pct_peak``
  numbers honest.  Dispatch-time checks are opt-in
  (``CME213_DIAG_ATTRIBUTION=1``) because lowering twice is not free;
  ``doctor calibrate`` always runs them.

CLI: ``python -m cme213_tpu doctor [--json]`` and ``doctor calibrate``
(``doctor_cli.py``).  This module imports only stdlib + sibling leaf
modules (``metrics``, ``trace``, lazily ``faults``/``platform``/jax), so
the resilience and program-cache layers can import it without cycles.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

#: directory for the persistent health-history ring (unset = no ring)
DIAG_DIR_ENV = "CME213_DIAG_DIR"
#: truthy = run predicted-vs-measured checks at program-cache build time
ATTRIBUTION_ENV = "CME213_DIAG_ATTRIBUTION"
#: mismatch tolerance as a measured/predicted ratio (default 2.0)
TOLERANCE_ENV = "CME213_DIAG_TOL"
#: per-stage watchdog budget for health probes, seconds
TIMEOUT_ENV = "CME213_DOCTOR_TIMEOUT_S"

RING_NAME = "health-ring.jsonl"
RING_CAP = 256

#: the dispatch stages forensics attributes failures to, in ladder order
STAGES = ("lower", "compile", "execute", "conformance")

#: attribute carried on exceptions (contextvars unwind before the
#: ladder's handler runs, so the tag must travel WITH the exception)
STAGE_ATTR = "_cme213_stage"

_LOCK = threading.Lock()
_LAST_HEALTH: dict | None = None
_OPEN_STAGE: dict | None = None
_LAST_FAILED_STAGE: dict | None = None
_ATTRIBUTION: list = []

# message fragments that identify the earlier stages when an exception
# carries no explicit tag (same family as resilience._COMPILE_MARKERS,
# split by stage: Mosaic/MLIR noise means lowering died; vmem exhaustion
# and generic compile errors mean codegen died)
_LOWER_MARKERS = ("mosaic", "mlir", "lowering", "unsupported",
                  "unimplemented")
_COMPILE_MARKERS = ("compil", "vmem")


# --------------------------------------------------------- staged forensics

def mark_stage(exc: BaseException, stage: str) -> BaseException:
    """Tag ``exc`` with the dispatch stage it escaped from (first tag
    wins — the innermost scope knows best)."""
    if getattr(exc, STAGE_ATTR, None) is None:
        try:
            setattr(exc, STAGE_ATTR, stage)
        except Exception:  # noqa: BLE001 — slotted exceptions: heuristics
            pass           # in failure_stage still apply
    return exc


def _tagged_stage(exc: BaseException) -> str | None:
    """Explicit stage tag on ``exc`` or anything in its cause chain."""
    seen = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        s = getattr(cur, STAGE_ATTR, None)
        if s:
            return s
        cur = cur.__cause__ or cur.__context__
    return None


def failure_stage(exc: BaseException, default: str = "execute") -> str:
    """Which dispatch stage ``exc`` belongs to: the explicit tag when one
    was attached (a ``compile``-tagged error whose message screams Mosaic
    is refined to ``lower`` — warmup is where lazily-built kernels really
    lower), else message heuristics, else ``default``."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    tagged = _tagged_stage(exc)
    if tagged == "compile" and any(m in msg for m in _LOWER_MARKERS):
        return "lower"
    if tagged:
        return tagged
    return stage_for_message(msg, default=default)


def stage_for_message(message: str, default: str = "execute") -> str:
    """Stage heuristics over bare error text (for failure rows that cross
    a process boundary, where the exception object is gone)."""
    msg = str(message).lower()
    if any(m in msg for m in _LOWER_MARKERS):
        return "lower"
    if any(m in msg for m in _COMPILE_MARKERS):
        return "compile"
    if "conformance" in msg:
        return "conformance"
    return default if default in STAGES else "execute"


@contextmanager
def stage_scope(op: str, stage: str):
    """Attribute any exception escaping the body to ``(op, stage)`` and
    track it as the open forensics stage (embedded in flight dumps)."""
    global _OPEN_STAGE, _LAST_FAILED_STAGE
    prev = _OPEN_STAGE
    frame = {"op": op, "stage": stage, "t": round(time.time(), 6)}
    _OPEN_STAGE = frame
    try:
        yield
    except BaseException as e:
        mark_stage(e, stage)
        with _LOCK:
            _LAST_FAILED_STAGE = dict(frame, error=type(e).__name__)
        raise
    finally:
        _OPEN_STAGE = prev


def forensics_state() -> dict:
    """Open and last-failed stage frames (both None when quiet) — the
    flight recorder embeds this so a crash dump says what was in flight."""
    with _LOCK:
        return {"open": dict(_OPEN_STAGE) if _OPEN_STAGE else None,
                "last_failed": (dict(_LAST_FAILED_STAGE)
                                if _LAST_FAILED_STAGE else None)}


# ------------------------------------------------------- health probe ladder

def _run_stage(name: str, fn, timeout_s: float) -> dict:
    """Run one probe under a watchdog: a daemon thread does the work, the
    caller waits at most ``timeout_s`` — a hung runtime becomes a
    ``timed_out`` stage row instead of a hung doctor."""
    done = threading.Event()
    result: dict = {}

    def runner():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — reported, not raised
            result["error"] = f"{type(e).__name__}: {e}"[:300]
        finally:
            done.set()

    t0 = time.perf_counter()
    threading.Thread(target=runner, daemon=True,
                     name=f"diag-{name}").start()
    finished = done.wait(timeout_s)
    row = {"stage": name, "ok": False,
           "ms": round((time.perf_counter() - t0) * 1e3, 3)}
    if not finished:
        row["timed_out"] = True
        row["detail"] = f"no response within {timeout_s}s"
    elif "error" in result:
        row["detail"] = result["error"]
    else:
        row["ok"] = True
        row["detail"] = result.get("value")
    return row


def _probe_enumerate() -> dict:
    from .platform import apply_platform_env
    apply_platform_env()
    import jax

    devs = jax.devices()
    return {"platform": devs[0].platform, "device_count": len(devs),
            "devices": [{"id": d.id,
                         "kind": getattr(d, "device_kind", "") or d.platform,
                         "process_index": getattr(d, "process_index", 0)}
                        for d in devs]}


def _probe_memory() -> dict:
    import jax

    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — CPU backends often lack this
            stats = None
        if stats:
            out[str(d.id)] = {k: stats[k] for k in
                              ("bytes_in_use", "bytes_limit",
                               "peak_bytes_in_use") if k in stats}
    return out if out else {"unavailable": True}


def _probe_liveness() -> dict:
    from .faults import InjectedFault, maybe_unreachable
    if maybe_unreachable("diag.liveness"):
        raise InjectedFault("injected: device unreachable")
    import jax.numpy as jnp

    t0 = time.perf_counter()
    (jnp.ones((8, 8)) * 2 + 1).block_until_ready()
    return {"probe_ms": round((time.perf_counter() - t0) * 1e3, 3)}


def health_report(timeout_s: float | None = None, ring: bool = True) -> dict:
    """Run the staged health ladder and return a JSON-able report.

    Stages run in order; ``memory`` is advisory (CPU backends have no
    ``memory_stats``), so ``healthy`` is ``enumerate ok AND liveness ok``.
    Side effects: a ``device-health`` event, ``diag.device.*`` gauges, the
    module-level last-health snapshot (embedded in flight dumps), and —
    when ``CME213_DIAG_DIR`` is set and ``ring`` — one appended line in
    the persistent history ring.
    """
    from .metrics import gauge
    from .trace import record_event

    if timeout_s is None:
        timeout_s = float(os.environ.get(TIMEOUT_ENV, "30") or 30)

    stages = [_run_stage("enumerate", _probe_enumerate, timeout_s)]
    enum_ok = stages[0]["ok"]
    enum_detail = stages[0]["detail"] if enum_ok else {}
    if enum_ok:
        stages.append(_run_stage("memory", _probe_memory, timeout_s))
        stages.append(_run_stage("liveness", _probe_liveness, timeout_s))
    by_name = {s["stage"]: s for s in stages}
    live = by_name.get("liveness", {"ok": False})
    healthy = bool(enum_ok and live["ok"])
    probe_ms = (live.get("detail") or {}).get("probe_ms") if live["ok"] \
        else None
    platform = enum_detail.get("platform") if enum_ok else None
    device_count = enum_detail.get("device_count", 0) if enum_ok else 0

    report = {
        "doctor": 1,
        "t": round(time.time(), 6),
        "pid": os.getpid(),
        "rank": os.environ.get("JAX_PROCESS_ID", ""),
        "incarnation": int(os.environ.get("CME213_INCARNATION", "0") or 0),
        "healthy": healthy,
        "platform": platform,
        "device_count": device_count,
        "probe_ms": probe_ms,
        "stages": stages,
    }

    gauge("diag.device.healthy").set(1.0 if healthy else 0.0)
    gauge("diag.device.count").set(float(device_count))
    if probe_ms is not None:
        gauge("diag.device.probe_ms").set(float(probe_ms))
    mem = by_name.get("memory")
    if mem is not None and mem["ok"] and isinstance(mem["detail"], dict):
        in_use = sum(v.get("bytes_in_use", 0)
                     for v in mem["detail"].values()
                     if isinstance(v, dict))
        if in_use:
            gauge("diag.device.memory_bytes_in_use").set(float(in_use))

    record_event("device-health", healthy=healthy, platform=platform,
                 devices=device_count, probe_ms=probe_ms)

    global _LAST_HEALTH
    with _LOCK:
        _LAST_HEALTH = report
    if ring:
        path = _append_ring(report)
        if path:
            report["ring_path"] = path
    return report


def last_health() -> dict | None:
    """Most recent in-process health report (None before any probe)."""
    with _LOCK:
        return dict(_LAST_HEALTH) if _LAST_HEALTH else None


def ring_path() -> str | None:
    d = os.environ.get(DIAG_DIR_ENV, "").strip()
    return os.path.join(d, RING_NAME) if d else None


def _append_ring(report: dict) -> str | None:
    """Append one report line to the JSONL history ring, keeping the last
    :data:`RING_CAP` entries (rewrite-via-tmp + ``os.replace``, the same
    torn-write discipline as the flight recorder).  Best-effort: a broken
    disk must not fail a health probe."""
    path = ring_path()
    if not path:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        lines: list[str] = []
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
        lines.append(json.dumps(report, default=str))
        lines = lines[-RING_CAP:]
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — diagnostics never take down the host
        return None


def read_ring() -> list:
    """Parsed entries of the health ring (oldest first; [] when absent)."""
    path = ring_path()
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return out


# ------------------------------------------- predicted-vs-measured costs

def attribution_enabled() -> bool:
    return os.environ.get(ATTRIBUTION_ENV, "").strip().lower() in (
        "1", "true", "yes", "on")


def tolerance() -> float:
    try:
        tol = float(os.environ.get(TOLERANCE_ENV, "2.0") or 2.0)
    except ValueError:
        tol = 2.0
    return max(tol, 1.0)


def measured_cost(fn, args: tuple) -> dict:
    """XLA's own accounting for ``fn(*args)``: lower + compile (cache-hit
    cheap for already-compiled programs) and read ``cost_analysis()``.
    Returns ``{"flops": float|None, "bytes": float|None}`` — None when
    the backend does not report that column."""
    import jax

    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    analysis = jfn.lower(*args).compile().cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        analysis = {}

    def pick(key):
        v = analysis.get(key)
        return float(v) if v is not None else None

    return {"flops": pick("flops"), "bytes": pick("bytes accessed")}


def check_attribution(op: str, rung: str, shape_class: str, fn,
                      args: tuple, cost, tol: float | None = None) -> dict:
    """Compare the roofline model ``cost`` (a ``roofline.Cost``) against
    ``compiled.cost_analysis()`` for ``fn(*args)``; record the row in the
    in-process calibration table and emit ``attribution-mismatch`` when
    any ratio falls outside ``[1/tol, tol]``."""
    from .metrics import counter
    from .trace import record_event

    tol = tolerance() if tol is None else max(float(tol), 1.0)
    measured = measured_cost(fn, args)
    row = {"op": op, "rung": rung, "shape_class": shape_class, "tol": tol,
           "predicted_flops": float(cost.flops),
           "predicted_bytes": float(cost.nbytes),
           "measured_flops": measured["flops"],
           "measured_bytes": measured["bytes"],
           "flops_ratio": None, "bytes_ratio": None,
           "mismatches": [], "ok": True}
    for metric, predicted, got in (
            ("flops", float(cost.flops), measured["flops"]),
            ("bytes", float(cost.nbytes), measured["bytes"])):
        if got is None or got <= 0 or predicted <= 0:
            continue  # no signal from one side -> nothing to contradict
        ratio = round(got / predicted, 4)
        row[f"{metric}_ratio"] = ratio
        if ratio > tol or ratio < 1.0 / tol:
            row["ok"] = False
            row["mismatches"].append(metric)
            counter("diag.attribution.mismatches").inc()
            record_event("attribution-mismatch", op=op, rung=rung,
                         shape_class=shape_class, metric=metric,
                         predicted=predicted, measured=got, ratio=ratio)
    counter("diag.attribution.checks").inc()
    with _LOCK:
        _ATTRIBUTION.append(row)
    return row


def maybe_check_attribution(op: str, rung: str, shape_class: str, fn,
                            probe, cost):
    """Dispatch-time hook (``programs.get``): run the cross-check only
    when ``CME213_DIAG_ATTRIBUTION`` is on, and never let a diagnostics
    failure take the program cache down with it."""
    if cost is None or probe is None or not attribution_enabled():
        return None
    from .metrics import counter

    try:
        args = probe() if callable(probe) else tuple(probe)
        return check_attribution(op, rung, shape_class, fn, args, cost)
    except Exception:  # noqa: BLE001 — attribution is best-effort
        counter("diag.attribution.errors").inc()
        return None


def attribution_records() -> list:
    """The in-process calibration table (one row per check)."""
    with _LOCK:
        return [dict(r) for r in _ATTRIBUTION]


def reset() -> None:
    """Forget in-process diagnostic state (tests)."""
    global _LAST_HEALTH, _OPEN_STAGE, _LAST_FAILED_STAGE
    with _LOCK:
        _LAST_HEALTH = None
        _OPEN_STAGE = None
        _LAST_FAILED_STAGE = None
        _ATTRIBUTION.clear()


# ------------------------------------------------------------- calibration

def calibrate() -> list:
    """Predicted-vs-measured table for the flagship ops on the local
    backend: one small program each for spmv (flat scan rung), heat
    (reference stencil), and sort, checked against the same
    ``core/roofline.py`` models their bench rows are graded with.
    Returns the rows (also appended to :func:`attribution_records`)."""
    from .platform import apply_platform_env
    apply_platform_env()
    import jax.numpy as jnp

    from . import roofline

    rows = []

    def run(op, rung, shape_class, fn, args, cost):
        try:
            rows.append(check_attribution(op, rung, shape_class, fn,
                                          tuple(args), cost))
        except Exception as e:  # noqa: BLE001 — report, don't die
            rows.append({"op": op, "rung": rung, "shape_class": shape_class,
                         "error": f"{type(e).__name__}: {e}"[:300],
                         "ok": False})

    n, iters = 2048, 4
    try:
        from ..apps.spmv_scan import _build_runner
        run("spmv_scan", "flat", f"n{n}/i{iters}",
            _build_runner("flat", iters),
            (jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32),
             jnp.zeros(n, jnp.int32), jnp.zeros(1, jnp.int32)),
            roofline.spmv_scan_cost(n, iters))
    except Exception as e:  # noqa: BLE001
        rows.append({"op": "spmv_scan", "rung": "flat",
                     "shape_class": f"n{n}/i{iters}",
                     "error": f"{type(e).__name__}: {e}"[:300], "ok": False})

    side, order = 64, 2
    try:
        from ..ops.stencil import run_heat
        run("heat", "xla", f"order{order}/{side}x{side}",
            lambda u: run_heat(u, iters, order, 0.1, 0.1),
            (jnp.zeros((side, side), jnp.float32),),
            roofline.heat_cost(side, side, order=order, iters=iters))
    except Exception as e:  # noqa: BLE001
        rows.append({"op": "heat", "rung": "xla",
                     "shape_class": f"order{order}/{side}x{side}",
                     "error": f"{type(e).__name__}: {e}"[:300], "ok": False})

    sn = 4096
    run("sort", "xla", f"n{sn}", lambda x: jnp.sort(x),
        (jnp.zeros(sn, jnp.float32),),
        roofline.sort_cost(sn, kind="merge", key_bytes=4))
    return rows
