"""Halo exchange over a device mesh via ``lax.ppermute``.

The distributed-communication backbone: replaces the reference's MPI halo
machinery — ``MPI_Isend/Irecv`` row-band exchange, manual pack/unpack buffers
for non-contiguous columns, and request bookkeeping
(``hw/hw5/programming/2dHeat.cpp:503-547, 468-500``) — with XLA collectives:

- a row/column slab of width ``border_size`` is shifted one step along a mesh
  axis with ``lax.ppermute`` (ICI neighbor traffic, no packing: XLA handles
  strided layout);
- a device with no neighbor on a side (physical boundary) receives zeros from
  ``ppermute`` (links simply absent from the permutation) and overwrites that
  band with the Dirichlet BC value, keyed on ``lax.axis_index`` — replacing
  the reference's "-1 neighbor ⇒ physical boundary" case analysis
  (``2dHeat.cpp:407-450``);
- there is no explicit wait: data dependence replaces ``MPI_Wait(all)``, and
  comm/compute overlap is expressed structurally (see ``heat.py``).

All functions here run INSIDE ``shard_map`` (they use ``axis_index`` /
``ppermute``).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _shift_perm(n: int, up: bool) -> list[tuple[int, int]]:
    """Permutation sending each shard's slab to its neighbor; edge links
    omitted (no wraparound — a halo exchange, not a ring rotation)."""
    if up:
        return [(i, i + 1) for i in range(n - 1)]
    return [(i + 1, i) for i in range(n - 1)]


def exchange_halo_1d(block: jnp.ndarray, axis_name: str, axis_size: int,
                     border: int, lo_fill, hi_fill):
    """Exchange ``border``-wide slabs along array dim 0 over mesh axis
    ``axis_name``.

    Returns ``(lo_halo, hi_halo)`` — the bands to prepend/append along dim 0.
    ``lo_halo`` comes from the lower neighbor's top rows (or ``lo_fill`` at
    the physical boundary), symmetric for ``hi_halo``.
    """
    idx = lax.axis_index(axis_name)
    # my top rows travel up to be the next shard's lo_halo
    lo_halo = lax.ppermute(block[-border:], axis_name,
                           _shift_perm(axis_size, up=True))
    # my bottom rows travel down to be the previous shard's hi_halo
    hi_halo = lax.ppermute(block[:border], axis_name,
                           _shift_perm(axis_size, up=False))
    lo_halo = jnp.where(idx == 0, jnp.asarray(lo_fill, block.dtype), lo_halo)
    hi_halo = jnp.where(idx == axis_size - 1,
                        jnp.asarray(hi_fill, block.dtype), hi_halo)
    return lo_halo, hi_halo


def pad_with_halos(block: jnp.ndarray, axis_name: str, axis_size: int,
                   border: int, lo_fill, hi_fill) -> jnp.ndarray:
    """Exchange along dim 0 and return the block extended by ``border`` rows
    on each side."""
    lo, hi = exchange_halo_1d(block, axis_name, axis_size, border,
                              lo_fill, hi_fill)
    return jnp.concatenate([lo, block, hi], axis=0)
