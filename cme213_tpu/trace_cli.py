"""Offline trace analysis — ``python -m cme213_tpu trace <cmd> files...``.

The reference derived all of its metrics offline: timer lines grepped out
of job logs into spreadsheets (SURVEY §5).  This CLI is that analysis
pass over the structured form — the JSON-lines files ``core/trace.py``
sinks (``CME213_TRACE_FILE``, one file per rank via ``{rank}``
templating).  Three commands:

- ``summary``  — per-phase/per-kernel span time, compile-vs-run split and
  retrace counts, roofline attribution, served-rung and demotion counts,
  checkpoint-commit latency percentiles, fault/retry/rollback tallies,
  gang verdicts.  ``--require a,b`` fails (exit 1) when a named span
  never completed — the CI smoke gate — and ``--single-trace`` fails
  unless the records carry exactly one cross-process trace id.
  ``--json`` prints the same aggregates as one JSON document (what CI
  and the regression gate consume instead of scraping text).
- ``timeline`` — one chronological line per event with relative
  timestamps and rank labels (span-begin records are folded into their
  span-end line; ``--all`` shows everything).  Both summary and
  timeline window long-horizon traces with ``--since <ms|ISO>`` and
  ``--last N``.
- ``merge``    — interleave many per-rank files into one time-sorted
  JSON-lines stream (stdout or ``--out``); ``--timeline`` renders the
  merged gang view instead — launch, heartbeats, epoch commits, the
  stall/exit verdict, restart, resume — which is how a 2-rank rankkill
  faultcheck run is reconstructed after the fact.  ``--follow`` tails
  the files live through ``core/collector.py`` instead of one
  post-mortem pass.
- ``export``   — convert traces (including ``merge``-style multi-rank
  sets) to Chrome trace-event JSON loadable in Perfetto or
  ``chrome://tracing``: rank → pid, span nesting depth → tid, spans as
  B/E pairs, everything else as instant events, and each request's
  ``serve.hop.*`` chain stitched with flow arrows (s/t/f) across the
  pid lanes it crossed.
- ``waterfall`` — one request's hops (matched by rid tag or trace id)
  reassembled into a cross-process tree, every timestamp shifted onto
  the front tier's clock via the ``clock-offset`` peer graph with the
  accumulated ± error bound rendered; ``--json`` for the CI gate.
- ``regress``  — the bench regression gate (``cme213_tpu.bench.regress``
  under the trace umbrella): fresh sweep CSVs + ``metrics.json`` vs a
  banked baseline directory, machine-readable verdict, nonzero exit
  under ``--strict``.
- ``metrics``  — render a metrics snapshot in the Prometheus text
  exposition format (``core/metrics.render_prometheus``).  Accepts a
  trace JSONL file (uses its last ``metrics-snapshot`` event), a
  snapshot JSON document, or a flight dump.
- ``flight``   — render a crash flight dump (``core/flight.py``):
  header, traceback, open spans, and the pre-crash event timeline.

Any unparseable line is a hard error (exit 2): a trace that cannot be
trusted end-to-end must fail the smoke gate, not be silently skipped.
Records missing fields their :data:`~cme213_tpu.core.trace.EVENT_SCHEMA`
entry requires are counted and reported (but don't fail the parse — old
traces stay readable).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import Counter, defaultdict

from .core.metrics import _nearest_rank
from .core.trace import validate_record


class TraceParseError(ValueError):
    """A sink file line that is not a JSON event record."""


#: tags every record carries; hidden from per-event detail rendering
_BASE_FIELDS = {"event", "t", "pid", "rank", "incarnation", "trace", "_file"}


def load_events(paths: list[str], *,
                tolerate_torn: bool = False) -> list[dict]:
    """Parse + time-sort the records of one or many sink files.  Raises
    TraceParseError on any malformed line (parse errors are fatal — see
    module docstring) unless ``tolerate_torn`` is set, in which case bad
    lines are skipped — the ``waterfall`` subcommand uses this because
    its whole job is reading the sink of a process that may have been
    SIGKILLed mid-write, leaving a torn final line."""
    events = []
    for path in paths:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    if tolerate_torn:
                        continue
                    raise TraceParseError(f"{path}:{lineno}: {e}") from e
                if not isinstance(rec, dict) or "event" not in rec:
                    if tolerate_torn:
                        continue
                    raise TraceParseError(
                        f"{path}:{lineno}: not an event record")
                rec["_file"] = os.path.basename(path)
                events.append(rec)
    # stable sort: equal timestamps keep file order (begin before end)
    events.sort(key=lambda r: r.get("t") or 0.0)
    return events


def _rank_label(rec: dict) -> str:
    r = rec.get("rank")
    return f"r{r}" if isinstance(r, int) else "main"


def window_events(events: list[dict], since=None,
                  last: int | None = None) -> list[dict]:
    """Windowing for long-horizon (collector-era) traces.  ``since``
    keeps records newer than a bound — a bare number is milliseconds
    back from the NEWEST record, an ISO-8601 timestamp is absolute;
    ``last`` keeps the N newest records (after ``since``).  Raises
    ValueError on an unparseable ``since``."""
    if since is not None:
        try:
            ms = float(since)
        except (TypeError, ValueError):
            from datetime import datetime

            try:
                cutoff = datetime.fromisoformat(str(since)).timestamp()
            except ValueError as e:
                raise ValueError(
                    f"--since {since!r} is neither a millisecond count "
                    f"nor an ISO-8601 timestamp") from e
        else:
            ts = [e["t"] for e in events
                  if isinstance(e.get("t"), (int, float))]
            cutoff = (max(ts) - ms / 1e3) if ts else None
        if cutoff is not None:
            events = [e for e in events
                      if isinstance(e.get("t"), (int, float))
                      and e["t"] >= cutoff]
    if last is not None:
        events = events[-last:] if last > 0 else []
    return events


def _error_class(error) -> str:
    """Forensics bucket for a kernel-failure ``error`` field: the
    exception type name when the field looks like ``Type: message``,
    else the (truncated) text itself — dispatch records type names,
    bench rows record free text."""
    s = str(error or "?").strip()
    head = s.split(":", 1)[0].strip()
    if head and " " not in head and len(head) <= 40:
        return head
    return s[:40]


def _percentiles(vals: list[float]) -> dict:
    vals = sorted(vals)

    def pct(q):
        return _nearest_rank(vals, q)

    return {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
            "max": vals[-1]}


# ------------------------------------------------------------------ summary

def summarize(events: list[dict], out=None) -> dict:
    """Print the aggregate report; returns the aggregates (tests use the
    dict, humans read the text)."""
    w = (out or sys.stdout).write
    ranks = sorted({_rank_label(e) for e in events})
    incarnations = sorted({e.get("incarnation", 0) for e in events})
    ts = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]
    span_s = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    w(f"{len(events)} events over {span_s:.3f}s, ranks: "
      f"{', '.join(ranks) or '-'}, incarnations: "
      f"{', '.join(str(i) for i in incarnations)}\n")

    # cross-process causality: the trace ids and pids the records span —
    # a launched gang shows ONE id across every pid it touched
    trace_ids = sorted({str(e["trace"]) for e in events if e.get("trace")})
    pids = sorted({e["pid"] for e in events
                   if isinstance(e.get("pid"), int)})
    if trace_ids:
        w(f"trace ids: {', '.join(trace_ids)} "
          f"(across {len(pids)} pid(s))\n")

    invalid = Counter()
    for e in events:
        for missing in validate_record(e):
            invalid[(e["event"], missing)] += 1
    if invalid:
        w("schema violations:\n")
        for (ev, field), n in sorted(invalid.items()):
            w(f"  {ev}: missing {field} x{n}\n")

    # spans: per-phase/per-kernel time (the reference's timer table)
    by_span = defaultdict(list)
    begun = {}
    for e in events:
        if e["event"] == "span-begin":
            begun[e.get("id")] = e
        elif e["event"] == "span-end":
            begun.pop(e.get("id"), None)
            if isinstance(e.get("ms"), (int, float)):
                by_span[e["span"]].append(e["ms"])
    if by_span:
        w("spans (ms):\n")
        w(f"  {'name':<38} {'count':>5} {'total':>10} {'mean':>9} "
          f"{'max':>9}\n")
        for name in sorted(by_span):
            vals = by_span[name]
            w(f"  {name:<38} {len(vals):>5} {sum(vals):>10.2f} "
              f"{sum(vals) / len(vals):>9.2f} {max(vals):>9.2f}\n")
    if begun:
        w(f"open spans (begun, never ended — killed mid-flight?): "
          f"{', '.join(sorted(b['span'] for b in begun.values()))}\n")

    # compile vs run split per (op, shape class) + the retrace detector
    # (ROADMAP item 5's measurement half): spans named <op>.compile /
    # <op>.run carrying a shape_class tag
    split = defaultdict(lambda: {"compiles": 0, "compile_ms": 0.0,
                                 "runs": 0, "run_ms": 0.0,
                                 "cache_hits": 0, "cache_misses": 0})
    for e in events:
        if e["event"] in ("program-cache-hit", "program-cache-miss"):
            # the program cache (core/programs.py): a hit is a dispatch
            # that skipped compile entirely, a miss is the one build+warm
            # that produced the row's compile span
            d = split[(e.get("op"), e.get("shape_class"))]
            d["cache_hits" if e["event"] == "program-cache-hit"
              else "cache_misses"] += 1
            continue
        if e["event"] != "span-end" or "shape_class" not in e:
            continue
        nm, ms = e.get("span", ""), e.get("ms")
        if not isinstance(ms, (int, float)):
            continue
        if nm.endswith(".compile"):
            d = split[(nm[:-len(".compile")], e["shape_class"])]
            d["compiles"] += 1
            d["compile_ms"] += ms
        elif nm.endswith(".run"):
            d = split[(nm[:-len(".run")], e["shape_class"])]
            d["runs"] += 1
            d["run_ms"] += ms
    retraces = Counter((e.get("op"), e.get("shape_class")) for e in events
                       if e["event"] == "compile-retrace")
    if split:
        w("compile vs run (ms):\n")
        w(f"  {'op [shape class]':<38} {'compiles':>8} {'ms':>9} "
          f"{'runs':>5} {'ms':>9} {'hit/miss':>9}\n")
        for (op, sc), d in sorted(split.items()):
            hit_miss = f"{d['cache_hits']}/{d['cache_misses']}"
            w(f"  {f'{op} [{sc}]':<38} {d['compiles']:>8} "
              f"{d['compile_ms']:>9.2f} {d['runs']:>5} {d['run_ms']:>9.2f} "
              f"{hit_miss:>9}\n")
    if retraces:
        w(f"compile retraces: {sum(retraces.values())} ("
          + ", ".join(f"{op} [{sc}] x{n}"
                      for (op, sc), n in sorted(retraces.items())) + ")\n")

    # roofline attribution: span-ends that declared their cost model
    # (sp.roofline(...)) carry achieved_gbs / pct_peak / bound
    att = defaultdict(list)
    for e in events:
        if e["event"] == "span-end" and "achieved_gbs" in e:
            att[(e.get("span", "?"), str(e.get("kernel", "-")))].append(e)
    if att:
        w("roofline attribution:\n")
        for (nm, kernel), recs in sorted(att.items()):
            best = max(recs, key=lambda r: r.get("achieved_gbs") or 0)
            line = (f"  {nm} [{kernel}]: best "
                    f"{best.get('achieved_gbs')} GB/s")
            if best.get("pct_peak") is not None:
                line += (f" ({best['pct_peak']}% of peak, "
                         f"{best.get('bound')}-bound)")
            w(line + f" x{len(recs)}\n")

    served = Counter((e["op"], e["rung"]) for e in events
                     if e["event"] == "served")
    demoted_serves = sum(1 for e in events
                         if e["event"] == "served" and e.get("demoted"))
    if served:
        w("served rungs:\n")
        for (op, rung), n in sorted(served.items()):
            w(f"  {op}: {rung} x{n}\n")
        w(f"  (demoted serves: {demoted_serves})\n")
    rung_failed = Counter((e["op"], e["rung"]) for e in events
                          if e["event"] == "rung-failed")
    if rung_failed:
        w("demotions (rung-failed):\n")
        for (op, rung), n in sorted(rung_failed.items()):
            w(f"  {op}.{rung} x{n}\n")

    # conformance verdicts (core/conformance.py): probes run and how
    # many diverged — the served/demoted counts above show the effect
    conf = Counter((e.get("op"), e.get("rung"), bool(e.get("ok")))
                   for e in events if e["event"] == "conformance-probe")
    conf_failed = [e for e in events if e["event"] == "conformance-failed"]
    if conf or conf_failed:
        n_pass = sum(n for (_, _, ok), n in conf.items() if ok)
        n_fail = sum(n for (_, _, ok), n in conf.items() if not ok)
        w(f"conformance: {n_pass + n_fail} probe(s), {n_pass} passed, "
          f"{n_fail} failed\n")
        for (op, rung, ok), n in sorted(conf.items(), key=lambda kv: (
                str(kv[0][0]), str(kv[0][1]))):
            w(f"  {op}.{rung}: {'pass' if ok else 'FAIL'} x{n}\n")
        for e in conf_failed:
            w(f"  failed: {e.get('op')}.{e.get('rung')} "
              f"[{e.get('shape_class')}] {e.get('detail')}\n")

    # staged kernel forensics (core/diag.py): every kernel-failure from
    # dispatch/bench/serve carries a stage tag; group by (op, kernel,
    # stage, error class), with conformance REFUSALS (the gate said no)
    # rendered apart from the lower/compile/execute CRASHES — "diverged
    # from reference" and "Mosaic blew up" are different diagnoses
    kfail = [e for e in events if e["event"] == "kernel-failure"]
    forensics = Counter(
        (str(e.get("op")), str(e.get("kernel")), str(e.get("stage") or "?"),
         _error_class(e.get("error"))) for e in kfail)
    if kfail:
        crashes = sorted((k, n) for k, n in forensics.items()
                         if k[2] != "conformance")
        refusals = sorted((k, n) for k, n in forensics.items()
                          if k[2] == "conformance")
        w(f"kernel forensics: {len(kfail)} failure(s), "
          f"{sum(n for _, n in crashes)} crash(es), "
          f"{sum(n for _, n in refusals)} conformance refusal(s)\n")
        if crashes:
            w(f"  {'op.kernel':<30} {'stage':<12} {'error class':<24} "
              f"{'count':>5}\n")
            for (op, kern, stage, err), n in crashes:
                w(f"  {f'{op}.{kern}':<30} {stage:<12} {err:<24} {n:>5}\n")
        for (op, kern, _, err), n in refusals:
            w(f"  refused: {op}.{kern} ({err}) x{n}\n")

    # device health (core/diag.py doctor ladder)
    health_evs = [e for e in events if e["event"] == "device-health"]
    health = None
    if health_evs:
        last = health_evs[-1]
        health = {"probes": len(health_evs),
                  "last_healthy": bool(last.get("healthy")),
                  "platform": last.get("platform"),
                  "devices": last.get("devices"),
                  "probe_ms": last.get("probe_ms")}
        w(f"device health: {len(health_evs)} probe(s); last "
          f"{'HEALTHY' if health['last_healthy'] else 'UNHEALTHY'} "
          f"({health['platform']}, {health['devices']} device(s), "
          f"probe {health['probe_ms']} ms)\n")

    # predicted-vs-measured attribution mismatches (core/diag.py): the
    # roofline cost model disagreed with compiled.cost_analysis()
    mismatches = [e for e in events if e["event"] == "attribution-mismatch"]
    if mismatches:
        w(f"attribution mismatches: {len(mismatches)} "
          f"(cost model vs XLA cost_analysis)\n")
        for e in mismatches:
            w(f"  {e.get('op')}.{e.get('rung')} [{e.get('shape_class')}] "
              f"{e.get('metric')}: predicted {e.get('predicted')} "
              f"measured {e.get('measured')} (x{e.get('ratio')})\n")

    # admission decisions (core/admission.py): rejections and the
    # chunk/tile shrink responses
    rejected = [e for e in events if e["event"] == "admission-rejected"]
    shrunk = [e for e in events if e["event"] == "chunk-shrunk"]
    if rejected or shrunk:
        w(f"admission: {len(rejected)} rejected, {len(shrunk)} "
          f"chunk(s)/tile(s) shrunk\n")
        for e in rejected:
            w(f"  rejected: {e.get('op')} needs {e.get('requested_bytes')}"
              f" B > budget {e.get('budget_bytes')} B\n")
        for e in shrunk:
            w(f"  shrunk: {e.get('op')} {e.get('from_size')} -> "
              f"{e.get('to_size')} ({e.get('reason')})\n")

    commits = [e for e in events if e["event"] == "epoch-commit"]
    commit_stats = None
    if commits:
        last = max(commits, key=lambda e: e.get("epoch", 0))
        line = (f"epoch commits: {len(commits)} "
                f"(latest epoch {last.get('epoch')}, step {last.get('step')})")
        ms = [e["ms"] for e in commits if isinstance(e.get("ms"), (int, float))]
        if ms:
            commit_stats = _percentiles(ms)
            line += ("  latency ms: " + " ".join(
                f"{k}={v:.2f}" for k, v in commit_stats.items()))
        w(line + "\n")
    loads = [e for e in events if e["event"] == "commit-loaded"]
    for e in loads:
        w(f"resume: epoch {e.get('epoch')}, step {e.get('step')} "
          f"from {e.get('candidate')} ({_rank_label(e)}, "
          f"incarnation {e.get('incarnation')})\n")
    bad = Counter(e.get("candidate") for e in events
                  if e["event"] == "commit-invalid")
    if bad:
        w("invalid commits skipped: "
          + ", ".join(f"{c} x{n}" for c, n in sorted(bad.items())) + "\n")

    verdicts = [e for e in events if e["event"] == "rank-failed"]
    restarts = [e for e in events if e["event"] == "gang-restart"]
    launches = [e for e in events if e["event"] == "gang-launch"]
    exits = [e for e in events if e["event"] == "gang-exit"]
    if launches or verdicts or restarts:
        w(f"gang: {len(launches)} launch(es), {len(verdicts)} verdict(s) "
          f"[{', '.join(v.get('reason', '?') for v in verdicts) or '-'}], "
          f"{len(restarts)} restart(s)"
          + (f", final rc {exits[-1].get('rc')}" if exits else "") + "\n")
    beats = defaultdict(list)
    for e in events:
        if e["event"] == "heartbeat":
            beats[e.get("rank")].append(e.get("step"))
    for rank in sorted(beats, key=str):
        w(f"heartbeats r{rank}: {len(beats[rank])} "
          f"(last step {beats[rank][-1]})\n")

    # serving front end (serve/): load shedding, breaker transitions,
    # batch occupancy — the stays-up-under-overload evidence
    shed = Counter()
    for e in events:
        if e["event"] == "queue-shed":
            shed[(e.get("op"), e.get("reason"))] += 1
        elif e["event"] == "deadline-shed":
            shed[(e.get("op"), "deadline")] += 1
    breaker = {"open": [], "half_open": [], "close": []}
    for e in events:
        if e["event"] == "breaker-open":
            breaker["open"].append((e.get("op"), e.get("rung")))
        elif e["event"] == "breaker-half-open":
            breaker["half_open"].append((e.get("op"), e.get("rung")))
        elif e["event"] == "breaker-close":
            breaker["close"].append((e.get("op"), e.get("rung")))
    batches = [e for e in events if e["event"] == "batch-executed"]
    degraded = sum(1 for e in events if e["event"] == "span-end"
                   and e.get("span") == "degraded-mode")
    reqs = [e for e in events if e["event"] == "request-served"]
    # transport codec span tags (serve/transport.py samples these past
    # the first 64 rids of a connection — counts here are of *traced*
    # codec operations; the full population lives in the
    # serve.request.{encode,decode}_ms histograms)
    codec = {"encode": [e for e in events
                        if e["event"] == "request-serialized"],
             "decode": [e for e in events
                        if e["event"] == "request-deserialized"]}
    serving = None
    if shed or any(breaker.values()) or batches or reqs or any(
            codec.values()):
        occ = [e["occupancy"] for e in batches
               if isinstance(e.get("occupancy"), (int, float))]
        sizes = [e["size"] for e in batches
                 if isinstance(e.get("size"), (int, float))]
        # stable shed keys: every serving op appears with every shed
        # reason, zero-filled, so downstream diffs never see keys
        # flicker in and out with the traffic
        serve_ops = sorted({str(e.get("op")) for e in
                            (batches + reqs)} |
                           {str(op) for op, _ in shed})
        shed_keys = {f"{op}:{reason}": 0 for op in serve_ops
                     for reason in ("queue-full", "deadline", "admission")}
        for (op, reason), n in shed.items():
            shed_keys[f"{op}:{reason}"] = n
        serving = {
            "shed": dict(sorted(shed_keys.items())),
            "breaker": {k: [f"{op}.{rung}" for op, rung in v]
                        for k, v in breaker.items()},
            "batches": len(batches),
            "batch_mean_size": (sum(sizes) / len(sizes)) if sizes else None,
            "batch_occupancy": (sum(occ) / len(occ)) if occ else None,
            "degraded_batches": degraded,
        }
        for d, evs in codec.items():
            ms = [e["ms"] for e in evs
                  if isinstance(e.get("ms"), (int, float))]
            nb = [e["nbytes"] for e in evs
                  if isinstance(e.get("nbytes"), (int, float))]
            serving[f"{d}_traced"] = len(evs)
            serving[f"{d}_ms_mean"] = (sum(ms) / len(ms)) if ms else None
            serving[f"{d}_bytes"] = sum(nb)
        w(f"serving: {len(batches)} batch(es)")
        if sizes:
            w(f", mean size {serving['batch_mean_size']:.2f}"
              f", occupancy {serving['batch_occupancy']:.2f}")
        if degraded:
            w(f", {degraded} degraded")
        w("\n")
        for d in ("encode", "decode"):
            if serving[f"{d}_traced"]:
                w(f"  wire {d}: {serving[f'{d}_traced']} traced, "
                  f"mean {serving[f'{d}_ms_mean']:.4f} ms, "
                  f"{serving[f'{d}_bytes']} B\n")
        for key, n in serving["shed"].items():
            if n:
                w(f"  shed {key} x{n}\n")
        for transition in ("open", "half_open", "close"):
            for target in breaker[transition]:
                w(f"  breaker {transition.replace('_', '-')}: "
                  f"{target[0]}.{target[1]}\n")

    # replicated fleet (serve/fleet.py + serve/router.py): per-replica
    # routing stats from the front tier's lifecycle + routing events —
    # the zero-accepted-request-loss evidence lives here (requeues)
    fleet_sec = None
    routed = [e for e in events if e["event"] == "request-routed"]
    requeued = [e for e in events if e["event"] == "request-requeued"]
    rep_ups = [e for e in events if e["event"] == "replica-up"]
    rep_downs = [e for e in events if e["event"] == "replica-down"]
    if routed or requeued or rep_ups or rep_downs:
        per_rep: dict[str, dict] = {}

        def _rep(label) -> dict:
            return per_rep.setdefault(str(label), {
                "routed": 0, "requeued": 0, "ups": 0, "downs": 0,
                "breaker": "closed"})

        for e in rep_ups:
            _rep(f"r{e.get('replica')}")["ups"] += 1
        for e in rep_downs:
            _rep(f"r{e.get('replica')}")["downs"] += 1
        for e in routed:
            _rep(f"r{e.get('replica')}")["routed"] += 1
        for e in requeued:
            _rep(f"r{e.get('from_replica')}")["requeued"] += 1
        # per-replica breaker state: the router keys its breaker
        # (op="fleet.route") by rung "r<rank>" — last transition wins
        for e in events:
            if (e.get("op") == "fleet.route"
                    and e["event"] in ("breaker-open", "breaker-half-open",
                                       "breaker-close")):
                _rep(e.get("rung"))["breaker"] = \
                    e["event"].removeprefix("breaker-")
        fleet_sec = {
            "replicas": {k: per_rep[k] for k in sorted(per_rep)},
            "routed": len(routed),
            "requeues": len(requeued),
            "replica_ups": len(rep_ups),
            "replica_downs": len(rep_downs),
            "scale_ups": sum(1 for e in events
                             if e["event"] == "scale-up"),
            "scale_downs": sum(1 for e in events
                               if e["event"] == "scale-down"),
        }
        w(f"fleet: {len(per_rep)} replica(s), {len(routed)} routed, "
          f"{len(requeued)} requeue(s), scale +{fleet_sec['scale_ups']}"
          f"/-{fleet_sec['scale_downs']}\n")
        for label, row in fleet_sec["replicas"].items():
            w(f"  {label}: {row['routed']} routed, "
              f"{row['requeued']} requeued, breaker {row['breaker']}"
              + (f" [DOWN x{row['downs']}]" if row["downs"] else "")
              + "\n")

    # request-lifecycle phase attribution: request-served events carry
    # the per-phase timing breakdown stamped by the server clock
    phases = None
    if reqs:
        per_op: dict[str, dict[str, list]] = defaultdict(
            lambda: defaultdict(list))
        for e in reqs:
            op = str(e.get("op"))
            for ph in ("queue_ms", "admit_ms", "batch_wait_ms", "run_ms",
                       "total_ms"):
                v = e.get(ph)
                if isinstance(v, (int, float)):
                    per_op[op][ph].append(v)
                    per_op["overall"][ph].append(v)
        phases = {}
        for op, cols in per_op.items():
            phases[op] = {ph: {"p50": round(_nearest_rank(sorted(vs), 0.5), 3),
                               "p99": round(_nearest_rank(sorted(vs), 0.99), 3)}
                          for ph, vs in cols.items() if vs}
        w(f"request phases (p50/p99 ms over {len(reqs)} request(s)):\n")
        for op in sorted(phases, key=lambda o: (o != "overall", o)):
            cells = "  ".join(
                f"{ph[:-3]} {d['p50']}/{d['p99']}"
                for ph, d in sorted(phases[op].items(), key=lambda kv: (
                    ("queue_ms", "admit_ms", "batch_wait_ms", "run_ms",
                     "total_ms").index(kv[0]))))
            w(f"  {op}: {cells}\n")

    # per-tenant accounting: request-served carries tenant; shed events
    # carry it as an optional tag
    tenants = None
    tenant_rows: dict[str, dict] = defaultdict(
        lambda: {"served": 0, "failed": 0, "shed": 0, "_lat": []})
    for e in reqs:
        row = tenant_rows[str(e.get("tenant"))]
        if e.get("status") == "ok":
            row["served"] += 1
            if isinstance(e.get("total_ms"), (int, float)):
                row["_lat"].append(e["total_ms"])
        else:
            row["failed"] += 1
    for e in events:
        if e["event"] in ("queue-shed", "deadline-shed") and "tenant" in e:
            tenant_rows[str(e.get("tenant"))]["shed"] += 1
    if tenant_rows:
        tenants = {}
        for t, row in sorted(tenant_rows.items()):
            lat = sorted(row.pop("_lat"))
            tenants[t] = {**row,
                          "p50_ms": (round(_nearest_rank(lat, 0.5), 3)
                                     if lat else None),
                          "p99_ms": (round(_nearest_rank(lat, 0.99), 3)
                                     if lat else None)}
        w("tenants:\n")
        for t, row in tenants.items():
            tail = (f", p50 {row['p50_ms']} p99 {row['p99_ms']} ms"
                    if row["p50_ms"] is not None else "")
            w(f"  {t}: {row['served']} served, {row['shed']} shed, "
              f"{row['failed']} failed{tail}\n")

    # SLO burn/recovery transitions (serve/slo.py)
    slo = None
    burns = [e for e in events if e["event"] == "slo-burn"]
    oks = [e for e in events if e["event"] == "slo-ok"]
    if burns or oks:
        slo = {
            "burns": len(burns),
            "oks": len(oks),
            "objectives": sorted({str(e.get("objective"))
                                  for e in burns + oks}),
            "last_burn": (
                {"objective": burns[-1].get("objective"),
                 "burn_short": burns[-1].get("burn_short"),
                 "burn_long": burns[-1].get("burn_long"),
                 "threshold": burns[-1].get("threshold")}
                if burns else None),
        }
        w(f"slo: {len(burns)} burn(s), {len(oks)} recover(ies) "
          f"[{', '.join(slo['objectives'])}]\n")
        for e in burns:
            w(f"  burn {e.get('objective')}: short {e.get('burn_short')} "
              f"long {e.get('burn_long')} >= {e.get('threshold')}\n")
        for e in oks:
            w(f"  ok {e.get('objective')}: short {e.get('burn_short')}\n")

    # numeric health (core/numerics.py): shadow conformance drift,
    # budget demotions, and output sentinels — the continuous form of
    # the conformance section's one-shot probes above
    numeric = None
    drifts = [e for e in events if e["event"] == "numeric-drift"]
    d_burns = [e for e in events if e["event"] == "drift-budget-burn"]
    d_oks = [e for e in events if e["event"] == "drift-budget-ok"]
    sentinels = [e for e in events if e["event"] == "numeric-sentinel"]
    if drifts or d_burns or sentinels:
        per_rung: dict = {}
        for e in drifts:
            key = f"{e.get('op')}.{e.get('rung')}"
            row = per_rung.setdefault(
                key, {"samples": 0, "over_budget": 0, "worst_rel_l2": 0.0,
                      "worst_ulps": 0})
            row["samples"] += 1
            row["over_budget"] += bool(e.get("over_budget"))
            rel = e.get("rel_l2")
            if isinstance(rel, (int, float)):
                row["worst_rel_l2"] = max(row["worst_rel_l2"], rel)
            else:  # "inf" marker: shape/dtype mismatch or non-finite
                row["worst_rel_l2"] = "inf"
            ulps = e.get("max_ulps")
            if isinstance(ulps, int) and isinstance(row["worst_ulps"], int):
                row["worst_ulps"] = (max(row["worst_ulps"], ulps)
                                     if ulps >= 0 else ulps)
        numeric = {
            "drift": per_rung,
            "samples": len(drifts),
            "over_budget": sum(1 for e in drifts if e.get("over_budget")),
            "demotions": [f"{e.get('op')}.{e.get('rung')}"
                          for e in d_burns],
            "recoveries": len(d_oks),
            "sentinels": {
                "trips": len(sentinels),
                "bad_elems": sum(e.get("count") or 0 for e in sentinels)},
        }
        w(f"numeric health: {numeric['samples']} shadow sample(s), "
          f"{numeric['over_budget']} over budget, "
          f"{len(d_burns)} budget burn(s), "
          f"{len(sentinels)} sentinel trip(s)\n")
        for key, row in sorted(per_rung.items()):
            w(f"  {key}: {row['samples']} sample(s), "
              f"{row['over_budget']} over, "
              f"worst rel_l2 {row['worst_rel_l2']}"
              + (f", worst ulps {row['worst_ulps']}"
                 if row["worst_ulps"] else "") + "\n")
        for e in d_burns:
            w(f"  DEMOTED {e.get('op')}.{e.get('rung')}: burn short "
              f"{e.get('burn_short')} long {e.get('burn_long')} "
              f">= {e.get('threshold')}\n")
        for e in sentinels:
            w(f"  sentinel {e.get('op')}.{e.get('rung')}: "
              f"{e.get('kind')} x{e.get('count')} "
              f"(of {e.get('size')} elems)\n")

    # convergence (core/numerics.ConvergenceTracker feeders): per-op
    # solver-progress rollup with the same stall policy `top` renders.
    # Keyed by (op, job) — two jobs iterating the same op must not fold
    # into one row, or a fresh job's high residual masks a stall.
    convergence = None
    progress = [e for e in events if e["event"] == "solver-progress"]
    if progress:
        convergence = {}
        for e in progress:
            op = str(e.get("op") or "solver")
            if e.get("job"):
                op = f"{op}[{e['job']}]"
            row = convergence.setdefault(
                op, {"epochs": 0, "first_residual": e.get("residual"),
                     "last_residual": None, "last_step": None,
                     "iters_per_s": None, "_best": None, "_since": 0,
                     "stalled": False})
            row["epochs"] += 1
            res = e.get("residual")
            row["last_residual"] = res
            row["last_step"] = e.get("step")
            row["iters_per_s"] = e.get("iters_per_s")
            if isinstance(res, (int, float)):
                if row["_best"] is None or res < row["_best"] * (1 - 1e-3):
                    row["_best"], row["_since"] = res, 0
                else:
                    row["_since"] += 1
                row["stalled"] = row["_since"] >= 5
        for op, row in convergence.items():
            row.pop("_best"), row.pop("_since")
        w(f"convergence: {len(convergence)} solver(s), "
          f"{len(progress)} progress event(s)\n")
        for op, row in sorted(convergence.items()):
            w(f"  {op}: {row['epochs']} epoch(s), residual "
              f"{row['first_residual']} -> {row['last_residual']} "
              f"@step {row['last_step']}, {row['iters_per_s']} iters/s "
              f"{'STALLED' if row['stalled'] else ''}".rstrip() + "\n")

    # durable long-job lane (serve/jobs.py): per-job lifecycle rollup.
    # job-epoch is emitted only after the durable publish, so duplicate
    # epoch numbers here mean a committed epoch was re-executed — the
    # invariant the lane exists to uphold.
    jobs_sec = None
    job_evs = [e for e in events if str(e["event"]).startswith("job-")]
    if job_evs:
        jobs_sec = {}
        for e in job_evs:
            jid = str(e.get("job") or "?")
            row = jobs_sec.setdefault(
                jid, {"op": None, "state": None, "epoch": None,
                      "total_epochs": None, "residual": None, "epochs": 0,
                      "dup_epochs": 0, "resumes": 0, "preemptions": 0,
                      "reassignments": 0, "_seen": set()})
            if e.get("op"):
                row["op"] = e.get("op")
            ev = e["event"]
            if ev == "job-submitted":
                row["state"] = "PENDING"
                row["total_epochs"] = e.get("total_epochs")
            elif ev == "job-epoch":
                row["state"] = "RUNNING"
                row["epoch"] = e.get("epoch")
                row["residual"] = e.get("residual")
                row["epochs"] += 1
                if e.get("epoch") in row["_seen"]:
                    row["dup_epochs"] += 1
                row["_seen"].add(e.get("epoch"))
            elif ev == "job-preempted":
                row["state"] = "PREEMPTED"
                row["preemptions"] += 1
            elif ev == "job-resumed":
                row["state"] = "RUNNING"
                row["resumes"] += 1
            elif ev == "job-reassigned":
                row["reassignments"] += 1
            elif ev == "job-done":
                row["state"] = e.get("state")
        for row in jobs_sec.values():
            row.pop("_seen")
        w(f"jobs: {len(jobs_sec)} job(s), {len(job_evs)} event(s)\n")
        for jid, row in sorted(jobs_sec.items()):
            w(f"  {jid} [{row['op']}]: {row['state']} "
              f"epoch {row['epoch']}/{row['total_epochs']}, "
              f"residual {row['residual']}, "
              f"{row['resumes']} resume(s), "
              f"{row['preemptions']} preemption(s)"
              + (f", {row['reassignments']} reassignment(s)"
                 if row["reassignments"] else "")
              + (f" [REEXECUTED x{row['dup_epochs']}]"
                 if row["dup_epochs"] else "") + "\n")

    # autotuning (core/tune.py): search activity + the tuned-vs-default
    # split at dispatch — the "is the cache actually consulted" signal
    tuning = None
    t_trials = [e for e in events if e["event"] == "tune-trial"]
    t_winners = [e for e in events if e["event"] == "tune-winner"]
    t_hits = sum(1 for e in events if e["event"] == "tune-hit")
    t_defaults = sum(1 for e in events if e["event"] == "tune-default")
    if t_trials or t_winners or t_hits or t_defaults:
        tuning = {
            "trials": len(t_trials),
            "rejected": sum(1 for e in t_trials if not e.get("ok")),
            "winners": {
                f"{e.get('op')} [{e.get('shape_class')}]": {
                    "candidate": e.get("candidate"),
                    "statics": e.get("statics"),
                    "gbs": e.get("gbs"),
                } for e in t_winners},
            "hits": t_hits,
            "defaults": t_defaults,
        }
        w(f"tuning: {len(t_trials)} trial(s) "
          f"({tuning['rejected']} rejected), {len(t_winners)} winner(s); "
          f"dispatch {t_hits} tuned / {t_defaults} default\n")
        for key, rec in sorted(tuning["winners"].items()):
            w(f"  {key}: {rec['candidate']} {rec['statics']} "
              f"{rec['gbs']} GB/s\n")

    counts = Counter(e["event"] for e in events)
    for label, ev in (("op failures", "op-failure"),
                      ("retries", "retry"),
                      ("numeric aborts", "numeric-abort"),
                      ("checkpoint rollbacks", "checkpoint-rollback"),
                      ("checkpoint quarantines", "checkpoint-quarantine")):
        if counts[ev]:
            w(f"{label}: {counts[ev]}\n")
    faults = Counter(e.get("kind") for e in events
                     if e["event"] == "fault-injected")
    if faults:
        w("faults injected: "
          + ", ".join(f"{k} x{n}" for k, n in sorted(faults.items())) + "\n")

    # all keys are strings so the dict doubles as the --json document
    return {"events": len(events), "ranks": ranks,
            "trace_ids": trace_ids, "pids": pids, "spans": dict(by_span),
            "served": {f"{op}.{rung}": n for (op, rung), n in served.items()},
            "rung_failed": {f"{op}.{rung}": n
                            for (op, rung), n in rung_failed.items()},
            "compile_run": {f"{op} [{sc}]": d
                            for (op, sc), d in split.items()},
            "retraces": {f"{op} [{sc}]": n
                         for (op, sc), n in retraces.items()},
            "attribution": {
                f"{nm} [{kernel}]": {
                    "count": len(recs),
                    "best_gbs": max(r.get("achieved_gbs") or 0
                                    for r in recs),
                    "pct_peak": max((r.get("pct_peak") or 0 for r in recs),
                                    default=0) or None,
                    "bound": recs[-1].get("bound"),
                } for (nm, kernel), recs in att.items()},
            "commits": len(commits), "commit_ms": commit_stats,
            "resumes": len(loads), "verdicts": len(verdicts),
            "restarts": len(restarts),
            "invalid": {f"{ev}:{field}": n
                        for (ev, field), n in invalid.items()},
            "conformance": {f"{op}.{rung}": {"ok": ok, "count": n}
                            for (op, rung, ok), n in conf.items()},
            "forensics": {f"{op}.{kern}:{stage}:{err}": n
                          for (op, kern, stage, err), n
                          in sorted(forensics.items())},
            "health": health,
            "attribution_mismatches": len(mismatches),
            "admission": {"rejected": len(rejected), "shrunk": len(shrunk)},
            "serving": serving,
            "fleet": fleet_sec,
            "phases": phases,
            "tenants": tenants,
            "slo": slo,
            "numerics": numeric,
            "convergence": convergence,
            "jobs": jobs_sec,
            "tuning": tuning,
            "counts": dict(counts)}


# ----------------------------------------------------------------- timeline

def _detail(rec: dict) -> str:
    ev = rec["event"]
    if ev in ("span-begin", "span-end"):
        parts = [str(rec.get("span", "?"))]
        if "ms" in rec:
            parts.append(f"ms={rec['ms']}")
        if "error" in rec:
            parts.append(f"error={rec['error']}")
        parts += [f"{k}={rec[k]}" for k in sorted(rec)
                  if k not in _BASE_FIELDS
                  and k not in ("span", "id", "parent", "ms", "error")]
        return " ".join(parts)
    if ev == "metrics-snapshot":
        m = rec.get("metrics", {})
        return (f"{len(m.get('counters', {}))} counters, "
                f"{len(m.get('gauges', {}))} gauges, "
                f"{len(m.get('histograms', {}))} histograms")
    parts = []
    for k in sorted(rec):
        if k in _BASE_FIELDS:
            continue
        v = rec[k]
        if isinstance(v, str) and len(v) > 60:
            v = v[:57] + "..."
        parts.append(f"{k}={v}")
    return " ".join(parts)


def _timeline_line(e: dict, t0: float) -> str:
    t = e.get("t")
    rel = f"+{t - t0:9.3f}s" if isinstance(t, (int, float)) else " " * 11
    inc = e.get("incarnation", 0)
    return (f"{rel} {_rank_label(e):>5} i{inc} "
            f"{e['event']:<22} {_detail(e)}\n")


def render_timeline(events: list[dict], out=None,
                    show_all: bool = False) -> None:
    """One line per event, chronological, relative to the first record —
    the merged gang view when fed every rank's file."""
    out = out or sys.stdout
    ts = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]
    t0 = min(ts) if ts else 0.0
    for e in events:
        if not show_all and e["event"] == "span-begin":
            continue  # folded into the span-end line (which carries ms)
        out.write(_timeline_line(e, t0))


# ------------------------------------------------------------------ export

def _chrome_pid(rec: dict) -> int:
    """rank → Chrome pid: rank r → r+1, non-rank (launcher/main) → 0."""
    r = rec.get("rank")
    return r + 1 if isinstance(r, int) else 0


def to_chrome_trace(events: list[dict]) -> dict:
    """Convert trace records to the Chrome trace-event format (Perfetto /
    ``chrome://tracing``).

    Mapping: rank → pid (with ``process_name`` metadata naming each),
    span nesting depth → tid (a span's depth comes from its parent
    chain, so causal trees render as stacked tracks), span begin/end
    pairs → ``B``/``E`` duration events, a ``span-end`` whose begin is
    missing (ring-buffer truncation) → a self-contained ``X`` complete
    event reconstructed from its ``ms``, and every non-span record → an
    instant (``i``) event.  Open spans (begun, never ended — a killed
    rank) are dropped so begin/end pairing stays valid for the viewer.
    Timestamps are microseconds relative to the first record.

    Request waterfalls additionally get Chrome *flow* events: the
    ``serve.hop.*`` spans of one request (grouped by walking parent
    links to their shared root) are stitched with ``s``/``t``/``f``
    arrows so Perfetto draws the request's path across the pid lanes it
    crossed — client to front tier to replica and back.
    """
    ts = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]
    t0 = min(ts) if ts else 0.0

    def us(t) -> float:
        return round((t - t0) * 1e6, 3)

    begins = {e.get("id"): e for e in events if e["event"] == "span-begin"}
    ends = {e.get("id"): e for e in events if e["event"] == "span-end"}

    depth_memo: dict = {}

    def depth(sid) -> int:
        d, chain = 0, sid
        seen = set()
        while chain is not None and chain not in seen:
            if chain in depth_memo:
                d += depth_memo[chain]
                break
            seen.add(chain)
            rec = begins.get(chain) or ends.get(chain)
            parent = rec.get("parent") if rec else None
            if parent is None:
                break
            d += 1
            chain = parent
        depth_memo[sid] = d
        return d

    out, pids = [], {}
    for e in events:
        pid = _chrome_pid(e)
        if pid not in pids:
            pids[pid] = ("main" if pid == 0
                         else f"rank {e.get('rank')}")
        args = {k: v for k, v in e.items()
                if k not in _BASE_FIELDS and k not in ("span", "id",
                                                       "parent")}
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        if e["event"] == "span-begin":
            if e.get("id") not in ends:
                continue  # open span: dropped to keep pairing valid
            out.append({"name": e.get("span", "?"), "cat": "span",
                        "ph": "B", "ts": us(t), "pid": pid,
                        "tid": depth(e.get("id")), "args": args})
        elif e["event"] == "span-end":
            sid = e.get("id")
            ms = e.get("ms") if isinstance(e.get("ms"), (int, float)) else 0.0
            if sid in begins:
                out.append({"name": e.get("span", "?"), "cat": "span",
                            "ph": "E", "ts": us(t), "pid": pid,
                            "tid": depth(sid), "args": args})
            else:  # begin lost (ring buffer): reconstruct from ms
                out.append({"name": e.get("span", "?"), "cat": "span",
                            "ph": "X", "ts": us(t - ms / 1e3),
                            "dur": round(ms * 1e3, 3), "pid": pid,
                            "tid": depth(sid), "args": args})
        else:
            out.append({"name": e["event"], "cat": "event", "ph": "i",
                        "s": "p", "ts": us(t), "pid": pid, "tid": 0,
                        "args": args})

    # request flow arrows: group closed serve.hop.* spans by the root of
    # their parent chain (one root = one request), then stitch the group
    # in begin-time order as s → t → ... → f steps.  Each step sits at
    # its hop's begin inside that hop's pid/tid lane, so the viewer
    # draws the request hopping across process lanes.
    def _flow_root(sid):
        seen = set()
        while sid not in seen:
            seen.add(sid)
            rec = begins.get(sid) or ends.get(sid)
            parent = rec.get("parent") if rec else None
            if parent is None or (parent not in begins
                                  and parent not in ends):
                return sid
            sid = parent
        return sid

    flows = defaultdict(list)
    for sid, b in begins.items():
        if (str(b.get("span", "")).startswith("serve.hop.")
                and sid in ends
                and isinstance(b.get("t"), (int, float))):
            flows[_flow_root(sid)].append(b)
    for flow_id, (root, hops) in enumerate(sorted(flows.items(),
                                                  key=lambda kv: str(kv[0])),
                                           start=1):
        if len(hops) < 2:
            continue  # a single-hop request has no arrow to draw
        hops.sort(key=lambda b: b["t"])
        for i, b in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            ev = {"name": "request", "cat": "flow", "ph": ph,
                  "id": flow_id, "ts": us(b["t"]),
                  "pid": _chrome_pid(b), "tid": depth(b.get("id"))}
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice, not the next
            out.append(ev)
    out.sort(key=lambda ev: ev["ts"])
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": label}} for pid, label in sorted(pids.items())]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


# --------------------------------------------------------------- waterfall

def clock_shifts(events: list[dict]) -> dict:
    """Per-pid clock edges from the ``clock-offset`` events.

    Each event was recorded by ``pid`` after pinging ``peer_pid`` and
    says: at one instant, the peer's clock read ``offset_ms`` more than
    ours, give or take ``err_ms`` (half the round trip — the classic
    Cristian bound).  Returns ``{(recorder, peer): (offset_ms, err_ms)}``
    keeping the last (most-converged EWMA) sample per pair.
    """
    edges: dict = {}
    for e in events:
        if e["event"] != "clock-offset":
            continue
        a, b = e.get("pid"), e.get("peer_pid")
        off = e.get("offset_ms")
        if not isinstance(a, int) or not isinstance(b, int) or \
                not isinstance(off, (int, float)):
            continue
        err = e.get("err_ms")
        edges[(a, b)] = (float(off),
                         float(err) if isinstance(err, (int, float)) else 0.0)
    return edges


def resolve_shifts(edges: dict, ref_pid: int) -> dict:
    """BFS the pid graph: ``{pid: (shift_ms, err_ms)}`` where adding
    ``shift_ms`` to a timestamp taken on ``pid``'s clock expresses it on
    ``ref_pid``'s timeline, with ``err_ms`` the accumulated uncertainty
    along the path (errors add — each synced link contributes its own
    half-RTT bound).  Unreachable pids are absent: the caller renders
    them unshifted and flags the missing alignment."""
    adj = defaultdict(list)
    for (a, b), (off, err) in edges.items():
        # recorded: t_peer = t_rec + off.  Walking rec→peer converts a
        # peer timestamp back by -off; peer→rec converts forward by +off.
        adj[a].append((b, -off, err))
        adj[b].append((a, +off, err))
    shifts = {ref_pid: (0.0, 0.0)}
    frontier = [ref_pid]
    while frontier:
        nxt = []
        for u in frontier:
            s, se = shifts[u]
            for v, d, err in adj[u]:
                if v not in shifts:
                    shifts[v] = (s + d, se + err)
                    nxt.append(v)
        frontier = nxt
    return shifts


def build_waterfalls(events: list[dict], key: str,
                     ref_pid: int | None = None) -> dict:
    """Reassemble per-request waterfalls from the ``serve.hop.*`` spans.

    ``key`` matches a hop's ``rid`` tag or a trace id.  Each matching
    hop's parent chain is walked to its root (the client hop when the
    client's sink file is included); every hop sharing that root is one
    request, rendered as one tree.  Rids restart per process — the same
    number can name different requests in the client, front-tier, and
    replica domains — so distinct roots become distinct trees and the
    caller picks by trace id.

    Timestamps are shifted onto ``ref_pid``'s clock (default: the pid
    that recorded the front tier's ``serve.hop.route``, else the root's
    recorder) via the ``clock-offset`` peer graph, carrying the
    accumulated ± error bound so hop ordering claims are honest about
    alignment uncertainty.
    """
    begins = {e["id"]: e for e in events
              if e["event"] == "span-begin" and e.get("id") is not None}
    ends = {e["id"]: e for e in events
            if e["event"] == "span-end" and e.get("id") is not None}

    def rec(sid):
        return begins.get(sid) or ends.get(sid)

    hop_ids = [sid for sid in {**begins, **ends}
               if str(rec(sid).get("span", "")).startswith("serve.hop.")]

    def root_of(sid):
        seen = set()
        while sid not in seen:
            seen.add(sid)
            parent = (rec(sid) or {}).get("parent")
            if parent is None or rec(parent) is None:
                return sid
            sid = parent
        return sid

    seeds = [sid for sid in hop_ids
             if str(rec(sid).get("rid")) == key
             or str(rec(sid).get("trace")) == key]
    roots = sorted({root_of(s) for s in seeds}, key=str)
    by_root = defaultdict(list)
    for sid in hop_ids:
        by_root[root_of(sid)].append(sid)

    trees = []
    for root in roots:
        members = by_root[root]
        pids = sorted({rec(s).get("pid") for s in members
                       if isinstance(rec(s).get("pid"), int)})
        traces = sorted({str(rec(s).get("trace")) for s in members
                         if rec(s).get("trace")})
        route = [s for s in members
                 if rec(s).get("span") == "serve.hop.route"]
        ref = ref_pid if ref_pid is not None else \
            rec((route or [root])[0]).get("pid")
        shifts = resolve_shifts(clock_shifts(events), ref) \
            if isinstance(ref, int) else {}

        hops = {}
        for sid in members:
            b, e = begins.get(sid), ends.get(sid)
            r = b or e
            pid = r.get("pid")
            shift, err = shifts.get(pid, (0.0, 0.0))
            ms = e.get("ms") if e and isinstance(e.get("ms"),
                                                 (int, float)) else None
            # an end without its begin (ring truncation) still has a
            # start: rewind its local ms from the end stamp
            t = b.get("t") if b else (
                e["t"] - (ms or 0.0) / 1e3
                if isinstance(e.get("t"), (int, float)) else None)
            hops[sid] = {
                "span": r.get("span"), "id": sid,
                "parent": r.get("parent"), "pid": pid,
                "rank": r.get("rank"), "rid": r.get("rid"),
                "start_s": (t + shift / 1e3
                            if isinstance(t, (int, float)) else None),
                "dur_ms": ms,
                "err_ms": round(err, 3),
                "aligned": pid in shifts,
                "open": e is None,
                "requeued": bool((e or {}).get("requeued")),
            }
        t0 = min((h["start_s"] for h in hops.values()
                  if h["start_s"] is not None), default=0.0)
        for h in hops.values():
            h["start_ms"] = (round((h.pop("start_s") - t0) * 1e3, 3)
                             if h["start_s"] is not None
                             else h.pop("start_s"))
        children = defaultdict(list)
        for sid, h in hops.items():
            if sid != root:
                children[h["parent"]].append(sid)
        for kids in children.values():
            kids.sort(key=lambda s: (hops[s]["start_ms"]
                                     if hops[s]["start_ms"] is not None
                                     else float("inf"), str(s)))

        ordered = []

        def _walk(sid, depth):
            h = dict(hops[sid])
            h["depth"] = depth
            ordered.append(h)
            for kid in children.get(sid, []):
                _walk(kid, depth + 1)

        _walk(root, 0)
        trees.append({"root": root, "ref_pid": ref, "pids": pids,
                      "trace_ids": traces, "hops": ordered})
    return {"key": key, "trees": trees}


def render_waterfall(doc: dict, out=None) -> None:
    """Text tree, one per matched request: indented hops with their
    start on the reference timeline (± the clock-alignment bound when
    the hop lives on a synced remote pid), duration, and the markers
    that matter for the zero-loss story (``REQUEUED``, ``[open]``)."""
    w = (out or sys.stdout).write
    if not doc["trees"]:
        w(f"no serve.hop.* spans match rid/trace {doc['key']!r}\n")
        return
    for tree in doc["trees"]:
        w(f"request {doc['key']} trace={','.join(tree['trace_ids']) or '-'} "
          f"({len(tree['hops'])} hop(s) across {len(tree['pids'])} pid(s), "
          f"timeline of pid {tree['ref_pid']})\n")
        for h in tree["hops"]:
            start = (f"+{h['start_ms']:.3f}" if h["start_ms"] is not None
                     else "?")
            err = ""
            if h["err_ms"] and h["aligned"]:
                err = f" ±{h['err_ms']:.3f}"
            elif not h["aligned"]:
                err = " ±?"  # pid never clock-synced against the ref
            dur = (f" {h['dur_ms']:.3f}ms" if h["dur_ms"] is not None
                   else " [open]")
            tags = f" rid={h['rid']}" if h.get("rid") is not None else ""
            if h["requeued"]:
                tags += " REQUEUED"
            w(f"  {'  ' * h['depth']}{h['span']:<{max(2, 24 - 2 * h['depth'])}}"
              f" pid {h['pid']} {start}{err}ms{dur}{tags}\n")


# ------------------------------------------------------------------ flight

def load_metrics_snapshot(path: str) -> dict:
    """A metrics snapshot from any of the formats that carry one: a
    snapshot JSON document, a flight dump (its ``metrics`` key), or a
    trace JSONL file (the last ``metrics-snapshot`` event).  Raises
    TraceParseError when none is found."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                doc = json.load(f)
            except ValueError:
                doc = None
            if isinstance(doc, dict):
                if "counters" in doc or "histograms" in doc:
                    return doc
                if isinstance(doc.get("metrics"), dict):
                    return doc["metrics"]
    snaps = [e for e in load_events([path]) if e["event"] == "metrics-snapshot"]
    if not snaps:
        raise TraceParseError(f"{path}: no metrics snapshot found")
    return snaps[-1].get("metrics", {})


def load_flight(path: str) -> dict:
    """Parse a flight dump; TraceParseError when it isn't one."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError as e:
        raise TraceParseError(f"{path}: {e}") from e
    if not isinstance(doc, dict) or "reason" not in doc or \
            not isinstance(doc.get("events"), list):
        raise TraceParseError(f"{path}: not a flight dump")
    return doc


def render_flight(doc: dict, out=None) -> None:
    """Human rendering of a flight dump: header, traceback, open spans,
    the pre-crash timeline, and a metrics digest."""
    out = out or sys.stdout
    w = out.write
    plat = doc.get("platform") or {}
    w(f"flight dump: reason {doc.get('reason')!r}, pid {doc.get('pid')}, "
      f"rank {doc.get('rank')}, incarnation {doc.get('incarnation')}\n")
    w(f"  platform: python {plat.get('python')}, jax {plat.get('jax')}, "
      f"{plat.get('platform')}\n")
    if plat.get("argv"):
        w(f"  argv: {' '.join(str(a) for a in plat['argv'])}\n")
    if doc.get("traceback"):
        w("traceback:\n")
        for line in str(doc["traceback"]).rstrip().split("\n"):
            w(f"  {line}\n")
    open_spans = doc.get("open_spans") or []
    if open_spans:
        w(f"open spans at death ({len(open_spans)}):\n")
        for s in open_spans:
            w(f"  {s.get('span')} (id {s.get('id')}, "
              f"parent {s.get('parent')})\n")
    health = doc.get("health")
    if health:
        w(f"last device health: "
          f"{'HEALTHY' if health.get('healthy') else 'UNHEALTHY'} "
          f"({health.get('platform')}, {health.get('device_count')} "
          f"device(s), probe {health.get('probe_ms')} ms)\n")
        for st in health.get("stages") or []:
            if not st.get("ok"):
                w(f"  failed stage {st.get('stage')}: "
                  f"{st.get('detail')}\n")
    forensics = doc.get("forensics") or {}
    for label, frame in (("open forensics stage", forensics.get("open")),
                         ("last failed stage",
                          forensics.get("last_failed"))):
        if frame:
            tail = (f" ({frame['error']})" if frame.get("error") else "")
            w(f"{label}: {frame.get('op')} @ {frame.get('stage')}{tail}\n")
    numeric = doc.get("numerics") or {}
    if numeric.get("budget") or numeric.get("demoted"):
        demoted = numeric.get("demoted") or []
        w(f"last numeric drift: {len(numeric.get('budget') or {})} "
          f"budgeted rung(s), {len(demoted)} demoted"
          + (f" ({', '.join(demoted)})" if demoted else "") + "\n")
        for key, st in sorted((numeric.get("budget") or {}).items()):
            w(f"  {key}: {st.get('samples')} sample(s), "
              f"{st.get('over')} over, last rel_l2 "
              f"{st.get('last_rel_l2')}"
              + (" BURNING" if st.get("burning") else "") + "\n")
    events = doc.get("events") or []
    w(f"last {len(events)} event(s) before death:\n")
    render_timeline(events, out=out)
    m = doc.get("metrics") or {}
    w(f"metrics at death: {len(m.get('counters', {}))} counters, "
      f"{len(m.get('gauges', {}))} gauges, "
      f"{len(m.get('histograms', {}))} histograms\n")


# -------------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cme213_tpu trace",
        description="analyze CME213_TRACE_FILE JSON-lines traces")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser("summary", help="aggregate report over traces")
    p_sum.add_argument("files", nargs="+")
    p_sum.add_argument("--require", default="",
                       help="comma-separated span OR event names that must "
                            "appear (a span must have completed; an event "
                            "name — e.g. conformance-failed — must occur "
                            "at least once); exit 1 otherwise — the CI "
                            "gate")
    p_sum.add_argument("--json", action="store_true",
                       help="print the aggregates as one JSON document "
                            "instead of the text report (what CI and the "
                            "regression gate consume)")
    p_sum.add_argument("--single-trace", action="store_true",
                       help="exit 1 unless the records carry exactly one "
                            "trace id — the cross-process propagation gate")

    p_tl = sub.add_parser("timeline", help="chronological event listing")
    p_tl.add_argument("files", nargs="+")
    p_tl.add_argument("--all", action="store_true",
                      help="include span-begin records")

    for p in (p_sum, p_tl):
        p.add_argument("--since", default=None,
                       help="only records newer than this: a number is "
                            "milliseconds back from the newest record, "
                            "else an ISO-8601 timestamp")
        p.add_argument("--last", type=int, default=None,
                       help="only the N newest records (after --since)")

    p_mg = sub.add_parser("merge", help="interleave per-rank files")
    p_mg.add_argument("files", nargs="+")
    p_mg.add_argument("--timeline", action="store_true",
                      help="render the merged gang timeline instead of "
                           "JSON lines")
    p_mg.add_argument("--out", default=None,
                      help="write merged JSON lines here (default stdout)")
    p_mg.add_argument("--follow", action="store_true",
                      help="keep tailing the files (live collector) "
                           "instead of one post-mortem pass; globs are "
                           "re-expanded as ranks appear")
    p_mg.add_argument("--interval", type=float, default=0.5,
                      help="seconds between polls in --follow mode")
    p_mg.add_argument("--max-seconds", type=float, default=None,
                      help="stop following after this many seconds")

    p_ex = sub.add_parser("export", help="Chrome trace-event JSON "
                                         "(Perfetto / chrome://tracing)")
    p_ex.add_argument("files", nargs="+")
    p_ex.add_argument("--out", default=None,
                      help="write the Chrome trace here (default stdout)")

    p_wf = sub.add_parser("waterfall",
                          help="one request's hops as a clock-aligned "
                               "cross-process tree")
    p_wf.add_argument("rid", help="request id (any hop's rid tag) or a "
                                  "trace id")
    p_wf.add_argument("files", nargs="+")
    p_wf.add_argument("--json", action="store_true",
                      help="print the waterfall document instead of the "
                           "text tree (what the CI gate consumes)")
    p_wf.add_argument("--ref-pid", type=int, default=None,
                      help="pid whose clock anchors the timeline "
                           "(default: the front tier's — the pid that "
                           "recorded serve.hop.route)")

    p_rg = sub.add_parser("regress", help="bench regression gate "
                                          "(cme213_tpu.bench.regress)")
    p_rg.add_argument("args", nargs=argparse.REMAINDER,
                      help="arguments forwarded to bench.regress")

    p_mt = sub.add_parser("metrics", help="Prometheus text exposition of "
                                          "a metrics snapshot")
    p_mt.add_argument("file",
                      help="trace JSONL (last metrics-snapshot event), "
                           "snapshot JSON, or flight dump")

    p_fl = sub.add_parser("flight", help="render a crash flight dump")
    p_fl.add_argument("file", help="flight-<pid>-<ts>.json dump")

    # intercepted before argparse: REMAINDER won't swallow leading flags
    # (``trace regress --fresh ...``), and regress owns its own CLI
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "regress":
        from .bench.regress import main as regress_main

        return regress_main(list(argv[1:]))

    args = ap.parse_args(argv)
    if args.cmd == "metrics":
        from .core.metrics import render_prometheus
        try:
            snap = load_metrics_snapshot(args.file)
        except (TraceParseError, OSError) as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2
        sys.stdout.write(render_prometheus(snap))
        return 0
    if args.cmd == "flight":
        try:
            doc = load_flight(args.file)
        except (TraceParseError, OSError) as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2
        render_flight(doc)
        return 0
    if args.cmd == "merge" and args.follow:
        # live mode rides the collector's tailer (rotation/truncation-
        # safe, partial-line tolerant) instead of the strict parser: a
        # torn tail line is pending input here, not a corrupt trace
        from .core.collector import Collector

        coll = Collector(args.files)
        deadline = (time.monotonic() + args.max_seconds
                    if args.max_seconds else None)
        out = open(args.out, "w") if args.out else sys.stdout
        t0: float | None = None
        try:
            while True:
                for e in coll.poll():
                    t = e.get("t")
                    if t0 is None and isinstance(t, (int, float)):
                        t0 = t
                    if args.timeline:
                        out.write(_timeline_line(e, t0 or 0.0))
                    else:
                        rec = {k: v for k, v in e.items() if k != "_file"}
                        out.write(json.dumps(rec, default=str) + "\n")
                    out.flush()
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        finally:
            if args.out:
                out.close()
        return 0
    try:
        events = load_events(args.files,
                             tolerate_torn=(args.cmd == "waterfall"))
    except (TraceParseError, OSError) as e:
        print(f"trace: {e}", file=sys.stderr)
        return 2
    if args.cmd in ("summary", "timeline"):
        try:
            events = window_events(events, since=args.since, last=args.last)
        except ValueError as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2

    if args.cmd == "waterfall":
        doc = build_waterfalls(events, args.rid, ref_pid=args.ref_pid)
        if args.json:
            print(json.dumps(doc, indent=2, default=str))
        else:
            render_waterfall(doc)
        return 0 if doc["trees"] else 1
    if args.cmd == "export":
        doc = to_chrome_trace(events)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(doc, f, default=str)
        else:
            json.dump(doc, sys.stdout, default=str)
            sys.stdout.write("\n")
        return 0
    if args.cmd == "summary":
        import io

        text = io.StringIO() if args.json else None
        agg = summarize(events, out=text)
        if args.json:
            print(json.dumps(agg, indent=2, default=str))
        required = [s.strip() for s in args.require.split(",") if s.strip()]
        missing = [s for s in required
                   if s not in agg["spans"] and not agg["counts"].get(s)]
        if missing:
            print(f"trace: required span(s)/event(s) never appeared: "
                  f"{', '.join(missing)}", file=sys.stderr)
            return 1
        if args.single_trace and len(agg["trace_ids"]) != 1:
            print(f"trace: expected exactly one trace id, saw "
                  f"{len(agg['trace_ids'])} "
                  f"({', '.join(agg['trace_ids']) or '-'})",
                  file=sys.stderr)
            return 1
        return 0
    if args.cmd == "timeline":
        render_timeline(events, show_all=args.all)
        return 0
    # merge
    if args.timeline:
        render_timeline(events)
        return 0
    out = open(args.out, "w") if args.out else sys.stdout
    try:
        for e in events:
            rec = {k: v for k, v in e.items() if k != "_file"}
            out.write(json.dumps(rec, default=str) + "\n")
    finally:
        if args.out:
            out.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
