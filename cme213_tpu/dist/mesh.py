"""Device-mesh construction — the process-topology layer.

Replaces the reference's MPI rank topology discovery (1-D stripes
``hw/hw5/programming/2dHeat.cpp:284-307``; 2-D √P×√P grids ``:308-377``;
launched by ``mpirun -np`` over Torque nodes, ``hw/hw5/PA5_Handout.pdf`` §4)
with ``jax.sharding.Mesh`` axes.  Neighbor relationships are not stored — they
are expressed per-step as ``lax.ppermute`` permutations along mesh axes (see
``halo.py``), with physical-boundary sides detected by ``lax.axis_index``
instead of the reference's "-1 neighbor" sentinel.

On real hardware the mesh axes ride ICI; multi-host extends the same code via
``jax.distributed.initialize`` + the global device list (ICI-vs-DCN placement
is mesh-axis assignment, SURVEY §2.8).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

from ..config import GridMethod


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the jax version straddle.

    ``jax.shard_map`` became a top-level API after 0.4.x; on 0.4.37 (this
    environment) the implementation lives in ``jax.experimental.shard_map``
    and spells the replication-check kwarg ``check_rep`` instead of
    ``check_vma``.  All sharded entry points in this package route through
    this wrapper so the straddle lives in exactly one place.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def make_mesh_1d(num_devices: int | None = None, axis: str = "y",
                 devices=None) -> Mesh:
    """1-D stripe decomposition mesh (hw5 gridMethod=1)."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), (axis,))


def make_mesh_2d(py: int, px: int, axes: tuple[str, str] = ("y", "x"),
                 devices=None) -> Mesh:
    """2-D block decomposition mesh (hw5 gridMethod=2).

    The reference asserts a square rank count (``2dHeat.cpp:316``); here any
    py×px rectangle is allowed — the constraint was an MPI bookkeeping
    simplification, not a capability.
    """
    devices = list(devices if devices is not None else jax.devices())
    if py * px > len(devices):
        raise ValueError(f"need {py * px} devices, have {len(devices)}")
    return Mesh(np.array(devices[: py * px]).reshape(py, px), axes)


def mesh_for_method(method: GridMethod, num_devices: int | None = None,
                    devices=None) -> Mesh:
    """Build the mesh a ``SimParams.grid_method`` asks for.  For BLOCKS_2D a
    near-square py×px factorization of the device count is chosen (square
    when the count is a perfect square, matching the reference's √P×√P)."""
    devices = list(devices if devices is not None else jax.devices())
    n = num_devices or len(devices)
    if method == GridMethod.STRIPES_1D:
        return make_mesh_1d(n, devices=devices)
    py = int(math.isqrt(n))
    while n % py:
        py -= 1
    return make_mesh_2d(py, n // py, devices=devices)
