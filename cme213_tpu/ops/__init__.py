from .stencil import STENCIL_COEFFS, stencil_interior, heat_step, run_heat
from .elementwise import (
    shift_cipher,
    shift_cipher_packed,
    vigenere_shift,
    vigenere_unshift,
)

__all__ = [
    "STENCIL_COEFFS",
    "stencil_interior",
    "heat_step",
    "run_heat",
    "shift_cipher",
    "shift_cipher_packed",
    "vigenere_shift",
    "vigenere_unshift",
]
