"""Capture device-profile evidence for the overlap (P11) and CA schemes.

SURVEY §7: overlap "must be verified from profiles, not assumed".  This
runs the 2000² order-8 distributed step on the available mesh (mesh=1 on
the single bench chip), records sync-vs-async-vs-CA wall-clock rows
(the analog of the hw5 measured table, ``hw/hw5/programming/data.ods``),
and wraps one async run in ``core.trace.device_trace`` so the XPlane
trace shows whether the ppermute halo exchange and the interior compute
actually overlap.

usage: tpu_overlap_trace.py [outdir] [--size=N] [--order=K] [--iters=I]
(the flags exist so tests can drive the script end-to-end at toy sizes;
the capture runs the defaults)

Writes ``<outdir>/overlap_sync_vs_async.csv`` and an XPlane trace under
``<outdir>/xplane_overlap/``.  One TPU client at a time — run only from
the capture watcher or after /tmp/tpu_capture_done exists.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

from cme213_tpu.core.platform import apply_platform_env

apply_platform_env()

import jax  # noqa: E402

from cme213_tpu.bench.sweeps import write_csv  # noqa: E402
from cme213_tpu.config import GridMethod, SimParams  # noqa: E402
from cme213_tpu.core.trace import device_trace  # noqa: E402
from cme213_tpu.dist import (mesh_for_method,  # noqa: E402
                             prepare_distributed_heat)


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    opts = dict(a[2:].split("=", 1) for a in sys.argv[1:]
                if a.startswith("--"))
    out = args[0] if args else "bench_results"
    os.makedirs(out, exist_ok=True)
    size = int(opts.get("size", 2000))
    order = int(opts.get("order", 8))
    iters = int(opts.get("iters", 100))
    nd = len(jax.devices())
    mesh = mesh_for_method(GridMethod.STRIPES_1D, nd)
    print(f"devices={nd} platform={jax.devices()[0].platform}")

    rows = []
    traced = None
    for requested, overlap, k in (("sync", False, 1), ("async", True, 1),
                                  ("ca-k4", False, 4)):
        p = SimParams(nx=size, ny=size, order=order, iters=iters)
        iterate, used_overlap, used_k = prepare_distributed_heat(
            p, mesh, overlap=overlap, steps_per_exchange=k)
        iterate()                   # warmup: same iters → same executable
        secs, _ = iterate()
        scheme = (f"ca-k{used_k}" if used_k > 1
                  else "async" if used_overlap else "sync")
        rows.append({"devices": nd, "size": size, "order": order,
                     "iters": iters, "requested": requested,
                     "scheme": scheme, "seconds": round(secs, 4)})
        print(rows[-1])
        if requested == "async":
            traced = iterate

    tracedir = os.path.join(out, "xplane_overlap")
    with device_trace(tracedir):
        traced()
    # the trace is the deliverable: fail loudly if nothing was written —
    # and only then write the CSV, so a drop mid-trace leaves no CSV and
    # the capture's sweep_attempted classifier retries the whole step
    # next window instead of reading the CSV as "already captured"
    found = [os.path.join(r, f) for r, _, fs in os.walk(tracedir)
             for f in fs if f.endswith(".xplane.pb")]
    print(f"xplane files: {found}")
    if not found:
        return 1
    write_csv(rows, os.path.join(out, "overlap_sync_vs_async.csv"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
