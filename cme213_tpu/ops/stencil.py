"""Central-difference heat stencils, orders 2/4/8 — pure-XLA path.

TPU-native redesign of the reference's stencil triple (CPU ``stencil2/4/8``,
``hw/hw2/programming/2dHeat.cu:361-386``; global-memory GPU kernel
``gpuGlobal`` ``:431-461``; shared-memory tiled kernel ``gpuShared``
``:466-515``).  Instead of per-thread gather loops, the stencil is expressed
as a sum of statically-shifted interior slices — XLA fuses the whole
expression into one pass over the grid, which plays the role the cooperative
shared-memory tile staging played on the GPU (the VMEM tiling is done by the
compiler; an explicit Pallas-tiled variant lives in ``stencil_pallas.py``).

Coefficients (1,-2,1 / -1,16,-30,16,-1 / -9,128,-1008,8064,-14350,…) match the
reference exactly.  The update is

    u' = u + xcfl * Dxx(u) + ycfl * Dyy(u)

applied to the interior only; the Dirichlet border band is never written
(reference kernels only write interior threads).

Iteration uses ``lax.fori_loop`` threading the grid functionally — the
TPU-native form of the reference's ping-pong double buffering (``swapState`` +
two concatenated grid copies, ``2dHeat.cu:243-245,530-560``); XLA buffer
donation gives the same two-buffer memory behavior.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# order -> 1-D second-derivative coefficients over offsets [-b..b]
STENCIL_COEFFS = {
    2: (1.0, -2.0, 1.0),
    4: (-1.0, 16.0, -30.0, 16.0, -1.0),
    8: (-9.0, 128.0, -1008.0, 8064.0, -14350.0, 8064.0, -1008.0, 128.0, -9.0),
}

BORDER_FOR_ORDER = {2: 1, 4: 2, 8: 4}


def flops_per_point(order: int) -> int:
    """Flops per grid point per timestep for the given stencil order.

    Per axis: one multiply per tap and one add per accumulation
    (``taps - 1``); the combine ``u + xcfl*accx + ycfl*accy`` adds 2
    multiplies and 2 adds.  Shared by ``bench.py`` and the sweep drivers so
    GF/s columns stay correct across orders (order 8 → the reference's
    38 flops/point accounting, ``hw/hw2/programming/data/data.ods``).
    """
    taps = len(STENCIL_COEFFS[order])
    return 2 * taps + 2 * (taps - 1) + 4


def stencil_interior(u: jnp.ndarray, order: int, xcfl, ycfl) -> jnp.ndarray:
    """New interior values (ny, nx) from a full halo grid (gy, gx)."""
    coeffs = STENCIL_COEFFS[order]
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    ny, nx = gy - 2 * b, gx - 2 * b
    center = u[b:-b, b:-b]
    xcfl = jnp.asarray(xcfl, u.dtype)
    ycfl = jnp.asarray(ycfl, u.dtype)

    accx = jnp.zeros_like(center)
    accy = jnp.zeros_like(center)
    for k, c in enumerate(coeffs):
        c = jnp.asarray(c, u.dtype)
        accx = accx + c * lax.slice(u, (b, k), (b + ny, k + nx))
        accy = accy + c * lax.slice(u, (k, b), (k + ny, b + nx))
    return center + xcfl * accx + ycfl * accy


def stencil_interior_conv(u: jnp.ndarray, order: int, xcfl,
                          ycfl) -> jnp.ndarray:
    """Same update as ``stencil_interior`` expressed as ONE 2-D convolution
    with a cross-shaped (2b+1)² kernel — a single XLA op the TPU backend
    can tile with full input reuse (each input element read once per
    output tile, vs once per tap in the fused shifted-slice formulation).

    Rounding: the conv accumulates taps in a different order (and may use
    the MXU's f32 decomposition), so results agree with the slice path to
    ~1e-6 relative, not bitwise — bench/unchecked paths only.
    """
    coeffs = STENCIL_COEFFS[order]
    b = BORDER_FOR_ORDER[order]
    w = 2 * b + 1
    kern = jnp.zeros((w, w), u.dtype)
    cx = jnp.asarray(coeffs, u.dtype) * jnp.asarray(xcfl, u.dtype)
    cy = jnp.asarray(coeffs, u.dtype) * jnp.asarray(ycfl, u.dtype)
    kern = kern.at[b, :].add(cx)
    kern = kern.at[:, b].add(cy)
    kern = kern.at[b, b].add(jnp.asarray(1.0, u.dtype))  # the center term
    out = lax.conv_general_dilated(
        u[None, None], kern[None, None], window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        # full f32 accumulation: the TPU default decomposes f32 convs into
        # bf16 MXU passes, which the 9..14350 coefficient spread would
        # amplify to ~1e-3 relative error
        precision=lax.Precision.HIGHEST)
    return out[0, 0]


@partial(jax.jit, static_argnames=("order", "iters"), donate_argnums=(0,))
def run_heat_conv(u: jnp.ndarray, iters: int, order: int, xcfl,
                  ycfl) -> jnp.ndarray:
    """``iters`` timesteps of the conv-formulated stencil."""
    b = BORDER_FOR_ORDER[order]

    def body(_, g):
        return g.at[b:-b, b:-b].set(
            stencil_interior_conv(g, order, xcfl, ycfl))

    return lax.fori_loop(0, iters, body, u)


@partial(jax.jit,
         static_argnames=("order", "iters", "xcfl", "ycfl", "bc", "k"),
         donate_argnums=(0,))
def run_heat_roll(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                  bc: tuple[float, float, float, float],
                  k: int = 1) -> jnp.ndarray:
    """``iters`` timesteps, full-grid roll formulation.

    Same arithmetic as ``run_heat`` but with no interior slicing and no
    dynamic-update-slice: every tap is a circular ``jnp.roll`` of the whole
    grid and the Dirichlet bands are re-imposed by iota masking (rows then
    columns, the reference's band order, ``2dHeat.cu:326-344``).  Rolled
    wrap-around only ever lands inside the masked border band, so results
    are bitwise-identical to ``run_heat`` — but the whole update is one
    scatter-free elementwise expression XLA can fuse into a single pass.

    ``k`` unrolls that many sub-steps inside each loop body (``iters`` must
    divide by ``k``) — temporal blocking at the XLA level: the compiler
    sees the k-step chain as one fusion candidate, the structural analog of
    the Pallas pipeline kernel's fused sub-steps but with the tiling left
    to XLA.  Results are bitwise-identical for every ``k``.
    """
    coeffs = STENCIL_COEFFS[order]
    b = BORDER_FOR_ORDER[order]
    gy, gx = u.shape
    if iters % k != 0:
        raise ValueError(f"iters={iters} must divide by k={k}")
    bc_top, bc_left, bc_bottom, bc_right = bc
    rows = jax.lax.broadcasted_iota(jnp.int32, (gy, gx), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (gy, gx), 1)

    def substep(g):
        dtype = g.dtype
        accx = jnp.zeros_like(g)
        accy = jnp.zeros_like(g)
        for kk, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * jnp.roll(g, b - kk, 1)
            accy = accy + c * jnp.roll(g, b - kk, 0)
        new = (g + jnp.asarray(xcfl, dtype) * accx
               + jnp.asarray(ycfl, dtype) * accy)
        new = jnp.where(rows < b, jnp.asarray(bc_bottom, dtype), new)
        new = jnp.where(rows >= gy - b, jnp.asarray(bc_top, dtype), new)
        new = jnp.where(cols < b, jnp.asarray(bc_left, dtype), new)
        new = jnp.where(cols >= gx - b, jnp.asarray(bc_right, dtype), new)
        return new

    def body(_, g):
        for _ in range(k):
            g = substep(g)
        return g

    return lax.fori_loop(0, iters // k, body, u)


@partial(jax.jit, static_argnames=("order",), donate_argnums=(0,))
def heat_step(u: jnp.ndarray, order: int, xcfl, ycfl) -> jnp.ndarray:
    """One timestep: write the stencil result into the interior."""
    b = BORDER_FOR_ORDER[order]
    return u.at[b:-b, b:-b].set(stencil_interior(u, order, xcfl, ycfl))


@partial(jax.jit, static_argnames=("order", "iters"), donate_argnums=(0,))
def run_heat(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl) -> jnp.ndarray:
    """``iters`` timesteps under ``lax.fori_loop`` (functional ping-pong)."""
    b = BORDER_FOR_ORDER[order]

    def body(_, g):
        return g.at[b:-b, b:-b].set(stencil_interior(g, order, xcfl, ycfl))

    return lax.fori_loop(0, iters, body, u)
