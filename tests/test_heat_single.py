import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid, interior, save_grid_to_file
from cme213_tpu.ops import heat_step, run_heat
from cme213_tpu.verify import check_ulp, golden


@pytest.mark.parametrize("order", [2, 4, 8])
def test_heat_matches_golden(order):
    p = SimParams(nx=24, ny=20, order=order, iters=10)
    u0 = make_initial_grid(p, dtype=jnp.float32)
    ref = golden.host_heat(np.asarray(u0), p.iters, order, p.xcfl, p.ycfl)
    out = run_heat(u0, p.iters, order, p.xcfl, p.ycfl)
    res = check_ulp(ref, np.asarray(out), max_ulps=10, label=f"heat-{order}")
    assert res, res.message


def test_heat_double_precision():
    """Double variant (reference hw2 double 4th-order benchmark row)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    try:
        p = SimParams(nx=24, ny=20, order=4, iters=10)
        u0 = make_initial_grid(p, dtype=jnp.float64)
        assert u0.dtype == jnp.float64
        ref = golden.host_heat(np.asarray(u0), p.iters, 4, p.xcfl, p.ycfl)
        out = run_heat(u0, p.iters, 4, p.xcfl, p.ycfl)
        # XLA contracts multiply-adds into FMAs, so bitwise ULP equality with
        # the numpy golden doesn't hold in f64; use the relative-error
        # tolerance model the reference applies to accumulating float
        # pipelines (SURVEY §4, Final_Report tolerance 1e-6..1e-3 — far looser
        # than the 1e-12 demanded here).
        from cme213_tpu.verify import relative_linf_error

        assert relative_linf_error(ref, np.asarray(out)) < 1e-12
    finally:
        jax.config.update("jax_enable_x64", False)


def test_initial_grid_bc_layout():
    p = SimParams(nx=10, ny=8, order=4, bc_top=1.0, bc_left=2.0,
                  bc_bottom=3.0, bc_right=4.0, ic=7.0)
    g = np.asarray(make_initial_grid(p))
    b = p.border_size
    # interior
    assert (interior(jnp.asarray(g), b) == 7.0).all()
    # left/right bands overwrite corners (reference BC loop order,
    # 2dHeat.cu:326-344)
    assert (g[:, :b] == 2.0).all()
    assert (g[:, -b:] == 4.0).all()
    assert (g[0, b:-b] == 3.0).all()       # bottom row (y=0)
    assert (g[-1, b:-b] == 1.0).all()      # top row
    assert g.shape == (p.gy, p.gx)


def test_single_step_only_touches_interior():
    p = SimParams(nx=12, ny=12, order=8)
    u0 = make_initial_grid(p)
    u1 = heat_step(jnp.array(u0), 8, p.xcfl, p.ycfl)
    b = p.border_size
    u0n, u1n = np.asarray(u0), np.asarray(u1)
    mask = np.ones_like(u0n, dtype=bool)
    mask[b:-b, b:-b] = False
    assert (u0n[mask] == u1n[mask]).all()


def test_uniform_interior_stays_uniform_order2():
    # with uniform ic and matching bc, the laplacian is zero everywhere
    p = SimParams(nx=10, ny=10, order=2, ic=3.0, bc_top=3.0, bc_left=3.0,
                  bc_bottom=3.0, bc_right=3.0, iters=5)
    u0 = make_initial_grid(p)
    out = run_heat(u0, 5, 2, p.xcfl, p.ycfl)
    np.testing.assert_allclose(np.asarray(out), 3.0, rtol=0, atol=1e-6)


def test_save_grid_to_file(tmp_path):
    p = SimParams(nx=6, ny=6, order=2)
    u0 = make_initial_grid(p)
    f = tmp_path / "grid_init.txt"
    save_grid_to_file(u0, str(f))
    lines = [l for l in f.read_text().splitlines() if l.strip()]
    assert len(lines) == p.gy
    # top row printed first = bc_top in interior columns
    first = lines[0].split()
    assert float(first[1]) == p.bc_top


def test_conv_stencil_matches_slices():
    import jax.numpy as jnp
    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat, run_heat_conv

    for order in (2, 4, 8):
        p = SimParams(nx=96, ny=64, order=order, iters=6)
        u0 = make_initial_grid(p, dtype=jnp.float32)
        a = np.asarray(run_heat(jnp.array(u0), 6, order, p.xcfl, p.ycfl))
        b = np.asarray(run_heat_conv(jnp.array(u0), 6, order, p.xcfl, p.ycfl))
        np.testing.assert_allclose(b, a, rtol=5e-6, atol=5e-6)
