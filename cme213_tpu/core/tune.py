"""Measured autotuning of dispatch statics — ROADMAP item 2(b).

The reference hand-tuned every performance-critical constant: hw2's
shared-memory tile shapes and hw_final's warp-scan block sizes were
chosen by a human sweeping configurations offline.  This repo inherited
those choices as hard-coded statics — the blocked-scan threshold, heat
``tile_y``/``tile_x``, serve batch widths — which, since the program
cache keys on statics (``core/programs.py``), are exactly the knobs an
empirical autotuner can turn: the classic ATLAS/FFTW mold, searching a
small registered candidate space per op and persisting the measured
winner for dispatch to consume.

The search protocol, per candidate:

1. **conformance-gate** (``core/conformance.py``) BEFORE any timing — a
   candidate whose probe diverges from the op's reference (including a
   ``wrong:<op>``-faulted probe) is excluded and can never win;
2. **build + warm** through ``core/programs.py`` so compiles happen in
   the usual ``<op>.compile`` spans, outside the timed region;
3. **median-of-k** measured runs, each under a ``tune.trial`` span whose
   declared cost (``core/roofline.py``) puts ``achieved_gbs``/
   ``pct_peak``/``bound`` on the span-end record.

Winners persist to a JSON disk cache (``CME213_TUNE_CACHE``) keyed
``device_kind|op|shape_class|dtype`` — the same pattern as
``CME213_CONFORMANCE_CACHE`` — and dispatch sites (``run_spmv_scan``,
``run_heat_resilient``, the serve batcher, ``segmented_scan``'s size
dispatch) resolve their statics as tuned-or-default via :func:`resolve`,
with ``CME213_TUNE=0`` as the kill-switch restoring every built-in
default.  Ties break deterministically: the first-registered candidate
wins, and the measurement clock is injectable so the tie-break is
testable without real timers.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from . import metrics, roofline
from .resilience import Clock
from .trace import record_event, span

#: on-disk winner cache (JSON) shared across processes
CACHE_ENV = "CME213_TUNE_CACHE"
#: kill-switch: ``CME213_TUNE=0`` makes every dispatch use its defaults
KILL_ENV = "CME213_TUNE"

#: measured runs per candidate (median taken)
TRIAL_RUNS = 5


class TuneError(RuntimeError):
    """No conformant candidate survived the gate for an op."""


@dataclass(frozen=True)
class Candidate:
    """One point in an op's search space.

    ``gate`` is a zero-arg callable returning truthy when the candidate's
    conformance probe passes (run BEFORE timing; ``None`` marks the op's
    reference configuration, which needs no probe).  ``build`` returns
    the zero-arg measured runner — building goes through
    ``core/programs.py`` so the compile is warmed outside the timed
    region.  ``scale`` divides the measured time for scoring (a serve
    candidate batching ``w`` requests scores per-request)."""

    label: str
    statics: dict
    build: object
    gate: object = None
    cost: roofline.Cost | None = None
    scale: float = 1.0


@dataclass(frozen=True)
class TuneSpace:
    """An op's registered candidate space for one shape class."""

    op: str
    shape_class: str
    dtype: str
    candidates: tuple
    cost: roofline.Cost | None = None


# key string -> winner record — the steady-state dict lookup
_WINNERS: dict[str, dict] = {}
_DISK_LOADED = False


def reset() -> None:
    """Forget every cached winner (tests); the disk cache is re-read."""
    global _DISK_LOADED
    _WINNERS.clear()
    _DISK_LOADED = False


def enabled() -> bool:
    """The kill-switch: ``CME213_TUNE=0`` disables all tuned lookups."""
    return os.environ.get(KILL_ENV, "1") != "0"


def cache_path() -> str | None:
    """The on-disk winner cache location, if one is configured."""
    return os.environ.get(CACHE_ENV) or None


def _cache_key(op: str, shape_class: str, dtype: str,
               device: str | None = None) -> str:
    return f"{device or roofline.detect_device()}|{op}|{shape_class}|{dtype}"


def _load_disk_cache() -> None:
    """Merge persisted winners (non-destructively: in-process wins)."""
    global _DISK_LOADED
    _DISK_LOADED = True
    path = os.environ.get(CACHE_ENV)
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return  # a corrupt cache must never break dispatch; defaults serve
    for key, rec in data.items():
        if (len(key.split("|")) != 4 or not isinstance(rec, dict)
                or not isinstance(rec.get("statics"), dict)):
            continue
        _WINNERS.setdefault(key, dict(rec))


def _persist(key: str, rec: dict) -> None:
    path = os.environ.get(CACHE_ENV)
    if not path:
        return
    try:
        data = {}
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    data[key] = rec
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # a read-only cache dir must never block dispatch


def store(op: str, shape_class: str, dtype: str, *, statics: dict,
          candidate: str, ms: float, gbs: float) -> dict:
    """Record (and persist) the measured winner for a tuning key."""
    rec = {"statics": dict(statics), "candidate": candidate,
           "ms": round(float(ms), 3), "gbs": round(float(gbs), 3)}
    key = _cache_key(op, shape_class, dtype)
    _WINNERS[key] = rec
    _persist(key, rec)
    return rec


def lookup(op: str, shape_class: str, dtype: str = "float32") -> dict | None:
    """The winner record for a key, or None (also None when the
    kill-switch is set).  Pure — no events; dispatch sites that should
    count tuned-vs-default traffic go through :func:`resolve`."""
    if not enabled():
        return None
    if not _DISK_LOADED:
        _load_disk_cache()
    return _WINNERS.get(_cache_key(op, shape_class, dtype))


def resolve(op: str, shape_class: str, dtype: str = "float32",
            **defaults) -> dict:
    """Tuned-or-default statics for a dispatch site.

    Returns ``defaults`` updated with the winning statics for the key —
    restricted to keys the call site declares, so a stale cache entry
    can never inject statics dispatch doesn't understand.  Counts every
    consult (``tune.hits``/``tune.defaults``) and records a
    ``tune-hit``/``tune-default`` event, the tuned-vs-default split the
    ``trace summary`` tuning section reports."""
    rec = lookup(op, shape_class, dtype)
    if rec is None:
        metrics.counter("tune.defaults").inc()
        record_event("tune-default", op=op, shape_class=shape_class)
        return dict(defaults)
    tuned = {k: v for k, v in rec["statics"].items() if k in defaults}
    metrics.counter("tune.hits").inc()
    record_event("tune-hit", op=op, shape_class=shape_class,
                 statics=json.dumps(tuned, sort_keys=True))
    return {**defaults, **tuned}


def entries() -> dict:
    """Merged snapshot (disk + in-process) of every winner record."""
    if not _DISK_LOADED:
        _load_disk_cache()
    return dict(_WINNERS)


def clear() -> int:
    """Drop every winner, in-process and on disk; returns the count."""
    global _DISK_LOADED
    if not _DISK_LOADED:
        _load_disk_cache()
    n = len(_WINNERS)
    reset()
    _DISK_LOADED = True  # do not resurrect the file we are clearing
    path = os.environ.get(CACHE_ENV)
    if path and os.path.exists(path):
        try:
            os.remove(path)
        except OSError:
            pass
    return n


# ------------------------------------------------------------------ search

def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _measure(op: str, shape_class: str, cand: Candidate, runner,
             clock: Clock, runs: int) -> float:
    """Median-of-``runs`` scored milliseconds for one warmed candidate,
    each run under a ``tune.trial`` span carrying roofline attribution."""
    times = []
    for _ in range(max(1, runs)):
        t0 = clock.now()
        with span("tune.trial", op=op, shape_class=shape_class,
                  candidate=cand.label) as sp:
            if cand.cost is not None:
                sp.roofline(cand.cost.nbytes, cand.cost.flops)
            out = runner()
            sp.block(out)
        times.append((clock.now() - t0) * 1e3 / cand.scale)
    return _median(times)


def run_space(space: TuneSpace, *, clock: Clock | None = None,
              runs: int = TRIAL_RUNS, persist: bool = True) -> dict:
    """Gate, warm, and time every candidate; pick and record the winner.

    Deterministic: candidates are visited in registration order and only
    a STRICTLY faster median displaces the incumbent, so exact ties go
    to the earlier candidate whatever dict/scheduler noise does.  The
    measurement clock is injectable (``core/resilience.Clock``) so the
    tie-break is testable."""
    clock = clock or Clock()
    trials = []
    best = None
    for cand in space.candidates:
        cost = cand.cost or space.cost
        c = Candidate(cand.label, cand.statics, cand.build, cand.gate,
                      cost, cand.scale)
        try:
            ok = True if cand.gate is None else bool(cand.gate())
        except Exception as e:  # noqa: BLE001 — a dying probe is a veto
            ok = False
            trials.append({"candidate": cand.label, "ok": False,
                           "ms": -1.0, "gbs": -1.0,
                           "error": f"{type(e).__name__}: {e}"})
        if not ok:
            metrics.counter("tune.rejected").inc()
            record_event("tune-trial", op=space.op,
                         shape_class=space.shape_class,
                         candidate=cand.label, ok=False, ms=-1.0, gbs=-1.0)
            if not trials or trials[-1].get("candidate") != cand.label:
                trials.append({"candidate": cand.label, "ok": False,
                               "ms": -1.0, "gbs": -1.0,
                               "error": "conformance probe failed"})
            continue
        try:
            runner = cand.build()
            ms = _measure(space.op, space.shape_class, c, runner, clock,
                          runs)
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # build or run (Mosaic lowering, OOM, injected fail) is
            # excluded, not fatal: the search banks what it measured
            metrics.counter("tune.rejected").inc()
            record_event("tune-trial", op=space.op,
                         shape_class=space.shape_class,
                         candidate=cand.label, ok=False, ms=-1.0, gbs=-1.0)
            trials.append({"candidate": cand.label, "ok": False,
                           "ms": -1.0, "gbs": -1.0,
                           "error": f"{type(e).__name__}: {e}"})
            continue
        gbs = cost.gbs(ms * cand.scale) if (cost and ms > 0) else 0.0
        metrics.counter("tune.trials").inc()
        record_event("tune-trial", op=space.op,
                     shape_class=space.shape_class, candidate=cand.label,
                     ok=True, ms=round(ms, 3), gbs=round(gbs, 3))
        trial = {"candidate": cand.label, "ok": True,
                 "ms": round(ms, 3), "gbs": round(gbs, 3),
                 "statics": dict(cand.statics)}
        trials.append(trial)
        if best is None or ms < best["ms"]:
            best = {"candidate": cand.label, "ms": ms, "gbs": gbs,
                    "statics": dict(cand.statics)}
    if best is None:
        raise TuneError(
            f"tune: no conformant candidate for {space.op} "
            f"[{space.shape_class}/{space.dtype}] "
            f"({len(space.candidates)} gated out)")
    metrics.counter("tune.winners").inc()
    record_event("tune-winner", op=space.op, shape_class=space.shape_class,
                 dtype=space.dtype, candidate=best["candidate"],
                 statics=json.dumps(best["statics"], sort_keys=True),
                 gbs=round(best["gbs"], 3))
    if persist:
        store(space.op, space.shape_class, space.dtype,
              statics=best["statics"], candidate=best["candidate"],
              ms=best["ms"], gbs=best["gbs"])
    return {"op": space.op, "shape_class": space.shape_class,
            "dtype": space.dtype, "device": roofline.detect_device(),
            "winner": {"candidate": best["candidate"],
                       "statics": best["statics"],
                       "ms": round(best["ms"], 3),
                       "gbs": round(best["gbs"], 3)},
            "trials": trials}


# ------------------------------------------------------- candidate spaces

#: blocked-scan block sizes searched for spmv_scan (the hw_final
#: warp-scan sizing axis, minus the warp)
SPMV_BLOCK_SIZES = (1024, 2048, 4096, 8192, 16384)
#: flat/blocked crossover thresholds searched for segmented_scan's auto
#: dispatch (current hard default: 2^16)
SCAN_THRESHOLDS = (1 << 14, 1 << 16, 1 << 18)
#: serve batch widths searched per bucket
SERVE_WIDTHS = (1, 2, 4, 8)


def _spmv_space(n: int = 1 << 20, iters: int = 8,
                dtype: str = "float32",
                block_sizes=SPMV_BLOCK_SIZES) -> TuneSpace:
    """spmv_scan: flat log-sweep vs blocked O(n) at each block size.

    The winner's statics (``kernel`` and, for blocked, ``block_size``)
    are what ``run_spmv_scan``'s auto dispatch resolves."""
    import jax.numpy as jnp

    from ..apps import spmv_scan as app
    from ..core import conformance, programs
    from ..ops.segmented import head_flags_from_starts

    jdt = np.dtype(dtype)
    nc = programs.canonical_size(n)
    prob = app.generate_problem(nc, p=max(2, nc // 64), q=max(2, nc // 2),
                                iters=iters, seed=0)
    cost = roofline.spmv_scan_cost(nc, iters, dtype=dtype)
    probe = app._probe_problem()
    probe_xx = jnp.asarray(probe.xx, jdt)
    probe_flags = head_flags_from_starts(jnp.asarray(probe.s[:-1]), probe.n)
    probe_starts = jnp.asarray(probe.s[:-1])

    def probe_run(kernel, block_size=None):
        def thunk():
            fn = app._program(kernel, probe.n, probe.iters, jdt,
                              p=probe.p, block_size=block_size)
            return np.asarray(fn(jnp.asarray(probe.a, jdt), probe_xx,
                                 probe_flags, probe_starts))
        return thunk

    def gate(label, kernel, block_size=None):
        return lambda: conformance.check(
            "spmv_scan", label, shape_class=np.dtype(dtype).name,
            candidate=probe_run(kernel, block_size),
            reference=probe_run("flat"),
            rel_l2=app.CONFORMANCE_REL_L2[kernel]).ok

    xx = jnp.asarray(prob.xx, jdt)
    flags = head_flags_from_starts(jnp.asarray(prob.s[:-1]), prob.n)
    starts = jnp.asarray(prob.s[:-1])

    def build(kernel, block_size=None):
        def builder():
            fn = app._program(kernel, prob.n, prob.iters, jdt, p=prob.p,
                              block_size=block_size)
            # _iterate donates the value buffer, so every timed run pays
            # the same fresh host->device upload — identical constant
            # overhead for every candidate, so the ranking is unbiased
            return lambda: fn(jnp.asarray(prob.a, jdt), xx, flags, starts)
        return builder

    cands = [Candidate("flat", {"kernel": "flat"}, build("flat"))]
    for bs in block_sizes:
        cands.append(Candidate(
            f"blocked/bs{bs}", {"kernel": "blocked", "block_size": bs},
            build("blocked", bs), gate(f"blocked/bs{bs}", "blocked", bs)))
    return TuneSpace("spmv_scan", f"n{nc}", np.dtype(dtype).name,
                     tuple(cands), cost)


def _crossover_space(n: int | None = None, dtype: str = "float32",
                     thresholds=SCAN_THRESHOLDS) -> TuneSpace:
    """segmented_scan: the flat/blocked crossover threshold, measured at
    the contested size (the default threshold itself).  Each candidate
    IS a threshold; what gets timed is the kernel that threshold selects
    at the probe size, so the measurement answers "which side of the
    boundary should this size fall on"."""
    import jax
    import jax.numpy as jnp

    from ..core import conformance, programs
    from ..ops import segmented

    n0 = programs.canonical_size(n or segmented.BLOCKED_SCAN_THRESHOLD)
    jdt = np.dtype(dtype)
    rng = np.random.default_rng(0)
    v_host = rng.uniform(-1, 1, n0).astype(dtype)
    f_host = (rng.uniform(size=n0) < (1 / 64)).astype(np.int32)
    f_host[0] = 1
    v, f = jnp.asarray(v_host), jnp.asarray(f_host)
    cost = roofline.Cost(n0 * (2 * jdt.itemsize + 4), 0)
    pn = 4096
    pv = jnp.asarray(v_host[:pn])
    pf = jnp.asarray(f_host[:pn]).at[0].set(1)

    def kernel_for(thr):
        return "blocked" if n0 >= thr else "flat"

    def program(kernel):
        def build():
            fn = {"flat": segmented.segmented_scan_flat,
                  "blocked": segmented.segmented_scan_blocked}[kernel]
            return jax.jit(lambda vv, ff: fn(vv, ff))

        def warm(fn):
            jax.block_until_ready(fn(jnp.zeros(n0, jdt),
                                     jnp.zeros(n0, jnp.int32)))

        return programs.get("segmented_scan", kernel, f"n{n0}", build,
                            dtype=np.dtype(dtype).name, warm=warm)

    def gate(label, kernel):
        if kernel == "flat":
            return None  # the reference form
        return lambda: conformance.check(
            "segmented_scan", label, shape_class=f"n{pn}",
            candidate=lambda: np.asarray(
                segmented.segmented_scan_blocked(pv, pf)),
            reference=lambda: np.asarray(
                segmented.segmented_scan_flat(pv, pf)),
            rel_l2=1e-5).ok

    cands = []
    for thr in thresholds:
        kernel = kernel_for(thr)
        label = f"thr{thr}/{kernel}"
        cands.append(Candidate(
            label, {"threshold": thr},
            (lambda k: lambda: (lambda fn: (lambda: fn(v, f)))(
                program(k)))(kernel),
            gate(label, kernel)))
    return TuneSpace("segmented_scan", "crossover", np.dtype(dtype).name,
                     tuple(cands), cost)


def _heat_space(gy: int = 64, gx: int = 64, order: int = 2, k: int = 1,
                iters: int = 4, dtype: str = "float32",
                tile_ys=None, tile_x: int | None = None,
                interpret: bool | None = None) -> TuneSpace:
    """heat: pipeline ``tile_y`` (×``tile_x``) per order×k class, against
    the XLA baseline.  Off-TPU the Pallas candidates time in interpret
    mode, so on CPU the XLA baseline wins and the winner's statics are
    empty — honest "defaults are best here" — while on TPU the same
    space searches real tile shapes."""
    import jax
    import jax.numpy as jnp

    from ..config import SimParams
    from ..grid import make_initial_grid
    from ..ops import run_heat
    from ..ops import stencil_pipeline as sp_mod

    p = SimParams(nx=gx, ny=gy, order=order, iters=iters)
    u0 = np.asarray(make_initial_grid(p, dtype=np.dtype(dtype)))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    tx = tile_x or min(512, gx)
    if tile_ys is None:
        picked = sp_mod.pick_pipeline_tile(gy, k, order, width=gx)
        tile_ys = sorted({t for t in (picked // 2, picked, picked * 2)
                          if 0 < t <= gy})
    cost = roofline.heat_cost(gy, gx, order=order, iters=iters, dtype=dtype)
    shape_class = f"{gy}x{gx}/order{order}/k{k}"

    def build_xla():
        def runner():
            return run_heat(jnp.asarray(u0), iters, order, p.xcfl, p.ycfl)
        runner()  # warm: compile lands outside the timed region
        return runner

    def build_pipeline(ty):
        def builder():
            def runner():
                # BOTH tile knobs pinned, so run_heat_resilient never
                # consults the very cache this search is filling
                res = sp_mod.run_heat_resilient(
                    jnp.asarray(u0), iters, order, p.xcfl, p.ycfl, p.bc,
                    k=k, tile_y=ty, tile_x=tx, interpret=interpret)
                return res.value
            runner()  # warm: compile + conformance probe outside timing
            return runner
        return builder

    def gate(ty):
        # rung-level probe (pipeline vs XLA, bitwise) via the stencil
        # module's own conformance gate — keyed per order×k, so the
        # whole tile ladder shares one verdict and a wrong: fault on
        # the probe vetoes every pipeline candidate at once
        return lambda: sp_mod._heat_conformance_gate(
            order, k, tx, interpret)("pipeline")

    cands = [Candidate("xla", {}, build_xla)]
    for ty in tile_ys:
        cands.append(Candidate(
            f"pipeline/ty{ty}/tx{tx}", {"tile_y": int(ty), "tile_x": int(tx)},
            build_pipeline(int(ty)), gate(int(ty))))
    return TuneSpace("heat", shape_class, np.dtype(dtype).name,
                     tuple(cands), cost)


def _sort_space(n: int = 1 << 20, dtype: str = "uint32",
                kernels=("lax", "radix", "bitonic")) -> TuneSpace:
    """sort: radix vs bitonic vs the ``lax.sort`` baseline at one size —
    the crossover data ``sort_auto``'s dispatch consumes."""
    import jax
    import jax.numpy as jnp

    from ..core import conformance, programs
    # NOT ``from ..ops import sort``: the package re-exports the sort
    # *function* under that name, shadowing the submodule attribute
    from ..ops.sort import bitonic_sort, radix_sort
    from ..ops.sort import sort as lax_sort

    nc = programs.canonical_size(n)
    rng = np.random.default_rng(0)
    keys_host = rng.integers(0, 2 ** 32, nc, dtype=np.uint32)
    keys = jnp.asarray(keys_host)
    pn = min(nc, 4096)
    probe_host = keys_host[:pn]
    probe = jnp.asarray(probe_host)
    probe_ref = np.sort(probe_host)
    fns = {"lax": lambda ks: lax_sort(ks),
           "radix": lambda ks: radix_sort(ks),
           "bitonic": lambda ks: bitonic_sort(ks)}

    def program(kernel):
        def build():
            return fns[kernel]

        def warm(fn):
            jax.block_until_ready(fn(jnp.zeros(nc, jnp.uint32)))

        return programs.get("sort", kernel, f"n{nc}", build,
                            dtype="uint32", warm=warm)

    def gate(kernel):
        if kernel == "lax":
            return None  # the reference rung
        return lambda: conformance.check(
            "sort", kernel, shape_class=f"n{pn}",
            candidate=lambda: np.asarray(fns[kernel](probe)),
            reference=lambda: probe_ref).ok

    cands = []
    for kernel in kernels:
        kind = "radix" if kernel == "radix" else "merge"
        cands.append(Candidate(
            kernel, {"kernel": kernel},
            (lambda kn: lambda: (lambda fn: (lambda: fn(keys)))(
                program(kn)))(kernel),
            gate(kernel),
            cost=roofline.sort_cost(nc, kind=kind)))
    return TuneSpace("sort", f"n{nc}", "uint32", tuple(cands))


def _serve_space(mix_op: str = "spmv", widths=SERVE_WIDTHS,
                 max_batch: int = 8, seed: int = 0) -> TuneSpace:
    """serve: batch width per bucket — each width w runs a w-wide batch
    through the op's adapter (scored per request), gated on lane 0 being
    bitwise-equal to the width-1 solve (the vmap-batching contract)."""
    from ..core import conformance
    from ..serve import loadgen
    from ..serve.workloads import ADAPTERS

    spec = loadgen.build_mix(mix_op, requests=1, seed=seed)[0]
    adapter = ADAPTERS[spec.op]
    payload = spec.payload
    shape_class = adapter.shape_class(payload)
    rung = adapter.rungs()[0]
    op = f"serve.{adapter.op}"

    def gate(w):
        if w == 1:
            return None  # the reference width
        return lambda: conformance.check(
            op, f"b{w}", shape_class=shape_class,
            candidate=lambda: np.asarray(
                adapter.run_batch([payload] * w, rung)[0]),
            reference=lambda: np.asarray(
                adapter.run_batch([payload], rung)[0])).ok

    def build(w):
        def builder():
            batch = [payload] * w
            runner = lambda: adapter.run_batch(batch, rung)[0]
            runner()  # warm: the batch program compiles outside timing
            return runner
        return builder

    cands = [Candidate(f"b{w}", {"max_batch": int(w)}, build(w), gate(w),
                       scale=float(w))
             for w in widths if 1 <= w <= max_batch]
    return TuneSpace(op, shape_class, "float32", tuple(cands))


#: op name -> space builder; ``run`` routes here.  ``serve.<mix-op>``
#: names route through the serve builder (e.g. ``serve.spmv``).
SPACES = {
    "spmv_scan": _spmv_space,
    "segmented_scan": _crossover_space,
    "heat": _heat_space,
    "sort": _sort_space,
}


def build_space(op: str, **kw) -> TuneSpace:
    """The registered candidate space for ``op`` (``serve.<mix-op>``
    routes to the serve-width builder)."""
    if op.startswith("serve."):
        return _serve_space(op.split(".", 1)[1], **kw)
    if op not in SPACES:
        raise TuneError(f"no candidate space registered for {op!r} "
                        f"(have {sorted(SPACES)} + serve.<op>)")
    return SPACES[op](**kw)


def run(op: str, *, clock: Clock | None = None, runs: int = TRIAL_RUNS,
        persist: bool = True, **kw) -> dict:
    """Search ``op``'s candidate space and persist the winner."""
    return run_space(build_space(op, **kw), clock=clock, runs=runs,
                     persist=persist)
