"""Serving front end: bounded-queue backpressure, deadline rejection,
circuit-breaker arcs, batch-bucket conformance, graceful degradation,
and the load generator — all CPU-deterministic (fault clauses for
failures, ``VirtualClock`` for every timing decision).

The conformance tests pin the serving tier's core contract: a request
served from a batch is BITWISE-equal to the same solve run alone —
batching is a scheduling decision, never a numerics decision.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.serve import (
    ADMISSION,
    DEADLINE,
    FAILED,
    OK,
    QUEUE_FULL,
    SHED,
    CipherRequest,
    Server,
    SolveResult,
)
from cme213_tpu.serve.loadgen import build_mix, run_load, slo_report


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    yield
    faults.reset()
    metrics.reset()


class EchoAdapter:
    """Minimal adapter for scheduler-behaviour tests: payloads are
    (class_key, value) tuples, two rungs both echoing the values —
    failure comes from ``fail:serve.echo.<rung>`` clauses, never the
    workload itself."""

    op = "echo"

    def __init__(self):
        self.calls: list[tuple[str, int]] = []  # (rung, batch size)

    def shape_class(self, payload, coarse: bool = False) -> str:
        return "any" if coarse else payload[0]

    def rungs(self, degraded: bool = False):
        return ("fast",) if degraded else ("fast", "safe")

    def run_batch(self, payloads, rung: str, coarse: bool = False):
        self.calls.append((rung, len(payloads)))
        return [p[1] for p in payloads]

    def preflight_builder(self, payloads, rung, coarse=False):
        return None


def echo_server(**kw):
    adapter = EchoAdapter()
    kw.setdefault("clock", VirtualClock())
    server = Server(adapters={"echo": adapter}, **kw)
    return server, adapter


# ----------------------------------------------------- queue backpressure

def test_queue_full_sheds_newest_keeps_fifo():
    server, adapter = echo_server(capacity=2, max_batch=2)
    r0 = server.submit("echo", ("k", 10))
    r1 = server.submit("echo", ("k", 11))
    shed = server.submit("echo", ("k", 12))   # over capacity: refused NOW
    assert isinstance(r0, int) and isinstance(r1, int)
    assert isinstance(shed, SolveResult)
    assert shed.status == SHED and shed.reason == QUEUE_FULL
    ev = trace.events("queue-shed")
    assert ev and ev[-1]["reason"] == QUEUE_FULL and ev[-1]["depth"] == 2
    assert metrics.counter(f"serve.shed.{QUEUE_FULL}").value == 1

    served = server.drain()                    # admitted requests unharmed
    assert [r.rid for r in served] == [r0, r1]  # FIFO order retained
    assert [r.value for r in served] == [10, 11]
    assert all(r.status == OK for r in served)


def test_unknown_op_rejected():
    server, _ = echo_server()
    with pytest.raises(ValueError, match="unknown op"):
        server.submit("nope", None)


# --------------------------------------------------------------- deadlines

def test_expired_deadline_rejected_before_execution():
    clock = VirtualClock()
    server, adapter = echo_server(clock=clock)
    rid = server.submit("echo", ("k", 1), deadline_ms=50)
    assert isinstance(rid, int)
    clock.advance(0.2)                         # deadline long gone
    results = server.step()
    assert [r.status for r in results] == [SHED]
    assert results[0].reason == DEADLINE
    assert adapter.calls == []                 # never executed late
    ev = trace.events("deadline-shed")
    assert ev[-1]["rid"] == rid and ev[-1]["late_ms"] >= 150
    assert metrics.counter(f"serve.shed.{DEADLINE}").value == 1


def test_nonpositive_deadline_shed_at_submit():
    server, adapter = echo_server()
    out = server.submit("echo", ("k", 1), deadline_ms=0)
    assert isinstance(out, SolveResult)
    assert out.status == SHED and out.reason == DEADLINE
    assert adapter.calls == []


def test_deadline_met_serves():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock)
    server.submit("echo", ("k", 7), deadline_ms=100)
    clock.advance(0.05)                        # inside the deadline
    results = server.step()
    assert [r.status for r in results] == [OK]
    assert results[0].value == 7


def test_deadline_sweep_spares_undated_requests():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=4)
    server.submit("echo", ("k", 1), deadline_ms=10)
    keep = server.submit("echo", ("k", 2))     # no deadline
    clock.advance(1.0)
    results = server.step()
    by_status = {r.status for r in results}
    assert by_status == {SHED, OK}
    ok = [r for r in results if r.status == OK]
    assert [r.rid for r in ok] == [keep]


# ---------------------------------------------------------- batch buckets

def test_batches_form_within_shape_class_only():
    server, adapter = echo_server(max_batch=8)
    for v in range(3):
        server.submit("echo", ("A", v))
    for v in range(2):
        server.submit("echo", ("B", 10 + v))
    first = server.step()                      # head bucket: all of A
    assert [r.value for r in first] == [0, 1, 2]
    assert adapter.calls == [("fast", 3)]
    second = server.step()                     # then B
    assert [r.value for r in second] == [10, 11]
    ev = trace.events("batch-executed")
    assert [e["size"] for e in ev] == [3, 2]
    assert ev[0]["shape_class"] == "A" and ev[1]["shape_class"] == "B"


def test_max_batch_caps_batch_size():
    server, adapter = echo_server(max_batch=2)
    for v in range(5):
        server.submit("echo", ("k", v))
    server.drain()
    assert [size for _, size in adapter.calls] == [2, 2, 1]
    ev = trace.events("batch-executed")
    assert ev[0]["occupancy"] == 1.0 and ev[-1]["occupancy"] == 0.5


# -------------------------------------------------------- circuit breaker

def test_breaker_open_routes_around_then_recovers():
    """The full arc: 3 classified failures open the circuit for the fast
    rung; while open, requests are routed to the safe rung WITHOUT
    executing the broken one; after the cooldown a half-open probe runs
    the healed rung and closes the circuit."""
    clock = VirtualClock()
    server, adapter = echo_server(
        clock=clock, max_batch=1, breaker_threshold=3,
        breaker_cooldown_s=10.0)
    with faults.injected("fail:serve.echo.fast:1:3"):
        for v in range(3):                     # three faulted serves
            server.submit("echo", ("k", v))
            (res,) = server.step()
            assert res.status == OK and res.rung == "safe"
        ev = trace.events("breaker-open")
        assert ev[-1]["op"] == "serve.echo" and ev[-1]["rung"] == "fast"
        assert ev[-1]["failures"] == 3

        # circuit open: fast is skipped (not executed, not a demotion)
        server.submit("echo", ("k", 99))
        (res,) = server.step()
        assert res.rung == "safe"
        assert metrics.counter("breaker.skipped").value == 1
        assert ("fast", 1) not in adapter.calls  # fast never ran at all

        # past the cooldown: half-open probe; the fault budget (3) is
        # exhausted, so the probe succeeds and the circuit closes
        clock.advance(11.0)
        server.submit("echo", ("k", 100))
        (res,) = server.step()
        assert res.rung == "fast"
        assert trace.events("breaker-half-open")
        assert trace.events("breaker-close")
    # healthy ever after
    server.submit("echo", ("k", 101))
    (res,) = server.step()
    assert res.rung == "fast" and res.value == 101


def test_breaker_halfopen_failure_reopens():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=1, breaker_threshold=2,
                            breaker_cooldown_s=5.0)
    with faults.injected("fail:serve.echo.fast:1:5"):
        for v in range(2):
            server.submit("echo", ("k", v))
            server.step()
        assert len(trace.events("breaker-open")) == 1
        clock.advance(6.0)
        server.submit("echo", ("k", 2))
        (res,) = server.step()                 # probe fails -> reopen
        assert res.status == OK and res.rung == "safe"
        assert len(trace.events("breaker-open")) == 2
        assert len(trace.events("breaker-close")) == 0


def test_breaker_events_feed_slo_report():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=1, breaker_threshold=2,
                            breaker_cooldown_s=1e9)
    before = metrics.snapshot()
    t0 = clock.now()
    with faults.injected("fail:serve.echo.fast:1:2"):
        results = []
        for v in range(3):
            server.submit("echo", ("k", v))
            results.extend(server.step())
    report = slo_report({"results": results, "elapsed_s": clock.now() - t0},
                        before, metrics.snapshot())
    assert report["served"] == 3 and report["breaker"]["opened"] == 1
    assert report["breaker"]["skipped"] == 1
    assert report["demotions"] == 2


# ------------------------------------------------------- slow: straggler

def test_slow_clause_stretches_latency_on_server_clock():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=1)
    with faults.injected("slow:serve.echo:250"):
        server.submit("echo", ("k", 1))
        (res,) = server.step()
    assert res.status == OK
    assert res.latency_ms >= 250                # straggler visible in SLO
    ev = trace.events("fault-injected")
    assert any(e["kind"] == "slow" and e["op"] == "serve.echo" for e in ev)


def test_slow_clause_can_push_next_request_past_deadline():
    """Injected straggler latency advances the same clock deadlines are
    judged by: a deadline that would have been met is now missed — the
    exact production failure mode (slow device -> deadline misses), fully
    deterministic."""
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=1)
    with faults.injected("slow:serve.echo:500:1"):
        server.submit("echo", ("k", 1))
        server.submit("echo", ("k", 2), deadline_ms=100)
        first = server.step()                  # pays the 500ms straggler
        second = server.step()                 # sweep finds rid 2 expired
    assert [r.status for r in first] == [OK]
    assert [(r.status, r.reason) for r in second] == [(SHED, DEADLINE)]


# ---------------------------------------------------- admission (budget)

class RejectingAdapter(EchoAdapter):
    """Echo adapter whose preflight admits nothing — the shape class that
    can never fit the budget."""

    def preflight_builder(self, payloads, rung, coarse=False):
        from cme213_tpu.core.admission import Decision

        def preflight_at(size):
            return Decision(False, 10**9, 1, "over budget")

        return preflight_at


class ShrinkingAdapter(EchoAdapter):
    """Preflight admits at most 2 lanes — forces batch shrink, leftover
    stays queued."""

    def preflight_builder(self, payloads, rung, coarse=False):
        from cme213_tpu.core.admission import Decision

        def preflight_at(size):
            return Decision(size <= 2, size, 2, f"size {size}")

        return preflight_at


def test_admission_rejection_sheds_with_reason(monkeypatch):
    monkeypatch.setenv("CME213_MEMORY_BUDGET", "1")
    adapter = RejectingAdapter()
    server = Server(adapters={"echo": adapter}, clock=VirtualClock(),
                    max_batch=4)
    for v in range(3):
        server.submit("echo", ("k", v))
    results = server.step()
    assert [r.status for r in results] == [SHED] * 3
    assert all(r.reason == ADMISSION for r in results)
    assert adapter.calls == []
    assert metrics.counter(f"serve.shed.{ADMISSION}").value == 3
    assert len(server.queue) == 0              # nothing left to spin on


def test_admission_shrinks_batch_keeps_overflow_queued(monkeypatch):
    monkeypatch.setenv("CME213_MEMORY_BUDGET", "1")
    adapter = ShrinkingAdapter()
    server = Server(adapters={"echo": adapter}, clock=VirtualClock(),
                    max_batch=4)
    for v in range(4):
        server.submit("echo", ("k", v))
    first = server.step()
    assert [r.value for r in first] == [0, 1]  # admitted pair served
    assert len(server.queue) == 2              # overflow queued, not shed
    second = server.step()
    assert [r.value for r in second] == [2, 3]
    assert all(size <= 2 for _, size in adapter.calls)


# ------------------------------------------------- graceful degradation

def test_degraded_mode_enters_exits_with_hysteresis():
    clock = VirtualClock()
    server, adapter = echo_server(clock=clock, max_batch=2,
                                  degrade_depth=3)
    for v in range(4):                         # depth 4 >= 3: degrade
        server.submit("echo", ("A" if v % 2 else "B", v))
    first = server.step()
    assert server.degraded
    # degraded keying is coarse ("any"): A and B merge into one batch
    assert adapter.calls[-1] == ("fast", 2)
    assert all(r.degraded for r in first)
    assert trace.events("span-begin")          # degraded-mode span emitted
    assert any(e["span"] == "degraded-mode"
               for e in trace.events("span-begin"))
    assert metrics.gauge("serve.degraded").value == 1

    server.step()                              # depth 2 > 3//2: still in
    assert server.degraded
    server.step()                              # depth 0 <= 1: exits
    assert not server.degraded
    assert metrics.gauge("serve.degraded").value == 0


def test_degraded_mode_uses_degraded_ladder():
    server, adapter = echo_server(max_batch=8, degrade_depth=2)
    with faults.injected("fail:serve.echo.fast:1:1"):
        for v in range(3):
            server.submit("echo", ("k", v))
        results = server.step()
    # degraded ladder is ("fast",) only: the injected failure has no safe
    # rung to demote to, so the batch FAILS (predictable over peak-fast)
    assert all(r.status == "failed" for r in results)


# --------------------------------------- batch conformance: real workloads

def _spmv_serial(prob, rung):
    from cme213_tpu.apps.spmv_scan import _iterate
    from cme213_tpu.ops.segmented import head_flags_from_starts

    flags = head_flags_from_starts(jnp.asarray(prob.s[:-1]), prob.n)
    return np.asarray(_iterate(
        jnp.asarray(prob.a, jnp.float32), jnp.asarray(prob.xx, jnp.float32),
        flags, prob.iters, scan=rung))


def test_spmv_batch_bitwise_equal_serial():
    from cme213_tpu.apps.spmv_scan import generate_problem

    probs = [generate_problem(256, p=6, q=64, iters=5, seed=s)
             for s in range(4)]
    server = Server(max_batch=4, clock=VirtualClock())
    for p in probs:
        server.submit("spmv_scan", p)
    results = server.drain()
    assert [r.status for r in results] == [OK] * 4
    assert results[0].batch_size == 4          # one program served all
    for r, p in zip(results, probs):
        np.testing.assert_array_equal(r.value, _spmv_serial(p, r.rung))


def test_heat_batch_bitwise_equal_serial():
    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops.stencil import run_heat

    params = [SimParams(nx=16, ny=16, order=2, iters=3, alpha=a)
              for a in (0.5, 1.0, 2.0)]
    server = Server(max_batch=4, clock=VirtualClock())
    for p in params:
        server.submit("heat", p)
    results = server.drain()
    assert [r.status for r in results] == [OK] * 3
    assert results[0].batch_size == 3
    for r, p in zip(results, params):
        u0 = jnp.asarray(np.asarray(make_initial_grid(p)))
        ref = np.asarray(run_heat(u0, p.iters, p.order, p.xcfl, p.ycfl))
        np.testing.assert_array_equal(r.value, ref)


def test_cipher_batch_bitwise_equal_serial_both_rungs():
    from cme213_tpu.ops.elementwise import shift_cipher, shift_cipher_packed

    rng = np.random.default_rng(3)
    reqs = [CipherRequest(rng.integers(0, 200, 256).astype(np.uint8),
                          int(rng.integers(0, 56))) for _ in range(5)]
    server = Server(max_batch=8, clock=VirtualClock())
    for q in reqs:
        server.submit("cipher", q)
    results = server.drain()
    assert [r.status for r in results] == [OK] * 5
    for r, q in zip(results, reqs):
        t = jnp.asarray(q.text)
        np.testing.assert_array_equal(r.value,
                                      np.asarray(shift_cipher_packed(t, q.shift)))
        np.testing.assert_array_equal(r.value,
                                      np.asarray(shift_cipher(t, q.shift)))


def test_cipher_breaker_fallback_bitwise_equal():
    """The acceptance arc on a real workload: fail the packed rung until
    its circuit opens, verify the bytes rung serves BITWISE-equal
    results, then recover via the half-open probe."""
    clock = VirtualClock()
    server = Server(max_batch=1, clock=clock, breaker_threshold=3,
                    breaker_cooldown_s=10.0)
    rng = np.random.default_rng(7)
    reqs = [CipherRequest(rng.integers(0, 200, 128).astype(np.uint8), s)
            for s in range(5)]
    from cme213_tpu.ops.elementwise import shift_cipher

    with faults.injected("fail:serve.cipher.packed:1:3"):
        for q in reqs[:4]:
            server.submit("cipher", q)
            (res,) = server.step()
            assert res.status == OK and res.rung == "bytes"
            ref = np.asarray(shift_cipher(jnp.asarray(q.text), q.shift))
            np.testing.assert_array_equal(res.value, ref)
        assert trace.events("breaker-open")
        clock.advance(11.0)
        server.submit("cipher", reqs[4])
        (res,) = server.step()                 # half-open probe succeeds
        assert res.rung == "packed"
        assert trace.events("breaker-close")


def test_spmv_coarse_bucket_pads_and_stays_bitwise():
    """Degraded-mode coarse keying: two near sizes merge into one pow2
    bucket; the padded tail is quarantined, so each request's prefix is
    still bitwise-equal to its serial solve."""
    from cme213_tpu.apps.spmv_scan import generate_problem

    probs = [generate_problem(200, p=4, q=32, iters=4, seed=1),
             generate_problem(250, p=4, q=32, iters=4, seed=2)]
    server = Server(max_batch=4, clock=VirtualClock(), degrade_depth=2)
    for p in probs:
        server.submit("spmv_scan", p)
    results = server.drain()
    assert [r.status for r in results] == [OK] * 2
    assert results[0].batch_size == 2          # merged despite n mismatch
    assert results[0].shape_class == "n256/i4"
    assert all(r.degraded for r in results)
    for r, p in zip(results, probs):
        assert r.value.shape == (p.n,)
        np.testing.assert_array_equal(r.value, _spmv_serial(p, r.rung))


# ------------------------------------------------------------- throughput

def test_batched_serving_at_least_2x_serial():
    """The tier's reason to exist: B same-class solves through one vmapped
    program beat B one-at-a-time dispatches by >= 2x (warmed, CPU)."""
    from cme213_tpu.apps.spmv_scan import generate_problem

    B = 32
    probs = [generate_problem(256, p=4, q=128, iters=4, seed=s)
             for s in range(B)]

    def run(max_batch):
        server = Server(max_batch=max_batch, capacity=B)
        for p in probs:
            server.submit("spmv_scan", p)
        t0 = time.perf_counter()
        results = server.drain()
        dt = time.perf_counter() - t0
        assert sum(r.status == OK for r in results) == B
        return dt

    run(B)       # warm the batched program (compile outside the clock)
    run(1)       # warm the serial program
    batched = min(run(B) for _ in range(3))   # best-of-3: measured ratio
    serial = min(run(1) for _ in range(3))    # is ~5x; 2x is the floor
    assert serial >= 2 * batched, (
        f"batched {batched:.4f}s vs serial {serial:.4f}s "
        f"({serial / batched:.2f}x)")


# ---------------------------------------------------------------- loadgen

def test_loadgen_closed_loop_serves_everything():
    specs = build_mix("cipher", 12, seed=0)
    server = Server(max_batch=4, capacity=16)
    before = metrics.snapshot()
    run = run_load(server, specs, mode="closed", concurrency=6)
    report = slo_report(run, before, metrics.snapshot())
    assert report["requests"] == 12 and report["served"] == 12
    assert report["shed"] == 0
    assert report["batches"] >= 3
    assert report["latency_ms"]["p50"] is not None
    assert report["throughput_rps"] > 0


def test_loadgen_open_burst_sheds_over_capacity():
    specs = build_mix("cipher", 24, seed=0)
    server = Server(max_batch=2, capacity=6)
    before = metrics.snapshot()
    run = run_load(server, specs, mode="open", burst=24)
    report = slo_report(run, before, metrics.snapshot())
    assert report["requests"] == 24
    assert report["shed"] >= 10                # overload MUST shed
    assert report["shed_by_reason"].get(QUEUE_FULL, 0) == report["shed"]
    assert report["served"] == 24 - report["shed"]
    assert trace.events("queue-shed")


def test_loadgen_mix_round_robins_ops():
    specs = build_mix("spmv,heat,cipher", 6, seed=0)
    assert [s.op for s in specs] == ["spmv_scan", "heat", "cipher"] * 2


def test_loadgen_rejects_unknown_mix():
    with pytest.raises(ValueError, match="unknown mix"):
        build_mix("spmv,warp", 4)


def test_serve_cli_registered(capsys):
    from cme213_tpu.models import dispatch

    assert dispatch(["serve"]) == 2            # no subcommand: usage
    assert dispatch(["serve", "--help"]) == 0
    out = capsys.readouterr().out
    assert "loadgen" in out


# ----------------------------------------------------- trace integration

def test_trace_summary_serving_section():
    from cme213_tpu.trace_cli import summarize

    clock = VirtualClock()
    server, _ = echo_server(clock=clock, capacity=2, max_batch=2,
                            degrade_depth=2)
    for v in range(3):
        server.submit("echo", ("k", v))        # third sheds
    server.drain()
    agg = summarize(trace.events())
    serving = agg["serving"]
    assert serving is not None
    assert serving["batches"] >= 1
    assert serving["shed"].get("echo:queue-full") == 1
    assert serving["degraded_batches"] >= 1


# ----------------------------------------------------- request lifecycle

def test_request_timing_phases_sum_to_total():
    """Every phase stamp comes from the server clock, so the phase
    breakdown sums to total_ms up to per-field rounding."""
    clock = VirtualClock()
    server, _ = echo_server(clock=clock, max_batch=2)
    server.submit("echo", ("k", 1))
    clock.advance(0.05)                        # 50ms queued before step
    with faults.injected("slow:serve.echo:20"):
        (res,) = server.step()
    t = res.timing
    assert t["queue_ms"] == 50.0 and t["run_ms"] == 20.0
    phase_sum = (t["queue_ms"] + t["admit_ms"] + t["batch_wait_ms"]
                 + t["run_ms"])
    assert abs(phase_sum - t["total_ms"]) < 0.005
    ev = trace.events("request-served")[-1]
    assert ev["status"] == OK and ev["total_ms"] == t["total_ms"]
    assert ev["run_ms"] == 20.0
    # per-phase histograms observed once per served request
    assert metrics.histogram("serve.request.total_ms").count == 1
    assert metrics.histogram("serve.request.run_ms").percentile(1.0) == 20.0


def test_request_served_event_links_batch_span():
    server, _ = echo_server(max_batch=4)
    server.submit("echo", ("k", 1))
    server.submit("echo", ("k", 2))
    server.drain()
    reqs = trace.events("request-served")
    assert len(reqs) == 2
    batch_ids = {e["batch"] for e in reqs}
    assert len(batch_ids) == 1                 # same batch -> same span
    span_ids = {e["id"] for e in trace.events("span-begin")
                if e.get("span") == "serve.batch"}
    assert batch_ids <= span_ids               # rid -> serve.batch linkage


def test_failed_request_lifecycle_and_tenant_counter():
    server, _ = echo_server()
    server.submit("echo", ("k", 1), tenant="acme")
    with faults.injected("fail:serve.echo.fast,fail:serve.echo.safe"):
        (res,) = server.step()
    assert res.status == FAILED and res.tenant == "acme"
    assert res.timing["total_ms"] is not None
    ev = trace.events("request-served")[-1]
    assert ev["status"] == FAILED and ev["tenant"] == "acme"
    assert metrics.counter("serve.tenant.acme.failed").value == 1


def test_tenant_counters_and_shed_tags():
    server, _ = echo_server(capacity=1)
    server.submit("echo", ("k", 1), tenant="a")
    shed = server.submit("echo", ("k", 2), tenant="b")   # queue full
    assert shed.status == SHED and shed.tenant == "b"
    server.drain()
    assert metrics.counter("serve.tenant.a.requests").value == 1
    assert metrics.counter("serve.tenant.a.served").value == 1
    assert metrics.counter("serve.tenant.b.requests").value == 1
    assert metrics.counter("serve.tenant.b.shed").value == 1
    ev = trace.events("queue-shed")[-1]
    assert ev["tenant"] == "b" and ev["age_ms"] == 0.0 and ev["depth"] == 1


def test_deadline_shed_carries_depth_and_age():
    clock = VirtualClock()
    server, _ = echo_server(clock=clock)
    server.submit("echo", ("k", 1), deadline_ms=50, tenant="late")
    clock.advance(0.2)
    (res,) = server.step()
    assert res.status == SHED and res.reason == DEADLINE
    ev = trace.events("deadline-shed")[-1]
    assert ev["depth"] == 0                    # already pulled off queue
    assert ev["age_ms"] == 200.0 and ev["tenant"] == "late"


def test_summary_zero_count_shed_keys_and_lifecycle_sections():
    import io

    from cme213_tpu.trace_cli import summarize

    server, _ = echo_server(max_batch=2)
    server.submit("echo", ("k", 1), tenant="a")
    server.submit("echo", ("k", 2), tenant="b")
    server.drain()                             # all served, nothing shed
    out = io.StringIO()
    agg = summarize(trace.events(), out=out)
    # stable shed keys: zero-filled for every (serving op, reason) pair
    assert agg["serving"]["shed"] == {"echo:admission": 0,
                                      "echo:deadline": 0,
                                      "echo:queue-full": 0}
    assert set(agg["phases"]) == {"echo", "overall"}
    assert agg["phases"]["overall"]["total_ms"]["p50"] is not None
    assert agg["tenants"]["a"]["served"] == 1
    assert agg["tenants"]["b"]["served"] == 1
    assert agg["slo"] is None                  # no monitor ran
    text = out.getvalue()
    assert "request phases" in text and "tenants:" in text


def test_loadgen_report_phases_tenants_slo_sections():
    from cme213_tpu.serve.loadgen import format_report
    from cme213_tpu.serve.slo import Objective, SLOMonitor

    specs = build_mix("cipher", 12, seed=0, tenants=2)
    assert {s.tenant for s in specs} == {"t0", "t1"}
    mon = SLOMonitor([Objective("p99-latency", "p99_latency_ms", 1e9)])
    server = Server(max_batch=4, capacity=16, slo=mon)
    before = metrics.snapshot()
    run = run_load(server, specs, mode="closed", concurrency=6)
    report = slo_report(run, before, metrics.snapshot(), slo=mon)
    assert report["served"] == 12
    overall = report["phases"]["overall"]
    assert set(overall) == {"queue", "admit", "batch_wait", "run", "total"}
    assert overall["total"]["p50"] is not None
    assert overall["total"]["p99"] >= overall["total"]["p50"]
    tn = report["tenants"]
    assert tn["t0"]["served"] + tn["t1"]["served"] == 12
    assert tn["t0"]["latency_ms"]["p50"] is not None
    assert report["slo"]["objectives"]["p99-latency"]["burning"] is False
    assert report["slo"]["burn_events"] == 0
    text = format_report(report)
    assert "phase attribution" in text and "tenants:" in text
    assert "slo:" in text
