"""The serving core: bounded queue, shape-class batcher, deadline-aware
scheduler — built to stay up and degrade predictably when traffic
exceeds capacity.

Control flow is synchronous and deterministic (the property every test
and faultcheck step leans on): ``submit`` either enqueues and returns a
request id, or refuses immediately with a structured shed result;
``step`` forms ONE batch from the queue head's (op, shape-class) bucket
and executes it through the resilience stack.  Every robustness decision
is observable:

- **backpressure**: the queue is bounded; an arrival past capacity is
  shed with a ``queue-shed`` event + ``serve.shed.queue-full`` counter
  and a 429-style result — bounded queueing delay for everyone admitted,
  an honest refusal for everyone else.
- **deadlines**: a request that cannot *start* before its deadline is
  rejected before execution (``deadline-shed`` + ``serve.shed.deadline``)
  — device minutes are never spent on an answer nobody is waiting for.
  Deadlines bound queue wait, not execution: a batch that *starts* in
  time serves even if it finishes past the mark (latency says so).
- **circuit breaking**: rung failures feed a per-(op, rung)
  ``core.resilience.CircuitBreaker``; an open circuit routes requests to
  the fallback rung without burning a failure per request, and a
  half-open probe restores the rung when it heals.
- **graceful degradation**: when the SLO monitor burns (``serve/slo.py``
  — the primary trigger when one is attached) or queue depth / latency
  p99 crosses its threshold (the backstops), the scheduler switches to
  the degraded rung ladder and coarser (power-of-two-padded) shape
  buckets, and wraps batch execution in a ``degraded-mode`` span — the
  trade shows up in ``trace summary``, not just in the latency
  distribution.  Exit has hysteresis (half the entry depth; the SLO
  monitor's own recovery hysteresis) so the mode doesn't flap.
- **request-lifecycle tracing**: every request is phase-stamped on the
  server clock (submit → dequeue → admit → execute → complete); results
  carry the ``timing`` breakdown, a ``request-served`` event links each
  rid to the ``serve.batch`` span that executed it, and the phases feed
  ``serve.request.<phase>_ms`` histograms plus per-tenant
  ``serve.tenant.<t>.*`` counters.
- **admission**: with a memory budget set (``CME213_MEMORY_BUDGET``),
  batch sizes are preflighted (``core.admission.admit_batch``) and
  shrink before dispatch; overflow requests stay queued, and a shape
  class whose single-request program cannot fit is shed with reason
  ``admission``.

All timing runs on an injectable ``core.resilience.Clock``; with a
``VirtualClock`` the entire deadline/breaker/straggler machinery is
testable without a single wall-clock sleep (``slow:`` fault clauses
advance the same clock).
"""

from __future__ import annotations

import itertools
from contextlib import nullcontext

from ..core import admission, metrics, numerics
from ..core.errors import FrameworkError
from ..core.faults import maybe_drift, maybe_slow
from ..core.resilience import CircuitBreaker, Clock, with_fallback
from ..core.trace import (begin_span, current_span_id, record_event, span,
                          tail_decide, tail_keep_reason,
                          trace_id as current_trace_id)
from .request import (
    ADMISSION,
    DEADLINE,
    FAILED,
    OK,
    QUEUE_FULL,
    SHED,
    SolveRequest,
    SolveResult,
)
from .workloads import ADAPTERS


def tuned_batch_cap(op: str, shape_class: str, default: int) -> int:
    """Batch width for one (op, shape-class) bucket: the measured winner
    from the tuning cache (``core/tune.py``, op ``serve.<op>``) when one
    is cached, else ``default`` (the server's ``max_batch``).  Never
    *raises* the cap past ``default`` — the queue/SLO sizing assumed it."""
    from ..core import tune

    resolved = tune.resolve(f"serve.{op}", shape_class, "float32",
                            max_batch=default)
    try:
        cap = int(resolved["max_batch"])
    except (KeyError, TypeError, ValueError):
        return default
    return max(1, min(cap, default))


class BoundedQueue:
    """FIFO with a hard capacity: ``push`` refuses (returns False) at
    capacity instead of growing — the arrival being refused is the
    *newest* one, so admitted requests keep their bounded wait."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._items: list[SolveRequest] = []

    def __len__(self) -> int:
        return len(self._items)

    def push(self, req: SolveRequest) -> bool:
        if len(self._items) >= self.capacity:
            return False
        self._items.append(req)
        return True

    def peek(self) -> SolveRequest | None:
        return self._items[0] if self._items else None

    def take(self, reqs: list[SolveRequest]) -> None:
        """Remove the given requests (batch formation / deadline sweep)."""
        drop = {id(r) for r in reqs}
        self._items = [r for r in self._items if id(r) not in drop]

    def items(self) -> list[SolveRequest]:
        return list(self._items)


class Server:
    """The multi-tenant front end; see the module docstring for the
    semantics of each knob."""

    def __init__(self, capacity: int = 64, max_batch: int = 8,
                 clock: Clock | None = None,
                 breaker: CircuitBreaker | None = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 degrade_depth: int | None = None,
                 degrade_p99_ms: float | None = None,
                 adapters: dict | None = None,
                 slo=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.clock = clock if clock is not None else Clock()
        self.queue = BoundedQueue(capacity)
        self.max_batch = max_batch
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s,
            clock=self.clock)
        self.degrade_depth = degrade_depth
        self.degrade_p99_ms = degrade_p99_ms
        self.degraded = False
        self._degrade_reason: str | None = None
        self.adapters = adapters if adapters is not None else dict(ADAPTERS)
        self.slo = slo                  # serve.slo.SLOMonitor | None
        self._rids = itertools.count()
        self._admit_cache: dict[tuple, int] = {}
        self._tuned_caps: dict[tuple, int] = {}
        # drive-mode hook: the caller-driven step() loop is the default
        # drive; a transport front end (serve/transport.py) attaches a
        # waker so its background batcher thread wakes on arrival instead
        # of polling.  Called after every successful enqueue.
        self.on_submit = None
        # the long-job lane (serve/jobs.py JobExecutor | None): driven by
        # job_tick() strictly in the gaps between interactive batches
        self.jobs = None

    # ------------------------------------------------------------ submit

    def submit(self, op: str, payload, deadline_ms: float | None = None,
               tenant: str = "default", trace_id: str | None = None,
               parent_span: str | None = None):
        """Accept (returns the request id) or refuse (returns a SHED
        :class:`SolveResult`) — never blocks, never queues unboundedly.

        ``trace_id`` joins the request to an existing cross-process trace
        (a remote caller forwarding its own id); by default the request
        rides this process's trace, so loadgen → queue → batch →
        execution → result share one process-spanning id.  ``parent_span``
        is the wire-carried upstream hop span id: the accepted request's
        ``serve.hop.replica`` span parents under it, so the request's
        replica-side residency joins the caller's waterfall."""
        if op not in self.adapters:
            raise ValueError(f"unknown op {op!r} "
                             f"(serving: {sorted(self.adapters)})")
        tid = trace_id or current_trace_id()
        metrics.counter("serve.requests").inc()
        metrics.counter(f"serve.tenant.{tenant}.requests").inc()
        now = self.clock.now()
        rid = next(self._rids)
        if deadline_ms is not None and deadline_ms <= 0:
            return self._shed_deadline(
                SolveRequest(rid, op, payload, now, now, tenant=tenant,
                             trace_id=tid),
                late_ms=-deadline_ms, now=now)
        req = SolveRequest(
            rid, op, payload, submitted_s=now,
            deadline_s=None if deadline_ms is None else now + deadline_ms / 1e3,
            tenant=tenant, trace_id=tid)
        if not self.queue.push(req):
            metrics.counter(f"serve.shed.{QUEUE_FULL}").inc()
            metrics.counter(f"serve.tenant.{tenant}.shed").inc()
            record_event("queue-shed", op=op, reason=QUEUE_FULL,
                         depth=len(self.queue), age_ms=0.0, tenant=tenant,
                         trace=req.trace_id)
            res = SolveResult(rid, op, SHED, reason=QUEUE_FULL, tenant=tenant,
                              timing=req.timing(), trace_id=req.trace_id)
            self._observe_slo(res)
            return res
        req.parent_span_id = parent_span
        req.hop = begin_span("serve.hop.replica", parent=parent_span,
                             tail_key=f"r{rid}", head_key=rid,
                             rid=rid, op=op, tenant=tenant, trace=tid)
        if self.on_submit is not None:
            self.on_submit()
        return rid

    def _shed_deadline(self, req: SolveRequest, late_ms: float,
                       now: float | None = None) -> SolveResult:
        now = self.clock.now() if now is None else now
        metrics.counter(f"serve.shed.{DEADLINE}").inc()
        metrics.counter(f"serve.tenant.{req.tenant}.shed").inc()
        record_event("deadline-shed", op=req.op, rid=req.rid,
                     late_ms=round(late_ms, 3), depth=len(self.queue),
                     age_ms=round((now - req.submitted_s) * 1e3, 3),
                     tenant=req.tenant, trace=req.trace_id)
        res = SolveResult(req.rid, req.op, SHED, reason=DEADLINE,
                          tenant=req.tenant, timing=req.timing(),
                          trace_id=req.trace_id)
        if req.hop is not None:
            req.hop.end(status=SHED, reason=DEADLINE)
            tail_decide(req.hop.tail_key, keep=True, reason="shed")
        self._observe_slo(res)
        return res

    def _observe_slo(self, result: SolveResult) -> None:
        if self.slo is not None:
            self.slo.observe_result(result)

    # -------------------------------------------------------------- step

    def step(self) -> list[SolveResult]:
        """Sweep expired deadlines, then form and execute ONE batch from
        the queue head's (op, shape-class) bucket.  Returns every result
        produced this step (shed and served)."""
        results: list[SolveResult] = []
        now = self.clock.now()

        expired = [r for r in self.queue.items()
                   if r.deadline_s is not None and now >= r.deadline_s]
        if expired:
            self.queue.take(expired)
            results.extend(
                self._shed_deadline(r, late_ms=(now - r.deadline_s) * 1e3,
                                    now=now)
                for r in expired)

        self._update_degraded()
        head = self.queue.peek()
        if head is None:
            return results

        adapter = self.adapters[head.op]
        coarse = self.degraded
        key = adapter.shape_class(head.payload, coarse=coarse)
        batch = [r for r in self.queue.items()
                 if r.op == head.op
                 and adapter.shape_class(r.payload, coarse=coarse) == key]
        cap = self._tuned_caps.get((head.op, key))
        if cap is None:
            cap = tuned_batch_cap(head.op, key, self.max_batch)
            self._tuned_caps[(head.op, key)] = cap
        batch = batch[:cap]

        dequeued = self.clock.now()
        for r in batch:
            r.dequeued_s = dequeued
        batch, admission_shed = self._admit(adapter, key, batch, coarse)
        results.extend(admission_shed)
        if not batch:
            return results
        admitted = self.clock.now()
        for r in batch:
            r.admitted_s = admitted
        self.queue.take(batch)
        results.extend(self._execute(adapter, key, batch, coarse))
        return results

    def drain(self) -> list[SolveResult]:
        """Step until the queue is empty."""
        results: list[SolveResult] = []
        while len(self.queue):
            results.extend(self.step())
        return results

    def job_tick(self) -> bool:
        """Run at most one long-job epoch through the attached executor
        (``serve/jobs.py``).  Interactive traffic strictly wins: the
        executor re-checks queue depth and SLO burn before every epoch
        and preempts at the boundary, so the caller may tick whenever a
        ``step()`` left the queue empty.  Returns True when durable job
        progress was made (more work may remain)."""
        if self.jobs is None:
            return False
        return self.jobs.tick()

    # ---------------------------------------------------------- internals

    def _admit(self, adapter, key: str, batch, coarse):
        """Memory-budget preflight: shrink the batch to the admitted
        size (overflow stays queued), or shed the whole bucket when even
        one request cannot fit."""
        if not batch or admission.memory_budget() is None:
            return batch, []
        rung = adapter.rungs(self.degraded)[0]
        builder = adapter.preflight_builder(
            [r.payload for r in batch], rung, coarse=coarse)
        if builder is None:
            return batch, []
        cache_key = (adapter.op, key, rung, len(batch))
        admitted = self._admit_cache.get(cache_key)
        if admitted is None:
            try:
                admitted = admission.admit_batch(
                    f"serve.{adapter.op}", len(batch), builder)
            except admission.AdmissionError:
                self.queue.take(batch)
                now = self.clock.now()
                shed = []
                for r in batch:
                    metrics.counter(f"serve.shed.{ADMISSION}").inc()
                    metrics.counter(f"serve.tenant.{r.tenant}.shed").inc()
                    record_event("queue-shed", op=r.op, reason=ADMISSION,
                                 depth=len(self.queue),
                                 age_ms=round((now - r.submitted_s) * 1e3, 3),
                                 tenant=r.tenant, trace=r.trace_id)
                    res = SolveResult(r.rid, r.op, SHED, reason=ADMISSION,
                                      tenant=r.tenant, timing=r.timing(),
                                      trace_id=r.trace_id)
                    if r.hop is not None:
                        r.hop.end(status=SHED, reason=ADMISSION)
                        tail_decide(r.hop.tail_key, keep=True, reason="shed")
                    self._observe_slo(res)
                    shed.append(res)
                return [], shed
            self._admit_cache[cache_key] = admitted
        return batch[:admitted], []

    def _execute(self, adapter, key: str, batch, coarse) -> list[SolveResult]:
        op = adapter.op
        payloads = [r.payload for r in batch]
        rungs = adapter.rungs(self.degraded)
        # ``drift:serve.<op>.<rung>`` clauses perturb the served outputs
        # *inside* the ladder, so the shadow sampler's reference
        # re-execution (a direct run_batch below) stays clean — exactly
        # the silent-divergence topology shadow sampling exists to catch
        ladder = [(rung,
                   (lambda rg: lambda: maybe_drift(
                       f"serve.{op}.{rg}", adapter.run_batch(
                           payloads, rg, coarse=coarse)))(rung))
                  for rung in rungs]
        ctx = (span("degraded-mode", op=op,
                    reason=self._degrade_reason or "pressure")
               if self.degraded else nullcontext())
        # the run phase starts here: injected straggler latency rides the
        # server clock, so it shows up in run_ms, latencies, and
        # subsequent deadline decisions exactly like a real slow device
        executed = self.clock.now()
        for r in batch:
            r.executed_s = executed
            if r.hop is not None:
                r.run_hop = begin_span("serve.hop.run", parent=r.hop.id,
                                       tail_key=r.hop.tail_key,
                                       head_key=r.rid, rid=r.rid, op=op,
                                       trace=r.trace_id)
        try:
            with ctx, span("serve.batch", op=op, shape_class=key,
                           size=len(batch)):
                batch_span = current_span_id()
                maybe_slow(f"serve.{op}", sleep=self.clock.sleep)
                # the gate is the drift budget's demotion hook: a rung
                # whose shadow-sample budget burned is routed around with
                # FailureKind.WRONG_ANSWER, exactly like a failed
                # conformance probe (core/numerics.py)
                res = with_fallback(
                    f"serve.{op}", ladder, breaker=self.breaker,
                    gate=lambda rg: not numerics.demoted(f"serve.{op}", rg))
        except FrameworkError as e:
            end = self.clock.now()
            metrics.counter("serve.failed").inc(len(batch))
            out = []
            for r in batch:
                r.completed_s = end
                metrics.counter(f"serve.tenant.{r.tenant}.failed").inc()
                timing = r.timing()
                record_event("request-served", rid=r.rid, op=op,
                             tenant=r.tenant, batch=batch_span,
                             status=FAILED, total_ms=timing["total_ms"],
                             trace=r.trace_id,
                             **{k: v for k, v in timing.items()
                                if k != "total_ms"})
                res_f = SolveResult(
                    r.rid, op, FAILED, reason=str(e)[:200], shape_class=key,
                    batch_size=len(batch), degraded=self.degraded,
                    tenant=r.tenant, timing=timing, trace_id=r.trace_id)
                if r.run_hop is not None:
                    r.run_hop.end(error="FrameworkError")
                if r.hop is not None:
                    r.hop.end(status=FAILED)
                    tail_decide(r.hop.tail_key, keep=True, reason="failed")
                self._observe_slo(res_f)
                out.append(res_f)
            return out
        end = self.clock.now()
        occupancy = len(batch) / self.max_batch
        metrics.counter("serve.batches").inc()
        metrics.histogram("serve.batch.size").observe(len(batch))
        record_event("batch-executed", op=op, shape_class=key,
                     size=len(batch), occupancy=round(occupancy, 4))
        # output sentinel: one vectorized non-finite reduction over the
        # served batch; a trip is recorded and fed to the breaker as
        # FailureKind.NUMERIC but the batch still serves (observability,
        # not a result change — the breaker decides about the *next* one)
        lo, hi = getattr(adapter, "sentinel_range", (None, None))
        numerics.sentinel(f"serve.{op}", res.rung, res.value, lo=lo, hi=hi,
                          breaker=self.breaker)
        out = []
        for r, value in zip(batch, res.value):
            r.completed_s = end
            latency_ms = (end - r.submitted_s) * 1e3
            metrics.histogram("serve.latency.ms").observe(latency_ms)
            metrics.histogram(f"serve.latency.{op}.ms").observe(latency_ms)
            metrics.counter(f"serve.tenant.{r.tenant}.served").inc()
            timing = r.timing()
            for phase in ("queue", "admit", "batch_wait", "run", "total"):
                v = timing[f"{phase}_ms"]
                if v is not None:
                    metrics.histogram(f"serve.request.{phase}_ms").observe(v)
            record_event("request-served", rid=r.rid, op=op, tenant=r.tenant,
                         batch=batch_span, status=OK,
                         total_ms=timing["total_ms"], trace=r.trace_id,
                         **{k: v for k, v in timing.items()
                            if k != "total_ms"})
            res_ok = SolveResult(
                r.rid, op, OK, value=value, rung=res.rung, shape_class=key,
                latency_ms=latency_ms, batch_size=len(batch),
                degraded=self.degraded, tenant=r.tenant, timing=timing,
                trace_id=r.trace_id)
            if r.run_hop is not None:
                r.run_hop.end(rung=res.rung)
            if r.hop is not None:
                r.hop.end(status=OK)
            self._observe_slo(res_ok)
            out.append(res_ok)
        # shadow conformance sampling runs LAST: every latency above was
        # already stamped on the clock, so the reference re-execution is
        # off the measured hot path by construction
        drifted = self._shadow(adapter, key, batch, payloads, res, coarse)
        # tail keep-decision at response time, after the drift verdict:
        # slow/drift-flagged requests keep their buffered hops, the
        # happy path drops them
        for r, res_r in zip(batch, out):
            if r.hop is not None and r.hop.tail_key is not None:
                reason = tail_keep_reason(status=res_r.status,
                                          latency_ms=res_r.latency_ms,
                                          drift=r.rid in drifted)
                tail_decide(r.hop.tail_key, keep=reason is not None,
                            reason=reason or "ok")
        metrics.write_exposition()   # no-op unless CME213_METRICS_FILE set
        return out

    def _shadow(self, adapter, key: str, batch, payloads, res,
                coarse) -> set:
        """Re-execute a deterministic 1-in-N sample of this batch's
        requests on the reference rung and fold the measured drift into
        the numeric-health observatory (``core/numerics.py``).  Never
        raises into the serving path; skipped entirely when the serving
        rung *is* the reference (drift against itself is zero).  Returns
        the sampled rids when the comparison went over budget (the
        drift-flagged keep rule for tail sampling), else an empty set."""
        rate = numerics.shadow_rate()
        if not rate:
            return set()
        op = adapter.op
        ref_rung = adapter.rungs(False)[-1]
        if res.rung == ref_rung:
            return set()
        picked = [i for i, r in enumerate(batch)
                  if numerics.should_sample(str(r.rid), rate=rate,
                                            trace=r.trace_id)]
        if not picked:
            return set()
        try:
            with span("serve.shadow", op=op, shape_class=key,
                      size=len(picked)):
                refs = adapter.run_batch([payloads[i] for i in picked],
                                         ref_rung, coarse=coarse)
            summary = numerics.shadow_compare(
                f"serve.{op}", res.rung, key,
                [res.value[i] for i in picked], refs)
        except Exception:  # noqa: BLE001 — the shadow path must never
            # take down serving; a crashed reference re-execution only
            # costs this sample
            metrics.counter("numerics.shadow.errors").inc()
            return set()
        if self.slo is not None:
            self.slo.observe(drift=summary["over_budget"])
        if summary.get("over_budget"):
            return {batch[i].rid for i in picked}
        return set()

    def _update_degraded(self) -> None:
        if self.slo is not None:
            self.slo.evaluate()
        depth = len(self.queue)
        p99 = metrics.histogram("serve.latency.ms").percentile(0.99)
        reason = None
        # objective violation is the primary trigger; raw queue depth and
        # the latency ring are the backstops for servers without an SLO
        if self.slo is not None and self.slo.burning:
            reason = "slo-burn"
        elif self.degrade_depth is not None and depth >= self.degrade_depth:
            reason = "queue-depth"
        elif (self.degrade_p99_ms is not None and p99 is not None
              and p99 >= self.degrade_p99_ms):
            reason = "latency-p99"
        if not self.degraded:
            if reason is not None:
                self.degraded = True
                self._degrade_reason = reason
                metrics.gauge("serve.degraded").set(1)
            return
        # hysteresis: leave only once depth has fallen to half the entry
        # threshold (and p99, if it triggered, has come back under) — the
        # latency ring decays slowly, so depth is the primary exit signal
        depth_ok = (self.degrade_depth is None
                    or depth <= self.degrade_depth // 2)
        p99_ok = (self.degrade_p99_ms is None or p99 is None
                  or p99 < self.degrade_p99_ms
                  or self._degrade_reason != "latency-p99")
        if depth_ok and p99_ok and reason is None:
            self.degraded = False
            self._degrade_reason = None
            metrics.gauge("serve.degraded").set(0)
