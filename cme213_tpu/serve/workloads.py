"""Workload adapters: how each solver batches, buckets, and degrades.

The request population is the paper's hw workload mix — heat grids
(hw2/hw5), SpMV-scan problems (hw_final), shift-cipher cracks (hw1) —
and each adapter maps its payload type onto the serving layer's four
needs:

- **shape-class keying** (``shape_class``): requests whose jitted
  program would be identical share a bucket, using the same keys the
  conformance cache uses (``core/conformance.py``) — spmv by canonical
  ``n`` bucket/iters, heat by grid shape/order/iters, cipher by byte
  length.  Spmv sizes are **always** snapped to their power-of-two
  bucket (``core/programs.canonical_size`` — requests are zero-padded
  with a quarantined tail segment, ``apps.spmv_scan.pad_problem``, and
  outputs sliced back), generalizing what used to be degraded-mode-only
  coarsening: near-sized classes share one cached program and the
  program cache stays finite under heterogeneous load.  Each bucket is
  conformance-probed once (``apps.spmv_scan._bucket_gate``) before it
  serves — padded-then-sliced must match the unpadded solve bitwise.
  Heat and cipher classes are exact by construction (padding a grid
  would move its physical boundary).
- **batched execution** (``run_batch``): all payloads of one bucket run
  as ONE device program via the apps' vmap/stacking entry points, each
  lane bitwise-equal to its serial solve.
- **rung ladders** (``rungs``): the kernel candidates ``with_fallback``
  walks, per mode.  Degraded mode serves from the always-conformant
  reference rung only (no probes, no extra compile classes — predictable
  over peak-fast).
- **admission preflight** (``preflight_builder``): a ``size ->
  Decision`` closure over the batched program, for
  ``core/admission.admit_batch`` when a memory budget is set.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _next_pow2(n: int) -> int:
    from ..core.programs import canonical_size

    return canonical_size(n)


@dataclass
class CipherRequest:
    """A shift-cipher solve: encrypt/decrypt ``text`` by ``shift``."""

    text: np.ndarray        # (n,) uint8
    shift: int


class SpmvAdapter:
    """``apps.spmv_scan.Problem`` payloads; XLA scan rungs only (the
    Pallas rungs don't stack — interpret mode on CPU would dominate any
    batching win, and serving wants predictable latency)."""

    op = "spmv_scan"

    def shape_class(self, prob, coarse: bool = False) -> str:
        # always the canonical power-of-two bucket: near-sized requests
        # share one cached program whatever the serving mode (coarse
        # keying used to be the degraded-mode exception; now it is the
        # rule, and degraded mode differs only in its rung ladder)
        return f"n{_next_pow2(prob.n)}/i{prob.iters}"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        # blocked is the O(n) throughput rung; flat is the bitwise-stable
        # reference every other rung is conformance-checked against, so
        # degraded mode serves from it alone
        return ("flat",) if degraded else ("blocked", "flat")

    def run_batch(self, probs, rung: str, coarse: bool = False):
        import jax.numpy as jnp

        from ..apps.spmv_scan import (_bucket_gate, pad_problem,
                                      run_spmv_scan_batched)

        ns = [p.n for p in probs]
        n_to = _next_pow2(max(ns))
        if any(n != n_to for n in ns):
            # one probe per (bucket, rung): padded-then-sliced must be
            # bitwise-equal to the unpadded solve before the bucket
            # serves.  A failing probe raises so the ladder demotes to a
            # rung whose padding IS exact instead of serving silently
            # wrong prefixes.
            if not _bucket_gate(n_to, rung, jnp.float32):
                raise RuntimeError(
                    f"pad-and-mask probe failed for bucket n{n_to} on "
                    f"rung {rung!r}")
            probs = [pad_problem(p, n_to) for p in probs]
        outs = run_spmv_scan_batched(list(probs), kernel=rung)
        return [o[:n] for n, o in zip(ns, outs)]

    def preflight_builder(self, probs, rung: str, coarse: bool = False):
        from ..core import admission
        from ..apps.spmv_scan import _iterate_batched, pad_problem

        import jax.numpy as jnp

        p0 = pad_problem(probs[0], _next_pow2(max(p.n for p in probs)))
        n, iters = p0.n, p0.iters

        def preflight_at(size: int) -> admission.Decision:
            z = jnp.zeros((size, n), jnp.float32)
            fl = jnp.zeros((size, n), jnp.int32)
            return admission.preflight(
                _iterate_batched, z, z, fl, op=f"serve.{self.op}",
                iters=iters, scan=rung)

        return preflight_at


class HeatAdapter:
    """``config.SimParams`` payloads — the initial grid is derived from
    the params the way the reference's driver built it, and CFL factors
    ride as vmapped per-lane scalars (so requests need not share
    diffusivity to share a bucket)."""

    op = "heat"

    def shape_class(self, params, coarse: bool = False) -> str:
        return f"{params.gy}x{params.gx}/order{params.order}/i{params.iters}"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        # one conformant rung: the XLA stencil (the Pallas pipeline runs
        # interpreted off-TPU — never the serving choice there, and
        # batching it is ROADMAP work, not this layer's)
        return ("xla",)

    def run_batch(self, params_list, rung: str, coarse: bool = False):
        from ..apps.heat2d import run_heat_batched
        from ..grid import make_initial_grid

        if rung != "xla":
            raise ValueError(f"unknown heat rung {rung!r}")
        p0 = params_list[0]
        grids = [np.asarray(make_initial_grid(p)) for p in params_list]
        return run_heat_batched(grids, p0.iters, p0.order,
                                [p.xcfl for p in params_list],
                                [p.ycfl for p in params_list])

    def preflight_builder(self, params_list, rung: str,
                          coarse: bool = False):
        from ..core import admission
        from ..apps.heat2d import _heat_batched

        import jax.numpy as jnp

        p0 = params_list[0]

        def preflight_at(size: int) -> admission.Decision:
            z = jnp.zeros((size, p0.gy, p0.gx), jnp.float32)
            c = jnp.zeros((size,), jnp.float32)
            return admission.preflight(
                _heat_batched, z, p0.iters, p0.order, c, c,
                op=f"serve.{self.op}")

        return preflight_at


class CipherAdapter:
    """:class:`CipherRequest` payloads.  Two bitwise-identical rungs —
    ``packed`` (4-bytes-per-lane, the reference's uint kernel) and
    ``bytes`` (plain per-byte) — which is what makes this op the breaker
    demonstration: a ``fail:serve.cipher.packed``-injected rung opens its
    circuit and the ``bytes`` rung serves bitwise-equal results."""

    op = "cipher"

    def shape_class(self, req: CipherRequest, coarse: bool = False) -> str:
        return f"n{req.text.shape[0]}/u8"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        return ("packed", "bytes")

    def run_batch(self, reqs, rung: str, coarse: bool = False):
        import jax.numpy as jnp

        from ..core import check_op, programs, span
        from ..ops.elementwise import (
            shift_cipher_batched,
            shift_cipher_packed_batched,
        )

        if rung == "packed":
            kernel_fn = shift_cipher_packed_batched
        elif rung == "bytes":
            kernel_fn = shift_cipher_batched
        else:
            raise ValueError(f"unknown cipher rung {rung!r}")
        b, n = len(reqs), int(reqs[0].text.shape[0])
        shape_class = f"n{n}/u8/b{b}"

        def warm(fn):
            check_op(f"cipher_batched.{rung}",
                     fn(jnp.zeros((b, n), jnp.uint8),
                        jnp.zeros((b,), jnp.int32)))

        runner = programs.get("cipher_batched", rung, shape_class,
                              lambda: kernel_fn, dtype="u8", warm=warm,
                              batch=b)
        data = jnp.asarray(np.stack([r.text for r in reqs]))
        shifts = jnp.asarray(np.array([r.shift for r in reqs],
                                      dtype=np.int32))
        with span("cipher_batched.run", kernel=rung,
                  shape_class=shape_class) as sp:
            out = runner(data, shifts)
            sp.block(out)
        out = np.asarray(out)
        return [out[i] for i in range(len(reqs))]

    def preflight_builder(self, reqs, rung: str, coarse: bool = False):
        return None  # bytes in ≈ bytes out: admission adds nothing here


def _sort_gate(n: int, rung: str) -> bool:
    """One verdict per (bucket, rung): prove the device sort matches the
    host ``np.sort`` golden bitwise before the bucket serves — hw4's
    offline checker (``radixsort.cpp``'s host compare) made an in-path
    gate.  Probe keys are fixed-seed, so the verdict is deterministic
    and cacheable (``CME213_CONFORMANCE_CACHE``)."""
    from ..core import conformance

    probe = np.random.default_rng(99).integers(
        0, 2**32, size=n, dtype=np.uint32)
    return conformance.check(
        "serve.sort", rung, shape_class=f"n{n}/u32",
        candidate=lambda: _sort_one(probe, rung),
        reference=lambda: np.sort(probe)).ok


def _sort_one(keys: np.ndarray, rung: str) -> np.ndarray:
    """One unbatched solve on the named rung (gate probes, references)."""
    import jax.numpy as jnp

    from ..ops.sort import bitonic_sort, radix_sort, sort as lax_sort

    x = jnp.asarray(np.asarray(keys, np.uint32))
    if rung == "lax":
        return np.asarray(lax_sort(x))
    if rung == "radix":
        return np.asarray(radix_sort(
            x, block_size=_sort_block(int(x.shape[0]))))
    if rung == "bitonic":
        return np.asarray(bitonic_sort(x))
    raise ValueError(f"unknown sort rung {rung!r}")


def _sort_block(n: int) -> int:
    # serving sizes are far below the CLI's 8192 default; a block the
    # size of the (padded) input keeps the one-hot histogram tensors
    # CPU-affordable without changing the 4-phase structure
    return min(8192, max(256, n))


class SortAdapter:
    """``np.ndarray`` uint32 key payloads over the hw4 sort pipelines
    (``ops/sort.py``).  Three bitwise-identical rungs — ``lax`` (the
    library path; single-lane batches dispatch through
    ``ops.sort.sort_auto`` so a tuned winner serves), ``radix`` (the
    4-phase LSD passes), ``bitonic`` (the merge network) — each gated
    once per (bucket, rung) against the host ``np.sort`` golden before
    it serves (:func:`_sort_gate`).  Sorted uint32 keys are unique per
    input whatever the kernel, so every rung is bitwise-substitutable:
    the chaos campaigns' fourth op family for breaker/demotion drills."""

    op = "sort"

    def shape_class(self, keys, coarse: bool = False) -> str:
        return f"n{int(np.asarray(keys).shape[0])}/u32"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        return ("lax",) if degraded else ("lax", "radix", "bitonic")

    def run_batch(self, payloads, rung: str, coarse: bool = False):
        import jax
        import jax.numpy as jnp

        from ..core import check_op, programs, span
        from ..ops.sort import bitonic_sort, radix_sort, sort_auto

        n = int(np.asarray(payloads[0]).shape[0])
        if not _sort_gate(n, rung):
            raise RuntimeError(
                f"np.sort golden probe failed for sort bucket n{n} on "
                f"rung {rung!r}")
        b = len(payloads)
        if rung == "lax" and b == 1:
            # single lane rides the tuned dispatch (ops.sort.sort_auto):
            # a `tune run` winner serves here, and the golden gate above
            # holds whatever kernel it picked to bitwise np.sort
            out = sort_auto(
                jnp.asarray(np.asarray(payloads[0], np.uint32)))
            return [np.asarray(out)]
        if rung == "lax":
            def kernel_fn(x):
                from jax import lax
                return lax.sort(x, dimension=1)
        elif rung == "radix":
            kernel_fn = jax.vmap(
                lambda x: radix_sort(x, block_size=_sort_block(n)))
        elif rung == "bitonic":
            kernel_fn = jax.vmap(bitonic_sort)
        else:
            raise ValueError(f"unknown sort rung {rung!r}")
        shape_class = f"n{n}/u32/b{b}"

        def warm(fn):
            check_op(f"sort_batched.{rung}",
                     fn(jnp.zeros((b, n), jnp.uint32)))

        runner = programs.get("sort_batched", rung, shape_class,
                              lambda: kernel_fn, dtype="u32", warm=warm,
                              batch=b)
        data = jnp.asarray(np.stack([np.asarray(p, np.uint32)
                                     for p in payloads]))
        with span("sort_batched.run", kernel=rung,
                  shape_class=shape_class) as sp:
            out = runner(data)
            sp.block(out)
        out = np.asarray(out)
        return [out[i] for i in range(b)]

    def preflight_builder(self, payloads, rung: str, coarse: bool = False):
        return None  # keys in ≈ keys out: admission adds nothing here


class StubAdapter:
    """``np.ndarray`` payloads echoed back untouched, no jax anywhere on
    the path.  This is the transport's honest-measurement op: with the
    solve stubbed out, a closed-loop loadgen run measures exactly what
    the wire + queue + batcher cost per request (the tier-1 gate holds
    this path to >= 10k req/s on CPU), and any device time would only
    hide transport regressions."""

    op = "stub"

    def shape_class(self, arr: np.ndarray, coarse: bool = False) -> str:
        return f"n{int(np.asarray(arr).size)}"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        return ("echo",)

    def run_batch(self, payloads, rung: str, coarse: bool = False):
        if rung != "echo":
            raise ValueError(f"unknown stub rung {rung!r}")
        return [np.asarray(p) for p in payloads]

    def preflight_builder(self, payloads, rung: str, coarse: bool = False):
        return None


#: the default adapter registry — the hw workload mix as request types
ADAPTERS = {a.op: a for a in (SpmvAdapter(), HeatAdapter(),
                              CipherAdapter(), SortAdapter(),
                              StubAdapter())}


# ---------------------------------------------------------------- job kinds
#
# Long-job kinds are the batch-queue analog of the adapters above: where
# an adapter maps a *request payload* onto one batched device program, a
# job kind maps a *job record's params* onto a checkpointable solve the
# executor (serve/jobs.py) drives one epoch at a time.  The contract:
#   normalize(params) -> validated param dict (what the record stores)
#   totals(params)    -> (total_iters, epoch_iters, total_epochs)
#   make(params)      -> (state0, step_fn) for run_with_checkpoints
#   tracker(params, job) -> ConvergenceTracker (stall policy + job tag)
#   finalize(state)   -> np.ndarray result to persist
#   reference(params) -> host-golden result for conformance checks

class PageRankJob:
    """hw1's PageRank power iteration as a durable long job — the solve
    the reference queued through Torque ``qsub`` (``jobs/``), now
    submitted over the serving wire and chunked into epochs through
    ``apps/pagerank.py``'s checkpointed entry."""

    op = "pagerank"

    _DEFAULTS = {"nodes": 4096, "avg_edges": 8, "iters": 48, "epoch": 8,
                 "seed": 0, "stall_epochs": 25, "tol": 0.0}

    @classmethod
    def normalize(cls, params: dict) -> dict:
        p = dict(cls._DEFAULTS)
        unknown = set(params) - set(p)
        if unknown:
            raise ValueError(f"unknown pagerank job params {sorted(unknown)}"
                             f" (have: {sorted(p)})")
        p.update(params)
        for k in ("nodes", "avg_edges", "iters", "epoch", "seed",
                  "stall_epochs"):
            p[k] = int(p[k])
        p["tol"] = float(p["tol"])
        if p["nodes"] < 2 or p["avg_edges"] < 1:
            raise ValueError("pagerank job needs nodes >= 2, avg_edges >= 1")
        # the reference iterates in even pairs (pagerank.cu:61,127); an
        # even epoch keeps every chunk on the fused even-iteration rung
        if p["iters"] < 2 or p["iters"] % 2:
            raise ValueError(f"iters must be even and >= 2, got {p['iters']}")
        if p["epoch"] < 2 or p["epoch"] % 2:
            raise ValueError(f"epoch must be even and >= 2, got {p['epoch']}")
        return p

    @staticmethod
    def totals(p: dict) -> tuple[int, int, int]:
        total, epoch = p["iters"], min(p["epoch"], p["iters"])
        return total, epoch, -(-total // epoch)

    @staticmethod
    def make(p: dict):
        from ..apps.pagerank import build_graph, pagerank_step

        graph = build_graph(p["nodes"], p["avg_edges"], p["seed"])
        return pagerank_step(graph)

    @staticmethod
    def tracker(p: dict, job: str):
        from ..core.numerics import ConvergenceTracker

        return ConvergenceTracker("job.pagerank",
                                  stall_epochs=p["stall_epochs"], job=job)

    @staticmethod
    def finalize(state) -> np.ndarray:
        return np.asarray(state)

    @staticmethod
    def reference(p: dict) -> np.ndarray:
        from ..apps.pagerank import build_graph
        from ..verify import golden

        g = build_graph(p["nodes"], p["avg_edges"], p["seed"])
        return golden.host_graph_iterate(g.indices, g.edges, g.rank0,
                                         g.inv_deg, p["iters"])


#: registered long-job kinds (serve/jobs.py executes these)
JOB_KINDS = {PageRankJob.op: PageRankJob}
